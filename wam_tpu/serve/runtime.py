"""Batched attribution serving runtime (the tentpole of `wam_tpu/serve/`).

Turns a stream of independent single-item attribution requests into
efficiently padded device batches. One `AttributionServer` owns one device
stream: client threads `submit()` items and block on futures; a single
worker thread coalesces same-bucket requests into fixed-shape batches
(always the bucket's full ``max_batch`` rows — one compiled graph per
bucket, ever), dispatches them through a jitted serving entry
(`serve.entry.jit_entry`, usually an engine's ``serve_entry()``), and
fans results back out per request.

Operational semantics (DESIGN.md "Serving runtime"):
- **Backpressure**: the queue is bounded by ``queue_depth`` items across
  all buckets; `submit` on a full queue raises `QueueFullError` carrying a
  ``retry_after_s`` estimate — the projected drain time summed PER BUCKET
  ((queued + in-flight batches) × that bucket's EMA service time, from
  `ServeMetrics.ema_service_s`), so a backed-up 224² bucket does not
  inflate the retry estimate of a cheap waveform bucket. The same
  projection (`projected_drain_s`) is the fleet's load-aware routing
  signal (`serve.fleet`) — reject-with-retry-after, never unbounded
  buffering.
- **Coalescing** (DESIGN.md "Admission & coalescing"): the worker serves
  the bucket whose head request is oldest, holding its dispatch inside an
  admission window — ``coalesce_ms`` when set, else ``max_wait_ms`` —
  until the bucket is FULL, the window expires, or the oldest queued
  deadline cannot survive sitting out the rest of the window plus one
  EMA batch service (early release). ``coalesce_ms=0`` (the default for
  direct constructions) is exactly the historical max_wait behavior;
  ``ServeConfig.coalesce_ms`` defaults it on for config-built servers.
  Cross-request coalescing is what amortizes the fixed per-dispatch
  tunnel cost: independent single-item ``submit()``s from many clients
  pack into one full bucket dispatch instead of N replicate-padded ones.
- **QoS lanes**: ``submit(..., qos="interactive"|"batch")`` places the
  request in one of two FIFO lanes per bucket. The pop drains the
  interactive lane first and BACKFILLS a partially-full interactive
  dispatch from the batch lane (padding rows that would be replicated
  anyway carry real batch work instead); bucket selection prefers buckets
  with interactive work. The admission window is still anchored at the
  oldest head across both lanes, so batch work cannot starve.
- **Deadlines**: a request whose deadline lapses while queued (including
  while held in the admission window) is completed with
  `DeadlineExceededError` at pop time, BEFORE slot accounting — expired
  requests leave the lanes without displacing live ones from the take.
- **Result cache** (``result_cache=``, `serve.result_cache.ResultCache`):
  `submit` consults a content-addressed cache before admission; hits
  resolve the future immediately — no queue, no memory admission, no
  batch slot. Worker harvest populates it per real row. Off by default
  for direct constructions (``ServeConfig.result_cache_mb`` turns it on
  in config-built servers); ``WAM_TPU_NO_RESULT_CACHE=1`` kills it live.
- **Degradation**: if the entry raises mid-run and `probe_accelerator`
  (forced re-probe) says the accelerator is gone, the server swaps in the
  ``fallback_factory`` entry (a CPU-backend rebuild) once, replays the
  failed batch on it, and keeps serving degraded rather than failing hard.
- **Shutdown**: `close()` stops intake immediately, drains queued work
  (including any in-flight batch), then joins the worker.
- **Pipelining** (``pipelined=True``, the default): the worker keeps one
  batch in flight — it assembles and stages batch *k+1* to the device
  (`pipeline.put_committed`, an async upload) and dispatches it *before*
  harvesting batch *k*'s results, so host assembly + H2D transfer overlap
  device compute instead of serializing with it. Entry exceptions that
  surface at the deferred `device_get` go through the same degradation
  path as dispatch-time failures (the host batch is kept for replay).
- **Device pinning** (``device=``): a fleet replica's server commits every
  staged batch (and its warmup zeros) to its own chip, so N servers in one
  process drive N chips concurrently instead of all landing on the default
  device (`serve.fleet.FleetServer` passes one device per replica).
- **Multi-model residency** (``models=``, `serve.models`): the server
  multiplexes extra models behind the same admission plane — queues,
  in-flight accounting, EMA service times, result-cache keys, and memory
  watermarks all key on ``(model, bucket)``; ``submit(model=...)`` pages
  a cold model in synchronously (registry hydration + warmup under a
  ``model_switch`` span, ``compile_count == 0`` on a warm bundle) and the
  pager evicts idle models under the HBM budget. The default entry is
  model ``None``: pinned, never paged, byte-identical behavior to a
  single-model server.
- **Tenant fairness** (``submit(tenant=)``): within each QoS lane the pop
  round-robins across tenants (single-tenant traffic keeps exact FIFO),
  ``tenant_quota`` caps one tenant's share of the bounded queue, and the
  SLO ladder extends to ``bucket@class@tenant`` windows — one flooding
  tenant cannot monopolize admission, dispatch order, or the error
  budget accounting of the others.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import jax
import numpy as np

from wam_tpu.obs import health as obs_health
from wam_tpu.obs import memory as obs_memory
from wam_tpu.obs import sentinel as obs_sentinel
from wam_tpu.obs import slo as obs_slo
from wam_tpu.obs import tracing as obs_tracing
from wam_tpu.pipeline.stager import put_committed
from wam_tpu.serve.buckets import Bucket, BucketTable, bucket_key, pad_item
from wam_tpu.serve.metrics import ServeMetrics
from wam_tpu.serve.models import ModelPager, ModelSpec
from wam_tpu.serve.result_cache import ResultCache

__all__ = [
    "AttributionServer",
    "ServeError",
    "QueueFullError",
    "MemoryAdmissionError",
    "DeadlineExceededError",
    "InvalidDeadlineError",
    "ServerClosedError",
    "WorkerCrashedError",
    "QOS_CLASSES",
]

# admission lanes, in drain order (interactive first, batch backfills)
QOS_CLASSES = ("interactive", "batch")


class ServeError(RuntimeError):
    """Base class for serving-runtime request failures."""


class QueueFullError(ServeError):
    """Backpressure: the bounded queue is full. ``retry_after_s`` is the
    server's estimate of when capacity frees up — clients should back off
    at least that long before resubmitting."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"queue full; retry after {retry_after_s:.3f}s")
        self.retry_after_s = retry_after_s


class MemoryAdmissionError(QueueFullError):
    """Cold-bucket admission rejected: warming this bucket's projected HBM
    watermark would exceed the configured device budget
    (`wam_tpu.obs.memory.MemoryBudget`). A `QueueFullError` subclass so
    clients and the fleet treat it as ordinary backpressure — retry after
    ``retry_after_s`` (by then warm buckets may have drained, or an
    operator raised the budget)."""

    def __init__(self, retry_after_s: float, bucket: str = ""):
        ServeError.__init__(
            self,
            f"cold bucket {bucket or '?'} over memory budget; "
            f"retry after {retry_after_s:.3f}s")
        self.retry_after_s = retry_after_s
        self.bucket = bucket


class DeadlineExceededError(ServeError):
    """The request's deadline lapsed while it was still queued."""


class InvalidDeadlineError(ServeError, ValueError):
    """``submit(deadline_ms=)`` with a zero or negative window: rejected at
    admission, carrying the offending value. (Before this check, a
    non-positive window silently computed an already-past absolute
    deadline, queued the request, and expired it at pop time — a client
    bug surfaced as a confusing `DeadlineExceededError` after a full queue
    round-trip.) Also a `ValueError`, since the deadline is a bad
    *argument*, not a runtime condition."""

    def __init__(self, deadline_ms):
        super().__init__(
            f"deadline_ms must be > 0 (or None for no deadline), "
            f"got {deadline_ms!r}")
        self.deadline_ms = deadline_ms


class ServerClosedError(ServeError):
    """`submit` after `close()` (or during drain)."""


class WorkerCrashedError(ServerClosedError):
    """The device-owner worker thread itself died (an exception OUTSIDE
    the per-batch entry/recover path). Queued futures are failed with this
    instead of hanging forever; a `ServerClosedError` subclass so the
    fleet treats it as a liveness event and re-routes rather than
    forwarding it to the client."""


@dataclass
class _Request:
    x: np.ndarray
    y: int | None
    bucket: Bucket
    t_submit: float
    deadline: float | None  # perf_counter timestamp, None = no deadline
    future: Future = field(default_factory=Future)
    # obs trace identity: (trace_id, span_id) this request's spans parent
    # to — captured at submit (the fleet router's context, or a fresh root
    # this server starts for direct submits)
    ctx: tuple | None = None
    qos: str = "interactive"  # admission lane (QOS_CLASSES)
    ckey: str | None = None  # result-cache key (None = cache off)
    # anytime serving: per-request confidence floor for the convergence
    # early exit (0.0 = any converged delivery clears it)
    min_confidence: float = 0.0
    model: str | None = None  # paged model id (None = the default entry)
    tenant: str | None = None  # fair-share identity (None = untracked)


class _Lanes:
    """One bucket's queue as two FIFO lanes (module docstring "QoS
    lanes"), tenant-fair within each lane: `pop` round-robins across the
    tenants present (FIFO within a tenant, rotating start so no tenant
    owns slot 0), which degenerates to exact FIFO when every request
    carries the same (or no) tenant. Only ever touched under the
    server's ``_cond``."""

    __slots__ = ("interactive", "batch", "_rr")

    def __init__(self):
        self.interactive: list[_Request] = []
        self.batch: list[_Request] = []
        self._rr = 0  # rotating round-robin start across tenants

    def __len__(self) -> int:
        return len(self.interactive) + len(self.batch)

    def append(self, r: _Request) -> None:
        (self.interactive if r.qos == "interactive" else self.batch).append(r)

    def head(self) -> _Request:
        """Oldest request across both lanes — the admission window (and
        the served-oldest-bucket choice) anchor here so the batch lane
        cannot starve behind a steady interactive trickle."""
        if self.interactive and self.batch:
            a, b = self.interactive[0], self.batch[0]
            return a if a.t_submit <= b.t_submit else b
        return (self.interactive or self.batch)[0]

    def min_deadline(self) -> float | None:
        """Tightest queued deadline (the early-release trigger)."""
        ds = [r.deadline for r in self.interactive if r.deadline is not None]
        ds += [r.deadline for r in self.batch if r.deadline is not None]
        return min(ds) if ds else None

    def drop_expired(self, now: float) -> list[_Request]:
        """Remove (and return) every request whose deadline lapsed — runs
        at pop time, before slot accounting, so an expired request never
        displaces a live one from the take (deadline hygiene)."""
        expired = [r for r in self.interactive
                   if r.deadline is not None and now > r.deadline]
        expired += [r for r in self.batch
                    if r.deadline is not None and now > r.deadline]
        if expired:
            gone = set(map(id, expired))
            self.interactive = [r for r in self.interactive
                                if id(r) not in gone]
            self.batch = [r for r in self.batch if id(r) not in gone]
        return expired

    def _fair_take(self, lane: str, k: int) -> list[_Request]:
        """Up to ``k`` requests from one lane, round-robin across the
        tenants present (FIFO within each tenant). One tenant in the lane
        is EXACTLY the historical FIFO slice — the fair path only engages
        on genuinely multi-tenant traffic."""
        reqs = getattr(self, lane)
        if k <= 0 or not reqs:
            return []
        order: list = []
        by_tenant: dict = {}
        for r in reqs:
            if r.tenant not in by_tenant:
                by_tenant[r.tenant] = []
                order.append(r.tenant)
            by_tenant[r.tenant].append(r)
        if len(order) <= 1:
            take = reqs[:k]
            del reqs[:k]
            return take
        start = self._rr % len(order)
        self._rr += 1
        order = order[start:] + order[:start]
        take: list[_Request] = []
        idx = dict.fromkeys(order, 0)
        while len(take) < k:
            progressed = False
            for t in order:
                if len(take) >= k:
                    break
                queued = by_tenant[t]
                if idx[t] < len(queued):
                    take.append(queued[idx[t]])
                    idx[t] += 1
                    progressed = True
            if not progressed:
                break
        gone = set(map(id, take))
        setattr(self, lane, [r for r in reqs if id(r) not in gone])
        return take

    def pop(self, k: int) -> list[_Request]:
        """Up to ``k`` requests: the interactive lane drains first, the
        batch lane backfills the remaining rows; each lane drains
        tenant-fair (`_fair_take`)."""
        take = self._fair_take("interactive", k)
        fill = k - len(take)
        if fill > 0 and self.batch:
            take += self._fair_take("batch", fill)
        return take

    def clear(self) -> list[_Request]:
        reqs = self.interactive + self.batch
        self.interactive = []
        self.batch = []
        return reqs


@dataclass
class _Inflight:
    """A dispatched-but-unharvested batch: ``out`` is the entry's (possibly
    still computing) result; the host-side ``xs``/``ys`` are kept so a
    failure surfacing at harvest can replay on the fallback entry."""

    bucket: Bucket
    live: list
    depth: int
    xs: np.ndarray
    ys: np.ndarray | None
    t0: float
    out: object
    # numeric-health vector (device future) riding the same harvest as
    # ``out`` — None when the health plane is off
    hvec: object = None
    # anytime serving: the (B, ANYTIME_VEC_SIZE) confidence vector (device)
    # riding the same harvest, and the driver's stride-loop info dict
    # (n_used / n_total / complete / converged / strides / deadline_hit) —
    # both None on a plain full-n batch
    cvec: object = None
    anytime: dict | None = None
    model: str | None = None  # paged model id (None = the default entry)


_NOT_READY = object()  # non-blocking _take_batch: nothing poppable yet


class AttributionServer:
    """See module docstring.

    Parameters
    ----------
    entry : ``(x, y) -> attribution pytree`` with leading batch axis on
        every leaf (an engine's ``serve_entry()`` or any jitted callable).
    buckets : `BucketTable` or iterable of admitted item shapes.
    max_batch : rows per dispatched batch (every batch is padded to exactly
        this, so each bucket compiles once).
    max_wait_ms : max time a head-of-bucket request waits for batch fill.
    coalesce_ms : cross-request admission window (module docstring
        "Coalescing"). 0 (default) = historical max_wait behavior; > 0
        holds a bucket's dispatch up to this long for batch fill, with
        deadline-pressure early release. Config-built servers default it
        on via ``ServeConfig.coalesce_ms``.
    queue_depth : bound on queued items across all buckets (backpressure).
    deadline_ms : default per-request deadline (0 = none; per-`submit`
        override).
    labeled : whether requests carry a class label. ``labeled=False``
        servers dispatch ``entry(x, None)`` (representation mode); mixing
        labeled and unlabeled requests in one server would need two graphs
        per bucket, so it is rejected at `submit`.
    warmup : compile every bucket at `start()` (after
        `config.enable_compilation_cache()` when ``compilation_cache``), so
        no request ever eats a cold compile on the hot path.
    metrics : a shared `ServeMetrics`; constructed fresh when None. Pass
        the same object given to ``serve_entry(on_trace=...)`` so compile
        counts land in the same ledger.
    metrics_path : when set, `close()` emits the batch rows + summary to
        this JSONL ledger (`results.JsonlWriter`).
    fallback_factory : zero-arg callable building a CPU-backend entry for
        degraded serving (see module docstring).
    dtype : host dtype items are staged as (one contiguous transfer per
        batch).
    pipelined : keep one batch in flight — stage + dispatch batch *k+1*
        before harvesting batch *k* (module docstring "Pipelining").
        ``False`` restores the synchronous dispatch-then-distribute loop.
    device : jax Device every staged batch (and warmup) is committed to;
        None keeps jax's default placement (single-chip behavior). A fleet
        replica passes its own chip (module docstring "Device pinning").
    replica_id : this worker's identity in a fleet ledger (None =
        single-chip); forwarded to a freshly constructed `ServeMetrics`.
    health : numeric-health monitoring (`wam_tpu.obs.health`): True or a
        `HealthConfig` builds a per-server `HealthMonitor`; an existing
        monitor is used as-is; None/False (default) disables. Health-fused
        entries (``serve_entry(with_health=True)``) carry the stats inside
        their own graph; other entries get a post-hoc on-device reduction —
        either way the vector is harvested in the worker's ONE existing
        ``device_get``, zero extra fetches.
    slo : SLO objectives (`wam_tpu.obs.slo`): a policy string / map /
        `SLObjectives` builds a per-server `SLOTracker`; an existing
        tracker is used as-is; None/"" disables. The tracker is attached
        to ``metrics.slo`` so `close()` writes the ``slo_status`` ledger
        row.
    memory : HBM accounting (`wam_tpu.obs.memory`): a byte budget (int)
        builds a per-server `MemoryBudget` on this server's device; an
        existing budget is used as-is; None/0 disables the admission check
        (watermarks are still captured when a budget object is given).
    registry : compile-artifact bundle to hydrate from BEFORE any warmup
        compile (`wam_tpu.registry`): a bundle path or `RegistryClient`;
        None/"" disables. Hydration is the first thing `start()` does —
        verified executables seed the AOT cache, XLA cache files and the
        tuned-schedule snapshot land before `load_schedule_cache()` reads
        the table — so a cold process warms at ``compile_count == 0``. A
        missing/corrupt/mismatched bundle silently falls back to compiling
        (per-artifact miss semantics); the `HydrationReport` lands on
        ``registry_report`` and, when ``metrics_path`` is set, as a
        ``registry_hydration`` ledger row.
    result_cache : content-addressed result cache
        (`serve.result_cache.ResultCache`): an int byte budget builds a
        per-server cache; an existing instance is SHARED as-is (the fleet
        keeps one at its admission tier and passes its replicas None);
        None/0 (default) disables — direct constructions keep exact
        pre-cache accounting (``completed == submitted`` stays pinned by
        tests), ``ServeConfig.result_cache_mb`` turns it on for
        config-built servers.
    cache_id : entry/model identity baked into cache keys; defaults to the
        entry's ``__name__`` (or type name). Pass an explicit id when one
        `ResultCache` instance must distinguish entries.
    models : extra paged models this server multiplexes
        (`serve.models.ModelSpec` iterable or ``{model_id: spec}`` map;
        None = single-model server, byte-identical historical behavior).
        Each spec's entry pages in on the first ``submit(model=...)`` —
        registry hydration + warmup under a ``model_switch`` span — and
        pages out under the memory budget's byte bound when idle
        (module docstring "Multi-model residency"). Paged models get no
        degradation fallback and no anytime semantics; those stay
        properties of the pinned default entry.
    tenant_quota : one tenant's maximum share of ``queue_depth`` as a
        fraction (0 = off). With it, a ``submit(tenant=...)`` whose
        tenant already holds ``ceil(queue_depth × quota)`` queued items
        is rejected with `QueueFullError` while other tenants (and
        tenant-less submits) still admit — per-tenant admission
        isolation in front of the fair lanes.
    """

    # checked by the lock-discipline lint rule: these attributes may only
    # be mutated inside `with self._cond:` outside __init__
    _GUARDED_BY = {
        "_queues": "_cond",
        "_popped": "_cond",
        "_active": "_cond",
        "_pending": "_cond",
        "_tenant_pending": "_cond",
        "_closed": "_cond",
        "_started": "_cond",
    }

    def __init__(
        self,
        entry,
        buckets,
        *,
        max_batch: int = 8,
        max_wait_ms: float = 5.0,
        coalesce_ms: float = 0.0,
        queue_depth: int = 64,
        deadline_ms: float = 0.0,
        labeled: bool = True,
        warmup: bool = True,
        compilation_cache: bool = False,
        metrics: ServeMetrics | None = None,
        metrics_path: str | None = None,
        fallback_factory=None,
        dtype=np.float32,
        pipelined: bool = True,
        device=None,
        replica_id=None,
        auto_start: bool = True,
        health=None,
        slo=None,
        memory=None,
        registry=None,
        result_cache=None,
        cache_id: str | None = None,
        models=None,
        tenant_quota: float = 0.0,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if coalesce_ms < 0:
            raise ValueError("coalesce_ms must be >= 0")
        self._entry = entry
        # anytime serving (wam_tpu.anytime): an entry built by
        # make_anytime_entry flips the server into progressive-refinement
        # mode — deadlines deliver best-so-far AnytimeResults instead of
        # raising, converged batches exit early. WAM_TPU_NO_ANYTIME=1 is
        # the kill switch: the entry's full-n __call__ serves as a plain
        # entry and every anytime semantic (including min_confidence)
        # is disabled.
        import os

        self._anytime = (bool(getattr(entry, "wam_anytime", False))
                         and os.environ.get("WAM_TPU_NO_ANYTIME") != "1")
        self.table = buckets if isinstance(buckets, BucketTable) else BucketTable(buckets)
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.coalesce_s = coalesce_ms / 1e3
        self.queue_depth = queue_depth
        self.default_deadline_s = deadline_ms / 1e3 if deadline_ms else None
        self.labeled = labeled
        self.warmup = warmup
        self.compilation_cache = compilation_cache
        self.replica_id = replica_id
        self.metrics = metrics if metrics is not None else ServeMetrics(replica_id=replica_id)
        self.metrics_path = metrics_path
        self._fallback_factory = fallback_factory
        self.dtype = dtype
        self.pipelined = pipelined
        self._device = device
        self.degraded = False
        self._registry = registry
        # HydrationReport from start()'s bundle hydration (None: no
        # registry, or not started yet)
        self.registry_report = None

        # health plane (DESIGN.md "Health plane"): all three default off so
        # direct constructions keep their exact pre-health behavior
        if isinstance(health, obs_health.HealthMonitor):
            self._health = health
        elif health:
            cfg = health if isinstance(health, obs_health.HealthConfig) else None
            self._health = obs_health.HealthMonitor(cfg, replica_id=replica_id)
        else:
            self._health = None
        if isinstance(slo, obs_slo.SLOTracker):
            self._slo = slo
        elif slo:
            self._slo = obs_slo.SLOTracker(slo, replica_id=replica_id)
        else:
            self._slo = None
        if self._slo is not None:
            # the ledger hook: ServeMetrics.emit writes the slo_status row
            self.metrics.slo = self._slo
        if isinstance(memory, obs_memory.MemoryBudget):
            self._memory = memory
        elif memory:
            self._memory = obs_memory.MemoryBudget(
                int(memory), device=device, replica_id=replica_id)
        else:
            self._memory = None
        # result cache (module docstring): off by default so direct
        # constructions keep exact pre-cache request accounting
        if isinstance(result_cache, ResultCache):
            self._cache = result_cache
        elif result_cache:
            self._cache = ResultCache(
                int(result_cache),
                cache_id=cache_id if cache_id is not None else getattr(
                    entry, "__name__", type(entry).__name__))
        else:
            self._cache = None
        if self._cache is not None:
            # the ledger hook: ServeMetrics.emit writes the result_cache row
            self.metrics.result_cache = self._cache

        # multi-model residency (serve.models): the pager owns page-in /
        # eviction; queues and in-flight accounting key on (model, bucket)
        # with model None = the pinned default entry
        if models:
            self._pager = ModelPager(
                models,
                budget_bytes=(self._memory.budget_bytes
                              if self._memory is not None else None),
                replica_id=replica_id,
                ema_fn=self._model_ema_s,
                busy_fn=self._model_busy,
                retry_after_s=(self._memory.retry_after_s
                               if self._memory is not None else 1.0))
        else:
            self._pager = None
        self.tenant_quota = float(tenant_quota)
        if not 0.0 <= self.tenant_quota <= 1.0:
            raise ValueError(
                f"tenant_quota must be in [0, 1], got {tenant_quota}")

        self._cond = threading.Condition()
        # queue/in-flight keys: (model_id | None, Bucket) — one lane pair
        # per model × admitted bucket, precreated so the locked paths never
        # mutate the dict structure
        self._queues: dict[tuple, _Lanes] = {
            (None, b): _Lanes() for b in self.table}
        if self._pager is not None:
            for mid, spec in self._pager.specs.items():
                for b in self._model_buckets(spec):
                    self._queues[(mid, b)] = _Lanes()
        # popped-but-unresolved requests: the crash guard's reach into
        # batches already taken off the queues (see _fail_pending)
        self._popped: list[_Request] = []
        # popped-but-unfinished batches per (model, bucket): the in-flight
        # half of the projected drain time (queued items alone would read
        # an actively serving replica as idle)
        self._active: dict[tuple, int] = dict.fromkeys(self._queues, 0)
        self._pending = 0
        # queued items per tenant (admission quota accounting; tenant-less
        # submits are not tracked)
        self._tenant_pending: dict[str, int] = {}
        self._closed = False
        self._started = False
        self._worker: threading.Thread | None = None
        self._degrade_lock = threading.Lock()
        if auto_start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "AttributionServer":
        """Warm every bucket (one compile each — the only compiles this
        server will ever do), then launch the worker. Idempotent.

        Buckets warm CONCURRENTLY: each warmup is one independent trace +
        compile, jax tracing is thread-safe, and XLA compiles different
        graphs in parallel — so N buckets cold-start in ~max(compile)
        instead of Σ(compile) (the first slice of ROADMAP item 2). Per-
        bucket warmup seconds land in the ledger (`ServeMetrics.note_warmup`
        → ``warmup_s``). Caveat: entries that set process-global backend
        knobs at trace time (`tune.apply_tuned_synth_impl`) resolve them per
        (workload, shape) — one server's buckets share a workload, so the
        tuned knobs agree across its concurrent traces."""
        if self._started:
            return self
        if self._registry is not None and self._registry != "":
            # hydrate FIRST: seeded AOT entries make the bucket warmups
            # below zero-trace, the bundle's XLA cache files must exist
            # before the compilation cache initializes over that dir, and
            # the schedule snapshot must land before load_schedule_cache()
            # reads the table
            from wam_tpu.registry.client import resolve_client

            client = resolve_client(self._registry)
            if client is not None:
                self.registry_report = client.hydrate()
        if self.compilation_cache:
            from wam_tpu.config import enable_compilation_cache

            enable_compilation_cache()
        if self.warmup:
            # Load the tuned schedule table BEFORE the warmup compiles: the
            # entries' sample_batch_size="auto" resolution reads it at trace
            # time, so a tuned chunk must be visible to the very first trace
            # or the bucket compiles (and serves) the fallback law schedule
            # (`wam_tpu.tune`; use `python -m wam_tpu.prewarm` to populate
            # both this and the XLA cache offline).
            from wam_tpu.tune import load_schedule_cache

            load_schedule_cache()

            def _warm(bucket: Bucket) -> None:
                t0 = time.perf_counter()
                # compile-sentinel attribution: traces fired here are
                # expected warmup compiles, not steady-state retraces
                with obs_sentinel.label(
                    replica=self.replica_id,
                    bucket=bucket_key(bucket.shape),
                    phase="warmup",
                ):
                    out = self._sync_dispatch(*self._stage_zeros(bucket))
                    if self._health is not None and not getattr(
                            self._entry, "wam_health", False):
                        # non-fused entries compute health via a separate
                        # batch_stats dispatch; warm its per-shape compile
                        # here so the served window stays compile-free
                        jax.block_until_ready(obs_health.batch_stats(out))
                self.metrics.note_warmup(bucket.shape, time.perf_counter() - t0)
                if self._memory is not None:
                    # per-bucket HBM watermark right after the warmup
                    # dispatch: device peak-bytes where the backend reports
                    # them, the shape-derived estimate otherwise
                    self._memory.capture_watermark(
                        bucket_key(bucket.shape), self._estimate_bytes(bucket))

            if len(self.table) == 1:
                _warm(next(iter(self.table)))
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                    max_workers=min(len(self.table), 8),
                    thread_name_prefix="wam-serve-warmup",
                ) as pool:
                    list(pool.map(_warm, self.table))  # list(): re-raise failures
        self._worker = threading.Thread(
            target=self._worker_loop, name="wam-serve-worker", daemon=True
        )
        with self._cond:
            self._started = True
        self._worker.start()
        return self

    def close(self, emit_metrics: bool = True) -> None:
        """Stop intake, drain queued requests, join the worker, and (when
        ``metrics_path`` is set) flush the ledger."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join()
        if emit_metrics and self.metrics_path:
            from wam_tpu.results import JsonlWriter

            writer = JsonlWriter(self.metrics_path)
            if self.registry_report is not None:
                writer.write(self.registry_report.row())
            if self._pager is not None:
                self.metrics.models_resident = self.models_resident()
            self.metrics.emit(writer, config=self.describe())
        with self._cond:
            self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def describe(self) -> dict:
        return {
            "buckets": [list(b.shape) for b in self.table],
            "max_batch": self.max_batch,
            "max_wait_ms": self.max_wait_s * 1e3,
            "coalesce_ms": self.coalesce_s * 1e3,
            "result_cache": (self._cache.stats()
                             if self._cache is not None else None),
            "queue_depth": self.queue_depth,
            "labeled": self.labeled,
            "pipelined": self.pipelined,
            "degraded": self.degraded,
            "replica_id": self.replica_id,
            "device": str(self._device) if self._device is not None else None,
            "health": self._health.describe() if self._health is not None else None,
            "slo": (
                {k: vars(v) for k, v in self._slo.policy.items()}
                if self._slo is not None
                else None
            ),
            "memory": self._memory.describe() if self._memory is not None else None,
            "registry": (getattr(self._registry, "bundle", None)
                         or (str(self._registry) if self._registry else None)),
            "models": (self._pager.describe()
                       if self._pager is not None else None),
            "tenant_quota": self.tenant_quota,
        }

    # -- client side --------------------------------------------------------

    def submit(self, x, y=None, deadline_ms: float | None = None,
               qos: str = "interactive",
               min_confidence: float = 0.0,
               model: str | None = None,
               tenant: str | None = None) -> Future:
        """Enqueue one item (NO leading batch axis — a client batch is a
        sequence of submits, coalesced back together by the worker).
        ``qos`` picks the admission lane (module docstring "QoS lanes").
        ``model`` routes to a configured paged model (None = the default
        entry), paying the synchronous page-in when it is cold. ``tenant``
        is the request's fair-share identity: it keys the per-tenant lane
        round-robin, the admission quota, the result-cache partition, and
        the ``bucket@class@tenant`` SLO window. Returns a
        `concurrent.futures.Future` resolving to the item's attribution
        (leading axis stripped), or raising `ServeError`.

        On an ANYTIME server (entry built by
        `wam_tpu.anytime.make_anytime_entry`) the future resolves to an
        `AnytimeResult`: a closing ``deadline_ms`` window delivers the
        best-so-far map + confidence instead of raising
        `DeadlineExceededError`, and ``min_confidence`` is the floor every
        batch row must clear for the convergence early exit. A zero or
        negative ``deadline_ms`` is a client bug and fails at admission
        with `InvalidDeadlineError` (any server kind)."""
        if self.labeled and y is None:
            raise ValueError("labeled server: submit(x, y) needs a class label")
        if not self.labeled and y is not None:
            raise ValueError("unlabeled server: submit() must not carry a label")
        if qos not in QOS_CLASSES:
            raise ValueError(f"qos must be one of {QOS_CLASSES}, got {qos!r}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise InvalidDeadlineError(deadline_ms)
        if min_confidence:
            if not self._anytime:
                raise ValueError(
                    "min_confidence needs an anytime server (an entry built "
                    "by wam_tpu.anytime.make_anytime_entry)")
            if not 0.0 <= min_confidence <= 1.0:
                raise ValueError(
                    f"min_confidence must be in [0, 1], got {min_confidence}")
        if model is not None:
            if self._pager is None or model not in self._pager.specs:
                known = (sorted(self._pager.specs)
                         if self._pager is not None else [])
                raise ValueError(
                    f"unknown model {model!r}; configured paged models: "
                    f"{known}")
            if min_confidence:
                raise ValueError(
                    "min_confidence is an anytime semantic of the default "
                    "entry; paged models serve plain full-n results")
        x = np.asarray(x, self.dtype)
        bucket = self.table.select(x.shape)  # NoBucketError before any queueing
        if model is not None and (model, bucket) not in self._queues:
            raise ValueError(
                f"model {model!r} does not serve bucket "
                f"{bucket_key(bucket.shape)}")
        self.metrics.note_submit()
        ckey = None
        if self._cache is not None and self._anytime and model is None:
            # anytime results are NOT cached: what a request gets back
            # depends on the batch's deadline/convergence trajectory, so a
            # cached partial would violate the bit-identical-hit contract
            pass
        elif self._cache is not None:
            # consult BEFORE admission: a hit resolves immediately and
            # never touches the queue, memory admission, a batch slot —
            # or, for a cold paged model, the page-in itself
            ckey = self._cache.key(x, y, model=model)
            hit = self._cache.get(ckey, tenant=tenant)
            if hit is not None:
                self.metrics.note_cache_hit()
                fut: Future = Future()
                fut.set_result(hit)
                return fut
        if model is not None:
            # synchronous page-in on the submitting thread: the first
            # request for a cold model pays (and measures) the switch;
            # `MemoryAdmissionError` here is ordinary backpressure
            self._ensure_model(model)
        if self._memory is not None:
            retry_after = self._memory.admit(
                self._lkey(model, bucket), self._estimate_bytes(bucket))
            if retry_after is not None:
                self.metrics.note_reject()
                raise MemoryAdmissionError(
                    retry_after, bucket=self._lkey(model, bucket))
        now = time.perf_counter()
        if deadline_ms is None:
            deadline = (now + self.default_deadline_s) if self.default_deadline_s else None
        else:
            deadline = now + deadline_ms / 1e3
        req = _Request(x, y, bucket, now, deadline, qos=qos, ckey=ckey,
                       min_confidence=float(min_confidence),
                       model=model, tenant=tenant)
        if obs_tracing._STATE.enabled:
            ctx = obs_tracing.current_context()
            if ctx is None:
                # direct (fleet-less) submit: this server owns the request
                # root span, ended when the future resolves either way
                root = obs_tracing.start_span(
                    "request", cat="serve",
                    bucket="x".join(str(d) for d in bucket.shape),
                    replica=self.replica_id)
                ctx = root.context
                req.future.add_done_callback(
                    lambda f: root.end(
                        error=type(f.exception()).__name__
                        if f.exception() else None))
            req.ctx = ctx
        with self._cond:
            if self._closed or not self._started:
                raise ServerClosedError("server is not accepting requests")
            if self._worker is not None and not self._worker.is_alive():
                raise WorkerCrashedError(
                    "serve worker is not running; the server cannot serve")
            if self._pending >= self.queue_depth:
                self.metrics.note_reject()
                # the TARGET bucket's own drain: an idle bucket's clients
                # retry immediately instead of backing off behind an
                # unrelated hot bucket (the all-bucket sum stays the
                # fleet routing signal, projected_drain_s)
                raise QueueFullError(retry_after_s=self._drain_locked(bucket))
            if tenant is not None and self.tenant_quota > 0.0:
                # per-tenant admission quota: one tenant's queued share is
                # capped, so a flooding tenant hits backpressure while the
                # others keep admitting into the same bounded queue
                cap = max(1, int(self.queue_depth * self.tenant_quota))
                if self._tenant_pending.get(tenant, 0) >= cap:
                    self.metrics.note_reject()
                    raise QueueFullError(
                        retry_after_s=self._drain_locked(bucket))
            self._queues[(model, bucket)].append(req)
            self._pending += 1
            if tenant is not None:
                self._tenant_pending[tenant] = (
                    self._tenant_pending.get(tenant, 0) + 1)
            self._cond.notify_all()
        return req.future

    def attribute(self, x, y=None, deadline_ms: float | None = None,
                  qos: str = "interactive", min_confidence: float = 0.0,
                  model: str | None = None, tenant: str | None = None):
        """Blocking convenience wrapper: submit + wait."""
        return self.submit(x, y, deadline_ms=deadline_ms, qos=qos,
                           min_confidence=min_confidence,
                           model=model, tenant=tenant).result()

    # -- load signal --------------------------------------------------------

    def _drain_locked(self, bucket: Bucket | None = None) -> float:
        """Projected seconds to drain everything queued + in flight:
        (queued batches + active batches) × that bucket's EMA service time
        (`ServeMetrics.ema_service_s`, seeded until the first batch
        lands). With a ``bucket``: that bucket's own drain — the
        `QueueFullError.retry_after_s` estimate, so a rejection against an
        idle bucket does not inherit an unrelated hot bucket's backlog.
        Without: the all-bucket sum — the fleet's routing score. Caller
        holds ``_cond``."""
        total = 0.0
        for (mid, b), q in self._queues.items():
            if bucket is not None and b is not bucket:
                continue
            n_batches = -(-len(q) // self.max_batch) + self._active[(mid, b)]
            if n_batches:
                total += n_batches * self.metrics.ema_service_s(
                    b.shape, model=mid)
        return total

    def projected_drain_s(self) -> float:
        """Thread-safe all-bucket `_drain_locked` — the load-aware dispatch
        signal the fleet router reads per submit (`serve.fleet.FleetServer`)."""
        with self._cond:
            return self._drain_locked()

    def qos_depths(self) -> dict[str, int]:
        """Queued items per QoS lane across all buckets — the fleet's
        interactive-pressure routing term (`FleetServer._score`) and the
        pod heartbeat's ``qos_depth`` signal (`FleetServer.pod_signals`)."""
        with self._cond:
            return {
                "interactive": sum(len(q.interactive)
                                   for q in self._queues.values()),
                "batch": sum(len(q.batch) for q in self._queues.values()),
            }

    def admission_free(self) -> int:
        """Free admission slots right now (``queue_depth - pending``,
        floored at 0) — the pod heartbeat's ``queue_free`` signal: 0
        means a submit would bounce `QueueFullError`, and the pod router
        deprioritizes the hop (a reject costs a cross-host round-trip
        on the tcp transport)."""
        with self._cond:
            return max(0, self.queue_depth - self._pending)

    def health_ok(self) -> bool:
        """Quarantine predicate for the fleet router: True when no health
        monitor is attached, the replica is healthy, or its quarantine has
        aged into probation (`obs.health.HealthMonitor.ok`)."""
        return self._health is None or self._health.ok()

    def slo_penalty_s(self, bucket_shape) -> float:
        """Burn-rate routing penalty for one bucket (0 without a tracker
        or at/below burn 1.0) — added to the fleet's load score so a
        replica burning its error budget sheds load before it dies."""
        if self._slo is None:
            return 0.0
        return self._slo.penalty_s(bucket_key(bucket_shape))

    # -- multi-model residency (serve.models) --------------------------------

    @staticmethod
    def _lkey(model: str | None, bucket: Bucket) -> str:
        """Ledger/EMA/watermark key for one (model, bucket) lane: the
        plain bucket key for the default model (every historical key is
        preserved verbatim), ``model|bucket`` for paged models."""
        bkey = bucket_key(bucket.shape)
        return bkey if model is None else f"{model}|{bkey}"

    def _model_buckets(self, spec: ModelSpec) -> list[Bucket]:
        """The server buckets a spec serves: its declared subset (each
        shape must be an admitted bucket) or every bucket."""
        if spec.buckets is None:
            return list(self.table)
        out = []
        for shape in spec.buckets:
            shape = tuple(shape)
            match = next((b for b in self.table if b.shape == shape), None)
            if match is None:
                raise ValueError(
                    f"model {spec.model_id!r} declares bucket {shape}, "
                    "which is not in the server's bucket table")
            out.append(match)
        return out

    def _model_ema_s(self, model_id: str) -> float:
        """Mean EMA batch service time across one model's buckets — the
        pager's eviction weight (0.0 until the model served a batch)."""
        prefix = f"{model_id}|"
        emas = [v for k, v in self.metrics.ema_service_s().items()
                if k.startswith(prefix)]
        return sum(emas) / len(emas) if emas else 0.0

    def _model_busy(self, model_id: str) -> bool:
        """Does this model have queued or in-flight work? Evictions of
        busy models are refused (`ModelPager._make_room`)."""
        with self._cond:
            for key, q in self._queues.items():
                if key[0] == model_id and (len(q) or self._active[key]):
                    return True
        return False

    def models_resident(self) -> dict[str, int]:
        """``{model_id: footprint_bytes}`` of resident paged models — the
        fleet heartbeat signal and the pod router's model affinity."""
        return self._pager.resident() if self._pager is not None else {}

    def _ensure_model(self, model: str) -> None:
        """Make ``model`` resident, paying the page-in synchronously on
        this (submit) thread — the measured model-switch latency."""
        self._pager.ensure(model, self._page_in)

    def _page_in(self, spec: ModelSpec):
        """One model's page-in, under its build lock (`ModelPager.ensure`):
        hydrate its registry bundle (seeded AOT executables make the
        warmups below replays, not compiles), build the entry, and warm
        every bucket the model serves — all inside one ``model_switch``
        span so traces show the switch cost end-to-end. Returns
        ``(entry, footprint_bytes)``."""
        buckets = self._model_buckets(spec)
        est = int(spec.est_bytes) or sum(
            self._estimate_bytes(b) for b in buckets)
        with obs_tracing.span(
            "model_switch", cat="serve", model=spec.model_id,
            replica=self.replica_id,
        ):
            client = None
            if spec.registry is not None and spec.registry != "":
                from wam_tpu.registry.client import resolve_client

                client = resolve_client(spec.registry)
            if client is not None:
                client.hydrate()
            entry = spec.factory()
            for bucket in buckets:
                with obs_sentinel.label(
                    replica=self.replica_id,
                    bucket=self._lkey(spec.model_id, bucket),
                    phase="pagein",
                ):
                    jax.block_until_ready(entry(*self._stage_zeros(bucket)))
                if self._memory is not None:
                    self._memory.capture_watermark(
                        self._lkey(spec.model_id, bucket),
                        self._estimate_bytes(bucket))
        return entry, est

    # -- worker side --------------------------------------------------------

    def _zeros_batch(self, bucket: Bucket):
        x = np.zeros((self.max_batch,) + bucket.shape, self.dtype)
        y = np.zeros((self.max_batch,), np.int32) if self.labeled else None
        return x, y

    def _estimate_bytes(self, bucket: Bucket) -> int:
        """Shape-derived device-footprint estimate for one bucket — the
        memory-admission projection and the watermark fallback."""
        return obs_memory.estimate_entry_bytes(
            bucket.shape, self.max_batch, np.dtype(self.dtype).itemsize)

    def _stage_zeros(self, bucket: Bucket):
        """Warmup batch, committed to this server's device when pinned so
        the warmup compile targets the replica's own chip."""
        xs, ys = self._zeros_batch(bucket)
        if self._device is None:
            return xs, ys
        return put_committed((xs, ys), self._device)

    def _call_entry(self, xs, ys):
        if self.degraded:
            self.metrics.note_fallback()
        return self._entry(xs, ys)

    def _recover(self, xs, ys):
        """Called from an ``except`` block after the entry failed (at
        dispatch or at the deferred harvest): degrade to the CPU fallback
        when the accelerator has actually gone away (forced re-probe
        distinguishes a device loss from a plain bug — an in-process
        exception with a healthy accelerator re-raises) and replay the
        failed batch on it. ``xs``/``ys`` are the kept host buffers. The
        degrade transition is serialized so concurrent bucket warmups
        cannot build the fallback entry twice."""
        if self._fallback_factory is None:
            raise
        with self._degrade_lock:
            if self.degraded:
                raise  # already on the fallback: this failure is its own
            from wam_tpu import config

            if config.probe_accelerator(force=True):
                raise  # accelerator healthy: the failure is not the device
            self._entry = self._fallback_factory()
            self.degraded = True
        self.metrics.note_fallback()
        out = jax.device_get(self._entry(xs, ys))
        # a health-fused fallback returns (out, hvec); replay consumers
        # only want the result tree (the batch already failed health-wise)
        if getattr(self._entry, "wam_health", False):
            out = out[0]
        return out

    def _sync_dispatch(self, xs, ys):
        """Dispatch + harvest in one step (warmup and the non-pipelined
        loop)."""
        try:
            return jax.device_get(self._call_entry(xs, ys))
        except Exception:
            return self._recover(xs, ys)

    def _tenants_left_locked(self, reqs: list[_Request]) -> None:
        """Release the per-tenant admission slots for requests leaving the
        lanes (popped into a batch or expired at pop). Callers already
        hold ``_cond``; the re-entrant acquire (Condition wraps an RLock)
        keeps the guarded mutation lexically inside the lock."""
        with self._cond:
            for r in reqs:
                if r.tenant is not None and r.tenant in self._tenant_pending:
                    n = self._tenant_pending[r.tenant] - 1
                    if n > 0:
                        self._tenant_pending[r.tenant] = n
                    else:
                        del self._tenant_pending[r.tenant]

    def _take_batch(self, block: bool = True):
        """Pop a ready batch (bucket full, admission window expired,
        deadline pressure, or draining at close). Returns ``((model,
        bucket), requests, queue_depth_at_pop, expired)``, None when closed and
        drained, or — with ``block=False`` — the `_NOT_READY` sentinel as
        soon as nothing is poppable *right now* (the pipelined worker uses
        this to go harvest the in-flight batch instead of sleeping on the
        queue). ``expired`` requests left the lanes at pop time without
        consuming a take slot; a pop may return ONLY expiries (empty
        ``requests`` — no ``_active`` increment, the worker just fails
        them and comes back)."""
        with self._cond:
            while True:
                if self._pending == 0:
                    if self._closed:
                        return None
                    if not block:
                        return _NOT_READY
                    self._cond.wait(0.05)
                    continue
                # serve the oldest head, preferring lanes with
                # interactive work (lanes drain interactive-first)
                key = min(
                    (k for k, q in self._queues.items() if len(q)),
                    key=lambda k: (0 if self._queues[k].interactive else 1,
                                   self._queues[k].head().t_submit),
                )
                bucket = key[1]
                q = self._queues[key]
                now = time.perf_counter()
                # deadline hygiene: expiries leave the lanes BEFORE slot
                # accounting, so they cannot displace live requests from
                # the take. Returned immediately (no pop) so their futures
                # fail outside the lock with no added hold time. An ANYTIME
                # server never drops: a lapsed deadline still gets
                # dispatched and delivers its best-so-far map (the driver
                # guarantees at least one stride).
                expired = [] if self._anytime else q.drop_expired(now)
                if expired:
                    self._pending -= len(expired)
                    self._tenants_left_locked(expired)
                    # crash-guard reach: until the worker fails them they
                    # live nowhere else (_fail_pending scans _popped)
                    self._popped = [r for r in self._popped
                                    if not r.future.done()]
                    self._popped.extend(expired)
                    return key, [], self._pending, expired
                head_wait = now - q.head().t_submit
                # the admission window: coalesce_ms when set, else the
                # historical max_wait bound (coalesce_ms=0 == old behavior)
                window_s = self.coalesce_s if self.coalesce_s > 0 else self.max_wait_s
                pressed = False
                dmin = q.min_deadline() if self.coalesce_s > 0 else None
                if dmin is not None:
                    # early release: the tightest queued deadline cannot
                    # survive sitting out the rest of the window plus one
                    # EMA batch service — go now, don't hold it to death
                    ema = self.metrics.ema_service_s(
                        bucket.shape, model=key[0])
                    pressed = dmin - now <= (window_s - head_wait) + ema
                if (
                    len(q) >= self.max_batch
                    or head_wait >= window_s
                    or pressed
                    or self._closed  # draining: don't sit out the window
                ):
                    take = q.pop(self.max_batch)
                    self._pending -= len(take)
                    self._tenants_left_locked(take)
                    self._active[key] += 1  # in flight until _finish_active
                    # only the worker thread mutates _popped; resolved
                    # entries age out here (at most ~2 batches stay live)
                    self._popped = [r for r in self._popped
                                    if not r.future.done()]
                    self._popped.extend(take)
                    return key, take, self._pending + len(take), []
                if not block:
                    return _NOT_READY
                wait_s = window_s - head_wait
                if dmin is not None:
                    # wake in time for the deadline-pressure release
                    wait_s = min(wait_s, max(dmin - now - ema, 0.0))
                self._cond.wait(max(wait_s, 1e-4))

    def _worker_loop(self):
        try:
            self._worker_loop_inner()
        except BaseException as e:  # noqa: BLE001 - crash guard (see below)
            # The loop body only reaches here through a bug outside the
            # guarded entry/recover paths (or an injected stager fault) —
            # without this guard every queued future would hang forever.
            self._fail_pending(WorkerCrashedError(
                f"serve worker crashed: {e!r}"))
            raise

    def _fail_pending(self, exc: Exception) -> None:
        """Stop intake and fail every unresolved request with ``exc`` —
        both the queued ones (the crashed worker can never pop them) and
        the popped-but-unresolved ones the crash stranded mid-batch."""
        with self._cond:
            self._closed = True
            reqs = [r for q in self._queues.values() for r in q.clear()]
            self._pending = 0
            self._tenant_pending = {}
            reqs += [r for r in self._popped if not r.future.done()]
            self._popped = []
            self._cond.notify_all()
        for r in reqs:
            r.future.set_exception(exc)
        if reqs:
            self.metrics.note_failed(len(reqs))

    def _worker_loop_inner(self):
        inflight: _Inflight | None = None
        while True:
            # Only block on the queue when nothing is in flight; otherwise
            # peek — either launch the next batch behind the in-flight one
            # or, with nothing poppable, harvest and come back.
            got = self._take_batch(block=inflight is None)
            if got is None:  # closed and drained
                if inflight is not None:
                    self._complete(inflight)
                return
            if got is _NOT_READY:
                self._complete(inflight)
                inflight = None
                continue
            key, reqs, depth, expired_at_pop = got
            # pop-time expiries never held a take slot (_take_batch drops
            # them before slot accounting); fail them outside the lock
            self._fail_expired(key[1], expired_at_pop)
            if not reqs:
                continue  # expiry-only wake: nothing was popped
            now = time.perf_counter()
            live, expired = [], []
            for r in reqs:
                # race-window recheck (pop -> here); _take_batch already
                # filtered, so this only catches deadlines that lapsed in
                # the microseconds since. Anytime servers serve lapsed
                # deadlines too (best-so-far delivery, never a drop).
                (expired if not self._anytime and r.deadline is not None
                 and now > r.deadline else live).append(r)
            self._fail_expired(key[1], expired)
            if not live:
                self._finish_active(key)
                continue
            batch = self._launch_batch(key, live, depth)
            if batch is None:  # failed at dispatch; futures already failed
                self._finish_active(key)
                continue
            if not self.pipelined:
                self._complete(batch)
                continue
            if inflight is not None:
                # batch k+1 is now queued on the device; harvesting k here
                # is exactly the overlap window
                self._complete(inflight)
            inflight = batch

    def _fail_expired(self, bucket: Bucket, expired: list[_Request]) -> None:
        """Fail expired requests with `DeadlineExceededError` and account
        them (per-QoS-class SLO errors)."""
        if not expired:
            return
        for r in expired:
            r.future.set_exception(
                DeadlineExceededError("deadline lapsed while queued")
            )
        self.metrics.note_expired(len(expired))
        if self._slo is not None:
            bkey = bucket_key(bucket.shape)
            groups: dict[tuple, int] = {}
            for r in expired:
                groups[(r.qos, r.tenant)] = groups.get((r.qos, r.tenant), 0) + 1
            for (qos, tenant), n in groups.items():
                self._slo.note_error(bkey, n, qos=qos, tenant=tenant)

    def _finish_active(self, key: tuple) -> None:
        with self._cond:
            self._active[key] -= 1

    def _launch_batch(self, key: tuple, live: list[_Request], depth: int):
        """Assemble the padded host batch, stage it to the device (async
        upload, committed to this server's device when pinned), and
        dispatch the entry WITHOUT harvesting the result."""
        mid, bucket = key
        n_real = len(live)
        with self.metrics.stages.stage("assemble"):
            xs = np.stack([pad_item(r.x, bucket) for r in live])
            if n_real < self.max_batch:
                # pad rows REPLICATE the first real item: duplicates cannot
                # move the engines' per-block max-normalizer, so real rows
                # come back identical to a full batch (serve.buckets)
                reps = np.repeat(xs[:1], self.max_batch - n_real, axis=0)
                xs = np.concatenate([xs, reps])
            if self.labeled:
                ys = np.asarray([r.y for r in live], np.int32)
                if n_real < self.max_batch:
                    ys = np.concatenate(
                        [ys, np.repeat(ys[:1], self.max_batch - n_real)]
                    )
            else:
                ys = None
            staged = put_committed((xs, ys), self._device)
        t0 = time.perf_counter()
        hvec = None
        cvec = None
        anytime_info = None
        entry = self._entry if mid is None else self._pager.entry(mid)
        try:
            with obs_sentinel.label(
                replica=self.replica_id,
                bucket=self._lkey(mid, bucket),
                phase="serve",
            ), self.metrics.stages.stage("dispatch"):
                if self._anytime and mid is None:
                    # progressive refinement: drive the begin/step/finalize
                    # stride loop (`anytime.driver` — the shared policy).
                    # Batch policy over the LIVE rows only (pad rows
                    # replicate row 0 and must not hold the batch open):
                    # tightest deadline, highest confidence floor.
                    from wam_tpu.anytime.driver import drive_anytime

                    deadlines = [r.deadline for r in live
                                 if r.deadline is not None]
                    out, cvec, anytime_info = drive_anytime(
                        self._entry, *staged,
                        deadline=min(deadlines) if deadlines else None,
                        min_confidence=max(
                            (r.min_confidence for r in live), default=0.0),
                        n_rows=n_real)
                elif mid is None:
                    out = self._call_entry(*staged)
                else:
                    # paged-model dispatch: the model's own compiled entry,
                    # no fallback/degradation ladder (those are properties
                    # of the default entry)
                    out = entry(*staged)
                if self._health is not None:
                    if getattr(entry, "wam_health", False):
                        # fused entry: the vector is a leaf of the same
                        # compiled program
                        out, hvec = out
                    else:
                        # post-hoc on-device reduction (fake/plain entries):
                        # one extra tiny DISPATCH, still harvested in the
                        # worker's single existing device_get
                        hvec = obs_health.batch_stats(out)
        except Exception:
            try:
                if mid is not None:
                    raise  # no fallback entry for paged models
                out = self._recover(xs, ys)  # already host-side on success
                hvec = None
            except Exception as e:
                for r in live:
                    r.future.set_exception(e)
                self.metrics.note_failed(n_real)
                if self._slo is not None:
                    bkey = bucket_key(bucket.shape)
                    for qos in QOS_CLASSES:
                        k = sum(1 for r in live if r.qos == qos)
                        if k:
                            self._slo.note_error(bkey, k, qos=qos)
                return None
        return _Inflight(bucket, live, depth, xs, ys, t0, out, hvec,
                         cvec=cvec, anytime=anytime_info, model=mid)

    def _complete(self, batch: _Inflight):
        """Harvest an in-flight batch (block on the device result — where
        async entry failures surface) and distribute rows to futures. The
        per-bucket service-time EMA feeding retry-after / routing updates
        inside `ServeMetrics.note_batch`."""
        live, n_real = batch.live, len(batch.live)
        bkey = bucket_key(batch.bucket.shape)
        healthy = True
        conf_host = None
        try:
            try:
                with self.metrics.stages.stage("harvest"):
                    if batch.anytime is not None:
                        # anytime batch: the confidence vector (and health
                        # vector, when on) rides the batch's ONE counted
                        # result fetch — `evalsuite.fan.device_fetch`, so
                        # fetch-accounting probes see exactly one fetch per
                        # served batch with checkpointing on
                        from wam_tpu.evalsuite.fan import device_fetch

                        if batch.hvec is not None:
                            out, conf_host, hvec_host = device_fetch(
                                (batch.out, batch.cvec, batch.hvec))
                        else:
                            out, conf_host = device_fetch(
                                (batch.out, batch.cvec))
                            hvec_host = None
                    elif batch.hvec is not None:
                        # the health vector rides the batch's one fetch
                        out, hvec_host = jax.device_get((batch.out, batch.hvec))
                    else:
                        out = jax.device_get(batch.out)
                        hvec_host = None
            except Exception:
                try:
                    if batch.model is not None:
                        raise  # no fallback entry for paged models
                    out = self._recover(batch.xs, batch.ys)
                    hvec_host = None
                    # the fallback entry is a plain full-n one: replayed
                    # rows distribute as ordinary attributions
                    batch.anytime = None
                    conf_host = None
                except Exception as e:
                    for r in live:
                        r.future.set_exception(e)
                    self.metrics.note_failed(n_real)
                    if self._slo is not None:
                        for qos in QOS_CLASSES:
                            k = sum(1 for r in live if r.qos == qos)
                            if k:
                                self._slo.note_error(bkey, k, qos=qos)
                    return
            if self._health is not None and hvec_host is not None:
                # recorded BEFORE rows distribute so a sequential client's
                # next submit observes the updated health_ok() verdict
                healthy = self._health.note(hvec_host, bucket=bkey)
            service_s = time.perf_counter() - batch.t0
            confidences: list[float] = []
            with self.metrics.stages.stage("distribute"):
                done = time.perf_counter()
                for i, r in enumerate(live):
                    row = jax.tree_util.tree_map(lambda a: np.asarray(a)[i], out)
                    if batch.anytime is not None:
                        # anytime delivery: the row plus its certainty —
                        # never cached (submit kept ckey None)
                        from wam_tpu.anytime.result import AnytimeResult
                        from wam_tpu.anytime.state import (
                            SLOT_CONFIDENCE, SLOT_DELTA, SLOT_REL_SEM)

                        conf = float(conf_host[i, SLOT_CONFIDENCE])
                        confidences.append(conf)
                        r.future.set_result(AnytimeResult(
                            attribution=row,
                            confidence=conf,
                            n_used=batch.anytime["n_used"],
                            n_total=batch.anytime["n_total"],
                            complete=batch.anytime["complete"],
                            converged=batch.anytime["converged"],
                            rel_sem=float(conf_host[i, SLOT_REL_SEM]),
                            delta=float(conf_host[i, SLOT_DELTA])))
                        continue
                    if (self._cache is not None and r.ckey is not None
                            and not self.degraded):
                        # populate at harvest (host-side rows). Degraded
                        # batches are not cached: the CPU-rebuilt entry's
                        # float rounding differs from the accelerator's,
                        # and mixing provenances would break the
                        # bit-identical-hit contract
                        self._cache.put(r.ckey, row, tenant=r.tenant)
                    r.future.set_result(row)
            if obs_tracing._STATE.enabled:
                # retroactive per-request phases: the worker only knows a
                # request's queue wait once its batch pops, so the spans are
                # recorded from timestamps already in hand — together they
                # tile submit->done, the trace_report coverage contract
                for r in live:
                    obs_tracing.record_span(
                        "queue_wait", r.t_submit, batch.t0, parent=r.ctx,
                        cat="serve", bucket=bkey, replica=self.replica_id)
                    obs_tracing.record_span(
                        "service", batch.t0, done, parent=r.ctx,
                        cat="serve", bucket=bkey, replica=self.replica_id,
                        n_real=n_real)
            latencies_s = [done - r.t_submit for r in live]
            self.metrics.note_batch(
                bucket_shape=batch.bucket.shape,
                n_real=n_real,
                max_batch=self.max_batch,
                pad_waste=float(np.mean([batch.bucket.pad_waste(r.x.shape) for r in live])),
                queue_depth=batch.depth,
                service_s=service_s,
                queue_waits_s=[batch.t0 - r.t_submit for r in live],
                latencies_s=latencies_s,
                qos=[r.qos for r in live],
                model_id=batch.model,
                tenants=[r.tenant for r in live],
            )
            if batch.anytime is not None:
                self.metrics.note_anytime(
                    bucket_shape=batch.bucket.shape,
                    n_used=batch.anytime["n_used"],
                    n_total=batch.anytime["n_total"],
                    strides=batch.anytime["strides"],
                    converged=batch.anytime["converged"],
                    deadline_hit=batch.anytime["deadline_hit"],
                    confidences=confidences)
            if self._slo is not None:
                for i, (r, lat) in enumerate(zip(live, latencies_s)):
                    self._slo.note(
                        bkey, latency_s=lat, ok=True, healthy=healthy,
                        qos=r.qos, tenant=r.tenant,
                        confidence=confidences[i] if confidences else 1.0)
        finally:
            self._finish_active((batch.model, batch.bucket))
