"""Multi-chip attribution fleet (ROADMAP item 1 — the scale-out layer).

`AttributionServer` deliberately owns exactly one device: one worker
thread, one chip, one bounded queue. `FleetServer` goes wider without
touching that invariant — it spins up one `AttributionServer` REPLICA per
chip (each pinned to its device via the runtime's ``device=`` commit and
carrying its own `ServeMetrics` ledger), and puts a shared admission +
routing layer in front:

- **Load-aware routing**: every admitted item is routed to the live
  replica with the lowest projected drain time —
  ``server.projected_drain_s()`` (per-bucket (queued + in-flight batches) ×
  per-bucket EMA service time) plus the item's own bucket EMA on that
  replica, so a replica that is merely *bad at this bucket* loses to an
  idle one even when both have empty queues. Ties resolve to the lowest
  replica id (deterministic for tests).
- **Shared admission**: the fleet rejects (`QueueFullError`) only when
  EVERY live replica's bounded queue rejected, carrying the smallest
  ``retry_after_s`` any replica offered. One hot replica never turns away
  work the rest of the fleet could absorb.
- **Oversize dispatch**: a whole batch larger than one chip's bucket cap
  (``max_batch``) would historically be the caller's problem; here
  `attribute_batch` dispatches it DATA-PARALLEL over the fleet mesh
  (`parallel.replica_mesh`) instead — rows are bucket-padded, replicate-
  padded up to the fleet-wide batch shape (``n_replicas × max_batch``,
  so the oversize graph compiles once per bucket), committed with a
  ``('data',)``-sharded `NamedSharding`, and pushed through a dedicated
  pjit'd entry built by the same ``entry_factory`` (id
  ``OVERSIZE_ENTRY_ID``). Per-row computations shard row-wise, so the
  oversize result is bit-identical to the single-chip entry on the same
  padded batch (tests/test_fleet.py pins this). AOT keys for this entry
  must be replica-count tagged (`serve.entry.fleet_aot_key`).
- **Oversize ITEMS (sequence-sharded route)**: a request whose ITEM shape
  exceeds every configured bucket used to be a hard `NoBucketError` from
  `attribute_batch`. With a ``seq_factory``, the fleet instead runs the
  whole batch through a sequence-sharded entry over the fleet mesh
  (`parallel.seq_estimators.SeqShardedWam` under the hood of a typical
  factory): the signal's sequence axis shards across chips, so a single
  long-context item that no chip could bucket still resolves — one fused
  dispatch per sample (the estimator's one-jit step), obs span
  ``seq_sharded_batch``, compile-sentinel labels (phase
  ``"seq_sharded"``; the estimator's jits self-report, so
  ``assert_no_retrace`` verifies the warm path), and a ``note_batch``
  ledger row on the shared oversize `ServeMetrics`. Per-item `submit`
  still raises `NoBucketError` — the route is batch-level and blocking.
- **Replica death**: a request whose entry raised (anything that is not a
  per-request `ServeError`) marks its replica dead fleet-wide and is
  re-routed to the survivors; items queued behind the failure drain with
  the same per-request re-route as their batches fail. A request that
  fails on every live replica propagates the last error
  (`NoLiveReplicaError` when none is left). Note the documented trade: a
  deterministic per-request bug (poison pill) is indistinguishable from a
  chip loss at this layer and can take one replica down per retry — the
  single-chip server's probe-before-degrade semantics still apply INSIDE
  each replica when it has a ``fallback_factory``; the fleet layer only
  reroutes. While any replica is dead, oversize batches fall back to
  routed per-item submits (the fleet mesh spans every chip, dead or not).

``entry_factory(replica_id, metrics) -> entry`` builds one serving entry
per replica (0..N-1) plus one for the oversize path
(``OVERSIZE_ENTRY_ID``). Each replica needs its OWN jitted entry object so
its ``on_trace`` hook counts that replica's compiles — the ledger
invariant is ``compile_count == n_buckets`` per replica, one more set on
the oversize entry when it is used. A typical factory::

    entry_factory = lambda rid, m: wam.serve_entry(on_trace=m.note_compile)

Warmup runs CONCURRENTLY across replicas (and, inside each replica,
across buckets — `AttributionServer.start`), so an N-chip fleet cold-
starts in ~max(bucket compile) rather than N × Σ(compile).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field

import jax
import numpy as np

from wam_tpu.obs import sentinel as obs_sentinel
from wam_tpu.obs import tracing as obs_tracing
from wam_tpu.pipeline.stager import put_committed
from wam_tpu.serve.buckets import (
    Bucket,
    BucketTable,
    NoBucketError,
    bucket_key,
    pad_item,
)
from wam_tpu.serve.metrics import EMA_SEED_S, FleetMetrics, ServeMetrics
from wam_tpu.serve.models import ModelSpec
from wam_tpu.serve.result_cache import ResultCache
from wam_tpu.serve.runtime import (
    QOS_CLASSES,
    AttributionServer,
    DeadlineExceededError,
    InvalidDeadlineError,
    QueueFullError,
    ServeError,
    ServerClosedError,
)

__all__ = ["FleetServer", "NoLiveReplicaError", "OVERSIZE_ENTRY_ID",
           "INTERACTIVE_DEPTH_WEIGHT", "MODEL_PAGEIN_PENALTY_S"]

# entry_factory's replica_id for the fleet-wide oversize pjit entry
OVERSIZE_ENTRY_ID = "fleet"

# routing penalty (seconds) for sending a paged model's request to a
# replica where that model is NOT resident: a page-in (hydration + first
# dispatch) is far dearer than a warm dispatch, so the router prefers
# replicas already holding the model — but a loaded resident replica can
# still lose to an idle cold one once its drain exceeds this
MODEL_PAGEIN_PENALTY_S = 0.25

# routing weight on a replica's queued-interactive depth (`_score`): each
# max_batch worth of queued interactive work on a replica makes it look
# this many bucket-EMAs busier, so latency-sensitive traffic spreads away
# from interactive-loaded replicas harder than raw drain alone implies
INTERACTIVE_DEPTH_WEIGHT = 0.5


class NoLiveReplicaError(ServeError):
    """Every replica is dead (or rejected this request after deaths) — the
    fleet cannot serve it RIGHT NOW. ``retry_after_s`` estimates when a
    supervised restart will have a replica back (None when the fleet is
    unsupervised or every dead replica escalated to permanent): with it,
    `serve.retry.RetryPolicy` floors its backoff at the restart window
    and treats fleet-wide death as backpressure instead of exhausting
    its attempts against a fleet that is seconds from recovering."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclass
class _Replica:
    rid: int
    device: object
    server: AttributionServer
    metrics: ServeMetrics
    alive: bool = True


@dataclass
class _FleetRequest:
    """One admitted item's routing state: the grown ``tried`` set is what
    makes re-dispatch after a replica death converge."""

    x: np.ndarray
    y: int | None
    bucket: Bucket
    deadline_at: float | None  # perf_counter timestamp, None = no deadline
    future: Future
    qos: str = "interactive"
    # anytime serving: the per-request confidence floor, threaded to
    # whichever replica wins the route (wam_tpu.anytime)
    min_confidence: float = 0.0
    # multi-model routing: which paged model serves this request (None =
    # the default entry); survives re-routes like the rest of the state
    model: str | None = None
    # fair-share identity: lanes/quota/cache-partition/SLO-window key
    tenant: str | None = None
    # fleet-tier result-cache key (None = cache off): computed once at
    # submit, survives re-routes, populated from whichever replica wins
    ckey: str | None = None
    tried: set = field(default_factory=set)
    # obs trace identity: every admission/queue/service span of this
    # request (including re-routes after a death) parents here
    ctx: tuple | None = None


class FleetServer:
    """One serve worker per chip behind shared admission + load-aware
    routing (module docstring). The client surface mirrors
    `AttributionServer` (`submit`/`attribute`/`close`/context manager) plus
    `attribute_batch` for whole batches incl. the oversize pjit path.

    Parameters mirror `AttributionServer` where shared; fleet-specific:

    replicas : worker count (one per chip). None = every visible device.
    devices : explicit device list (default `jax.devices()`); the first
        ``replicas`` entries become the fleet.
    oversize : "pjit" dispatches oversize batches data-parallel over the
        fleet mesh; "fanout" always splits them into routed per-item
        submits (no fleet-wide graph, no extra compile).
    seq_factory : optional ``seq_factory(mesh) -> entry`` building the
        sequence-sharded handler for ITEM shapes no bucket admits;
        ``entry(xs, ys)`` (``ys=None`` on an unlabeled fleet) must accept
        the whole host batch and return the stacked attribution (e.g. a
        `WaveletAttribution1D(..., mesh=mesh).smooth_wam` closure). Built
        LAZILY on the first oversize-item batch — a fleet that never sees
        one never traces the seq graph. Without it, such batches keep
        raising `NoBucketError` (module docstring).
    queue_depth : per-replica bound — total fleet admission capacity is
        ``replicas × queue_depth``.
    metrics : a shared `FleetMetrics` (fresh when None); per-replica
        `ServeMetrics` are created through it so the fleet summary sees
        every ledger.
    prom_port : when not None, serve the obs registry in Prometheus text
        format at ``GET http://127.0.0.1:{prom_port}/metrics`` for this
        fleet's lifetime (`wam_tpu.obs.start_metrics_server`; pass 0 to
        bind an ephemeral port — read ``fleet.prom_server.server_port``).
    health : numeric-health monitoring per replica — ``True`` or a
        `wam_tpu.obs.HealthConfig` (each replica gets its OWN monitor, so
        quarantine is per-chip). A replica whose batches go non-finite
        ``quarantine_after`` times in a row is routed around like a death,
        but recovers after ``recovery_s`` (`AttributionServer` docs).
        Quarantined replicas remain LAST-RESORT candidates — a request is
        never failed while any live replica exists.
    slo : per-bucket service objectives (`wam_tpu.obs.parse_slo` spec or
        policy dict), tracked per replica; a replica's burn-rate adds a
        routing penalty (`AttributionServer.slo_penalty_s`) so an
        objective-violating replica sheds load before it pages.
    memory_budget : per-replica HBM budget in BYTES — cold-bucket
        admission control (`wam_tpu.obs.MemoryBudget`); each replica gets
        its own budget on its own device.
    supervise : replica supervision (`serve.supervisor.ReplicaSupervisor`):
        ``True`` or a `SupervisorConfig` restarts dead replicas with
        backoff + jitter and escalates crash loops to permanent-dead;
        None/False (default) keeps the historical permanent-on-first-death
        semantics. In-flight/queued work re-routes to survivors either way
        — supervision only changes whether the replica comes BACK.
    registry : compile-artifact bundle (`wam_tpu.registry`): a bundle path
        or `RegistryClient`, hydrated ONCE fleet-wide before the replicas
        warm (the AOT/XLA/schedule caches are process-local, so one
        hydration serves every replica) and AGAIN before each supervisor
        rebuild (idempotent — already-present artifacts are skipped, but a
        cache wiped under a running fleet re-seeds instead of recompiling).
        Can also be passed to `start(registry=...)`. Same silent-miss
        fallback as `AttributionServer`.
    coalesce_ms : per-replica cross-request admission window
        (`AttributionServer` "Coalescing"); forwarded to every replica so
        routed single-item submits pack into full bucket dispatches.
    result_cache : ONE shared content-addressed result cache at the fleet
        admission tier (int byte budget or a `ResultCache`): `submit`
        consults it before routing (a hit costs no replica slot),
        `_harvest` populates it from whichever replica computed the row.
        Replicas themselves carry no cache.
    cache_id : entry identity baked into fleet cache keys (defaults to
        the entry factory's ``__name__``).
    models : additional paged model families served by every replica
        (`serve.models.ModelSpec` list/dict; `AttributionServer` docs).
        Fleet-level spec factories take ``(replica_id, metrics)`` like
        ``entry_factory`` — each replica wraps them into its own zero-arg
        closures, so per-replica compile accounting holds for paged
        models too. Route with ``submit(model=...)``; the router prefers
        replicas where the model is already resident
        (`MODEL_PAGEIN_PENALTY_S`).
    tenant_quota : per-tenant admission-queue share in (0, 1], forwarded
        to every replica (`AttributionServer` docs); 0 disables quotas.
    """

    # checked by the lock-discipline lint rule: mutations outside __init__
    # must hold the mapped lock
    _GUARDED_BY = {
        "_closed": "_lock",
        "_started": "_lock",
        "_canary": "_lock",
        "_canary_fp": "_lock",
        "_canary_t0": "_lock",
        "_seq_entry": "_os_lock",
    }

    def __init__(
        self,
        entry_factory,
        buckets,
        *,
        replicas: int | None = None,
        devices=None,
        max_batch: int = 8,
        max_wait_ms: float = 5.0,
        coalesce_ms: float = 0.0,
        queue_depth: int = 64,
        deadline_ms: float = 0.0,
        labeled: bool = True,
        warmup: bool = True,
        compilation_cache: bool = False,
        metrics: FleetMetrics | None = None,
        metrics_path: str | None = None,
        oversize: str = "pjit",
        seq_factory=None,
        dtype=np.float32,
        pipelined: bool = True,
        auto_start: bool = True,
        prom_port: int | None = None,
        health=None,
        slo=None,
        memory_budget=None,
        supervise=None,
        registry=None,
        result_cache=None,
        cache_id: str | None = None,
        models=None,
        tenant_quota: float = 0.0,
    ):
        if not callable(entry_factory):
            raise TypeError("entry_factory must be callable(replica_id, metrics)")
        if oversize not in ("pjit", "fanout"):
            raise ValueError(f"oversize must be 'pjit' or 'fanout', got {oversize!r}")
        devices = list(jax.devices()) if devices is None else list(devices)
        n = len(devices) if replicas is None else int(replicas)
        if not 1 <= n <= len(devices):
            raise ValueError(f"replicas={n} with {len(devices)} visible devices")
        self.devices = devices[:n]
        self.n_replicas = n
        self.table = buckets if isinstance(buckets, BucketTable) else BucketTable(buckets)
        self.max_batch = max_batch
        self.default_deadline_s = deadline_ms / 1e3 if deadline_ms else None
        self.labeled = labeled
        self.metrics = metrics if metrics is not None else FleetMetrics()
        self.metrics_path = metrics_path
        self.oversize = oversize
        self.dtype = dtype
        self._lock = threading.Lock()
        self._closed = False
        self._started = False
        # online-tuner canary (pin_canary): rid of the replica serving the
        # CHALLENGER schedule, None = no A/B in progress
        self._canary = None
        self._canary_fp = None
        self._canary_t0 = 0.0
        self._canary_overrides = False
        self._registry = registry
        self.registry_report = None  # latest fleet-wide HydrationReport

        # everything _make_server needs to (re)build one replica server —
        # the restart path constructs from the same recipe as first start
        self._entry_factory = entry_factory
        self._server_kw = dict(
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            coalesce_ms=coalesce_ms,
            queue_depth=queue_depth,
            deadline_ms=0.0,  # the fleet applies its default at admission
            labeled=labeled,
            warmup=warmup,
            compilation_cache=compilation_cache,
            metrics_path=None,  # the fleet emits one merged ledger
            dtype=dtype,
            pipelined=pipelined,
            auto_start=False,
            health=health,
            slo=slo,
            memory=memory_budget,
            # replicas carry NO result cache: the fleet keeps ONE shared
            # cache at its admission tier (consulted in submit, populated
            # in _harvest), so a hit never costs a routing decision and
            # N replicas never hold N copies of the same hot row
            result_cache=None,
            tenant_quota=tenant_quota,
        )
        self.tenant_quota = float(tenant_quota)

        # paged model families (serve.models): normalized to a spec map;
        # factories stay fleet-level 2-arg here, wrapped per replica in
        # _server_models so each replica owns its entries
        specs = []
        if models:
            for spec in (models.values() if isinstance(models, dict)
                         else models):
                if isinstance(spec, dict):
                    spec = ModelSpec(**spec)
                specs.append(spec)
        self._models = {s.model_id: s for s in specs}

        # fleet-tier content-addressed result cache (serve.result_cache):
        # an int byte budget builds one; an instance is shared as-is
        if isinstance(result_cache, ResultCache):
            self._cache = result_cache
        elif result_cache:
            self._cache = ResultCache(
                int(result_cache),
                cache_id=cache_id if cache_id is not None else getattr(
                    entry_factory, "__name__", type(entry_factory).__name__))
        else:
            self._cache = None
        if self._cache is not None:
            self.metrics.result_cache = self._cache

        self._replicas: list[_Replica] = []
        for rid, dev in enumerate(self.devices):
            m = self.metrics.replica(rid)
            self._replicas.append(_Replica(rid, dev, self._make_server(rid, m), m))

        # replica supervision (serve.supervisor): None/False = historical
        # permanent-on-first-death; True or a SupervisorConfig opts in
        self._supervisor = None
        if supervise:
            from wam_tpu.serve.supervisor import ReplicaSupervisor, SupervisorConfig

            cfg = supervise if isinstance(supervise, SupervisorConfig) else None
            self._supervisor = ReplicaSupervisor(self, cfg)

        self._os_entry = None
        self._mesh = None
        self._os_lock = threading.Lock()
        self._seq_factory = seq_factory
        self._seq_entry = None  # built lazily on first oversize-item batch
        if oversize == "pjit" and n > 1:
            from wam_tpu.parallel.mesh import replica_mesh

            self._mesh = replica_mesh(n, self.devices)
            self._os_entry = entry_factory(OVERSIZE_ENTRY_ID, self.metrics.oversize)
        self.prom_server = None
        if prom_port is not None:
            from wam_tpu.obs import start_metrics_server

            self.prom_server = start_metrics_server(prom_port)
        if auto_start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def _server_models(self, rid, metrics):
        """Per-replica `ModelSpec` list: the fleet-level 2-arg factories
        (``factory(replica_id, metrics)`` — the ``entry_factory``
        convention) become this replica's zero-arg closures, so a paged
        model's compiles count into ITS replica's ledger."""
        if not self._models:
            return None
        return [
            ModelSpec(
                s.model_id,
                (lambda f=s.factory, r=rid, m=metrics: f(r, m)),
                registry=s.registry,
                buckets=s.buckets,
                est_bytes=s.est_bytes,
                cache_id=s.cache_id,
            )
            for s in self._models.values()
        ]

    def _make_server(self, rid, metrics) -> AttributionServer:
        """Build one replica's `AttributionServer` from the fleet recipe —
        first construction and supervisor restarts share this, so a
        restarted replica is configured identically (same entry factory,
        same accumulating `ServeMetrics`, same device pin)."""
        return AttributionServer(
            self._entry_factory(rid, metrics),
            self.table,
            metrics=metrics,
            device=self.devices[rid],
            replica_id=rid,
            models=self._server_models(rid, metrics),
            **self._server_kw,
        )

    def _hydrate(self):
        """Hydrate the configured registry bundle into the process-local
        caches (no-op without one). Idempotent — already-present artifacts
        are skipped — so the supervisor calls it before every rebuild:
        normally free, but a cache wiped under a running fleet re-seeds
        from the bundle instead of recompiling."""
        if self._registry is None or self._registry == "":
            return None
        from wam_tpu.registry.client import resolve_client

        client = resolve_client(self._registry)
        if client is None:
            return None
        self.registry_report = client.hydrate()
        return self.registry_report

    def _rebuild_replica(self, rid) -> None:
        """Supervisor restart procedure: close the dead server (drains any
        request that raced in — each fails with `ServerClosedError` and
        re-routes), re-hydrate the registry bundle (when configured),
        rebuild + warm a fresh one (`start()` re-runs the parallel bucket
        warmup; the registry-seeded / process-level jit+AOT caches make it
        a rehydration, not a recompile), then swap it live under the fleet
        lock."""
        replica = self._replicas[rid]
        try:
            replica.server.close(emit_metrics=False)
        except Exception:
            pass  # the old server may be arbitrarily broken; the fresh
            # one replaces it regardless
        self._hydrate()
        server = self._make_server(rid, replica.metrics)
        server.start()
        with self._lock:
            if self._closed:
                closing = True
            else:
                closing = False
                replica.server = server
                replica.alive = True
        if closing:
            server.close(emit_metrics=False)
            raise ServerClosedError("fleet closed during replica rebuild")

    def start(self, registry=None) -> "FleetServer":
        """Start (and warm) every replica concurrently. Idempotent.
        ``registry`` overrides the constructor's bundle for this start —
        hydration runs ONCE here, before any replica's warmup compiles."""
        if self._started:
            return self
        if registry is not None:
            self._registry = registry
        self._hydrate()
        live = [r for r in self._replicas if r.alive]
        if len(live) == 1:
            live[0].server.start()
        else:
            with ThreadPoolExecutor(
                max_workers=len(live), thread_name_prefix="wam-fleet-start"
            ) as pool:
                list(pool.map(lambda r: r.server.start(), live))
        with self._lock:
            self._started = True
        return self

    def close(self, emit_metrics: bool = True) -> None:
        """Stop intake, drain every replica, and (when ``metrics_path`` is
        set) flush the merged fleet ledger."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._supervisor is not None:
            self._supervisor.close()
        for r in self._replicas:
            r.server.close(emit_metrics=False)
        if emit_metrics and self.metrics_path:
            from wam_tpu.results import JsonlWriter

            writer = JsonlWriter(self.metrics_path)
            if self.registry_report is not None:
                writer.write(self.registry_report.row())
            self.metrics.emit(
                writer,
                config=self.describe(),
                replica_configs={r.rid: r.server.describe() for r in self._replicas},
            )
        if self.prom_server is not None:
            from wam_tpu.obs import stop_metrics_server

            stop_metrics_server(self.prom_server)
            self.prom_server = None
        with self._lock:
            self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def describe(self) -> dict:
        return {
            "replicas": self.n_replicas,
            "coalesce_ms": self._server_kw["coalesce_ms"],
            "result_cache": (self._cache.stats()
                             if self._cache is not None else None),
            "devices": [str(d) for d in self.devices],
            "dead": [r.rid for r in self._replicas if not r.alive],
            "quarantined": [
                r.rid for r in self._replicas if r.alive and not r.server.health_ok()
            ],
            "buckets": [list(b.shape) for b in self.table],
            "max_batch": self.max_batch,
            "labeled": self.labeled,
            "oversize": self.oversize,
            "seq_route": self._seq_factory is not None,
            "canary": self._canary,
            "supervised": self._supervisor is not None,
            "supervision": (
                self._supervisor.describe() if self._supervisor is not None
                else None
            ),
            "registry": (getattr(self._registry, "bundle", None)
                         or (str(self._registry) if self._registry else None)),
            "models": sorted(self._models) if self._models else None,
            "tenant_quota": self.tenant_quota,
        }

    def _restart_hint_s(self) -> float | None:
        """How long a client should wait for a supervised restart to put a
        replica back: the supervisor's worst-case backoff (every dead
        replica restarts within it). None when nobody is coming back —
        unsupervised fleet, or every dead replica escalated permanent."""
        if self._supervisor is None:
            return None
        with self._lock:
            dead = [r.rid for r in self._replicas if not r.alive]
        if dead and all(self._supervisor.permanently_dead(rid) for rid in dead):
            return None
        cfg = self._supervisor.config
        return cfg.backoff_cap_s * (1.0 + cfg.jitter_frac)

    def pod_signals(self) -> dict:
        """The health-plane aggregate a pod worker ships in its heartbeat
        `WorkerSnapshot` — the same quantities `_score` routes on, rolled
        up to whole-fleet granularity for the tier above (the pod router
        scores worker PROCESSES the way this fleet scores replicas).
        Drain is the best live replica's (the fleet itself routes new work
        there); EMAs are per-bucket means over live replicas; the SLO
        penalty is the worst bucket's mean; ``quarantined`` only when
        EVERY live replica is (a partially-quarantined fleet still takes
        front-door traffic)."""
        with self._lock:
            replicas = list(self._replicas)
        live = [r for r in replicas if r.alive]
        ema: dict[str, float] = {}
        penalties: list[float] = []
        for b in self.table:
            vals = [r.metrics.ema_service_s(b.shape) for r in live]
            ema[b.key] = sum(vals) / len(vals) if vals else EMA_SEED_S
            pen = [r.server.slo_penalty_s(b.shape) for r in live]
            if pen:
                penalties.append(sum(pen) / len(pen))
        # paged-model lanes ride along under their model|bucket keys, so
        # the pod router's heartbeat sees per-model service costs too
        model_ema: dict[str, list[float]] = {}
        for r in live:
            for k, v in r.metrics.ema_service_s().items():
                if "|" in k:
                    model_ema.setdefault(k, []).append(v)
        for k, vals in model_ema.items():
            ema[k] = sum(vals) / len(vals)
        models_resident: dict[str, int] = {}
        for r in live:
            for mid, nbytes in r.server.models_resident().items():
                models_resident[mid] = max(
                    models_resident.get(mid, 0), int(nbytes))
        snaps = [r.metrics.snapshot() for r in replicas]
        os_snap = self.metrics.oversize.snapshot()
        qos_depth = dict.fromkeys(QOS_CLASSES, 0)
        for r in live:
            for cls, depth in r.server.qos_depths().items():
                qos_depth[cls] = qos_depth.get(cls, 0) + depth
        submitted = sum(s["submitted"] for s in snaps) + os_snap["submitted"]
        # fleet-tier cache hits resolve BEFORE routing, so they never enter
        # ``submitted`` — the hit rate the autoscaler discounts drain by is
        # hits / total front-door traffic (hits + routed submits)
        cache_hits = self.metrics.cache_hits
        return {
            "projected_drain_s": min(
                (r.server.projected_drain_s() for r in live), default=0.0),
            "qos_depth": qos_depth,
            "queue_free": sum(r.server.admission_free() for r in live),
            "ema_service_s": ema,
            "slo_penalty_s": max(penalties, default=0.0),
            "quarantined": bool(live)
            and not any(r.server.health_ok() for r in live),
            "live_replicas": len(live),
            "dead_replicas": len(replicas) - len(live),
            "submitted": submitted,
            "completed": sum(s["completed"] for s in snaps)
            + os_snap["completed"],
            "compile_count": sum(s["compile_count"] for s in snaps)
            + os_snap["compile_count"],
            "cache_hits": cache_hits,
            "cache_hit_rate": cache_hits / max(1, cache_hits + submitted),
            "models_resident": models_resident,
        }

    # -- online-tuner canary (wam_tpu.tune.online) ---------------------------

    def pin_canary(self, fingerprint: str, *, replica_id: int | None = None,
                   overrides: dict | None = None) -> int:
        """Pin one replica as the CHALLENGER arm of a schedule A/B: its
        ``serve_batch`` rows are stamped with ``fingerprint`` (instead of
        the process-global champion fingerprint) and the batch-QoS lane
        prefers it at routing time, so the canary slice is the throughput
        lane — interactive traffic only lands there as a last resort.

        ``overrides`` merges challenger serving knobs into the replica
        recipe (e.g. ``{"max_batch": 16}`` from a retuned ``bucket_cap``)
        and rebuilds the replica with them — in-flight work re-routes to
        the champions exactly like a supervisor restart. Defaults to the
        highest live rid (the replica the stable-tie router loads LAST).
        Returns the pinned rid."""
        with self._lock:
            if self._canary is not None:
                raise ValueError(
                    f"replica {self._canary} is already the canary; "
                    "clear_canary() first")
            live = [r for r in self._replicas if r.alive]
            if len(live) < 2:
                raise ValueError(
                    "canary A/B needs >= 2 live replicas (one per arm), "
                    f"have {len(live)}")
            if replica_id is None:
                replica_id = max(r.rid for r in live)
            replica = self._replicas[replica_id]
            if not replica.alive:
                raise ValueError(f"replica {replica_id} is dead")
        if overrides:
            replica.server.close(emit_metrics=False)
            kw = dict(self._server_kw)
            kw.update(overrides)
            server = AttributionServer(
                self._entry_factory(replica_id, replica.metrics),
                self.table, metrics=replica.metrics,
                device=self.devices[replica_id], replica_id=replica_id,
                models=self._server_models(replica_id, replica.metrics),
                **kw)
            server.start()
            with self._lock:
                replica.server = server
        replica.metrics.schedule_fingerprint = fingerprint
        with self._lock:
            self._canary = replica_id
            self._canary_fp = fingerprint
            self._canary_t0 = time.time()
            self._canary_overrides = bool(overrides)
        return replica_id

    def clear_canary(self) -> None:
        """End the A/B: the replica's rows stamp the champion fingerprint
        again, and a replica rebuilt with challenger overrides goes back to
        the fleet recipe (same path as a supervisor restart)."""
        with self._lock:
            rid = self._canary
            had_overrides = self._canary_overrides
            self._canary = None
            self._canary_fp = None
            self._canary_t0 = 0.0
            self._canary_overrides = False
        if rid is None:
            return
        self._replicas[rid].metrics.schedule_fingerprint = None
        if had_overrides:
            self._rebuild_replica(rid)

    def canary_report(self, *, min_batches: int = 8,
                      margin: float = 0.05) -> dict:
        """Champion-vs-challenger comparison from the replicas' OWN batch
        ledgers (`ServeMetrics.batch_sample`) — self-contained, no tuner
        import, same verdict rule as `tune.online.canary_verdict`: the
        challenger wins when both arms hold ≥ ``min_batches`` batches and
        its mean per-item service beats the champion mean by ≥ ``margin``.
        SLO burn is compared alongside (a faster canary that is burning an
        objective is NOT a win). Only rows from the OPEN canary window
        count: the challenger arm is filtered to rows stamped with the
        challenger fingerprint, the champion arm to rows dispatched after
        the pin — neither arm coasts on its pre-A/B history."""
        with self._lock:
            rid = self._canary
            fp = self._canary_fp
            t0 = self._canary_t0
            replicas = list(self._replicas)
        if rid is None:
            return {"canary": None, "verdict": "none", "win": False}

        def _per_item(rows, want_fp=None):
            return [float(r.get("service_s", 0.0)) / max(1, int(r["n_real"]))
                    for r in rows
                    if r.get("n_real")
                    and float(r.get("timestamp", 0.0)) >= t0
                    and (want_fp is None
                         or r.get("schedule_fingerprint") == want_fp)]

        def _penalty(r):
            return max((r.server.slo_penalty_s(b.shape) for b in self.table),
                       default=0.0)

        chall = _per_item(replicas[rid].metrics.batch_sample(), want_fp=fp)
        champ: list[float] = []
        champ_pen: list[float] = []
        for r in replicas:
            if r.rid != rid and r.alive:
                champ.extend(_per_item(r.metrics.batch_sample()))
                champ_pen.append(_penalty(r))
        out = {
            "canary": rid,
            "challenger_batches": len(chall),
            "champion_batches": len(champ),
            "margin": margin,
            "challenger_slo_penalty_s": _penalty(replicas[rid]),
            "champion_slo_penalty_s": max(champ_pen, default=0.0),
        }
        if len(chall) < min_batches or len(champ) < min_batches:
            out.update(verdict="insufficient", win=False)
            return out
        champ_s = sum(champ) / len(champ)
        chall_s = sum(chall) / len(chall)
        win = (chall_s <= champ_s * (1.0 - margin)
               and out["challenger_slo_penalty_s"]
               <= out["champion_slo_penalty_s"])
        out.update(
            champion_per_item_s=champ_s,
            challenger_per_item_s=chall_s,
            improvement=(champ_s - chall_s) / champ_s if champ_s > 0 else 0.0,
            verdict="challenger" if win else "champion",
            win=win,
        )
        return out

    # -- client side --------------------------------------------------------

    def submit(self, x, y=None, deadline_ms: float | None = None,
               qos: str = "interactive",
               min_confidence: float = 0.0,
               model: str | None = None,
               tenant: str | None = None) -> Future:
        """Admit one item and route it to the least-loaded live replica.
        Returns a fleet-level future — it survives a replica death by
        re-routing to survivors. ``qos`` is the request's admission class
        (threaded to the replica's lanes and into routing via the
        interactive-depth weight). ``min_confidence`` is the anytime
        convergence floor, threaded to the winning replica (only
        meaningful for fleets over anytime entries —
        `wam_tpu.anytime`). ``model`` routes to a configured paged model
        family (None = the default entry) — the router prefers replicas
        where it is already resident. ``tenant`` is the request's
        fair-share identity (`AttributionServer.submit`). Raises
        `QueueFullError` only when every live replica rejected; a
        zero/negative ``deadline_ms`` fails at admission with
        `InvalidDeadlineError` before any routing."""
        if self.labeled and y is None:
            raise ValueError("labeled fleet: submit(x, y) needs a class label")
        if not self.labeled and y is not None:
            raise ValueError("unlabeled fleet: submit() must not carry a label")
        if qos not in QOS_CLASSES:
            raise ValueError(f"qos must be one of {QOS_CLASSES}, got {qos!r}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise InvalidDeadlineError(deadline_ms)
        if model is not None and model not in self._models:
            raise ValueError(
                f"unknown model {model!r}; configured fleet models: "
                f"{sorted(self._models)}")
        x = np.asarray(x, self.dtype)
        bucket = self.table.select(x.shape)  # NoBucketError before any queueing
        ckey = None
        if self._cache is not None:
            # fleet-tier consult BEFORE routing: a hit never costs a
            # replica queue slot or a scoring pass
            ckey = self._cache.key(x, y, model=model)
            hit = self._cache.get(ckey, tenant=tenant)
            if hit is not None:
                self.metrics.note_cache_hit()
                fut: Future = Future()
                fut.set_result(hit)
                return fut
        now = time.perf_counter()
        if deadline_ms is None:
            deadline_at = (now + self.default_deadline_s) if self.default_deadline_s else None
        else:
            deadline_at = now + deadline_ms / 1e3
        req = _FleetRequest(x, y, bucket, deadline_at, Future(),
                            qos=qos, min_confidence=float(min_confidence),
                            model=model, tenant=tenant,
                            ckey=ckey)
        if obs_tracing._STATE.enabled:
            # detached per-request root: ends on whichever thread resolves
            # the fleet future (worker callback), closing the trace
            root = obs_tracing.start_span(
                "request", cat="fleet", bucket=bucket_key(bucket.shape))
            req.ctx = root.context
            req.future.add_done_callback(
                lambda f: root.end(
                    error=type(f.exception()).__name__ if f.exception() else None))
            try:
                self._route(req, raise_errors=True)
            except Exception as e:
                root.end(error=type(e).__name__)  # rejected before queueing
                raise
        else:
            self._route(req, raise_errors=True)
        return req.future

    def attribute(self, x, y=None, deadline_ms: float | None = None,
                  qos: str = "interactive", min_confidence: float = 0.0,
                  model: str | None = None, tenant: str | None = None):
        """Blocking convenience wrapper: submit + wait."""
        return self.submit(x, y, deadline_ms=deadline_ms, qos=qos,
                           min_confidence=min_confidence,
                           model=model, tenant=tenant).result()

    def submit_with_retry(self, x, y=None, *, policy=None, stats=None,
                          rng=None, deadline_ms: float | None = None) -> Future:
        """`submit` driven by a `serve.retry.RetryPolicy`: backpressure
        rejections back off (honoring ``retry_after_s``, capped + jittered)
        and resubmit within the policy's attempt/budget limits; optional
        hedging races a second submit against a slow first one. Returns a
        future resolving to the result or a typed `ServeError`
        (`RetryBudgetExceededError` once the policy gives up) — one daemon
        driver thread per call, sized for closed-loop client counts."""
        from wam_tpu.serve.retry import RetryPolicy

        policy = policy if policy is not None else RetryPolicy()
        outer: Future = Future()

        def _submit(remaining_s):
            per_attempt = deadline_ms
            if remaining_s is not None:
                rem_ms = remaining_s * 1e3
                per_attempt = (rem_ms if per_attempt is None
                               else min(per_attempt, rem_ms))
            return self.submit(x, y, deadline_ms=per_attempt)

        def _drive():
            try:
                outer.set_result(policy.run(_submit, rng=rng, stats=stats))
            except BaseException as e:  # noqa: BLE001 - future carries it
                outer.set_exception(e)

        threading.Thread(target=_drive, daemon=True,
                         name="wam-retry-driver").start()
        return outer

    def attribute_batch(self, xs, ys=None, deadline_ms: float | None = None,
                        qos: str = "batch"):
        """Attribute a whole batch. ``len(xs) <= max_batch`` fans out as
        routed per-item submits (the workers coalesce them back into full
        device batches); anything larger takes the oversize data-parallel
        path over the fleet mesh (module docstring) instead of being the
        caller's chunking problem. Blocking; returns the stacked result.
        Fanned-out items default to the ``batch`` QoS lane — whole-batch
        callers are throughput work that must not displace interactive
        single-item submits (override with ``qos="interactive"``)."""
        xs = np.asarray(xs, self.dtype)
        if xs.ndim < 2:
            raise ValueError("attribute_batch needs a leading batch axis")
        if self.labeled:
            ys = np.asarray(ys, np.int32).reshape(-1)
            if len(ys) != len(xs):
                raise ValueError(f"{len(xs)} items but {len(ys)} labels")
        elif ys is not None:
            raise ValueError("unlabeled fleet: attribute_batch() must not carry labels")
        try:
            bucket = self.table.select(xs.shape[1:])
        except NoBucketError:
            # item shape exceeds every bucket: sequence-sharded route when
            # configured (module docstring), the historical rejection if not
            if self._seq_factory is None:
                raise
            return self._dispatch_seq_sharded(xs, ys)
        with self._lock:
            fleet_whole = self._os_entry is not None and all(
                r.alive for r in self._replicas
            )
        if len(xs) <= self.max_batch or not fleet_whole:
            futs = [
                self.submit(x, int(ys[i]) if self.labeled else None,
                            deadline_ms, qos=qos)
                for i, x in enumerate(xs)
            ]
            rows = [f.result() for f in futs]
            return jax.tree_util.tree_map(lambda *r: np.stack(r), *rows)
        return self._dispatch_oversize(xs, ys, bucket)

    # -- routing ------------------------------------------------------------

    def _score(self, replica: _Replica, bucket: Bucket,
               model: str | None = None) -> float:
        """Projected completion estimate for a new item on this replica:
        its whole-queue drain plus one batch of the item's own bucket at
        the replica's OWN per-bucket EMA (an idle-but-slow replica loses
        to an idle-and-fast one), plus the replica's SLO burn-rate penalty
        (`AttributionServer.slo_penalty_s` — an objective-violating
        replica sheds load proportionally to how hard it is burning),
        plus the interactive-depth weight: queued interactive work counts
        EXTRA beyond its share of raw drain (`INTERACTIVE_DEPTH_WEIGHT`),
        so interactive-loaded replicas shed new work to keep the
        latency-sensitive lane short. A paged-model request reads the
        model's own lane EMA and pays `MODEL_PAGEIN_PENALTY_S` on
        replicas where the model is not resident, concentrating each
        model's traffic instead of thrashing page-ins across the fleet."""
        ema = replica.metrics.ema_service_s(bucket.shape, model=model)
        interactive_depth = replica.server.qos_depths()["interactive"]
        score = (
            replica.server.projected_drain_s()
            + ema
            + replica.server.slo_penalty_s(bucket.shape)
            + INTERACTIVE_DEPTH_WEIGHT
            * (interactive_depth / replica.server.max_batch)
            * ema
        )
        if model is not None and model not in replica.server.models_resident():
            score += MODEL_PAGEIN_PENALTY_S
        return score

    def _route(self, req: _FleetRequest, raise_errors: bool) -> None:
        """Submit ``req`` to the best untried live replica; on total
        rejection raise/fail with the backpressure (or liveness) error.
        ``raise_errors`` distinguishes the synchronous admission path
        (client expects `QueueFullError` from `submit`) from async
        re-dispatch inside a future callback (errors land on the fleet
        future)."""

        def _fail(exc: Exception) -> None:
            if raise_errors:
                raise exc
            req.future.set_exception(exc)

        # admission span under the request's trace: scoring + the routed
        # submit happen inside, so re-routes after a death show up as a
        # second admission span on the same trace id
        with obs_tracing.use_context(req.ctx), obs_tracing.span(
            "admission", cat="fleet", rerouted=bool(req.tried)
        ):
            return self._route_inner(req, _fail)

    def _route_inner(self, req: _FleetRequest, _fail) -> None:
        with self._lock:
            if self._closed or not self._started:
                return _fail(ServerClosedError("fleet is not accepting requests"))
            cands = [r for r in self._replicas if r.alive and r.rid not in req.tried]
        if not cands:
            return _fail(NoLiveReplicaError(
                "no live replica left for this request",
                retry_after_s=self._restart_hint_s()))
        if req.deadline_at is not None:
            remaining_ms = (req.deadline_at - time.perf_counter()) * 1e3
            if remaining_ms <= 0.0:
                return _fail(DeadlineExceededError("deadline lapsed during re-route"))
        else:
            remaining_ms = None
        cands.sort(key=lambda r: self._score(r, req.bucket, req.model))  # stable: rid ties
        with self._lock:
            canary = self._canary
        if canary is not None:
            # schedule-A/B traffic split (pin_canary): the batch lane IS
            # the canary slice — it prefers the challenger replica; the
            # interactive lane avoids it except as a last resort. Stable
            # sorts preserve the score order within each arm.
            cands.sort(key=lambda r: (r.rid != canary) if req.qos == "batch"
                       else (r.rid == canary))
        ok = {r.rid: r.server.health_ok() for r in cands}
        if not all(ok.values()):
            # numeric-health partition: quarantined replicas are routed
            # around like deaths but stay LAST-RESORT candidates, so a
            # fully-quarantined fleet still serves rather than failing
            cands = [r for r in cands if ok[r.rid]] + [r for r in cands if not ok[r.rid]]
        retry_after = None
        for r in cands:
            try:
                inner = r.server.submit(req.x, req.y, deadline_ms=remaining_ms,
                                        qos=req.qos,
                                        min_confidence=req.min_confidence,
                                        model=req.model, tenant=req.tenant)
            except QueueFullError as e:
                retry_after = (
                    e.retry_after_s
                    if retry_after is None
                    else min(retry_after, e.retry_after_s)
                )
                continue
            except ServerClosedError:
                continue
            inner.add_done_callback(lambda f, r=r: self._harvest(f, r, req))
            return
        if retry_after is not None:
            return _fail(QueueFullError(retry_after))
        return _fail(NoLiveReplicaError(
            "every live replica refused this request",
            retry_after_s=self._restart_hint_s()))

    def _harvest(self, inner: Future, replica: _Replica, req: _FleetRequest) -> None:
        """Future callback (runs on the replica's worker thread): forward
        success and per-request errors; treat anything else as a chip loss
        — mark the replica dead, notify the supervisor (when supervised),
        and re-route to survivors."""
        exc = inner.exception()
        if exc is None:
            result = inner.result()
            if (self._cache is not None and req.ckey is not None
                    and not replica.server.degraded
                    and not getattr(replica.server, "_anytime", False)):
                # anytime replicas excluded: their results depend on the
                # batch's deadline/convergence trajectory, which would
                # break the cache's bit-identical-hit contract
                # populate at the fleet tier (replicas carry no cache);
                # degraded CPU-rebuilt entries are skipped — their rounding
                # differs from the accelerator rows the cache promises
                self._cache.put(req.ckey, result, tenant=req.tenant)
            req.future.set_result(result)
            return
        if isinstance(exc, ServerClosedError):
            # the REPLICA closed under this request (supervisor restart in
            # progress, or its worker crashed mid-queue): a liveness event,
            # not a client semantic — re-route instead of forwarding
            with self._lock:
                fleet_closed = self._closed
            if not fleet_closed:
                req.tried.add(replica.rid)
                try:
                    self._route(req, raise_errors=False)
                except Exception as e:  # defensive: a callback must never raise
                    req.future.set_exception(e)
                return
            req.future.set_exception(exc)
            return
        if isinstance(exc, ServeError):
            # deadline / backpressure: per-request semantics, not a device
            # loss — the client decides what to do
            req.future.set_exception(exc)
            return
        with self._lock:
            was_alive = replica.alive
            replica.alive = False
        if was_alive:
            self.metrics.note_replica_death(replica.rid, repr(exc))
            if self._supervisor is not None:
                self._supervisor.notify_death(replica.rid, repr(exc))
        req.tried.add(replica.rid)
        try:
            self._route(req, raise_errors=False)
        except Exception as e:  # defensive: a callback must never raise
            req.future.set_exception(e)

    # -- oversize-item sequence-sharded path --------------------------------

    def _dispatch_seq_sharded(self, xs: np.ndarray, ys):
        """Run a batch whose ITEM shape no bucket admits through the
        sequence-sharded entry over the fleet mesh. Serialized on
        ``_os_lock`` for the same reason as `_dispatch_oversize` (the
        dispatch owns every chip); the entry is built lazily from
        ``seq_factory`` on first use, so the seq graph only ever compiles
        in fleets that see long-context traffic. Deadlines do not preempt
        the dispatch — the route is synchronous and whole-batch. Ledger
        rows land on the shared oversize `ServeMetrics` with the item
        shape as the bucket key (no configured bucket names this shape)."""
        metrics = self.metrics.oversize
        metrics.note_submit(len(xs))
        item_shape = tuple(xs.shape[1:])
        skey = bucket_key(item_shape)
        with self._os_lock:
            entry = self._seq_entry
            if entry is None:
                mesh = self._mesh
                if mesh is None:
                    # oversize="fanout" / single-replica fleets build no
                    # pjit mesh up front; the seq route needs one either way
                    from wam_tpu.parallel.mesh import replica_mesh

                    mesh = replica_mesh(self.n_replicas, self.devices)
                entry = self._seq_entry = self._seq_factory(mesh)
            t0 = time.perf_counter()
            # sentinel labels so the seq graph's (expected) first traces
            # self-identify; the estimator's jits report under kind "seq"
            with obs_tracing.span(
                "seq_sharded_batch", cat="fleet", bucket=skey, n_real=len(xs)
            ), obs_sentinel.label(
                replica=OVERSIZE_ENTRY_ID, bucket=skey, phase="seq_sharded"
            ):
                with metrics.stages.stage("dispatch"):
                    out = entry(xs, ys if self.labeled else None)
                with metrics.stages.stage("harvest"):
                    out = jax.device_get(out)
            service_s = time.perf_counter() - t0
            metrics.note_batch(
                bucket_shape=item_shape,
                n_real=len(xs),
                max_batch=len(xs),  # whole batch in one dispatch: fill 1.0
                pad_waste=0.0,  # no bucket pad — the entry takes exact shapes
                queue_depth=0,
                service_s=service_s,
                queue_waits_s=[0.0] * len(xs),
                latencies_s=[service_s] * len(xs),
            )
        return jax.tree_util.tree_map(np.asarray, out)

    # -- oversize data-parallel path ----------------------------------------

    def _dispatch_oversize(self, xs: np.ndarray, ys, bucket: Bucket):
        """Data-parallel dispatch over the fleet mesh: chunk to the fleet-
        wide batch shape (``n_replicas × max_batch`` rows — ONE compiled
        oversize graph per bucket), shard rows over the ``'data'`` axis,
        and run the pjit'd oversize entry. Serialized (`_os_lock`): each
        dispatch owns every chip, so overlapping two would just interleave
        on the same hardware."""
        from jax.sharding import NamedSharding, PartitionSpec

        rows_per = self.n_replicas * self.max_batch
        xspec = NamedSharding(self._mesh, PartitionSpec("data", *([None] * len(bucket.shape))))
        yspec = NamedSharding(self._mesh, PartitionSpec("data"))
        metrics = self.metrics.oversize
        metrics.note_submit(len(xs))
        outs = []
        with self._os_lock:
            bkey = bucket_key(bucket.shape)
            for lo in range(0, len(xs), rows_per):
                chunk = xs[lo : lo + rows_per]
                k = len(chunk)
                t0 = time.perf_counter()
                # one span per fleet-wide chunk; compile-sentinel labels so
                # the oversize graph's (expected) first trace self-identifies
                with obs_tracing.span(
                    "oversize_chunk", cat="fleet", bucket=bkey, n_real=k
                ), obs_sentinel.label(
                    replica=OVERSIZE_ENTRY_ID, bucket=bkey, phase="oversize"
                ):
                    with metrics.stages.stage("assemble"):
                        padded = np.stack([pad_item(r, bucket) for r in chunk])
                        if k < rows_per:
                            # replicate-pad rows, same exactness argument as
                            # the single-chip batch pad (serve.buckets)
                            reps = np.repeat(padded[:1], rows_per - k, axis=0)
                            padded = np.concatenate([padded, reps])
                        if self.labeled:
                            yc = ys[lo : lo + rows_per]
                            if k < rows_per:
                                yc = np.concatenate(
                                    [yc, np.repeat(yc[:1], rows_per - k)]
                                )
                            sx, sy = put_committed((padded, yc), (xspec, yspec))
                        else:
                            sx, sy = put_committed(padded, xspec), None
                    with metrics.stages.stage("dispatch"):
                        out = self._os_entry(sx, sy)
                    with metrics.stages.stage("harvest"):
                        out = jax.device_get(out)
                service_s = time.perf_counter() - t0
                metrics.note_batch(
                    bucket_shape=bucket.shape,
                    n_real=k,
                    max_batch=rows_per,
                    pad_waste=float(np.mean([bucket.pad_waste(r.shape) for r in chunk])),
                    queue_depth=0,
                    service_s=service_s,
                    queue_waits_s=[0.0] * k,
                    latencies_s=[service_s] * k,
                )
                outs.append(
                    jax.tree_util.tree_map(lambda a: np.asarray(a)[:k], out)
                )
        return jax.tree_util.tree_map(lambda *p: np.concatenate(p), *outs)
