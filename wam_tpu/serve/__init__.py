"""`wam_tpu.serve` — batched attribution serving runtime.

The production layer the ROADMAP north star asks for: a stream of
independent attribution requests (mixed shapes, mixed arrival times) in, a
small fixed set of warm compiled graphs and a single device-owning worker
loop out. See `serve.runtime` for the operational semantics, `serve.buckets`
for the shape-admission policy, `serve.metrics` for the ledger schema, and
`scripts/bench_serve.py` for the closed-loop load generator.

Engines plug in via their ``serve_entry()`` methods (wam1d/wam2d/wam3d) —
thread-safe batched callables jitted with donated input buffers on TPU
(`serve.entry.jit_entry`).
"""

from wam_tpu.serve.buckets import Bucket, BucketTable, NoBucketError, pad_item
from wam_tpu.serve.entry import jit_entry
from wam_tpu.serve.metrics import ServeMetrics, percentile_ms
from wam_tpu.serve.runtime import (
    AttributionServer,
    DeadlineExceededError,
    QueueFullError,
    ServeError,
    ServerClosedError,
)

__all__ = [
    "AttributionServer",
    "Bucket",
    "BucketTable",
    "NoBucketError",
    "ServeError",
    "QueueFullError",
    "DeadlineExceededError",
    "ServerClosedError",
    "ServeMetrics",
    "percentile_ms",
    "jit_entry",
    "pad_item",
]
