"""`wam_tpu.serve` — batched attribution serving runtime.

The production layer the ROADMAP north star asks for: a stream of
independent attribution requests (mixed shapes, mixed arrival times) in, a
small fixed set of warm compiled graphs and a single device-owning worker
loop out. See `serve.runtime` for the operational semantics, `serve.buckets`
for the shape-admission policy, `serve.metrics` for the ledger schema
(v2: per-bucket EMA service time, replica identity, fleet summaries), and
`scripts/bench_serve.py` for the closed-loop load generator.

Multi-chip: `serve.fleet.FleetServer` runs one `AttributionServer` replica
per chip behind shared admission + load-aware bucket routing, and
dispatches oversize batches data-parallel over the fleet mesh
(`parallel.replica_mesh`). `scripts/bench_serve.py --fleet N` drives it.

Engines plug in via their ``serve_entry()`` methods (wam1d/wam2d/wam3d) —
thread-safe batched callables jitted with donated input buffers on TPU
(`serve.entry.jit_entry`).
"""

from wam_tpu.serve.buckets import Bucket, BucketTable, NoBucketError, bucket_key, pad_item
from wam_tpu.serve.entry import fleet_aot_key, jit_entry
from wam_tpu.serve.fleet import OVERSIZE_ENTRY_ID, FleetServer, NoLiveReplicaError
from wam_tpu.serve.metrics import SCHEMA_VERSION, FleetMetrics, ServeMetrics, percentile_ms
from wam_tpu.serve.models import ModelPager, ModelSpec, model_paging_disabled
from wam_tpu.serve.result_cache import ResultCache, result_cache_key
from wam_tpu.serve.retry import RetryBudgetExceededError, RetryPolicy, RetryStats
from wam_tpu.serve.runtime import (
    QOS_CLASSES,
    AttributionServer,
    DeadlineExceededError,
    InvalidDeadlineError,
    MemoryAdmissionError,
    QueueFullError,
    ServeError,
    ServerClosedError,
    WorkerCrashedError,
)
from wam_tpu.serve.supervisor import ReplicaSupervisor, SupervisorConfig

__all__ = [
    "AttributionServer",
    "FleetServer",
    "Bucket",
    "BucketTable",
    "NoBucketError",
    "NoLiveReplicaError",
    "ServeError",
    "QueueFullError",
    "MemoryAdmissionError",
    "DeadlineExceededError",
    "InvalidDeadlineError",
    "ServerClosedError",
    "WorkerCrashedError",
    "RetryBudgetExceededError",
    "RetryPolicy",
    "RetryStats",
    "ReplicaSupervisor",
    "SupervisorConfig",
    "ServeMetrics",
    "FleetMetrics",
    "SCHEMA_VERSION",
    "OVERSIZE_ENTRY_ID",
    "percentile_ms",
    "ResultCache",
    "result_cache_key",
    "ModelSpec",
    "ModelPager",
    "model_paging_disabled",
    "QOS_CLASSES",
    "jit_entry",
    "fleet_aot_key",
    "bucket_key",
    "pad_item",
]
