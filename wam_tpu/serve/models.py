"""Multi-model residency: HBM-aware model paging for the serving plane.

One `AttributionServer` historically pinned exactly one entry/model; the
multi-model round (ROADMAP item 4) lets one server — and through it one
fleet — serve many model families concurrently by treating MODELS the way
the runtime already treats buckets: as pageable device residents under a
byte budget.

`ModelSpec` declares one servable model: an ``entry_factory`` building its
jitted serving entry, an optional compile-artifact ``registry`` bundle
(`wam_tpu.registry`) so page-in is a HYDRATION rather than a compile, the
bucket subset it serves, and a device-footprint estimate. `ModelPager`
owns the residency state machine:

- **Page-in** (`ensure`): the first `submit(model=...)` for a non-resident
  model pays the switch synchronously — registry hydration, entry build,
  and per-bucket warmup dispatches all run under the model's own build
  lock inside a ``model_switch`` obs span, so concurrent submits for the
  same cold model block on ONE build instead of racing N. With a warm
  bundle the warmup dispatches replay seeded AOT executables and the
  model serves its first request at ``compile_count == 0`` — the measured
  perf win (`bench_serve --multi-model` A/Bs switch-by-hydration against
  switch-by-compile).
- **Eviction**: under a byte budget (the server's `MemoryBudget`
  watermarks, `ServeConfig.hbm_budget_mb`) the pager evicts the
  least-valuable resident first — LRU weighted by the model's mean EMA
  service time: ``score = idle_s / max(ema_s, EMA_SEED_S)``, so an old
  AND cheap model pages out before a recently-hot or expensive one. A
  model with queued or in-flight work is NEVER evicted (``busy_fn`` —
  the server answers it under its own condition lock); when nothing
  evictable frees enough bytes the page-in is refused as ordinary
  memory backpressure (`MemoryAdmissionError`).
- **Kill switch**: ``WAM_TPU_NO_MODEL_PAGING=1`` disables the budget and
  the evictor (models still page in, nothing pages out, nothing is
  refused) — the bisection lever for "is the pager wrong" in production.

The default model (``model=None``) is pinned by the runtime and never
enters the pager. Instrumentation: ``wam_tpu_serve_model_pagein_total`` /
``_pagein_seconds`` / ``_pageout_total`` / ``_resident`` /
``_resident_bytes`` (declared in `obs/schema.py`).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from wam_tpu.obs.registry import registry as _obs_registry

__all__ = ["ModelSpec", "ModelPager", "model_paging_disabled"]

_c_pagein = _obs_registry.counter(
    "wam_tpu_serve_model_pagein_total",
    "model page-ins (hydration + build + warmup)",
    labels=("replica", "model"))
_h_pagein_s = _obs_registry.histogram(
    "wam_tpu_serve_model_pagein_seconds",
    "model switch latency: submit blocked on page-in",
    labels=("replica", "model"))
_c_pageout = _obs_registry.counter(
    "wam_tpu_serve_model_pageout_total",
    "model evictions under the HBM budget",
    labels=("replica", "model"))
_g_resident = _obs_registry.gauge(
    "wam_tpu_serve_model_resident",
    "resident paged models on this server", labels=("replica",))
_g_resident_bytes = _obs_registry.gauge(
    "wam_tpu_serve_model_resident_bytes",
    "summed device-footprint estimate of resident paged models",
    labels=("replica",))


def model_paging_disabled() -> bool:
    """``WAM_TPU_NO_MODEL_PAGING=1`` kill switch, read per call so flipping
    the env var takes effect without a restart (the serve kill-switch
    convention — `WAM_TPU_NO_RESULT_CACHE` et al.)."""
    return os.environ.get("WAM_TPU_NO_MODEL_PAGING", "") not in ("", "0")


@dataclass
class ModelSpec:
    """One servable model on a multiplexed server.

    ``factory`` is a ZERO-ARG callable building the model's serving entry
    (the fleet wraps its ``(replica_id, metrics)`` factories into closures
    per replica — `FleetServer`). ``registry`` is the model's
    compile-artifact bundle (path or `RegistryClient`) hydrated before the
    entry builds, so page-in warmups replay AOT executables instead of
    compiling. ``buckets`` restricts the model to a subset of the server's
    bucket shapes (None = every bucket). ``est_bytes`` overrides the
    shape-derived device-footprint estimate (0 = derive). ``cache_id``
    names the model in result-cache keys (defaults to ``model_id``)."""

    model_id: str
    factory: object
    registry: object = None
    buckets: object = None
    est_bytes: int = 0
    cache_id: str | None = None

    def __post_init__(self):
        if not self.model_id:
            raise ValueError("ModelSpec needs a non-empty model_id")
        if "|" in self.model_id or "@" in self.model_id:
            # '|' delimits model-prefixed EMA/watermark keys, '@' the SLO
            # ladder segments — a model id containing either would alias
            raise ValueError(
                f"model_id must not contain '|' or '@': {self.model_id!r}")
        if not callable(self.factory):
            raise TypeError("ModelSpec.factory must be a zero-arg callable")


@dataclass
class _Resident:
    spec: ModelSpec
    entry: object
    nbytes: int
    paged_in_at: float
    last_used: float = field(default=0.0)
    pagein_s: float = 0.0


class ModelPager:
    """Residency state machine for one server's paged models (module
    docstring). Thread-safe: a meta lock guards the resident map, one
    build lock per model serializes its page-in.

    ``budget_bytes`` bounds the summed footprint estimates of resident
    paged models (None = unbounded). ``ema_fn(model_id) -> float`` returns
    the model's mean EMA batch service time (the eviction weight);
    ``busy_fn(model_id) -> bool`` answers whether the model has queued or
    in-flight work (evictions of busy models are refused)."""

    def __init__(self, specs, *, budget_bytes=None, replica_id=None,
                 ema_fn=None, busy_fn=None, retry_after_s: float = 1.0):
        if isinstance(specs, dict):
            specs = list(specs.values())
        self.specs: dict[str, ModelSpec] = {}
        for spec in specs or []:
            if not isinstance(spec, ModelSpec):
                spec = ModelSpec(**spec)
            if spec.model_id in self.specs:
                raise ValueError(f"duplicate model_id {spec.model_id!r}")
            self.specs[spec.model_id] = spec
        self.budget_bytes = int(budget_bytes) if budget_bytes else None
        self.replica_id = replica_id
        self._rl = "-" if replica_id is None else str(replica_id)
        self._ema_fn = ema_fn or (lambda mid: 0.0)
        self._busy_fn = busy_fn or (lambda mid: False)
        self.retry_after_s = retry_after_s
        self._meta = threading.Lock()
        self._resident: dict[str, _Resident] = {}
        self._locks: dict[str, threading.Lock] = {
            mid: threading.Lock() for mid in self.specs}
        self.pageins = 0
        self.pageouts = 0

    # -- queries ------------------------------------------------------------

    def is_resident(self, model_id: str) -> bool:
        with self._meta:
            return model_id in self._resident

    def resident(self) -> dict[str, int]:
        """``{model_id: footprint_bytes}`` of resident paged models — the
        fleet heartbeat's ``models_resident`` signal and the routing
        affinity the pod router scores on."""
        with self._meta:
            return {mid: r.nbytes for mid, r in self._resident.items()}

    def resident_bytes(self) -> int:
        with self._meta:
            return sum(r.nbytes for r in self._resident.values())

    def entry(self, model_id: str):
        """The resident entry, touching its LRU clock. KeyError when the
        model is not resident (callers `ensure` first)."""
        with self._meta:
            r = self._resident[model_id]
            r.last_used = time.perf_counter()
            return r.entry

    def touch(self, model_id: str) -> None:
        with self._meta:
            r = self._resident.get(model_id)
            if r is not None:
                r.last_used = time.perf_counter()

    def describe(self) -> dict:
        with self._meta:
            return {
                "models": sorted(self.specs),
                "resident": {mid: {"bytes": r.nbytes,
                                   "pagein_s": r.pagein_s}
                             for mid, r in self._resident.items()},
                "budget_bytes": self.budget_bytes,
                "pageins": self.pageins,
                "pageouts": self.pageouts,
                "paging_disabled": model_paging_disabled(),
            }

    # -- page-in ------------------------------------------------------------

    def ensure(self, model_id: str, page_in_fn):
        """Resident entry for ``model_id``, paging it in when cold.
        ``page_in_fn(spec) -> (entry, nbytes)`` does the server-side work
        (hydration, build, warmup) and runs under the model's build lock —
        concurrent submits for the same cold model serialize here and the
        losers find it resident. Eviction under the byte budget happens
        BEFORE the build so the incoming model's warmup allocates into
        freed headroom."""
        spec = self.specs.get(model_id)
        if spec is None:
            raise KeyError(f"unknown model {model_id!r}; "
                           f"configured: {sorted(self.specs)}")
        with self._locks[model_id]:
            with self._meta:
                r = self._resident.get(model_id)
                if r is not None:
                    r.last_used = time.perf_counter()
                    return r.entry
            est = self._estimate(spec)
            self._make_room(model_id, est)
            t0 = time.perf_counter()
            entry, nbytes = page_in_fn(spec)
            pagein_s = time.perf_counter() - t0
            now = time.perf_counter()
            with self._meta:
                self._resident[model_id] = _Resident(
                    spec, entry, int(nbytes or est), now,
                    last_used=now, pagein_s=pagein_s)
                self.pageins += 1
                n, total = len(self._resident), sum(
                    r.nbytes for r in self._resident.values())
            _c_pagein.inc(replica=self._rl, model=model_id)
            _h_pagein_s.observe(pagein_s, replica=self._rl, model=model_id)
            _g_resident.set(n, replica=self._rl)
            _g_resident_bytes.set(total, replica=self._rl)
            return entry

    def _estimate(self, spec: ModelSpec) -> int:
        return int(spec.est_bytes) if spec.est_bytes else 0

    def set_estimate(self, model_id: str, nbytes: int) -> None:
        """Refine a resident model's footprint after warmup captured a
        real watermark (the `MemoryBudget` device-peak path)."""
        with self._meta:
            r = self._resident.get(model_id)
            if r is not None and nbytes > 0:
                r.nbytes = int(nbytes)

    # -- eviction -----------------------------------------------------------

    def _make_room(self, incoming: str, est_bytes: int) -> None:
        """Evict idle residents (LRU weighted by EMA service time) until
        ``est_bytes`` fits under the budget; refuse with memory
        backpressure when busy models pin the budget. No-op without a
        budget or with paging disabled. Caller holds the incoming model's
        build lock (never the meta lock)."""
        if self.budget_bytes is None or model_paging_disabled():
            return
        while True:
            with self._meta:
                used = sum(r.nbytes for r in self._resident.values())
                if used + est_bytes <= self.budget_bytes:
                    return
                now = time.perf_counter()
                victims = sorted(
                    ((mid, r) for mid, r in self._resident.items()
                     if mid != incoming),
                    key=lambda it: self._evict_score(it[0], it[1], now),
                    reverse=True)
            evicted = False
            for mid, _ in victims:
                if self._busy_fn(mid):
                    continue  # queued/in-flight work: never evicted
                if self._evict(mid):
                    evicted = True
                    break
            if not evicted:
                from wam_tpu.serve.runtime import MemoryAdmissionError

                raise MemoryAdmissionError(
                    self.retry_after_s, bucket=f"model:{incoming}")

    def _evict_score(self, mid: str, r: _Resident, now: float) -> float:
        """Higher = evict first: idle seconds over the model's mean EMA
        batch service time (seeded), so old-and-cheap pages out before
        recently-hot-or-expensive."""
        from wam_tpu.serve.metrics import EMA_SEED_S

        ema = self._ema_fn(mid) or 0.0
        return (now - r.last_used) / max(ema, EMA_SEED_S)

    def _evict(self, model_id: str) -> bool:
        """Drop one resident (its entry object is released; jax frees the
        device buffers when the last reference dies). Rechecks busy-ness
        under the meta lock against the map it mutates."""
        with self._meta:
            r = self._resident.get(model_id)
            if r is None:
                return False
            del self._resident[model_id]
            self.pageouts += 1
            n, total = len(self._resident), sum(
                x.nbytes for x in self._resident.values())
        _c_pageout.inc(replica=self._rl, model=model_id)
        _g_resident.set(n, replica=self._rl)
        _g_resident_bytes.set(total, replica=self._rl)
        return True
