"""Content-addressed attribution result cache (the serve admission tier).

WAM attribution is DETERMINISTIC per (input, label, entry, schedule): the
engines derive their SmoothGrad noise from a fixed per-entry RNG path, the
serve runtime's replicate-batch padding keeps real rows bit-identical
regardless of batch fill, and tuned schedules are the only knob that moves
the sampling chunking (and with it the noise realization). So a repeated
query — the Zipf head of real traffic, viral inputs, retried clients — can
be answered from a byte-bounded cache with EXACT results, not approximate
ones.

Key = ``sha256(input bytes | shape | dtype) | label | cache_id |
schedule_fingerprint | precision_tag``:

- the input digest covers the raw bytes plus shape/dtype, so a reshaped
  or recast array never collides;
- ``cache_id`` names the entry/model/method this cache serves. A cache is
  only shared between servers running the SAME logical entry (fleet
  replicas built from one factory); callers serving multiple entries from
  one cache must pass distinguishing ids;
- the schedule fingerprint (`tune.cache.schedule_fingerprint`) changes
  whenever a tuned schedule lands or the schedule kill switch flips, so
  stale-schedule hits are structurally impossible — the key stops
  matching (tests pin this);
- the precision tag (`config.precision_tag`) covers env-routed precision
  flips (``WAM_TPU_FAN_DTYPE`` / ``WAM_TPU_MEL_BF16``) the same way —
  a bf16 run can never replay a cached f32 result or vice versa.

Placement: `AttributionServer.submit` / `FleetServer.submit` consult the
cache BEFORE admission — a hit resolves the future immediately and never
touches the bounded queue, memory admission, or a batch slot (DESIGN.md
"Admission & coalescing"). Population happens at harvest: each real row of
a completed batch is stored host-side.

Bounding: a plain LRU over an `OrderedDict` with a BYTE budget (values are
numpy pytrees; their ``nbytes`` sum is the charge). Oversized single
values are refused rather than evicting the whole cache. Eviction, hit,
and miss counts publish to the obs registry
(``wam_tpu_serve_cache_{hits,misses,evictions}_total``) and to a v2
``result_cache`` ledger row (`serve.metrics.write_result_cache`).

Kill switch: ``WAM_TPU_NO_RESULT_CACHE=1`` bypasses get/put per call
(mirrors ``WAM_TPU_NO_SCHEDULE_CACHE`` / the AOT cache convention) — for
bisecting "is the cache wrong" in production without a restart.

Exactness caveat (serve.buckets): deterministic entries (``method=
"gradcam"``/plain gradients) are bit-exact by construction. SmoothGrad
entries are bit-exact PER ROW POSITION — the serve runtime always packs a
request into *some* row of a full ``max_batch`` batch, and the engines'
per-batch RNG gives each row its own noise stream, so two computes of the
same input in different row positions differ by the (unbiased) sampling
noise. The cache returns whichever realization was computed first —
deterministic for a given arrival order, within estimator variance always.
Callers for whom realization identity matters (eval suites) should bypass
the cache (kill switch) rather than depend on arrival order.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from wam_tpu.obs.registry import registry as _obs_registry

__all__ = ["ResultCache", "result_cache_key", "cache_disabled"]

_c_hits = _obs_registry.counter(
    "wam_tpu_serve_cache_hits_total",
    "result-cache hits (futures resolved without admission)")
_c_misses = _obs_registry.counter(
    "wam_tpu_serve_cache_misses_total",
    "result-cache misses (requests that went through admission)")
_c_evictions = _obs_registry.counter(
    "wam_tpu_serve_cache_evictions_total",
    "result-cache LRU evictions under the byte budget")
_g_bytes = _obs_registry.gauge(
    "wam_tpu_serve_cache_bytes", "resident result-cache payload bytes")
_g_entries = _obs_registry.gauge(
    "wam_tpu_serve_cache_entries", "resident result-cache entries")


def cache_disabled() -> bool:
    """``WAM_TPU_NO_RESULT_CACHE=1`` kill switch, read per call so flipping
    the env var takes effect without a restart."""
    return os.environ.get("WAM_TPU_NO_RESULT_CACHE", "") not in ("", "0")


def result_cache_key(x: np.ndarray, y, cache_id: str,
                     model: str | None = None) -> str:
    """Content address for one request: input digest + label + entry id +
    the live tuned-schedule fingerprint (module docstring) + the live
    precision tag. Tuned-entry precision flips already move the schedule
    fingerprint; the tag covers the ENV route (``WAM_TPU_FAN_DTYPE`` /
    ``WAM_TPU_MEL_BF16``), read per call like the fingerprint, so flipping
    a precision knob can never replay a result computed under the other
    policy. ``model`` folds a paged model's identity into the key (multi-
    model fleets share one cache), so exact-replay hits can never cross
    models; None keeps the historical single-model key unchanged."""
    from wam_tpu.config import precision_tag
    from wam_tpu.tune.cache import schedule_fingerprint

    h = hashlib.sha256()
    h.update(x.tobytes())
    h.update(repr((x.shape, str(x.dtype))).encode())
    key = (f"{h.hexdigest()}|{y}|{cache_id}|{schedule_fingerprint()}"
           f"|{precision_tag()}")
    if model is not None:
        key = f"{key}|{model}"
    return key


def _tree_bytes(value) -> int:
    import jax

    return sum(int(np.asarray(leaf).nbytes)
               for leaf in jax.tree_util.tree_leaves(value))


class ResultCache:
    """Thread-safe bounded LRU of attribution result pytrees.

    ``max_bytes`` bounds the summed payload ``nbytes`` (keys and dict
    overhead are not charged — the payloads dominate by orders of
    magnitude). ``cache_id`` is baked into every key (module docstring).
    One instance may be shared by many servers: client threads `get` under
    `submit`, worker threads `put` at harvest; one lock covers both (the
    critical sections are dict moves, not hashing — keys are computed
    outside).

    Tenant partitioning: entries live in per-tenant LRU shards (the
    ``None`` shard is the tenant-less default and recovers the historical
    single-LRU behavior exactly). Each live shard gets an equal slice of
    the byte budget, a hot tenant trims its OWN shard first, and the
    global bound evicts from the LARGEST shard — so one hot tenant can
    never flush everyone else's working set. A tenant's `get` only sees
    its own shard: hit/miss accounting (and the nonzero-hit-rate isolation
    gate) is per tenant.
    """

    def __init__(self, max_bytes: int, *, cache_id: str = ""):
        if max_bytes < 1:
            raise ValueError("max_bytes must be >= 1")
        self.max_bytes = int(max_bytes)
        self.cache_id = str(cache_id)
        self._lock = threading.Lock()
        # tenant (None | str) -> LRU shard of key -> (value, nbytes)
        self._shards: dict = {None: OrderedDict()}
        self._shard_bytes: dict = {None: 0}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._tstats: dict = {}  # tenant -> {"hits": n, "misses": n}

    def key(self, x: np.ndarray, y, model: str | None = None) -> str:
        return result_cache_key(x, y, self.cache_id, model=model)

    def _evict_one_locked(self, tenant) -> None:
        shard = self._shards[tenant]
        _, (_, sz) = shard.popitem(last=False)
        self._shard_bytes[tenant] -= sz
        self._bytes -= sz
        self.evictions += 1

    def get(self, key: str, tenant: str | None = None):
        """The cached pytree, or None. Counts a hit or a miss — call it
        once per admission decision, not speculatively."""
        if cache_disabled():
            return None
        with self._lock:
            shard = self._shards.get(tenant)
            entry = shard.get(key) if shard is not None else None
            if tenant is not None:
                ts = self._tstats.setdefault(
                    tenant, {"hits": 0, "misses": 0})
            if entry is None:
                self.misses += 1
                if tenant is not None:
                    ts["misses"] += 1
                _c_misses.inc()
                return None
            shard.move_to_end(key)
            self.hits += 1
            if tenant is not None:
                ts["hits"] += 1
        _c_hits.inc()
        return entry[0]

    def put(self, key: str, value, tenant: str | None = None) -> bool:
        """Insert (host-side pytree) into the tenant's shard, evicting LRU
        entries down to the fair-share and global byte budgets. A single
        value over the whole budget is refused (returns False) instead of
        flushing everything for an uncacheable row."""
        if cache_disabled():
            return False
        nbytes = _tree_bytes(value)
        if nbytes > self.max_bytes:
            return False
        evicted0 = self.evictions
        with self._lock:
            shard = self._shards.get(tenant)
            if shard is None:
                shard = self._shards[tenant] = OrderedDict()
                self._shard_bytes[tenant] = 0
            old = shard.pop(key, None)
            if old is not None:
                self._shard_bytes[tenant] -= old[1]
                self._bytes -= old[1]
            # fair share: every LIVE (non-empty, plus the inserting) shard
            # gets an equal budget slice; a hot tenant evicts from its OWN
            # shard before touching others
            live = {t for t, s in self._shards.items() if s} | {tenant}
            cap = self.max_bytes // len(live)
            while self._shard_bytes[tenant] + nbytes > cap and shard:
                self._evict_one_locked(tenant)
            # global bound: trim the LARGEST shard (ties break arbitrarily)
            while self._bytes + nbytes > self.max_bytes:
                victim = max(
                    (t for t, s in self._shards.items() if s),
                    key=lambda t: self._shard_bytes[t], default=None)
                if victim is None:
                    break
                self._evict_one_locked(victim)
            shard[key] = (value, nbytes)
            self._shard_bytes[tenant] += nbytes
            self._bytes += nbytes
            evicted = self.evictions - evicted0
            nbytes_now = self._bytes
            entries_now = sum(len(s) for s in self._shards.values())
        if evicted:
            _c_evictions.inc(evicted)
        _g_bytes.set(nbytes_now)
        _g_entries.set(entries_now)
        return True

    def __len__(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._shards.values())

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        """Counter snapshot (the ``result_cache`` ledger-row body and the
        bench's hit-rate report)."""
        with self._lock:
            hits, misses = self.hits, self.misses
            out = {
                "hits": hits,
                "misses": misses,
                "evictions": self.evictions,
                "entries": sum(len(s) for s in self._shards.values()),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
                "cache_id": self.cache_id,
                "disabled": cache_disabled(),
            }
            if self._tstats:
                out["tenants"] = {
                    str(t): {
                        "hits": ts["hits"],
                        "misses": ts["misses"],
                        "hit_rate": (ts["hits"] / (ts["hits"] + ts["misses"])
                                     if ts["hits"] + ts["misses"] else 0.0),
                        "entries": len(self._shards.get(t, ())),
                        "bytes": self._shard_bytes.get(t, 0),
                    }
                    for t, ts in sorted(self._tstats.items())
                }
            return out

    def row(self) -> dict:
        """The v2 ``result_cache`` ledger row (schema stamped by
        `serve.metrics.write_result_cache`, which owns the envelope)."""
        row = {"metric": "result_cache", "timestamp": time.time()}
        row.update(self.stats())
        return row
