"""Replica supervision: restart-on-death with backoff, crash-loop
escalation, and the ``replica_restart`` ledger trail (resilience tentpole
part 1).

Before this module a replica that threw a non-`ServeError` was dead
fleet-wide FOREVER — correct for draining in-flight work (the re-route
path), wrong for a production fleet where most deaths are transient
(preempted chip, injected fault, driver hiccup). `ReplicaSupervisor` owns
the lifecycle past the death notification:

1. `serve.fleet.FleetServer._harvest` marks the replica dead and notifies
   the supervisor (the EXISTING drain/re-route semantics are untouched —
   in-flight and queued requests fail over to survivors immediately, they
   never wait on a restart).
2. A restart thread backs off (exponential in the replica's recent restart
   count, jittered from the supervisor's seeded RNG, capped), closes the
   dead server (draining anything that raced in), rebuilds it through the
   fleet's own factory (`FleetServer._rebuild_replica`) — the SAME
   entry_factory and per-replica `ServeMetrics`, so compile counts
   accumulate across incarnations — and re-runs the parallel bucket
   warmup. Warm state rehydrates through the same caches the first start
   used: the jit/AOT executable caches (`serve.entry.jit_entry` /
   ``aot_key``) and the tuned-schedule cache, so a restart on a warm
   process recompiles nothing the process already traced and the
   restarted replica rejoins at ZERO served-window compiles
   (sentinel-verified in tests/test_resilience.py).
3. Every transition lands as a ``replica_restart`` v2 ledger row
   (`FleetMetrics.note_restart`): ``restarting`` → ``alive`` on success,
   ``restart_failed`` when the rebuild itself raised, and
   ``permanent_dead`` when the replica crash-loops — more than
   ``max_restarts`` completed restarts inside ``window_s`` — at which
   point the supervisor stops trying and the fleet serves on the
   survivors (the historical permanent-death behavior, now a deliberate
   escalation instead of the only option).

Supervision is OPT-IN at the `FleetServer` surface (``supervise=``):
existing death-semantics tests and any caller relying on
permanent-on-first-death keep their behavior; `ServeConfig.supervise`
defaults it ON for the bench/CLI path.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from wam_tpu.obs import tracing as obs_tracing

__all__ = ["ReplicaSupervisor", "SupervisorConfig"]


@dataclass(frozen=True)
class SupervisorConfig:
    """Restart policy. ``max_restarts`` completed restarts within
    ``window_s`` escalate the NEXT death to permanent-dead (crash-loop
    detection); backoff before restart ``k`` (k = recent restarts) is
    ``min(cap, base·2^k)`` times a jitter in [1, 1+jitter_frac] from a
    seeded RNG (deterministic schedules in tests)."""

    max_restarts: int = 3
    window_s: float = 60.0
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter_frac: float = 0.2
    seed: int | None = None


class ReplicaSupervisor:
    """One per supervised `FleetServer`. Thread-safe; every death spawns
    one daemon restart thread (deaths are rare — thread-per-event keeps
    the fleet's hot path free of supervisor machinery)."""

    # checked by the lock-discipline lint rule
    _GUARDED_BY = {
        "_history": "_lock",
        "_permanent": "_lock",
        "_threads": "_lock",
    }

    def __init__(self, fleet, config: SupervisorConfig | None = None):
        self._fleet = fleet
        self.config = config if config is not None else SupervisorConfig()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._rng = random.Random(self.config.seed)
        # per-replica completed-restart timestamps (monotonic) — the
        # crash-loop window — and permanent-dead flags
        self._history: dict = {}
        self._permanent: set = set()
        self._threads: list[threading.Thread] = []

    # -- death notification (called from _harvest, post mark-dead) ----------

    def notify_death(self, rid, reason: str = "") -> None:
        """Schedule a restart for a replica just marked dead. No-op once
        the replica is permanently dead or the supervisor is closing."""
        if self._stop.is_set():
            return
        with self._lock:
            if rid in self._permanent:
                return
            now = time.monotonic()
            recent = [t for t in self._history.get(rid, [])
                      if now - t <= self.config.window_s]
            self._history[rid] = recent
            if len(recent) >= self.config.max_restarts:
                self._permanent.add(rid)
                escalate = True
            else:
                escalate = False
                attempt = len(recent) + 1
            t = None
            if not escalate:
                t = threading.Thread(
                    target=self._restart, args=(rid, attempt, reason),
                    name=f"wam-supervisor-{rid}", daemon=True)
                self._threads.append(t)
        if escalate:
            self._fleet.metrics.note_restart(
                rid, "permanent_dead",
                attempt=self.config.max_restarts, reason=reason
                or f"crash loop: {self.config.max_restarts} restarts "
                   f"in {self.config.window_s:g}s")
            return
        t.start()

    def _restart(self, rid, attempt: int, reason: str) -> None:
        backoff = min(self.config.backoff_cap_s,
                      self.config.backoff_base_s * 2 ** (attempt - 1))
        with self._lock:
            backoff *= 1.0 + self.config.jitter_frac * self._rng.random()
        self._fleet.metrics.note_restart(
            rid, "restarting", attempt=attempt, backoff_s=backoff,
            reason=reason)
        if self._stop.wait(backoff):
            return  # fleet closing: leave the replica down
        with obs_tracing.span("replica_restart", cat="fleet", replica=rid,
                              attempt=attempt):
            try:
                self._fleet._rebuild_replica(rid)
            except Exception as e:  # noqa: BLE001 - a supervisor thread must not die
                self._fleet.metrics.note_restart(
                    rid, "restart_failed", attempt=attempt,
                    backoff_s=backoff, reason=repr(e))
                # a failed rebuild is itself a death: escalate through the
                # same crash-loop accounting (counts as a completed try)
                with self._lock:
                    self._history.setdefault(rid, []).append(time.monotonic())
                if not self._stop.is_set():
                    self.notify_death(rid, reason=f"rebuild failed: {e!r}")
                return
        with self._lock:
            self._history.setdefault(rid, []).append(time.monotonic())
        self._fleet.metrics.note_restart(
            rid, "alive", attempt=attempt, backoff_s=backoff, reason=reason)

    # -- introspection / lifecycle ------------------------------------------

    def permanently_dead(self, rid=None):
        with self._lock:
            if rid is None:
                return sorted(self._permanent, key=str)
            return rid in self._permanent

    def describe(self) -> dict:
        with self._lock:
            return {
                "max_restarts": self.config.max_restarts,
                "window_s": self.config.window_s,
                "restarts": {str(r): len(ts) for r, ts in self._history.items()
                             if ts},
                "permanent_dead": sorted(self._permanent, key=str),
            }

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop scheduling restarts and join any in-flight restart thread
        (each bounded by backoff_cap + one warmup)."""
        self._stop.set()
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
