"""Shape bucketing for the serving runtime.

A jit-compiled attribution graph is specialized to one input shape; a
stray request shape on the hot path means a 20-40s TPU recompile stall for
every request behind it (DESIGN.md round-4: the host is the hot path on a
tunneled accelerator). The dispatcher therefore admits only a small fixed
set of *bucket* shapes, decided at server construction: every request is
routed to the smallest bucket that fits it, right-padded up to the bucket's
spatial dims, and batches are always dispatched at the bucket's full
``max_batch`` rows — so each bucket compiles exactly once, at warmup.

Padding semantics:
- **Batch rows** are padded by REPLICATING the first real item. With the
  engines' default per-block max-normalization (`ops.packing2d.mosaic2d`),
  duplicate rows cannot move a block's max, so batch padding leaves real
  rows' attributions numerically unchanged for deterministic entries (the
  correctness property tests/test_serve.py asserts). Zero rows would
  perturb the normalizer. Stochastic entries (SmoothGrad) draw noise per
  batch SHAPE, and every dispatch is the same full ``max_batch`` shape —
  so serving is deterministic given a request's row position, but it is a
  different (equally valid) noise realization than an unbatched call.
- **Spatial dims** are right/bottom zero-padded to the bucket. This changes
  the transform's boundary context, so a spatially padded attribution is
  the attribution *of the padded input* at the bucket's resolution — the
  standard serving trade (documented per bucket in the metrics as
  ``pad_waste``). Route exact shapes to exact buckets when parity with an
  unbatched call matters.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["Bucket", "BucketTable", "NoBucketError", "bucket_key", "pad_item"]


def bucket_key(shape) -> str:
    """Stable string form of a bucket shape — "1x224x224" — the same
    grammar `config.ServeConfig.buckets` parses. Used as the JSON-safe key
    of the per-bucket ledger maps (`serve.metrics` EMA / warmup seconds)."""
    return "x".join(str(int(s)) for s in shape)


class NoBucketError(ValueError):
    """No configured bucket admits the request's shape — a permanent
    condition for this server (unlike `QueueFullError`, retrying cannot
    help)."""


@dataclass(frozen=True, order=True)
class Bucket:
    """One compiled item shape (no batch dim; e.g. (C, H, W) for images,
    (W,) for waveforms, (1, D, H, W) for volumes). Ordering is by padded
    element count so `BucketTable.select` prefers the least-waste fit."""

    elements: int
    shape: tuple[int, ...]

    @classmethod
    def of(cls, shape) -> "Bucket":
        shape = tuple(int(s) for s in shape)
        return cls(int(np.prod(shape)) if shape else 1, shape)

    @property
    def key(self) -> str:
        return bucket_key(self.shape)

    def fits(self, item_shape: tuple[int, ...]) -> bool:
        return len(item_shape) == len(self.shape) and all(
            s <= b for s, b in zip(item_shape, self.shape)
        )

    def pad_waste(self, item_shape: tuple[int, ...]) -> float:
        """Fraction of this bucket's elements that padding ``item_shape``
        up to it would waste (0.0 for an exact fit)."""
        return 1.0 - float(np.prod(item_shape)) / self.elements


class BucketTable:
    """The fixed admitted-shape set. ``select`` returns the smallest (by
    element count) bucket whose every dim >= the item's — i.e. minimal pad
    waste among fitting buckets — or raises `NoBucketError`."""

    def __init__(self, shapes):
        if not shapes:
            raise ValueError("at least one bucket shape is required")
        self.buckets = sorted(Bucket.of(s) for s in shapes)
        if len({b.shape for b in self.buckets}) != len(self.buckets):
            raise ValueError("duplicate bucket shapes")

    def select(self, item_shape) -> Bucket:
        item_shape = tuple(int(s) for s in item_shape)
        for b in self.buckets:
            if b.fits(item_shape):
                return b
        raise NoBucketError(
            f"no bucket fits item shape {item_shape}; "
            f"buckets: {[b.shape for b in self.buckets]}"
        )

    def __iter__(self):
        return iter(self.buckets)

    def __len__(self):
        return len(self.buckets)


def pad_item(x: np.ndarray, bucket: Bucket) -> np.ndarray:
    """Right/bottom zero-pad one item up to the bucket shape (host-side, so
    the padded batch assembles into one contiguous transfer)."""
    if x.shape == bucket.shape:
        return x
    widths = [(0, b - s) for s, b in zip(x.shape, bucket.shape)]
    return np.pad(x, widths)
