"""Jitted serving entries — the glue between an engine and the runtime.

A serving entry is a pure callable ``entry(x, y) -> attribution pytree``
with a leading batch axis on every input and output leaf, no instance-
attribute stashing (the worker loop is a thread; the engines' ``__call__``
convenience surface mutates ``self.scales`` etc. and is NOT thread-safe),
and jit applied here so the runtime can:

- **donate** the padded input batch (the dispatcher builds a fresh host
  buffer per batch, so aliasing it into the graph saves one HBM copy per
  dispatch on TPU; donation is off on backends that cannot use it), and
- **count jit cache misses** via ``on_trace``: the wrapped Python callable
  runs exactly once per compiled shape, so the hook is a direct cache-miss
  counter — the serve ledger's ``compile_count`` and the one-compile-per-
  bucket test assertion.
"""

from __future__ import annotations

from typing import Callable

import jax

__all__ = ["jit_entry"]


def jit_entry(
    impl: Callable,
    *,
    donate: bool | None = None,
    on_trace: Callable[[], None] | None = None,
):
    """Wrap ``impl(x, y)`` as a serving entry (see module docstring).

    ``donate=None`` resolves to "donate on TPU only" — XLA:CPU leaves
    donated buffers unused and warns per call."""
    if donate is None:
        donate = jax.default_backend() == "tpu"

    def wrapped(x, y):
        if on_trace is not None:
            on_trace()  # trace-time only: one call per jit cache miss
        return impl(x, y)

    return jax.jit(wrapped, donate_argnums=(0,) if donate else ())
