"""Jitted serving entries — the glue between an engine and the runtime.

A serving entry is a pure callable ``entry(x, y) -> attribution pytree``
with a leading batch axis on every input and output leaf, no instance-
attribute stashing (the worker loop is a thread; the engines' ``__call__``
convenience surface mutates ``self.scales`` etc. and is NOT thread-safe),
and jit applied here so the runtime can:

- **donate** the padded input batch (the dispatcher stages a fresh device
  buffer per batch, so aliasing it into the graph saves one HBM copy per
  dispatch on TPU; donation is off on backends that cannot use it),
- **count jit cache misses** via ``on_trace``: the wrapped Python callable
  runs exactly once per compiled shape, so the hook is a direct cache-miss
  counter — the serve ledger's ``compile_count`` and the one-compile-per-
  bucket test assertion, and
- **skip the trace entirely** on later processes via ``aot_key``: the
  entry is routed through the AOT executable cache
  (`wam_tpu.pipeline.aot.cached_entry`), so a warmup that already exported
  this model's buckets deserializes instead of retracing — ``on_trace``
  then never fires, which is exactly what the warm-start tests probe.
  The key must uniquely identify the model + params (exported modules
  bake in closed-over constants); no key → no AOT.
"""

from __future__ import annotations

from typing import Callable

import jax

from wam_tpu.obs import sentinel
from wam_tpu.pipeline.donation import resolve_donate

__all__ = ["jit_entry", "fleet_aot_key"]


def fleet_aot_key(aot_key: str | None, n_replicas: int | None,
                  precision: str | None = None) -> str | None:
    """Replica-count (and precision) tag for fleet AOT keys. The fleet's
    oversize entry is dispatched data-parallel over an N-chip mesh, and an
    exported executable bakes that mesh size in — so an export built for a
    4-chip fleet must be a cache MISS on an 8-chip one. Likewise the
    precision policy is baked into the traced program (bf16 param casts,
    boundary input casts), so a non-default ``precision`` tag
    (`config.PrecisionPolicy.tag()`, e.g. "bf16" or "bf16+mel") is appended
    — a bf16 export must never cache-hit the f32 one. Single-chip keys
    (``n_replicas`` in {None, 1}) and the default policy ("f32"/None/"")
    pass through unchanged, keeping existing AOT caches warm."""
    if aot_key is None:
        return None
    if n_replicas not in (None, 1):
        aot_key = f"{aot_key}|fleet{int(n_replicas)}"
    if precision not in (None, "", "f32"):
        aot_key = f"{aot_key}|{precision}"
    return aot_key


def jit_entry(
    impl: Callable,
    *,
    donate: bool | None = None,
    on_trace: Callable[[], None] | None = None,
    aot_key: str | None = None,
    obs_kind: str = "serve",
    with_health: bool | str = False,
):
    """Wrap ``impl(x, y)`` as a serving entry (see module docstring).

    ``donate=None`` resolves to "donate on TPU only" — XLA:CPU leaves
    donated buffers unused and warns per call. ``aot_key`` opts the entry
    into the AOT executable cache. Every jit trace is also reported to the
    compile sentinel (`wam_tpu.obs.sentinel`) under ``obs_kind``, tagged
    with whatever bucket/replica/phase labels the caller's thread holds.

    ``with_health=True`` fuses the numeric-health reduction into the SAME
    compiled graph: the entry returns ``(out, health_vec)`` where the
    vector is `wam_tpu.obs.health.health_stats` over the output — one more
    output leaf of the program already being fetched, never a second
    fetch. ``with_health="fused"`` declares that ``impl`` ALREADY returns
    that tuple (engines that fold gradient stats into the vector use this,
    e.g. via `WamEngine.attribute_with_health`). Either way the returned
    entry carries ``entry.wam_health = True`` so the serve worker knows to
    unpack, and the AOT key is tagged ``|health`` — a health-fused export
    must never cache-hit a plain one."""
    fused = with_health == "fused"
    if with_health and not fused:
        from wam_tpu.obs.health import health_stats

        base_impl = impl

        def impl(x, y):  # noqa: F811 - deliberate health-wrapped rebind
            out = base_impl(x, y)
            return out, health_stats(out)

        impl.__name__ = getattr(base_impl, "__name__", "entry") + "+health"
    if with_health and aot_key is not None:
        aot_key = f"{aot_key}|health"

    if aot_key is not None:
        from wam_tpu.pipeline.aot import cached_entry

        jitted = cached_entry(
            impl,
            aot_key,
            donate_argnums=(0,) if resolve_donate(donate) else (),
            on_trace=on_trace,
            obs_kind=obs_kind,
        )
    else:
        def wrapped(x, y):
            # trace-time only: one execution per jit cache miss
            sentinel.record_trace(obs_kind,
                                  detail=getattr(impl, "__name__", ""),
                                  bucket=_bucket_of(x))
            if on_trace is not None:
                on_trace()
            return impl(x, y)

        jitted = jax.jit(
            wrapped, donate_argnums=(0,) if resolve_donate(donate) else ())
    if not with_health:
        return jitted

    # plain-function shell: jit/AOT callables reject attribute assignment
    def entry(x, y):
        return jitted(x, y)

    entry.wam_health = True
    return entry


def _bucket_of(x):
    """Bucket label for a compile event: the traced input's shape (jit
    passes ShapedArray tracers, so .shape is static and host-safe)."""
    try:
        return "x".join(str(d) for d in x.shape)
    except Exception:
        return None
