"""Deterministic, schedule-driven fault injection (resilience tentpole
part 2).

The failover machinery this repo grew — replica death re-route (PR 6),
quarantine probation (PR 8), supervised restart and client retry (this
round) — is only trustworthy if it is EXERCISED under systematic fault
load, not just unit-tested transition by transition. This module is the
chaos engine: a seeded per-replica fault stream wrapped around the same
entry factories production uses, so the serve stack runs its real code
paths while faults arrive at configurable probabilities.

Fault kinds (drawn once per entry call from one uniform variate, so a
replica's fault sequence is a pure function of ``(seed, replica_id)``):

- ``exc``     — the entry raises `ChaosFault` (a non-`ServeError`): the
                fleet marks the replica dead, the supervisor restarts it.
- ``oom``     — same, with a RESOURCE_EXHAUSTED-shaped message (simulated
                device OOM; the serve layer treats any non-ServeError as a
                chip loss, so this documents the failure mode rather than
                taking a different path).
- ``nan``     — the entry's OUTPUT is poisoned with NaN: the health plane
                sees a non-finite batch (quarantine pressure, not death).
- ``latency`` — the entry sleeps ``latency_ms`` before serving (tail
                inflation; exercises retry/hedging and SLO burn).

Determinism: each replica's `FaultInjector` owns a
``random.Random(f"wam-chaos:{seed}:{rid}")`` — string seeding hashes with
a stable algorithm, so schedules reproduce across processes regardless of
``PYTHONHASHSEED``. A replica's serve worker is single-threaded, so the
draw sequence maps 1:1 to its batch sequence.

Spec grammar (``bench_serve --chaos SPEC``)::

    default                         # DEFAULT_CHAOS on every replica
    off                             # all probabilities zero
    nan=0.05,exc=0.02,latency=0.1:20   # one spec for every replica
    0:exc=0.5;*:nan=0.1             # per-replica overrides ('*' = rest)

``latency=p`` uses the default 5 ms; ``latency=p:ms`` sets both.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass

from wam_tpu.obs import sentinel as _sentinel
from wam_tpu.obs.registry import registry as _registry

__all__ = [
    "ChaosFault",
    "ChaosSchedule",
    "DEFAULT_CHAOS",
    "FaultInjector",
    "FaultSpec",
    "PodChaosKiller",
    "parse_chaos",
    "stager_chaos",
]

_c_injected = _registry.counter(
    "wam_tpu_chaos_injected_total", "faults injected by the chaos layer",
    labels=("kind", "replica"))


class ChaosFault(RuntimeError):
    """An injected entry failure. Deliberately NOT a `ServeError`: the
    fleet's `_harvest` treats it as a chip loss — replica marked dead,
    request re-routed — which is exactly the path chaos must exercise."""


@dataclass(frozen=True)
class FaultSpec:
    """Per-call fault probabilities for one replica. Probabilities are
    mutually exclusive slices of one uniform draw; their sum must be
    <= 1 (the remainder is a clean call)."""

    nan_p: float = 0.0
    exc_p: float = 0.0
    oom_p: float = 0.0
    latency_p: float = 0.0
    latency_ms: float = 5.0

    def __post_init__(self):
        total = self.nan_p + self.exc_p + self.oom_p + self.latency_p
        if not 0.0 <= total <= 1.0:
            raise ValueError(
                f"fault probabilities sum to {total:.3f}; must be in [0, 1]")


# The default chaos schedule (`--chaos default`, the CI smoke + acceptance
# gate): per-BATCH probabilities tuned so a toy 2-replica run reliably sees
# latency + backpressure-retry pressure and a 4-replica bench run sees
# multiple deaths/restarts, while clean batches still dominate.
DEFAULT_CHAOS = FaultSpec(nan_p=0.05, exc_p=0.05, oom_p=0.02,
                          latency_p=0.10, latency_ms=5.0)

_ZERO = FaultSpec()


def _parse_one(spec: str) -> FaultSpec:
    spec = spec.strip().lower()
    if spec in ("default", ""):
        return DEFAULT_CHAOS
    if spec in ("off", "none"):
        return _ZERO
    kw: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key == "latency":
            p, _, ms = val.partition(":")
            kw["latency_p"] = float(p)
            if ms:
                kw["latency_ms"] = float(ms)
        elif key in ("nan", "exc", "oom"):
            kw[f"{key}_p"] = float(val)
        else:
            raise ValueError(
                f"unknown chaos fault {key!r} (want nan/exc/oom/latency)")
    return FaultSpec(**kw)


def parse_chaos(spec: str) -> dict[str, FaultSpec]:
    """Parse a chaos spec string into ``{replica_key: FaultSpec}`` —
    ``"*"`` is the every-replica default (grammar in module docstring)."""
    spec = (spec or "").strip()
    if ";" not in spec and ":" not in spec.split(",")[0].partition("=")[0]:
        return {"*": _parse_one(spec)}
    out: dict[str, FaultSpec] = {}
    for seg in spec.split(";"):
        seg = seg.strip()
        if not seg:
            continue
        head, sep, rest = seg.partition(":")
        if sep and "=" not in head:
            out[head.strip()] = _parse_one(rest)
        else:
            out["*"] = _parse_one(seg)
    return out


class FaultInjector:
    """One replica's deterministic fault stream: a private seeded RNG and
    the spec's probability partition. ``draw()`` consumes exactly one
    variate per call, so the Nth call's fault kind is reproducible."""

    def __init__(self, spec: FaultSpec, seed: int, replica=None):
        self.spec = spec
        self.replica = "-" if replica is None else str(replica)
        self._rng = random.Random(f"wam-chaos:{seed}:{self.replica}")
        self._lock = threading.Lock()
        self.counts: dict[str, int] = {}

    def draw(self) -> str | None:
        """The next call's fault kind (None = clean), from one uniform
        draw partitioned [exc | oom | nan | latency | clean]."""
        s = self.spec
        with self._lock:
            u = self._rng.random()
        edges = (("exc", s.exc_p), ("oom", s.oom_p), ("nan", s.nan_p),
                 ("latency", s.latency_p))
        acc = 0.0
        for kind, p in edges:
            acc += p
            if u < acc:
                return kind
        return None

    def fire(self, kind: str) -> None:
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
        _c_injected.inc(kind=kind, replica=self.replica)

    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())


def _poison_nan(tree):
    """NaN-poison every inexact leaf of a result tree (host-side numpy —
    the chaos harness runs on virtual CPU fleets; on real hardware this
    would force a transfer, which is fine for a test harness)."""
    import jax
    import numpy as np

    def leaf(a):
        arr = np.asarray(a)
        if not np.issubdtype(arr.dtype, np.inexact):
            return arr
        out = arr.copy()
        out.reshape(-1)[0] = np.nan
        return out

    return jax.tree_util.tree_map(leaf, tree)


class ChaosEntry:
    """Wraps a serving entry with one injector. Health-fused entries
    (``entry.wam_health``) get their health vector RECOMPUTED over the
    poisoned output — the fused vector described the clean result, and a
    poisoned batch must look poisoned to the quarantine machinery."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self.injector = injector
        self.wam_health = bool(getattr(inner, "wam_health", False))

    def __call__(self, xs, ys):
        # warmup dispatches are exempt and consume NO draws: a warmup fault
        # would fail server start (and a restart's re-warm would perturb the
        # replica's deterministic fault stream). The serve warm path labels
        # its dispatches phase="warmup" on the calling thread.
        if _sentinel._current_labels().get("phase") == "warmup":
            return self._inner(xs, ys)
        kind = self.injector.draw()
        if kind == "exc":
            self.injector.fire(kind)
            raise ChaosFault(
                f"chaos: injected entry failure (replica {self.injector.replica})")
        if kind == "oom":
            self.injector.fire(kind)
            raise ChaosFault(
                "RESOURCE_EXHAUSTED: chaos-simulated device OOM "
                f"(replica {self.injector.replica})")
        if kind == "latency":
            self.injector.fire(kind)
            time.sleep(self.spec_latency_s)
        out = self._inner(xs, ys)
        if kind == "nan":
            self.injector.fire(kind)
            if self.wam_health:
                from wam_tpu.obs.health import batch_stats

                res, _ = out
                res = _poison_nan(res)
                return res, batch_stats(res)
            return _poison_nan(out)
        return out

    @property
    def spec_latency_s(self) -> float:
        return self.injector.spec.latency_ms / 1e3


class ChaosSchedule:
    """A parsed chaos spec + seed: builds one deterministic `FaultInjector`
    per replica and wraps entry factories for `FleetServer` /
    `AttributionServer` construction."""

    def __init__(self, specs: dict[str, FaultSpec] | FaultSpec | str = "default",
                 seed: int = 0):
        if isinstance(specs, str):
            specs = parse_chaos(specs)
        elif isinstance(specs, FaultSpec):
            specs = {"*": specs}
        self.specs = dict(specs)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._injectors: dict[str, FaultInjector] = {}

    def spec_for(self, rid) -> FaultSpec:
        key = "-" if rid is None else str(rid)
        return self.specs.get(key, self.specs.get("*", _ZERO))

    def injector(self, rid) -> FaultInjector:
        """Get-or-create the replica's injector — a restarted replica's
        fresh entry keeps the SAME fault stream (the supervisor rebuilt
        the server, not the chaos schedule)."""
        key = "-" if rid is None else str(rid)
        with self._lock:
            if key not in self._injectors:
                self._injectors[key] = FaultInjector(
                    self.spec_for(rid), self.seed, replica=rid)
            return self._injectors[key]

    def wrap_factory(self, entry_factory):
        """``entry_factory(rid, metrics) -> entry`` with chaos wrapped in.
        The fleet's oversize/seq entries get the "*" (or their own id's)
        stream too."""

        def factory(rid, metrics):
            return ChaosEntry(entry_factory(rid, metrics), self.injector(rid))

        return factory

    def injected_total(self) -> int:
        with self._lock:
            injectors = list(self._injectors.values())
        return sum(i.total() for i in injectors)

    def injected_counts(self) -> dict[str, int]:
        with self._lock:
            injectors = list(self._injectors.values())
        out: dict[str, int] = {}
        for i in injectors:
            for kind, n in i.counts.items():
                out[kind] = out.get(kind, 0) + n
        return out


class PodChaosKiller:
    """Process-kill chaos for the pod tier: SIGKILL a live worker each
    time the driven request count crosses a progress threshold.

    Where `ChaosSchedule` injects faults INSIDE a process (entry raises,
    NaN poison, staging latency), this kills the process itself — the
    failure mode the pod tier exists to survive. Deterministic like the
    rest of the chaos layer: thresholds are fixed fractions of the
    planned request count and the victim at each crossing comes from a
    seeded RNG over the live worker ids, so a failing chaos run replays
    exactly (`random.Random(f"wam-pod-chaos:{seed}")`).

    Drive it from the client loop: ``on_progress(resolved_so_far)`` after
    every resolved request; at most one kill fires per threshold
    crossing, and kills land mid-stream by construction (fractions
    strictly inside (0, 1)). The kill goes through
    `PodRouter.kill_worker`, so detection, in-flight re-route, and
    supervised respawn all exercise the REAL failure paths — nothing is
    mocked."""

    def __init__(self, router, total_requests: int, *,
                 fractions=(0.25, 0.6), seed: int = 0):
        for f in fractions:
            if not 0.0 < f < 1.0:
                raise ValueError(f"kill fraction {f} not inside (0, 1)")
        self._router = router
        self._thresholds = sorted(
            max(1, int(f * total_requests)) for f in fractions)
        self._rng = random.Random(f"wam-pod-chaos:{seed}")
        self._lock = threading.Lock()
        self._fired = 0
        self.kills: list[dict] = []

    def on_progress(self, resolved: int) -> None:
        """Fire every threshold ``resolved`` has crossed (one victim
        each). Thread-safe; a crossing with zero live workers is consumed
        without a kill (the pod is already fully down — nothing to do)."""
        while True:
            with self._lock:
                if (self._fired >= len(self._thresholds)
                        or resolved < self._thresholds[self._fired]):
                    return
                threshold = self._thresholds[self._fired]
                self._fired += 1
                live = self._router.live_worker_ids()
                wid = (live[self._rng.randrange(len(live))] if live else None)
            killed = wid is not None and self._router.kill_worker(wid)
            if killed:
                _c_injected.inc(kind="kill", replica=str(wid))
            with self._lock:
                self.kills.append({"threshold": threshold,
                                   "worker_id": wid, "killed": killed})


@contextlib.contextmanager
def stager_chaos(injector: FaultInjector):
    """Inject faults at the STAGING hook: patches the serve runtime's
    ``put_committed`` so H2D uploads sleep (``latency``) or raise
    (``exc``/``oom`` → dispatch-time failure, the `_launch_batch` recover
    path) per the injector's stream. Explicitly a test-harness context
    manager — the only patched internal in the chaos layer."""
    from wam_tpu.serve import runtime

    orig = runtime.put_committed

    def staged(tree, dev):
        kind = injector.draw()
        if kind in ("exc", "oom"):
            injector.fire(kind)
            raise ChaosFault(f"chaos: injected staging failure ({kind})")
        if kind == "latency":
            injector.fire(kind)
            time.sleep(injector.spec.latency_ms / 1e3)
        return orig(tree, dev)

    runtime.put_committed = staged
    try:
        yield injector
    finally:
        runtime.put_committed = orig
