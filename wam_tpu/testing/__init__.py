"""`wam_tpu.testing` — deterministic fault injection for resilience tests
and the chaos bench (`scripts/bench_serve.py --chaos`). Production code
never imports this package; the serve stack is exercised through its
public factories (entry_factory wrapping), not patched internals — except
the stager latency hook, which is an explicit context manager.
"""

from wam_tpu.testing.faults import (
    DEFAULT_CHAOS,
    ChaosFault,
    ChaosSchedule,
    FaultInjector,
    FaultSpec,
    PodChaosKiller,
    parse_chaos,
    stager_chaos,
)

__all__ = [
    "ChaosFault",
    "ChaosSchedule",
    "DEFAULT_CHAOS",
    "FaultInjector",
    "FaultSpec",
    "PodChaosKiller",
    "parse_chaos",
    "stager_chaos",
]
