"""Typed configuration — the explicit replacement for the reference's
constructor-kwargs-only knob surface (SURVEY.md §5.6), with the reference's
defaults preserved verbatim: 2D (haar, J=3, reflect, n=25, σ-spread 0.25,
seed 42 — `lib/wam_2D.py:343-356`), 1D (haar, J=3, n=25, σ-spread 0.001,
n_mels=128, n_fft=1024, sr=44100 — `lib/wam_1D.py:249-263`), 3D (haar, J=3,
symmetric, n=25, σ-spread 1e-4, EPS=0.451 — `lib/wam_3D.py:501-520`).

`device=` is the backend selector mandated by BASELINE.json's north star:
"pipelines pick the backend via a device= flag".
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field, fields

__all__ = [
    "WAM2DConfig",
    "WAM1DConfig",
    "WAM3DConfig",
    "EvalConfig",
    "ServeConfig",
    "ObsConfig",
    "PrecisionPolicy",
    "resolve_precision",
    "precision_tag",
    "compute_cast",
    "fp8_supported",
    "select_backend",
    "enable_compilation_cache",
    "add_config_args",
    "config_from_args",
]

FAN_DTYPES = ("f32", "bf16", "fp8")


def fp8_supported() -> bool:
    """True when the active backend can actually run an fp8 matmul with f32
    accumulation (not merely when jnp exposes the dtype — older backends
    expose ``float8_e4m3fn`` as a storage type and fail at lowering). The
    probe compiles a tiny dot once and caches the verdict for the process.
    """
    global _fp8_result
    if _fp8_result is not None:
        return _fp8_result
    try:
        import jax.numpy as jnp

        if not hasattr(jnp, "float8_e4m3fn"):
            _fp8_result = False
            return False
        a = jnp.ones((8, 8), jnp.float8_e4m3fn)
        out = jnp.matmul(a, a, preferred_element_type=jnp.float32)
        out.block_until_ready()
        _fp8_result = bool(out.dtype == jnp.float32)
    except Exception:
        _fp8_result = False
    return _fp8_result


_fp8_result: bool | None = None


@dataclass(frozen=True)
class PrecisionPolicy:
    """Low-precision policy for the eval fans and the mel chain.

    ``fan_dtype`` is the compute dtype of the eval-fan model forwards
    ("f32" | "bf16" | "fp8"); ``mel_bf16`` flips the mel front-end's two
    DFT/filterbank contractions to bf16 inputs. Either way every
    contraction stays f32-accumulated (``preferred_element_type``) and
    every reduction downstream of the cast (softmax, AUC trapezoid,
    Spearman) runs in f32 — the cast is a boundary shim, never a policy
    on the math that ranks things. "fp8" degrades to bf16 when the
    backend fails the `fp8_supported` probe, so a policy tuned on an
    fp8-capable chip still runs (slower, more accurate) elsewhere.

    Resolution (`resolve_precision`) is explicit-arg > env knob
    (``WAM_TPU_FAN_DTYPE`` / ``WAM_TPU_MEL_BF16``) > tuned schedule entry
    (fields written by the `tune.autotuner` `fan_dtype`/`mel_bf16`
    Candidate axes) > f32 defaults.
    """

    fan_dtype: str = "f32"
    mel_bf16: bool = False

    def __post_init__(self):
        if self.fan_dtype not in FAN_DTYPES:
            raise ValueError(
                f"fan_dtype must be one of {FAN_DTYPES}, got {self.fan_dtype!r}")

    def compute_dtype(self):
        """The jnp dtype the fan forward casts to, or None for pure f32.
        The None return is what lets callers skip the shim entirely — an
        f32 policy adds zero ops to the traced graph."""
        if self.fan_dtype == "f32":
            return None
        import jax.numpy as jnp

        if self.fan_dtype == "fp8" and fp8_supported():
            return jnp.float8_e4m3fn
        return jnp.bfloat16

    def tag(self) -> str:
        """Short stable tag for AOT / result-cache keys ("f32", "bf16",
        "bf16+mel", ...). bf16 and f32 executables (and their cached
        results) must never collide on a key."""
        return self.fan_dtype + ("+mel" if self.mel_bf16 else "")


def _validate_fan_dtype(value: str, source: str) -> str:
    if value not in FAN_DTYPES:
        raise ValueError(
            f"{source} must be one of {FAN_DTYPES}, got {value!r}")
    return value


def resolve_precision(workload: str | None = None,
                      shape: tuple | None = None,
                      batch: int | None = None,
                      *,
                      fan_dtype: str | None = None,
                      mel_bf16: bool | None = None) -> PrecisionPolicy:
    """Resolve the precision policy for one workload.

    Explicit args win; then the ``WAM_TPU_FAN_DTYPE`` / ``WAM_TPU_MEL_BF16``
    env knobs (validated at read, like ``WAM_TPU_STFT_IMPL``); then — only
    when a (workload, batch) key is given — the tuned schedule entry's
    ``fan_dtype`` / ``mel_bf16`` fields; then f32. Pass ``workload=None``
    to skip the tuned layer (the plan-fan convention for explicit caps:
    an explicit geometry ignores tuned entries, env knobs still apply).
    """
    import os

    ent = None
    if workload is not None and batch is not None:
        from wam_tpu.tune.cache import lookup_schedule

        ent = lookup_schedule(workload, shape or (batch,), batch)
    if fan_dtype is None:
        env = os.environ.get("WAM_TPU_FAN_DTYPE", "")
        if env:
            fan_dtype = _validate_fan_dtype(env, "WAM_TPU_FAN_DTYPE")
        elif ent and ent.get("fan_dtype"):
            fan_dtype = _validate_fan_dtype(
                str(ent["fan_dtype"]), "tuned fan_dtype")
        else:
            fan_dtype = "f32"
    else:
        fan_dtype = _validate_fan_dtype(fan_dtype, "fan_dtype")
    if mel_bf16 is None:
        env = os.environ.get("WAM_TPU_MEL_BF16", "")
        if env:
            mel_bf16 = env not in ("0", "false", "no")
        elif ent is not None:
            mel_bf16 = bool(ent.get("mel_bf16", False))
        else:
            mel_bf16 = False
    return PrecisionPolicy(fan_dtype=fan_dtype, mel_bf16=bool(mel_bf16))


def precision_tag() -> str:
    """The live process-level precision tag (env knobs only) — folded into
    serve result-cache keys so flipping a knob can never replay a stale
    f32/bf16 result. Read per call, like WAM_TPU_NO_RESULT_CACHE."""
    return resolve_precision().tag()


def compute_cast(x, dtype):
    """Cast an array to a policy compute dtype at a precision boundary;
    ``dtype=None`` (the f32 policy) is the identity. Named so the
    `precision-flow` lint rule can treat its result as low-precision
    tainted even though the dtype is a runtime value."""
    return x if dtype is None else x.astype(dtype)


_probe_result: bool | None = None


def probe_accelerator(timeout_s: float = 180.0, force: bool = False) -> bool:
    """Check in a SUBPROCESS whether the accelerator backend can initialize.

    The axon TPU plugin can block indefinitely inside client creation when
    its pool is unreachable, so a simple try/except in-process would hang;
    a throwaway subprocess with a hard timeout is the only safe probe.
    The answer rarely changes within a process, so it is cached after the
    first call; ``force=True`` re-probes (and refreshes the cache) — the
    serving runtime uses this to distinguish a mid-run device loss from an
    in-process bug before degrading to its CPU fallback entry.
    """
    import subprocess
    import sys

    global _probe_result
    if _probe_result is not None and not force:
        return _probe_result
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        _probe_result = proc.returncode == 0
    except subprocess.TimeoutExpired:
        _probe_result = False
    return _probe_result


def ensure_usable_backend(timeout_s: float = 180.0) -> str:
    """Fall back to CPU (before any backend init) when the accelerator is
    unreachable. Returns the platform that will be used."""
    import os

    import jax

    want = os.environ.get("JAX_PLATFORMS", "")
    if want == "cpu":
        return "cpu"
    if probe_accelerator(timeout_s):
        return want or "auto"
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def select_backend(device: str | None) -> None:
    """Pick the JAX platform ('tpu'/'cpu'/None=auto). Must run before the
    first backend use."""
    import jax

    if device is None or device == "auto":
        return
    platform = {"tpu": "tpu,axon", "axon": "axon", "cpu": "cpu"}.get(device, device)
    jax.config.update("jax_platforms", platform)


def enable_compilation_cache(
    cache_dir: str | None = None, min_compile_time_secs: float | None = None
) -> str:
    """Persist compiled XLA executables across processes.

    First TPU compiles of the full estimator graph run 20-40s; with the
    on-disk cache, repeat runs of the same (shape, J, wavelet, model) config
    deserialize in well under a second. Default location:
    $WAM_TPU_CACHE_DIR or ~/.cache/wam_tpu/xla. Returns the directory used.
    """
    import os

    import jax

    cache_dir = cache_dir or os.environ.get(
        "WAM_TPU_CACHE_DIR", os.path.expanduser("~/.cache/wam_tpu/xla")
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything that took noticeable compile time — but never clobber
    # a threshold the user already configured via env var or jax.config
    # (round-1 ADVICE.md item 4).
    if min_compile_time_secs is not None:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
        )
    elif (
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ
        and jax.config.jax_persistent_cache_min_compile_time_secs == 1.0  # stock default
    ):
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache_dir


@dataclass
class WAM2DConfig:
    wavelet: str = "haar"
    method: str = "smooth"
    J: int = 3
    mode: str = "reflect"
    approx_coeffs: bool = False
    normalize_coeffs: bool = True
    n_samples: int = 25
    stdev_spread: float = 0.25
    random_seed: int = 42
    # "auto" = the benched TPU schedule (~128 rows/step); see
    # WaveletAttribution2D's scheduling docstring
    sample_batch_size: int | None | str = "auto"
    device: str = "auto"


@dataclass
class WAM1DConfig:
    wavelet: str = "haar"
    method: str = "smooth"
    J: int = 3
    mode: str = "reflect"
    approx_coeffs: bool = False
    n_mels: int = 128
    n_fft: int = 1024
    sample_rate: int = 44100
    n_samples: int = 25
    stdev_spread: float = 0.001
    random_seed: int = 42
    sample_batch_size: int | None | str = "auto"
    device: str = "auto"


@dataclass
class WAM3DConfig:
    wavelet: str = "haar"
    method: str = "smooth"
    J: int = 3
    mode: str = "symmetric"
    instance: str = "voxels"
    normalize: bool = True
    EPS: float = 0.451
    n_samples: int = 25
    stdev_spread: float = 1e-4
    random_seed: int = 42
    sample_batch_size: int | None | str = "auto"
    device: str = "auto"


@dataclass
class ServeConfig:
    """Knobs of `wam_tpu.serve.AttributionServer` / `serve.FleetServer`
    (and the scripts/bench_serve.py load generator). ``buckets`` is the
    admitted item-shape set as a CLI-friendly string: comma-separated, dims
    joined by 'x' — e.g. "3x224x224,3x256x256" for images, "32768,65536"
    for waveforms; "" lets the caller pick programmatically.
    ``fleet`` > 1 serves with one replica worker per chip; ``oversize``
    picks what happens to a whole batch larger than one chip's bucket cap
    ("pjit" = data-parallel over the fleet mesh, "fanout" = per-item
    routing). ``max_batch`` accepts "auto" — the tuned per-bucket cap from
    the schedule cache (`tune.resolve_bucket_cap`), falling back to 8."""

    max_batch: int | str = 8  # rows per dispatched batch, or "auto" (tuned)
    max_wait_ms: float = 5.0
    # cross-request admission window (serve/runtime "Coalescing"): hold a
    # bucket's dispatch up to this long for batch fill, with deadline-
    # pressure early release. 0 = historical max_wait-only behavior. ON by
    # default for config-built servers — the open-loop round-13 A/B showed
    # it is what amortizes the fixed per-dispatch tunnel cost.
    coalesce_ms: float = 3.0
    # content-addressed result cache budget (serve/result_cache), MB per
    # server (fleet: one shared cache at the admission tier). 0 = off.
    result_cache_mb: float = 64.0
    queue_depth: int = 64
    deadline_ms: float = 0.0  # 0 = no per-request deadline
    buckets: str = ""
    warmup: bool = True
    pipelined: bool = True  # one-in-flight overlapped dispatch (serve/runtime)
    compilation_cache: bool = True
    metrics_path: str = ""
    device: str = "auto"
    fleet: int = 1  # replica workers (one per chip); 1 = single-chip server
    oversize: str = "pjit"  # "pjit" | "fanout" (serve/fleet oversize path)
    # -- health plane (obs.health / obs.memory / obs.slo) -------------------
    health: bool = True  # on-device numeric-health monitors + quarantine
    health_quarantine_n: int = 3  # consecutive non-finite batches -> degraded
    health_recovery_s: float = 30.0  # quarantine probation window
    hbm_budget_mb: float = 0.0  # per-replica HBM budget (MiB); 0 = no limit
    # per-tenant admission quota as a fraction of queue_depth (serve/runtime
    # "Tenant-fair admission"): one tenant may hold at most
    # max(1, queue_depth * tenant_quota) queued requests. 0 = no quota.
    tenant_quota: float = 0.0
    # per-bucket SLOs, e.g. "p99_ms=50,error_rate=0.01,health_rate=0.999"
    # optionally bucket-prefixed: "3x224x224: p99_ms=30; *: p99_ms=80"
    slo: str = ""
    # -- resilience (serve.supervisor / serve.retry) ------------------------
    supervise: bool = True  # restart dead replicas (fleets only)
    restart_max: int = 3  # completed restarts in restart_window_s -> permanent
    restart_window_s: float = 60.0
    restart_backoff_ms: float = 50.0  # base restart backoff (exp, jittered)
    retry_attempts: int = 4  # client-side submit attempts (bench_serve)
    retry_budget_s: float = 30.0  # total per-request retry budget; 0 = none
    # -- cold start (wam_tpu.registry) --------------------------------------
    registry: str = ""  # compile-artifact bundle to hydrate before warmup

    def bucket_shapes(self) -> list[tuple[int, ...]]:
        if not self.buckets:
            return []
        return [
            tuple(int(d) for d in part.strip().split("x"))
            for part in self.buckets.split(",")
            if part.strip()
        ]


@dataclass
class ObsConfig:
    """Knobs of the unified observability layer (`wam_tpu.obs`). Apply
    with ``wam_tpu.obs.configure(cfg)``. ``enabled=False`` turns every
    span/counter call into a near-zero-overhead no-op (the compile
    sentinel keeps counting — trace-time-rare by construction).
    ``prom_port`` is consumed by `serve.FleetServer(prom_port=...)` /
    ``bench_serve --prom-port``: 0 = no endpoint."""

    enabled: bool = True
    ring_size: int = 4096  # span ring capacity (oldest spans drop first)
    prom_port: int = 0  # /metrics HTTP port; 0 = disabled


@dataclass
class EvalConfig:
    n_iter: int = 64
    baseline_n_iter: int = 128
    grid_size: int = 28
    sample_size: int = 128
    subset_size: int = 157
    # "auto" = the tuned eval fan_cap when a schedule entry exists, else 128
    # (wam_tpu.tune.resolve_fan_cap)
    batch_size: int | str = 128
    device: str = "auto"


def _int_or_str(s: str):
    """Converter for `int | None | str` fields (e.g. sample_batch_size:
    4 / "auto"): argparse applies `type` to STRING DEFAULTS too, so a plain
    int converter would crash parse_args() on the "auto" default."""
    try:
        return int(s)
    except ValueError:
        return s


def add_config_args(parser: argparse.ArgumentParser, cfg_cls, prefix: str = "") -> None:
    """Register every dataclass field as a CLI flag (the thin CLI)."""
    for f in fields(cfg_cls):
        name = f"--{prefix}{f.name.replace('_', '-')}"
        if f.type in ("bool", bool):
            parser.add_argument(name, type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=f.default)
        else:
            typ = {int: int, float: float}.get(f.type, str)
            if isinstance(f.type, str):
                parts = {p.strip() for p in f.type.replace("|", " ").split()}
                if "int" in parts and "str" in parts:
                    typ = _int_or_str
                elif "int" in parts:
                    typ = int
                elif "float" in parts:
                    typ = float
                else:
                    typ = str
            default = f.default if f.default is not dataclasses.MISSING else None
            parser.add_argument(name, type=typ, default=default)


def config_from_args(args: argparse.Namespace, cfg_cls, prefix: str = ""):
    kwargs = {}
    for f in fields(cfg_cls):
        key = f"{prefix}{f.name}"
        if hasattr(args, key):
            v = getattr(args, key)
            if v is not None:
                kwargs[f.name] = v
    return cfg_cls(**kwargs)
