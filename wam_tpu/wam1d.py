"""WAM-1D: audio/waveform attribution in the wavelet domain (TPU-native).

Capability parity with `lib/wam_1D.py` (BaseWAM1D / WaveletAttribution1D /
VisualizerWAM1D): the differentiable chain is

    waveform → DWT → IDWT → mel-spectrogram → CNN → diag-logit loss

with gradients harvested at TWO taps — the wavelet coefficients and the
melspec pixels (`lib/wam_1D.py:117-150`) — here obtained from a single
backward pass via the engine's zero-tap trick instead of retain_grad.

Outputs follow the reference layout: melspec gradients (N, T, n_mels) and a
coefficient-gradient list [cA_J, cD_J, ..., cD_1].
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from wam_tpu.core.engine import WamEngine
from wam_tpu.core.estimators import (
    resolve_sample_chunk,
    smoothgrad,
    trapezoid,
    validate_sample_batch_size,
)
from wam_tpu.ops.melspec import melspectrogram, mel_to_stft_magnitude, stft_power
from wam_tpu.wavelets import wavedec, waverec

__all__ = [
    "normalize_waveforms",
    "BaseWAM1D",
    "WaveletAttribution1D",
    "VisualizerWAM1D",
    "scaleogram",
]


def normalize_waveforms(x) -> jnp.ndarray:
    """List of (possibly int16) waveforms → (N, W) float32, each divided by
    its max (`lib/wam_1D.py:105-106`)."""
    if isinstance(x, (list, tuple)):
        x = np.stack([np.asarray(wf) / np.asarray(wf).max() for wf in x])
    return jnp.asarray(x, dtype=jnp.float32)


def scaleogram(coeff_grads: Sequence, J: int) -> np.ndarray:
    """Pseudo-scaleogram (B, J+1, maxlen), NaN-padded: row 0 = normalized
    |approx| grads, row j+1 = level-j details, coarsest first
    (`lib/wam_1D.py:152-192`). Host-side viz helper."""
    arrs = [np.asarray(c) for c in coeff_grads]
    batch = arrs[0].shape[0]
    maxlen = arrs[-1].shape[-1]
    out = np.full((batch, J + 1, maxlen), np.nan)
    for i in range(batch):
        for j, level in enumerate(arrs):
            a = np.abs(level[i])
            m = a.max()
            out[i, j, : a.shape[-1]] = a / (m if m > 0 else 1.0)
    return out


class BaseWAM1D:
    """Single-pass WAM-1D (`lib/wam_1D.py:54-150`).

    ``model_fn`` maps melspec batches (N, 1, T, n_mels) to logits; the mel
    front-end is built in (differentiable, wam_tpu.ops.melspec).
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        wavelet: str = "haar",
        J: int = 2,
        mode: str = "symmetric",
        approx_coeffs: bool = False,
        n_mels: int = 128,
        n_fft: int = 1024,
        sample_rate: int = 44100,
    ):
        self.wavelet = wavelet
        self.J = J
        self.mode = mode
        self.approx_coeffs = approx_coeffs
        self.n_mels = n_mels
        self.n_fft = n_fft
        self.sample_rate = sample_rate

        def front(wave):  # (N, W) -> (N, 1, T, n_mels)
            mel = melspectrogram(wave, sample_rate=sample_rate, n_fft=n_fft, n_mels=n_mels)
            return mel[:, None, :, :]

        self.engine = WamEngine(
            model_fn, ndim=1, wavelet=wavelet, level=J, mode=mode, front_fn=front
        )

    def compute_melspec(self, wave: jax.Array) -> jax.Array:
        """(N, W) → (N, 1, T, n_mels) in dB (lib/wam_1D.py:194-219)."""
        mel = melspectrogram(
            wave, sample_rate=self.sample_rate, n_fft=self.n_fft, n_mels=self.n_mels
        )
        return mel[:, None, :, :]

    def __call__(self, x, y, waveform: bool = True):
        """Returns (melspec gradients (N, T, n_mels), coefficient-gradient
        list). ``waveform=False`` passes a coefficient pytree directly, the
        IG path's entry point (`lib/wam_1D.py:111-112`)."""
        if waveform:
            x = normalize_waveforms(x)
            coeffs = self.engine.decompose(x)
            length = x.shape[-1]
        else:
            coeffs = x
            length = waverec(coeffs, self.wavelet).shape[-1]
        y = jnp.asarray(y)

        def loss(cs, tap):
            wave = self.engine.reconstruct(cs, (length,))
            mel = self.engine.front_fn(wave) + tap
            out = self.engine.model_fn(mel)
            picked = jnp.take_along_axis(out, y[:, None], axis=1)[:, 0]
            return picked.mean()

        mel_shape = jax.eval_shape(
            lambda cs: self.engine.front_fn(self.engine.reconstruct(cs, (length,))), coeffs
        )
        g_coeffs, g_mel = jax.grad(loss, argnums=(0, 1))(
            coeffs, jnp.zeros(mel_shape.shape, mel_shape.dtype)
        )
        self.wavelet_coeffs = coeffs
        self.gradient_coeffs = g_coeffs
        return g_mel[:, 0, :, :], g_coeffs

    def visualize_grad_wam(self, coeff_grads):
        return scaleogram(coeff_grads, self.J)

    def filter(self, EPS: float):
        """Hard-threshold reconstruction: keep coefficients whose normalized
        |gradient| exceeds EPS, then inverse transform
        (`lib/wam_1D.py:221-246`)."""
        masks = [
            (jnp.abs(g) / jnp.max(jnp.abs(g)) > EPS).astype(jnp.float32)
            for g in self.gradient_coeffs
        ]
        filtered = [c * m for c, m in zip(self.wavelet_coeffs, masks)]
        return waverec(filtered, self.wavelet)


class WaveletAttribution1D(BaseWAM1D):
    """SmoothGrad / IG WAM-1D (`lib/wam_1D.py:249-435`), one jit graph.

    Long-context mode: pass ``mesh=`` (and optionally ``seq_axis=``) to run
    the WHOLE estimator sequence-sharded — wavedec, waverec, model, grads,
    and the SmoothGrad/IG loops all operate on waveforms whose sample axis
    is sharded over the mesh, so no device ever holds the full signal
    (reference ceiling removed: `lib/wam_1D.py:88-150` back-props through a
    whole in-memory waveform). The model (and the built-in melspec front)
    must be XLA-partitionable over time for the sharding to survive into the
    model; the DWT/IDWT stages are gather-free by construction
    (`parallel.seq_estimators`, audited like tests/test_halo_modes.py).
    SmoothGrad noise is drawn shard-local with the same fold_in key stream
    as ``stream_noise=True`` — per-sample results are bit-identical to the
    single-device estimator; sample means differ only by summation order.
    NOTE: ``stream_noise`` itself is ignored under ``mesh=`` — with the
    default ``stream_noise=False``, adding ``mesh=`` therefore changes the
    (equally valid) noise realization.
    """

    def __init__(
        self,
        model_fn,
        wavelet: str = "haar",
        J: int = 3,
        method: str = "smooth",
        mode: str = "reflect",
        approx_coeffs: bool = False,
        n_mels: int = 128,
        n_fft: int = 1024,
        sample_rate: int = 44100,
        n_samples: int = 25,
        stdev_spread: float = 0.001,
        random_seed: int = 42,
        sample_batch_size: int | None | str = "auto",
        stream_noise: bool = False,
        mesh=None,
        seq_axis: str = "data",
        batch_axis: str | None = None,
        seq_fused: bool | str = "auto",
    ):
        super().__init__(
            model_fn,
            wavelet=wavelet,
            J=J,
            mode=mode,
            approx_coeffs=approx_coeffs,
            n_mels=n_mels,
            n_fft=n_fft,
            sample_rate=sample_rate,
        )
        if method not in ("smooth", "integratedgrad"):
            raise ValueError(f"Unknown method {method!r}")
        validate_sample_batch_size(sample_batch_size)
        self.method = method
        self.n_samples = n_samples
        self.stdev_spread = stdev_spread
        self.random_seed = random_seed
        # "auto" = ~128 model rows per mapped step on TPU, full vmap
        # elsewhere. Round 3's "audio prefers full sample vmap" was a
        # single-min noise artifact: the round-4 median-of-k sweep measured
        # chunk 16 (128 rows at b8) at 77.2 wf/s vs full vmap's 62-67
        # (+24%) — the flagship's 128-row law holds here too (BASELINE.md).
        self.sample_batch_size = sample_batch_size
        # stream_noise: draw SmoothGrad noise inside the sample map instead
        # of materializing the (n_samples, N, W) buffer (different, equally
        # valid draws; see core.estimators.smoothgrad).
        self.stream_noise = stream_noise
        # jit once per instance so repeated calls reuse the compiled graph.
        # Estimator config (n_samples, stdev_spread, ...) is frozen at first
        # trace; build a new instance to change it (constructor-kwargs config
        # surface, SURVEY.md §5.6).
        self._jit_smooth = jax.jit(self._smooth_impl)
        self._jit_ig = jax.jit(self._ig_impl)
        if mesh is None and batch_axis is not None:
            raise ValueError("batch_axis= requires mesh=")
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis
        if mesh is not None:
            from wam_tpu.parallel.seq_estimators import SeqShardedWam

            # the mesh path pins the matmul STFT: the DFT-as-matmul form is
            # GSPMD-partitionable over time (it is also the TPU default),
            # while the fft path is not shardable and trips an XLA CPU
            # fft-thunk layout check on sharded operands
            def seq_front(wave):
                mel = melspectrogram(wave, sample_rate=sample_rate,
                                     n_fft=n_fft, n_mels=n_mels, impl="matmul")
                return mel[:, None, :, :]

            self._seq_front = seq_front
            self._seq = SeqShardedWam(
                mesh,
                self.engine.model_fn,
                ndim=1,
                wavelet=wavelet,
                level=J,
                mode=mode,
                seq_axis=seq_axis,
                front_fn=seq_front,
                front_grads=True,
                batch_axis=batch_axis,
                fused=seq_fused,
            )

    def _resolve_chunk(self, x_shape) -> int | None:
        # tuned schedule-cache entries win over the 128-row law (round-6
        # autotuner; see core.estimators.resolve_sample_chunk)
        return resolve_sample_chunk(
            self.sample_batch_size, x_shape[0], self.n_samples,
            workload="wam1d", shape=tuple(x_shape[1:]),
        )

    def _tap_grads(self, x, y):
        """(mel grads, coeff grads) for one (possibly perturbed) batch."""
        coeffs = self.engine.decompose(x)
        length = x.shape[-1]

        def loss(cs, tap):
            wave = self.engine.reconstruct(cs, (length,))
            mel = self.engine.front_fn(wave) + tap
            out = self.engine.model_fn(mel)
            return jnp.take_along_axis(out, y[:, None], axis=1)[:, 0].mean()

        mel_shape = jax.eval_shape(
            lambda cs: self.engine.front_fn(self.engine.reconstruct(cs, (length,))), coeffs
        )
        g_coeffs, g_mel = jax.grad(loss, argnums=(0, 1))(
            coeffs, jnp.zeros(mel_shape.shape, mel_shape.dtype)
        )
        return g_mel[:, 0, :, :], g_coeffs

    def _smooth_impl(self, x, y, key):
        return smoothgrad(
            lambda noisy: self._tap_grads(noisy, y),
            x,
            key,
            n_samples=self.n_samples,
            stdev_spread=self.stdev_spread,
            batch_size=self._resolve_chunk(x.shape),
            materialize_noise=not self.stream_noise,
        )

    def smooth_wam(self, x, y):
        x = normalize_waveforms(x)
        y = jnp.asarray(y)
        key = jax.random.PRNGKey(self.random_seed)
        if self.mesh is not None:
            # sample_batch_size governs the mesh path too: chunk samples
            # into the batch axis ("auto" = the 128-row law; None = all
            # samples in one dispatch)
            grad_avg, mel_tap = self._seq.smoothgrad(
                x, y, key, n_samples=self.n_samples,
                stdev_spread=self.stdev_spread,
                sample_chunk=self._resolve_chunk(x.shape),
            )
            mel_avg = mel_tap[:, 0, :, :]
        else:
            mel_avg, grad_avg = self._jit_smooth(x, y, key)
        self.melspecs = mel_avg
        self.grad_coeffs = grad_avg
        return mel_avg, grad_avg

    def _ig_impl(self, x, y):
        coeffs = self.engine.decompose(x)
        baseline_mel = self.compute_melspec(x)[:, 0]
        alphas = jnp.linspace(0.0, 1.0, self.n_samples, dtype=x.dtype)

        def one(alpha):
            scaled = jax.tree_util.tree_map(lambda c: c * alpha, coeffs)
            return self._tap_grads_from_coeffs(scaled, y, x.shape[-1])

        path = jax.lax.map(one, alphas, batch_size=self._resolve_chunk(x.shape))
        integ = jax.tree_util.tree_map(trapezoid, path)
        mel_attr = baseline_mel * integ[0]
        coeff_attr = [c * g for c, g in zip(coeffs, integ[1])]
        return mel_attr, coeff_attr

    def integrated_wam(self, x, y):
        """Path integral per tap, each multiplied by its baseline: melspec ×
        ∫ mel-grads, coeffs × ∫ coeff-grads (`lib/wam_1D.py:353-421`)."""
        x = normalize_waveforms(x)
        y = jnp.asarray(y)
        if self.mesh is not None:
            coeffs, (coeff_integ, mel_integ) = self._seq.integrated(
                x, y, n_steps=self.n_samples,
                sample_chunk=self._resolve_chunk(x.shape),
            )
            baseline_mel = self._seq_front(x)[:, 0]
            mel_attr = baseline_mel * mel_integ[:, 0, :, :]
            coeff_attr = [c * g for c, g in zip(coeffs, coeff_integ)]
        else:
            mel_attr, coeff_attr = self._jit_ig(x, y)
        self.melspecs = mel_attr
        self.grad_coeffs = coeff_attr
        return mel_attr, coeff_attr

    def _tap_grads_from_coeffs(self, coeffs, y, length):
        def loss(cs, tap):
            wave = self.engine.reconstruct(cs, (length,))
            mel = self.engine.front_fn(wave) + tap
            out = self.engine.model_fn(mel)
            return jnp.take_along_axis(out, y[:, None], axis=1)[:, 0].mean()

        mel_shape = jax.eval_shape(
            lambda cs: self.engine.front_fn(self.engine.reconstruct(cs, (length,))), coeffs
        )
        g_coeffs, g_mel = jax.grad(loss, argnums=(0, 1))(
            coeffs, jnp.zeros(mel_shape.shape, mel_shape.dtype)
        )
        return g_mel[:, 0, :, :], g_coeffs

    def alter(self, alpha, coeffs):
        return [alpha * c for c in coeffs]

    def __call__(self, x, y):
        if self.method == "smooth":
            return self.smooth_wam(x, y)
        return self.integrated_wam(x, y)

    def serve_entry(self, donate: bool | None = None, on_trace=None,
                    aot_key: str | None = None, with_health: bool = False):
        """Batched serving entry ``(x, y) -> (mel_attr, coeff_attr)`` for the
        `wam_tpu.serve` worker: x is (B, W) float32 waveforms (already
        peak-normalized — the list form of `normalize_waveforms` is a host
        step), y is (B,) int labels. Returns the same pytree as ``__call__``
        minus the instance-attribute stashing (``self.melspecs`` /
        ``self.grad_coeffs``) that makes it thread-unsafe; the serve runtime
        distributes rows of every leaf. SmoothGrad folds the instance seed in
        at entry-build time. ``mesh=`` is rejected: the serving worker owns
        exactly one device. ``with_health=True`` fuses the numeric-health
        vector over the result pytree into the same graph
        (`serve.entry.jit_entry`)."""
        if self.mesh is not None:
            raise ValueError(
                "serve_entry() does not support mesh=; the serve worker owns "
                "a single device — drive the sharded estimator directly")
        from wam_tpu.serve.entry import jit_entry

        if self.method == "smooth":
            key = jax.random.PRNGKey(self.random_seed)
            impl = lambda x, y: self._smooth_impl(  # noqa: E731
                jnp.asarray(x, jnp.float32), y, key)
        else:
            impl = lambda x, y: self._ig_impl(  # noqa: E731
                jnp.asarray(x, jnp.float32), y)
        return jit_entry(impl, donate=donate, on_trace=on_trace,
                         aot_key=aot_key, with_health=with_health)


def _minmax_normalize(a):
    lo, hi = np.min(a), np.max(a)
    return (a - lo) / (hi - lo if hi > lo else 1.0)


class VisualizerWAM1D(WaveletAttribution1D):
    """Spectrogram-domain filtering/visualization (`lib/wam_1D.py:451-643`).

    Host-side (numpy) post-processing of attribution outputs: melspec
    filtering (ht / modulation), wavelet-domain filtering (ht / st /
    modulation), and spectrogram rendering. The mel→STFT inversion uses a
    pinv projection (librosa's NNLS equivalent role, viz-only).
    """

    def __init__(self, model_fn, x, **kwargs):
        super().__init__(model_fn, **kwargs)
        self.x = x
        self.source_spectrograms = None

    def compute_melspec_power(self, x) -> np.ndarray:
        """Power-scale melspec (no dB), (N, n_mels, T) mel-major like the
        reference's viz layout (`lib/wam_1D.py:457-476`)."""
        wave = normalize_waveforms(x)
        mel = melspectrogram(
            wave, sample_rate=self.sample_rate, n_fft=self.n_fft, n_mels=self.n_mels, to_db=False
        )
        return np.transpose(np.asarray(mel), (0, 2, 1))

    def compute_spectrogram(self, melspecs: np.ndarray) -> np.ndarray:
        """Approximate STFT magnitudes from mel-power spectrograms."""
        out = [
            mel_to_stft_magnitude(m.T, self.sample_rate, self.n_fft, self.n_mels).T
            for m in melspecs
        ]
        return np.asarray(out)

    def filter_melspec(self, audio_melspecs, grad_melspecs, filtering_method, EPS=0.2):
        """ht: binary mask of min-max-normalized grads > EPS; modulation:
        melspec × |grads| (`lib/wam_1D.py:490-520`)."""
        grads = np.transpose(np.asarray(grad_melspecs), (0, 2, 1))
        if filtering_method == "ht":
            mask = (_minmax_normalize(grads) > EPS).astype(audio_melspecs.dtype)
            return audio_melspecs * mask
        if filtering_method == "modulation":
            return audio_melspecs * np.abs(grads)
        raise ValueError(f"Unknown filtering method {filtering_method!r}")

    def spectrogram_from_waveform(self, waveform) -> np.ndarray:
        """|STFT| with hop n_fft//4 (`lib/wam_1D.py:522-530`), freq-major."""
        wave = normalize_waveforms(waveform)
        p = stft_power(wave, n_fft=self.n_fft, hop=self.n_fft // 4)
        return np.sqrt(np.asarray(p)).transpose(0, 2, 1)

    def filter_from_wavelet_coefficients(self, coefficients, gradients, filtering_method="ht", EPS=0.2):
        """Wavelet-domain filtering then inverse transform
        (`lib/wam_1D.py:532-587`): ht = binary mask on normalized |grads|;
        st = soft shrinkage of normalized coeff·grad; modulation =
        coeff × |grad| re-weighted by per-scale importance shares."""
        coefficients = [np.asarray(c) for c in coefficients]
        gradients = [np.asarray(g) for g in gradients]
        if filtering_method == "ht":
            masks = [
                (np.abs(g) / np.max(np.abs(g)) > EPS).astype(np.float32) for g in gradients
            ]
            filtered = [c * m for c, m in zip(coefficients, masks)]
        elif filtering_method == "st":
            masks = [
                np.maximum(_minmax_normalize(c * g) - EPS, 0.0)
                for c, g in zip(coefficients, gradients)
            ]
            filtered = [c * m for c, m in zip(coefficients, masks)]
        elif filtering_method == "modulation":
            # per-scale importance share: sum of grads per level, normalized
            # over levels for each batch element
            importances = np.stack([g.sum(axis=-1) for g in gradients])  # (L, B)
            shares = importances / np.maximum(importances.sum(axis=0, keepdims=True), 1e-12)
            modulated = [c * np.abs(g) for c, g in zip(coefficients, gradients)]
            filtered = [m * shares[i][:, None] for i, m in enumerate(modulated)]
        else:
            raise ValueError(f"Unknown filtering method {filtering_method!r}")
        rec = waverec([jnp.asarray(c, dtype=jnp.float32) for c in filtered], self.wavelet)
        return np.asarray(rec)

    def filtered_spectrogram_from_wavelet_coefficients(self, grad_coeffs, filtering_method, EPS=0.2):
        wave = normalize_waveforms(self.x)
        self.source_spectrograms = self.spectrogram_from_waveform(wave)
        coeffs = wavedec(wave, self.wavelet, level=self.J, mode=self.mode)
        filtered = self.filter_from_wavelet_coefficients(
            coeffs, grad_coeffs, filtering_method=filtering_method, EPS=EPS
        )
        return self.source_spectrograms, self.spectrogram_from_waveform(filtered)

    def filtered_spectrogram_from_melspec(self, grad_melspecs, filtering_method, EPS=0.2):
        audio_melspecs = self.compute_melspec_power(self.x)
        self.source_spectrograms = self.compute_spectrogram(audio_melspecs)
        filtered = self.filter_melspec(audio_melspecs, grad_melspecs, filtering_method, EPS=EPS)
        return self.source_spectrograms, self.compute_spectrogram(filtered)
