"""Flax Vision Transformer (ViT-B/16 family) — the `BASELINE.json` IG
workload model ("wam_2D: ViT-B/16 ImageNet, Integrated-Gradients-in-wavelet")
and a timm-zoo counterpart (`src/helpers.py:468-479`).

Pre-norm encoder, learned position embeddings, class token. Sizes are
constructor fields so tests can instantiate tiny variants.

``capture_attn=True`` swaps the attention body for an intermediate-capturing
variant (`capturing_attention`): per-block softmax weights are sown into the
'intermediates' collection and tapped with a zero `perturb` for gradient
capture — the transformer-native baselines (attention rollout, grad⊙attn)
read both (`wam_tpu.xattr.attention`). The flag changes NO parameters and,
when off, NO code path: the encoder calls the stock
`nn.MultiHeadDotProductAttention` body exactly as before, so checkpoints
ingest identically and logits are bit-equal (tests/test_xattr.py parity).
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from wam_tpu.models.patchconv import PatchConv

__all__ = ["ViT", "bind_vit_inference", "capturing_attention", "vit_b16",
           "vit_tiny_test"]


def capturing_attention(query, key, value, dtype=None, precision=None,
                        module=None):
    """Drop-in `attention_fn` for `nn.MultiHeadDotProductAttention` that
    exposes the softmax weights twice: sown into
    ('intermediates', 'attention_weights') for the forward-only readers
    (attention rollout), and routed through a zero `perturb` tap named
    'attention_weights' so ∂logit/∂A materializes under a 'perturbations'
    collection (grad⊙attn — the JAX analogue of Chefer et al.'s backward
    hooks). Numerically identical to the stock path: the weights come from
    flax's own `dot_product_attention_weights` and the value contraction is
    the stock einsum, and both sow and perturb are identity when their
    collections are absent."""
    weights = nn.dot_product_attention_weights(
        query, key, dtype=dtype, precision=precision
    )
    module.sow("intermediates", "attention_weights", weights)
    # Tap only when the tap can exist: materialization passes (mutable
    # 'perturbations') and gradient passes (tap variable supplied). A plain
    # apply with init-time variables carries the ViT's 'tokens' tap but not
    # these — `perturb` would raise on the missing name, so skip (identical
    # forward either way; the tap adds zero).
    if module.is_mutable_collection("perturbations") or module.scope.has_variable(
        "perturbations", "attention_weights"
    ):
        weights = module.perturb("attention_weights", weights)
    return jnp.einsum("...hqk,...khd->...qhd", weights, value,
                      precision=precision)


class MlpBlock(nn.Module):
    hidden: int

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        # exact (erf) GELU — what timm/torchvision ViTs use; the tanh
        # approximation breaks checkpoint logit parity
        x = nn.gelu(nn.Dense(self.hidden, name="fc1")(x), approximate=False)
        return nn.Dense(d, name="fc2")(x)


class EncoderBlock(nn.Module):
    heads: int
    mlp_hidden: int
    capture_attn: bool = False

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(name="ln1")(x)
        # capture on: same params ({query,key,value,out} under 'attn'), same
        # math — only the attention_fn differs, and it sows/taps the weights
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, name="attn",
            **({"attention_fn": capturing_attention} if self.capture_attn
               else {}),
        )
        y = attn(y, y, sow_weights=self.capture_attn)
        x = x + y
        y = nn.LayerNorm(name="ln2")(x)
        return x + MlpBlock(self.mlp_hidden, name="mlp")(y)


class ViT(nn.Module):
    num_classes: int = 1000
    patch: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    mlp_hidden: int = 3072
    capture_attn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: (B, H, W, C) NHWC → logits (B, num_classes)."""
        B = x.shape[0]
        # Patch embedding as extract-patches+matmul (same {kernel, bias}
        # params as the conv form; see models/patchconv.py for why — the
        # conv form's input gradient is pathologically slow on TPU).
        x = PatchConv(self.dim, self.patch, name="patch_embed")(x)
        x = x.reshape(B, -1, self.dim)
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.dim))
        x = jnp.concatenate([jnp.tile(cls, (B, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.dim)
        )
        x = x + pos
        for i in range(self.depth):
            x = EncoderBlock(self.heads, self.mlp_hidden,
                             capture_attn=self.capture_attn,
                             name=f"block{i}")(x)
        self.sow("intermediates", "tokens", x)
        # Gradient tap for the GradCAM-family baselines (token-grid CAM):
        # no-op unless a 'perturbations' collection is passed
        # (wam_tpu.evalsuite.baselines._acts_and_grads).
        x = self.perturb("tokens", x)
        x = nn.LayerNorm(name="ln")(x)
        return nn.Dense(self.num_classes, name="head")(x[:, 0])


vit_b16 = partial(ViT, patch=16, dim=768, depth=12, heads=12, mlp_hidden=3072)
vit_tiny_test = partial(ViT, patch=8, dim=64, depth=2, heads=4, mlp_hidden=128)


def bind_vit_inference(model: ViT, variables, nchw: bool = False,
                       compute_dtype=None):
    """Bind ViT params into a pure ``x -> logits`` function — the
    transformer twin of `models.resnet.bind_inference`'s casting shim.

    compute_dtype (jnp dtype or the policy strings "bf16"/"fp8", resolved
    through `config.PrecisionPolicy` — fp8 degrades to bf16 off-backend):
    float params cast ONCE here, input cast at the model boundary, logits
    back to f32, so attention softmax statistics and downstream metric
    reductions see f32 logits. The init-time 'perturbations' collection
    (the ViT's gradient taps) is dropped like the evaluators do — it is
    an artifact of init, not a parameter."""
    import jax

    base = {k: v for k, v in variables.items() if k != "perturbations"}
    if isinstance(compute_dtype, str):
        from wam_tpu.config import PrecisionPolicy

        compute_dtype = PrecisionPolicy(fan_dtype=compute_dtype).compute_dtype()
    if compute_dtype is not None:
        base = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a,
            base,
        )

    def fn(x):
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        if compute_dtype is not None:
            return model.apply(base, x.astype(compute_dtype)).astype(jnp.float32)
        return model.apply(base, x)

    return fn
