"""Flax Vision Transformer (ViT-B/16 family) — the `BASELINE.json` IG
workload model ("wam_2D: ViT-B/16 ImageNet, Integrated-Gradients-in-wavelet")
and a timm-zoo counterpart (`src/helpers.py:468-479`).

Pre-norm encoder, learned position embeddings, class token. Sizes are
constructor fields so tests can instantiate tiny variants.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from wam_tpu.models.patchconv import PatchConv

__all__ = ["ViT", "vit_b16", "vit_tiny_test"]


class MlpBlock(nn.Module):
    hidden: int

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        # exact (erf) GELU — what timm/torchvision ViTs use; the tanh
        # approximation breaks checkpoint logit parity
        x = nn.gelu(nn.Dense(self.hidden, name="fc1")(x), approximate=False)
        return nn.Dense(d, name="fc2")(x)


class EncoderBlock(nn.Module):
    heads: int
    mlp_hidden: int

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(name="ln1")(x)
        y = nn.MultiHeadDotProductAttention(num_heads=self.heads, name="attn")(y, y)
        x = x + y
        y = nn.LayerNorm(name="ln2")(x)
        return x + MlpBlock(self.mlp_hidden, name="mlp")(y)


class ViT(nn.Module):
    num_classes: int = 1000
    patch: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    mlp_hidden: int = 3072

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: (B, H, W, C) NHWC → logits (B, num_classes)."""
        B = x.shape[0]
        # Patch embedding as extract-patches+matmul (same {kernel, bias}
        # params as the conv form; see models/patchconv.py for why — the
        # conv form's input gradient is pathologically slow on TPU).
        x = PatchConv(self.dim, self.patch, name="patch_embed")(x)
        x = x.reshape(B, -1, self.dim)
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.dim))
        x = jnp.concatenate([jnp.tile(cls, (B, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.dim)
        )
        x = x + pos
        for i in range(self.depth):
            x = EncoderBlock(self.heads, self.mlp_hidden, name=f"block{i}")(x)
        self.sow("intermediates", "tokens", x)
        # Gradient tap for the GradCAM-family baselines (token-grid CAM):
        # no-op unless a 'perturbations' collection is passed
        # (wam_tpu.evalsuite.baselines._acts_and_grads).
        x = self.perturb("tokens", x)
        x = nn.LayerNorm(name="ln")(x)
        return nn.Dense(self.num_classes, name="head")(x[:, 0])


vit_b16 = partial(ViT, patch=16, dim=768, depth=12, heads=12, mlp_hidden=3072)
vit_tiny_test = partial(ViT, patch=8, dim=64, depth=2, heads=4, mlp_hidden=128)
