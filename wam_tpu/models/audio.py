"""Flax audio CNN — capability equivalent of the reference's VGG-style
`weak_mxh64_1024` (`src/network_architectures.py:219-272`): 3×3 conv-BN-ReLU
pairs with 2×2 maxpools, a 2×2 conv to 1024 channels, a 1×1 sigmoid head,
global pooling; exposes the four intermediate activation taps (out0..out3)
via `sow` for the GradCAM-family baselines.

Input layout: melspec batches (B, 1, T, n_mels) (reference `src/dataloader.py`
`[1, T, 128]` items) — converted to NHWC internally.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["AudioCNN", "bind_audio_inference", "toy_wave_model"]


class AudioCNN(nn.Module):
    num_classes: int = 50
    pool: str = "max"  # reference passes F.max_pool2d / F.avg_pool2d as glplfn

    @nn.compact
    def __call__(self, x, train: bool = False):
        # (B, 1, T, M) -> NHWC
        x = jnp.transpose(x, (0, 2, 3, 1))
        norm = partial(nn.BatchNorm, use_running_average=not train)

        def block(z, feats, name):
            z = nn.Conv(feats, (3, 3), padding=1, name=f"{name}_conv")(z)
            z = norm(name=f"{name}_bn")(z)
            return nn.relu(z)

        x = block(x, 16, "b1")
        x = block(x, 16, "b2")
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = block(x, 32, "b3")
        x = block(x, 32, "b4")
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = block(x, 64, "b5")
        x = block(x, 64, "b6")
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = block(x, 128, "b7")
        out0 = block(x, 128, "b8")
        self.sow("intermediates", "out0", out0)
        out0 = self.perturb("out0", out0)
        x = nn.max_pool(out0, (2, 2), (2, 2))
        x = block(x, 256, "b9")
        out1 = block(x, 256, "b10")
        self.sow("intermediates", "out1", out1)
        out1 = self.perturb("out1", out1)
        x = nn.max_pool(out1, (2, 2), (2, 2))
        out2 = block(x, 512, "b11")
        self.sow("intermediates", "out2", out2)
        out2 = self.perturb("out2", out2)
        x = nn.max_pool(out2, (2, 2), (2, 2))
        out3 = nn.relu(norm(name="b12_bn")(nn.Conv(1024, (2, 2), padding="VALID", name="b12_conv")(x)))
        self.sow("intermediates", "out3", out3)
        out3 = self.perturb("out3", out3)
        x = nn.sigmoid(nn.Conv(self.num_classes, (1, 1), name="head")(out3))
        if self.pool == "max":
            x = x.max(axis=(1, 2))
        else:
            x = x.mean(axis=(1, 2))
        return x


def bind_audio_inference(model: nn.Module, variables,
                         compute_dtype=None,
                         fold_bn: bool = False) -> Callable[[jax.Array], jax.Array]:
    """Pure `(B, 1, T, M) -> (B, K)` function (the FtEx-wrapper role,
    `src/helpers.py:289-325`).

    compute_dtype=jnp.bfloat16 runs the CNN fwd/VJP at the MXU's native
    precision (params cast once, melspec input cast at the boundary,
    logits back in f32) — the round-4 audio trace showed the conv stack
    running f32 activations at ~45% of the attribution step
    (BASELINE.md round-4 audio breakdown).

    fold_bn=True folds the inference-mode BatchNorms into the conv kernels
    (value-preserving; `resnet._fold_bn_variables` matches the b{N}_bn ↔
    b{N}_conv naming) — one fewer full-tensor multiply per BN site in the
    VJP, same role as the vision flagship's fold_bn."""
    if fold_bn:
        from wam_tpu.models.resnet import _fold_bn_variables

        variables = _fold_bn_variables(variables)
    if isinstance(compute_dtype, str):
        # policy string form ("bf16"/"fp8") — same resolution as
        # resnet.bind_inference / vit.bind_vit_inference
        from wam_tpu.config import PrecisionPolicy

        compute_dtype = PrecisionPolicy(fan_dtype=compute_dtype).compute_dtype()
    if compute_dtype is not None:
        variables = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            variables,
        )
        return lambda x: model.apply(
            variables, x.astype(compute_dtype)
        ).astype(jnp.float32)
    return lambda x: model.apply(variables, x)


def toy_wave_model(key=None, classes: int = 4, taps: int = 9):
    """Tiny sequence-partitionable waveform classifier, (B, N) ->
    (B, classes): the 1D instance of `wam_tpu.models.toy.toy_conv_model`
    (see there for the demo/dry-run rationale)."""
    from wam_tpu.models.toy import toy_conv_model

    return toy_conv_model(key, ndim=1, classes=classes, taps=taps)
