"""Flax 3D ResNet — volumetric classifier for the BASELINE.json
"wam_3D: 3D-ResNet on MRI/ShapeNet volumes" benchmark config. The reference
model zoo has no 3D ResNet (its volume model is the two-stage `VoxelModel`,
`src/network_architectures.py:190-215`); this fills the canonical-workload
gap with the same structure as `wam_tpu.models.resnet` lifted to 3D convs.

Input layout: (B, 1, D, H, W) like the reference volume tensors; NDHWC
internally for the TPU conv path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet3D", "resnet3d_10", "resnet3d_18"]

ModuleDef = Any


class BasicBlock3D(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        s = (self.strides,) * 3
        y = nn.Conv(self.features, (3, 3, 3), s, padding=1, use_bias=False,
                    name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = nn.Conv(self.features, (3, 3, 3), padding=1, use_bias=False,
                    name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1, 1), s, use_bias=False,
                               name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(y + residual)


class ResNet3D(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 10
    width: int = 16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: (B, 1, D, H, W). Returns logits (B, num_classes)."""
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5)
        x = jnp.transpose(x, (0, 2, 3, 4, 1))  # NDHWC
        x = nn.Conv(self.width, (3, 3, 3), padding=1, use_bias=False,
                    name="conv1")(x)
        x = norm(name="bn1")(x)
        x = self.act(x)
        for stage, n_blocks in enumerate(self.stage_sizes):
            for i in range(n_blocks):
                strides = 2 if stage > 0 and i == 0 else 1
                x = BasicBlock3D(self.width * 2**stage, strides=strides,
                                 norm=norm, act=self.act,
                                 name=f"layer{stage + 1}_{i}")(x)
            self.sow("intermediates", f"stage{stage + 1}", x)
            x = self.perturb(f"stage{stage + 1}", x)
        x = x.mean(axis=(1, 2, 3))
        return nn.Dense(self.num_classes, name="fc")(x)


resnet3d_10 = partial(ResNet3D, stage_sizes=(1, 1, 1, 1))
resnet3d_18 = partial(ResNet3D, stage_sizes=(2, 2, 2, 2))
