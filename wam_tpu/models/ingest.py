"""Checkpoint ingestion: PyTorch state dicts → Flax variable pytrees.

The reference loads torch checkpoints from disk (`src/helpers.py:95,111,283`)
and pretrained models via timm/torchvision (`src/helpers.py:468-479`). This
module maps torchvision-style ResNet state dicts into the
`wam_tpu.models.resnet` variable tree, handling:

- conv weights (O, I, kh, kw) → (kh, kw, I, O)
- linear weights (out, in) → kernel (in, out)
- batchnorm weight/bias/running_mean/running_var → scale/bias + batch_stats
- DataParallel "module."-prefix stripping (`src/helpers.py:315-325`)

Pure numpy — no torch import needed at runtime; any mapping of
name → array-like works (a torch state_dict, an npz, ...).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

__all__ = ["strip_module_prefix", "torch_resnet_to_flax"]


def strip_module_prefix(state: Mapping[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Remove the 'module.' prefix DataParallel training leaves on keys."""
    return {k.removeprefix("module."): v for k, v in state.items()}


def _np(v) -> np.ndarray:
    # torch tensors expose .detach().cpu().numpy(); arrays pass through.
    if hasattr(v, "detach"):
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def _conv(w) -> np.ndarray:
    return _np(w).transpose(2, 3, 1, 0)


def torch_resnet_to_flax(state: Mapping[str, np.ndarray]) -> dict:
    """Convert a torchvision ResNet state dict to this package's
    {'params': ..., 'batch_stats': ...} tree."""
    state = strip_module_prefix(state)
    params: dict = {}
    stats: dict = {}

    def put(tree: dict, path: tuple[str, ...], value: np.ndarray):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = value

    def take_bn(prefix: str, flax_name: tuple[str, ...]):
        put(params, flax_name + ("scale",), _np(state[prefix + ".weight"]))
        put(params, flax_name + ("bias",), _np(state[prefix + ".bias"]))
        put(stats, flax_name + ("mean",), _np(state[prefix + ".running_mean"]))
        put(stats, flax_name + ("var",), _np(state[prefix + ".running_var"]))

    put(params, ("conv1", "kernel"), _conv(state["conv1.weight"]))
    take_bn("bn1", ("bn1",))

    for key in state:
        parts = key.split(".")
        if parts[0].startswith("layer") and parts[-1] == "weight" and parts[2].startswith("conv"):
            stage, idx, conv = parts[0], parts[1], parts[2]
            block = f"{stage}_{idx}"
            put(params, (block, conv, "kernel"), _conv(state[key]))
            take_bn(f"{stage}.{idx}.bn{conv[-1]}", (block, f"bn{conv[-1]}"))
        elif parts[0].startswith("layer") and "downsample" in key and key.endswith("0.weight"):
            stage, idx = parts[0], parts[1]
            block = f"{stage}_{idx}"
            put(params, (block, "downsample_conv", "kernel"), _conv(state[key]))
            take_bn(f"{stage}.{idx}.downsample.1", (block, "downsample_bn"))

    put(params, ("fc", "kernel"), _np(state["fc.weight"]).T)
    put(params, ("fc", "bias"), _np(state["fc.bias"]))
    return {"params": params, "batch_stats": stats}
