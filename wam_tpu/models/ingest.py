"""Checkpoint ingestion: PyTorch state dicts → Flax variable pytrees.

The reference loads torch checkpoints from disk (`src/helpers.py:95,111,283`)
and pretrained models via timm/torchvision (`src/helpers.py:468-479`). This
module maps torchvision-style ResNet state dicts into the
`wam_tpu.models.resnet` variable tree, handling:

- conv weights (O, I, kh, kw) → (kh, kw, I, O)
- linear weights (out, in) → kernel (in, out)
- batchnorm weight/bias/running_mean/running_var → scale/bias + batch_stats
- DataParallel "module."-prefix stripping (`src/helpers.py:315-325`)

Pure numpy — no torch import needed at runtime; any mapping of
name → array-like works (a torch state_dict, an npz, ...).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

__all__ = [
    "strip_module_prefix",
    "torch_resnet_to_flax",
    "torch_vit_to_flax",
    "torch_convnext_to_flax",
]


def strip_module_prefix(state: Mapping[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Remove the 'module.' prefix DataParallel training leaves on keys."""
    return {k.removeprefix("module."): v for k, v in state.items()}


def _np(v) -> np.ndarray:
    # torch tensors expose .detach().cpu().numpy(); arrays pass through.
    if hasattr(v, "detach"):
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def _conv(w) -> np.ndarray:
    return _np(w).transpose(2, 3, 1, 0)


def _put(tree: dict, path: tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def _ln(state: Mapping[str, np.ndarray], tree: dict, prefix: str, path: tuple[str, ...]) -> None:
    """LayerNorm weight/bias -> flax scale/bias at `path`."""
    _put(tree, path + ("scale",), _np(state[prefix + ".weight"]))
    _put(tree, path + ("bias",), _np(state[prefix + ".bias"]))


def torch_resnet_to_flax(state: Mapping[str, np.ndarray]) -> dict:
    """Convert a torchvision ResNet state dict to this package's
    {'params': ..., 'batch_stats': ...} tree."""
    state = strip_module_prefix(state)
    params: dict = {}
    stats: dict = {}

    put = _put

    def take_bn(prefix: str, flax_name: tuple[str, ...]):
        put(params, flax_name + ("scale",), _np(state[prefix + ".weight"]))
        put(params, flax_name + ("bias",), _np(state[prefix + ".bias"]))
        put(stats, flax_name + ("mean",), _np(state[prefix + ".running_mean"]))
        put(stats, flax_name + ("var",), _np(state[prefix + ".running_var"]))

    put(params, ("conv1", "kernel"), _conv(state["conv1.weight"]))
    take_bn("bn1", ("bn1",))

    for key in state:
        parts = key.split(".")
        if parts[0].startswith("layer") and parts[-1] == "weight" and parts[2].startswith("conv"):
            stage, idx, conv = parts[0], parts[1], parts[2]
            block = f"{stage}_{idx}"
            put(params, (block, conv, "kernel"), _conv(state[key]))
            take_bn(f"{stage}.{idx}.bn{conv[-1]}", (block, f"bn{conv[-1]}"))
        elif parts[0].startswith("layer") and "downsample" in key and key.endswith("0.weight"):
            stage, idx = parts[0], parts[1]
            block = f"{stage}_{idx}"
            put(params, (block, "downsample_conv", "kernel"), _conv(state[key]))
            take_bn(f"{stage}.{idx}.downsample.1", (block, "downsample_bn"))

    put(params, ("fc", "kernel"), _np(state["fc.weight"]).T)
    put(params, ("fc", "bias"), _np(state["fc.bias"]))
    return {"params": params, "batch_stats": stats}


def torch_vit_to_flax(state: Mapping[str, np.ndarray], num_heads: int = 12) -> dict:
    """Convert a timm-style ViT state dict (`vit_base_patch16_224` naming:
    cls_token, pos_embed, patch_embed.proj, blocks.{i}.{norm1,attn.qkv,
    attn.proj,norm2,mlp.fc1,mlp.fc2}, norm, head) to the `wam_tpu.models.vit`
    variable tree. Fused qkv weights are split into flax's per-projection
    (embed, heads, head_dim) kernels."""
    state = strip_module_prefix(state)
    params: dict = {}

    def put(path, value):
        _put(params, path, value)

    def ln(prefix, path):
        _ln(state, params, prefix, path)

    put(("cls_token",), _np(state["cls_token"]))
    put(("pos_embed",), _np(state["pos_embed"]))
    put(("patch_embed", "kernel"), _conv(state["patch_embed.proj.weight"]))
    put(("patch_embed", "bias"), _np(state["patch_embed.proj.bias"]))

    depth = 1 + max(
        int(k.split(".")[1]) for k in state if k.startswith("blocks.")
    )
    for i in range(depth):
        p, b = f"blocks.{i}", f"block{i}"
        ln(f"{p}.norm1", (b, "ln1"))
        ln(f"{p}.norm2", (b, "ln2"))

        qkv_w = _np(state[f"{p}.attn.qkv.weight"])  # (3*dim, dim)
        qkv_b = _np(state[f"{p}.attn.qkv.bias"])
        dim = qkv_w.shape[1]
        head_dim = dim // num_heads
        for j, proj in enumerate(("query", "key", "value")):
            w = qkv_w[j * dim : (j + 1) * dim]  # (dim, dim), row-major out
            put((b, "attn", proj, "kernel"), w.T.reshape(dim, num_heads, head_dim))
            put((b, "attn", proj, "bias"),
                qkv_b[j * dim : (j + 1) * dim].reshape(num_heads, head_dim))
        ow = _np(state[f"{p}.attn.proj.weight"])  # (dim, dim)
        put((b, "attn", "out", "kernel"), ow.T.reshape(num_heads, head_dim, dim))
        put((b, "attn", "out", "bias"), _np(state[f"{p}.attn.proj.bias"]))

        for t, f in (("mlp.fc1", "fc1"), ("mlp.fc2", "fc2")):
            put((b, "mlp", f, "kernel"), _np(state[f"{p}.{t}.weight"]).T)
            put((b, "mlp", f, "bias"), _np(state[f"{p}.{t}.bias"]))

    ln("norm", ("ln",))
    put(("head", "kernel"), _np(state["head.weight"]).T)
    put(("head", "bias"), _np(state["head.bias"]))
    return {"params": params}


def torch_convnext_to_flax(state: Mapping[str, np.ndarray]) -> dict:
    """Convert a torchvision ConvNeXt state dict (`convnext_tiny` naming —
    the fork's IoU-experiment model, `compare_iou_models.ipynb` cell 3:
    features.0 stem, features.{2s} downsample, features.{2s+1} blocks with
    block.{0,2,3,5} + layer_scale, classifier.{0,2}) to the
    `wam_tpu.models.convnext` variable tree."""
    state = strip_module_prefix(state)
    params: dict = {}

    def put(path, value):
        _put(params, path, value)

    def ln(prefix, path):
        _ln(state, params, prefix, path)

    put(("stem_conv", "kernel"), _conv(state["features.0.0.weight"]))
    put(("stem_conv", "bias"), _np(state["features.0.0.bias"]))
    ln("features.0.1", ("stem_ln",))

    n_stages = (
        1 + max(int(k.split(".")[1]) for k in state if k.startswith("features."))
    ) // 2
    for s in range(n_stages):
        if s > 0:
            ln(f"features.{2 * s}.0", (f"down{s}_ln",))
            put((f"down{s}_conv", "kernel"), _conv(state[f"features.{2 * s}.1.weight"]))
            put((f"down{s}_conv", "bias"), _np(state[f"features.{2 * s}.1.bias"]))
        stage_prefix = f"features.{2 * s + 1}"
        depth = 1 + max(
            int(k.split(".")[2]) for k in state if k.startswith(stage_prefix + ".")
        )
        for i in range(depth):
            p, b = f"{stage_prefix}.{i}", f"stage{s}_block{i}"
            # torchvision CNBlock: block.0 dwconv, block.2 LN, block.3 fc1,
            # block.5 fc2, layer_scale (dim,1,1). Depthwise torch weights
            # (dim, 1, kh, kw) transpose to flax grouped-conv (kh, kw, 1, dim).
            put((b, "dwconv", "kernel"), _conv(state[f"{p}.block.0.weight"]))
            put((b, "dwconv", "bias"), _np(state[f"{p}.block.0.bias"]))
            ln(f"{p}.block.2", (b, "ln"))
            put((b, "pw1", "kernel"), _np(state[f"{p}.block.3.weight"]).T)
            put((b, "pw1", "bias"), _np(state[f"{p}.block.3.bias"]))
            put((b, "pw2", "kernel"), _np(state[f"{p}.block.5.weight"]).T)
            put((b, "pw2", "bias"), _np(state[f"{p}.block.5.bias"]))
            put((b, "gamma"), _np(state[f"{p}.layer_scale"]).reshape(-1))

    ln("classifier.0", ("head_ln",))
    put(("head", "kernel"), _np(state["classifier.2.weight"]).T)
    put(("head", "bias"), _np(state["classifier.2.bias"]))
    return {"params": params}
