"""Tiny sequence-partitionable conv classifiers for demos, tests, and the
driver's multi-chip dry-run: one SAME conv + tanh + global mean over the
spatial axes, (B, spatial...) -> (B, classes). Every op is local or a plain
reduction along any spatial axis, so GSPMD shards these over the same mesh
axis as the sharded DWT — the halo/all-reduce pattern a real CNN exhibits,
at a scale that compiles in milliseconds."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["toy_conv_model"]


def toy_conv_model(key=None, ndim: int = 2, classes: int = 4, taps: int = 5):
    """(B, S1..Sn) -> (B, classes); ``ndim`` spatial dims (1=waveform,
    2=single-channel image, 3=volume)."""
    if key is None:
        key = jax.random.PRNGKey(3)
    kern = jax.random.normal(key, (classes, 1) + (taps,) * ndim, jnp.float32) * 0.3
    spatial = "HWD"[:ndim]
    dn = lax.conv_dimension_numbers(
        (1, 1) + (1,) * ndim, (1, 1) + (1,) * ndim,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial),
    )
    pad = [(taps // 2, taps // 2)] * ndim

    def model_fn(x):
        out = lax.conv_general_dilated(
            x[:, None], kern, (1,) * ndim, pad, dimension_numbers=dn
        )
        return jnp.tanh(out).mean(axis=tuple(range(2, 2 + ndim)))

    return model_fn
