"""Flax ResNet family (18/34/50/101) — NHWC, TPU-friendly.

Replaces the reference's timm/torchvision model loading
(`src/helpers.py:468-479`, `wam_example.ipynb` cell 3) with native Flax
modules. Weights can be ingested from torchvision-style PyTorch state dicts
via `wam_tpu.models.ingest.load_torch_resnet` (checkpoint layer,
SURVEY.md §5.4).

Intermediate activations for the GradCAM-family baselines
(`src/evaluation_helpers.py:72-230`) are exposed through `nn.Module.sow`
taps after every stage: apply with ``mutable=["intermediates"]``.

Module naming is deliberately aligned with torchvision's state-dict keys
(conv1, bn1, layer{1..4}.{i}.conv{1..3}/bn{1..3}/downsample, fc) so
checkpoint ingestion is a mechanical rename.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "bind_inference"]

ModuleDef = Any


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides), padding=1,
                    use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = nn.Conv(self.features, (3, 3), padding=1, use_bias=False, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), (self.strides, self.strides),
                               use_bias=False, name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(y + residual)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 4
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides), padding=1,
                    use_bias=False, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = nn.Conv(self.features * self.expansion, (1, 1), use_bias=False, name="conv3")(y)
        y = self.norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * self.expansion, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    # Activation is an attribute so baselines can swap in a modified-backward
    # ReLU (guided backprop) on a clone that reuses the same params.
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: (B, H, W, C) NHWC. Returns logits (B, num_classes)."""
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9, epsilon=1e-5)
        x = nn.Conv(64, (7, 7), (2, 2), padding=3, use_bias=False, name="conv1")(x)
        x = norm(name="bn1")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for i in range(n_blocks):
                strides = 2 if stage > 0 and i == 0 else 1
                x = self.block_cls(64 * 2**stage, strides=strides, norm=norm,
                                   act=self.act, name=f"layer{stage + 1}_{i}")(x)
            self.sow("intermediates", f"stage{stage + 1}", x)
            # Gradient tap for the GradCAM-family baselines: no-op unless a
            # 'perturbations' collection is passed (wam_tpu.evalsuite.baselines).
            x = self.perturb(f"stage{stage + 1}", x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, name="fc")(x)


resnet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
resnet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
resnet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck)
resnet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=Bottleneck)


def bind_inference(
    model: nn.Module,
    variables,
    nchw: bool = True,
    compute_dtype: Any | None = None,
) -> Callable[[jax.Array], jax.Array]:
    """Bind params into a pure `x -> logits` function.

    nchw=True accepts (B, C, H, W) input — the reference's tensor layout
    (`lib/wam_2D.py:79-81`) — and transposes to NHWC for the TPU conv path.

    compute_dtype=jnp.bfloat16 runs the model forward (and hence its VJP) on
    the MXU's native precision: params are cast once here, the input is cast
    at the model boundary, and logits are cast back to float32. The wavelet
    transform outside the model stays float32. Attribution maps agree with
    the float32 path to high cosine similarity because SmoothGrad's noise
    floor (σ = 0.25·range) dominates bf16 rounding.
    """
    if compute_dtype is not None:
        variables = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
            variables,
        )

    def fn(x):
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        if compute_dtype is not None:
            return model.apply(variables, x.astype(compute_dtype)).astype(jnp.float32)
        return model.apply(variables, x)

    return fn
