"""Flax ResNet family (18/34/50/101) — NHWC, TPU-friendly.

Replaces the reference's timm/torchvision model loading
(`src/helpers.py:468-479`, `wam_example.ipynb` cell 3) with native Flax
modules. Weights can be ingested from torchvision-style PyTorch state dicts
via `wam_tpu.models.ingest.load_torch_resnet` (checkpoint layer,
SURVEY.md §5.4).

Intermediate activations for the GradCAM-family baselines
(`src/evaluation_helpers.py:72-230`) are exposed through `nn.Module.sow`
taps after every stage: apply with ``mutable=["intermediates"]``.

Module naming is deliberately aligned with torchvision's state-dict keys
(conv1, bn1, layer{1..4}.{i}.conv{1..3}/bn{1..3}/downsample, fc) so
checkpoint ingestion is a mechanical rename.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "bind_inference"]

ModuleDef = Any


def _identity(z):
    return z


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu
    # Hook applied after every linear(+BN) output — identity by default;
    # LRP swaps in an ε-rule cotangent tap via model.clone (evalsuite).
    post_linear: Callable = _identity

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides), padding=1,
                    use_bias=False, name="conv1")(x)
        y = self.post_linear(self.norm(name="bn1")(y))
        y = self.act(y)
        y = nn.Conv(self.features, (3, 3), padding=1, use_bias=False, name="conv2")(y)
        y = self.post_linear(self.norm(name="bn2")(y))
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), (self.strides, self.strides),
                               use_bias=False, name="downsample_conv")(x)
            residual = self.post_linear(self.norm(name="downsample_bn")(residual))
        return self.act(y + residual)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 4
    act: Callable = nn.relu
    post_linear: Callable = _identity

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = self.post_linear(self.norm(name="bn1")(y))
        y = self.act(y)
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides), padding=1,
                    use_bias=False, name="conv2")(y)
        y = self.post_linear(self.norm(name="bn2")(y))
        y = self.act(y)
        y = nn.Conv(self.features * self.expansion, (1, 1), use_bias=False, name="conv3")(y)
        y = self.post_linear(self.norm(name="bn3")(y))
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * self.expansion, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               name="downsample_conv")(x)
            residual = self.post_linear(self.norm(name="downsample_bn")(residual))
        return self.act(y + residual)


class _StemConv(nn.Module):
    """The ResNet stem conv (7x7/2, pad 3, no bias) with an optional
    space-to-depth execution path.

    The parameter is ALWAYS the standard (7, 7, C, 64) kernel — checkpoint
    ingestion and the torchvision-aligned naming are unchanged. With
    ``s2d=True`` (and even spatial dims) the input is rearranged to
    (H/2, W/2, 4C) and convolved with an equivalent (4, 4, 4C, 64) kernel
    built from the 7x7 weights inside the traced graph (XLA constant-folds
    it). Identical function; the backward then produces the input gradient
    at H/2 resolution with 4x the channels — a far better MXU/bandwidth
    shape than a 3-channel transposed conv at full resolution (the single
    largest op in the round-2 flagship trace). MLPerf-style stem transform.
    """

    s2d: bool = False

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (7, 7, C, 64), jnp.float32
        ).astype(x.dtype)
        dn = ("NHWC", "HWIO", "NHWC")
        if not self.s2d or x.shape[1] % 2 or x.shape[2] % 2:
            return lax.conv_general_dilated(
                x, kernel, (2, 2), [(3, 3), (3, 3)], dimension_numbers=dn
            )
        B, H, W, _ = x.shape
        # out[o] = sum_k w[k] x[2o+k-3]; with x index 2u+a the kernel tap is
        # k = 2(u-o)+a+3, i.e. 4 taps j=u-o+2 in [0,4) and k = 2j+a-1
        # (k=-1 at j=0,a=0 is the zero guard row added by the pad).
        wp = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k2 = wp.reshape(4, 2, 4, 2, C, 64).transpose(0, 2, 1, 3, 4, 5)
        k2 = k2.reshape(4, 4, 4 * C, 64)
        xs = x.reshape(B, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 2, 4, 5)
        xs = xs.reshape(B, H // 2, W // 2, 4 * C)
        return lax.conv_general_dilated(
            xs, k2, (1, 1), [(2, 1), (2, 1)], dimension_numbers=dn
        )


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    # Activation is an attribute so baselines can swap in a modified-backward
    # ReLU (guided backprop) on a clone that reuses the same params.
    act: Callable = nn.relu
    # Space-to-depth stem: same parameters, same function, cheaper input
    # gradient on TPU (see _StemConv).
    # (A Pallas stem-pool backward was evaluated and REMOVED in round 3:
    # measured slower than XLA's own select-and-scatter — BASELINE.md.)
    stem_s2d: bool = False
    # Post-linear hook threaded to every block (see BasicBlock.post_linear).
    post_linear: Callable = _identity

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: (B, H, W, C) NHWC. Returns logits (B, num_classes)."""
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9, epsilon=1e-5)
        x = _StemConv(s2d=self.stem_s2d, name="conv1")(x)
        x = self.post_linear(norm(name="bn1")(x))
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for i in range(n_blocks):
                strides = 2 if stage > 0 and i == 0 else 1
                x = self.block_cls(64 * 2**stage, strides=strides, norm=norm,
                                   act=self.act, post_linear=self.post_linear,
                                   name=f"layer{stage + 1}_{i}")(x)
            self.sow("intermediates", f"stage{stage + 1}", x)
            # Gradient tap for the GradCAM-family baselines: no-op unless a
            # 'perturbations' collection is passed (wam_tpu.evalsuite.baselines).
            x = self.perturb(f"stage{stage + 1}", x)
        x = x.mean(axis=(1, 2))
        return self.post_linear(nn.Dense(self.num_classes, name="fc")(x))


resnet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
resnet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
resnet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck)
resnet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=Bottleneck)


def _fold_bn_variables(variables, eps: float = 1e-5):
    """Fold inference-mode BatchNorm affines into the preceding conv weights.

    BN with running stats is y = x·a + b with a = γ/√(var+ε),
    b = β − mean·a. Scaling the conv kernel's output channels by `a` and
    re-parameterizing the BN to the pure shift (scale=1, bias=b, mean=0,
    var=1−ε so rsqrt(var+ε)=1) produces bit-comparable forwards while
    removing the per-BN elementwise multiply from the VJP — on the
    attribution hot path every cotangent otherwise pays a full-tensor
    multiply per BN site. Pairs are found by this package's naming
    convention (bnN ↔ convN, downsample_bn ↔ downsample_conv); unmatched
    norms are left untouched.
    """
    import numpy as np

    def walk(p_node, s_node):
        for name in list(p_node):
            child = p_node[name]
            if not isinstance(child, dict):
                continue
            if "scale" in child and "bias" in child and name in s_node:
                conv_name = (
                    "downsample_conv" if name == "downsample_bn"
                    else "conv" + name[2:] if name.startswith("bn")
                    # AudioCNN-style naming: b1_bn ↔ b1_conv
                    else name[:-3] + "_conv" if name.endswith("_bn")
                    else None
                )
                if conv_name is None or conv_name not in p_node:
                    continue
                kernel = p_node[conv_name]["kernel"]
                gamma, beta = child["scale"], child["bias"]
                mean, var = s_node[name]["mean"], s_node[name]["var"]
                a = gamma / jnp.sqrt(var + eps)
                folded = dict(p_node[conv_name], kernel=kernel * a)
                if "bias" in folded:
                    # biased convs (e.g. AudioCNN): BN(z + c) = a·z + a·c + …
                    # — the bias must ride the same per-channel scale
                    folded["bias"] = folded["bias"] * a
                p_node[conv_name] = folded
                p_node[name] = dict(child, scale=jnp.ones_like(gamma),
                                    bias=beta - mean * a)
                s_node[name] = dict(s_node[name], mean=jnp.zeros_like(mean),
                                    var=jnp.full_like(var, np.float32(1.0 - eps)))
            elif isinstance(child, dict):
                walk(child, s_node.get(name, {}))

    params = _deep_mutable(variables["params"])
    stats = _deep_mutable(variables.get("batch_stats", {}))
    walk(params, stats)
    out = dict(variables, params=params)
    if stats:
        out["batch_stats"] = stats
    return out


def _deep_mutable(tree):
    if isinstance(tree, dict) or type(tree).__name__ == "FrozenDict":
        return {k: _deep_mutable(v) for k, v in tree.items()}
    return tree


def bind_inference(
    model: nn.Module,
    variables,
    nchw: bool = True,
    compute_dtype: Any | None = None,
    fold_bn: bool = False,
    fused_relu_vjp: bool = False,
) -> Callable[[jax.Array], jax.Array]:
    """Bind params into a pure `x -> logits` function.

    nchw=True accepts (B, C, H, W) input — the reference's tensor layout
    (`lib/wam_2D.py:79-81`) — and transposes to NHWC for the TPU conv path.

    compute_dtype=jnp.bfloat16 runs the model forward (and hence its VJP) on
    the MXU's native precision: params are cast once here, the input is cast
    at the model boundary, and logits are cast back to float32. The wavelet
    transform outside the model stays float32. Attribution maps agree with
    the float32 path to high cosine similarity because SmoothGrad's noise
    floor (σ = 0.25·range) dominates bf16 rounding. The policy strings
    "bf16"/"fp8" are accepted too and resolve through
    `config.PrecisionPolicy` — "fp8" degrades to bf16 when the backend
    fails the `config.fp8_supported` probe, so a tuned schedule carrying
    fp8 still binds everywhere.

    fold_bn=True folds BatchNorm multiplies into conv kernels (see
    `_fold_bn_variables`) — same function, cheaper VJP.

    fused_relu_vjp=True swaps the model's ``act`` for
    `wam_tpu.tune.fused_relu` — a `custom_vjp` ReLU whose residual is a
    bit-packed sign mask (1/32 the bytes of the activation XLA's default
    VJP saves) and whose backward is one masked multiply. Same values, same
    gradients (gate x>0, like `jax.nn.relu`); parameters untouched, so it
    composes with ``fold_bn``/``compute_dtype`` and checkpoint ingestion.
    """
    if fused_relu_vjp:
        if not hasattr(model, "act"):
            raise ValueError(
                "fused_relu_vjp=True requires a model with an `act` attribute "
                f"(got {type(model).__name__})"
            )
        from wam_tpu.tune.fused_relu import fused_relu

        model = model.clone(act=fused_relu)
    if fold_bn:
        variables = _fold_bn_variables(variables)
    if isinstance(compute_dtype, str):
        from wam_tpu.config import PrecisionPolicy

        compute_dtype = PrecisionPolicy(fan_dtype=compute_dtype).compute_dtype()
    if compute_dtype is not None:
        variables = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
            variables,
        )

    def fn(x):
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        if compute_dtype is not None:
            return model.apply(variables, x.astype(compute_dtype)).astype(jnp.float32)
        return model.apply(variables, x)

    return fn
