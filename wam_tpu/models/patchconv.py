"""Non-overlapping (stride == kernel) convolution as extract-patches+matmul.

When a conv's stride equals its kernel size (ViT patch embedding, ConvNeXt
stem and stage downsamplers), the operation is exactly a block reshape
followed by one (p·p·C → features) matmul. The parameters are kept as the
conv's ``{kernel: (p, p, C, features), bias}`` so checkpoint ingestion is
unchanged; only the execution form differs.

Why: XLA lowers the CONV form's input gradient to a stride-p transposed
convolution that is catastrophically slow on TPU — 82 ms per call on v5e
for ViT-B/16's 16×16 embedding, 93% of the whole IG attribution graph
(round-2 trace; the rewrite took the ViT IG workload from 1.37 to 15.1
items/s). The matmul form's VJP is a matmul + free reshape.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["PatchConv"]


class PatchConv(nn.Module):
    """(B, H, W, C) → (B, H//p, W//p, features); VALID semantics (H, W
    remainders cropped, matching Conv(kernel=p, stride=p, VALID))."""

    features: int
    patch: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        p, C = self.patch, x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (p, p, C, self.features),
            jnp.float32,
        )
        B, H, W, _ = x.shape
        if H % p or W % p:
            x = x[:, : H // p * p, : W // p * p]
            H, W = x.shape[1], x.shape[2]
        x = x.reshape(B, H // p, p, W // p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // p, W // p, p * p * C)
        out = x @ kernel.reshape(-1, self.features).astype(x.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
            out = out + bias.astype(x.dtype)
        return out
