from wam_tpu.models.resnet import (
    ResNet,
    bind_inference,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
)
from wam_tpu.models.ingest import strip_module_prefix, torch_resnet_to_flax
from wam_tpu.models.resnet3d import ResNet3D, resnet3d_10, resnet3d_18
from wam_tpu.models.vit import bind_vit_inference

__all__ = [
    "bind_vit_inference",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "ResNet3D",
    "resnet3d_10",
    "resnet3d_18",
    "bind_inference",
    "strip_module_prefix",
    "torch_resnet_to_flax",
]
