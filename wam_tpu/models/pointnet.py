"""Flax PointNet family — parity with `src/network_architectures.py:15-188`
(STN3d / STNkd / PointNetfeat / PointNetCls / PointNetDenseCls +
feature_transform_regularizer).

Point clouds are (B, 3, N) like the reference; internally (B, N, C) so the
1×1 Conv1d stacks become point-shared Dense layers (same math, MXU-friendly
matmuls).
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = [
    "STN",
    "STN3d",
    "STNkd",
    "PointNetFeat",
    "PointNetfeat",
    "PointNetCls",
    "PointNetDenseCls",
    "feature_transform_regularizer",
]


class STN(nn.Module):
    """Spatial transformer: predicts a (k, k) alignment matrix (+identity)."""

    k: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: (B, N, k)
        norm = partial(nn.BatchNorm, use_running_average=not train)
        z = nn.relu(norm(name="bn1")(nn.Dense(64, name="mlp1")(x)))
        z = nn.relu(norm(name="bn2")(nn.Dense(128, name="mlp2")(z)))
        z = nn.relu(norm(name="bn3")(nn.Dense(1024, name="mlp3")(z)))
        z = z.max(axis=1)  # global max pool over points
        z = nn.relu(norm(name="bn4")(nn.Dense(512, name="fc1")(z)))
        z = nn.relu(norm(name="bn5")(nn.Dense(256, name="fc2")(z)))
        z = nn.Dense(self.k * self.k, name="fc3")(z)
        eye = jnp.eye(self.k, dtype=z.dtype).reshape(-1)
        return (z + eye).reshape(-1, self.k, self.k)


class PointNetFeat(nn.Module):
    global_feat: bool = True
    feature_transform: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: (B, 3, N) -> (B, N, 3)
        x = jnp.transpose(x, (0, 2, 1))
        n_pts = x.shape[1]
        norm = partial(nn.BatchNorm, use_running_average=not train)
        trans = STN(k=3, name="stn")(x, train)
        x = jnp.einsum("bnk,bkj->bnj", x, trans)
        x = nn.relu(norm(name="bn1")(nn.Dense(64, name="mlp1")(x)))
        if self.feature_transform:
            trans_feat = STN(k=64, name="fstn")(x, train)
            x = jnp.einsum("bnk,bkj->bnj", x, trans_feat)
        else:
            trans_feat = None
        point_feat = x
        x = nn.relu(norm(name="bn2")(nn.Dense(128, name="mlp2")(x)))
        x = norm(name="bn3")(nn.Dense(1024, name="mlp3")(x))
        x = x.max(axis=1)  # (B, 1024)
        if self.global_feat:
            return x, trans, trans_feat
        tiled = jnp.repeat(x[:, None, :], n_pts, axis=1)
        return jnp.concatenate([tiled, point_feat], axis=-1), trans, trans_feat


class PointNetCls(nn.Module):
    k: int = 2
    feature_transform: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train)
        feat, trans, trans_feat = PointNetFeat(
            global_feat=True, feature_transform=self.feature_transform, name="feat"
        )(x, train)
        z = nn.relu(norm(name="bn1")(nn.Dense(512, name="fc1")(feat)))
        z = nn.Dense(256, name="fc2")(z)
        if train:
            z = nn.Dropout(0.3, deterministic=False)(z)
        z = nn.relu(norm(name="bn2")(z))
        z = nn.Dense(self.k, name="fc3")(z)
        return nn.log_softmax(z, axis=1), trans, trans_feat


class PointNetDenseCls(nn.Module):
    """Per-point segmentation head (`src/network_architectures.py:154-179`)."""

    k: int = 2
    feature_transform: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train)
        feat, trans, trans_feat = PointNetFeat(
            global_feat=False, feature_transform=self.feature_transform, name="feat"
        )(x, train)  # (B, N, 1088)
        z = nn.relu(norm(name="bn1")(nn.Dense(512, name="c1")(feat)))
        z = nn.relu(norm(name="bn2")(nn.Dense(256, name="c2")(z)))
        z = nn.relu(norm(name="bn3")(nn.Dense(128, name="c3")(z)))
        z = nn.Dense(self.k, name="c4")(z)
        return nn.log_softmax(z, axis=-1), trans, trans_feat


def feature_transform_regularizer(trans: jax.Array) -> jax.Array:
    """‖T Tᵀ − I‖ mean over the batch (`src/network_architectures.py:181-188`)."""
    d = trans.shape[1]
    eye = jnp.eye(d, dtype=trans.dtype)
    diff = jnp.einsum("bij,bkj->bik", trans, trans) - eye
    return jnp.linalg.norm(diff, axis=(1, 2)).mean()


# Reference-shaped aliases (`src/network_architectures.py:15-131`) with the
# reference's defaults: STN3d is k=3, STNkd defaults to k=64
# (`src/network_architectures.py:53-54`); PointNetfeat spells feat lowercase.
STN3d = partial(STN, k=3)
STNkd = partial(STN, k=64)
PointNetfeat = PointNetFeat
