"""Flax 3D voxel CNN — parity with the reference's `VoxelModel`
(`src/network_architectures.py:190-215`): two (Conv3d → ReLU → MaxPool3d)
stages then an MLP head, for 16³ voxel grids (3D-MNIST).

Input layout: (B, 1, D, H, W) like the reference; NDHWC internally.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["VoxelModel"]


class VoxelModel(nn.Module):
    num_classes: int = 10
    # swappable so guided backprop can substitute its modified-backward ReLU
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = jnp.transpose(x, (0, 2, 3, 4, 1))  # (B, D, H, W, C)
        x = self.act(nn.Conv(32, (3, 3, 3), padding="VALID", name="conv1")(x))
        x = nn.max_pool(x, (2, 2, 2), (2, 2, 2))
        x = self.act(nn.Conv(128, (3, 3, 3), padding="VALID", name="conv2")(x))
        x = nn.max_pool(x, (2, 2, 2), (2, 2, 2))
        self.sow("intermediates", "features", x)
        x = x.reshape(x.shape[0], -1)
        x = self.act(nn.Dense(256, name="fc1")(x))
        return nn.Dense(self.num_classes, name="fc2")(x)
