"""Flax ConvNeXt (Tiny by default) — the model behind the fork's
cross-wavelet IoU experiment (`compare_iou_models.ipynb` cell 3:
torchvision convnext_tiny).

Standard ConvNeXt recipe: patchify stem (4×4/4 conv + LayerNorm), stages of
(7×7 depthwise conv → LN → 4× pointwise MLP with GELU → layer scale →
residual), LN+2×2/2 downsampling between stages, global-pool LN head.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from wam_tpu.models.patchconv import PatchConv

__all__ = ["ConvNeXt", "convnext_tiny", "convnext_test"]


class ConvNeXtBlock(nn.Module):
    dim: int
    ls_init: float = 1e-6

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.dim, (7, 7), padding=3, feature_group_count=self.dim, name="dwconv")(x)
        y = nn.LayerNorm(name="ln")(y)
        # exact GELU for torchvision checkpoint parity
        y = nn.gelu(nn.Dense(4 * self.dim, name="pw1")(y), approximate=False)
        y = nn.Dense(self.dim, name="pw2")(y)
        gamma = self.param("gamma", nn.initializers.constant(self.ls_init), (self.dim,))
        return x + gamma * y


class ConvNeXt(nn.Module):
    num_classes: int = 1000
    depths: Sequence[int] = (3, 3, 9, 3)
    dims: Sequence[int] = (96, 192, 384, 768)

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: (B, H, W, C) NHWC → logits."""
        # stride==kernel conv as matmul: same {kernel,bias} params, MXU-fast
        # input gradient (see models/patchconv.py)
        x = PatchConv(self.dims[0], 4, name="stem_conv")(x)
        x = nn.LayerNorm(name="stem_ln")(x)
        for stage, (depth, dim) in enumerate(zip(self.depths, self.dims)):
            if stage > 0:
                x = nn.LayerNorm(name=f"down{stage}_ln")(x)
                x = PatchConv(dim, 2, name=f"down{stage}_conv")(x)
            for i in range(depth):
                x = ConvNeXtBlock(dim, name=f"stage{stage}_block{i}")(x)
            self.sow("intermediates", f"stage{stage + 1}", x)
            x = self.perturb(f"stage{stage + 1}", x)
        x = x.mean(axis=(1, 2))
        x = nn.LayerNorm(name="head_ln")(x)
        return nn.Dense(self.num_classes, name="head")(x)


convnext_tiny = partial(ConvNeXt, depths=(3, 3, 9, 3), dims=(96, 192, 384, 768))
convnext_test = partial(ConvNeXt, depths=(1, 1), dims=(16, 32))
