"""Channel-last (NHWC) 2D DWT/IDWT — the flagship layout-seam killer.

The standard `transform.wavedec2` operates on the LAST two axes, so the 2D
engine historically ran NCHW and `bind_inference(nchw=True)` transposed the
reconstruction to NHWC inside every mapped sample-chunk — the
`%copy.179/.184` layout copies in the round-3 op-level audit (BASELINE.md),
~3.5% of the flagship step plus the mirrored cotangent copies on the way
back. Here the analysis/synthesis run directly over axes (-3, -2) of an
NHWC tensor as per-axis banded-matrix contractions (the
`wavelets.matmul` formulation, reused): channels ride along as a trailing
vectorized dim, the model consumes the reconstruction with ZERO layout
conversion, and the coefficient gradients come back NHWC for channel-mean
mosaic packing (`ops.packing2d.mosaic2d(channel_axis=-1)`).

Boundary modes, filters, and values are identical to the NCHW path
(`tests/test_dwt.py::test_nhwc_matches_nchw_*` — same matrices, different
contraction axes). dtype policy is the framework-wide bf16-in /
f32-accumulate: bf16 inputs contract with f32 accumulation
(`preferred_element_type`), coefficients come back float32.

Reference being replaced: the torch NCHW pipeline of `lib/wam_2D.py:96-116`
(ptwt is NCHW-only; TPU convs are NHWC-native, so the layout boundary moves
from per-sample to never).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from wam_tpu.wavelets.matmul import analysis_matrices, synthesis_matrices
from wam_tpu.wavelets.transform import Detail2D, _resolve

__all__ = ["dwt2_nhwc", "idwt2_nhwc", "wavedec2_nhwc", "waverec2_nhwc"]


def _contract_rows(M: jax.Array, x: jax.Array) -> jax.Array:
    """einsum('pH,...HWc->...pWc') with f32 accumulation."""
    return jnp.einsum(
        "pH,...HWc->...pWc", M, x,
        precision=lax.Precision.HIGHEST, preferred_element_type=jnp.float32,
    )


def _contract_cols(x: jax.Array, M: jax.Array) -> jax.Array:
    """einsum('...HWc,qW->...Hqc') with f32 accumulation."""
    return jnp.einsum(
        "...HWc,qW->...Hqc", x, M,
        precision=lax.Precision.HIGHEST, preferred_element_type=jnp.float32,
    )


def dwt2_nhwc(x: jax.Array, wavelet, mode: str = "reflect"):
    """Single-level 2D DWT over axes (-3, -2) of (..., H, W, C).

    Returns (cA, Detail2D), every leaf (..., H', W', C) float32 — the same
    values and subband convention as `transform.dwt2` on the transposed
    input (horizontal = row-detail block, vertical = col-detail block)."""
    wav = _resolve(wavelet)
    h, w = x.shape[-3], x.shape[-2]
    A = analysis_matrices(h, wav, mode, jnp.float32)
    B = analysis_matrices(w, wav, mode, jnp.float32)
    y = _contract_cols(_contract_rows(A, x), B)  # (..., 2h', 2w', C) blocks
    hp, wp = A.shape[0] // 2, B.shape[0] // 2
    aa = y[..., :hp, :wp, :]
    ad = y[..., :hp, wp:, :]
    da = y[..., hp:, :wp, :]
    dd = y[..., hp:, wp:, :]
    return aa, Detail2D(horizontal=da, vertical=ad, diagonal=dd)


def idwt2_nhwc(cA: jax.Array, detail: Detail2D, wavelet, out_shape=None):
    """Inverse of `dwt2_nhwc`: (..., H', W', C) leaves -> (..., H, W, C)."""
    wav = _resolve(wavelet)
    n0, n1 = cA.shape[-3], cA.shape[-2]
    L = wav.filt_len
    target = (2 * n0 - L + 2, 2 * n1 - L + 2) if out_shape is None else tuple(out_shape)
    top = jnp.concatenate([cA, detail.vertical], axis=-2)
    bot = jnp.concatenate([detail.horizontal, detail.diagonal], axis=-2)
    y = jnp.concatenate([top, bot], axis=-3)  # (..., 2h', 2w', C) blocks
    S_r = synthesis_matrices(n0, wav, jnp.float32)
    S_c = synthesis_matrices(n1, wav, jnp.float32)
    out = _contract_cols(_contract_rows(S_r, y), S_c)
    return out[..., : target[0], : target[1], :]


def wavedec2_nhwc(x: jax.Array, wavelet, level: int, mode: str = "reflect"):
    """Multi-level NHWC 2D DWT: [cA_J, Detail2D_J, ..., Detail2D_1], each
    leaf (..., h, w, C) — `transform.wavedec2`'s structure, channel-last."""
    wav = _resolve(wavelet)
    coeffs = []
    a = x
    for _ in range(level):
        a, det = dwt2_nhwc(a, wav, mode)
        coeffs.append(det)
    coeffs.append(a)
    return coeffs[::-1]


def waverec2_nhwc(coeffs, wavelet):
    """Inverse of `wavedec2_nhwc`."""
    wav = _resolve(wavelet)
    a = coeffs[0]
    for det in coeffs[1:]:
        tgt = det.horizontal.shape[-3:-1]
        a = a[..., : tgt[0], : tgt[1], :]
        L = wav.filt_len
        a = idwt2_nhwc(a, det, wav, out_shape=(2 * tgt[0] - L + 2, 2 * tgt[1] - L + 2))
    return a
