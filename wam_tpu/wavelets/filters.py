"""Orthogonal wavelet filter-bank generation.

The reference stack obtains its filters from PyWavelets / ptwt (C/Cython), e.g.
``ptwt.wavedec2(x, "haar", ...)`` at ``lib/wam_2D.py:96`` and the wavelet names
exercised by the reference experiments (haar, db4, db6, db8, sym3, sym4, sym8 —
`compare_iou_models.ipynb` cell 4, `results/plots_mean_grads/*.png`).

Here the filters are *generated* numerically at import time (host-side, float64
numpy) rather than vendored as tables:

- Daubechies (dbN): spectral factorization of the maximally-flat half-band
  product filter — roots of the binomial polynomial P(y), minimum-phase root
  selection (|z| < 1).
- Symlets (symN): same product filter, root assignment chosen per
  conjugate-reciprocal group to minimize phase non-linearity
  (least-asymmetric Daubechies).
- Haar = db1.

Filter layout follows the pywt convention so coefficient semantics match the
reference: ``rec_lo`` is the scaling filter h (sum = sqrt(2)), ``dec_lo`` its
reverse, and the high-pass pair comes from the quadrature-mirror relation
g[k] = (-1)^k h[L-1-k].
"""

from __future__ import annotations

import dataclasses
import functools
import math

import numpy as np

__all__ = ["Wavelet", "build_wavelet", "qmf", "daubechies_scaling", "symlet_scaling"]


@dataclasses.dataclass(frozen=True)
class Wavelet:
    """An orthogonal wavelet filter bank (pywt-compatible layout)."""

    name: str
    dec_lo: np.ndarray  # analysis low-pass (reversed scaling filter)
    dec_hi: np.ndarray  # analysis high-pass
    rec_lo: np.ndarray  # synthesis low-pass (the scaling filter h)
    rec_hi: np.ndarray  # synthesis high-pass

    @property
    def filt_len(self) -> int:
        return len(self.dec_lo)


def qmf(h: np.ndarray) -> np.ndarray:
    """Quadrature-mirror high-pass from scaling filter: g[k] = (-1)^k h[L-1-k]."""
    g = h[::-1].copy()
    g[1::2] = -g[1::2]
    return g


def _binomial_poly(N: int) -> np.ndarray:
    """P(y) = sum_{k=0}^{N-1} C(N-1+k, k) y^k, descending-order coeffs for np.roots."""
    coeffs = [math.comb(N - 1 + k, k) for k in range(N)]
    return np.array(coeffs[::-1], dtype=np.float64)


def _z_roots_of_y(y: complex) -> tuple[complex, complex]:
    """Solve z^2 + (4y - 2) z + 1 = 0, i.e. y = (2 - z - 1/z)/4; roots are reciprocal."""
    b = 4.0 * y - 2.0
    disc = np.sqrt(b * b - 4.0 + 0j)
    z1 = (-b + disc) / 2.0
    z2 = (-b - disc) / 2.0
    return z1, z2


def _poly_from_roots(roots: list[complex]) -> np.ndarray:
    p = np.array([1.0 + 0j])
    for r in roots:
        p = np.convolve(p, np.array([1.0, -r]))
    return p


def _assemble_scaling(N: int, selected_z: list[complex]) -> np.ndarray:
    """h(z) = ((1+z)/2)^N * L(z) with L built from selected roots; normalize sum=sqrt(2)."""
    h = np.array([1.0 + 0j])
    for _ in range(N):
        h = np.convolve(h, np.array([0.5, 0.5]))
    h = np.convolve(h, _poly_from_roots(selected_z))
    h = np.real(h)
    h *= np.sqrt(2.0) / h.sum()
    return h


@functools.lru_cache(maxsize=None)
def daubechies_scaling(N: int) -> np.ndarray:
    """Minimum-phase (standard dbN) scaling filter of length 2N.

    Verified against the closed-form db2 coefficients
    ((1±sqrt(3))/(4 sqrt(2)) family) in tests/test_filters.py.
    """
    if N < 1:
        raise ValueError("Daubechies order must be >= 1")
    if N == 1:
        return np.array([1.0, 1.0]) / np.sqrt(2.0)
    yroots = np.roots(_binomial_poly(N))
    selected = []
    for y in yroots:
        z1, z2 = _z_roots_of_y(y)
        selected.append(z1 if abs(z1) < abs(z2) else z2)
    h = _assemble_scaling(N, selected)
    # Standard orientation: energy front-loaded (matches pywt rec_lo for dbN).
    if abs(h[0]) < abs(h[-1]):
        h = h[::-1]
    return h


def _phase_nonlinearity(h: np.ndarray) -> float:
    """Squared deviation of the unwrapped frequency-response phase from linear."""
    n = 1024
    w = np.linspace(1e-3, np.pi - 1e-3, n)
    H = np.polyval(h[::-1].astype(complex), np.exp(-1j * w))
    phase = np.unwrap(np.angle(H))
    # least-squares linear fit
    A = np.stack([w, np.ones_like(w)], axis=1)
    resid = phase - A @ np.linalg.lstsq(A, phase, rcond=None)[0]
    return float(np.sum(resid**2))


@functools.lru_cache(maxsize=None)
def symlet_scaling(N: int) -> np.ndarray:
    """Least-asymmetric Daubechies (symN) scaling filter of length 2N.

    Enumerates root-group assignments of the shared product filter and picks
    the one with the most linear phase.
    """
    if N < 2:
        raise ValueError("Symlet order must be >= 2")
    yroots = list(np.roots(_binomial_poly(N)))
    # Group y-roots: complex-conjugate pairs must flip together to keep h real.
    groups: list[list[complex]] = []
    used = [False] * len(yroots)
    for i, y in enumerate(yroots):
        if used[i]:
            continue
        used[i] = True
        if abs(y.imag) < 1e-12:
            groups.append([complex(y.real, 0.0)])
        else:
            for j in range(i + 1, len(yroots)):
                if not used[j] and abs(yroots[j] - np.conj(y)) < 1e-8:
                    used[j] = True
                    groups.append([y, yroots[j]])
                    break
            else:
                groups.append([y])  # unpaired (numerical); treat alone
    best_h, best_score = None, np.inf
    for mask in range(1 << len(groups)):
        selected: list[complex] = []
        for gi, group in enumerate(groups):
            take_inside = not (mask >> gi) & 1
            for y in group:
                z1, z2 = _z_roots_of_y(y)
                zin, zout = (z1, z2) if abs(z1) < abs(z2) else (z2, z1)
                selected.append(zin if take_inside else zout)
        h = _assemble_scaling(N, selected)
        score = _phase_nonlinearity(h)
        if score < best_score:
            best_score, best_h = score, h
    h = best_h
    if abs(h[0]) < abs(h[-1]):
        h = h[::-1]
    return h


@functools.lru_cache(maxsize=None)
def build_wavelet(name: str) -> Wavelet:
    """Build a named wavelet filter bank: 'haar', 'dbN', 'symN'."""
    key = name.lower().strip()
    if key == "haar" or key == "db1":
        h = daubechies_scaling(1)
    elif key.startswith("db"):
        h = daubechies_scaling(int(key[2:]))
    elif key.startswith("sym"):
        h = symlet_scaling(int(key[3:]))
    else:
        raise ValueError(f"Unsupported wavelet: {name!r} (expected haar/dbN/symN)")
    g = qmf(h)
    return Wavelet(
        name=key,
        dec_lo=h[::-1].copy(),
        dec_hi=g[::-1].copy(),
        rec_lo=h.copy(),
        rec_hi=g.copy(),
    )
