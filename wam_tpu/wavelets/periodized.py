"""Periodized (orthogonal) DWT — the distributed/long-context variant.

With circular boundary handling the DWT is an exactly orthogonal N → N map
(N/2 + N/2 coefficients, no boundary redundancy), which makes it the right
form for sequence-sharded execution: each shard only needs a ring halo of
L−2 neighbour samples (wam_tpu.parallel.halo), the collective pattern
SURVEY.md §5.7 prescribes for long sequences.

The inverse is obtained with `jax.linear_transpose` of the forward — for an
orthogonal transform the adjoint IS the inverse, so reconstruction is exact
by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from wam_tpu.wavelets.filters import Wavelet, build_wavelet

__all__ = ["dwt_per", "idwt_per", "wavedec_per", "waverec_per", "separable_dwt2", "dwt2_per", "wavedec2_per", "idwt2_per", "waverec2_per", "separable_dwt3", "dwt3_per", "wavedec3_per", "idwt3_per", "waverec3_per"]


def _resolve(wavelet) -> Wavelet:
    return wavelet if isinstance(wavelet, Wavelet) else build_wavelet(wavelet)


def _corr_kernel(wav: Wavelet, dtype):
    import numpy as np

    k = np.stack([np.asarray(wav.dec_lo[::-1]), np.asarray(wav.dec_hi[::-1])])[:, None]
    return jnp.asarray(k, dtype=dtype)


def dwt_per(x: jax.Array, wavelet) -> tuple[jax.Array, jax.Array]:
    """Single-level periodized DWT along the last axis (even length N).

    out[k] = Σ_j f[j] · x[(2k − L + 2 + j) mod N], k < N/2 — the same
    alignment as the zero-padded transform, with circular wrap.
    """
    wav = _resolve(wavelet)
    L = wav.filt_len
    N = x.shape[-1]
    if N % 2:
        raise ValueError("periodized DWT requires even length")
    batch_shape = x.shape[:-1]
    xb = x.reshape(-1, 1, N)
    if L > 2:
        xb = jnp.concatenate([xb[..., -(L - 2):], xb], axis=-1)
    out = lax.conv_general_dilated(
        xb,
        _corr_kernel(wav, x.dtype),
        window_strides=(2,),
        padding=[(0, 0)],
        dimension_numbers=lax.conv_dimension_numbers((1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")),
    )
    out = out.reshape(batch_shape + (2, N // 2))
    return out[..., 0, :], out[..., 1, :]


def idwt_per(cA: jax.Array, cD: jax.Array, wavelet) -> jax.Array:
    """Exact inverse via the adjoint (orthogonal transform)."""
    wav = _resolve(wavelet)
    N = 2 * cA.shape[-1]
    x_spec = jax.ShapeDtypeStruct(cA.shape[:-1] + (N,), cA.dtype)
    transpose = jax.linear_transpose(lambda v: dwt_per(v, wav), x_spec)
    (x,) = transpose((cA, cD))
    return x


def wavedec_per(x: jax.Array, wavelet, level: int):
    """Multi-level periodized decomposition [cA_J, cD_J, ..., cD_1]."""
    coeffs = []
    a = x
    for _ in range(level):
        a, d = dwt_per(a, wavelet)
        coeffs.append(d)
    coeffs.append(a)
    return coeffs[::-1]


def waverec_per(coeffs, wavelet):
    a = coeffs[0]
    for d in coeffs[1:]:
        a = idwt_per(a, d, wavelet)
    return a


def separable_dwt2(x: jax.Array, dwt1_w, dwt1_h):
    """Single-level separable 2D DWT from two 1D transforms: ``dwt1_w`` along
    the last axis (W), ``dwt1_h`` along the second-to-last (H, applied after
    a swap). Returns (cA, Detail2D) with the subband naming of
    `wam_tpu.wavelets.transform.dwt2` — shared by the single-device and the
    halo-sharded 2D transforms so the assembly cannot drift.

    The H transform runs FIRST, on the raw block: in the halo-sharded use
    that axis carries the ring exchange, so this order issues one collective
    per level instead of one per W-subband."""
    from wam_tpu.wavelets.transform import Detail2D

    def along_h(t):
        tt = jnp.swapaxes(t, -1, -2)
        a, d = dwt1_h(tt)
        return jnp.swapaxes(a, -1, -2), jnp.swapaxes(d, -1, -2)

    aH, dH = along_h(x)
    aa, ad = dwt1_w(aH)
    da, dd = dwt1_w(dH)
    return aa, Detail2D(horizontal=da, vertical=ad, diagonal=dd)


def dwt2_per(x: jax.Array, wavelet):
    """Single-level separable periodized 2D DWT over the last two axes
    (both even). Returns (cA, Detail2D) with the same subband naming as
    `wam_tpu.wavelets.transform.dwt2`."""
    wav = _resolve(wavelet)
    one = lambda t: dwt_per(t, wav)
    return separable_dwt2(x, one, one)


def wavedec2_per(x: jax.Array, wavelet, level: int):
    """Multi-level periodized 2D decomposition [cA_J, Detail2D_J, ...,
    Detail2D_1]."""
    coeffs = []
    a = x
    for _ in range(level):
        a, det = dwt2_per(a, wavelet)
        coeffs.append(det)
    coeffs.append(a)
    return coeffs[::-1]


def idwt2_per(cA: jax.Array, detail, wavelet) -> jax.Array:
    """Exact inverse of `dwt2_per` via the adjoint (orthogonal transform)."""
    wav = _resolve(wavelet)
    H, W = 2 * cA.shape[-2], 2 * cA.shape[-1]
    x_spec = jax.ShapeDtypeStruct(cA.shape[:-2] + (H, W), cA.dtype)
    transpose = jax.linear_transpose(lambda v: dwt2_per(v, wav), x_spec)
    (x,) = transpose((cA, detail))
    return x


def waverec2_per(coeffs, wavelet):
    """Inverse of `wavedec2_per`."""
    a = coeffs[0]
    for det in coeffs[1:]:
        a = idwt2_per(a, det, wavelet)
    return a


def separable_dwt3(x: jax.Array, dwt1_w, dwt1_h, dwt1_d):
    """Single-level separable 3D DWT over the last three axes (D, H, W) from
    three 1D transforms (each applied along the last axis after a move).
    Returns (cA, {key: arr}) with `wam_tpu.wavelets.transform.dwt3` naming:
    key letters are (D, H, W) order — 'aad' = approx D, approx H, detail W."""

    def along(t, axis, dwt1):
        tt = jnp.moveaxis(t, axis, -1)
        a, d = dwt1(tt)
        return jnp.moveaxis(a, -1, axis), jnp.moveaxis(d, -1, axis)

    # D (the halo-sharded axis in sharded use) runs FIRST, on the raw block:
    # one collective per level instead of one per (H, W)-subband.
    out = {}
    aD, dD = along(x, -3, dwt1_d)
    for d_letter, d_arr in (("a", aD), ("d", dD)):
        aH, dH = along(d_arr, -2, dwt1_h)
        for h_letter, h_arr in (("a", aH), ("d", dH)):
            aW, dW = dwt1_w(h_arr)
            out[d_letter + h_letter + "a"] = aW
            out[d_letter + h_letter + "d"] = dW
    return out.pop("aaa"), out


def dwt3_per(x: jax.Array, wavelet):
    """Single-level separable periodized 3D DWT (all three sizes even)."""
    wav = _resolve(wavelet)
    one = lambda t: dwt_per(t, wav)
    return separable_dwt3(x, one, one, one)


def wavedec3_per(x: jax.Array, wavelet, level: int):
    """Multi-level periodized 3D decomposition [cA_J, {aad..ddd}_J, ...,
    {aad..ddd}_1]."""
    coeffs = []
    a = x
    for _ in range(level):
        a, det = dwt3_per(a, wavelet)
        coeffs.append(det)
    coeffs.append(a)
    return coeffs[::-1]


def idwt3_per(cA: jax.Array, details: dict, wavelet) -> jax.Array:
    """Exact inverse of `dwt3_per` via the adjoint."""
    wav = _resolve(wavelet)
    D, H, W = (2 * s for s in cA.shape[-3:])
    x_spec = jax.ShapeDtypeStruct(cA.shape[:-3] + (D, H, W), cA.dtype)
    transpose = jax.linear_transpose(lambda v: dwt3_per(v, wav), x_spec)
    (x,) = transpose((cA, details))
    return x


def waverec3_per(coeffs, wavelet):
    """Inverse of `wavedec3_per`."""
    a = coeffs[0]
    for det in coeffs[1:]:
        a = idwt3_per(a, det, wavelet)
    return a
