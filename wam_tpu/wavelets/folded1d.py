"""Polyphase-folded 1D DWT/IDWT — the long-signal TPU fast path.

The plain conv form of the 1D transform runs a 12-tap convolution over a
(B, 1, 220500)-shaped tensor: with one input channel the TPU tiles it as
T(1,128), using 1/8 of the sublanes, and the round-3 audio trace showed the
synthesis chain alone costing ~30% of the attribution step at ~1% of HBM
bandwidth. Folding P signal phases into the CHANNEL dimension turns the
same linear map into a conv with 2P=128 input × 2P output channels and
2-3 taps — a dense 128×128 matmul per tap that tiles onto the MXU with
full sublane occupancy.

Math (analysis): with xp the pywt-padded signal (`transform._analysis`
semantics: out[i] = Σ_k f_rev[k]·xp[2i+k]), write xp indices as
n = 2P·m + r and outputs as i = P·mo + s. Then

    out[f, P·mo + s] = Σ_{r,j} W[(f,s), r, j] · ph[r, mo + j],
    W[(f,s), r, j]   = f_rev[2P·j + r − 2s]   (0 ≤ · < L, else 0)

— one VALID stride-1 grouped-as-channels convolution. Synthesis folds the
transposed map the same way (taps j ∈ {0..}, input padded right). Both are
EXACT re-expressions of the conv path (no approximation; parity tested in
tests/test_dwt.py against the reference indexing implementation).

Layouts: the original "nch" form feeds the conv (B, 2P, chunks), which
costs a real transpose copy on each side of the phase-split reshape. The
"nhc" layout keeps chunks outer — the analysis phase split
``(B, total) → (B, chunks, 2P)`` and the synthesis output flatten
``(B, Mt, 2P) → (B, Mt·2P)`` become FREE reshapes (trailing axes merge in
row-major order) and only one transpose per direction remains. Same kernel
entries, transposed to HIO; bit-identical results up to conv layout
lowering (parity tested at f32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wam_tpu.wavelets.filters import Wavelet

__all__ = ["fold_analysis1d", "fold_synthesis1d", "FOLD_P"]

FOLD_P = 64  # phases per output chunk: 2P = 128 channels = one MXU tile


@functools.lru_cache(maxsize=128)
def _analysis_kernel_np(dec_lo: tuple, dec_hi: tuple, P: int) -> np.ndarray:
    """(out=(f,s)=2P, in=r=2P, taps=J) folded analysis kernel."""
    L = len(dec_lo)
    J = (2 * (P - 1) + L - 1) // (2 * P) + 1
    W = np.zeros((2 * P, 2 * P, J), dtype=np.float32)
    for f, filt in enumerate((dec_lo, dec_hi)):
        f_rev = np.asarray(filt[::-1], dtype=np.float64)
        for s in range(P):
            for j in range(J):
                for r in range(2 * P):
                    k = 2 * P * j + r - 2 * s
                    if 0 <= k < L:
                        W[f * P + s, r, j] = f_rev[k]
    return W


@functools.lru_cache(maxsize=128)
def _synthesis_kernel_np(rec_lo: tuple, rec_hi: tuple, P: int) -> np.ndarray:
    """(out=rt=2P, in=(f,si)=2P, taps=T) folded synthesis kernel.

    out[2P·mt + rt] = Σ_i sub[f, i]·rec_f[t + L − 2 − 2i]; tap τ covers
    input chunk mt + τ (input padded right by T−1 chunks)."""
    L = len(rec_lo)
    # j = mt − mi ranges over [jmin, 0]; tap τ = −j
    jmin = -((2 * P + L - 3) // (2 * P))
    T = -jmin + 1
    W = np.zeros((2 * P, 2 * P, T), dtype=np.float32)
    for f, filt in enumerate((rec_lo, rec_hi)):
        rec = np.asarray(filt, dtype=np.float64)
        for rt in range(2 * P):
            for si in range(P):
                for tau in range(T):
                    g = -2 * P * tau + rt + (L - 2) - 2 * si
                    if 0 <= g < L:
                        W[rt, f * P + si, tau] = rec[g]
    return W


_DN = lax.conv_dimension_numbers((1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH"))
_DN_NHC = lax.conv_dimension_numbers((1, 1, 1), (1, 1, 1), ("NHC", "HIO", "NHC"))


def fold_analysis1d(xp: jax.Array, wav: Wavelet, n_out: int,
                    P: int = FOLD_P, layout: str = "nch") -> jax.Array:
    """Folded equivalent of the 1D analysis conv.

    ``xp``: the ALREADY pywt-padded signal (`pad(x, L-1)[..., 1:]`),
    shape (..., Np). Returns (..., 2, n_out) identical to
    `transform._analysis`'s channel layout. ``layout`` picks the conv data
    layout: "nch" (original) or "nhc" (free phase-split reshape — the input
    transpose disappears; see module docstring).
    """
    L = wav.filt_len
    batch_shape = xp.shape[:-1]
    Np = xp.shape[-1]
    xb = xp.reshape((-1, Np))

    J = (2 * (P - 1) + L - 1) // (2 * P) + 1
    M = -(-n_out // P)
    total = (M + J - 1) * 2 * P
    xb = jnp.pad(xb, ((0, 0), (0, max(0, total - Np))))[:, :total]

    Wk = _analysis_kernel_np(tuple(wav.dec_lo), tuple(wav.dec_hi), P)
    if layout == "nhc":
        # phase split (B, chunks, 2P) is a FREE reshape in this layout
        ph = xb.reshape(-1, M + J - 1, 2 * P)
        out = lax.conv_general_dilated(
            ph, jnp.asarray(Wk.transpose(2, 1, 0), dtype=xp.dtype),
            window_strides=(1,), padding=[(0, 0)],
            dimension_numbers=_DN_NHC, precision=lax.Precision.HIGHEST,
        )  # (B, M, 2P)
        out = out.reshape(-1, M, 2, P).swapaxes(1, 2).reshape(-1, 2, M * P)
    else:
        ph = xb.reshape(-1, M + J - 1, 2 * P).swapaxes(1, 2)  # (B, 2P, chunks)
        out = lax.conv_general_dilated(
            ph, jnp.asarray(Wk, dtype=xp.dtype),
            window_strides=(1,), padding=[(0, 0)],
            dimension_numbers=_DN, precision=lax.Precision.HIGHEST,
        )  # (B, 2P, M)
        out = out.reshape(-1, 2, P, M).swapaxes(2, 3).reshape(-1, 2, M * P)
    return out[:, :, :n_out].reshape(batch_shape + (2, n_out))


def fold_synthesis1d(sub: jax.Array, wav: Wavelet, P: int = FOLD_P,
                     layout: str = "nch") -> jax.Array:
    """Folded equivalent of the 1D synthesis conv.

    ``sub``: (..., 2, n) [cA; cD]. Returns the FULL reconstruction
    (..., 2n − L + 2) — the caller crops to its target length exactly like
    `transform._synthesis`. ``layout`` as in `fold_analysis1d`; under "nhc"
    the output flatten (B, Mt, 2P) → (B, Mt·2P) is a free reshape.
    """
    L = wav.filt_len
    batch_shape = sub.shape[:-2]
    n = sub.shape[-1]
    full = 2 * n - L + 2
    sb = sub.reshape((-1, 2, n))

    jmin = -((2 * P + L - 3) // (2 * P))
    T = -jmin + 1
    Mt = -(-full // (2 * P))
    Mi = Mt + T - 1
    # input chunks over i: (f, si) channels, chunk index mi
    pad_i = Mi * P - n
    sbp = jnp.pad(sb, ((0, 0), (0, 0), (0, max(0, pad_i))))[:, :, : Mi * P]

    Wk = _synthesis_kernel_np(tuple(wav.rec_lo), tuple(wav.rec_hi), P)
    if layout == "nhc":
        ph = sbp.reshape(-1, 2, Mi, P).swapaxes(1, 2).reshape(-1, Mi, 2 * P)
        out = lax.conv_general_dilated(
            ph, jnp.asarray(Wk.transpose(2, 1, 0), dtype=sub.dtype),
            window_strides=(1,), padding=[(0, 0)],
            dimension_numbers=_DN_NHC, precision=lax.Precision.HIGHEST,
        )  # (B, Mt, 2P) — flattens to out[2P·mt + rt] with no transpose
        y = out.reshape(-1, Mt * 2 * P)[:, :full]
    else:
        ph = sbp.reshape(-1, 2, Mi, P).swapaxes(2, 3).reshape(-1, 2 * P, Mi)
        out = lax.conv_general_dilated(
            ph, jnp.asarray(Wk, dtype=sub.dtype),
            window_strides=(1,), padding=[(0, 0)],
            dimension_numbers=_DN, precision=lax.Precision.HIGHEST,
        )  # (B, 2P, Mt)
        y = out.swapaxes(1, 2).reshape(-1, Mt * 2 * P)[:, :full]
    return y.reshape(batch_shape + (full,))
