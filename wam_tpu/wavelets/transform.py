"""Differentiable multi-level discrete wavelet transforms for TPU (JAX/XLA).

This is the central build item: the TPU-native replacement for the reference's
ptwt/pywt usage — ``ptwt.wavedec/waverec`` (`lib/wam_1D.py:109,117`),
``ptwt.wavedec2/waverec2`` (`lib/wam_2D.py:96,113`) and
``ptwt.wavedec3/waverec3`` (`lib/wam_3D.py:194,206`). Coefficient layouts and
boundary-mode semantics follow the pywt conventions those call sites rely on:

- 1D ``wavedec`` returns ``[cA_J, cD_J, ..., cD_1]`` with per-level length
  floor((n + L - 1)/2).
- 2D ``wavedec2`` returns ``[cA_J, Detail2D(H_J, V_J, D_J), ..., Detail2D_1]``
  where H = hi-pass along rows (axis -2), V = hi-pass along cols (axis -1),
  D = hi-pass along both (pywt's (cH, cV, cD) = dwtn 'da','ad','dd').
- 3D ``wavedec3`` returns ``[cA_J, {'aad': ..., ..., 'ddd': ...}, ...]``
  with keys ordered by axes (-3, -2, -1), matching ptwt's dicts
  (`lib/wam_3D.py:197-202`).

Everything is expressed as XLA strided convolutions (`lax.conv_general_dilated`)
over fused subband channels — 2 channels for 1D, 4 for 2D, 8 for 3D — so a full
level is ONE conv that tiles onto the MXU, and the whole decomposition is
differentiable by construction (no requires_grad dance; `jax.grad` flows
through). All functions are jit/vmap/shard_map compatible: static shapes,
no Python control flow on traced values.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wam_tpu.wavelets.filters import Wavelet, build_wavelet

__all__ = [
    "Detail2D",
    "dwt",
    "idwt",
    "wavedec",
    "waverec",
    "dwt2",
    "idwt2",
    "wavedec2",
    "waverec2",
    "dwt3",
    "idwt3",
    "wavedec3",
    "waverec3",
    "dwt_max_level",
    "set_dwt2_impl",
    "get_dwt2_impl",
    "set_dwt1_impl",
    "set_synth2_impl",
    "get_synth2_impl",
    "resolved_synth2_impl",
]

# 2D transform backend: "conv" = fused strided lax.conv, "matmul" =
# banded-matmul form on the MXU, "pallas" = fused Pallas kernel (interpreted
# off-TPU), "auto" (default) = pallas on TPU / conv elsewhere. All produce
# identical values (measured on v5e: pallas is ~4x faster than conv for
# 96x224x224 db4 and f32-exact where the bf16 conv default drifts ~1e-2);
# see wavelets/matmul.py.
_DWT2_IMPLS = ("auto", "conv", "matmul", "pallas")


def set_dwt2_impl(name: str) -> None:
    """Select the 2D DWT backend for *not-yet-traced* calls.

    jit caches compiled executables by shape/dtype; a function already traced
    under one backend keeps it until re-traced (new shapes or a fresh jit
    wrapper). For A/B comparisons, build a fresh jitted callable per impl.
    """
    global _dwt2_impl
    if name not in _DWT2_IMPLS:
        raise ValueError(f"impl {name!r} not one of {_DWT2_IMPLS}")
    _dwt2_impl = name


_dwt2_impl = "auto"
set_dwt2_impl(os.environ.get("WAM_TPU_DWT2_IMPL", "auto"))


# 1D transform backend: "conv" = the plain fused conv; "folded" = the
# polyphase channel-fold (wavelets/folded1d.py — same linear map expressed
# as a 128-channel conv, full sublane occupancy on long signals);
# "folded_nhc" = the same fold with chunks-outer conv layout, which turns
# the phase-split reshape on one side of each conv into a free reshape
# (one transpose copy saved per direction); "auto" (default) = folded on
# TPU for signals past the fold break-even, conv elsewhere. Exact
# re-expression up to float summation order.
_DWT1_IMPLS = ("auto", "conv", "folded", "folded_nhc")
_FOLD1D_MIN_LEN = 4096


def set_dwt1_impl(name: str) -> None:
    """Select the 1D DWT backend for *not-yet-traced* calls (see
    set_dwt2_impl's note on jit caching)."""
    global _dwt1_impl
    if name not in _DWT1_IMPLS:
        raise ValueError(f"impl {name!r} not one of {_DWT1_IMPLS}")
    _dwt1_impl = name


_dwt1_impl = "auto"
set_dwt1_impl(os.environ.get("WAM_TPU_DWT1_IMPL", "auto"))


def _use_folded1d(n: int) -> bool:
    if _dwt1_impl in ("folded", "folded_nhc"):
        return True
    if _dwt1_impl == "conv":
        return False
    return jax.default_backend() == "tpu" and n >= _FOLD1D_MIN_LEN


def _fold1d_layout() -> str:
    """Conv data layout for the folded 1D kernels ("nch" unless the
    "folded_nhc" impl was selected)."""
    return "nhc" if _dwt1_impl == "folded_nhc" else "nch"


def get_dwt2_impl() -> str:
    return _dwt2_impl


def _resolved_dwt2_impl() -> str:
    if _dwt2_impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "conv"
    return _dwt2_impl


# 2D SYNTHESIS backend, the mirror knob of set_dwt2_impl (ISSUE 4): "conv" =
# dilated conv-transpose, "matmul" = banded-matmul `synthesis2_mm`, "pallas" =
# the fused `idwt2_pallas` kernel (subband merge + both synthesis matmuls in
# one VMEM pass, backward = the fused analysis kernel), "auto" (default) =
# pallas on TPU / follow the analysis impl elsewhere. On the pallas impl,
# `waverec2` additionally COLLAPSES every contiguous coarsest level whose
# side length is below `_SYNTH_COLLAPSE` into one host-composed banded
# operator pair (matmul.waverec2_collapsed) — the deep tail of sub-tile
# levels becomes a single MXU-shaped matmul instead of J tiny launches.
_SYNTH2_IMPLS = ("auto", "conv", "matmul", "pallas")


def set_synth2_impl(name: str) -> None:
    """Select the 2D synthesis backend for *not-yet-traced* calls (see
    set_dwt2_impl's note on jit caching)."""
    global _synth2_impl
    if name not in _SYNTH2_IMPLS:
        raise ValueError(f"impl {name!r} not one of {_SYNTH2_IMPLS}")
    _synth2_impl = name


_synth2_impl = "auto"
set_synth2_impl(os.environ.get("WAM_TPU_SYNTH2_IMPL", "auto"))

# Level-collapse tile crossover: levels with every detail side BELOW this
# are folded into the collapsed operator pair (default 128 = one TPU tile's
# lane width; a level at or past it occupies the MXU on its own).
_SYNTH_COLLAPSE = int(os.environ.get("WAM_TPU_SYNTH_COLLAPSE", "128"))


def get_synth2_impl() -> str:
    return _synth2_impl


def _resolved_synth2_impl() -> str:
    if _synth2_impl == "auto":
        if jax.default_backend() == "tpu":
            return "pallas"
        # Off-TPU, follow the analysis impl so dwt2/idwt2 stay paired
        # (conv-with-conv keeps the seed CPU graphs byte-identical).
        return "conv" if _resolved_dwt2_impl() == "conv" else "matmul"
    return _synth2_impl


def resolved_synth2_impl() -> str:
    """The impl `idwt2`/`waverec2` would trace RIGHT NOW ("conv" | "matmul" |
    "pallas") — engines tag AOT cache keys with this so an exported
    executable records which synthesis path it baked in."""
    return _resolved_synth2_impl()

DETAIL3D_KEYS = ("aad", "ada", "add", "daa", "dad", "dda", "ddd")


class Detail2D(NamedTuple):
    """One level of 2D detail coefficients (ptwt WaveletDetailTuple2d analogue,
    `lib/wam_2D.py:29`)."""

    horizontal: jax.Array
    vertical: jax.Array
    diagonal: jax.Array


# pywt boundary-mode name -> jnp.pad mode. Note the naming mismatch:
# pywt 'constant' replicates the edge value (jnp 'edge'); pywt 'zero' pads
# zeros (jnp 'constant'); pywt 'reflect' is whole-sample, 'symmetric'
# half-sample — same names in jnp.pad.
_PAD_MODE = {
    "zero": "constant",
    "constant": "edge",
    "symmetric": "symmetric",
    "reflect": "reflect",
    "periodic": "wrap",
}


def _resolve(wavelet) -> Wavelet:
    return wavelet if isinstance(wavelet, Wavelet) else build_wavelet(wavelet)


def dwt_max_level(data_len: int, filt_len: int) -> int:
    """pywt.dwt_max_level: floor(log2(data_len / (filt_len - 1)))."""
    if data_len < filt_len - 1 or filt_len < 2:
        return 0
    return int(np.floor(np.log2(data_len / (filt_len - 1.0))))


def _pad_axes(x: jax.Array, pad: int, axes: Sequence[int], mode: str) -> jax.Array:
    if mode not in _PAD_MODE:
        raise ValueError(f"Unsupported mode {mode!r}; one of {sorted(_PAD_MODE)}")
    widths = [(0, 0)] * x.ndim
    for ax in axes:
        widths[ax % x.ndim] = (pad, pad)
    jmode = _PAD_MODE[mode]
    if jmode in ("reflect", "symmetric"):
        # jnp.pad cannot extend past the signal in one go; loop for tiny inputs.
        while True:
            ok = all(
                widths[ax % x.ndim][0] < x.shape[ax % x.ndim]
                or jmode == "symmetric"
                and widths[ax % x.ndim][0] <= x.shape[ax % x.ndim]
                for ax in axes
            )
            if ok:
                break
            step = [(0, 0)] * x.ndim
            rem = list(widths)
            for ax in axes:
                a = ax % x.ndim
                cap = x.shape[a] - 1 if jmode == "reflect" else x.shape[a]
                take = min(widths[a][0], max(cap, 1))
                step[a] = (take, take)
                rem[a] = (widths[a][0] - take, widths[a][1] - take)
            x = jnp.pad(x, step, mode=jmode)
            widths = rem
            if all(w == (0, 0) for w in widths):
                return x
    return jnp.pad(x, widths, mode=jmode)


def _subband_kernel(wav: Wavelet, ndim: int, dtype) -> jnp.ndarray:
    """Fused analysis kernel: (2^ndim, 1, L, ..., L) of flipped dec-filter
    outer products, channel order = binary a/d counting over axes."""
    lo = np.asarray(wav.dec_lo[::-1])
    hi = np.asarray(wav.dec_hi[::-1])
    banks = []
    for code in range(2**ndim):
        k = np.array(1.0)
        for axis in range(ndim):
            f = hi if (code >> (ndim - 1 - axis)) & 1 else lo
            k = np.multiply.outer(k, f)
        banks.append(k)
    kernel = np.stack(banks)[:, None]  # (O, I=1, L...L)
    return jnp.asarray(kernel, dtype=dtype)


def _inv_subband_kernel(wav: Wavelet, ndim: int, dtype) -> jnp.ndarray:
    """Fused synthesis kernel: (1, 2^ndim, L, ..., L), rec-filter outer
    products flipped along every spatial axis (true convolution)."""
    lo = np.asarray(wav.rec_lo)
    hi = np.asarray(wav.rec_hi)
    banks = []
    for code in range(2**ndim):
        k = np.array(1.0)
        for axis in range(ndim):
            f = hi if (code >> (ndim - 1 - axis)) & 1 else lo
            k = np.multiply.outer(k, f)
        for axis in range(k.ndim):
            k = np.flip(k, axis=axis)
        banks.append(k)
    kernel = np.stack(banks)[None]  # (O=1, I, L...L)
    return jnp.asarray(kernel, dtype=dtype)


def _conv_dims(ndim: int):
    spatial = "HWD"[:ndim] if ndim <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return lax.conv_dimension_numbers((1, 1) + (1,) * ndim, (1, 1) + (1,) * ndim, (lhs, rhs, lhs))


def _analysis(x: jax.Array, wav: Wavelet, mode: str, ndim: int) -> jax.Array:
    """One analysis level over the trailing `ndim` axes.

    x: (..., S1..Sn) -> (..., 2^ndim, S1'..Sn') with Si' = floor((Si+L-1)/2).
    """
    L = wav.filt_len
    batch_shape = x.shape[:-ndim]
    spatial = x.shape[-ndim:]
    xb = x.reshape((-1, 1) + spatial)
    xp = _pad_axes(xb, L - 1, range(-ndim, 0), mode)
    # Offset so strided correlation lands on pywt's odd output positions.
    xp = xp[(Ellipsis,) + tuple(slice(1, None) for _ in range(ndim))]
    kernel = _subband_kernel(wav, ndim, x.dtype)
    out = lax.conv_general_dilated(
        xp,
        kernel,
        window_strides=(2,) * ndim,
        padding=[(0, 0)] * ndim,
        dimension_numbers=_conv_dims(ndim),
        precision=lax.Precision.HIGHEST,  # TPU conv defaults to bf16 inputs
    )
    return out.reshape(batch_shape + out.shape[1:])


def _synthesis(subbands: jax.Array, wav: Wavelet, ndim: int, out_shape: Sequence[int]) -> jax.Array:
    """Inverse of one analysis level.

    subbands: (..., 2^ndim, S1..Sn) -> (..., O1..On), trimmed to out_shape.
    """
    L = wav.filt_len
    batch_shape = subbands.shape[: -(ndim + 1)]
    xb = subbands.reshape((-1,) + subbands.shape[-(ndim + 1) :])
    kernel = _inv_subband_kernel(wav, ndim, subbands.dtype)
    # Full reconstruction = true convolution with the rec filters (padding
    # L-1) trimmed by L-2 per side, i.e. correlation with the flipped
    # kernel at padding 1 — for every filter length.
    out = lax.conv_general_dilated(
        xb,
        kernel,
        window_strides=(1,) * ndim,
        padding=[(1, 1)] * ndim,
        lhs_dilation=(2,) * ndim,
        dimension_numbers=_conv_dims(ndim),
        precision=lax.Precision.HIGHEST,  # TPU conv defaults to bf16 inputs
    )
    out = out[(slice(None), 0)]
    # Full reconstruction length is 2*Si - L + 2; trim to requested shape.
    out = out[(Ellipsis,) + tuple(slice(0, s) for s in out_shape)]
    return out.reshape(batch_shape + tuple(out_shape))


# ---------------------------------------------------------------------------
# 1D  (reference: ptwt.wavedec/waverec at lib/wam_1D.py:109,117)
# ---------------------------------------------------------------------------


def dwt(x: jax.Array, wavelet, mode: str = "symmetric"):
    """Single-level 1D DWT along the last axis. Returns (cA, cD).

    bf16 inputs produce f32 coefficients (the framework-wide bf16-in /
    f32-accumulate policy — see dwt2)."""
    wav = _resolve(wavelet)
    if x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    n = x.shape[-1]
    with jax.named_scope("wam_analysis"):
        if _use_folded1d(n):
            from wam_tpu.wavelets.folded1d import fold_analysis1d

            L = wav.filt_len
            xp = _pad_axes(x, L - 1, (-1,), mode)[..., 1:]
            n_out = (n + L - 1) // 2
            out = fold_analysis1d(xp, wav, n_out, layout=_fold1d_layout())
        else:
            out = _analysis(x, wav, mode, 1)
    return out[..., 0, :], out[..., 1, :]


def idwt(cA: jax.Array, cD: jax.Array, wavelet, out_len: int | None = None):
    """Single-level inverse 1D DWT. Output length 2n - L + 2 unless trimmed.

    bf16 coefficients are upcast before the synthesis conv (bf16-in /
    f32-accumulate, same contract as the forward `dwt`)."""
    wav = _resolve(wavelet)
    n = cA.shape[-1]
    full = 2 * n - wav.filt_len + 2
    target = full if out_len is None else out_len
    if cA.dtype == jnp.bfloat16 or cD.dtype == jnp.bfloat16:
        cA = cA.astype(jnp.float32)
        cD = cD.astype(jnp.float32)
    sub = jnp.stack([cA, cD], axis=-2)
    # The fold decision is made on the COEFFICIENT-determined full length,
    # not the requested crop: a caller-supplied out_len (waverec's
    # intermediate levels) must not disqualify the folded kernel — it
    # produces the full reconstruction anyway and cropping is free.
    with jax.named_scope("wam_synth"):
        if _use_folded1d(full):
            from wam_tpu.wavelets.folded1d import fold_synthesis1d

            return fold_synthesis1d(
                sub, wav, layout=_fold1d_layout())[..., :target]
        return _synthesis(sub, wav, 1, (target,))


def wavedec(x: jax.Array, wavelet, level: int, mode: str = "symmetric"):
    """Multi-level 1D DWT: [cA_J, cD_J, ..., cD_1] (coarsest first, pywt order)."""
    wav = _resolve(wavelet)
    coeffs = []
    a = x
    for _ in range(level):
        a, d = dwt(a, wav, mode)
        coeffs.append(d)
    coeffs.append(a)
    return coeffs[::-1]


def waverec(coeffs: Sequence[jax.Array], wavelet):
    """Inverse of `wavedec`. Trims each level to the next detail's length.

    Every level goes through `idwt` with an explicit out_len, and `idwt`
    decides the folded1d kernel on the coefficient length — so when
    `_use_folded1d` holds at an intermediate level it folds there too, not
    just at the (untrimmed) top level."""
    wav = _resolve(wavelet)
    a = coeffs[0]
    for i in range(1, len(coeffs)):
        d = coeffs[i]
        if a.shape[-1] > d.shape[-1]:
            a = a[..., : d.shape[-1]]
        nxt = coeffs[i + 1].shape[-1] if i + 1 < len(coeffs) else None
        a = idwt(a, d, wav, out_len=nxt)
    return a


# ---------------------------------------------------------------------------
# 2D  (reference: ptwt.wavedec2/waverec2 at lib/wam_2D.py:96,113)
# ---------------------------------------------------------------------------


def dwt2(x: jax.Array, wavelet, mode: str = "reflect"):
    """Single-level 2D DWT over the last two axes. Returns (cA, Detail2D).

    bf16 inputs produce FLOAT32 coefficients on every backend (bf16-in /
    f32-accumulate): the pallas kernel reads bf16 natively and upcasts in
    VMEM; conv/matmul upcast at this dispatch so all three impls agree in
    dtype and accuracy — the only bf16 effect is the one-time input
    rounding, never a per-level coefficient re-round (VERDICT.md r2 #6)."""
    wav = _resolve(wavelet)
    impl = _resolved_dwt2_impl()
    if x.dtype == jnp.bfloat16 and impl != "pallas":
        x = x.astype(jnp.float32)
    with jax.named_scope("wam_analysis"):
        if impl != "conv":
            from wam_tpu.wavelets import matmul as _mm

            if impl == "pallas":
                out = _mm.dwt2_pallas(x, wav, mode)
            else:
                out = _mm.analysis2_mm(x, wav, mode)
        else:
            out = _analysis(x, wav, mode, 2)
    # channel order (row, col): 0=aa, 1=ad, 2=da, 3=dd
    return out[..., 0, :, :], Detail2D(
        horizontal=out[..., 2, :, :], vertical=out[..., 1, :, :], diagonal=out[..., 3, :, :]
    )


def idwt2(cA: jax.Array, detail: Detail2D, wavelet, out_shape=None):
    """Single-level inverse 2D DWT, dispatched on `set_synth2_impl`.

    bf16 coefficients produce FLOAT32 pixels on every impl (bf16-in /
    f32-accumulate, the mirror of dwt2's contract): the pallas kernel reads
    bf16 natively and upcasts in VMEM; conv/matmul upcast at this dispatch."""
    wav = _resolve(wavelet)
    n0, n1 = cA.shape[-2:]
    L = wav.filt_len
    target = (2 * n0 - L + 2, 2 * n1 - L + 2) if out_shape is None else tuple(out_shape)
    impl = _resolved_synth2_impl()
    sub = jnp.stack([cA, detail.vertical, detail.horizontal, detail.diagonal], axis=-3)
    if sub.dtype == jnp.bfloat16 and impl != "pallas":
        sub = sub.astype(jnp.float32)
    with jax.named_scope("wam_synth"):
        if impl != "conv":
            from wam_tpu.wavelets import matmul as _mm

            if impl == "pallas":
                return _mm.idwt2_pallas(sub, wav, target)
            return _mm.synthesis2_mm(sub, wav, target)
        return _synthesis(sub, wav, 2, target)


def wavedec2(x: jax.Array, wavelet, level: int, mode: str = "reflect"):
    """Multi-level 2D DWT: [cA_J, Detail2D_J, ..., Detail2D_1]."""
    wav = _resolve(wavelet)
    coeffs = []
    a = x
    for _ in range(level):
        a, det = dwt2(a, wav, mode)
        coeffs.append(det)
    coeffs.append(a)
    return coeffs[::-1]


def _collapse_count(details) -> int:
    """How many contiguous COARSEST levels fall below the collapse
    crossover (every detail side < _SYNTH_COLLAPSE). Those levels are
    sub-tile on the MXU individually; `waverec2_collapsed` runs them as one
    operator pair."""
    k = 0
    for det in details:
        if max(det.horizontal.shape[-2:]) >= _SYNTH_COLLAPSE:
            break
        k += 1
    return k


def waverec2(coeffs, wavelet):
    """Inverse of `wavedec2` (reference reconstruction path, lib/wam_2D.py:113).

    On the pallas synthesis impl, the deep tail of sub-tile levels (every
    side below `_SYNTH_COLLAPSE`, coarsest-first contiguous run of >= 2) is
    collapsed into ONE banded operator pair (matmul.waverec2_collapsed);
    remaining fine levels then run per-level through `idwt2`."""
    wav = _resolve(wavelet)
    a = coeffs[0]
    details = list(coeffs[1:])
    start = 0
    if _resolved_synth2_impl() == "pallas":
        k = _collapse_count(details)
        if k >= 2:
            from wam_tpu.wavelets import matmul as _mm

            with jax.named_scope("wam_synth"):
                a = _mm.waverec2_collapsed(a, details[:k], wav)
            start = k
    for det in details[start:]:
        tgt = det.horizontal.shape[-2:]
        a = a[..., : tgt[0], : tgt[1]]
        L = wav.filt_len
        a = idwt2(a, det, wav, out_shape=(2 * tgt[0] - L + 2, 2 * tgt[1] - L + 2))
    return a


# ---------------------------------------------------------------------------
# 3D  (reference: ptwt.wavedec3/waverec3 at lib/wam_3D.py:194,206)
# ---------------------------------------------------------------------------


def dwt3(x: jax.Array, wavelet, mode: str = "symmetric"):
    """Single-level 3D DWT over the last three axes. Returns (cA, {key: arr}).

    bf16 inputs produce f32 coefficients (the framework-wide bf16-in /
    f32-accumulate policy — see dwt2)."""
    wav = _resolve(wavelet)
    if x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    with jax.named_scope("wam_analysis"):
        out = _analysis(x, wav, mode, 3)
    keys = ("aaa",) + DETAIL3D_KEYS
    coeffs = {k: out[..., i, :, :, :] for i, k in enumerate(keys)}
    return coeffs.pop("aaa"), coeffs


def idwt3(cA: jax.Array, details: dict, wavelet, out_shape=None):
    """Single-level inverse 3D DWT. On the matmul/pallas synthesis impls the
    conv-transpose is replaced by three banded matmuls (`synthesis3_mm` —
    the MXU form; there is no 3D pallas kernel, so "pallas" resolves to the
    matmul form here). bf16 coefficients are upcast on every path (bf16-in /
    f32-accumulate, the mirror of dwt3's contract)."""
    wav = _resolve(wavelet)
    L = wav.filt_len
    n = cA.shape[-3:]
    target = tuple(2 * s - L + 2 for s in n) if out_shape is None else tuple(out_shape)
    impl = _resolved_synth2_impl()
    sub = jnp.stack([cA] + [details[k] for k in DETAIL3D_KEYS], axis=-4)
    with jax.named_scope("wam_synth"):
        if impl != "conv":
            from wam_tpu.wavelets import matmul as _mm

            return _mm.synthesis3_mm(sub, wav, target)
        if sub.dtype == jnp.bfloat16:
            sub = sub.astype(jnp.float32)
        return _synthesis(sub, wav, 3, target)


def wavedec3(x: jax.Array, wavelet, level: int, mode: str = "symmetric"):
    """Multi-level 3D DWT: [cA_J, {aad..ddd}_J, ..., {aad..ddd}_1]."""
    wav = _resolve(wavelet)
    coeffs = []
    a = x
    for _ in range(level):
        a, det = dwt3(a, wav, mode)
        coeffs.append(det)
    coeffs.append(a)
    return coeffs[::-1]


def waverec3(coeffs, wavelet):
    wav = _resolve(wavelet)
    a = coeffs[0]
    L = wav.filt_len
    for det in coeffs[1:]:
        tgt = det["ddd"].shape[-3:]
        a = a[..., : tgt[0], : tgt[1], : tgt[2]]
        a = idwt3(a, det, wav, out_shape=tuple(2 * s - L + 2 for s in tgt))
    return a
