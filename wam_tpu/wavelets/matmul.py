"""DWT as banded matmuls on the MXU, with a fused Pallas TPU kernel.

The conv-form transforms in `wam_tpu.wavelets.transform` express one analysis
level as a strided `lax.conv_general_dilated`. This module provides the
matmul form of the same linear map: boundary padding (reflect / symmetric /
zero / edge / periodic — the pywt semantics the reference relies on, e.g.
``mode="reflect"`` at `lib/wam_2D.py:56`) is folded into a dense per-axis
analysis matrix, so one full 2D level becomes

    [[aa, ad], [da, dd]] = [A_lo; A_hi] @ X @ [B_lo; B_hi]^T

— two matrix products that tile directly onto the 128x128 systolic array.
The Pallas kernel `dwt2_pallas` fuses both products and the subband split
into a single VMEM-resident kernel per image (custom VJP: the exact adjoint
matmuls). The plain-XLA `analysis2_mm` / `synthesis2_mm` forms are used as
the backward pass and as the CPU fallback, and are differentiable by
construction.

Matrices depend only on (length, wavelet, mode) — static under jit — and are
cached host-side.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wam_tpu.wavelets.filters import Wavelet, build_wavelet

__all__ = [
    "analysis_matrices",
    "synthesis_matrices",
    "analysis2_mm",
    "synthesis2_mm",
    "dwt2_pallas",
]


def _source_index(p: int, n: int, mode: str) -> int:
    """Map an (possibly out-of-range) padded position to an index in [0, n),
    or -1 when the contribution is zero (mode='zero'). Follows pywt/jnp.pad
    semantics: 'reflect' = whole-sample, 'symmetric' = half-sample,
    'constant' = edge-replicate (pywt naming), 'periodic' = wrap."""
    if 0 <= p < n:
        return p
    if mode == "zero":
        return -1
    if mode == "constant":  # pywt 'constant' replicates the edge value
        return 0 if p < 0 else n - 1
    if mode == "periodic":
        return p % n
    if mode == "reflect":
        if n == 1:
            return 0
        period = 2 * n - 2
        m = p % period
        return m if m < n else period - m
    if mode == "symmetric":
        period = 2 * n
        m = p % period
        return m if m < n else period - 1 - m
    raise ValueError(f"Unsupported mode {mode!r}")


@functools.lru_cache(maxsize=256)
def _analysis_np(n: int, dec_lo: tuple, dec_hi: tuple, mode: str) -> np.ndarray:
    """Stacked analysis matrix [A_lo; A_hi] of shape (2*n_out, n): row i of
    A_f computes coefficient i of the f-subband, boundary handling folded in.
    Matches the conv path exactly: out[i] = sum_k f_rev[k] * xp[2i + k] with
    xp = pad(x, L-1)[1:]  (transform._analysis). Cached on the actual filter
    taps, not the wavelet name, so custom Wavelet objects are honored."""
    L = len(dec_lo)
    n_out = (n + L - 1) // 2
    mats = []
    for filt in (dec_lo, dec_hi):
        f_rev = np.asarray(filt[::-1], dtype=np.float64)
        A = np.zeros((n_out, n))
        for i in range(n_out):
            for k in range(L):
                s = _source_index(2 * i + k - L + 2, n, mode)
                if s >= 0:
                    A[i, s] += f_rev[k]
        mats.append(A)
    return np.concatenate(mats, axis=0)


@functools.lru_cache(maxsize=256)
def _synthesis_np(n_out: int, rec_lo: tuple, rec_hi: tuple) -> np.ndarray:
    """Stacked synthesis matrix [S_lo | S_hi] of shape (full, 2*n_out) with
    full = 2*n_out - L + 2: the zero-stuffed true convolution with the rec
    filters, trimmed by L-2 per side (transform._synthesis)."""
    L = len(rec_lo)
    full = 2 * n_out - L + 2
    mats = []
    for filt in (rec_lo, rec_hi):
        f = np.asarray(filt, dtype=np.float64)
        S = np.zeros((full, n_out))
        for i in range(n_out):
            for k in range(L):
                t = 2 * i + k - (L - 2)
                if 0 <= t < full:
                    S[t, i] += f[k]
        mats.append(S)
    return np.concatenate(mats, axis=1)


def _wav(wavelet) -> Wavelet:
    return wavelet if isinstance(wavelet, Wavelet) else build_wavelet(str(wavelet))


def analysis_matrices(n: int, wavelet, mode: str, dtype=jnp.float32) -> jax.Array:
    """(2*n_out, n) stacked [A_lo; A_hi] analysis matrix for one axis."""
    w = _wav(wavelet)
    return jnp.asarray(
        _analysis_np(n, tuple(w.dec_lo), tuple(w.dec_hi), mode), dtype=dtype
    )


def synthesis_matrices(n_out: int, wavelet, dtype=jnp.float32) -> jax.Array:
    """(2*n_out - L + 2, 2*n_out) stacked [S_lo | S_hi] synthesis matrix."""
    w = _wav(wavelet)
    return jnp.asarray(
        _synthesis_np(n_out, tuple(w.rec_lo), tuple(w.rec_hi)), dtype=dtype
    )


def _split_quadrants(y: jax.Array, h_out: int, w_out: int) -> jax.Array:
    """(..., 2*h_out, 2*w_out) block matrix -> (..., 4, h_out, w_out) in the
    conv path's channel order (row, col): 0=aa, 1=ad, 2=da, 3=dd."""
    aa = y[..., :h_out, :w_out]
    ad = y[..., :h_out, w_out:]
    da = y[..., h_out:, :w_out]
    dd = y[..., h_out:, w_out:]
    return jnp.stack([aa, ad, da, dd], axis=-3)


def analysis2_mm(x: jax.Array, wavelet, mode: str) -> jax.Array:
    """One 2D analysis level as two matmuls. x: (..., H, W) ->
    (..., 4, H', W') matching `transform._analysis(x, wav, mode, 2)`."""
    h, w = x.shape[-2:]
    A = analysis_matrices(h, wavelet, mode, x.dtype)
    B = analysis_matrices(w, wavelet, mode, x.dtype)
    y = jnp.matmul(jnp.matmul(A, x, precision=lax.Precision.HIGHEST), B.T,
                   precision=lax.Precision.HIGHEST)
    return _split_quadrants(y, A.shape[0] // 2, B.shape[0] // 2)


def synthesis2_mm(subbands: jax.Array, wavelet, out_shape) -> jax.Array:
    """Inverse of one 2D level as two matmuls. subbands: (..., 4, h, w) ->
    (..., out_shape), trimmed like `transform._synthesis`."""
    h, w = subbands.shape[-2:]
    S_r = synthesis_matrices(h, wavelet, subbands.dtype)
    S_c = synthesis_matrices(w, wavelet, subbands.dtype)
    aa, ad, da, dd = (subbands[..., i, :, :] for i in range(4))
    top = jnp.concatenate([aa, ad], axis=-1)
    bot = jnp.concatenate([da, dd], axis=-1)
    y = jnp.concatenate([top, bot], axis=-2)  # (..., 2h, 2w) block matrix
    out = jnp.matmul(jnp.matmul(S_r, y, precision=lax.Precision.HIGHEST), S_c.T,
                     precision=lax.Precision.HIGHEST)
    return out[..., : out_shape[0], : out_shape[1]]


# ---------------------------------------------------------------------------
# Fused Pallas kernel: both matmuls + subband split in one VMEM-resident pass
# ---------------------------------------------------------------------------


def _fused_kernel(a_ref, bt_ref, x_ref, out_ref):
    # bf16 inputs are upcast HERE, in VMEM: HBM streams half the bytes while
    # both matmuls still run with f32 operands/accumulators (VERDICT.md
    # round-2 #6 — bf16-in/f32-accumulate).
    t = jnp.dot(a_ref[:], x_ref[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)
    y = jnp.dot(t, bt_ref[:], preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)
    h2, w2 = y.shape
    h_out, w_out = h2 // 2, w2 // 2
    out_ref[0, 0] = y[:h_out, :w_out]
    out_ref[0, 1] = y[:h_out, w_out:]
    out_ref[0, 2] = y[h_out:, :w_out]
    out_ref[0, 3] = y[h_out:, w_out:]


def _pallas_forward(x3: jax.Array, A: jax.Array, Bt: jax.Array) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, w = x3.shape
    h2, w2 = A.shape[0], Bt.shape[1]
    h_out, w_out = h2 // 2, w2 // 2
    interpret = jax.default_backend() != "tpu"
    # Inside shard_map (check_vma=True, the jax 0.9 default) every output
    # aval must carry its varying-manual-axes set; the kernel is elementwise
    # in the grid dim, so outputs vary over exactly the axes the operands
    # do. Outside shard_map all vmas are empty frozensets — a no-op.
    out_vma = frozenset().union(
        *(getattr(jax.typeof(a), "vma", frozenset()) for a in (x3, A, Bt))
    )
    return pl.pallas_call(
        _fused_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((h2, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((w, w2), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h, w), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 4, h_out, w_out), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, 4, h_out, w_out), jnp.float32,
                                       vma=out_vma),
        interpret=interpret,
    )(A, Bt, x3)


@jax.custom_vjp
def _dwt2_pallas_core(x3: jax.Array, A: jax.Array, Bt: jax.Array) -> jax.Array:
    return _pallas_forward(x3, A, Bt)


def _core_fwd(x3, A, Bt):
    # dtype token: custom_vjp residuals must be JAX values, so the input
    # dtype rides along as a size-0 array
    return _pallas_forward(x3, A, Bt), (A, Bt, jnp.zeros((0,), x3.dtype))


def _core_bwd(res, g):
    A, Bt, dtype_token = res
    x_dtype = dtype_token.dtype
    h_out, w_out = g.shape[-2:]
    top = jnp.concatenate([g[:, 0], g[:, 1]], axis=-1)
    bot = jnp.concatenate([g[:, 2], g[:, 3]], axis=-1)
    gy = jnp.concatenate([top, bot], axis=-2)  # (n, 2h', 2w')
    dx = jnp.matmul(jnp.matmul(A.T, gy, precision=lax.Precision.HIGHEST), Bt.T,
                    precision=lax.Precision.HIGHEST)  # adjoint of y = A x B^T
    return dx.astype(x_dtype), jnp.zeros_like(A), jnp.zeros_like(Bt)


_dwt2_pallas_core.defvjp(_core_fwd, _core_bwd)


def dwt2_pallas(x: jax.Array, wavelet, mode: str) -> jax.Array:
    """One 2D analysis level via the fused Pallas kernel (interpreted off-TPU).

    x: (..., H, W) -> (..., 4, H', W'), identical layout/values to
    `transform._analysis(x, wav, mode, 2)`; differentiable (custom VJP is the
    exact adjoint matmul pair).

    Dtype contract: bf16 inputs are accepted as-is (half the HBM read
    traffic) and upcast inside the kernel; bf16 and f32 inputs both return
    FLOAT32 coefficients, so the multi-level approx cascade never re-rounds
    to bf16 between levels — the round-2 ablation measured that cascade
    costing cosine 0.9987 → 0.977 (VERDICT.md round-2 #6). Float64 inputs
    (x64 mode) round-trip to float64-TYPED output for downstream dtype
    compatibility, but the kernel itself computes in f32 — for genuine f64
    precision select the conv or matmul impl
    (`wam_tpu.wavelets.set_dwt2_impl("conv")`), since on TPU the default
    "auto" impl routes `transform.wavedec2` back to this kernel."""
    h, w = x.shape[-2:]
    A = analysis_matrices(h, wavelet, mode, jnp.float32)
    B = analysis_matrices(w, wavelet, mode, jnp.float32)
    batch_shape = x.shape[:-2]
    x3 = x.reshape((-1, h, w))
    wide = x3.dtype == jnp.float64
    if x3.dtype != jnp.bfloat16:
        x3 = x3.astype(jnp.float32)
    out = _dwt2_pallas_core(x3, A, B.T)
    if wide:
        out = out.astype(x.dtype)
    return out.reshape(batch_shape + out.shape[1:])
