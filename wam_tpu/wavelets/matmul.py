"""DWT as banded matmuls on the MXU, with a fused Pallas TPU kernel.

The conv-form transforms in `wam_tpu.wavelets.transform` express one analysis
level as a strided `lax.conv_general_dilated`. This module provides the
matmul form of the same linear map: boundary padding (reflect / symmetric /
zero / edge / periodic — the pywt semantics the reference relies on, e.g.
``mode="reflect"`` at `lib/wam_2D.py:56`) is folded into a dense per-axis
analysis matrix, so one full 2D level becomes

    [[aa, ad], [da, dd]] = [A_lo; A_hi] @ X @ [B_lo; B_hi]^T

— two matrix products that tile directly onto the 128x128 systolic array.
The Pallas kernel `dwt2_pallas` fuses both products and the subband split
into a single VMEM-resident kernel per image (custom VJP: the exact adjoint
matmuls). The plain-XLA `analysis2_mm` / `synthesis2_mm` forms are used as
the backward pass and as the CPU fallback, and are differentiable by
construction.

Matrices depend only on (length, wavelet, mode) — static under jit — and are
cached host-side.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wam_tpu import compat
from wam_tpu.wavelets.filters import Wavelet, build_wavelet

__all__ = [
    "analysis_matrices",
    "synthesis_matrices",
    "analysis2_mm",
    "synthesis2_mm",
    "synthesis3_mm",
    "dwt2_pallas",
    "idwt2_pallas",
    "waverec2_collapsed",
]


def _source_index(p: int, n: int, mode: str) -> int:
    """Map an (possibly out-of-range) padded position to an index in [0, n),
    or -1 when the contribution is zero (mode='zero'). Follows pywt/jnp.pad
    semantics: 'reflect' = whole-sample, 'symmetric' = half-sample,
    'constant' = edge-replicate (pywt naming), 'periodic' = wrap."""
    if 0 <= p < n:
        return p
    if mode == "zero":
        return -1
    if mode == "constant":  # pywt 'constant' replicates the edge value
        return 0 if p < 0 else n - 1
    if mode == "periodic":
        return p % n
    if mode == "reflect":
        if n == 1:
            return 0
        period = 2 * n - 2
        m = p % period
        return m if m < n else period - m
    if mode == "symmetric":
        period = 2 * n
        m = p % period
        return m if m < n else period - 1 - m
    raise ValueError(f"Unsupported mode {mode!r}")


@functools.lru_cache(maxsize=256)
def _analysis_np(n: int, dec_lo: tuple, dec_hi: tuple, mode: str) -> np.ndarray:
    """Stacked analysis matrix [A_lo; A_hi] of shape (2*n_out, n): row i of
    A_f computes coefficient i of the f-subband, boundary handling folded in.
    Matches the conv path exactly: out[i] = sum_k f_rev[k] * xp[2i + k] with
    xp = pad(x, L-1)[1:]  (transform._analysis). Cached on the actual filter
    taps, not the wavelet name, so custom Wavelet objects are honored."""
    L = len(dec_lo)
    n_out = (n + L - 1) // 2
    mats = []
    for filt in (dec_lo, dec_hi):
        f_rev = np.asarray(filt[::-1], dtype=np.float64)
        A = np.zeros((n_out, n))
        for i in range(n_out):
            for k in range(L):
                s = _source_index(2 * i + k - L + 2, n, mode)
                if s >= 0:
                    A[i, s] += f_rev[k]
        mats.append(A)
    return np.concatenate(mats, axis=0)


@functools.lru_cache(maxsize=256)
def _synthesis_np(n_out: int, rec_lo: tuple, rec_hi: tuple) -> np.ndarray:
    """Stacked synthesis matrix [S_lo | S_hi] of shape (full, 2*n_out) with
    full = 2*n_out - L + 2: the zero-stuffed true convolution with the rec
    filters, trimmed by L-2 per side (transform._synthesis)."""
    L = len(rec_lo)
    full = 2 * n_out - L + 2
    mats = []
    for filt in (rec_lo, rec_hi):
        f = np.asarray(filt, dtype=np.float64)
        S = np.zeros((full, n_out))
        for i in range(n_out):
            for k in range(L):
                t = 2 * i + k - (L - 2)
                if 0 <= t < full:
                    S[t, i] += f[k]
        mats.append(S)
    return np.concatenate(mats, axis=1)


def _wav(wavelet) -> Wavelet:
    return wavelet if isinstance(wavelet, Wavelet) else build_wavelet(str(wavelet))


def analysis_matrices(n: int, wavelet, mode: str, dtype=jnp.float32) -> jax.Array:
    """(2*n_out, n) stacked [A_lo; A_hi] analysis matrix for one axis."""
    w = _wav(wavelet)
    return jnp.asarray(
        _analysis_np(n, tuple(w.dec_lo), tuple(w.dec_hi), mode), dtype=dtype
    )


def synthesis_matrices(n_out: int, wavelet, dtype=jnp.float32) -> jax.Array:
    """(2*n_out - L + 2, 2*n_out) stacked [S_lo | S_hi] synthesis matrix."""
    w = _wav(wavelet)
    return jnp.asarray(
        _synthesis_np(n_out, tuple(w.rec_lo), tuple(w.rec_hi)), dtype=dtype
    )


def _split_quadrants(y: jax.Array, h_out: int, w_out: int) -> jax.Array:
    """(..., 2*h_out, 2*w_out) block matrix -> (..., 4, h_out, w_out) in the
    conv path's channel order (row, col): 0=aa, 1=ad, 2=da, 3=dd."""
    aa = y[..., :h_out, :w_out]
    ad = y[..., :h_out, w_out:]
    da = y[..., h_out:, :w_out]
    dd = y[..., h_out:, w_out:]
    return jnp.stack([aa, ad, da, dd], axis=-3)


def analysis2_mm(x: jax.Array, wavelet, mode: str) -> jax.Array:
    """One 2D analysis level as two matmuls. x: (..., H, W) ->
    (..., 4, H', W') matching `transform._analysis(x, wav, mode, 2)`."""
    h, w = x.shape[-2:]
    A = analysis_matrices(h, wavelet, mode, x.dtype)
    B = analysis_matrices(w, wavelet, mode, x.dtype)
    y = jnp.matmul(jnp.matmul(A, x, precision=lax.Precision.HIGHEST), B.T,
                   precision=lax.Precision.HIGHEST)
    return _split_quadrants(y, A.shape[0] // 2, B.shape[0] // 2)


def synthesis2_mm(subbands: jax.Array, wavelet, out_shape) -> jax.Array:
    """Inverse of one 2D level as two matmuls. subbands: (..., 4, h, w) ->
    (..., out_shape), trimmed like `transform._synthesis`."""
    h, w = subbands.shape[-2:]
    S_r = synthesis_matrices(h, wavelet, subbands.dtype)
    S_c = synthesis_matrices(w, wavelet, subbands.dtype)
    aa, ad, da, dd = (subbands[..., i, :, :] for i in range(4))
    top = jnp.concatenate([aa, ad], axis=-1)
    bot = jnp.concatenate([da, dd], axis=-1)
    y = jnp.concatenate([top, bot], axis=-2)  # (..., 2h, 2w) block matrix
    out = jnp.matmul(jnp.matmul(S_r, y, precision=lax.Precision.HIGHEST), S_c.T,
                     precision=lax.Precision.HIGHEST)
    return out[..., : out_shape[0], : out_shape[1]]


# ---------------------------------------------------------------------------
# Fused Pallas kernel: both matmuls + subband split in one VMEM-resident pass
# ---------------------------------------------------------------------------


def _fused_kernel(a_ref, bt_ref, x_ref, out_ref):
    # bf16 inputs are upcast HERE, in VMEM: HBM streams half the bytes while
    # both matmuls still run with f32 operands/accumulators (VERDICT.md
    # round-2 #6 — bf16-in/f32-accumulate).
    t = jnp.dot(a_ref[:], x_ref[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)
    y = jnp.dot(t, bt_ref[:], preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)
    h2, w2 = y.shape
    h_out, w_out = h2 // 2, w2 // 2
    out_ref[0, 0] = y[:h_out, :w_out]
    out_ref[0, 1] = y[:h_out, w_out:]
    out_ref[0, 2] = y[h_out:, :w_out]
    out_ref[0, 3] = y[h_out:, w_out:]


def _pallas_forward(x3: jax.Array, A: jax.Array, Bt: jax.Array) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, w = x3.shape
    h2, w2 = A.shape[0], Bt.shape[1]
    h_out, w_out = h2 // 2, w2 // 2
    interpret = jax.default_backend() != "tpu"
    # Inside shard_map (check_vma=True, the jax 0.9 default) every output
    # aval must carry its varying-manual-axes set; the kernel is elementwise
    # in the grid dim, so outputs vary over exactly the axes the operands
    # do. Outside shard_map (and on legacy jax) all vmas are empty — a no-op.
    out_vma = compat.operand_vma(x3, A, Bt)
    return pl.pallas_call(
        _fused_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((h2, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((w, w2), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h, w), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 4, h_out, w_out), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=compat.shape_dtype_struct((n, 4, h_out, w_out), jnp.float32,
                                            vma=out_vma),
        interpret=interpret,
    )(A, Bt, x3)


@jax.custom_vjp
def _dwt2_pallas_core(x3: jax.Array, A: jax.Array, Bt: jax.Array) -> jax.Array:
    return _pallas_forward(x3, A, Bt)


def _core_fwd(x3, A, Bt):
    # dtype token: custom_vjp residuals must be JAX values, so the input
    # dtype rides along as a size-0 array
    return _pallas_forward(x3, A, Bt), (A, Bt, jnp.zeros((0,), x3.dtype))


def _core_bwd(res, g):
    A, Bt, dtype_token = res
    x_dtype = dtype_token.dtype
    h_out, w_out = g.shape[-2:]
    top = jnp.concatenate([g[:, 0], g[:, 1]], axis=-1)
    bot = jnp.concatenate([g[:, 2], g[:, 3]], axis=-1)
    gy = jnp.concatenate([top, bot], axis=-2)  # (n, 2h', 2w')
    dx = jnp.matmul(jnp.matmul(A.T, gy, precision=lax.Precision.HIGHEST), Bt.T,
                    precision=lax.Precision.HIGHEST)  # adjoint of y = A x B^T
    return dx.astype(x_dtype), jnp.zeros_like(A), jnp.zeros_like(Bt)


_dwt2_pallas_core.defvjp(_core_fwd, _core_bwd)


def synthesis3_mm(subbands: jax.Array, wavelet, out_shape) -> jax.Array:
    """Inverse of one 3D level as three banded matmuls (MXU form of the
    conv-transpose in `transform._synthesis(ndim=3)`).

    subbands: (..., 8, d0, d1, d2) in the binary a/d channel order over axes
    (-3, -2, -1) -> (..., out_shape). bf16 subbands are upcast here so the
    contraction accumulates f32 (bf16-in/f32-accumulate); f64 inputs keep
    f64 matrices/contractions (x64 mode)."""
    d0, d1, d2 = subbands.shape[-3:]
    batch_shape = subbands.shape[:-4]
    if subbands.dtype == jnp.bfloat16:
        subbands = subbands.astype(jnp.float32)
    S0 = synthesis_matrices(d0, wavelet, subbands.dtype)
    S1 = synthesis_matrices(d1, wavelet, subbands.dtype)
    S2 = synthesis_matrices(d2, wavelet, subbands.dtype)
    # channel (b0, b1, b2) is the (b0*d0.., b1*d1.., b2*d2..) block of the
    # stacked coefficient tensor [lo; hi] per axis — the layout the
    # [S_lo | S_hi] matrices consume (reshape keeps blocks contiguous).
    y = subbands.reshape(batch_shape + (2, 2, 2, d0, d1, d2))
    y = jnp.moveaxis(y, (-6, -5, -4), (-6, -4, -2))  # (..., 2, d0, 2, d1, 2, d2)
    y = y.reshape(batch_shape + (2 * d0, 2 * d1, 2 * d2))
    hi = lax.Precision.HIGHEST
    y = jnp.einsum("ij,...jkl->...ikl", S0, y, precision=hi)
    y = jnp.einsum("ij,...kjl->...kil", S1, y, precision=hi)
    y = jnp.einsum("ij,...klj->...kli", S2, y, precision=hi)
    return y[..., : out_shape[0], : out_shape[1], : out_shape[2]]


def dwt2_pallas(x: jax.Array, wavelet, mode: str) -> jax.Array:
    """One 2D analysis level via the fused Pallas kernel (interpreted off-TPU).

    x: (..., H, W) -> (..., 4, H', W'), identical layout/values to
    `transform._analysis(x, wav, mode, 2)`; differentiable (custom VJP is the
    exact adjoint matmul pair).

    Dtype contract: bf16 inputs are accepted as-is (half the HBM read
    traffic) and upcast inside the kernel; bf16 and f32 inputs both return
    FLOAT32 coefficients, so the multi-level approx cascade never re-rounds
    to bf16 between levels — the round-2 ablation measured that cascade
    costing cosine 0.9987 → 0.977 (VERDICT.md round-2 #6). Float64 inputs
    (x64 mode) round-trip to float64-TYPED output for downstream dtype
    compatibility, but the kernel itself computes in f32 — for genuine f64
    precision select the conv or matmul impl
    (`wam_tpu.wavelets.set_dwt2_impl("conv")`), since on TPU the default
    "auto" impl routes `transform.wavedec2` back to this kernel."""
    h, w = x.shape[-2:]
    A = analysis_matrices(h, wavelet, mode, jnp.float32)
    B = analysis_matrices(w, wavelet, mode, jnp.float32)
    batch_shape = x.shape[:-2]
    x3 = x.reshape((-1, h, w))
    wide = x3.dtype == jnp.float64
    if x3.dtype != jnp.bfloat16:
        x3 = x3.astype(jnp.float32)
    out = _dwt2_pallas_core(x3, A, B.T)
    if wide:
        out = out.astype(x.dtype)
    return out.reshape(batch_shape + out.shape[1:])


# ---------------------------------------------------------------------------
# Fused Pallas synthesis: subband merge + both synthesis matmuls, one kernel
# ---------------------------------------------------------------------------


def _fused_synth_kernel(sr_ref, sct_ref, sub_ref, out_ref):
    # bf16 subbands are upcast HERE, in VMEM (see _fused_kernel): the merge
    # and both matmuls run with f32 operands/accumulators.
    sub = sub_ref[0].astype(jnp.float32)  # (4, h, w): aa, ad, da, dd
    top = jnp.concatenate([sub[0], sub[1]], axis=-1)
    bot = jnp.concatenate([sub[2], sub[3]], axis=-1)
    y = jnp.concatenate([top, bot], axis=-2)  # (2h, 2w) block matrix
    t = jnp.dot(sr_ref[:], y, preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)
    out_ref[0] = jnp.dot(t, sct_ref[:], preferred_element_type=jnp.float32,
                         precision=lax.Precision.HIGHEST)


def _synth_pallas_forward(sub3: jax.Array, Sr: jax.Array, Sct: jax.Array) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, _, h, w = sub3.shape
    full_h, full_w = Sr.shape[0], Sct.shape[1]
    interpret = jax.default_backend() != "tpu"
    out_vma = compat.operand_vma(sub3, Sr, Sct)
    return pl.pallas_call(
        _fused_synth_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((full_h, 2 * h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((2 * w, full_w), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4, h, w), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, full_h, full_w), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=compat.shape_dtype_struct((n, full_h, full_w), jnp.float32,
                                            vma=out_vma),
        interpret=interpret,
    )(Sr, Sct, sub3)


@jax.custom_vjp
def _idwt2_pallas_core(sub3: jax.Array, Sr: jax.Array, Sct: jax.Array) -> jax.Array:
    return _synth_pallas_forward(sub3, Sr, Sct)


def _synth_fwd(sub3, Sr, Sct):
    return _synth_pallas_forward(sub3, Sr, Sct), (Sr, Sct,
                                                  jnp.zeros((0,), sub3.dtype))


def _synth_bwd(res, g):
    # The adjoint of out = Sr @ Y @ Sct w.r.t. the quadrant-stacked subbands
    # is quadrant-split(Sr^T @ g @ Sct^T) — exactly the fused ANALYSIS kernel
    # with A = Sr^T, B^T = Sct^T, so both directions of the per-sample
    # reconstruct/grad loop run as single fused VMEM-resident kernels.
    Sr, Sct, dtype_token = res
    dsub = _pallas_forward(g, Sr.T, Sct.T)
    return dsub.astype(dtype_token.dtype), jnp.zeros_like(Sr), jnp.zeros_like(Sct)


_idwt2_pallas_core.defvjp(_synth_fwd, _synth_bwd)


def idwt2_pallas(subbands: jax.Array, wavelet, out_shape=None) -> jax.Array:
    """Inverse of one 2D level via the fused Pallas kernel (interpreted
    off-TPU): subband merge + both synthesis matmuls in one VMEM-resident
    pass per image. subbands: (..., 4, h, w) in the conv channel order
    (aa, ad, da, dd) -> (..., out_shape) (full 2h-L+2 x 2w-L+2 when None).

    Dtype contract mirrors `dwt2_pallas`: bf16 subbands are read natively
    and upcast in VMEM, bf16 and f32 both return FLOAT32 pixels; f64 inputs
    round-trip to f64-TYPED output but compute in f32 (select the conv or
    matmul synthesis impl for genuine f64). Custom VJP: the backward is the
    fused analysis kernel `_pallas_forward` (the exact adjoint)."""
    h, w = subbands.shape[-2:]
    Sr = synthesis_matrices(h, wavelet, jnp.float32)
    Sc = synthesis_matrices(w, wavelet, jnp.float32)
    batch_shape = subbands.shape[:-3]
    sub3 = subbands.reshape((-1, 4, h, w))
    wide = sub3.dtype == jnp.float64
    if sub3.dtype != jnp.bfloat16:
        sub3 = sub3.astype(jnp.float32)
    out = _idwt2_pallas_core(sub3, Sr, Sc.T)
    if out_shape is not None:
        out = out[..., : out_shape[0], : out_shape[1]]
    if wide:
        out = out.astype(subbands.dtype)
    return out.reshape(batch_shape + out.shape[1:])


# ---------------------------------------------------------------------------
# Level-collapsed waverec2: the deep tail of tiny levels as ONE operator pair
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _collapsed_axis_np(sizes: tuple, rec_lo: tuple, rec_hi: tuple) -> np.ndarray:
    """Per-axis level-collapsed synthesis operator.

    ``sizes`` are the per-level coefficient lengths along one axis,
    COARSEST FIRST (n_J, ..., n_1) — the `waverec` loop order. Since the
    loop is linear in the coefficients, the whole cascade composes into one
    banded matrix: with S_l = [S_lo | S_hi] the level-l synthesis matrix and
    the inter-level trim folded in as a row slice (level l's full output is
    trimmed to level l-1's coefficient length before re-entering),

        C_1 = S_1,   C_l = C_{l-1}[:, :n_{l-1}] @ S_l[:n_{l-1}, :]

    maps level-l [lo; hi] coefficients straight to the FINEST level's full
    output. Returns [C_J | C_{J-1} | ... | C_1], shape
    (2*n_1 - L + 2, 2*sum(sizes)) — the cascade's lo chain rides inside
    each C_l, so the collapsed 2D apply needs the approx block only at the
    coarsest level (see `waverec2_collapsed`)."""
    fine_first = sizes[::-1]
    blocks: list[np.ndarray] = []
    e_lo = None  # C_{l-1}[:, :n_{l-1}]: the lo chain up to the previous level
    for i, n in enumerate(fine_first):
        S = _synthesis_np(int(n), rec_lo, rec_hi)  # (2n - L + 2, 2n)
        if e_lo is None:
            C = S
        else:
            n_prev = int(fine_first[i - 1])
            C = e_lo @ S[:n_prev, :]
        blocks.append(C)
        e_lo = C[:, : int(n)]
    return np.concatenate(blocks[::-1], axis=1)


def _pair_kernel(r_ref, ct_ref, y_ref, out_ref):
    t = jnp.dot(r_ref[:], y_ref[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)
    out_ref[0] = jnp.dot(t, ct_ref[:], preferred_element_type=jnp.float32,
                         precision=lax.Precision.HIGHEST)


def _pair_forward(y3: jax.Array, R: jax.Array, Ct: jax.Array) -> jax.Array:
    """out[i] = R @ y3[i] @ Ct, one fused VMEM pass per item on TPU; the
    plain-XLA matmul pair elsewhere (identical math, and keeps the graph
    free of pallas custom calls where `jax.export` cannot serialize them)."""
    if jax.default_backend() != "tpu":
        y = y3 if y3.dtype != jnp.bfloat16 else y3.astype(jnp.float32)
        return jnp.matmul(jnp.matmul(R, y, precision=lax.Precision.HIGHEST),
                          Ct, precision=lax.Precision.HIGHEST)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, wr, wc = y3.shape
    fr, fc = R.shape[0], Ct.shape[1]
    out_vma = compat.operand_vma(y3, R, Ct)
    return pl.pallas_call(
        _pair_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((fr, wr), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((wc, fc), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, wr, wc), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, fr, fc), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct((n, fr, fc), jnp.float32,
                                            vma=out_vma),
        interpret=False,
    )(R, Ct, y3)


@jax.custom_vjp
def _pair_core(y3: jax.Array, R: jax.Array, Ct: jax.Array) -> jax.Array:
    return _pair_forward(y3, R, Ct)


def _pair_fwd(y3, R, Ct):
    return _pair_forward(y3, R, Ct), (R, Ct, jnp.zeros((0,), y3.dtype))


def _pair_bwd(res, g):
    R, Ct, dtype_token = res
    dy = jnp.matmul(jnp.matmul(R.T, g, precision=lax.Precision.HIGHEST), Ct.T,
                    precision=lax.Precision.HIGHEST)  # adjoint of R y Ct
    return dy.astype(dtype_token.dtype), jnp.zeros_like(R), jnp.zeros_like(Ct)


_pair_core.defvjp(_pair_fwd, _pair_bwd)


def waverec2_collapsed(cA: jax.Array, details, wavelet) -> jax.Array:
    """Multi-level 2D synthesis of the given levels as ONE banded operator
    pair: out = R @ Y @ C^T with R/C the host-composed per-axis collapsed
    operators (`_collapsed_axis_np`, cached, static under jit) and Y the
    block-diagonal coefficient matrix — per level a 2x2 quadrant block
    [[aa, V], [H, D]] whose aa slot is ZERO except at the coarsest level
    (the approx cascade is already folded into the operators). The J
    sub-tile per-level launches of the deep `waverec2` tail become one
    MXU-shaped matmul pair.

    ``details`` are Detail2D-shaped levels COARSEST FIRST (the `waverec2`
    slice to collapse). Returns the FULL reconstruction of the finest given
    level (2n - L + 2 per side) — the caller trims, exactly like the
    per-level loop. bf16 leaves are upcast at assembly (f32 accumulate);
    f64 runs the plain-XLA f64 matmul pair."""
    w = _wav(wavelet)
    rlo, rhi = tuple(w.rec_lo), tuple(w.rec_hi)
    rsizes = tuple(int(d.horizontal.shape[-2]) for d in details)
    csizes = tuple(int(d.horizontal.shape[-1]) for d in details)
    wide = cA.dtype == jnp.float64
    dtype = jnp.float64 if wide else jnp.float32
    R = jnp.asarray(_collapsed_axis_np(rsizes, rlo, rhi), dtype)
    C = jnp.asarray(_collapsed_axis_np(csizes, rlo, rhi), dtype)
    batch_shape = cA.shape[:-2]
    Y = jnp.zeros(batch_shape + (R.shape[1], C.shape[1]), dtype)
    off_r = off_c = 0
    for i, det in enumerate(details):
        hr, wc = rsizes[i], csizes[i]
        if i == 0:  # coarsest: the only level whose aa slot carries data
            a = cA[..., :hr, :wc].astype(dtype)
            Y = Y.at[..., off_r : off_r + hr, off_c : off_c + wc].set(a)
        Y = Y.at[..., off_r : off_r + hr, off_c + wc : off_c + 2 * wc].set(
            det.vertical.astype(dtype))
        Y = Y.at[..., off_r + hr : off_r + 2 * hr, off_c : off_c + wc].set(
            det.horizontal.astype(dtype))
        Y = Y.at[..., off_r + hr : off_r + 2 * hr, off_c + wc : off_c + 2 * wc].set(
            det.diagonal.astype(dtype))
        off_r += 2 * hr
        off_c += 2 * wc
    if wide:  # x64 mode: genuine f64 via the plain pair (no f32 kernel)
        out = jnp.matmul(jnp.matmul(R, Y, precision=lax.Precision.HIGHEST),
                         C.T, precision=lax.Precision.HIGHEST)
        return out
    y3 = Y.reshape((-1,) + Y.shape[-2:])
    out = _pair_core(y3, R, C.T)
    return out.reshape(batch_shape + out.shape[1:])
