"""Differentiable wavelet transforms — the foundation of the framework.

TPU-native replacement for the reference's ptwt (differentiable, torch) and
pywt (C, non-differentiable) usage; one implementation serves both roles here
because JAX transforms are differentiable by construction.
"""

from wam_tpu.wavelets.filters import Wavelet, build_wavelet, qmf
from wam_tpu.wavelets.periodized import (
    dwt2_per,
    dwt3_per,
    dwt_per,
    idwt2_per,
    idwt3_per,
    idwt_per,
    wavedec2_per,
    wavedec3_per,
    wavedec_per,
    waverec2_per,
    waverec3_per,
    waverec_per,
)
from wam_tpu.wavelets.transform import (
    DETAIL3D_KEYS,
    get_dwt2_impl,
    set_dwt2_impl,
    Detail2D,
    dwt,
    dwt2,
    dwt3,
    dwt_max_level,
    idwt,
    idwt2,
    idwt3,
    wavedec,
    wavedec2,
    wavedec3,
    waverec,
    waverec2,
    waverec3,
)

__all__ = [
    "Wavelet",
    "set_dwt2_impl",
    "get_dwt2_impl",
    "build_wavelet",
    "qmf",
    "Detail2D",
    "DETAIL3D_KEYS",
    "dwt",
    "idwt",
    "dwt2",
    "idwt2",
    "dwt3",
    "idwt3",
    "wavedec",
    "waverec",
    "wavedec2",
    "waverec2",
    "wavedec3",
    "waverec3",
    "dwt_max_level",
    "dwt_per",
    "idwt_per",
    "dwt2_per",
    "idwt2_per",
    "dwt3_per",
    "idwt3_per",
    "wavedec_per",
    "waverec_per",
    "wavedec2_per",
    "waverec2_per",
    "wavedec3_per",
    "waverec3_per",
]
