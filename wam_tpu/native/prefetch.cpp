// Native threaded WAV prefetcher — the data-loader runtime component
// (role of the reference's torch DataLoader worker pool feeding
// `src/dataloader.py`'s ESC-50 pipeline): a C++ thread pool decodes WAV
// files AHEAD of Python consumption into a bounded, ORDERED queue, so the
// host-side IO+decode overlaps TPU compute without touching the GIL.
//
// Ordering contract: items are delivered strictly in submission order
// (index 0, 1, 2, ...) regardless of which worker finished first — the
// consumer of a training epoch needs deterministic batches.
//
// API (C linkage; see wam_tpu/native/__init__.py for the ctypes bindings):
//   pf_create(paths, n, workers, capacity, max_frames) -> handle (0 on err)
//   pf_next_size(handle)
//       -> frames*channels of the NEXT ordinal item (blocking) WITHOUT
//          consuming it, so the caller can size its buffer exactly;
//          negative codes as pf_next (the erroneous item stays queued —
//          the following pf_next consumes and reports it).
//   pf_next(handle, out, max_samples, &sample_rate, &channels)
//       -> frames written for the NEXT ordinal item (blocking),
//          -1 ONLY when the path list is exhausted; per-item failures are
//          distinct negative codes that can never collide with -1:
//            -11/-12/-13 : wavio decode error (wav error code - 10)
//            -5          : file longer than max_frames (raise the limit)
//            -6          : frames*channels exceeds the caller's buffer
//                          (item NOT consumed — grow and retry)
//            -8          : pf_destroy ran concurrently (stopping); the
//                          handle must be considered dead
//          Truncation is never silent — parity with read_wav's full decode
//          is an error, not a clamp.
//   pf_destroy(handle)
//
// pf_destroy may race an ALREADY-IN-FLIGHT pf_next/pf_next_size on the
// same handle: it wakes blocked consumers (they return -8) and DRAINS
// them — the delete only happens once every in-flight call has left. The
// drain cannot see a call that has not yet locked the mutex, so the
// caller must still guarantee no NEW pf_next/pf_next_size call starts
// once pf_destroy has been CALLED (the Python wrapper serializes call
// starts against close() with a lock for exactly this reason).
//
// Decoding reuses wavio.cpp's wav_read_f32/wav_info (both sources are
// compiled into one shared library).

#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int wav_info(const char* path, int* sample_rate, int* channels, long* frames);
long wav_read_f32(const char* path, float* out, long capacity_frames);
}

namespace {

struct Item {
  long frames = -3;  // <0: decode error code
  int sample_rate = 0;
  int channels = 0;
  std::vector<float> samples;
};

struct Prefetcher {
  std::vector<std::string> paths;
  long max_frames = 0;
  size_t capacity = 0;

  std::mutex mu;
  std::condition_variable cv_space;  // workers wait for queue space
  std::condition_variable cv_ready;  // consumer waits for the next ordinal
  std::condition_variable cv_drained;  // pf_destroy waits for consumers
  std::map<size_t, Item> ready;      // finished items keyed by index
  size_t next_submit = 0;            // next index a worker should take
  size_t next_consume = 0;           // next index the consumer wants
  int consumers_in_call = 0;         // pf_next/pf_next_size currently inside
  bool stopping = false;
  std::vector<std::thread> workers;

  // RAII guard counting consumers so pf_destroy can drain them before
  // deleting. Must be constructed and destructed WITH mu held; everything a
  // consumer touches after the guard drops must be thread-local.
  struct ConsumerGuard {
    Prefetcher* pf;
    explicit ConsumerGuard(Prefetcher* p) : pf(p) { ++pf->consumers_in_call; }
    ~ConsumerGuard() {
      if (--pf->consumers_in_call == 0 && pf->stopping)
        pf->cv_drained.notify_all();
    }
  };

  void worker_loop() {
    for (;;) {
      size_t idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        // bound work-ahead: never run more than `capacity` items past the
        // consumer (finished-but-unconsumed + in-flight)
        cv_space.wait(lk, [&] {
          return stopping || (next_submit < paths.size() &&
                              next_submit < next_consume + capacity);
        });
        if (stopping || next_submit >= paths.size()) return;
        idx = next_submit++;
      }

      Item item;
      long frames_in_file = 0;
      int info_rc = wav_info(paths[idx].c_str(), &item.sample_rate,
                             &item.channels, &frames_in_file);
      if (info_rc != 0) {
        item.frames = info_rc - 10;  // -11/-12: never collides with -1
      } else if (frames_in_file > max_frames ||
                 frames_in_file * static_cast<long>(item.channels) >
                     2 * max_frames) {
        // bound SAMPLES too: a corrupt header claiming a huge channel
        // count must become a catchable error, not a giant allocation
        item.frames = -5;
      } else {
        try {
          item.samples.resize(static_cast<size_t>(frames_in_file) *
                              item.channels);
          long got = wav_read_f32(paths[idx].c_str(), item.samples.data(),
                                  frames_in_file);
          item.frames = got < 0 ? got - 10 : got;
        } catch (const std::exception&) {
          // bad_alloc etc. must not escape a std::thread (std::terminate)
          item.frames = -7;
          item.samples.clear();
        }
      }

      {
        std::lock_guard<std::mutex> lk(mu);
        ready.emplace(idx, std::move(item));
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* pf_create(const char** paths, long n, int n_workers, long capacity,
                long max_frames) {
  if (n < 0 || n_workers < 1 || capacity < 1 || max_frames < 1) return nullptr;
  auto* pf = new Prefetcher();
  pf->paths.reserve(n);
  for (long i = 0; i < n; ++i) pf->paths.emplace_back(paths[i]);
  pf->max_frames = max_frames;
  pf->capacity = static_cast<size_t>(capacity);
  int workers = n_workers;
  if (static_cast<long>(workers) > n && n > 0) workers = static_cast<int>(n);
  for (int i = 0; i < workers; ++i)
    pf->workers.emplace_back(&Prefetcher::worker_loop, pf);
  return pf;
}

long pf_next_size(void* handle) {
  auto* pf = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(pf->mu);
  Prefetcher::ConsumerGuard guard(pf);  // destructs before lk unlocks
  if (pf->stopping) return -8;
  if (pf->next_consume >= pf->paths.size()) return -1;  // exhausted
  size_t want = pf->next_consume;
  pf->cv_ready.wait(lk, [&] { return pf->stopping || pf->ready.count(want) > 0; });
  if (pf->stopping) return -8;
  const Item& item = pf->ready[want];
  if (item.frames < 0) return item.frames;
  return item.frames * item.channels;
}

long pf_next(void* handle, float* out, long max_samples, int* sample_rate,
             int* channels) {
  auto* pf = static_cast<Prefetcher*>(handle);
  Item item;
  {
    std::unique_lock<std::mutex> lk(pf->mu);
    Prefetcher::ConsumerGuard guard(pf);  // destructs before lk unlocks
    if (pf->stopping) return -8;
    if (pf->next_consume >= pf->paths.size()) return -1;  // exhausted
    size_t want = pf->next_consume;
    pf->cv_ready.wait(lk, [&] { return pf->stopping || pf->ready.count(want) > 0; });
    if (pf->stopping) return -8;
    Item& peek = pf->ready[want];
    if (peek.frames >= 0 && peek.frames * peek.channels > max_samples) {
      return -6;  // buffer small; item stays queued — grow and retry
    }
    item = std::move(peek);
    pf->ready.erase(want);
    pf->next_consume = want + 1;
    // notify under the lock: after the guard drops, this thread must not
    // touch pf again (pf_destroy may be freeing it)
    pf->cv_space.notify_all();  // consuming freed work-ahead budget
  }

  if (item.frames < 0) return item.frames;
  *sample_rate = item.sample_rate;
  *channels = item.channels;
  std::memcpy(out, item.samples.data(),
              static_cast<size_t>(item.frames) * item.channels *
                  sizeof(float));
  return item.frames;
}

void pf_destroy(void* handle) {
  auto* pf = static_cast<Prefetcher*>(handle);
  {
    std::unique_lock<std::mutex> lk(pf->mu);
    pf->stopping = true;
    pf->cv_space.notify_all();
    pf->cv_ready.notify_all();
    // drain in-flight pf_next/pf_next_size calls: they wake on cv_ready,
    // observe stopping, return -8, and drop their ConsumerGuard under mu —
    // only then is deleting pf safe
    pf->cv_drained.wait(lk, [&] { return pf->consumers_in_call == 0; });
  }
  for (auto& t : pf->workers) t.join();
  delete pf;
}

}  // extern "C"
