// Native WAV decoder — the host-side IO fast path of the audio data layer.
// Role of the reference's scipy.io.wavfile/soundfile C backends
// (src/dataloader.py:93-96, src/helpers.py:246-267): parse RIFF/WAVE PCM
// (16-bit int / 32-bit float), return float32 samples. Built as a shared
// library and loaded through ctypes (wam_tpu/native/__init__.py), with a
// pure-scipy fallback when the toolchain is unavailable.
//
// API (C linkage):
//   wav_info(path, &sample_rate, &channels, &frames)  -> 0 on success
//   wav_read_f32(path, out, capacity_frames)          -> frames read (<0 err)
//     `out` receives channel-interleaved float32 in [-1, 1].

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

struct WavMeta {
  uint32_t sample_rate = 0;
  uint16_t channels = 0;
  uint16_t bits = 0;
  uint16_t format = 0;  // 1 = PCM, 3 = IEEE float
  long data_offset = -1;
  uint32_t data_bytes = 0;
};

bool parse_header(FILE* f, WavMeta* meta) {
  char tag[4];
  uint32_t riff_size;
  if (fread(tag, 1, 4, f) != 4 || memcmp(tag, "RIFF", 4) != 0) return false;
  if (fread(&riff_size, 4, 1, f) != 1) return false;
  if (fread(tag, 1, 4, f) != 4 || memcmp(tag, "WAVE", 4) != 0) return false;

  while (fread(tag, 1, 4, f) == 4) {
    uint32_t chunk_size;
    if (fread(&chunk_size, 4, 1, f) != 1) return false;
    if (memcmp(tag, "fmt ", 4) == 0) {
      uint16_t fmt, ch;
      uint32_t sr, byte_rate;
      uint16_t block_align, bits;
      if (chunk_size < 16) return false;
      if (fread(&fmt, 2, 1, f) != 1 || fread(&ch, 2, 1, f) != 1 ||
          fread(&sr, 4, 1, f) != 1 || fread(&byte_rate, 4, 1, f) != 1 ||
          fread(&block_align, 2, 1, f) != 1 || fread(&bits, 2, 1, f) != 1)
        return false;
      meta->format = fmt;
      meta->channels = ch;
      meta->sample_rate = sr;
      meta->bits = bits;
      if (chunk_size > 16) fseek(f, chunk_size - 16, SEEK_CUR);
    } else if (memcmp(tag, "data", 4) == 0) {
      meta->data_offset = ftell(f);
      meta->data_bytes = chunk_size;
      return meta->sample_rate != 0;
    } else {
      // chunks are word-aligned
      fseek(f, chunk_size + (chunk_size & 1), SEEK_CUR);
    }
  }
  return false;
}

}  // namespace

extern "C" {

int wav_info(const char* path, int* sample_rate, int* channels, long* frames) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  WavMeta meta;
  bool ok = parse_header(f, &meta);
  fclose(f);
  if (!ok || meta.channels == 0 || meta.bits == 0) return -2;
  *sample_rate = static_cast<int>(meta.sample_rate);
  *channels = meta.channels;
  *frames = static_cast<long>(meta.data_bytes) / (meta.channels * meta.bits / 8);
  return 0;
}

long wav_read_f32(const char* path, float* out, long capacity_frames) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  WavMeta meta;
  if (!parse_header(f, &meta)) {
    fclose(f);
    return -2;
  }
  const long frames =
      static_cast<long>(meta.data_bytes) / (meta.channels * meta.bits / 8);
  const long n = frames < capacity_frames ? frames : capacity_frames;
  const long samples = n * meta.channels;
  fseek(f, meta.data_offset, SEEK_SET);

  long written = -3;
  if (meta.format == 1 && meta.bits == 16) {
    std::vector<int16_t> buf(samples);
    if (fread(buf.data(), 2, samples, f) == static_cast<size_t>(samples)) {
      constexpr float kScale = 1.0f / 32768.0f;
      for (long i = 0; i < samples; ++i) out[i] = buf[i] * kScale;
      written = n;
    }
  } else if (meta.format == 3 && meta.bits == 32) {
    if (fread(out, 4, samples, f) == static_cast<size_t>(samples)) written = n;
  } else if (meta.format == 1 && meta.bits == 32) {
    std::vector<int32_t> buf(samples);
    if (fread(buf.data(), 4, samples, f) == static_cast<size_t>(samples)) {
      constexpr double kScale = 1.0 / 2147483648.0;
      for (long i = 0; i < samples; ++i)
        out[i] = static_cast<float>(buf[i] * kScale);
      written = n;
    }
  }
  fclose(f);
  return written;
}

}  // extern "C"
