"""Native runtime components (C++ via ctypes).

`read_wav(path)` decodes a WAV file to float32 through the compiled
shared library when available (built lazily with g++ from `wavio.cpp` +
`prefetch.cpp`), falling back to scipy.io.wavfile otherwise. Both paths
return (sample_rate, samples) with samples (frames,) mono or
(frames, channels).

`WavPrefetcher(paths, workers, capacity)` streams decoded waveforms in
submission order from a C++ thread pool that decodes AHEAD of the
consumer (the torch-DataLoader-worker role for the ESC-50 pipeline,
`prefetch.cpp`); a Python-threaded fallback covers environments without
the toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["read_wav", "native_available", "WavPrefetcher"]

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "wavio.cpp")
_SRC_PF = os.path.join(_HERE, "prefetch.cpp")
_LIB_PATH = os.path.join(_HERE, "_wamnative.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            newest_src = max(os.path.getmtime(_SRC), os.path.getmtime(_SRC_PF))
            if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < newest_src:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                     "-o", _LIB_PATH, _SRC, _SRC_PF],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_LIB_PATH)
            lib.wav_info.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_long),
            ]
            lib.wav_info.restype = ctypes.c_int
            lib.wav_read_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_long,
            ]
            lib.wav_read_f32.restype = ctypes.c_long
            lib.pf_create.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_long,
                ctypes.c_int, ctypes.c_long, ctypes.c_long,
            ]
            lib.pf_create.restype = ctypes.c_void_p
            lib.pf_next.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
                ctypes.c_long, ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.pf_next.restype = ctypes.c_long
            lib.pf_next_size.argtypes = [ctypes.c_void_p]
            lib.pf_next_size.restype = ctypes.c_long
            lib.pf_destroy.argtypes = [ctypes.c_void_p]
            lib.pf_destroy.restype = None
            _lib = lib
        except Exception:
            _build_failed = True
        return _lib


def native_available() -> bool:
    return _load() is not None


def read_wav(path: str) -> tuple[int, np.ndarray]:
    lib = _load()
    if lib is None:
        from scipy.io import wavfile

        sr, data = wavfile.read(path)
        if data.dtype == np.int16:
            data = data.astype(np.float32) / 32768.0
        elif data.dtype == np.int32:
            data = (data.astype(np.float64) / 2147483648.0).astype(np.float32)
        else:
            data = data.astype(np.float32)
        return int(sr), data

    sr = ctypes.c_int()
    ch = ctypes.c_int()
    frames = ctypes.c_long()
    rc = lib.wav_info(path.encode(), ctypes.byref(sr), ctypes.byref(ch), ctypes.byref(frames))
    if rc != 0:
        raise IOError(f"wav_info failed ({rc}) for {path}")
    out = np.empty(frames.value * ch.value, dtype=np.float32)
    got = lib.wav_read_f32(path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), frames.value)
    if got < 0:
        raise IOError(f"wav_read_f32 failed ({got}) for {path}")
    samples = out[: got * ch.value]
    if ch.value > 1:
        samples = samples.reshape(-1, ch.value)
    return sr.value, samples


class WavPrefetcher:
    """Ordered, bounded, threaded WAV prefetch (prefetch.cpp).

    Iterate to receive (sample_rate, samples) per path IN ORDER; decoding
    runs up to ``capacity`` items ahead on ``workers`` C++ threads. Use as
    a context manager (or exhaust the iterator) so threads are joined.
    Falls back to a Python ThreadPool when the native library is missing —
    same contract, GIL-scheduled.

    Thread safety: one iterator at a time (a second ``iter()`` raises
    eagerly). ``close()`` may be called from another thread while the
    iterator runs; it serializes behind the in-flight item (waits out at
    most one decode) and the iterator then stops cleanly. The C API's -8
    stop code additionally defends direct C callers that race pf_destroy
    against a blocked pf_next (prefetch.cpp).
    """

    def __init__(self, paths: list[str], workers: int = 4, capacity: int = 8,
                 max_frames: int = 16_000_000):
        self.paths = [str(p) for p in paths]
        self.workers = max(1, int(workers))
        self.capacity = max(1, int(capacity))
        self.max_frames = int(max_frames)
        self._handle = None
        self._fallback = None
        self._closed = False
        self._iterating = False
        # serializes native calls against close() from another thread: a
        # call started after pf_destroy returns would be a dangling handle
        self._native_lock = threading.Lock()
        lib = _load()
        if lib is not None and self.paths:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths]
            )
            self._paths_arr = arr  # keep alive for the worker threads
            self._handle = lib.pf_create(
                arr, len(self.paths), self.workers, self.capacity,
                self.max_frames,
            )
        if self._handle is None and self.paths:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=self.workers)
            self._fallback = True  # futures submitted lazily (bounded)
        # unconditional cleanup: a constructed-but-abandoned prefetcher must
        # not leak native worker threads (round-3 advisor finding)
        import weakref

        self._finalizer = weakref.finalize(self, WavPrefetcher._finalize,
                                           _load(), self._handle)

    @staticmethod
    def _finalize(lib, handle):
        if lib is not None and handle is not None:
            lib.pf_destroy(handle)

    def __iter__(self):
        # eager single-use guard: __iter__ is NOT a generator, so calling
        # iter() twice raises immediately instead of handing out a second
        # generator that would interleave the shared native ordinal stream
        # (round-3 advisor finding); check-and-set under the lock so two
        # threads cannot both pass it
        with self._native_lock:
            if self._closed or self._iterating:
                raise RuntimeError(
                    "WavPrefetcher is single-use: it is already being "
                    "iterated or was closed; construct a new one for "
                    "another pass"
                )
            self._iterating = True
        if self._handle is not None:
            return self._iter_native()
        if self._fallback:
            return self._iter_fallback()
        return iter(())

    def _iter_native(self):
        lib = _load()
        try:
            # buffer grown to each item's exact size via pf_next_size —
            # no worst-case (max_frames*2 ≈ 128 MB) preallocation
            buf = np.empty(1 << 18, dtype=np.float32)  # 1 MB start
            sr = ctypes.c_int()
            ch = ctypes.c_int()
            for path in self.paths:
                with self._native_lock:
                    if self._handle is None:  # closed concurrently
                        return
                    need = lib.pf_next_size(self._handle)
                    if need > buf.size:
                        buf = np.empty(need, dtype=np.float32)
                    elif buf.size > (1 << 18) and 0 < need < buf.size // 4:
                        # shrink after an outlier so one huge file doesn't
                        # pin its worst-case buffer for the rest of the epoch
                        buf = np.empty(max(need, 1 << 18), dtype=np.float32)
                    got = lib.pf_next(
                        self._handle,
                        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                        buf.size, ctypes.byref(sr), ctypes.byref(ch),
                    )
                if got == -1:  # exhausted (item errors are < -1)
                    return
                if got < 0:
                    raise IOError(
                        f"prefetch decode failed (code {got}) for {path}"
                        + (" — file exceeds max_frames" if got == -5 else "")
                        + (" — prefetcher was destroyed concurrently"
                           if got == -8 else "")
                    )
                samples = buf[: got * ch.value].copy()
                if ch.value > 1:
                    samples = samples.reshape(-1, ch.value)
                yield sr.value, samples
        finally:
            # exhaustion, break, or error all join the C++ workers
            self.close()

    def _iter_fallback(self):
        from collections import deque
        from concurrent.futures import CancelledError

        pending: deque = deque()
        try:
            it = iter(self.paths)
            # bounded work-ahead, honoring `capacity` like the C++ path
            for p in it:
                pending.append(self._pool.submit(read_wav, p))
                if len(pending) >= self.capacity:
                    break
            for p in it:
                yield pending.popleft().result()
                pending.append(self._pool.submit(read_wav, p))
            while pending:
                yield pending.popleft().result()
        except (CancelledError, RuntimeError):
            # concurrent close() cancels pending futures / shuts the pool
            # down; mirror the native path's clean stop rather than leaking
            # the pool's internals to the consumer
            if not self._closed:
                raise
        finally:
            for fut in pending:
                fut.cancel()
            self.close()

    def close(self):
        self._closed = True
        lib = _load()
        with self._native_lock:
            if self._handle is not None and lib is not None:
                self._finalizer.detach()  # we destroy now; finalizer must not
                lib.pf_destroy(self._handle)
                self._handle = None
        if self._fallback:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._fallback = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
