"""Native runtime components (C++ via ctypes).

`read_wav(path)` decodes a WAV file to float32 through the compiled
`wavio.cpp` shared library when available (built lazily with g++), falling
back to scipy.io.wavfile otherwise. Both paths return
(sample_rate, samples) with samples (frames,) mono or (frames, channels).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["read_wav", "native_available"]

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "wavio.cpp")
_LIB_PATH = os.path.join(_HERE, "_wavio.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB_PATH, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_LIB_PATH)
            lib.wav_info.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_long),
            ]
            lib.wav_info.restype = ctypes.c_int
            lib.wav_read_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_long,
            ]
            lib.wav_read_f32.restype = ctypes.c_long
            _lib = lib
        except Exception:
            _build_failed = True
        return _lib


def native_available() -> bool:
    return _load() is not None


def read_wav(path: str) -> tuple[int, np.ndarray]:
    lib = _load()
    if lib is None:
        from scipy.io import wavfile

        sr, data = wavfile.read(path)
        if data.dtype == np.int16:
            data = data.astype(np.float32) / 32768.0
        elif data.dtype == np.int32:
            data = (data.astype(np.float64) / 2147483648.0).astype(np.float32)
        else:
            data = data.astype(np.float32)
        return int(sr), data

    sr = ctypes.c_int()
    ch = ctypes.c_int()
    frames = ctypes.c_long()
    rc = lib.wav_info(path.encode(), ctypes.byref(sr), ctypes.byref(ch), ctypes.byref(frames))
    if rc != 0:
        raise IOError(f"wav_info failed ({rc}) for {path}")
    out = np.empty(frames.value * ch.value, dtype=np.float32)
    got = lib.wav_read_f32(path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), frames.value)
    if got < 0:
        raise IOError(f"wav_read_f32 failed ({got}) for {path}")
    samples = out[: got * ch.value]
    if ch.value > 1:
        samples = samples.reshape(-1, ch.value)
    return sr.value, samples
