"""JAX version-compatibility shims.

The parallel stack is written against the stabilized `jax.shard_map`
surface (top-level export, ``check_vma=`` knob). Older jax (< 0.6, e.g. the
0.4.x line) ships the same functionality as
`jax.experimental.shard_map.shard_map` with the knob spelled ``check_rep=``.
This module resolves whichever is available so every call site imports
`shard_map` from here and keeps writing the modern spelling.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size", "operand_vma", "shape_dtype_struct"]


def operand_vma(*operands) -> frozenset:
    """Union of the operands' varying-manual-axes sets (jax >= 0.6 inside
    `shard_map` with check_vma). On jax 0.4.x avals carry no vma at all
    (the legacy check_rep machinery) — empty set."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:  # pragma: no cover - exercised on jax 0.4.x only
        return frozenset()
    return frozenset().union(
        *(getattr(typeof(a), "vma", frozenset()) for a in operands)
    )


def shape_dtype_struct(shape, dtype, *, vma=frozenset()):
    """`jax.ShapeDtypeStruct` carrying the ``vma=`` aval annotation where
    this jax supports it; on 0.4.x the kwarg does not exist and the
    annotation is meaningless, so it is dropped."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # pragma: no cover - exercised on jax 0.4.x only
        return jax.ShapeDtypeStruct(shape, dtype)

try:
    from jax import shard_map  # jax >= 0.6: stable top-level export
except ImportError:  # pragma: no cover - exercised on jax 0.4.x only
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f=None, /, **kwargs):
        """`jax.experimental.shard_map.shard_map` with the modern kwarg
        spelling: ``check_vma=`` maps onto the experimental ``check_rep=``.
        Supports both direct calls and the `partial(shard_map, ...)`
        decorator idiom used across wam_tpu.parallel."""
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return lambda g: _experimental_shard_map(g, **kwargs)
        return _experimental_shard_map(f, **kwargs)


try:
    from jax.lax import axis_size  # jax >= 0.6
except ImportError:  # pragma: no cover - exercised on jax 0.4.x only
    from jax.core import axis_frame as _axis_frame

    def axis_size(axis_name) -> int:
        """Static size of a mapped mesh axis inside shard_map — on jax
        0.4.x `jax.core.axis_frame(name)` already returns the plain int."""
        return _axis_frame(axis_name)
