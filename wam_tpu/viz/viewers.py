"""2D mosaic viewers — parity with `src/viewers.py` (plot_wam with optional
Gaussian smoothing and approx-normalization, dyadic level separator lines)
and the fork's `plot_utils.py` (diagonal panels, explanation side-by-sides,
grouped level-share bars). Matplotlib, host-side."""

from __future__ import annotations

import numpy as np

__all__ = [
    "plot_wam",
    "wavelet_region_lines",
    "plot_wavelet_regions",
    "add_lines",
    "plot_diagonal",
    "visualize_explanations_basic",
    "visualize_gradients_at_levels",
]


def wavelet_region_lines(size: int, levels: int):
    """Endpoints of the separator lines between dyadic blocks
    (`src/viewers.py:39-63`): at each level ℓ a horizontal and a vertical
    line at size/2^(ℓ+1), spanning size/2^ℓ."""
    lines = []
    for lev in range(levels):
        span = size // (2**lev)
        mid = size // (2 ** (lev + 1))
        lines.append((((0, mid), (span, mid)), ((mid, span), (mid, 0))))
    return lines


def plot_wavelet_regions(size: int, levels: int):
    """Reference-shaped variant of `wavelet_region_lines`
    (`src/viewers.py:39-63`): dicts `h[k]`, `v[k]` of (2, 2) endpoint arrays
    per level, halving each level."""
    lines = wavelet_region_lines(size, levels)
    h = {i: np.array(hline) for i, (hline, _) in enumerate(lines)}
    v = {i: np.array(vline) for i, (_, vline) in enumerate(lines)}
    return h, v


def add_lines(size: int, levels: int, ax) -> None:
    """White dyadic separators on an imshow'd mosaic (`src/viewers.py:65-79`)."""
    ax.set_xlim(0, size)
    ax.set_ylim(size, 0)
    for (h0, h1), (v0, v1) in wavelet_region_lines(size, levels):
        ax.plot([h0[0], h1[0]], [h0[1], h1[1]], c="w")
        ax.plot([v0[0], v1[0]], [v0[1], v1[1]], c="w")


def plot_wam(ax, wam, levels: int, smooth: bool = False, sigma: float = 1.0,
             cmap: str = "viridis", normalize_approx: bool = False):
    """Render one attribution mosaic with level separators
    (`src/viewers.py:12-36`)."""
    wam = np.asarray(wam)
    size = wam.shape[0]
    display = wam
    if normalize_approx:
        b = size // (2**levels)
        display = wam / (wam.max() if wam.max() else 1.0)
        display = display.copy()
        display[:b, :b] = 0.0
    if smooth:
        import jax.numpy as jnp

        from wam_tpu.ops.filters import gaussian_filter2d

        display = np.asarray(gaussian_filter2d(jnp.asarray(display), sigma=sigma))
    ax.imshow(display, cmap=cmap)
    add_lines(size, levels, ax)


def plot_diagonal(diagonals: dict, cmap: str = "viridis", figsize=(14, 4)):
    """Panel of diagonal blocks + approx (`plot_utils.py:7-24`)."""
    import matplotlib.pyplot as plt

    keys = list(diagonals)
    fig, axes = plt.subplots(1, len(keys), figsize=figsize)
    if len(keys) == 1:
        axes = [axes]
    for ax, key in zip(axes, keys):
        im = ax.imshow(diagonals[key], cmap=cmap)
        ax.set_title(str(key))
        ax.axis("off")
        fig.colorbar(im, ax=ax, fraction=0.046, pad=0.04)
    fig.tight_layout()
    return fig


def visualize_explanations_basic(explanations, images, levels: int, cmap="viridis",
                                 smooth: bool = True, which=0):
    """Original image + WAM side by side (`plot_utils.py:26-76`)."""
    import matplotlib.pyplot as plt

    indices = range(len(explanations)) if which == "all" else [which]
    figs = []
    for i in indices:
        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 5))
        ax1.imshow(np.asarray(images[i]))
        ax1.set_title("Original Image")
        ax1.axis("off")
        plot_wam(ax2, explanations[i], levels=levels, cmap=cmap, smooth=smooth)
        ax2.set_title("WAM")
        ax2.axis("off")
        fig.tight_layout()
        figs.append(fig)
    return figs


def visualize_gradients_at_levels(gradients_at_levels, title: str, names=None):
    """Grouped bar plot of per-level attribution shares
    (`plot_utils.py:79-114`)."""
    import matplotlib.pyplot as plt

    arr = np.asarray(gradients_at_levels)
    n_samples, n_levels = arr.shape
    names = names or [f"Sample {i + 1}" for i in range(n_samples)]
    levels = np.arange(n_levels)
    width = 0.8 / n_samples
    fig = plt.figure(figsize=(10, 6))
    for i in range(n_samples):
        plt.bar(levels + i * width, arr[i], width=width, label=names[i])
    plt.xlabel("Scale level")
    plt.ylabel("Attribution")
    plt.title(title)
    plt.xticks(levels + 0.4, levels + 1)
    plt.legend()
    plt.tight_layout()
    return fig
