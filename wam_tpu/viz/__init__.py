from wam_tpu.viz.viewers import (
    add_lines,
    plot_diagonal,
    plot_wam,
    plot_wavelet_regions,
    visualize_explanations_basic,
    visualize_gradients_at_levels,
    wavelet_region_lines,
)
from wam_tpu.viz.viz3d import (
    scatter3d,
    scatter3d_batch,
    scatter3d_colors,
    scatter3d_explanation_batch,
    scatter3d_superpose,
    voxel_figure,
    voxel_superpose,
)

__all__ = [
    "plot_wam",
    "add_lines",
    "wavelet_region_lines",
    "plot_wavelet_regions",
    "plot_diagonal",
    "visualize_explanations_basic",
    "visualize_gradients_at_levels",
    "scatter3d",
    "scatter3d_batch",
    "scatter3d_superpose",
    "scatter3d_colors",
    "scatter3d_explanation_batch",
    "voxel_figure",
    "voxel_superpose",
]
