"""3D visualization — point-cloud scatters and voxel renders with heatmap
superposition, the role of the reference's plotly module
(`src/utils_viz3D.py:95-655`). Backend: matplotlib 3D (always available
here); if plotly is installed, `scatter3d_plotly`/`voxels_plotly` return
plotly figures with the same data.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "scatter3d",
    "scatter3d_batch",
    "scatter3d_superpose",
    "scatter3d_colors",
    "scatter3d_explanation_batch",
    "voxel_figure",
    "voxel_superpose",
    "HAS_PLOTLY",
]

try:  # optional backend
    import plotly.graph_objects as _go  # noqa: F401

    HAS_PLOTLY = True
except Exception:  # pragma: no cover
    HAS_PLOTLY = False


def _as_points(cloud) -> np.ndarray:
    """Accept (3, N) or (N, 3); return (N, 3)."""
    a = np.asarray(cloud)
    if a.ndim != 2:
        raise ValueError(f"Expected 2D point array, got {a.shape}")
    return a.T if a.shape[0] == 3 and a.shape[1] != 3 else a


def scatter3d(cloud, ax=None, color=None, size: float = 4.0, title: str | None = None):
    """One point cloud (`src/utils_viz3D.py:95-126`)."""
    import matplotlib.pyplot as plt

    pts = _as_points(cloud)
    if ax is None:
        fig = plt.figure()
        ax = fig.add_subplot(projection="3d")
    sc = ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], c=color, s=size)
    if title:
        ax.set_title(title)
    return ax, sc


def scatter3d_batch(clouds, titles=None, ncols: int = 4, size: float = 4.0):
    """Grid of point clouds (`src/utils_viz3D.py:130-176`)."""
    import matplotlib.pyplot as plt

    n = len(clouds)
    ncols = min(ncols, n)
    nrows = (n + ncols - 1) // ncols
    fig = plt.figure(figsize=(4 * ncols, 4 * nrows))
    for i, cloud in enumerate(clouds):
        ax = fig.add_subplot(nrows, ncols, i + 1, projection="3d")
        scatter3d(cloud, ax=ax, size=size, title=titles[i] if titles else None)
    fig.tight_layout()
    return fig


def scatter3d_superpose(cloud_a, cloud_b, labels=("source", "filtered"), size: float = 4.0):
    """Two clouds overlaid (`src/utils_viz3D.py:179-222`)."""
    import matplotlib.pyplot as plt

    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    for cloud, lbl, c in zip((cloud_a, cloud_b), labels, ("tab:blue", "tab:red")):
        pts = _as_points(cloud)
        ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], s=size, label=lbl, color=c, alpha=0.6)
    ax.legend()
    return fig


def scatter3d_colors(cloud, values, cmap: str = "viridis", size: float = 6.0):
    """Cloud colored by per-point scalar (`src/utils_viz3D.py:224-258`)."""
    import matplotlib.pyplot as plt

    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    pts = _as_points(cloud)
    v = np.asarray(values)
    sc = ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], c=v, cmap=cmap, s=size)
    fig.colorbar(sc, ax=ax, fraction=0.03)
    return fig


def scatter3d_explanation_batch(clouds, importances, ncols: int = 4, cmap: str = "viridis"):
    """Batch of clouds colored by importance (`src/utils_viz3D.py:261-314`)."""
    import matplotlib.pyplot as plt

    n = len(clouds)
    ncols = min(ncols, n)
    nrows = (n + ncols - 1) // ncols
    fig = plt.figure(figsize=(4 * ncols, 4 * nrows))
    for i, (cloud, imp) in enumerate(zip(clouds, importances)):
        ax = fig.add_subplot(nrows, ncols, i + 1, projection="3d")
        pts = _as_points(cloud)
        ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], c=np.asarray(imp), cmap=cmap, s=6)
    fig.tight_layout()
    return fig


def voxel_figure(volume, threshold: float = 0.5, facecolor: str = "#7aa6c2"):
    """Solid voxel render of a (D, H, W) occupancy grid
    (`src/utils_viz3D.py:539-582`)."""
    import matplotlib.pyplot as plt

    vol = np.asarray(volume)
    filled = vol > threshold
    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    ax.voxels(filled, facecolors=facecolor, edgecolor="k", linewidth=0.2)
    return fig


def voxel_superpose(volume, heatmap, vox_threshold: float = 0.5, heat_threshold: float = 0.5,
                    cmap: str = "inferno"):
    """Voxel shape + thresholded attribution heatmap overlay
    (`src/utils_viz3D.py:585-655`)."""
    import matplotlib
    import matplotlib.pyplot as plt

    vol = np.asarray(volume)
    heat = np.asarray(heatmap)
    hmin, hmax = heat.min(), heat.max()
    heat_n = (heat - hmin) / (hmax - hmin if hmax > hmin else 1.0)

    shape_mask = vol > vox_threshold
    heat_mask = heat_n > heat_threshold

    colors = np.zeros(shape_mask.shape + (4,))
    colors[shape_mask] = (0.6, 0.6, 0.6, 0.25)
    mapped = matplotlib.colormaps[cmap](heat_n)
    mapped[..., 3] = 0.9
    colors[heat_mask] = mapped[heat_mask]

    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    ax.voxels(shape_mask | heat_mask, facecolors=colors)
    return fig
