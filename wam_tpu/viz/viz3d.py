"""3D visualization — point-cloud scatters and voxel renders with heatmap
superposition, the role of the reference's plotly module
(`src/utils_viz3D.py:95-655`). Backend: matplotlib 3D (always available
here). The reference's `VoxelData`/`CubeData` mesh machinery
(`src/utils_viz3D.py:331-536`, a per-voxel Python loop) is restated as the
vectorized `voxel_surface_mesh` — exposed-face extraction via shifted
occupancy masks, O(6) numpy passes regardless of voxel count. If plotly is
installed, `scatter3d_plotly` / `voxels_plotly` / `voxel_superpose_plotly`
render the same data as plotly figures; without it they raise ImportError
(check `HAS_PLOTLY`).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "scatter3d",
    "scatter3d_batch",
    "scatter3d_superpose",
    "scatter3d_colors",
    "scatter3d_explanation_batch",
    "voxel_figure",
    "voxel_superpose",
    "voxel_surface_mesh",
    "scatter3d_plotly",
    "voxels_plotly",
    "voxel_superpose_plotly",
    "HAS_PLOTLY",
]

try:  # optional backend
    import plotly.graph_objects as _go  # noqa: F401

    HAS_PLOTLY = True
except Exception:  # pragma: no cover
    HAS_PLOTLY = False


def _require_plotly():
    if not HAS_PLOTLY:
        raise ImportError(
            "plotly is not installed; use the matplotlib functions "
            "(scatter3d/voxel_figure/voxel_superpose) or install plotly"
        )
    import plotly.graph_objects as go

    return go


def _as_points(cloud) -> np.ndarray:
    """Accept (3, N) or (N, 3); return (N, 3)."""
    a = np.asarray(cloud)
    if a.ndim != 2:
        raise ValueError(f"Expected 2D point array, got {a.shape}")
    return a.T if a.shape[0] == 3 and a.shape[1] != 3 else a


def scatter3d(cloud, ax=None, color=None, size: float = 4.0, title: str | None = None):
    """One point cloud (`src/utils_viz3D.py:95-126`)."""
    import matplotlib.pyplot as plt

    pts = _as_points(cloud)
    if ax is None:
        fig = plt.figure()
        ax = fig.add_subplot(projection="3d")
    sc = ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], c=color, s=size)
    if title:
        ax.set_title(title)
    return ax, sc


def scatter3d_batch(clouds, titles=None, ncols: int = 4, size: float = 4.0):
    """Grid of point clouds (`src/utils_viz3D.py:130-176`)."""
    import matplotlib.pyplot as plt

    n = len(clouds)
    ncols = min(ncols, n)
    nrows = (n + ncols - 1) // ncols
    fig = plt.figure(figsize=(4 * ncols, 4 * nrows))
    for i, cloud in enumerate(clouds):
        ax = fig.add_subplot(nrows, ncols, i + 1, projection="3d")
        scatter3d(cloud, ax=ax, size=size, title=titles[i] if titles else None)
    fig.tight_layout()
    return fig


def scatter3d_superpose(cloud_a, cloud_b, labels=("source", "filtered"), size: float = 4.0):
    """Two clouds overlaid (`src/utils_viz3D.py:179-222`)."""
    import matplotlib.pyplot as plt

    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    for cloud, lbl, c in zip((cloud_a, cloud_b), labels, ("tab:blue", "tab:red")):
        pts = _as_points(cloud)
        ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], s=size, label=lbl, color=c, alpha=0.6)
    ax.legend()
    return fig


def scatter3d_colors(cloud, values, cmap: str = "viridis", size: float = 6.0):
    """Cloud colored by per-point scalar (`src/utils_viz3D.py:224-258`)."""
    import matplotlib.pyplot as plt

    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    pts = _as_points(cloud)
    v = np.asarray(values)
    sc = ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], c=v, cmap=cmap, s=size)
    fig.colorbar(sc, ax=ax, fraction=0.03)
    return fig


def scatter3d_explanation_batch(clouds, importances, ncols: int = 4, cmap: str = "viridis"):
    """Batch of clouds colored by importance (`src/utils_viz3D.py:261-314`)."""
    import matplotlib.pyplot as plt

    n = len(clouds)
    ncols = min(ncols, n)
    nrows = (n + ncols - 1) // ncols
    fig = plt.figure(figsize=(4 * ncols, 4 * nrows))
    for i, (cloud, imp) in enumerate(zip(clouds, importances)):
        ax = fig.add_subplot(nrows, ncols, i + 1, projection="3d")
        pts = _as_points(cloud)
        ax.scatter(pts[:, 0], pts[:, 1], pts[:, 2], c=np.asarray(imp), cmap=cmap, s=6)
    fig.tight_layout()
    return fig


def voxel_figure(volume, threshold: float = 0.5, facecolor: str = "#7aa6c2"):
    """Solid voxel render of a (D, H, W) occupancy grid
    (`src/utils_viz3D.py:539-582`)."""
    import matplotlib.pyplot as plt

    vol = np.asarray(volume)
    filled = vol > threshold
    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    ax.voxels(filled, facecolors=facecolor, edgecolor="k", linewidth=0.2)
    return fig


# Face tables for exposed-face extraction: per direction, the axis offset to
# the neighbor and the 4 unit-cube corners of that face in CCW order viewed
# from OUTSIDE (outward normals — same closed surface the reference's
# CubeData tables produce, `src/utils_viz3D.py:458-536`).
_FACES = [
    ((1, 0, 0), np.array([(1, 0, 0), (1, 1, 0), (1, 1, 1), (1, 0, 1)])),
    ((-1, 0, 0), np.array([(0, 0, 0), (0, 0, 1), (0, 1, 1), (0, 1, 0)])),
    ((0, 1, 0), np.array([(0, 1, 0), (0, 1, 1), (1, 1, 1), (1, 1, 0)])),
    ((0, -1, 0), np.array([(0, 0, 0), (1, 0, 0), (1, 0, 1), (0, 0, 1)])),
    ((0, 0, 1), np.array([(0, 0, 1), (1, 0, 1), (1, 1, 1), (0, 1, 1)])),
    ((0, 0, -1), np.array([(0, 0, 0), (0, 1, 0), (1, 1, 0), (1, 0, 0)])),
]


def voxel_surface_mesh(volume, threshold: float = 0.0):
    """Surface mesh of the occupied region of a (D, H, W) grid.

    Returns ``(vertices, triangles, intensity)``: vertices ``(N, 3)``
    float, triangles ``(M, 3)`` int vertex indices with outward-facing
    winding, and per-vertex ``intensity`` ``(N,)`` carrying the source
    voxel's value (the reference colors mesh faces by voxel intensity,
    `src/utils_viz3D.py:445-456`). Only EXPOSED faces are emitted — a face
    between two occupied voxels is interior and skipped — so N scales with
    surface area, not volume. Vectorized restatement of the reference's
    per-voxel `VoxelData` loop (`src/utils_viz3D.py:331-456`): one shifted
    occupancy mask per direction, 6 passes total.
    """
    vol = np.asarray(volume)
    if vol.ndim != 3:
        raise ValueError(f"Expected (D, H, W) volume, got {vol.shape}")
    occ = vol > threshold
    padded = np.pad(occ, 1, constant_values=False)
    verts, tris, inten = [], [], []
    base = 0
    for (ox, oy, oz), corners in _FACES:
        nb = padded[
            1 + ox : 1 + ox + occ.shape[0],
            1 + oy : 1 + oy + occ.shape[1],
            1 + oz : 1 + oz + occ.shape[2],
        ]
        exposed = occ & ~nb
        coords = np.argwhere(exposed)  # (F, 3)
        if coords.size == 0:
            continue
        f = len(coords)
        verts.append((coords[:, None, :] + corners[None, :, :]).reshape(-1, 3))
        first = base + 4 * np.arange(f)[:, None]
        quad = np.concatenate(
            [first + np.array([[0, 1, 2]]), first + np.array([[0, 2, 3]])], axis=0
        )
        tris.append(quad)
        inten.append(np.repeat(vol[exposed], 4))
        base += 4 * f
    if not verts:
        return (
            np.zeros((0, 3), np.float64),
            np.zeros((0, 3), np.int64),
            np.zeros((0,), np.float64),
        )
    return (
        np.concatenate(verts).astype(np.float64),
        np.concatenate(tris).astype(np.int64),
        np.concatenate(inten).astype(np.float64),
    )


def scatter3d_plotly(cloud, values=None, size: float = 4.0, cmap: str = "Viridis",
                     title: str | None = None):
    """Point cloud as a plotly Scatter3d figure (`src/utils_viz3D.py:95-126`
    and the colored variant at `:224-258`). Requires plotly."""
    go = _require_plotly()
    pts = _as_points(cloud)
    marker = dict(size=size)
    if values is not None:
        marker.update(color=np.asarray(values), colorscale=cmap, showscale=True)
    fig = go.Figure(
        data=go.Scatter3d(
            x=pts[:, 0], y=pts[:, 1], z=pts[:, 2], mode="markers", marker=marker
        )
    )
    fig.update_layout(
        title=title,
        showlegend=False,
        margin=dict(l=30.0, r=30.0, b=80.0, t=50.0),
        scene=dict(
            xaxis=dict(visible=False),
            yaxis=dict(visible=False),
            zaxis=dict(visible=False),
        ),
    )
    return fig


def _mesh3d_trace(go, volume, threshold, colorscale, opacity):
    v, t, inten = voxel_surface_mesh(volume, threshold)
    return go.Mesh3d(
        x=v[:, 0], y=v[:, 1], z=v[:, 2],
        i=t[:, 0], j=t[:, 1], k=t[:, 2],
        intensity=inten, colorscale=colorscale, showscale=False,
        opacity=opacity,
    )


def voxels_plotly(volume, threshold: float = 0.0, cmap: str = "Viridis",
                  opacity: float = 0.5):
    """Voxel grid as a plotly Mesh3d figure (`src/utils_viz3D.py:539-582`).
    Requires plotly; the mesh itself comes from `voxel_surface_mesh`."""
    go = _require_plotly()
    fig = go.Figure(data=_mesh3d_trace(go, volume, threshold, cmap, opacity),
                    layout=go.Layout(height=500, width=600))
    fig.update_layout(
        scene=dict(
            xaxis=dict(visible=False),
            yaxis=dict(visible=False),
            zaxis=dict(visible=False),
        )
    )
    return fig


def voxel_superpose_plotly(volume, heatmap, vox_threshold: float = 0.5,
                           heat_threshold: float = 0.3,
                           cmap_shape: str = "Blues", cmap_heat: str = "Viridis"):
    """Shape mesh + thresholded attribution-heatmap mesh overlaid
    (`src/utils_viz3D.py:585-655`). Requires plotly."""
    go = _require_plotly()
    heat = np.asarray(heatmap, dtype=np.float64)
    hmin, hmax = heat.min(), heat.max()
    heat_n = (heat - hmin) / (hmax - hmin if hmax > hmin else 1.0)
    fig = go.Figure(
        data=[
            _mesh3d_trace(go, np.asarray(volume), vox_threshold, cmap_shape, 0.25),
            _mesh3d_trace(go, np.where(heat_n > heat_threshold, heat_n, 0.0),
                          heat_threshold, cmap_heat, 0.9),
        ],
        layout=go.Layout(height=500, width=600),
    )
    fig.update_layout(
        scene=dict(
            xaxis=dict(visible=False),
            yaxis=dict(visible=False),
            zaxis=dict(visible=False),
        )
    )
    return fig


def voxel_superpose(volume, heatmap, vox_threshold: float = 0.5, heat_threshold: float = 0.5,
                    cmap: str = "inferno"):
    """Voxel shape + thresholded attribution heatmap overlay
    (`src/utils_viz3D.py:585-655`)."""
    import matplotlib
    import matplotlib.pyplot as plt

    vol = np.asarray(volume)
    heat = np.asarray(heatmap)
    hmin, hmax = heat.min(), heat.max()
    heat_n = (heat - hmin) / (hmax - hmin if hmax > hmin else 1.0)

    shape_mask = vol > vox_threshold
    heat_mask = heat_n > heat_threshold

    colors = np.zeros(shape_mask.shape + (4,))
    colors[shape_mask] = (0.6, 0.6, 0.6, 0.25)
    mapped = matplotlib.colormaps[cmap](heat_n)
    mapped[..., 3] = 0.9
    colors[heat_mask] = mapped[heat_mask]

    fig = plt.figure()
    ax = fig.add_subplot(projection="3d")
    ax.voxels(shape_mask | heat_mask, facecolors=colors)
    return fig
