"""Request-scoped tracing — the first pillar of `wam_tpu.obs`.

A span is one named host-side interval with a trace identity: ``trace_id``
(shared by every span of one request), ``span_id``, and ``parent_id``.
Spans are recorded into a process-level thread-safe ring buffer as plain
dicts and exported as Chrome trace-event JSON (Perfetto-loadable) via
`export_chrome_trace`. Clocks are ``time.perf_counter()`` — monotonic, so
span timestamps order correctly across the serve worker / client / warmup
threads of one process.

Three span shapes cover every call site in the request path:

- ``with span("dispatch", bucket=...):`` — a live span on the current
  thread. It nests: the thread-local context stack parents it to the
  enclosing span, and the new context is visible to everything called
  under it (`AttributionServer.submit` captures it into the request). Live
  spans also enter a `jax.profiler.TraceAnnotation` named scope, so host
  spans line up with device xplane rows in a profiler capture.
- ``start_span("request")`` — a DETACHED span: it does not touch the
  thread-local stack, and it ends on whatever thread resolves it
  (`Span.end`, usually a future callback). This is the per-request root.
- ``record_span("queue_wait", t0, t1, parent=ctx)`` — retroactive: the
  worker loop knows a request's queue wait only once the batch pops, so it
  records the interval after the fact from timestamps it already holds.

Cross-thread propagation is explicit: `current_context()` reads the
calling thread's innermost span, `use_context(ctx)` re-establishes a
context on another thread (the fleet router wraps re-routes in the
original request's context so a re-dispatched request keeps its trace id).

When tracing is disabled (`ObsConfig.enabled=False` via
`wam_tpu.obs.configure`), `span()` returns a shared no-op context manager
singleton and `start_span`/`record_span` return/do nothing — one branch
per call, no allocation, nothing recorded (the satellite-1 near-zero-
overhead contract; `scripts/bench_serve.py --obs-bench` measures it).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

__all__ = [
    "Span",
    "span",
    "start_span",
    "record_span",
    "current_context",
    "use_context",
    "spans",
    "clear_spans",
    "export_chrome_trace",
    "set_enabled",
    "enabled",
    "set_ring_size",
    "namespace_ids",
    "spans_to_events",
]


class _State:
    """Shared mutable observability state (also consulted by the metrics
    registry): one enabled flag, one span ring."""

    def __init__(self, ring_size: int = 4096):
        self.enabled = True
        self.ring: deque = deque(maxlen=ring_size)


_STATE = _State()
_ids = itertools.count(1)  # itertools.count.__next__ is atomic under the GIL
_tls = threading.local()


def enabled() -> bool:
    return _STATE.enabled


def set_enabled(flag: bool) -> None:
    _STATE.enabled = bool(flag)


def set_ring_size(n: int) -> None:
    """Resize the span ring, keeping the newest recorded spans."""
    if n < 1:
        raise ValueError("ring_size must be >= 1")
    _STATE.ring = deque(_STATE.ring, maxlen=int(n))


def _next_id() -> str:
    return f"{next(_ids):x}"


def namespace_ids(pid: int) -> None:
    """Partition the span-id space by process: restart this process's id
    counter at ``pid << 40``. Pod workers call it once at startup so ids
    minted in N processes never collide when the router merges their span
    rings into one trace (2^40 ids per process before overlap — the ring
    holds 4096). Idempotent in effect; call before any spans record."""
    global _ids
    _ids = itertools.count((int(pid) << 40) + 1)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_context():
    """(trace_id, span_id) of the innermost live span on this thread, or
    None — what a child span (or a request capturing its trace identity)
    parents to."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class use_context:
    """Re-establish a span context on the current thread (no-op on None):
    spans opened under it — and `current_context()` reads — see ``ctx`` as
    the parent. The cross-thread half of request-scoped tracing."""

    __slots__ = ("_ctx", "_pushed")

    def __init__(self, ctx):
        self._ctx = ctx
        self._pushed = False

    def __enter__(self):
        if self._ctx is not None:
            _stack().append(self._ctx)
            self._pushed = True
        return self._ctx

    def __exit__(self, *exc):
        if self._pushed:
            _stack().pop()
        return False


class Span:
    """A started-but-unfinished span handle. `end()` stamps ``t1`` and
    records it; safe to call from a different thread than the starter."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id", "t0",
                 "attrs", "_done")

    def __init__(self, name, cat, trace_id, span_id, parent_id, attrs):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.t0 = time.perf_counter()
        self._done = False

    @property
    def context(self):
        return (self.trace_id, self.span_id)

    def end(self, t1: float | None = None, **attrs) -> None:
        if self._done:  # idempotent: racing future callbacks end once
            return
        self._done = True
        if attrs:
            self.attrs = {**self.attrs, **attrs}
        _record(self.name, self.cat, self.trace_id, self.span_id,
                self.parent_id, self.t0,
                time.perf_counter() if t1 is None else t1, self.attrs)


class _NullSpan:
    """The disabled-path span: every operation is a no-op, every id None."""

    __slots__ = ()
    name = cat = trace_id = span_id = parent_id = None
    attrs: dict = {}
    t0 = 0.0
    context = None

    def end(self, t1=None, **attrs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _SpanCM:
    """Live span context manager (enabled path): parents to the thread's
    current context, pushes its own, and mirrors the interval into a
    `jax.profiler.TraceAnnotation` named scope."""

    __slots__ = ("_name", "_cat", "_attrs", "_span", "_annot")

    def __init__(self, name, cat, attrs):
        self._name = name
        self._cat = cat
        self._attrs = attrs
        self._span = None
        self._annot = None

    def __enter__(self) -> Span:
        parent = current_context()
        sp = Span(
            self._name,
            self._cat,
            parent[0] if parent else _next_id(),
            _next_id(),
            parent[1] if parent else None,
            self._attrs,
        )
        _stack().append(sp.context)
        try:
            import jax

            self._annot = jax.profiler.TraceAnnotation(self._name)
            self._annot.__enter__()
        except Exception:  # profiler backend unavailable: spans still record
            self._annot = None
        self._span = sp
        return sp

    def __exit__(self, exc_type, exc, tb):
        if self._annot is not None:
            self._annot.__exit__(exc_type, exc, tb)
        _stack().pop()
        if exc_type is not None:
            self._span.attrs = {**self._span.attrs, "error": exc_type.__name__}
        self._span.end()
        return False


def span(name: str, *, cat: str = "obs", **attrs):
    """``with span("dispatch", bucket="3x224x224") as sp:`` — a live span on
    the current thread (module docstring). Disabled: a shared no-op."""
    if not _STATE.enabled:
        return NULL_SPAN
    return _SpanCM(name, cat, attrs)


def start_span(name: str, *, cat: str = "obs", parent=None, **attrs):
    """Start a DETACHED span (not on the thread-local stack): the caller
    owns ending it, possibly from another thread. ``parent=None`` starts a
    fresh trace unless the current thread has a live context."""
    if not _STATE.enabled:
        return NULL_SPAN
    if parent is None:
        parent = current_context()
    return Span(
        name,
        cat,
        parent[0] if parent else _next_id(),
        _next_id(),
        parent[1] if parent else None,
        attrs,
    )


def record_span(name: str, t0: float, t1: float, *, parent=None,
                cat: str = "obs", **attrs) -> None:
    """Record a span retroactively from perf_counter timestamps the caller
    already holds (queue waits, batch service intervals)."""
    if not _STATE.enabled:
        return
    _record(name, cat,
            parent[0] if parent else _next_id(), _next_id(),
            parent[1] if parent else None, t0, t1, attrs)


def _record(name, cat, trace_id, span_id, parent_id, t0, t1, attrs) -> None:
    _STATE.ring.append({
        "name": name,
        "cat": cat,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "t0": t0,
        "t1": t1,
        "thread": threading.current_thread().name,
        "attrs": attrs,
    })


def spans() -> list[dict]:
    """Snapshot of the recorded span ring (oldest first)."""
    return list(_STATE.ring)


def clear_spans() -> None:
    _STATE.ring.clear()


def spans_to_events(rows, *, pid: int, clock_offset_s: float = 0.0,
                    process_name: str | None = None) -> list[dict]:
    """Span-ring dicts → Chrome trace events, attributable to ``pid``.

    The cross-process half of trace export: a pod router calls this on
    the span ring each worker ships at shutdown, with ``clock_offset_s``
    the worker→router perf_counter offset estimated from heartbeat RTTs
    — so spans minted on N different monotonic clocks land on ONE shared
    timeline (`ts`/`dur` in µs, offset applied). ``process_name`` adds
    the Perfetto process-label metadata row. Thread-name metadata is
    emitted per distinct thread seen in ``rows``."""
    tids: dict[str, int] = {}
    events: list[dict] = []
    for r in rows:
        tid = tids.setdefault(r["thread"], len(tids) + 1)
        events.append({
            "name": r["name"],
            "cat": r["cat"],
            "ph": "X",
            "ts": (r["t0"] + clock_offset_s) * 1e6,
            "dur": max(0.0, (r["t1"] - r["t0"]) * 1e6),
            "pid": pid,
            "tid": tid,
            "args": {
                "trace_id": r["trace_id"],
                "span_id": r["span_id"],
                "parent_id": r["parent_id"],
                **r["attrs"],
            },
        })
    events.extend(
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": thread}}
        for thread, tid in tids.items()
    )
    if process_name is not None:
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": process_name}})
    return events


def export_chrome_trace(path: str, extra_events: list[dict] | None = None) -> str:
    """Write the span ring as Chrome trace-event JSON (Perfetto-loadable).

    Every span becomes one complete (``"ph": "X"``) event: ``ts``/``dur``
    in microseconds on the perf_counter timebase, ``pid`` = this process,
    ``tid`` = a stable per-thread-name integer, and the trace identity
    (``trace_id``/``span_id``/``parent_id``) plus user attrs under
    ``args``. `scripts/trace_report.py` consumes this file; so does
    ``chrome://tracing`` / https://ui.perfetto.dev. Returns ``path``."""
    events = spans_to_events(spans(), pid=os.getpid())
    if extra_events:
        events.extend(extra_events)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    dirname = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirname, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path
