"""Declarative SLOs + rolling-window burn rates (health-plane pillar 3).

An *objective* is a per-bucket target over a rolling window: p99 latency,
error rate, health rate (`SLObjectives`). A *burn rate* is how fast the
window is consuming its error budget — 1.0 means exactly on budget, >1
means the objective will be violated if the window's behavior persists
(the standard SRE multi-window formulation, collapsed to one window):

- latency burn  = fraction of requests over the p99 target / 0.01 (the 1%
  a p99 objective budgets for),
- error burn    = observed error rate / error-rate budget,
- health burn   = observed unhealthy fraction / (1 - health-rate target),
- burn_rate     = max of the enabled components (disabled ones — target
  0/unset — contribute nothing).

`SLOTracker` keeps one deque of ``(t, latency_s, ok, healthy)`` per bucket,
prunes it to ``window_s`` on every read, and surfaces the results three
ways, all fed from the SAME floats so they can be cross-checked exactly:

- ``wam_tpu_slo_*`` registry gauges (→ ``/metrics``), republished at most
  once a second from the note path so scrapes see live values;
- an ``slo_status`` row in the v2 JSONL ledger
  (`serve.metrics.write_slo_status` wraps `snapshot_row`);
- a routing penalty: `penalty_s` maps burn > 1 onto seconds added to the
  fleet's load score, so a replica burning its budget sheds load *before*
  it dies (`serve.fleet.FleetServer._score`).

Objective policies are declared as CLI-friendly strings in
``ServeConfig.slo`` (`parse_slo`): ``"p99_ms=250,error_rate=0.01"`` applies
one objective set to every bucket; per-bucket overrides are
``;``-separated with a bucket-key prefix —
``"*:p99_ms=250;3x32x32:p99_ms=100,health_rate=0.99"``.

**QoS class dimension** (the serve admission lanes): a key may carry an
``@<class>`` suffix — ``"*@interactive:p99_ms=50;*:p99_ms=500"`` holds
interactive traffic to a tight p99 while batch traffic rides the loose
default. The serve worker notes each request with its class
(``note(bkey, ..., qos="interactive")``), which lands the sample in the
``<bucket>@<class>`` window, so burn rates stay per bucket×class.
Objective resolution for a classed window walks ``bucket@class`` →
``*@class`` → ``bucket`` → ``*``; class-less notes keep their historical
plain-bucket windows and ladder. `penalty_s` aggregates a bucket's
windows across classes (max burn), so the fleet's per-bucket routing
penalty sees a violated class even when the bucket aggregate looks fine.

**Tenant dimension** (multi-tenant serving): a classed key may carry a
second suffix — ``bucket@class@tenant`` — so each tenant's traffic burns
its own window (``note(bkey, ..., qos="interactive", tenant="acme")``).
Resolution for a tenant window walks the exact key →
``*@class@tenant`` → ``bucket@class`` → ``*@class`` → ``bucket`` → ``*``,
so a tenant with no dedicated objective inherits its class's. `penalty_s`
already aggregates by bucket prefix, so tenant windows feed the same
routing penalty.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass

from wam_tpu.obs.registry import registry as _registry

__all__ = ["SLObjectives", "parse_slo", "SLOTracker", "PENALTY_SCALE_S"]

# penalty_s = max(0, burn_rate - 1) * this — at burn 2x the replica looks
# one EMA-seed's worth of service time busier than it is, enough to lose
# routing ties without starving it outright
PENALTY_SCALE_S = 0.05

# republish gauges from the note path at most this often (full window
# stats per note would sort the latency sample on every request)
_PUBLISH_MIN_INTERVAL_S = 1.0


@dataclass(frozen=True)
class SLObjectives:
    """One bucket's objectives over a rolling window. A zero/unset target
    disables that component (its burn contributes 0)."""

    p99_ms: float = 0.0
    error_rate: float = 0.0
    health_rate: float = 0.0
    window_s: float = 60.0
    # anytime serving (wam_tpu.anytime): confidence-at-delivery floor —
    # burn counts requests delivered BELOW this confidence against a 1%
    # budget (the p99 convention: an anytime server may hand out up to 1%
    # of its maps under the floor before the objective burns)
    min_confidence: float = 0.0


def parse_slo(spec) -> dict | None:
    """Parse a ``ServeConfig.slo`` policy string into a ``{bucket_key:
    SLObjectives}`` map ('*' = default). Keys may carry an ``@<class>``
    QoS suffix (``*@interactive``, ``3x32x32@batch`` — module docstring).
    Accepts an existing map or a bare `SLObjectives` (becomes the '*'
    entry); returns None for empty specs."""
    if spec is None or spec == "":
        return None
    if isinstance(spec, SLObjectives):
        return {"*": spec}
    if isinstance(spec, dict):
        return {
            str(k): (v if isinstance(v, SLObjectives) else SLObjectives(**v))
            for k, v in spec.items()
        }
    policy: dict[str, SLObjectives] = {}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        bucket = "*"
        body = part
        # a bucket prefix is "<key>:"; objective keys always carry '='
        if ":" in part and "=" not in part.split(":", 1)[0]:
            bucket, body = part.split(":", 1)
            bucket = bucket.strip()
        kwargs = {}
        for kv in body.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k = k.strip()
            if k not in ("p99_ms", "error_rate", "health_rate", "window_s",
                         "min_confidence"):
                raise ValueError(f"unknown SLO objective {k!r} in {spec!r}")
            kwargs[k] = float(v)
        parts = bucket.split("@")
        if any(not p for p in parts[1:]):
            raise ValueError(f"empty QoS class in SLO key {bucket!r}")
        policy[bucket] = SLObjectives(**kwargs)
    return policy or None


def _label(value) -> str:
    return "-" if value is None else str(value)


_g_burn = _registry.gauge(
    "wam_tpu_slo_burn_rate",
    "error-budget burn rate over the rolling window (max component; "
    ">1 = violating)", labels=("replica", "bucket"))
_g_err = _registry.gauge(
    "wam_tpu_slo_error_rate", "observed error rate over the window",
    labels=("replica", "bucket"))
_g_health = _registry.gauge(
    "wam_tpu_slo_health_rate", "observed healthy fraction over the window",
    labels=("replica", "bucket"))
_g_p99 = _registry.gauge(
    "wam_tpu_slo_p99_seconds", "observed p99 latency over the window",
    labels=("replica", "bucket"))
_g_n = _registry.gauge(
    "wam_tpu_slo_window_requests", "requests inside the rolling window",
    labels=("replica", "bucket"))
_g_conf = _registry.gauge(
    "wam_tpu_slo_confidence",
    "mean anytime confidence-at-delivery over the window",
    labels=("replica", "bucket"))


class SLOTracker:
    """Rolling-window SLO accounting for one server (fleet replicas each
    carry their own). ``policy`` is anything `parse_slo` accepts; a None
    policy tracks nothing and burns nothing. Thread-safe; ``now`` is
    injectable for deterministic tests."""

    def __init__(self, policy, *, replica_id=None):
        self.policy = parse_slo(policy) or {}
        self.replica_id = replica_id
        self._rl = _label(replica_id)
        self._lock = threading.Lock()
        self._windows: dict[str, deque] = {}
        self._last_publish = 0.0

    def objectives_for(self, bucket_key: str) -> SLObjectives | None:
        """Policy lookup for a (possibly suffixed) window key:
        ``bucket@class`` → ``*@class`` → ``bucket`` → ``*``, and for a
        tenant window (``bucket@class@tenant``) the exact key →
        ``*@class@tenant`` → ``bucket@class`` → ``*@class`` → ``bucket``
        → ``*`` (module docstring)."""
        obj = self.policy.get(bucket_key)
        if obj is not None:
            return obj
        if "@" in bucket_key:
            bare, rest = bucket_key.split("@", 1)
            candidates = [f"*@{rest}"]
            if "@" in rest:
                qos = rest.split("@", 1)[0]
                candidates += [f"{bare}@{qos}", f"*@{qos}"]
            candidates.append(bare)
            for k in candidates:
                obj = self.policy.get(k)
                if obj is not None:
                    return obj
        return self.policy.get("*")

    # -- note path (serve worker) -------------------------------------------

    def note(self, bucket_key: str, *, latency_s: float = 0.0,
             ok: bool = True, healthy: bool = True,
             confidence: float = 1.0,
             now: float | None = None, qos: str | None = None,
             tenant: str | None = None) -> None:
        """One resolved request. ``qos`` lands the sample in the
        ``bucket@class`` window and ``tenant`` (only meaningful with a
        class) narrows it to ``bucket@class@tenant`` (module docstring).
        ``confidence`` is the anytime confidence-at-delivery (1.0 for
        full-n results, so plain servers never burn a confidence budget).
        Errors and expiries go through `note_error` (they have no
        meaningful latency sample)."""
        key = f"{bucket_key}@{qos}" if qos else bucket_key
        if qos and tenant:
            key = f"{key}@{tenant}"
        if self.objectives_for(key) is None:
            return
        now = time.perf_counter() if now is None else now
        publish = False
        with self._lock:
            self._windows.setdefault(key, deque()).append(
                (now, float(latency_s), bool(ok), bool(healthy),
                 float(confidence)))
            if now - self._last_publish >= _PUBLISH_MIN_INTERVAL_S:
                self._last_publish = now
                publish = True
        if publish:
            self.snapshot_row(now=now)

    def note_error(self, bucket_key: str, n: int = 1,
                   now: float | None = None, qos: str | None = None,
                   tenant: str | None = None) -> None:
        """Failed/expired requests: counted against the error AND health
        budgets, no latency sample."""
        key = f"{bucket_key}@{qos}" if qos else bucket_key
        if qos and tenant:
            key = f"{key}@{tenant}"
        if self.objectives_for(key) is None:
            return
        now = time.perf_counter() if now is None else now
        with self._lock:
            w = self._windows.setdefault(key, deque())
            for _ in range(int(n)):
                w.append((now, 0.0, False, False, 0.0))

    # -- window reads -------------------------------------------------------

    def _pruned(self, bucket_key: str, now: float) -> list:
        """Prune + copy one bucket's window. Caller holds no lock."""
        obj = self.objectives_for(bucket_key)
        horizon = now - (obj.window_s if obj else 60.0)
        with self._lock:
            w = self._windows.get(bucket_key)
            if w is None:
                return []
            while w and w[0][0] < horizon:
                w.popleft()
            return list(w)

    def bucket_stats(self, bucket_key: str, now: float | None = None) -> dict:
        """The window's observed rates + burn components, computed once and
        shared verbatim by the gauges, the ledger row, and the routing
        penalty (the exact-round-trip invariant). p99 is reported in
        SECONDS everywhere — no ms<->s conversion between the sinks."""
        now = time.perf_counter() if now is None else now
        obj = self.objectives_for(bucket_key) or SLObjectives()
        window = self._pruned(bucket_key, now)
        n = len(window)
        if n == 0:
            return {"n": 0, "error_rate": 0.0, "health_rate": 1.0,
                    "p99_s": 0.0, "mean_confidence": 1.0, "burn_rate": 0.0}
        errors = sum(1 for _, _, ok, _, _ in window if not ok)
        unhealthy = sum(1 for _, _, _, h, _ in window if not h)
        error_rate = errors / n
        health_rate = 1.0 - unhealthy / n
        lats = sorted(lat for _, lat, ok, _, _ in window if ok)
        confs = [c for _, _, ok, _, c in window if ok]
        mean_conf = sum(confs) / len(confs) if confs else 1.0
        if lats:
            i = min(len(lats) - 1, int(round(0.99 * (len(lats) - 1))))
            p99_s = lats[i]
        else:
            p99_s = 0.0
        burn = 0.0
        if obj.error_rate > 0.0:
            burn = max(burn, error_rate / obj.error_rate)
        if obj.health_rate > 0.0:
            allowed = max(1.0 - obj.health_rate, 1e-9)
            burn = max(burn, (1.0 - health_rate) / allowed)
        if obj.p99_ms > 0.0 and lats:
            over = sum(1 for lat in lats if lat > obj.p99_ms / 1e3)
            burn = max(burn, (over / len(lats)) / 0.01)
        if obj.min_confidence > 0.0 and confs:
            # the p99 convention: 1% of delivered maps may land under the
            # confidence floor before the objective burns (docstring)
            under = sum(1 for c in confs if c < obj.min_confidence)
            burn = max(burn, (under / len(confs)) / 0.01)
        return {"n": n, "error_rate": error_rate, "health_rate": health_rate,
                "p99_s": p99_s, "mean_confidence": mean_conf,
                "burn_rate": burn}

    def burn_rate(self, bucket_key: str, now: float | None = None) -> float:
        return self.bucket_stats(bucket_key, now=now)["burn_rate"]

    def penalty_s(self, bucket_key: str, now: float | None = None) -> float:
        """Routing penalty: seconds added to the fleet's load score while
        this bucket burns over budget (0 at/below burn 1.0). Takes the MAX
        burn across the bucket's windows — the aggregate window plus every
        per-class one — so one violated class penalizes the bucket even
        when the other class dilutes the aggregate."""
        with self._lock:
            keys = [k for k in self._windows
                    if k == bucket_key or k.startswith(bucket_key + "@")]
        if not keys:
            keys = [bucket_key]
        burn = max(self.burn_rate(k, now=now) for k in keys)
        return max(0.0, burn - 1.0) * PENALTY_SCALE_S

    # -- snapshot (gauges + ledger row, same floats) ------------------------

    def snapshot_row(self, publish: bool = True,
                     now: float | None = None) -> dict:
        """Per-bucket stats as an ``slo_status`` ledger-row body, publishing
        the same float values to the ``wam_tpu_slo_*`` gauges when asked —
        one computation, two sinks, exact agreement by construction.
        (`serve.metrics.write_slo_status` adds the schema envelope.)"""
        now = time.perf_counter() if now is None else now
        with self._lock:
            keys = sorted(self._windows)
        buckets = {}
        for bkey in keys:
            st = self.bucket_stats(bkey, now=now)
            buckets[bkey] = st
            if publish:
                _g_burn.set(st["burn_rate"], replica=self._rl, bucket=bkey)
                _g_err.set(st["error_rate"], replica=self._rl, bucket=bkey)
                _g_health.set(st["health_rate"], replica=self._rl, bucket=bkey)
                _g_p99.set(st["p99_s"], replica=self._rl, bucket=bkey)
                _g_n.set(st["n"], replica=self._rl, bucket=bkey)
                _g_conf.set(st["mean_confidence"], replica=self._rl,
                            bucket=bkey)
        row = {
            "metric": "slo_status",
            "replica_id": self.replica_id,
            "objectives": {k: asdict(v) for k, v in self.policy.items()},
            "buckets": buckets,
            "timestamp": time.time(),
        }
        tenants = sorted({k.rsplit("@", 1)[1] for k in keys
                          if k.count("@") >= 2})
        if tenants:
            row["tenants"] = tenants
        return row
