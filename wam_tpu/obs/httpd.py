"""Optional stdlib-http ``/metrics`` endpoint for the obs registry.

`start_metrics_server(port)` spins up a `ThreadingHTTPServer` on a daemon
thread serving `registry.render_prom()` at ``GET /metrics`` (anything
else 404s). Port 0 binds an ephemeral port — the returned server's
``server_port`` tells you which; `FleetServer(prom_port=...)` and
``bench_serve --prom-port`` use this. No dependencies beyond the stdlib:
this is deliberately NOT a prometheus_client integration, just the text
exposition over the simplest possible server.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from wam_tpu.obs.registry import registry

__all__ = ["start_metrics_server", "stop_metrics_server"]


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path.rstrip("/") not in ("/metrics", ""):
            self.send_error(404)
            return
        body = registry.render_prom().encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # keep scrape noise off stderr
        pass


def start_metrics_server(port: int, host: str = "127.0.0.1"):
    """Serve ``/metrics`` on ``host:port`` from a daemon thread. Returns
    the `ThreadingHTTPServer` (read ``.server_port``; call
    `stop_metrics_server` or ``.shutdown()`` to stop)."""
    server = ThreadingHTTPServer((host, port), _MetricsHandler)
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever,
                         name="obs-metrics-http", daemon=True)
    t.start()
    server._obs_thread = t
    return server


def stop_metrics_server(server) -> None:
    server.shutdown()
    server.server_close()
    t = getattr(server, "_obs_thread", None)
    if t is not None:
        t.join(timeout=5)
