"""Declared observability schema: the single source of truth for metric
instrument names and v2 ledger row types.

Dashboards, alert rules, and ledger readers key on these literals, so
they are an *external contract*: renaming an instrument or adding a row
type without updating this registry silently breaks consumers. The
`schema-drift` lint rule (``python -m wam_tpu.lint --rules schema-drift``)
AST-scans the tree and flags any ``registry.counter/gauge/histogram``
name or ``{"metric": ...}`` row literal that is not declared here — so
the workflow for a new instrument is: declare it here first, then wire
it up.

Both containers are pure string literals on purpose: the lint rule
reads this file with ``ast.parse`` (never imports it), which only works
if every entry is a constant.
"""

from __future__ import annotations

# Prometheus-style instrument names, grouped by subsystem family.
METRIC_NAMES = frozenset({
    # serve admission / batching (serve/runtime.py, serve/metrics.py)
    "wam_tpu_serve_batch_occupancy",
    "wam_tpu_serve_batches_total",
    "wam_tpu_serve_compile_total",
    "wam_tpu_serve_completed_total",
    "wam_tpu_serve_ema_service_seconds",
    "wam_tpu_serve_expired_total",
    "wam_tpu_serve_failed_total",
    "wam_tpu_serve_fallback_batches_total",
    "wam_tpu_serve_latency_seconds",
    "wam_tpu_serve_ledger_corrupt_lines_total",
    "wam_tpu_serve_queue_depth",
    "wam_tpu_serve_rejected_total",
    "wam_tpu_serve_restarts_total",
    "wam_tpu_serve_service_seconds",
    "wam_tpu_serve_submitted_total",
    # multi-model residency (serve/models.py)
    "wam_tpu_serve_model_pagein_seconds",
    "wam_tpu_serve_model_pagein_total",
    "wam_tpu_serve_model_pageout_total",
    "wam_tpu_serve_model_resident",
    "wam_tpu_serve_model_resident_bytes",
    # serve result cache (serve/result_cache.py)
    "wam_tpu_serve_cache_bytes",
    "wam_tpu_serve_cache_entries",
    "wam_tpu_serve_cache_evictions_total",
    "wam_tpu_serve_cache_hits_total",
    "wam_tpu_serve_cache_misses_total",
    # fleet (serve/fleet.py)
    "wam_tpu_fleet_compile_count",
    "wam_tpu_fleet_replica_deaths_total",
    "wam_tpu_fleet_warmup_seconds",
    # numeric health (obs/health.py)
    "wam_tpu_health_checks_total",
    "wam_tpu_health_consecutive_nonfinite",
    "wam_tpu_health_grad_norm",
    "wam_tpu_health_max_abs",
    "wam_tpu_health_nonfinite_batches_total",
    "wam_tpu_health_nonfinite_values_total",
    "wam_tpu_health_quarantined",
    "wam_tpu_health_saturation_fraction",
    # HBM budget / admission (obs/memory.py)
    "wam_tpu_memory_admission_rejects_total",
    "wam_tpu_memory_bucket_watermark_bytes",
    "wam_tpu_memory_budget_bytes",
    "wam_tpu_memory_device_bytes_in_use",
    "wam_tpu_memory_staged_bytes",
    # SLO tracker (obs/slo.py)
    "wam_tpu_slo_burn_rate",
    "wam_tpu_slo_confidence",
    "wam_tpu_slo_error_rate",
    "wam_tpu_slo_health_rate",
    "wam_tpu_slo_p99_seconds",
    "wam_tpu_slo_window_requests",
    # anytime attribution (anytime/, serve/metrics.py)
    "wam_tpu_anytime_batches_total",
    "wam_tpu_anytime_confidence",
    "wam_tpu_anytime_deadline_partial_total",
    "wam_tpu_anytime_early_exit_total",
    "wam_tpu_anytime_samples_fraction",
    "wam_tpu_anytime_strides_total",
    # retry / hedging (serve/retry.py)
    "wam_tpu_retry_attempts_total",
    "wam_tpu_retry_exhausted_total",
    "wam_tpu_retry_hedge_wins_total",
    "wam_tpu_retry_hedges_total",
    "wam_tpu_retry_retries_total",
    # pod router / workers (pod/)
    "wam_tpu_pod_autoscale_total",
    "wam_tpu_pod_requests_completed_total",
    "wam_tpu_pod_worker_deaths_total",
    "wam_tpu_pod_worker_drain_seconds",
    "wam_tpu_pod_worker_restarts_total",
    "wam_tpu_pod_workers_alive",
    # pod wire transport (pod/netchannel.py, pod/metrics.py)
    "wam_tpu_pod_net_handshakes_total",
    "wam_tpu_pod_net_heartbeats_coalesced_total",
    "wam_tpu_pod_net_host_rtt_seconds",
    "wam_tpu_pod_net_messages_total",
    "wam_tpu_pod_net_registry_stream_bytes_total",
    "wam_tpu_pod_net_rx_bytes_total",
    "wam_tpu_pod_net_tx_bytes_total",
    # compile-artifact registry (registry/)
    "wam_tpu_registry_artifacts_total",
    "wam_tpu_registry_hydrations_total",
    "wam_tpu_registry_schedules_total",
    # online schedule tuner (tune/online.py, tune/mix.py)
    "wam_tpu_tune_drift_ratio",
    "wam_tpu_tune_promotions_total",
    "wam_tpu_tune_sweeps_total",
    # compile observability + fan engine + chaos + stager
    "wam_tpu_chaos_injected_total",
    "wam_tpu_compile_aot_events_total",
    "wam_tpu_compile_jit_traces_total",
    "wam_tpu_fan_result_fetches_total",
    "wam_tpu_stager_h2d_bytes_total",
})

# v2 JSONL ledger row discriminators: the "metric" field of every row
# appended by obs ledgers (SCHEMA_VERSION = 2 in serve/metrics.py).
LEDGER_ROW_TYPES = frozenset({
    "fleet_summary",
    "obs_snapshot",
    "partial_result",
    "pod_autoscale",
    "pod_host",
    "pod_summary",
    "pod_worker",
    "registry_hydration",
    "replica_restart",
    "result_cache",
    "schedule_drift",
    "schedule_promotion",
    "serve_batch",
    "serve_summary",
    "slo_status",
    "worker_restart",
})
