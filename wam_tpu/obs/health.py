"""Numeric-health monitoring (health-plane pillar 1).

WAM's output quality rests on a numerically delicate chain — differentiable
IDWT reconstruction, bf16 synthesis with f32 accumulation, gradient
estimators that need explicit ``nan_to_num`` hygiene — and this module is
what watches it in production. The design constraint is the same one the
eval fan engine lives by: **zero extra result fetches**. `health_stats` is
a pure-jax reduction producing one tiny fixed-size vector that rides
*inside* the result tree already being fetched:

- fused into the serving graph when the entry was built with
  ``serve_entry(with_health=True)`` (`serve.entry.jit_entry`) — the stats
  are one more output leaf of the same compiled program;
- dispatched post-hoc by the serve worker (`batch_stats`) for entries that
  are not health-fused (fake entries, user callables) — a second tiny
  *dispatch*, still harvested in the worker's single existing
  ``device_get``;
- piggybacked onto the fan engine's single `device_fetch`
  (`evalsuite.fan.run_fan`): the fetched tree becomes ``(out, stats)`` and
  the fetch count stays exactly 1 (`fetch_scope` pins this).

The host side (`summarize`, `publish_stats`, `HealthMonitor`) turns the
vector into ``wam_tpu_health_*`` registry series and the quarantine
decision: N consecutive non-finite batches mark a replica degraded —
`serve.fleet.FleetServer` routes around it like a death, but unlike a
death it is *recoverable*: after ``recovery_s`` the replica accepts probe
traffic again and one healthy batch clears the quarantine.

Like the rest of `wam_tpu.obs`, this module imports only the stdlib at
import time; jax/numpy are imported lazily inside the device-side helpers.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass

from wam_tpu.obs import tracing as _tracing
from wam_tpu.obs.registry import registry as _registry

__all__ = [
    "HEALTH_VEC_SIZE",
    "SAT_THRESHOLD",
    "health_stats",
    "combine_output_grads",
    "batch_stats",
    "summarize",
    "publish_stats",
    "HealthConfig",
    "HealthMonitor",
    "fan_health_enabled",
    "set_fan_health",
]

# The on-device vector layout (f32, fixed size so every health-fused graph
# has the same extra output shape):
#   [0] non-finite element count (output tree + gradient tree when given)
#   [1] total inexact elements behind [0]
#   [2] saturation count over the OUTPUT: |v| >= SAT_THRESHOLD
#   [3] output element count (denominator of the saturation fraction)
#   [4] max |v| over the output
#   [5] sum of squares over the GRADIENTS (output when no gradient tree) —
#       grad_norm = sqrt of this, the per-call grad-norm summary
HEALTH_VEC_SIZE = 6

# Engines max-normalize attribution mosaics into [0, 1]; a value this close
# to the top of the range counts as saturated (a clipped/flat attribution).
SAT_THRESHOLD = 0.995

# Fan-engine health piggyback switch (module-level: the fan has no server
# object to carry per-instance config). Gated on the obs enabled flag too.
_FAN_HEALTH = True


def set_fan_health(enabled: bool) -> None:
    global _FAN_HEALTH
    _FAN_HEALTH = bool(enabled)


def fan_health_enabled() -> bool:
    """Whether `evalsuite.fan.run_fan` should piggyback health stats onto
    its single fetch: the module switch AND the obs enabled flag."""
    return _FAN_HEALTH and _tracing._STATE.enabled


# -- device side (pure jax, usable inside jit) ------------------------------


def _inexact_leaves(tree):
    import jax
    import jax.numpy as jnp

    return [
        l for l in jax.tree_util.tree_leaves(tree)
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact)
    ]


def health_stats(out, grads=None, *, sat_threshold: float = SAT_THRESHOLD):
    """The on-device health reduction: one ``(HEALTH_VEC_SIZE,)`` f32
    vector over an attribution output tree (and optionally the coefficient
    gradients behind it). Pure jax — traceable inside a serving entry so
    the stats are one more leaf of the already-fetched result, never a
    second fetch. Counts are f32 sums (exact to 2**24 elements — far above
    any serve batch; a giant fan tree may round, which cannot flip the
    finite/non-finite decision)."""
    import jax.numpy as jnp

    leaves = _inexact_leaves(out)
    if not leaves:
        return jnp.zeros((HEALTH_VEC_SIZE,), jnp.float32)
    gleaves = _inexact_leaves(grads) if grads is not None else []
    if not gleaves:
        grads = None  # no gradient tree (or nothing inexact in it)
        gleaves = leaves

    def _f(x):
        return jnp.asarray(x, jnp.float32)

    nonfinite = sum(_f(jnp.sum(~jnp.isfinite(l))) for l in leaves)
    total = float(sum(l.size for l in leaves))
    if grads is not None:
        nonfinite = nonfinite + sum(_f(jnp.sum(~jnp.isfinite(l)))
                                    for l in gleaves)
        total += float(sum(l.size for l in gleaves))
    # NaN propagates through abs/>= as False, so a poisoned batch shows up
    # in the non-finite count, not a phantom saturation count
    sat = sum(_f(jnp.sum(jnp.abs(l) >= sat_threshold)) for l in leaves)
    out_count = float(sum(l.size for l in leaves))
    max_abs = jnp.stack([jnp.max(jnp.abs(_f(l))) for l in leaves]).max()
    sumsq = sum(jnp.sum(jnp.square(_f(l))) for l in gleaves)
    return jnp.stack([
        nonfinite, jnp.float32(total), sat, jnp.float32(out_count),
        max_abs, sumsq,
    ])


def combine_output_grads(out_vec, grad_vec):
    """Merge an output-tree vector with a gradient-tree vector into one:
    non-finite/total pool both trees, saturation/max stay output-only, the
    grad-norm sum-of-squares comes from the gradients. Used by health-fused
    engine entries (`core.engine.WamEngine.attribute_with_health`)."""
    import jax.numpy as jnp

    return jnp.stack([
        out_vec[0] + grad_vec[0],
        out_vec[1] + grad_vec[1],
        out_vec[2], out_vec[3], out_vec[4],
        grad_vec[5],
    ])


_stats_jit = None


def batch_stats(out):
    """Dispatch the health reduction on-device for a result tree that is
    NOT health-fused (fake entries, arbitrary callables). Returns a device
    array future — the caller harvests it together with the result in its
    one existing ``device_get`` (`serve.runtime._complete`). The jit here
    is a plain one (invisible to the compile sentinel on purpose: these
    retraces are per result *structure*, not serving-entry cache misses)."""
    global _stats_jit
    import jax

    if _stats_jit is None:
        _stats_jit = jax.jit(lambda tree: health_stats(tree))
    return _stats_jit(out)


# -- host side --------------------------------------------------------------


def summarize(vec) -> dict:
    """Host-side view of a fetched health vector."""
    import numpy as np

    v = [float(x) for x in np.asarray(vec).reshape(-1)]
    nonfinite, total, sat, out_n, max_abs, sumsq = v[:HEALTH_VEC_SIZE]
    return {
        "nonfinite": int(nonfinite),
        "total": int(total),
        "finite": nonfinite == 0.0,
        "sat_frac": sat / out_n if out_n else 0.0,
        "max_abs": max_abs,
        # sqrt(NaN) is NaN, which is the honest grad norm of a poisoned batch
        "grad_norm": math.sqrt(sumsq) if sumsq == sumsq and sumsq >= 0.0
        else float("nan"),
    }


def _label(value) -> str:
    return "-" if value is None else str(value)


_c_checks = _registry.counter(
    "wam_tpu_health_checks_total", "health vectors evaluated",
    labels=("source", "replica"))
_c_bad_batches = _registry.counter(
    "wam_tpu_health_nonfinite_batches_total",
    "batches whose output carried any NaN/Inf", labels=("source", "replica"))
_c_bad_values = _registry.counter(
    "wam_tpu_health_nonfinite_values_total",
    "individual non-finite elements observed", labels=("source", "replica"))
_g_sat = _registry.gauge(
    "wam_tpu_health_saturation_fraction",
    "fraction of output elements at/above the saturation threshold",
    labels=("source", "replica", "bucket"))
_g_maxabs = _registry.gauge(
    "wam_tpu_health_max_abs", "max |output| of the last checked batch",
    labels=("source", "replica", "bucket"))
_g_gnorm = _registry.gauge(
    "wam_tpu_health_grad_norm", "grad-norm summary of the last checked batch",
    labels=("source", "replica", "bucket"))
_g_quarantined = _registry.gauge(
    "wam_tpu_health_quarantined",
    "1 while the replica is quarantined by the health monitor",
    labels=("replica",))
_g_consecutive = _registry.gauge(
    "wam_tpu_health_consecutive_nonfinite",
    "current run of consecutive non-finite batches", labels=("replica",))


def publish_stats(vec, *, source: str, replica=None, bucket=None) -> bool:
    """Publish one fetched health vector to the ``wam_tpu_health_*`` series.
    Returns whether the batch was finite (the quarantine input)."""
    s = summarize(vec)
    src, rl, bk = _label(source), _label(replica), _label(bucket)
    _c_checks.inc(source=src, replica=rl)
    if not s["finite"]:
        _c_bad_batches.inc(source=src, replica=rl)
        _c_bad_values.inc(s["nonfinite"], source=src, replica=rl)
    _g_sat.set(s["sat_frac"], source=src, replica=rl, bucket=bk)
    _g_maxabs.set(s["max_abs"], source=src, replica=rl, bucket=bk)
    _g_gnorm.set(s["grad_norm"], source=src, replica=rl, bucket=bk)
    return s["finite"]


@dataclass(frozen=True)
class HealthConfig:
    """Quarantine policy knobs (`ServeConfig.health_*` surfaces them on the
    CLI). ``quarantine_after`` consecutive non-finite batches mark the
    replica degraded; after the recovery window it accepts probe traffic
    again and ``clear_after`` consecutive healthy batches clear the state
    (a bad probe re-arms it). The recovery window ESCALATES on every
    re-quarantine — ``recovery_s × backoff_factor^(arms-1)``, capped at
    ``max_recovery_s`` — and the escalation survives clears: a replica
    flapping between poisoned and healthy bursts would otherwise oscillate
    quarantine↔probation at a constant period forever, while escalating
    windows bound the transition count logarithmically (the hysteresis the
    chaos tests pin). Operators can forgive a fixed replica with
    `HealthMonitor.reset_escalation`."""

    enabled: bool = True
    quarantine_after: int = 3
    recovery_s: float = 30.0
    sat_threshold: float = SAT_THRESHOLD
    clear_after: int = 1
    backoff_factor: float = 2.0
    max_recovery_s: float = 300.0


class HealthMonitor:
    """Per-server quarantine state machine over the batch health stream.

    ``note(vec)`` is called by the serve worker once per harvested batch
    (before results are distributed, so routing observes the updated state
    no later than the client sees the result); ``ok()`` is read by the
    fleet router. Thread-safe; ``now`` is injectable for deterministic
    tests."""

    def __init__(self, config: HealthConfig | None = None, *, replica_id=None):
        self.config = config if config is not None else HealthConfig()
        self.replica_id = replica_id
        self._rl = _label(replica_id)
        self._lock = threading.Lock()
        self._consecutive = 0
        self._consecutive_ok = 0
        self._quarantined_at: float | None = None
        self._arms = 0  # quarantine entries ever; NOT reset on clear
        self.checks = 0
        self.nonfinite_batches = 0

    def _recovery_window_locked(self) -> float:
        """Current probation delay: the configured window escalated by how
        many times this replica has been quarantined (caller holds lock)."""
        c = self.config
        return min(c.max_recovery_s,
                   c.recovery_s * c.backoff_factor ** max(0, self._arms - 1))

    def note(self, vec, *, bucket=None, now: float | None = None) -> bool:
        """Record one batch's health vector; returns whether it was finite."""
        finite = publish_stats(vec, source="serve", replica=self.replica_id,
                               bucket=bucket)
        now = time.perf_counter() if now is None else now
        with self._lock:
            self.checks += 1
            if finite:
                self._consecutive = 0
                self._consecutive_ok += 1
                if self._consecutive_ok >= self.config.clear_after:
                    self._quarantined_at = None
            else:
                self.nonfinite_batches += 1
                self._consecutive_ok = 0
                self._consecutive += 1
                if self._consecutive >= self.config.quarantine_after:
                    # (re-)arm: a bad probe during probation restarts the
                    # recovery clock; only the None->armed transition
                    # escalates (a long bad burst is one quarantine, not N)
                    if self._quarantined_at is None:
                        self._arms += 1
                    self._quarantined_at = now
            _g_consecutive.set(self._consecutive, replica=self._rl)
            _g_quarantined.set(0.0 if self._quarantined_at is None else 1.0,
                               replica=self._rl)
        return finite

    @property
    def quarantined(self) -> bool:
        with self._lock:
            return self._quarantined_at is not None

    def ok(self, now: float | None = None) -> bool:
        """Routing predicate: healthy, or quarantined-but-probational
        (``recovery_s`` elapsed — let probe traffic through so a recovered
        replica can prove itself)."""
        if not self.config.enabled:
            return True
        with self._lock:
            if self._quarantined_at is None:
                return True
            now = time.perf_counter() if now is None else now
            return (now - self._quarantined_at) >= self._recovery_window_locked()

    def reset_escalation(self) -> None:
        """Operator forgiveness: drop the escalated recovery window back to
        the configured base (e.g. after the poisoning cause was fixed)."""
        with self._lock:
            self._arms = min(self._arms, 1)

    def describe(self) -> dict:
        with self._lock:
            return {
                "checks": self.checks,
                "nonfinite_batches": self.nonfinite_batches,
                "consecutive_nonfinite": self._consecutive,
                "quarantined": self._quarantined_at is not None,
                "quarantine_arms": self._arms,
                "recovery_window_s": self._recovery_window_locked(),
            }
