"""HBM memory accounting + cold-bucket admission (health-plane pillar 2).

Before this module, `serve/runtime.py` rejected on queue depth alone: a
cold bucket's first request triggers a warmup compile + buffer allocation
with no idea whether the chip has room for it. `MemoryBudget` closes that
gap with three pieces:

- **Watermark capture at warmup**: right after a bucket's warmup dispatch
  the server records the device's peak-bytes watermark (via
  ``device.memory_stats()`` where the backend exposes it — TPU and recent
  CPU runtimes do) with an *estimated-bytes fallback* computed from the
  bucket shape × max_batch × dtype plus the AOT executable size when one
  is cached (`estimate_entry_bytes`). A warm bucket is thereafter always
  admitted — its memory is already paid for.
- **Live-bytes gauge**: the stager's existing per-transfer byte counter
  feeds ``wam_tpu_memory_staged_bytes`` (`note_staged`, called from
  `pipeline.stager.put_committed`), so dashboards see transfer pressure
  next to the watermark series without any device sync.
- **Admission check**: `admit()` — called by `AttributionServer.submit`
  before queueing — projects ``bytes_in_use + estimate`` for a COLD bucket
  and rejects with a ``retry_after_s`` (surfaced as
  `serve.runtime.MemoryAdmissionError`, a `QueueFullError` subclass so the
  fleet treats it as ordinary backpressure) when the projection exceeds
  the configured budget.

``in_use_fn`` injects a simulated bytes-in-use reading for deterministic
tests (and for platforms with no ``memory_stats()`` at all, where the
fallback is the max recorded watermark).
"""

from __future__ import annotations

import os
import threading

from wam_tpu.obs.registry import registry as _registry

__all__ = [
    "MemoryBudget",
    "device_memory_stats",
    "estimate_entry_bytes",
    "executable_bytes",
    "note_staged",
]


def _label(value) -> str:
    return "-" if value is None else str(value)


_g_watermark = _registry.gauge(
    "wam_tpu_memory_bucket_watermark_bytes",
    "device peak-bytes watermark captured at the bucket's warmup "
    "(estimated when the backend exposes no memory_stats)",
    labels=("replica", "bucket"))
_g_in_use = _registry.gauge(
    "wam_tpu_memory_device_bytes_in_use",
    "device bytes in use as of the last admission projection",
    labels=("replica",))
_g_budget = _registry.gauge(
    "wam_tpu_memory_budget_bytes", "configured device memory budget",
    labels=("replica",))
_c_rejects = _registry.counter(
    "wam_tpu_memory_admission_rejects_total",
    "cold-bucket submits rejected because the projected watermark "
    "exceeded the budget", labels=("replica",))
_g_staged = _registry.gauge(
    "wam_tpu_memory_staged_bytes",
    "cumulative host->device bytes staged (live-bytes feed from the "
    "stager's transfer counter)")


def note_staged(nbytes: int) -> None:
    """Live-bytes feed: `pipeline.stager.put_committed` forwards every
    staged transfer's host-side byte count here (gauge mutation no-ops
    when obs is disabled, same as the counter next to it)."""
    _g_staged.inc(nbytes)


def device_memory_stats(device=None) -> dict | None:
    """``device.memory_stats()`` guarded against backends that lack it
    (the method may be missing, raise, or return None/{}). ``device=None``
    asks the first local device."""
    try:
        import jax

        if device is None:
            devs = jax.local_devices()
            if not devs:
                return None
            device = devs[0]
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def estimate_entry_bytes(bucket_shape, max_batch: int, itemsize: int = 4, *,
                         multiplier: float = 4.0, aot_bytes: int = 0) -> int:
    """Estimated-bytes fallback for a bucket's device footprint: the padded
    input batch (``max_batch × prod(shape) × itemsize``) times a working-set
    multiplier (input + output + ~2x transient coefficient pyramids — the
    IDWT chain holds per-level subband buffers live across the VJP), plus
    the AOT executable size when one is cached (`executable_bytes` of
    `pipeline.aot.aot_entry_path`). Deliberately coarse: it only has to be
    the right order of magnitude for admission, and only until the first
    real watermark replaces it."""
    elems = int(max_batch)
    for d in bucket_shape:
        elems *= int(d)
    return int(elems * int(itemsize) * float(multiplier)) + int(aot_bytes)


def executable_bytes(path: str | None) -> int:
    """Size of a serialized AOT executable (0 when absent). Callers resolve
    the path via `wam_tpu.pipeline.aot.aot_entry_path` — obs stays free of
    wam_tpu imports (the one-way dependency edge)."""
    if not path:
        return 0
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


class MemoryBudget:
    """Per-device HBM accounting + the cold-bucket admission decision.

    One instance per `AttributionServer` (the fleet builds one per replica
    from its ``memory_budget`` bytes). ``budget_bytes`` None/0 disables
    admission but keeps watermark capture. Thread-safe: warmups run
    concurrently across buckets."""

    def __init__(self, budget_bytes: int | None = None, *, device=None,
                 replica_id=None, retry_after_s: float = 1.0,
                 in_use_fn=None):
        self.budget_bytes = int(budget_bytes) if budget_bytes else None
        self.retry_after_s = float(retry_after_s)
        self.replica_id = replica_id
        self._rl = _label(replica_id)
        self._device = device
        self._in_use_fn = in_use_fn
        self._lock = threading.Lock()
        self._watermarks: dict[str, int] = {}
        self.rejects = 0
        if self.budget_bytes:
            _g_budget.set(self.budget_bytes, replica=self._rl)

    def capture_watermark(self, bucket_key: str, fallback_bytes: int) -> int:
        """Record a bucket's post-warmup watermark: the device's
        ``peak_bytes_in_use`` when the backend reports one, else the
        caller's shape-derived estimate. The bucket is 'warm' (always
        admitted) from here on."""
        stats = device_memory_stats(self._device)
        wm = None
        if stats:
            wm = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        wm = int(fallback_bytes) if wm is None else int(wm)
        with self._lock:
            self._watermarks[bucket_key] = wm
        _g_watermark.set(wm, replica=self._rl, bucket=bucket_key)
        return wm

    def is_warm(self, bucket_key: str) -> bool:
        with self._lock:
            return bucket_key in self._watermarks

    def bytes_in_use(self) -> int:
        """Current device bytes in use: the injected reading, else the
        backend's live counter, else the largest recorded watermark (the
        most conservative figure a stats-less backend can offer)."""
        if self._in_use_fn is not None:
            return int(self._in_use_fn())
        stats = device_memory_stats(self._device)
        if stats and stats.get("bytes_in_use") is not None:
            return int(stats["bytes_in_use"])
        with self._lock:
            return max(self._watermarks.values(), default=0)

    def admit(self, bucket_key: str, estimate_bytes: int) -> float | None:
        """Admission decision for one submit: None admits; a float is the
        ``retry_after_s`` to reject with. Warm buckets and unbudgeted
        servers always admit; a cold bucket is admitted only when its
        projected watermark (bytes in use + estimate) fits the budget."""
        if self.budget_bytes is None or self.is_warm(bucket_key):
            return None
        in_use = self.bytes_in_use()
        _g_in_use.set(in_use, replica=self._rl)
        if in_use + int(estimate_bytes) <= self.budget_bytes:
            return None
        with self._lock:
            self.rejects += 1
        _c_rejects.inc(replica=self._rl)
        return self.retry_after_s

    def describe(self) -> dict:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "watermarks": dict(self._watermarks),
                "rejects": self.rejects,
            }
