"""wam_tpu.obs — unified observability: tracing, metrics, compile sentinel.

Three pillars, one import surface:

- **Request-scoped tracing** (`obs.span`, `obs.start_span`,
  `obs.record_span`, `obs.export_chrome_trace`) — per-request span trees
  with trace/parent ids on monotonic clocks, exported as Chrome
  trace-event JSON. See `wam_tpu.obs.tracing`.
- **Metrics registry** (`obs.registry`, `obs.render_prom`,
  `obs.start_metrics_server`) — process-level counters/gauges/histograms
  in the ``wam_tpu_<subsystem>_<name>`` namespace with Prometheus text
  exposition. See `wam_tpu.obs.registry`.
- **Compile/retrace sentinel** (`obs.sentinel`, `obs.assert_no_retrace`)
  — every jit trace and AOT cache event counted and attributed. See
  `wam_tpu.obs.sentinel`.

The health plane (DESIGN.md "Health plane") builds on the pillars:

- **Numeric health** (`obs.health`) — on-device NaN/Inf + saturation +
  grad-norm reductions riding inside existing result fetches, and the
  `HealthMonitor` quarantine state machine the fleet routes around.
- **Memory accounting** (`obs.memory`) — per-bucket HBM watermarks at
  warmup, a live staged-bytes gauge, and the `MemoryBudget` cold-bucket
  admission check.
- **SLO engine** (`obs.slo`) — declarative per-bucket objectives, rolling
  burn rates, and the routing penalty that sheds load off a replica
  burning its error budget.

`configure(ObsConfig(...))` (or `configure(enabled=False)`) flips the
shared enabled flag: disabled, spans are a shared no-op singleton and
registry mutations return on one branch — near-zero overhead. The
sentinel keeps counting regardless (compile events are trace-time-rare
and the retrace invariant must hold even in overhead-sensitive runs).

`reset()` clears spans, registry values, and sentinel events — bench
sweep points and tests call it between runs so process-global state
can't leak across measurements.

This package imports only the stdlib and (lazily, for profiler
annotations) jax — never wam_tpu.serve/pipeline/evalsuite, which all
import obs. That one-way edge is what lets every subsystem publish here
without cycles.
"""

from __future__ import annotations

from wam_tpu.obs import health, memory, sentinel, slo
from wam_tpu.obs.health import HealthConfig, HealthMonitor, health_stats
from wam_tpu.obs.httpd import start_metrics_server, stop_metrics_server
from wam_tpu.obs.memory import MemoryBudget
from wam_tpu.obs.slo import SLObjectives, SLOTracker, parse_slo
from wam_tpu.obs.registry import Registry, registry, render_prom
from wam_tpu.obs.sentinel import (RetraceError, assert_no_retrace,
                                  compile_events, record_aot, record_trace,
                                  trace_count)
from wam_tpu.obs.tracing import (NULL_SPAN, Span, clear_spans,
                                 current_context, enabled,
                                 export_chrome_trace, record_span,
                                 set_enabled, set_ring_size, span, spans,
                                 start_span, use_context)

__all__ = [
    "span", "start_span", "record_span", "current_context", "use_context",
    "spans", "clear_spans", "export_chrome_trace", "Span", "NULL_SPAN",
    "registry", "Registry", "render_prom", "start_metrics_server",
    "stop_metrics_server",
    "sentinel", "record_trace", "record_aot", "trace_count",
    "compile_events", "assert_no_retrace", "RetraceError",
    "health", "memory", "slo",
    "HealthConfig", "HealthMonitor", "health_stats", "MemoryBudget",
    "SLObjectives", "SLOTracker", "parse_slo",
    "configure", "reset", "enabled", "set_enabled", "set_ring_size",
]


def configure(cfg=None, *, enabled: bool | None = None,
              ring_size: int | None = None) -> None:
    """Apply an `ObsConfig` (duck-typed: any object with
    enabled/ring_size/prom_port attrs) or individual overrides. Starting
    the prom endpoint is the server's job (`FleetServer(prom_port=...)`)
    — configure only sets process-level tracing state."""
    if cfg is not None:
        enabled = cfg.enabled if enabled is None else enabled
        ring_size = getattr(cfg, "ring_size", None) if ring_size is None else ring_size
    if enabled is not None:
        set_enabled(enabled)
    if ring_size is not None:
        set_ring_size(ring_size)


def reset() -> None:
    """Clear all recorded observability state: span ring, registry
    values (instruments stay registered), sentinel events + counts."""
    clear_spans()
    registry.reset()
    sentinel.clear_events()
