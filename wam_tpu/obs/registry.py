"""Fleet-wide metrics registry — the second pillar of `wam_tpu.obs`.

One process-level `Registry` of counters, gauges, and histograms that the
serving runtime (`ServeMetrics`/`FleetMetrics`), the AOT cache, the
stager, and the eval fan engine publish into. The registry is a SECOND
sink alongside the v2 JSONL ledger, not a replacement: JSONL rows stay
the per-run archival record, the registry is the live cross-subsystem
view that `render_prom()` exposes in Prometheus text exposition format
(and the optional `/metrics` stdlib HTTP endpoint serves — see
`wam_tpu.obs.httpd`).

Naming convention (documented in DESIGN.md): every metric is
``wam_tpu_<subsystem>_<name>`` with unit suffixes per Prometheus custom —
``_total`` for counters, ``_seconds``/``_bytes`` for unit-carrying
values. Labels are low-cardinality only (replica id, bucket, event kind);
never request ids.

Instruments are get-or-create (`registry.counter(name, ...)` returns the
existing instrument on a second call with the same name) so publishing
call sites don't coordinate. Mutations honor the shared obs enabled flag:
when observability is off every `inc`/`set`/`observe` returns on one
branch without taking the lock (the satellite-1 overhead contract).
"""

from __future__ import annotations

import threading

from wam_tpu.obs import tracing as _tracing

__all__ = ["Registry", "Counter", "Gauge", "Histogram", "registry",
           "render_prom"]

DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _escape_label(v) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _fmt(v: float) -> str:
    # Prometheus exposition wants plain decimals; repr keeps full precision
    # for floats while ints stay ints.
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _Instrument:
    """Base: named, typed, label-keyed values behind the registry lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple, lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._values: dict = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _reset(self) -> None:
        self._values.clear()


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _tracing._STATE.enabled:
            return
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not _tracing._STATE.enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _tracing._STATE.enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Instrument):
    """Cumulative-bucket histogram; per-label-set value is
    ``[counts_per_bucket..., +Inf_count, sum]``."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        if not _tracing._STATE.enabled:
            return
        key = self._key(labels)
        with self._lock:
            row = self._values.get(key)
            if row is None:
                row = self._values[key] = [0] * (len(self.buckets) + 1) + [0.0]
            for i, le in enumerate(self.buckets):
                if value <= le:
                    row[i] += 1
            row[len(self.buckets)] += 1  # +Inf / _count
            row[-1] += value  # _sum

    def count(self, **labels) -> int:
        with self._lock:
            row = self._values.get(self._key(labels))
            return int(row[len(self.buckets)]) if row else 0

    def sum(self, **labels) -> float:
        with self._lock:
            row = self._values.get(self._key(labels))
            return float(row[-1]) if row else 0.0


class Registry:
    """Get-or-create instrument registry with Prometheus rendering."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"{name} already registered as {inst.kind}")
                return inst
            inst = cls(name, help, tuple(labelnames), self._lock, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "", labels=()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def collect(self) -> dict:
        """Flat snapshot for ledger rows: ``{name{label="v",...}: value}``
        (histograms contribute ``name_count`` and ``name_sum``)."""
        out: dict[str, float] = {}
        with self._lock:
            for inst in self._instruments.values():
                for key, val in inst._values.items():
                    lbl = ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in zip(inst.labelnames, key))
                    suffix = f"{{{lbl}}}" if lbl else ""
                    if inst.kind == "histogram":
                        out[f"{inst.name}_count{suffix}"] = float(
                            val[len(inst.buckets)])
                        out[f"{inst.name}_sum{suffix}"] = float(val[-1])
                    else:
                        out[f"{inst.name}{suffix}"] = float(val)
        return out

    def render_prom(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: list[str] = []
        with self._lock:
            for name in sorted(self._instruments):
                inst = self._instruments[name]
                lines.append(f"# HELP {name} {inst.help}")
                lines.append(f"# TYPE {name} {inst.kind}")
                for key in sorted(inst._values):
                    val = inst._values[key]
                    pairs = [
                        f'{k}="{_escape_label(v)}"'
                        for k, v in zip(inst.labelnames, key)]
                    if inst.kind == "histogram":
                        # bucket counts are stored cumulatively (observe()
                        # increments every le >= value), as exposition wants
                        for i, le in enumerate(inst.buckets):
                            blbl = "{" + ",".join(pairs + [f'le="{_fmt(float(le))}"']) + "}"
                            lines.append(f"{name}_bucket{blbl} {val[i]}")
                        inf_lbl = "{" + ",".join(pairs + ['le="+Inf"']) + "}"
                        lines.append(
                            f"{name}_bucket{inf_lbl} {val[len(inst.buckets)]}")
                        base = "{" + ",".join(pairs) + "}" if pairs else ""
                        lines.append(f"{name}_sum{base} {_fmt(val[-1])}")
                        lines.append(
                            f"{name}_count{base} {val[len(inst.buckets)]}")
                    else:
                        lbl = "{" + ",".join(pairs) + "}" if pairs else ""
                        lines.append(f"{name}{lbl} {_fmt(val)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every instrument's values (instruments stay registered) —
        bench sweep points and tests call this between runs."""
        with self._lock:
            for inst in self._instruments.values():
                inst._reset()


registry = Registry()


def render_prom() -> str:
    return registry.render_prom()
