"""Compile/retrace sentinel — the third pillar of `wam_tpu.obs`.

Every jit trace and AOT cache event in the process flows through here:
`wam_tpu.serve.entry.jit_entry` calls `record_trace` from inside its
trace-time hook, `wam_tpu.pipeline.aot.cached_jit` calls `record_trace`
on cache miss and `record_aot` on hit/miss/export, and the eval fan's
plain-jit branch probes its first trace. Each event is attributed to a
``(entry_kind, bucket, replica, phase, origin)`` tuple: bucket/replica/
phase come from the ambient `label(...)` context the serve warmup and
worker threads establish, and ``origin`` is the innermost wam_tpu frames
of the recording stack (the obs frames themselves excluded) — enough to
answer "WHICH call path retraced", not just "something retraced".

`assert_no_retrace()` is the enforcement surface: as a context manager it
snapshots the trace count on entry and raises `RetraceError` listing the
new compile events on exit — the one-compile-per-bucket-per-replica
invariant the serve warm path pins, and the measurement substrate for the
ROADMAP's "zero compiles at first request".

The sentinel stays live even when observability is disabled: compile
events are rare (trace time only), and a sentinel that silently stops
counting when tracing is off would make the retrace invariant
unenforceable exactly when overhead-sensitive benchmarks run.
"""

from __future__ import annotations

import threading
import time
import traceback
from collections import deque

from wam_tpu.obs.registry import registry

__all__ = ["RetraceError", "label", "record_trace", "record_aot",
           "trace_count", "aot_event_count", "compile_events", "aot_events",
           "assert_no_retrace", "clear_events"]

_lock = threading.Lock()
_events: deque = deque(maxlen=1024)
_aot_log: deque = deque(maxlen=1024)
_trace_count = 0
_aot_seq = 0
_aot_counts: dict[str, int] = {}
_tls = threading.local()

_jit_traces = registry.counter(
    "wam_tpu_compile_jit_traces_total",
    "jit traces observed by the compile sentinel", labels=("entry_kind",))
_aot_events = registry.counter(
    "wam_tpu_compile_aot_events_total",
    "AOT executable cache events (hit/miss/export)", labels=("event",))


class RetraceError(AssertionError):
    """Raised by `assert_no_retrace` when compile events occur inside the
    guarded region; carries the offending event dicts as ``.events``."""

    def __init__(self, events):
        self.events = list(events)
        lines = [
            f"  {e['entry_kind']} bucket={e['bucket']} replica={e['replica']}"
            f" phase={e['phase']} origin={e['origin']}"
            for e in self.events]
        super().__init__(
            f"{len(self.events)} unexpected compile event(s):\n"
            + "\n".join(lines))


class label:
    """Attach attribution labels to compile events recorded on this thread:

        with sentinel.label(replica=rid, bucket=bucket, phase="warmup"):
            entry(x, y)   # any trace inside is tagged

    Nests; inner values shadow outer ones. The serve warmup and worker
    loops establish these so retraces self-identify."""

    def __init__(self, **labels):
        self._labels = labels
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "labels", None)
        merged = dict(self._prev) if self._prev else {}
        merged.update(self._labels)
        _tls.labels = merged
        return self

    def __exit__(self, *exc):
        _tls.labels = self._prev
        return False


def _current_labels() -> dict:
    return getattr(_tls, "labels", None) or {}


def _origin(skip_obs: bool = True) -> str:
    """Innermost wam_tpu frames of the current stack (obs frames excluded),
    newest last, as ``file.py:lineno:func`` joined by ``<-``."""
    frames = []
    for fr in traceback.extract_stack():
        fn = fr.filename.replace("\\", "/")
        if "wam_tpu" not in fn:
            continue
        if skip_obs and "/obs/" in fn:
            continue
        frames.append(f"{fn.rsplit('/', 1)[-1]}:{fr.lineno}:{fr.name}")
    return "<-".join(frames[-3:]) if frames else "?"


def record_trace(entry_kind: str, detail: str = "", **labels) -> dict:
    """Record one jit trace. ``entry_kind`` names the entry family
    ("serve", "aot", "fan", ...); explicit ``labels`` override the ambient
    `label(...)` context. Returns the structured event row."""
    global _trace_count
    merged = dict(_current_labels())
    merged.update({k: v for k, v in labels.items() if v is not None})
    event = {
        "event": "compile_event",
        "entry_kind": entry_kind,
        "detail": detail,
        "bucket": merged.get("bucket"),
        "replica": merged.get("replica"),
        "phase": merged.get("phase", "serve"),
        "origin": _origin(),
        "t": time.time(),
    }
    with _lock:
        _trace_count += 1
        event["seq"] = _trace_count
        _events.append(event)
    _jit_traces.inc(entry_kind=entry_kind)
    return event


def record_aot(event: str, key: str = "") -> dict:
    """Record an AOT executable cache event: "hit", "miss", "export", or —
    with the compile-artifact registry — "registry_hit" (an executable
    seeded from a bundle skipped this compile) / "registry_miss" (a bundle
    artifact failed verification and could not be seeded). Each event also
    lands as a structured row (ambient `label(...)` attribution, own seq
    stream — AOT events never trip `assert_no_retrace`) so the serve
    ledgers can attribute every consult to its origin."""
    global _aot_seq
    merged = _current_labels()
    row = {
        "event": "aot_event",
        "aot_event": event,
        "key": key,
        "bucket": merged.get("bucket"),
        "replica": merged.get("replica"),
        "phase": merged.get("phase"),
        "t": time.time(),
    }
    with _lock:
        _aot_counts[event] = _aot_counts.get(event, 0) + 1
        _aot_seq += 1
        row["seq"] = _aot_seq
        _aot_log.append(row)
    _aot_events.inc(event=event)
    return row


def trace_count() -> int:
    with _lock:
        return _trace_count


def aot_event_count(event: str | None = None) -> int:
    with _lock:
        if event is None:
            return sum(_aot_counts.values())
        return _aot_counts.get(event, 0)


def compile_events(since_seq: int = 0) -> list[dict]:
    """Structured compile_event rows with ``seq > since_seq`` (bounded by
    the event ring — 1024 events dwarfs any real compile volume)."""
    with _lock:
        return [dict(e) for e in _events if e["seq"] > since_seq]


def aot_events(since_seq: int = 0) -> list[dict]:
    """Structured aot_event rows (hit / miss / export / registry_hit /
    registry_miss) with ``seq > since_seq`` — a separate seq stream from
    `compile_events` so consuming one does not skip the other."""
    with _lock:
        return [dict(e) for e in _aot_log if e["seq"] > since_seq]


class assert_no_retrace:
    """``with obs.assert_no_retrace():`` — raises `RetraceError` if any jit
    trace is recorded inside the block. The warm-path invariant: after
    warmup, steady-state serving compiles NOTHING."""

    def __init__(self):
        self._seq0 = 0

    def __enter__(self):
        self._seq0 = trace_count()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False  # don't mask the real failure
        fresh = compile_events(since_seq=self._seq0)
        if fresh:
            raise RetraceError(fresh)
        return False


def clear_events() -> None:
    """Forget all compile/AOT events and zero the trace count (the
    registry counters are reset separately via `registry.reset()`)."""
    global _trace_count, _aot_seq
    with _lock:
        _events.clear()
        _aot_log.clear()
        _trace_count = 0
        _aot_seq = 0
        _aot_counts.clear()
