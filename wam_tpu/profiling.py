"""Tracing/profiling utilities (SURVEY.md §5.1): named trace annotations
that show up in `jax.profiler` timelines, plus a wall-clock stage timer for
the benchmark harness. The reference has no instrumentation at all.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["trace", "StageTimer", "start_server", "profile_to", "device_sync",
           "bench_time", "bench_samples", "median_iqr", "device_time_samples",
           "h2d_stats", "named_op_split", "synth_device_split",
           "metric_fetch_split"]


def device_sync(out) -> None:
    """Force completion AND a host round-trip of a reduced scalar per leaf.

    On tunneled/remote TPU platforms `block_until_ready` alone occasionally
    returns before remote execution finishes, producing bogus ~0s timings;
    fetching a reduced scalar cannot complete early. Use this (not
    block_until_ready) to close a timed region in benchmarks.
    """
    import jax.numpy as jnp

    jax.device_get(jax.tree_util.tree_map(lambda a: jnp.sum(a), out))


def bench_time(fn, *args, repeats: int = 3, laps: int = 1) -> float:
    """Min wall-clock seconds per call of `fn(*args)`, after one untimed
    compile/warm-up run. Uses `device_sync` to close each timed region.

    ``laps`` > 1 enqueues that many calls per timed region and syncs once:
    TPU executes enqueued programs in order, so the region measures true
    aggregate device time plus a single host round trip. On tunneled
    platforms the round trip is ~100 ms (measured v5e-over-axon), which a
    per-call sync would otherwise add to every lap — the round-1 flagship
    numbers carried exactly that bias (BASELINE.md round-2 note)."""
    device_sync(fn(*args))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(laps):
            out = fn(*args)
        device_sync(out)
        times.append((time.perf_counter() - t0) / laps)
    return min(times)


def bench_samples(fn, *args, k: int = 7, laps: int = 1, warmup: int = 1) -> list[float]:
    """``k`` independent lap-amortized wall-clock samples (seconds/call).

    Same regions as `bench_time` but ALL samples are returned instead of the
    min, so the caller can report median + IQR — short workloads on the
    tunneled TPU vary ±10% run to run, and a single min cannot adjudicate a
    10% regression (VERDICT.md round-3 weak #2)."""
    for _ in range(max(1, warmup)):
        device_sync(fn(*args))
    times = []
    for _ in range(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(laps):
            out = fn(*args)
        device_sync(out)
        times.append((time.perf_counter() - t0) / laps)
    return times


def _union_seconds(events) -> float:
    """Total covered time of possibly-overlapping [offset, offset+duration)
    event intervals."""
    iv = sorted((ev.offset_ps, ev.offset_ps + ev.duration_ps) for ev in events)
    total = 0
    cur_s = cur_e = None
    for s, e in iv:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total / 1e12


def _device_busy_seconds(logdir: str) -> float | None:
    """Total device execution time in a profiler capture: the interval
    UNION of "XLA Modules" events on the TPU device plane (one event per
    program execution — the program's device span). Module spans overlap
    too once dispatch is pipelined (batch k+1's program starts while k is
    still running on a multi-queue device, and donated-alias programs can
    report nested spans), so a plain duration sum over-reports busy time
    exactly like the per-op line does — every line is union-reduced. A
    plain sum over the per-op "XLA Ops" line double-counts ~2× (events
    overlap/nest: measured 0.738 s op-sum vs 0.379 s module span on the
    flagship step); it is the fallback when no module line exists. None
    when no TPU device plane exists (CPU backend).

    Multi-chip captures expose one TPU plane PER DEVICE, each carrying the
    same SPMD program's span — summing across planes would report k× the
    step time on k chips. The capture is therefore reduced per plane and the
    BUSIEST plane wins (max), which is the wall-clock-limiting chip of an
    SPMD step; per-chip skew stays invisible here, by design."""
    import glob

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        # tensorflow is not a declared dependency — without its xplane
        # protos there is no device-time protocol; callers get the same
        # "no device plane" signal as on CPU backends
        return None

    paths = glob.glob(f"{logdir}/plugins/profile/*/*.xplane.pb")
    if not paths:
        return None
    space = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        space.ParseFromString(f.read())
    per_plane = []
    for plane in space.planes:
        if "TPU" not in plane.name:
            continue
        lines = {line.name: line for line in plane.lines}
        if "XLA Modules" in lines and lines["XLA Modules"].events:
            per_plane.append(_union_seconds(lines["XLA Modules"].events))
        elif "XLA Ops" in lines:
            per_plane.append(_union_seconds(lines["XLA Ops"].events))
    return max(per_plane) if per_plane else None


def _merged_intervals(iv):
    """Sorted, overlap-merged [(start, end), ...] interval list."""
    out: list[list[int]] = []
    for s, e in sorted(iv):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _intersection_ps(a, b) -> int:
    """Total overlap between two merged interval lists (picoseconds)."""
    i = j = total = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


_H2D_TOKENS = ("h2d", "hosttodevice", "host to device", "transfertodevice",
               "copy to device", "transfer to device", "memcpyh", "infeed")


def h2d_stats(logdir: str) -> dict | None:
    """Host→device transfer stats from a profiler capture, or None.

    Scans every plane of the newest xplane capture for transfer-shaped
    events (line/event names matching H2D/infeed/copy-to-device tokens),
    totals their bytes (largest byte-valued stat per event — events often
    carry several byte stats describing the same buffer) and busy time,
    and measures how much of that transfer time ran CONCURRENTLY with
    device compute (the TPU planes' "XLA Modules" program spans). Event
    offsets are rebased onto each line's absolute timestamp so intervals
    compare across lines and planes.

    Returns ``{"h2d_bytes", "h2d_seconds", "overlap_frac"}`` —
    ``overlap_frac`` is None when the capture has no module spans to
    compare against (any CPU capture: no TPU device plane). Returns None
    when the xplane protos (tensorflow) are unavailable, no capture
    exists, or no transfer events were recorded at all. On CPU
    `jax.device_put` is a host-side aliasing no-op — a capture may still
    carry a few zero-byte transfer-shaped host events, so callers should
    treat the bytes/overlap fields as device-backend data only."""
    import glob

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        return None

    paths = glob.glob(f"{logdir}/plugins/profile/*/*.xplane.pb")
    if not paths:
        return None
    space = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        space.ParseFromString(f.read())

    transfer_iv: list[tuple[int, int]] = []
    module_iv: list[tuple[int, int]] = []
    transfer_bytes = 0
    for plane in space.planes:
        event_names = {m.id: m.name for m in plane.event_metadata.values()}
        stat_names = {m.id: m.name for m in plane.stat_metadata.values()}
        for line in plane.lines:
            base_ps = line.timestamp_ns * 1000
            if "TPU" in plane.name and line.name == "XLA Modules":
                module_iv.extend(
                    (base_ps + ev.offset_ps,
                     base_ps + ev.offset_ps + ev.duration_ps)
                    for ev in line.events
                )
            for ev in line.events:
                label = f"{line.name} {event_names.get(ev.metadata_id, '')}".lower()
                if not any(tok in label for tok in _H2D_TOKENS):
                    continue
                start = base_ps + ev.offset_ps
                transfer_iv.append((start, start + ev.duration_ps))
                nbytes = 0
                for st in ev.stats:
                    if "byte" not in stat_names.get(st.metadata_id, "").lower():
                        continue
                    nbytes = max(nbytes, st.int64_value, st.uint64_value,
                                 int(st.double_value))
                transfer_bytes += nbytes

    if not transfer_iv:
        return None
    merged_t = _merged_intervals(transfer_iv)
    h2d_s = sum(e - s for s, e in merged_t) / 1e12
    overlap_frac = None
    if module_iv and h2d_s > 0:
        inter = _intersection_ps(merged_t, _merged_intervals(module_iv))
        overlap_frac = inter / (h2d_s * 1e12)
    return {
        "h2d_bytes": transfer_bytes,
        "h2d_seconds": h2d_s,
        "overlap_frac": overlap_frac,
    }


def named_op_split(logdir: str,
                   tokens=("wam_synth", "wam_analysis")) -> dict | None:
    """Per-token device-time buckets from a profiler capture, or None.

    `jax.named_scope` annotations propagate into XLA op metadata (the
    scope joins the op's long name / op_name stat), so device ops traced
    under ``jax.named_scope("wam_synth")`` carry the token. This scans the
    BUSIEST TPU plane's "XLA Ops" line of the newest capture (max over
    planes, the `_device_busy_seconds` multi-chip convention), matches each
    op's metadata name / display name / string stats against the tokens,
    and reports the interval-UNION seconds per token — op events overlap
    and nest (fusions), a plain sum double-counts ~2x.

    Returns ``{token: seconds..., "total": seconds}`` (``total`` = union of
    every op on the line; tokens can overlap it partially — an op both
    inside and outside a scope buckets by its own metadata only). None when
    the xplane protos (tensorflow) are unavailable, no capture exists, or
    no TPU device plane carries an op line (any CPU capture)."""
    import glob

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        return None

    paths = glob.glob(f"{logdir}/plugins/profile/*/*.xplane.pb")
    if not paths:
        return None
    space = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        space.ParseFromString(f.read())

    best = None  # (busy_seconds, plane, op_line) of the busiest TPU plane
    for plane in space.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Ops" or not line.events:
                continue
            busy = _union_seconds(line.events)
            if best is None or busy > best[0]:
                best = (busy, plane, line)
    if best is None:
        return None
    _, plane, ops = best
    event_meta = dict(plane.event_metadata)
    stat_names = {m.id: m.name for m in plane.stat_metadata.values()}
    per_token: dict[str, list] = {t: [] for t in tokens}
    all_iv = []
    for ev in ops.events:
        md = event_meta.get(ev.metadata_id)
        parts = []
        if md is not None:
            parts.append(md.name)
            parts.append(getattr(md, "display_name", ""))
        for st in ev.stats:
            if st.str_value:
                parts.append(st.str_value)
            elif st.ref_value:
                # string stats may be interned in the stat_metadata table
                parts.append(stat_names.get(st.ref_value, ""))
        label = " ".join(parts).lower()
        iv = (ev.offset_ps, ev.offset_ps + ev.duration_ps)
        all_iv.append(iv)
        for t in tokens:
            if t.lower() in label:
                per_token[t].append(iv)
    out = {
        t: sum(e - s for s, e in _merged_intervals(per_token[t])) / 1e12
        for t in tokens
    }
    out["total"] = sum(e - s for s, e in _merged_intervals(all_iv)) / 1e12
    return out


def synth_device_split(fn, *args, laps: int = 1, warmup: int = 1) -> dict | None:
    """Analysis-vs-synthesis device-time split of one runner: traces one
    lap-amortized region and buckets device op time by the wavelet core's
    `named_scope` tokens (``wam_synth`` wraps every synthesis dispatch,
    ``wam_analysis`` the analysis ones — wavelets/transform.py). Seconds are
    per call (divided by ``laps``); fractions are of the op-union total.
    None on backends with no TPU device plane (CPU) or without the xplane
    protos — callers must treat the split as device-backend data only."""
    import shutil
    import tempfile

    for _ in range(max(1, warmup)):
        device_sync(fn(*args))
    d = tempfile.mkdtemp(prefix="wam_synth_split_")
    try:
        jax.profiler.start_trace(d)
        try:
            out = None
            for _ in range(laps):
                out = fn(*args)
            device_sync(out)
        finally:
            jax.profiler.stop_trace()
        split = named_op_split(d)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    if split is None:
        return None
    total = split.pop("total")
    res = {f"{k}_s": v / laps for k, v in split.items()}
    res["op_total_s"] = total / laps
    if total > 0:
        for k, v in split.items():
            res[f"{k}_frac"] = v / total
    return res


def metric_fetch_split(fn, *args, k: int = 3, laps: int = 1,
                       warmup: int = 1) -> dict:
    """Wall vs device-span split of one METRIC call (a full evalsuite metric:
    μ-fidelity, an AUC fan, input fidelity — python in, python out).

    Under the fan engine's single-fetch contract
    (`wam_tpu.evalsuite.fan.run_fan`) a metric call is one enqueued program
    plus exactly one result fetch, so its wall time decomposes as
    ``wall ≈ device_span + fetch residue`` where the residue is the host
    round trip (~100 ms on the tunneled TPU) plus host glue. This measures
    both planes of the same runner and reports the residue explicitly — the
    number the fan engine exists to pin at ONE RTT per call.

    Returns ``{"wall_s", "wall_q1_s", "wall_q3_s", "device_s", "residue_s",
    "plane"}``; wall fields are `bench_samples` medians/quartiles. On
    backends with no TPU device plane (CPU) or without the xplane protos,
    ``device_s``/``residue_s`` are honest None and ``plane`` is "wall" —
    callers must label such rows CPU/wall, never report them as device
    numbers (the rounds 6-8 convention)."""
    wall = bench_samples(fn, *args, k=k, laps=laps, warmup=warmup)
    med, q1, q3, _ = median_iqr(wall)
    res = {"wall_s": med, "wall_q1_s": q1, "wall_q3_s": q3,
           "device_s": None, "residue_s": None, "plane": "wall"}
    dev = device_time_samples(fn, *args, k=k, laps=laps, warmup=0)
    if dev:
        dmed = median_iqr(dev)[0]
        res.update(device_s=dmed, residue_s=max(0.0, med - dmed),
                   plane="device")
    return res


def device_time_samples(fn, *args, k: int = 3, laps: int = 1, warmup: int = 1) -> list[float]:
    """``k`` device-time samples (seconds/call): each sample traces one
    lap-amortized region with `jax.profiler` and reports the busiest TPU
    device plane's "XLA Modules" program spans / laps (op-interval union as
    fallback; max over planes, NOT a sum — a multi-chip SPMD capture carries
    the same program on every plane. See `_device_busy_seconds`).

    This measures the CHIP, not the tunnel: wall samples of sub-100 ms
    steps on the tunneled TPU are dominated by host/tunnel state and turn
    bimodal ACROSS processes even when each process's IQR is tight (the
    round-4 `wam2d_base` ledger: 22.5/91.5/96.5/26.4 items/s on identical
    code). Returns [] when the backend exposes no TPU device plane or the
    xplane protos (tensorflow) are unavailable."""
    import shutil
    import tempfile

    for _ in range(max(1, warmup)):
        device_sync(fn(*args))
    samples = []
    for _ in range(k):
        d = tempfile.mkdtemp(prefix="wam_devtime_")
        try:
            jax.profiler.start_trace(d)
            try:
                out = None
                for _ in range(laps):
                    out = fn(*args)
                device_sync(out)
            finally:
                jax.profiler.stop_trace()
            busy = _device_busy_seconds(d)
            if busy is None:
                return []
            samples.append(busy / laps)
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return samples


def median_iqr(samples: list[float]) -> tuple[float, float, float, float]:
    """(median, q1, q3, iqr) of a sample list (linear-interpolated quartiles)."""
    import numpy as np

    a = np.asarray(sorted(samples), dtype=np.float64)
    q1, med, q3 = np.quantile(a, [0.25, 0.5, 0.75])
    return float(med), float(q1), float(q3), float(q3 - q1)


@contextlib.contextmanager
def trace(name: str):
    """Annotate a region in device traces (XLA op names) AND host timelines."""
    with jax.profiler.TraceAnnotation(name), jax.profiler.StepTraceAnnotation(name):
        yield


class StageTimer:
    """Accumulating wall-clock timer: `with timer.stage("dwt"): ...`;
    blocks on device results when given an output to ready-wait.

    With ``span_prefix`` set (e.g. ``"serve."``), every stage interval is
    also recorded as an obs span named ``{span_prefix}{name}`` — it
    parents to the calling thread's current span context, so stages that
    run inside a request's context join that request's trace for free."""

    def __init__(self, span_prefix: str | None = None):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.span_prefix = span_prefix

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self.totals[name] += t1 - t0
            self.counts[name] += 1
            if self.span_prefix is not None:
                from wam_tpu.obs import tracing as _obs_tracing

                _obs_tracing.record_span(
                    f"{self.span_prefix}{name}", t0, t1,
                    parent=_obs_tracing.current_context(), cat="stage")

    def timed(self, name: str, fn, *args, **kwargs):
        with self.stage(name):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            k: {"total_s": self.totals[k], "calls": self.counts[k],
                "mean_s": self.totals[k] / max(self.counts[k], 1)}
            for k in self.totals
        }


def start_server(port: int = 9999):
    """Expose the live profiler (for `tensorboard --logdir` capture)."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def profile_to(logdir: str):
    """Write a full device trace for one region."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
