from wam_tpu.evalsuite.baselines import (
    gradcam,
    gradcam_pp,
    integrated_gradients,
    layercam,
    saliency,
    smoothgrad_pixel,
)
from wam_tpu.evalsuite.eval1d import Eval1DWAM
from wam_tpu.evalsuite.fan import (
    FanPlan,
    device_fetch,
    fan_runner,
    fetch_count,
    fetch_scope,
    plan_fan,
    reset_fetch_count,
    run_fan,
)
from wam_tpu.evalsuite.eval2d import Eval2DWAM, imagenet_denormalize, imagenet_preprocess
from wam_tpu.evalsuite.eval_baselines import AUDIO_METHODS, IMAGE_METHODS, EvalAudioBaselines, EvalImageBaselines
from wam_tpu.evalsuite.metrics import compute_auc, generate_masks, minmax_normalize, softmax_probs, spearman
from wam_tpu.evalsuite.packing import (
    array_to_coeffs1d,
    array_to_coeffs2d,
    coeffs_to_array1d,
    coeffs_to_array2d,
    packed2d_shape,
)

__all__ = [
    "Eval1DWAM",
    "Eval2DWAM",
    "FanPlan",
    "plan_fan",
    "fan_runner",
    "run_fan",
    "device_fetch",
    "fetch_count",
    "fetch_scope",
    "reset_fetch_count",
    "EvalImageBaselines",
    "EvalAudioBaselines",
    "IMAGE_METHODS",
    "AUDIO_METHODS",
    "saliency",
    "integrated_gradients",
    "smoothgrad_pixel",
    "gradcam",
    "gradcam_pp",
    "layercam",
    "compute_auc",
    "generate_masks",
    "minmax_normalize",
    "softmax_probs",
    "spearman",
    "coeffs_to_array1d",
    "array_to_coeffs1d",
    "coeffs_to_array2d",
    "array_to_coeffs2d",
    "packed2d_shape",
    "imagenet_preprocess",
    "imagenet_denormalize",
]
