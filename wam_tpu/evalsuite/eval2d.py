"""Eval2DWAM — faithfulness benchmarks for WAM-2D (`src/evaluators.py:553-802`):
insertion / deletion AUC (Petsiuk et al.) and μ-fidelity (Bhatt et al.).

TPU-first restatement of the reference's host loops (SURVEY.md §3.2): the
65 per-mask pywt reconstructions ×3 channels become ONE vmapped masked
packed-array multiply + batched inverse DWT on device; the model evaluates
all perturbed images in one (chunked) call. Explanations are computed once
and cached on the instance (the reference's intentional stateful caching,
SURVEY.md §2.11.8, made explicit via `precompute`/`reset`).

Device boundary: perturbation + inference stay fully on device; the
reference's PIL round-trip (`src/evaluators.py:628-633`) is replaced by a
per-image min-max rescale + a user preprocess_fn (default: ImageNet
normalization — the effect of its uint8 → ToTensor → Normalize chain).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from wam_tpu.evalsuite.fan import (
    FanPlan,
    cast_model_fn,
    fan_runner,
    make_chunked_forward,
    plan_fan,
    run_fan,
)
from wam_tpu.evalsuite.metrics import (
    batch_fingerprint as _batch_fingerprint,
    generate_masks,
    run_cached_auc,
    softmax_probs,
    spearman,
)
from wam_tpu.evalsuite.packing import array_to_coeffs2d, coeffs_to_array2d
from wam_tpu.ops.filters import gaussian_filter2d, superpixel_sum, upsample_nearest
from wam_tpu.wavelets import wavedec2, waverec2

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)

__all__ = ["Eval2DWAM", "imagenet_preprocess", "imagenet_denormalize"]


def imagenet_preprocess(img01: jax.Array) -> jax.Array:
    """[0,1] image (.., 3, H, W) → standardized (the reference transform,
    `src/evaluators.py:595-599`)."""
    mean = jnp.asarray(IMAGENET_MEAN).reshape(3, 1, 1)
    std = jnp.asarray(IMAGENET_STD).reshape(3, 1, 1)
    return (img01 - mean) / std


def imagenet_denormalize(x: jax.Array) -> jax.Array:
    """Standardized tensor → [0,1] image (the `show` role,
    `src/helpers.py:421-448`)."""
    mean = jnp.asarray(IMAGENET_MEAN).reshape(3, 1, 1)
    std = jnp.asarray(IMAGENET_STD).reshape(3, 1, 1)
    return jnp.clip(x * std + mean, 0.0, 1.0)


def _minmax01(a: jax.Array) -> jax.Array:
    lo = a.min(axis=(-3, -2, -1), keepdims=True)
    hi = a.max(axis=(-3, -2, -1), keepdims=True)
    return (a - lo) / jnp.where(hi > lo, hi - lo, 1.0)


class Eval2DWAM:
    """Faithfulness evaluation of a 2D wavelet attribution explainer.

    ``explainer``: callable (x, y) → (B, S, S) attribution mosaics (e.g.
    `WaveletAttribution2D`). ``model_fn``: (B, 3, H, W) → logits.
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        explainer: Callable,
        wavelet: str = "haar",
        J: int = 3,
        mode: str = "reflect",
        batch_size: int | str = 128,
        denormalize_fn: Callable = imagenet_denormalize,
        preprocess_fn: Callable = imagenet_preprocess,
        random_seed: int = 42,
        mesh=None,
        data_axis: str = "data",
        donate_inputs: bool | None = None,
        aot_key: str | None = None,
        precision=None,
    ):
        """Constructor args are frozen config (the reference's
        constructor-kwargs surface, SURVEY.md §5.6) — build a new evaluator
        to change them. ``mesh``: optional `jax.sharding.Mesh` — when given,
        every metric's perturbation-inference batch (the 65-reconstruction
        insertion fan, μ-fidelity subsets, ...) is sharded over ``data_axis``
        instead of chunked on one device (the SURVEY.md §2.10 evaluation
        fan-out). ``batch_size="auto"`` resolves the memory cap per metric
        from the tuned schedule cache (`wam_tpu.tune.resolve_fan_cap`,
        workload "eval2d"), falling back to the 128 the rounds 1-5 numbers
        were recorded at.

        ``donate_inputs`` (None = donate on TPU only, the serve policy)
        donates the image/explanation buffers into the metric graphs,
        freeing one batch-sized HBM buffer per call; instance-cached and
        caller-held arrays are protected by `pipeline.donation
        .donation_safe` copies. ``aot_key`` opts the single-device metric
        runners into the AOT executable cache (`wam_tpu.pipeline.aot`) —
        it must uniquely identify model + params; both are ignored on the
        mesh path.

        ``precision``: a `config.PrecisionPolicy`, a ``fan_dtype`` string
        ("bf16"/"fp8"), or None — None resolves the fan compute dtype per
        metric fan (``WAM_TPU_FAN_DTYPE`` env knob / tuned ``fan_dtype``
        schedule axis via `plan_fan`). The shim casts fan inputs at the
        jit boundary and logits back to f32 before every reduction; bind
        the model's params at the matching dtype
        (`models.bind_inference(compute_dtype=...)`) for the MXU win."""
        self.model_fn = model_fn
        self.explainer = explainer
        self.wavelet = wavelet
        self.J = J
        self.mode = mode
        self.batch_size = batch_size
        self.denormalize_fn = denormalize_fn
        self.preprocess_fn = preprocess_fn
        self.random_seed = random_seed
        self.mesh = mesh
        self.data_axis = data_axis
        self.donate_inputs = donate_inputs
        self.aot_key = aot_key
        from wam_tpu.config import PrecisionPolicy

        if isinstance(precision, str):
            precision = PrecisionPolicy(fan_dtype=precision)
        self._fan_dtype = precision.fan_dtype if precision is not None else None
        self._auc_runners: dict = {}
        self._mu_runners: dict = {}
        self._mu_draw_cache: dict = {}
        self.grad_wams = None
        self._expl_key = None
        self.insertion_curves = []
        self.deletion_curves = []

    # -- explanation cache -------------------------------------------------

    def precompute(self, x, y):
        """Compute (or reuse) the cached explanations for this batch.

        The cache is fingerprinted on ``(shape, dtype, y)``: a second call
        with a different batch recomputes instead of silently reusing the
        first batch's explanations (the pre-round-7 footgun). Explanations
        injected by direct ``grad_wams`` assignment adopt the first
        fingerprint they are used with (scripts/bench_eval.py shares one
        explainer pass across evaluator configs this way)."""
        key = _batch_fingerprint(x, y)
        if self.grad_wams is not None:
            if self._expl_key is None or self._expl_key == key:
                self._expl_key = key
                return self.grad_wams
        self.grad_wams = jnp.asarray(self.explainer(x, y))
        self._expl_key = key
        return self.grad_wams

    def reset(self):
        self.grad_wams = None
        self._expl_key = None

    def _fan_plan(self, fan: int) -> FanPlan:
        """Per-metric fan geometry: explicit int ``batch_size`` pins the
        memory cap (law-derived chunks); "auto" consults the tuned schedule
        cache (round-6 ``fan_cap`` + this round's ``fan_chunk`` override)
        keyed by this metric's fan."""
        return plan_fan(self.batch_size, fan, fan_dtype=self._fan_dtype)

    def _fan_cap(self, fan: int) -> int:
        return self._fan_plan(fan).cap

    # -- shared reconstruction machinery -----------------------------------

    def _coeff_shapes(self, img_hw):
        probe = jnp.zeros((1,) + tuple(img_hw))
        coeffs = wavedec2(probe, self.wavelet, self.J, self.mode)
        shapes = [tuple(coeffs[0].shape[-2:])] + [
            tuple(d.diagonal.shape[-2:]) for d in coeffs[1:]
        ]
        return shapes

    def _masked_reconstructions(self, image01: jax.Array, masks: jax.Array) -> jax.Array:
        """image01 (3, H, W), masks (M, Ph, Pw) in the packed-coefficient
        domain → (M, 3, H, W) preprocessed model inputs."""
        H, W = image01.shape[-2:]
        coeffs = wavedec2(image01, self.wavelet, self.J, self.mode)
        packed = coeffs_to_array2d(coeffs)  # (3, Ph, Pw)
        shapes = [tuple(coeffs[0].shape[-2:])] + [
            tuple(d.diagonal.shape[-2:]) for d in coeffs[1:]
        ]
        masked = packed[None] * masks[:, None]  # (M, 3, Ph, Pw)
        recon = waverec2(array_to_coeffs2d(masked, shapes), self.wavelet)[..., :H, :W]
        return self.preprocess_fn(_minmax01(recon))

    # -- insertion / deletion ---------------------------------------------

    def _perturb_for_auc(self, img, wam, mode: str, n_iter: int):
        """One sample's perturbation fan: resize the mosaic into the packed
        coefficient domain (equal for haar on dyadic sizes), build the mask
        family, reconstruct."""
        image01 = self.denormalize_fn(img)
        coeffs = wavedec2(image01, self.wavelet, self.J, self.mode)
        ph, pw = coeffs_to_array2d(coeffs).shape[-2:]
        if wam.shape != (ph, pw):  # static shapes
            wam = jax.image.resize(wam, (ph, pw), method="nearest")
        ins, dele = generate_masks(n_iter, wam)
        masks = ins if mode == "insertion" else dele
        return self._masked_reconstructions(image01, masks)

    def evaluate_auc(self, x, y, mode: str, n_iter: int = 64):
        """Per-sample AUC of class probability along the nested mask family
        (`src/evaluators.py:605-647`). Returns (scores, curves).

        ONE jit dispatch for the whole batch either way
        (`batched_auc_runner`): single-device it lax.map-chunks; with a
        mesh attached the image batch is sharded over ``data_axis`` via
        shard_map — no per-image host loop in either configuration
        (round-4 verdict #4)."""
        x = jnp.asarray(x)
        y = np.asarray(y)
        wams = self.precompute(x, y)

        return run_cached_auc(
            self._auc_runners,
            (mode, tuple(wams.shape[1:])),
            lambda img, wam: self._perturb_for_auc(img, wam, mode, n_iter),
            self.model_fn,
            self._fan_plan(n_iter + 1),
            n_iter,
            x,
            wams,
            y,
            mesh=self.mesh,
            data_axis=self.data_axis,
            donate=self.donate_inputs,
            aot_key=self.aot_key,
        )

    def insertion(self, x, y, n_iter: int = 64):
        scores, curves = self.evaluate_auc(x, y, "insertion", n_iter)
        self.insertion_curves = curves
        return scores

    def deletion(self, x, y, n_iter: int = 64):
        scores, curves = self.evaluate_auc(x, y, "deletion", n_iter)
        self.deletion_curves = curves
        return scores

    # -- μ-fidelity --------------------------------------------------------

    def _mu_random_draws(self, n_images: int, grid_size: int, sample_size: int,
                         subset_size: int):
        """Shared cached μ randomness (metrics.mu_fidelity_draws), WITH the
        per-image continuous baseline-search masks this evaluator needs."""
        from wam_tpu.evalsuite.metrics import mu_fidelity_draws

        return mu_fidelity_draws(
            self._mu_draw_cache, self.random_seed, n_images, grid_size,
            sample_size, subset_size, with_rand_masks=True,
        )

    def _make_mu_runner(self, grid_size: int, sample_size: int,
                        plan: FanPlan | None = None):
        """ONE-jit-dispatch μ-fidelity for the whole batch (VERDICT.md
        round-2 weak #3): per-image reconstruction fans run under `lax.map`
        chunked per the fan plan (tuned cap + fan_chunk override), Spearman
        included — correlations accumulate device-resident across chunks.
        With a mesh, the image batch is sharded over ``data_axis`` via
        shard_map — same body per device, still one dispatch (round-4
        verdict #4). ``plan`` overrides the resolved geometry (the
        autotuner's fan_chunk sweep builds runners at explicit plans)."""
        if plan is None:
            plan = self._fan_plan(sample_size)
        images_per_chunk = plan.images_per_chunk
        # logits come back f32 from the shim, so the Spearman/softmax
        # reductions below stay f32 whatever the fan compute dtype
        forward = cast_model_fn(
            make_chunked_forward(self.model_fn, plan.fan_chunk),
            plan.fan_dtype)
        base_fn = cast_model_fn(self.model_fn, plan.fan_dtype)

        def forward_probs(inputs, label):
            return jnp.take(softmax_probs(forward(inputs)), label, axis=1)

        def reconstruct(img, masks_grid):
            image01 = self.denormalize_fn(img)
            coeffs = wavedec2(image01, self.wavelet, self.J, self.mode)
            ph, pw = coeffs_to_array2d(coeffs).shape[-2:]
            masks = upsample_nearest(masks_grid, (ph, pw))
            return self._masked_reconstructions(image01, masks)

        def run(xb, wamsb, yb, randb, onehotb):
            base_probs = jnp.take_along_axis(
                softmax_probs(base_fn(xb)), yb[:, None], axis=1
            )[:, 0]

            def one(args):
                img, wam, lab, rand_masks, onehot, bp = args
                wam_blur = gaussian_filter2d(wam, sigma=2.0)
                # baseline-state search: random continuous masks, keep the
                # one minimizing the class prob (src/evaluators.py:767-801)
                probs = forward_probs(reconstruct(img, rand_masks), lab)
                baseline_mask = rand_masks[jnp.argmin(probs)]
                onehot_g = onehot.reshape(sample_size, grid_size, grid_size)
                masks_grid = jnp.where(onehot_g > 0, baseline_mask[None], 1.0)
                probs_alt = forward_probs(reconstruct(img, masks_grid), lab)
                deltas = bp - probs_alt
                # attribution mass per superpixel of the (blurred) mosaic;
                # every pixel lands in the same cell the mask upsample maps
                # it to (superpixel_sum's nearest-resize partition)
                cell_sums = superpixel_sum(wam_blur, grid_size).reshape(-1)
                attrs = onehot @ cell_sums
                return spearman(deltas, attrs)

            return jax.lax.map(
                one,
                (xb, wamsb, yb, randb, onehotb, base_probs),
                batch_size=images_per_chunk,
            )

        aot_key = None
        if self.aot_key is not None:
            # dtype-tagged so a bf16 μ executable can never collide with
            # the f32 one under the same model key
            aot_key = (f"{self.aot_key}|mu|g{grid_size}|s{sample_size}"
                       f"|c{images_per_chunk}|{plan.fan_dtype}")
        return fan_runner(run, mesh=self.mesh, data_axis=self.data_axis,
                          donate=self.donate_inputs, donate_argnums=(0,),
                          aot_key=aot_key)

    def mu_fidelity(
        self,
        x,
        y,
        grid_size: int = 28,
        sample_size: int = 128,
        subset_size: int = 157,
    ):
        """mean Spearman ρ between Δ-probability under superpixel masking and
        summed attribution of the masked superpixels
        (`src/evaluators.py:667-765`).

        One jit dispatch for the whole batch in BOTH configurations: the
        mesh variant shards the image batch over ``data_axis`` inside the
        same runner (round-4 verdict #4 — the per-image mesh loop is
        gone)."""
        x = jnp.asarray(x)
        y = np.asarray(y)
        wams = self.precompute(x, y)
        rand_all, onehot_all = self._mu_random_draws(
            x.shape[0], grid_size, sample_size, subset_size
        )

        plan = self._fan_plan(sample_size)
        key = (grid_size, sample_size, tuple(x.shape[1:]),
               tuple(wams.shape[1:]), plan.images_per_chunk, plan.fan_chunk,
               plan.fan_dtype)
        runner = self._mu_runners.get(key)
        if runner is None:
            runner = self._make_mu_runner(grid_size, sample_size, plan)
            self._mu_runners[key] = runner
        # the whole batch's correlations come back in ONE counted fetch
        out = run_fan(runner, (x, wams, jnp.asarray(y), rand_all, onehot_all),
                      donate=self.donate_inputs, mesh=self.mesh, protect=(0,))
        return [float(v) for v in np.asarray(out)]
