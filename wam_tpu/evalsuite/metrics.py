"""Shared metric machinery: AUC, nested insertion/deletion masks, softmax
probabilities, min-max normalization, Spearman rank correlation.

Vectorized restatement of `src/evaluation_helpers.py:395-499` — the
reference's Python mask loop becomes one broadcast comparison against the
rank array, and the whole (n_iter+1)-mask family is a single (n+1, ...)
tensor ready for a vmapped reconstruction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "softmax_probs",
    "compute_auc",
    "generate_masks",
    "minmax_normalize",
    "spearman",
    "make_probs_fn",
    "batched_auc_runner",
    "run_cached_auc",
    "fan_chunk_geometry",
    "make_chunked_forward",
]


def softmax_probs(logits: jax.Array) -> jax.Array:
    return jax.nn.softmax(logits, axis=-1)


def compute_auc(probs: jax.Array) -> jax.Array:
    """sum(p) / (max(p) · len(p)) over the last axis
    (`src/evaluation_helpers.py:437-453`)."""
    denom = jnp.max(probs, axis=-1) * probs.shape[-1]
    return jnp.sum(probs, axis=-1) / jnp.where(denom == 0, 1.0, denom)


def generate_masks(n_iter: int, attribution: jax.Array, signed: bool = False):
    """Nested insertion/deletion masks from an attribution map of any shape.

    Returns (insertion, deletion), each (n_iter+1, *attribution.shape):
    insertion[k] keeps the top k·(size/n_iter) most-important cells
    (insertion[0] empty, insertion[-1] full); deletion is the complement
    family starting full. Importance is the raw value (2D reference,
    `src/evaluation_helpers.py:455-499`) or |value| when ``signed``
    (1D reference, `src/evaluators.py:87`).
    """
    flat = attribution.reshape(-1)
    if signed:
        flat = jnp.abs(flat)
    n = flat.shape[0]
    order = jnp.argsort(-flat)  # descending
    rank = jnp.zeros(n, dtype=jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    n_components = n // n_iter
    ks = jnp.arange(1, n_iter + 1, dtype=jnp.int32) * n_components  # (n_iter,)
    keep = rank[None, :] < ks[:, None]  # (n_iter, n)
    ins = jnp.concatenate([jnp.zeros((1, n), bool), keep], axis=0)
    ins = ins.at[-1].set(True)  # last mask keeps everything
    dele = jnp.concatenate([jnp.ones((1, n), bool), ~keep], axis=0)
    dele = dele.at[-1].set(False)
    shape = (n_iter + 1,) + attribution.shape
    return (
        ins.astype(attribution.dtype).reshape(shape),
        dele.astype(attribution.dtype).reshape(shape),
    )


def minmax_normalize(a: jax.Array) -> jax.Array:
    lo, hi = jnp.min(a), jnp.max(a)
    return (a - lo) / jnp.where(hi > lo, hi - lo, 1.0)


def spearman(a: jax.Array, b: jax.Array) -> jax.Array:
    """Spearman rank correlation of two 1D vectors (scipy.stats.spearmanr
    role in μ-fidelity, `src/evaluators.py:761-763`), on-device.

    Ties receive AVERAGED ranks, matching scipy's default — μ-fidelity
    probability deltas tie routinely (saturated softmax identical to float
    precision), where first-occurrence ranks would diverge from the
    reference (VERDICT.md round-1 weak #6). rank(v) = (#less + (#leq−1)/2),
    via two searchsorted passes on the sorted copy."""

    def ranks(v):
        sv = jnp.sort(v)
        lo = jnp.searchsorted(sv, v, side="left")
        hi = jnp.searchsorted(sv, v, side="right")
        return (lo + hi - 1).astype(v.dtype) / 2.0

    ra, rb = ranks(a), ranks(b)
    ra = ra - ra.mean()
    rb = rb - rb.mean()
    denom = jnp.sqrt((ra**2).sum() * (rb**2).sum())
    return (ra * rb).sum() / jnp.where(denom == 0, 1.0, denom)


def fan_chunk_geometry(batch_size: int, fan: int) -> tuple[int, int | None]:
    """Shared chunk geometry honoring the caller's ``batch_size`` memory cap:
    several images per `lax.map` chunk when the per-image fan is small, an
    inner fan-chunked forward when one sample's fan alone exceeds the cap.
    Returns (images_per_chunk, fan_chunk)."""
    images_per_chunk = max(1, batch_size // fan)
    fan_chunk = batch_size if (images_per_chunk == 1 and fan > batch_size) else None
    return images_per_chunk, fan_chunk


def make_chunked_forward(model_fn, fan_chunk: int | None):
    """Forward over a per-image fan, `lax.map`-chunked when the fan exceeds
    the memory cap (`fan_chunk_geometry`)."""

    def forward(inputs):
        if fan_chunk is not None and fan_chunk < inputs.shape[0]:
            return jax.lax.map(
                lambda r: model_fn(r[None])[0], inputs, batch_size=fan_chunk
            )
        return model_fn(inputs)

    return forward


def batched_auc_runner(
    inputs_fn,
    model_fn,
    images_per_chunk: int,
    return_logits: bool = False,
    fan_chunk: int | None = None,
):
    """One-jit-dispatch insertion/deletion evaluation across an image batch.

    Round 1 looped the batch on the host — jitting per-image perturbation
    and paying a dispatch + host round trip per image, ~1000 of them for the
    reference's ImageNet sweep (`src/helpers.py:328-368`; VERDICT.md round-1
    weak #5). Here the whole batch is ONE jit call: ``lax.map`` (vmap-chunked
    by ``images_per_chunk`` to bound the live perturbation fan at
    images_per_chunk × (n_iter+1) model rows) runs per-sample
    perturbation + forward + class-prob extraction on device, and AUCs for
    every image return in a single transfer.

    ``inputs_fn(x_s, expl_s) -> (M, ...)`` builds one sample's perturbation
    fan (mask generation included; ``expl_s`` may be any pytree).
    ``fan_chunk`` bounds the model rows WITHIN one sample's fan (an inner
    lax.map) for when the fan alone exceeds the caller's batch-size memory
    cap. ``return_logits=True`` returns raw logits rows (the 1D
    input-fidelity argmax path) instead of (scores, prob_curves).
    """

    forward = make_chunked_forward(model_fn, fan_chunk)

    @jax.jit
    def run(xb, explb, yb):
        def one(args):
            xs, es, lab = args
            logits = forward(inputs_fn(xs, es))
            if return_logits:
                return logits
            return jnp.take(softmax_probs(logits), lab, axis=1)

        out = jax.lax.map(one, (xb, explb, yb), batch_size=images_per_chunk)
        if return_logits:
            return out
        return compute_auc(out), out

    return run


def run_cached_auc(
    cache: dict,
    key_extra,
    inputs_fn,
    model_fn,
    batch_size: int,
    n_iter: int,
    x,
    expl,
    y,
    return_logits: bool = False,
):
    """Memoized `batched_auc_runner` invocation shared by the evaluators.

    Chunk geometry honors the caller's ``batch_size`` memory cap in both
    regimes: several images per chunk when the fan is small, an inner
    fan-chunked forward when one sample's fan alone exceeds it."""
    import numpy as np

    images_per_chunk, fan_chunk = fan_chunk_geometry(batch_size, n_iter + 1)
    key = (n_iter, return_logits, tuple(x.shape[1:]), key_extra)
    runner = cache.get(key)
    if runner is None:
        runner = batched_auc_runner(
            inputs_fn, model_fn, images_per_chunk, return_logits, fan_chunk
        )
        cache[key] = runner
    out = runner(x, expl, jnp.asarray(y))
    if return_logits:
        return list(np.asarray(out))
    scores, ps = out
    return [float(v) for v in scores], [np.asarray(p) for p in ps]


def make_probs_fn(model_fn, batch_size: int = 128, mesh=None, data_axis: str = "data"):
    """Build a `probs(inputs, label) -> (M,)` class-probability extractor.

    Without a mesh: single-device, chunked by ``batch_size``. With a mesh:
    the whole perturbation batch runs as ONE forward sharded over
    ``data_axis`` (the SURVEY.md §2.10 evaluation fan-out), cyclically
    padded to the axis multiple and sliced back.
    """
    if mesh is None:

        def probs_fn(inputs, label):
            chunks = []
            for i in range(0, inputs.shape[0], batch_size):
                logits = model_fn(inputs[i : i + batch_size])
                chunks.append(softmax_probs(logits)[:, label])
            return jnp.concatenate(chunks)

        return probs_fn

    from jax.sharding import NamedSharding, PartitionSpec

    @jax.jit
    def run(padded, lab):
        return jnp.take(softmax_probs(model_fn(padded)), lab, axis=1)

    n = mesh.shape[data_axis]
    # Per-dispatch cap: batch_size per shard (a huge fan — e.g. μ-fidelity
    # with a large sample_size — must not exceed per-device memory just
    # because a mesh is attached; round-1 ADVICE.md item 1).
    chunk = max(batch_size, 1) * n

    def probs_fn(inputs, label):
        lab = jnp.asarray(label)
        sharding = NamedSharding(mesh, PartitionSpec(data_axis))
        outs = []
        for i in range(0, inputs.shape[0], chunk):
            part = inputs[i : i + chunk]
            m = part.shape[0]
            pad = (-m) % n
            if pad:
                # cyclic tiling handles pad > m (mesh wider than the batch)
                part = jnp.resize(part, (m + pad,) + part.shape[1:])
            part = jax.device_put(part, sharding)
            outs.append(run(part, lab)[:m])
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs)

    return probs_fn
