"""Shared metric machinery: AUC, nested insertion/deletion masks, softmax
probabilities, min-max normalization, Spearman rank correlation.

Vectorized restatement of `src/evaluation_helpers.py:395-499` — the
reference's Python mask loop becomes one broadcast comparison against the
rank array, and the whole (n_iter+1)-mask family is a single (n+1, ...)
tensor ready for a vmapped reconstruction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from wam_tpu.evalsuite.fan import (  # noqa: F401  (re-exported: pre-fan import sites)
    FanPlan,
    cast_model_fn,
    fan_chunk_geometry,
    fan_runner,
    make_chunked_forward,
    make_sharded_runner,
    plan_fan,
    run_fan,
)

__all__ = [
    "softmax_probs",
    "compute_auc",
    "generate_masks",
    "minmax_normalize",
    "spearman",
    "batched_auc_runner",
    "batch_fingerprint",
    "make_sharded_runner",
    "mu_fidelity_draws",
    "run_cached_auc",
    "fan_chunk_geometry",
    "make_chunked_forward",
]


def batch_fingerprint(x, y) -> tuple:
    """Identity of an evaluation batch for the explanation caches:
    ``(shape, dtype, labels)``. Cheap host-side values only — both inputs
    are concrete (numpy or committed) arrays by the time the evaluators
    fingerprint them."""
    import numpy as np

    ys = () if y is None else tuple(int(v) for v in np.asarray(y).reshape(-1))
    return (tuple(x.shape), str(x.dtype), ys)


def softmax_probs(logits: jax.Array) -> jax.Array:
    return jax.nn.softmax(logits, axis=-1)


def compute_auc(probs: jax.Array) -> jax.Array:
    """sum(p) / (max(p) · len(p)) over the last axis
    (`src/evaluation_helpers.py:437-453`)."""
    denom = jnp.max(probs, axis=-1) * probs.shape[-1]
    return jnp.sum(probs, axis=-1) / jnp.where(denom == 0, 1.0, denom)


def generate_masks(n_iter: int, attribution: jax.Array, signed: bool = False):
    """Nested insertion/deletion masks from an attribution map of any shape.

    Returns (insertion, deletion), each (n_iter+1, *attribution.shape):
    insertion[k] keeps the top k·(size/n_iter) most-important cells
    (insertion[0] empty, insertion[-1] full); deletion is the complement
    family starting full. Importance is the raw value (2D reference,
    `src/evaluation_helpers.py:455-499`) or |value| when ``signed``
    (1D reference, `src/evaluators.py:87`).
    """
    flat = attribution.reshape(-1)
    if signed:
        flat = jnp.abs(flat)
    n = flat.shape[0]
    order = jnp.argsort(-flat)  # descending
    rank = jnp.zeros(n, dtype=jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    n_components = n // n_iter
    ks = jnp.arange(1, n_iter + 1, dtype=jnp.int32) * n_components  # (n_iter,)
    keep = rank[None, :] < ks[:, None]  # (n_iter, n)
    ins = jnp.concatenate([jnp.zeros((1, n), bool), keep], axis=0)
    ins = ins.at[-1].set(True)  # last mask keeps everything
    dele = jnp.concatenate([jnp.ones((1, n), bool), ~keep], axis=0)
    dele = dele.at[-1].set(False)
    shape = (n_iter + 1,) + attribution.shape
    return (
        ins.astype(attribution.dtype).reshape(shape),
        dele.astype(attribution.dtype).reshape(shape),
    )


def minmax_normalize(a: jax.Array) -> jax.Array:
    lo, hi = jnp.min(a), jnp.max(a)
    return (a - lo) / jnp.where(hi > lo, hi - lo, 1.0)


def spearman(a: jax.Array, b: jax.Array) -> jax.Array:
    """Spearman rank correlation of two 1D vectors (scipy.stats.spearmanr
    role in μ-fidelity, `src/evaluators.py:761-763`), on-device.

    Ties receive AVERAGED ranks, matching scipy's default — μ-fidelity
    probability deltas tie routinely (saturated softmax identical to float
    precision), where first-occurrence ranks would diverge from the
    reference (VERDICT.md round-1 weak #6). rank(v) = (#less + (#leq−1)/2),
    via two searchsorted passes on the sorted copy."""

    def ranks(v):
        sv = jnp.sort(v)
        lo = jnp.searchsorted(sv, v, side="left")
        hi = jnp.searchsorted(sv, v, side="right")
        return (lo + hi - 1).astype(v.dtype) / 2.0

    ra, rb = ranks(a), ranks(b)
    ra = ra - ra.mean()
    rb = rb - rb.mean()
    denom = jnp.sqrt((ra**2).sum() * (rb**2).sum())
    return (ra * rb).sum() / jnp.where(denom == 0, 1.0, denom)


def mu_fidelity_draws(cache: dict, seed: int, n_images: int, grid_size: int,
                      sample_size: int, subset_size: int,
                      with_rand_masks: bool):
    """Cached host-side μ-fidelity randomness, in each evaluator's exact
    per-image draw order (continuous baseline-search masks first when used,
    then the feature subsets — `src/evaluators.py:700-760`). Deterministic
    for a fixed seed, so cached per full config INCLUDING the seed:
    regenerating the 1024 `rng.choice` calls at production geometry cost
    ~40% of the μ wall time (round-4 trace). Returns (rand_masks, onehots)
    or just onehots.

    The two tensors are FUSED into one host→device upload (round 6): the
    continuous masks (B, S, g, g) and the subset one-hots (B, S, g²) have
    equal element counts, so they stack into one (B, 2, S, g²) host array
    transferred once — on the tunneled platform each separate upload costs
    its own ~100 ms round trip. The returned arrays are on-device slices of
    that single buffer; call-site signature is unchanged."""
    import numpy as np

    key = (seed, n_images, grid_size, sample_size, subset_size, with_rand_masks)
    cached = cache.get(key)
    if cached is not None:
        return cached
    rng = np.random.default_rng(seed)
    rand_masks, onehots = [], []
    for _ in range(n_images):
        if with_rand_masks:
            rand_masks.append(
                rng.uniform(size=(sample_size, grid_size, grid_size)).astype(np.float32)
            )
        subsets = np.stack(
            [
                rng.choice(grid_size * grid_size, size=subset_size, replace=False)
                for _ in range(sample_size)
            ]
        )
        onehot = np.zeros((sample_size, grid_size * grid_size), dtype=np.float32)
        np.put_along_axis(onehot, subsets, 1.0, axis=1)
        onehots.append(onehot)
    if with_rand_masks:
        g2 = grid_size * grid_size
        fused_host = np.stack(
            [np.stack(rand_masks).reshape(n_images, sample_size, g2),
             np.stack(onehots)],
            axis=1,
        )  # (B, 2, S, g²): ONE tunnel crossing for both tensors
        fused = jnp.asarray(fused_host)
        out = (
            fused[:, 0].reshape(n_images, sample_size, grid_size, grid_size),
            fused[:, 1],
        )
    else:
        out = jnp.asarray(np.stack(onehots))
    cache[key] = out
    return out


def batched_auc_runner(
    inputs_fn,
    model_fn,
    images_per_chunk: int,
    return_logits: bool = False,
    fan_chunk: int | None = None,
    mesh=None,
    data_axis: str = "data",
    donate: bool | None = None,
    aot_key: str | None = None,
    fan_dtype: str = "f32",
):
    """One-jit-dispatch insertion/deletion evaluation across an image batch.

    Round 1 looped the batch on the host — jitting per-image perturbation
    and paying a dispatch + host round trip per image, ~1000 of them for the
    reference's ImageNet sweep (`src/helpers.py:328-368`; VERDICT.md round-1
    weak #5). Here the whole batch is ONE jit call: ``lax.map`` (vmap-chunked
    by ``images_per_chunk`` to bound the live perturbation fan at
    images_per_chunk × (n_iter+1) model rows) runs per-sample
    perturbation + forward + class-prob extraction on device, and AUCs for
    every image return in a single transfer.

    ``inputs_fn(x_s, expl_s) -> (M, ...)`` builds one sample's perturbation
    fan (mask generation included; ``expl_s`` may be any pytree).
    ``fan_chunk`` bounds the model rows WITHIN one sample's fan (an inner
    lax.map) for when the fan alone exceeds the caller's batch-size memory
    cap. ``return_logits=True`` returns raw logits rows (the 1D
    input-fidelity argmax path).

    RETURN-TYPE CHANGE (round 5): the default (non-logits) path now returns
    ONE fused ``(B, 1 + n_iter+1)`` array — column 0 the AUC score, columns
    1: the prob curve — where it previously returned a ``(scores, curves)``
    tuple. Two separate result tensors cost one tunnel round trip each;
    unpack with ``out[:, 0], out[:, 1:]``.

    With ``mesh``, the image batch is sharded over ``data_axis`` via
    `shard_map` — each device runs the identical per-image body on its
    shard (params replicated, no cross-device traffic inside a fan), so the
    on-mesh evaluation is STILL one dispatch (round-4 verdict #4; replaces
    the reference's per-image fan loop, `src/evaluators.py:605-647`). The
    batch is cyclically padded to the axis size and sliced back.

    ``donate`` (None = the shared "TPU-only" policy) donates the ``xb``/
    ``explb`` buffers into the graph — the perturbation fan is the HBM
    hog, so aliasing the inputs frees one batch-sized buffer per call.
    Callers who re-read their arrays after the call must pass copies
    (`pipeline.donation.donation_safe`; `run_cached_auc` does). ``aot_key``
    opts the single-device runner into the AOT executable cache; both are
    ignored on the mesh path (shard_map programs neither donate cleanly
    nor export on the pinned jax).

    ``fan_dtype`` ("f32"/"bf16"/"fp8") wraps the chunked forward in the
    precision boundary shim (`fan.cast_model_fn`): the whole perturbation
    fan casts to the compute dtype once per chunk and the stacked logits
    cast back to f32 BEFORE softmax/AUC, so the rank-forming reductions
    never run low-precision.
    """

    forward = cast_model_fn(make_chunked_forward(model_fn, fan_chunk),
                            fan_dtype)

    def body(xb, explb, yb):
        def one(args):
            xs, es, lab = args
            logits = forward(inputs_fn(xs, es))
            if return_logits:
                return logits
            return jnp.take(softmax_probs(logits), lab, axis=1)

        out = jax.lax.map(one, (xb, explb, yb), batch_size=images_per_chunk)
        if return_logits:
            return out
        # ONE output array [score | curve] per image: two result tensors
        # fetched separately cost one ~100 ms tunnel round trip EACH — the
        # round-5 insertion trace measured 54 ms device inside a 267 ms
        # wall, i.e. the two fetches were 80% of the call
        return jnp.concatenate([compute_auc(out)[:, None], out], axis=1)

    return fan_runner(body, mesh=mesh, data_axis=data_axis, donate=donate,
                      donate_argnums=(0, 1), aot_key=aot_key)


def run_cached_auc(
    cache: dict,
    key_extra,
    inputs_fn,
    model_fn,
    batch_size,
    n_iter: int,
    x,
    expl,
    y,
    return_logits: bool = False,
    mesh=None,
    data_axis: str = "data",
    donate: bool | None = None,
    aot_key: str | None = None,
):
    """Memoized `batched_auc_runner` invocation shared by the evaluators.

    ``batch_size`` is either a resolved `FanPlan` (the evaluators'
    `_fan_plan`, which consults the tuned fan_cap AND fan_chunk schedule)
    or a plain int memory cap whose geometry falls back to the cap//fan
    law. Either way the call ends in EXACTLY ONE result fetch
    (`fan.run_fan`): the fused [score | curve] array — or the raw logits
    tensor on the ``return_logits`` path — crosses the tunnel once.
    ``mesh`` shards the image batch (see `batched_auc_runner`); ``donate``/
    ``aot_key`` are forwarded there, with ``x``/``expl`` routed through
    `donation_safe` so caller-held and instance-cached jax Arrays survive
    the donation (host arrays upload fresh either way)."""
    import numpy as np

    if isinstance(batch_size, FanPlan):
        plan = batch_size
    else:
        plan = FanPlan(batch_size, *fan_chunk_geometry(batch_size, n_iter + 1))
    key = (n_iter, return_logits, tuple(x.shape[1:]), key_extra,
           plan.images_per_chunk, plan.fan_chunk, plan.fan_dtype)
    runner = cache.get(key)
    if runner is None:
        if aot_key is not None:
            # the caller's key identifies model+params; the runner-cache key
            # carries the metric mode / fan geometry this body bakes in, and
            # the synth tag pins the synthesis impl the perturbation fan's
            # reconstructions (eval2d waverec2) will trace under
            from wam_tpu.wavelets.transform import resolved_synth2_impl

            aot_key = f"{aot_key}|auc|{key!r}|synth-{resolved_synth2_impl()}"
        runner = batched_auc_runner(
            inputs_fn, model_fn, plan.images_per_chunk, return_logits,
            plan.fan_chunk, mesh, data_axis, donate, aot_key,
            plan.fan_dtype,
        )
        cache[key] = runner
    # ONE device fetch for the whole call: round 4 batched the per-element
    # float(v)/np.asarray(p) fetches (16 sequential ~100 ms tunnel RTTs)
    # into one per tensor; round 5 fused the two result tensors into one
    # [score | curve] array; the fan engine routes it through the counted
    # `device_fetch` so the single-RTT contract is enforced, not implied
    out = run_fan(runner, (x, expl, jnp.asarray(y)), donate=donate,
                  mesh=mesh, protect=(0, 1))
    if return_logits:
        return list(np.asarray(out))
    arr = np.asarray(out)
    return [float(v) for v in arr[:, 0]], list(arr[:, 1:])
