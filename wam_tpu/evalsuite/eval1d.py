"""Eval1DWAM — audio faithfulness benchmarks (`src/evaluators.py:39-306`):
insertion/deletion AUC with perturbations in either the melspec or the
wavelet domain, faithfulness-of-spectra (Parekh et al.) and input-fidelity
(Paissan et al.).

The reference's per-sample host loops (65 pywt reconstructions + melspec
recomputation per sound) become vmapped on-device mask applications: the
wavelet-domain family is one (n_iter+1, W) batched inverse DWT + melspec.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from wam_tpu.evalsuite.fan import FanPlan, plan_fan
from wam_tpu.evalsuite.metrics import (
    batch_fingerprint as _batch_fingerprint,
    generate_masks,
    run_cached_auc,
)
from wam_tpu.evalsuite.packing import array_to_coeffs1d, coeffs_to_array1d
from wam_tpu.ops.melspec import get_mel_bf16, melspectrogram
from wam_tpu.wam1d import normalize_waveforms
from wam_tpu.wavelets import wavedec, waverec

__all__ = ["Eval1DWAM"]


class Eval1DWAM:
    """``explainer``: callable (x, y) → (melspec grads (B, T, M), coefficient
    grad list); ``model_fn``: melspec batches (B, 1, T, M) → logits."""

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        explainer: Callable,
        wavelet: str = "haar",
        J: int = 3,
        mode: str = "reflect",
        n_mels: int = 128,
        n_fft: int = 1024,
        sample_rate: int = 44100,
        batch_size: int | str = 128,
        mesh=None,
        data_axis: str = "data",
        donate_inputs: bool | None = None,
        aot_key: str | None = None,
        precision=None,
    ):
        """Constructor args are frozen config (the reference's
        constructor-kwargs surface, SURVEY.md §5.6) — build a new evaluator
        to change them. ``mesh``: shard every metric's perturbation-inference
        batch over ``data_axis`` (SURVEY.md §2.10 evaluation fan-out).
        ``batch_size="auto"`` resolves the memory cap per metric from the
        tuned schedule cache (`wam_tpu.tune.resolve_fan_cap`, workload
        "eval1d"), falling back to 128 — the same auto plumbing eval2d and
        the baseline evaluators grew in round 6. ``donate_inputs`` /
        ``aot_key``: see `Eval2DWAM` (same policy and caveats).
        ``precision``: a `config.PrecisionPolicy`, a ``fan_dtype`` string
        ("bf16"/"fp8"), or None — None resolves fan_dtype per metric fan
        (env knob / tuned entry via `plan_fan`) and mel_bf16 once here
        (env knob / melspec global). The mel flag is frozen at
        construction like every other constructor arg."""
        self.model_fn = model_fn
        self.explainer = explainer
        self.wavelet = wavelet
        self.J = J
        self.mode = mode
        self.n_mels = n_mels
        self.n_fft = n_fft
        self.sample_rate = sample_rate
        self.batch_size = batch_size
        self.mesh = mesh
        self.data_axis = data_axis
        self.donate_inputs = donate_inputs
        self.aot_key = aot_key
        from wam_tpu.config import PrecisionPolicy

        if isinstance(precision, str):
            precision = PrecisionPolicy(fan_dtype=precision)
        self._fan_dtype = precision.fan_dtype if precision is not None else None
        # None defers to the melspec-global default (set_mel_bf16 /
        # WAM_TPU_MEL_BF16) at trace time
        self._mel_bf16 = precision.mel_bf16 if precision is not None else None
        self._auc_runners: dict = {}
        self.grad_wams = None
        self._expl_key = None
        self.insertion_curves = []
        self.deletion_curves = []

    def precompute(self, x, y):
        """Compute (or reuse) the cached explanations, fingerprinted on
        ``(shape, dtype, y)`` — a different batch recomputes instead of
        silently reusing stale explanations; directly-assigned
        ``grad_wams`` adopt the first fingerprint they are used with
        (see `Eval2DWAM.precompute`)."""
        key = _batch_fingerprint(x, y)
        if self.grad_wams is not None:
            if self._expl_key is None or self._expl_key == key:
                self._expl_key = key
                return self.grad_wams
        self.grad_wams = self.explainer(x, y)
        self._expl_key = key
        return self.grad_wams

    def reset(self):
        self.grad_wams = None
        self._expl_key = None

    def _fan_plan(self, fan: int) -> FanPlan:
        """Explicit int ``batch_size`` pins the memory cap; "auto" consults
        the tuned schedule cache keyed by this metric's fan (workload
        "eval1d": fan_cap + fan_chunk override)."""
        return plan_fan(self.batch_size, fan, workload="eval1d",
                        fan_dtype=self._fan_dtype)

    def _fan_cap(self, fan: int) -> int:
        return self._fan_plan(fan).cap

    def _melspec(self, wave: jax.Array) -> jax.Array:
        mel = melspectrogram(
            wave, sample_rate=self.sample_rate, n_fft=self.n_fft,
            n_mels=self.n_mels, bf16=self._mel_bf16,
        )
        return mel[:, None, :, :]  # (B, 1, T, M)

    # -- perturbation families --------------------------------------------

    def perturbed_from_melspec(self, grad_mel: jax.Array, source_mel: jax.Array, mode: str, n_iter: int):
        """(T, M) grads + source → (n_iter+1, 1, T, M) masked melspecs
        (`src/evaluators.py:145-176`)."""
        ins, dele = generate_masks(n_iter, grad_mel)
        masks = ins if mode == "insertion" else dele
        return (masks * source_mel[None])[:, None]

    def perturbed_from_wavelet(self, wave: jax.Array, grads, mode: str, n_iter: int):
        """Flattened multi-scale masks on the coefficients of one waveform
        (W,) → (n_iter+1, 1, T, M) melspecs of the reconstructions
        (`src/evaluators.py:56-143`)."""
        coeffs = wavedec(wave[None], self.wavelet, level=self.J, mode=self.mode)
        lengths = [c.shape[-1] for c in coeffs]
        flat_grads = coeffs_to_array1d([jnp.asarray(g) for g in grads])
        ins, dele = generate_masks(n_iter, flat_grads, signed=True)
        masks = ins if mode == "insertion" else dele  # (n+1, total)
        packed = coeffs_to_array1d([c[0] for c in coeffs])  # (total,)
        masked = packed[None] * masks
        rec = waverec(
            [c for c in array_to_coeffs1d(masked, lengths)], self.wavelet
        )[..., : wave.shape[-1]]
        # renormalize each reconstruction like the reference (wf / wf.max())
        peak = jnp.max(rec, axis=-1, keepdims=True)
        rec = rec / jnp.where(jnp.abs(peak) > 0, peak, 1.0)
        return self._melspec(rec)

    # -- metrics -----------------------------------------------------------

    def evaluate_auc(self, x, y, mode: str, target: str, n_iter: int = 64, argmax: bool = False):
        x = normalize_waveforms(x)
        y = np.asarray(y)
        mel_grads, coeff_grads = self.precompute(x, y)
        source_mels = self._melspec(x)[:, 0]

        if target == "melspec":
            expl = (jnp.asarray(mel_grads), jnp.asarray(source_mels))

            def inputs_fn(x_s, expl_s):
                grad_mel, source_mel = expl_s
                return self.perturbed_from_melspec(grad_mel, source_mel, mode, n_iter)

        elif target == "wavelet":
            expl = tuple(jnp.asarray(g) for g in coeff_grads)

            def inputs_fn(x_s, expl_s):
                return self.perturbed_from_wavelet(x_s, list(expl_s), mode, n_iter)

        else:
            raise ValueError(f"Unknown target {target!r}")

        # one jit dispatch for the whole batch (VERDICT.md round-1 #6);
        # the argmax (input-fidelity) variant returns raw logit rows. With a
        # mesh, the sample axis is sharded inside the same runner — no
        # per-sample host loop in any configuration (r4 verdict #4).
        # the mel flag is part of the traced program, so it must be part of
        # the runner-cache key (and through it the AOT key): a bf16-mel
        # runner must never serve an f32-mel call
        mel_bf16 = (self._mel_bf16 if self._mel_bf16 is not None
                    else get_mel_bf16())
        return run_cached_auc(
            self._auc_runners,
            (mode, target, mel_bf16),
            inputs_fn,
            self.model_fn,
            self._fan_plan(n_iter + 1),
            n_iter,
            x,
            expl,
            y,
            return_logits=argmax,
            mesh=self.mesh,
            data_axis=self.data_axis,
            donate=self.donate_inputs,
            aot_key=self.aot_key,
        )

    def insertion(self, x, y, target: str = "wavelet", n_iter: int = 64):
        scores, curves = self.evaluate_auc(x, y, "insertion", target, n_iter)
        self.insertion_curves = curves
        return scores

    def deletion(self, x, y, target: str = "wavelet", n_iter: int = 64):
        scores, curves = self.evaluate_auc(x, y, "deletion", target, n_iter)
        self.deletion_curves = curves
        return scores

    def faithfulness_of_spectra(self, x, y, target: str = "wavelet"):
        """FF_i = p(full) − p(half-deleted) via deletion with n_iter=2
        (`src/evaluators.py:247-277`)."""
        _, curves = self.evaluate_auc(x, y, "deletion", target, n_iter=2)
        arr = np.asarray(curves)
        return (arr[:, 0] - arr[:, 1]).tolist()

    def input_fidelity(self, x, y, target: str = "wavelet"):
        """Argmax agreement between masked-only and full input, insertion
        n_iter=2 (`src/evaluators.py:279-306`)."""
        raw = self.evaluate_auc(x, y, "insertion", target, n_iter=2, argmax=True)
        preds = np.asarray(raw)[:, 1:, :]  # drop the empty-signal row
        return np.argmax(preds, axis=2).tolist()
