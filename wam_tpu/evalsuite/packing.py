"""Invertible coefficient↔array packing (the pywt coeffs_to_array /
array_to_coeffs role, `src/evaluation_helpers.py:521-531`,
`src/analyzers_helpers.py:67-77`) — pure index arithmetic on static shapes,
jit/vmap-safe, so evaluation masks can be applied in one fused multiply.

2D layout matches the attribution mosaic quadrants (approx top-left, H
top-right, V bottom-left, D diagonal); levels may be non-dyadic (long
filters) — the array grows to fit, like pywt's padded layout.

1D layout is the flattened concatenation [cA_J | cD_J | ... | cD_1] used by
the reference's flattened multi-scale masks (`src/evaluators.py:56-143`).

Fan-engine contract (evalsuite/fan.py): these pack/unpack calls execute
INSIDE the jitted fan step — masked packed-array multiplies and the
reconstructions they feed never leave the device, so a metric's per-chunk
work stays device-resident and only the reduced result crosses the host
boundary (one `device_fetch` per metric call). Keeping the index
arithmetic static-shape (no traced values in offsets) is what makes that
legal under jit.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from wam_tpu.wavelets import Detail2D

__all__ = [
    "coeffs_to_array1d",
    "array_to_coeffs1d",
    "coeffs_to_array2d",
    "array_to_coeffs2d",
    "packed2d_shape",
]


# -- 1D ---------------------------------------------------------------------


def coeffs_to_array1d(coeffs: Sequence[jax.Array]) -> jax.Array:
    """[cA_J, cD_J, ..., cD_1] (each (..., n_i)) → (..., Σ n_i)."""
    return jnp.concatenate(list(coeffs), axis=-1)


def array_to_coeffs1d(arr: jax.Array, lengths: Sequence[int]) -> list[jax.Array]:
    out, off = [], 0
    for n in lengths:
        out.append(arr[..., off : off + n])
        off += n
    return out


# -- 2D ---------------------------------------------------------------------


def _level_layout(shapes: Sequence[tuple[int, int]]):
    """Per-level block sizes: t_j = elementwise max(prev packed, detail),
    packed after level j = 2·t_j (pywt pads the smaller side to fit)."""
    p = tuple(shapes[0])
    layout = []
    for d in shapes[1:]:
        t = (max(p[0], d[0]), max(p[1], d[1]))
        layout.append((t, tuple(d)))
        p = (2 * t[0], 2 * t[1])
    return layout, p


def packed2d_shape(coeffs) -> tuple[int, int]:
    shapes = [tuple(coeffs[0].shape[-2:])] + [tuple(d.diagonal.shape[-2:]) for d in coeffs[1:]]
    return _level_layout(shapes)[1]


def _pad_to(a: jax.Array, h: int, w: int) -> jax.Array:
    ph, pw = h - a.shape[-2], w - a.shape[-1]
    if ph == 0 and pw == 0:
        return a
    widths = [(0, 0)] * (a.ndim - 2) + [(0, ph), (0, pw)]
    return jnp.pad(a, widths)


def coeffs_to_array2d(coeffs) -> jax.Array:
    """[cA, Detail2D_J..1] → one packed array, block-recursive:
    arr_j = [[arr_{j+1}, H], [V, D]] with both sides zero-padded to the
    level block size. Leading batch/channel dims pass through."""
    arr = coeffs[0]
    for det in coeffs[1:]:
        dh, dw = det.diagonal.shape[-2:]
        th = max(arr.shape[-2], dh)
        tw = max(arr.shape[-1], dw)
        P = _pad_to(arr, th, tw)
        H = _pad_to(det.horizontal, th, tw)
        V = _pad_to(det.vertical, th, tw)
        D = _pad_to(det.diagonal, th, tw)
        arr = jnp.concatenate(
            [jnp.concatenate([P, H], axis=-1), jnp.concatenate([V, D], axis=-1)], axis=-2
        )
    return arr


def array_to_coeffs2d(arr: jax.Array, shapes: Sequence[tuple[int, int]]) -> list:
    """Inverse of `coeffs_to_array2d`. ``shapes`` = [(hA, wA), (h_J, w_J),
    ..., (h_1, w_1)] — approx then per-level detail shapes, coarse→fine
    (grab them from a reference decomposition)."""
    layout, _ = _level_layout(shapes)
    details = []
    for (th, tw), (dh, dw) in reversed(layout):
        H = arr[..., :dh, tw : tw + dw]
        V = arr[..., th : th + dh, :dw]
        D = arr[..., th : th + dh, tw : tw + dw]
        details.append(Detail2D(horizontal=H, vertical=V, diagonal=D))
        arr = arr[..., :th, :tw]
    hA, wA = shapes[0]
    approx = arr[..., :hA, :wA]
    return [approx] + details[::-1]
