"""Shared eval fan engine: chunk planning, dispatch, and the single result
fetch for every fan-shaped faithfulness metric (insertion/deletion AUC,
μ-fidelity, input fidelity, the baseline comparison fans).

Fan-step contract
-----------------
A metric's *fan step* is one pure function ``body(*device_args) -> result``
traced once and dispatched once per metric call:

- masks, perturbed inputs, and one-hot label gathers are constructed
  ON-DEVICE inside the step — the host uploads raw inputs plus cached
  randomness once per batch, never a per-chunk masked copy of it;
- per-image fans run under ``lax.map`` chunked to `FanPlan
  .images_per_chunk` (with an inner fan-chunked forward when one sample's
  fan alone exceeds the cap), so metric reductions — μ-fidelity Spearman
  correlations, AUC partial sums — accumulate DEVICE-RESIDENT across
  chunks instead of round-tripping per chunk;
- the reduced result crosses back in EXACTLY ONE fetch (`device_fetch`)
  per metric call. On the tunneled platform each extra fetch is its own
  ~100 ms round trip (round-5 insertion trace: 54 ms device inside a
  267 ms wall — the second result tensor was 40% of the call).

`plan_fan` supplies the tuned chunk geometry (the round-6 ``fan_cap``
schedule plus this round's ``fan_chunk`` images-per-chunk override),
`fan_runner` the shared dispatch (jit with TPU-only donation, AOT
executable cache, or the shard_map mesh path), and `run_fan` the
donation-protected invocation that ends in the single fetch.
"""

from __future__ import annotations

import dataclasses
import threading

import jax
import jax.numpy as jnp

from wam_tpu.obs import tracing as _obs_tracing
from wam_tpu.obs.registry import registry as _registry

__all__ = [
    "FanPlan",
    "plan_fan",
    "fan_chunk_geometry",
    "cast_model_fn",
    "make_chunked_forward",
    "make_sharded_runner",
    "fan_runner",
    "run_fan",
    "device_fetch",
    "fetch_count",
    "reset_fetch_count",
    "fetch_scope",
]


# -- the single result fetch ----------------------------------------------

_FETCH_COUNT = 0
_fetch_tls = threading.local()  # per-thread stack of live fetch_scopes

_c_fetches = _registry.counter(
    "wam_tpu_fan_result_fetches_total",
    "device_fetch calls (one per fan metric call is the contract)")


def device_fetch(out):
    """THE result fetch: one `jax.device_get` of the whole result tree.

    Every fan metric funnels its device→host transfer through here, so the
    one-fetch contract is testable three ways: a `fetch_scope()` delta (the
    scoped counter — preferred), the legacy process-global `fetch_count()`,
    or patching ``jax.device_get`` itself (the call is late-bound on
    purpose — tests monkeypatch the attribute and count). Each call also
    lands on the obs registry's fan-fetch counter."""
    global _FETCH_COUNT
    _FETCH_COUNT += 1
    for scope in getattr(_fetch_tls, "scopes", ()):
        scope._count += 1
    _c_fetches.inc()
    return jax.device_get(out)


def fetch_count() -> int:
    """Number of `device_fetch` calls since import / last reset — the
    legacy PROCESS-GLOBAL counter (scripts/bench_eval.py per-row deltas).
    Concurrent threads (fleet replicas, parallel test runs) all bump it;
    for an isolated count use `fetch_scope`."""
    return _FETCH_COUNT


def reset_fetch_count() -> None:
    global _FETCH_COUNT
    _FETCH_COUNT = 0


class fetch_scope:
    """Scoped, thread-isolated fetch counter:

        with fetch_scope() as fs:
            metric(...)
        assert fs.count == 1

    Counts only `device_fetch` calls made by THE CURRENT THREAD while the
    scope is live, so fleet replica workers and parallel test runs cannot
    cross-contaminate each other's probes (the process-global
    `fetch_count` cannot make that promise). Scopes nest — each level
    counts independently. ``count`` stays readable after exit."""

    def __init__(self):
        self._count = 0

    @property
    def count(self) -> int:
        return self._count

    def __enter__(self) -> "fetch_scope":
        scopes = getattr(_fetch_tls, "scopes", None)
        if scopes is None:
            scopes = _fetch_tls.scopes = []
        scopes.append(self)
        return self

    def __exit__(self, *exc):
        _fetch_tls.scopes.remove(self)
        return False


# -- chunk geometry --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FanPlan:
    """Resolved chunk geometry for one metric's perturbation fan.

    ``cap``: the memory cap in model rows (tuned ``fan_cap`` or the
    caller's explicit batch_size). ``images_per_chunk``: images per
    ``lax.map`` chunk of the fan step. ``fan_chunk``: inner per-sample
    chunk when one sample's fan alone exceeds the cap (else None).
    ``fan_dtype``: the fan forward's compute dtype ("f32"/"bf16"/"fp8" —
    `config.PrecisionPolicy`); part of the plan because it is part of the
    traced program — every runner cache / AOT key derived from a plan must
    separate dtypes or a schedule flip replays the wrong executable."""

    cap: int
    images_per_chunk: int
    fan_chunk: int | None
    fan_dtype: str = "f32"


def fan_chunk_geometry(batch_size: int, fan: int) -> tuple[int, int | None]:
    """Shared chunk geometry honoring the caller's ``batch_size`` memory cap:
    several images per `lax.map` chunk when the per-image fan is small, an
    inner fan-chunked forward when one sample's fan alone exceeds the cap.
    Returns (images_per_chunk, fan_chunk)."""
    images_per_chunk = max(1, batch_size // fan)
    fan_chunk = batch_size if (images_per_chunk == 1 and fan > batch_size) else None
    return images_per_chunk, fan_chunk


def plan_fan(batch_size, fan: int, *, workload: str = "eval2d",
             shape=None, default: int = 128,
             fan_dtype: str | None = None) -> FanPlan:
    """Tuned fan geometry for one metric call.

    Explicit int ``batch_size`` pins the cap (the caller's memory budget —
    the pre-round-6 contract, geometry derived by the cap//fan law).
    ``"auto"`` consults the schedule cache twice: ``fan_cap`` via
    `wam_tpu.tune.resolve_fan_cap` (round 6), and — new this round — a
    tuned ``fan_chunk`` entry that overrides images_per_chunk directly
    (the autotuner's `Candidate.fan_chunk` sweep axis: at a fixed cap the
    law picks one images-per-chunk, but the best lax.map chunk on real
    hardware need not equal cap//fan).

    ``fan_dtype`` pins the fan compute dtype; None resolves it the policy
    way (`config.resolve_precision`): ``WAM_TPU_FAN_DTYPE`` env knob, then
    — under ``"auto"`` geometry only, like the cap — the tuned entry's
    ``fan_dtype`` axis, then f32."""
    from wam_tpu.config import resolve_precision
    from wam_tpu.tune import resolve_fan_cap

    cap = resolve_fan_cap(batch_size, fan, workload=workload, shape=shape,
                          default=default)
    images_per_chunk, fan_chunk = fan_chunk_geometry(cap, fan)
    if batch_size == "auto":
        from wam_tpu.tune.cache import lookup_schedule

        ent = lookup_schedule(workload, shape or (fan,), fan)
        if ent and ent.get("fan_chunk"):
            images_per_chunk = max(1, int(ent["fan_chunk"]))
            if images_per_chunk > 1:
                fan_chunk = None  # several whole images per chunk: no inner split
    policy = resolve_precision(
        workload if batch_size == "auto" else None,
        shape or (fan,), fan, fan_dtype=fan_dtype)
    return FanPlan(cap, images_per_chunk, fan_chunk, policy.fan_dtype)


def cast_model_fn(model_fn, fan_dtype: str):
    """Precision boundary shim for the fan forward: inputs cast to the
    policy compute dtype ONCE at the jit boundary, logits cast back to f32
    so every reduction downstream (softmax, AUC trapezoid, Spearman)
    accumulates in f32. "f32" returns ``model_fn`` unchanged — zero traced
    ops. Pair with params bound at the same dtype
    (`models.bind_inference(compute_dtype=...)` /
    `EvalBaselines(compute_dtype=...)`) for the MXU win; against f32
    params the cast is promoted away by XLA — safe, just not faster."""
    from wam_tpu.config import PrecisionPolicy, compute_cast

    dtype = PrecisionPolicy(fan_dtype=fan_dtype).compute_dtype()
    if dtype is None:
        return model_fn

    def cast_fn(x):
        low = compute_cast(x, dtype)
        return model_fn(low).astype(jnp.float32)

    return cast_fn


def make_chunked_forward(model_fn, fan_chunk: int | None):
    """Forward over a per-image fan, `lax.map`-chunked when the fan exceeds
    the memory cap (`fan_chunk_geometry`)."""

    def forward(inputs):
        if fan_chunk is not None and fan_chunk < inputs.shape[0]:
            return jax.lax.map(
                lambda r: model_fn(r[None])[0], inputs, batch_size=fan_chunk
            )
        return model_fn(inputs)

    return forward


# -- dispatch --------------------------------------------------------------


def _pad_to_multiple(tree, n: int):
    """Cyclically pad every leaf's axis 0 to a multiple of ``n``; returns
    (padded_tree, original_len). Per-image metrics ignore the pad rows."""
    lead = jax.tree_util.tree_leaves(tree)[0].shape[0]
    pad = (-lead) % n
    if pad == 0:
        return tree, lead
    return (
        jax.tree_util.tree_map(
            lambda a: jnp.resize(a, (lead + pad,) + a.shape[1:]), tree
        ),
        lead,
    )


def make_sharded_runner(body, mesh, data_axis: str = "data"):
    """jit(shard_map(body)) sharding axis 0 of every positional arg over
    ``data_axis``, with cyclic padding to the axis size and slice-back of
    every output leaf — the one-dispatch on-mesh evaluation shape shared by
    the AUC and μ-fidelity runners (round-4 verdict #4)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from wam_tpu.compat import shard_map

    sharded = jax.jit(
        partial(shard_map, mesh=mesh, in_specs=P(data_axis),
                out_specs=P(data_axis))(body)
    )

    def run(*args):
        args, lead = _pad_to_multiple(args, mesh.shape[data_axis])
        out = sharded(*args)
        return jax.tree_util.tree_map(lambda a: a[:lead], out)

    return run


def fan_runner(body, *, mesh=None, data_axis: str = "data",
               donate: bool | None = None, donate_argnums: tuple = (),
               aot_key: str | None = None):
    """The shared dispatch wrapper every fan step goes through.

    Single device: ``jax.jit`` with ``donate_argnums`` under the shared
    TPU-only donation policy (`pipeline.donation.resolve_donate`), or the
    AOT executable cache (`pipeline.aot.cached_entry`) when the caller
    supplies an ``aot_key`` (which must identify model + params — exported
    modules bake them in). With ``mesh``, `make_sharded_runner` shards
    axis 0 over ``data_axis``; donation and AOT are ignored there
    (shard_map programs neither donate cleanly nor export on the pinned
    jax)."""
    if mesh is not None:
        return make_sharded_runner(body, mesh, data_axis)
    from wam_tpu.pipeline.donation import resolve_donate

    argnums = tuple(donate_argnums) if resolve_donate(donate) else ()
    if aot_key is not None:
        from wam_tpu.pipeline.aot import cached_entry

        return cached_entry(body, aot_key, donate_argnums=argnums,
                            obs_kind="fan")

    from wam_tpu.obs import sentinel as _obs_sentinel

    def probed(*step_args):
        # trace-time only: fan-step compiles land on the compile sentinel
        _obs_sentinel.record_trace("fan", detail=getattr(body, "__name__", ""))
        return body(*step_args)

    return jax.jit(probed, donate_argnums=argnums)


def run_fan(runner, args: tuple, *, donate: bool | None = None, mesh=None,
            protect: tuple = ()):
    """Invoke a fan runner and fetch its result ONCE.

    ``protect``: positional indices routed through `donation_safe` when
    donation is active (mirror of the runner's donate_argnums) — instance-
    cached and caller-held jax Arrays survive the donation; host arrays
    upload fresh either way, no extra copy on the common path. Returns the
    host-side (numpy) result of the single `device_fetch`."""
    from wam_tpu.pipeline.donation import donation_safe, resolve_donate

    donating = mesh is None and resolve_donate(donate)
    if donating and protect:
        args = tuple(
            donation_safe(a, True) if i in protect else a
            for i, a in enumerate(args)
        )
    with _obs_tracing.span("fan.dispatch", cat="fan"):
        out = runner(*args)
    from wam_tpu.obs.health import batch_stats, fan_health_enabled, publish_stats

    if fan_health_enabled():
        # numeric-health piggyback: one extra tiny DISPATCH
        # (`batch_stats` is its own jitted reduction over the result
        # tree), zero extra FETCHES — the 6-float vector rides the
        # metric's single `device_fetch` below, so the one-fetch
        # contract (`fetch_scope` probes) is untouched.
        stats = batch_stats(out)
        with _obs_tracing.span("fan.fetch", cat="fan"):
            host, host_stats = device_fetch((out, stats))
        publish_stats(host_stats, source="fan")
        return host
    with _obs_tracing.span("fan.fetch", cat="fan"):
        return device_fetch(out)
