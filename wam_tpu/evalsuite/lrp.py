"""Layer-wise Relevance Propagation for the ResNet zoo, native JAX.

Faithful counterpart of the reference's `lrp` registry entry — zennit's
`EpsilonPlusFlat` composite with a `ResNetCanonizer`
(`/root/reference/src/evaluators.py:885-899`):

- **canonizer**: BatchNorm is folded into the preceding conv
  (`wam_tpu.models.resnet._fold_bn_variables`), so every linear site is one
  conv-plus-bias layer;
- **Flat** rule on the first (stem) conv: relevance is spread uniformly over
  the receptive field (modified input = 1, modified weight = 1);
- **ZPlus** rule on every other conv: only positive contributions carry
  relevance, z+ = conv(x+, W+) + conv(x-, W-);
- **Epsilon** rule on dense layers: R_in = x ⊙ Wᵀ(R / (z + ε·sign z));
- maxpool routes relevance winner-take-all (its exact VJP), average pooling
  spreads proportionally, residual additions split relevance in proportion
  to each branch's activation, and ReLU passes relevance through.

Each per-layer step is the generic ρ-rule
    R_in = x_in ⊙ ρ(W)ᵀ[R_out / (z_ρ + ε·sign z_ρ)],  z_ρ = ρ-forward(x_in)
computed with `jax.vjp` of the ρ-modified layer forward — per-layer
conservation (up to the ε stabilizer and bias absorption) is tested in
tests/test_evalsuite.py.

The walker mirrors `wam_tpu.models.resnet.ResNet.__call__` structurally and
reads every site's activations from one `capture_intermediates` forward.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["lrp_resnet"]

_DN = ("NHWC", "HWIO", "NHWC")


def _stab(z: jax.Array, eps: float) -> jax.Array:
    s = z + eps * jnp.sign(z)
    return jnp.where(s == 0, eps if eps > 0 else 1.0, s)


def _rho_step(rho_fwd: Callable, x_in: jax.Array, R: jax.Array, eps: float) -> jax.Array:
    """Generic LRP ρ-rule: R_in = x ⊙ ρ(W)ᵀ[R / (z_ρ + ε sign z_ρ)]."""
    z, vjp = jax.vjp(rho_fwd, x_in)
    (c,) = vjp(R / _stab(z, eps))
    return x_in * c


def _conv_fwd(W, b, stride):
    def f(t):
        out = lax.conv_general_dilated(
            t, W, (stride, stride), [(W.shape[0] // 2,) * 2, (W.shape[1] // 2,) * 2],
            dimension_numbers=_DN,
        )
        return out if b is None else out + b
    return f


def _conv_site(x_in, W, b, stride, R, rule: str, eps: float):
    """One conv(+folded-BN bias) site under the given rule."""
    if rule == "zplus":
        Wp, Wn = jnp.maximum(W, 0.0), jnp.minimum(W, 0.0)
        xp, xn = jnp.maximum(x_in, 0.0), jnp.minimum(x_in, 0.0)
        # zennit's ZPlus pairs the clamped-positive bias with the (x+, W+)
        # branch and ZEROES the bias in the (x-, W-) branch — the bias term
        # enters z (stabilizing the denominator and absorbing relevance) but
        # receives none itself (round-2 advisor finding: post-canonization
        # every conv carries a folded-BN bias, so omitting it deviated).
        bp = None if b is None else jnp.maximum(b, 0.0)

        def zfwd(pair):
            p, n = pair
            z = _conv_fwd(Wp, None, stride)(p) + _conv_fwd(Wn, None, stride)(n)
            return z if bp is None else z + bp

        z, vjp = jax.vjp(zfwd, (xp, xn))
        cp, cn = vjp(R / _stab(z, eps))[0]
        return xp * cp + xn * cn
    if rule == "flat":
        ones_W = jnp.ones_like(W)
        ones_x = jnp.ones_like(x_in)
        z, vjp = jax.vjp(_conv_fwd(ones_W, None, stride), ones_x)
        (c,) = vjp(R / _stab(z, eps))
        return ones_x * c
    # epsilon
    return _rho_step(_conv_fwd(W, b, stride), x_in, R, eps)


def _maxpool_route(x_in, R):
    """Winner-take-all relevance routing through the 3x3/2 stem pool."""
    pool = lambda t: nn.max_pool(t, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
    _, vjp = jax.vjp(pool, x_in)
    return vjp(R)[0]


def _add_split(a, b, R, eps):
    """Residual add: relevance splits in proportion to the branch values."""
    tot = _stab(a + b, eps)
    return R * a / tot, R * b / tot


def _bn_bias(params, name):
    """Post-fold BN is the pure shift beta' (scale 1, mean 0, var 1-eps)."""
    return params[name]["bias"]


# jitted-walker cache: the walker body is ~260 conv/VJP ops; dispatched
# eagerly over the tunneled TPU each op pays the ~100 ms host RTT, which is
# where the round-3 "216 s per LRP explain" went (compile-inclusive row in
# methods_tpu.jsonl). One jit turns that into a single dispatch; keyed per
# (model-config, composite, eps, nchw) with jax.jit's own shape cache
# underneath.
_JIT_CACHE: dict = {}


def lrp_resnet(
    model,
    variables,
    x: jax.Array,
    y,
    *,
    eps: float = 1e-6,
    composite: str = "epsilon_plus_flat",
    nchw: bool = True,
) -> jax.Array:
    """EpsilonPlusFlat LRP through a `wam_tpu.models.resnet.ResNet`.

    Returns the (B, H, W) channel-summed input relevance, seeded with a
    plain one-hot at the picked class (output relevance = 1), matching the
    reference's zennit attribution semantics (`src/evaluators.py:885-899`,
    Gradient attributor seeded with a one-hot at `:950-952`).
    composite="epsilon" applies the ε-rule everywhere instead (no ZPlus/Flat).
    """
    from wam_tpu.models.resnet import ResNet

    if not isinstance(model, ResNet):
        raise ValueError(
            f"lrp_resnet walks the ResNet structure; got {type(model).__name__}"
        )
    if model.stem_s2d:
        model = model.clone(stem_s2d=False)  # walker assumes the 7x7 stem form
    key = (model, composite, float(eps), bool(nchw))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            lambda v, xx, yy: _lrp_resnet_body(
                model, v, xx, yy, eps=eps, composite=composite, nchw=nchw
            )
        )
        _JIT_CACHE[key] = fn
    return fn(variables, x, jnp.asarray(y))


def _lrp_resnet_body(model, variables, x, y, *, eps, composite, nchw):
    from wam_tpu.models.resnet import Bottleneck, _fold_bn_variables
    # LRP is an f32-only computation: the ε-stabilizer (1e-6 relative to
    # O(1) activations) vanishes in bf16's 8-bit mantissa, and the walker
    # drives lax.conv directly with raw kernels (no flax promotion). If the
    # caller evaluates at compute_dtype=bf16 (eval_baselines), params are
    # upcast HERE and the relevance map is computed in f32 throughout.
    variables = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32)
        if hasattr(a, "dtype") and a.dtype == jnp.bfloat16
        else a,
        variables,
    )
    folded = _fold_bn_variables(variables)
    params = folded["params"]
    base = {k: v for k, v in folded.items() if k != "perturbations"}
    inp = jnp.transpose(x, (0, 2, 3, 1)) if nchw else x

    logits, state = model.apply(
        base, inp, capture_intermediates=True, mutable=["intermediates"]
    )
    logits = logits[0] if isinstance(logits, tuple) else logits
    inter = state["intermediates"]

    def out_of(*path):
        node = inter
        for p in path:
            node = node[p]
        return node["__call__"][0]

    is_bottleneck = model.block_cls is Bottleneck or (
        getattr(model.block_cls, "func", None) is Bottleneck
    )
    conv_rule = "zplus" if composite == "epsilon_plus_flat" else "epsilon"
    first_rule = "flat" if composite == "epsilon_plus_flat" else "epsilon"

    # ---- output seed: plain one-hot (relevance 1 at the picked class) ------
    # zennit's Gradient attributor is seeded with a one-hot, NOT the logit
    # value (`src/evaluators.py:950-952`) — seeding with onehot*logits would
    # flip the whole map's sign whenever the target logit is negative,
    # inverting insertion/deletion orderings (round-2 advisor finding).
    yy = jnp.asarray(y)
    R = jax.nn.one_hot(yy, logits.shape[-1], dtype=logits.dtype)

    # Reconstruct the stage wiring from captured block outputs.
    n_stages = len(model.stage_sizes)
    blocks_out = {}
    for s in range(n_stages):
        for i in range(model.stage_sizes[s]):
            blocks_out[(s, i)] = out_of(f"layer{s + 1}_{i}")
    last_stage_out = blocks_out[(n_stages - 1, model.stage_sizes[-1] - 1)]
    pooled = last_stage_out.mean(axis=(1, 2))

    # ---- fc (Dense, epsilon rule) ------------------------------------------
    Wfc, bfc = params["fc"]["kernel"], params["fc"]["bias"]
    R = _rho_step(lambda t: t @ Wfc + bfc, pooled, R, eps)

    # ---- global average pool (proportional spread) --------------------------
    B_, H_, W_, C_ = last_stage_out.shape
    z = pooled  # (B, C)
    s = R / _stab(z * (H_ * W_), eps)  # relevance per unit activation
    R = last_stage_out * s[:, None, None, :]

    # ---- stages, backwards --------------------------------------------------
    stem_bn_out = out_of("bn1")
    stem_relu = jax.nn.relu(stem_bn_out)
    stem_pool = nn.max_pool(stem_relu, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

    def stage_input(s):
        if s > 0:
            return blocks_out[(s - 1, model.stage_sizes[s - 1] - 1)]
        return stem_pool

    def _block_step(x_in, bp, acts, stride, R):
        """Relevance through one residual block. ``acts`` holds the captured
        bn outputs; ``bp`` the folded conv params (+ downsample when the
        block has one)."""
        a1 = jax.nn.relu(acts["bn1"])
        if is_bottleneck:
            a2 = jax.nn.relu(acts["bn2"])
            main_out = acts["bn3"]
        else:
            main_out = acts["bn2"]
        res_out = acts["downsample_bn"] if "downsample_conv" in bp else x_in

        # block output = relu(main + res); relevance passes the relu
        R_main, R_res = _add_split(main_out, res_out, R, eps)
        if is_bottleneck:
            R_main = _conv_site(a2, bp["conv3"]["kernel"], _bn_bias(bp, "bn3"),
                                1, R_main, conv_rule, eps)
            R_main = _conv_site(a1, bp["conv2"]["kernel"], _bn_bias(bp, "bn2"),
                                stride, R_main, conv_rule, eps)
            R_main = _conv_site(x_in, bp["conv1"]["kernel"], _bn_bias(bp, "bn1"),
                                1, R_main, conv_rule, eps)
        else:
            R_main = _conv_site(a1, bp["conv2"]["kernel"], _bn_bias(bp, "bn2"),
                                1, R_main, conv_rule, eps)
            R_main = _conv_site(x_in, bp["conv1"]["kernel"], _bn_bias(bp, "bn1"),
                                stride, R_main, conv_rule, eps)
        if "downsample_conv" in bp:
            R_res = _conv_site(x_in, bp["downsample_conv"]["kernel"],
                               _bn_bias(bp, "downsample_bn"),
                               stride, R_res, conv_rule, eps)
        return R_main + R_res

    for s in range(n_stages - 1, -1, -1):
        size = model.stage_sizes[s]
        if size > 1:
            # blocks i >= 1 are homogeneous (stride 1, no downsample, same
            # shapes), so their relevance steps run as ONE lax.scan — the
            # block subgraph compiles once per stage instead of once per
            # block, which is what made the first LRP call ~3x the compile
            # cost of a plain fwd+bwd (BASELINE.md round-4 LRP section).
            # Tradeoff: jnp.stack copies every block's captured activations
            # and folded params while the originals stay live, roughly
            # doubling peak trace-time memory per stage — acceptable for the
            # compile-time win; on ResNet-101-scale stages with large inputs
            # consider deleting blocks_out entries after stacking.
            idxs = list(range(size - 1, 0, -1))  # reversed relevance order

            def stacked(fn):
                return jnp.stack([fn(i) for i in idxs])

            names = [f"layer{s + 1}_{i}" for i in idxs]
            acts_keys = ("bn1", "bn2", "bn3") if is_bottleneck else ("bn1", "bn2")
            xs = {
                "x_in": stacked(lambda i: blocks_out[(s, i - 1)]),
                "acts": {k: stacked(lambda i: out_of(f"layer{s + 1}_{i}", k))
                         for k in acts_keys},
                "bp": jax.tree_util.tree_map(
                    lambda *leaves: jnp.stack(leaves), *(params[n] for n in names)
                ),
            }

            def body(R, t):
                return _block_step(t["x_in"], t["bp"], t["acts"], 1, R), None

            R, _ = lax.scan(body, R, xs)
        # first block of the stage: stride-2 entry (stages > 0) + downsample
        name = f"layer{s + 1}_0"
        acts = {k: out_of(name, k)
                for k in (("bn1", "bn2", "bn3") if is_bottleneck else ("bn1", "bn2"))}
        if "downsample_conv" in params[name]:
            acts["downsample_bn"] = out_of(name, "downsample_bn")
        R = _block_step(stage_input(s), params[name], acts,
                        2 if s > 0 else 1, R)

    # ---- stem (7x7/2 conv = _conv_fwd's pad L//2 = 3 at stride 2) ----------
    R = _maxpool_route(stem_relu, R)
    R = _conv_site(inp, params["conv1"]["kernel"], _bn_bias(params, "bn1"),
                   2, R, first_rule, eps)

    # input relevance map, channel-summed (input layout is always NHWC here)
    return R.sum(axis=-1)
