"""Baseline-method evaluators — `EvalImageBaselines` / `EvalAudioBaselines`
(`src/evaluators.py:805-1180` and `:310-548`): run the classic attribution
methods (saliency / integrated gradients / smoothgrad / GradCAM / GradCAM++ /
LayerCAM) and score them with the same insertion/deletion AUC and μ-fidelity
machinery as WAM, with perturbations applied in the native domain of each
modality (pixels for images, melspec cells for audio).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from wam_tpu.evalsuite import baselines as B
from wam_tpu.evalsuite.eval2d import _minmax01, imagenet_denormalize, imagenet_preprocess
from wam_tpu.evalsuite.fan import (
    FanPlan,
    fan_runner,
    make_chunked_forward,
    plan_fan,
    run_fan,
)
from wam_tpu.evalsuite.metrics import (
    batch_fingerprint as _batch_fingerprint,
    generate_masks,
    run_cached_auc,
    softmax_probs,
    spearman,
)
from wam_tpu.ops.filters import gaussian_filter2d, superpixel_sum, upsample_nearest

__all__ = ["EvalImageBaselines", "EvalAudioBaselines", "IMAGE_METHODS", "AUDIO_METHODS"]

IMAGE_METHODS = (
    "saliency",
    "integratedgrad",
    "smoothgrad",
    "gradcam",
    "gradcampp",
    "layercam",
    "guided_backprop",
    "gradxinput",
    "lrp",
    # transformer-native (wam_tpu.xattr.attention; need a ViT built with
    # capture_attn=True so the softmax weights materialize)
    "rollout",
    "attngrad",
)
AUDIO_METHODS = ("saliency", "integratedgrad", "smoothgrad", "gradcam")


class _BaseEvalBaselines:
    """Shared machinery: method registry + cached explanations + AUC loop.

    Constructor args are frozen config (SURVEY.md §5.6) — build a new
    evaluator to change them. ``mesh`` shards every metric's
    perturbation-inference batch over ``data_axis`` (§2.10)."""

    def __init__(self, model, variables, method: str, batch_size: int | str,
                 random_seed: int,
                 n_samples: int, stdev_spread: float, cam_layer: str, nchw: bool,
                 methods: tuple[str, ...], mesh=None, data_axis: str = "data",
                 compute_dtype=None, donate_inputs: bool | None = None,
                 aot_key: str | None = None, precision=None):
        if method == "srd":
            raise NotImplementedError(
                "'srd' is excluded by design: the reference imports it from a "
                "`lib.srd` package that does not exist in the repository "
                "(src/evaluators.py:33-34), so its semantics cannot be "
                "reproduced faithfully. Permanently retired — see PARITY.md "
                "defect ledger #1. Use 'guided_backprop'/'lrp' instead."
            )
        if method not in methods:
            raise ValueError(f"Unknown method {method!r}; expected one of {methods}")
        if method in ("rollout", "attngrad") and not getattr(model, "capture_attn", False):
            raise ValueError(
                f"method {method!r} reads per-block attention weights — build "
                "the ViT with capture_attn=True (models/vit.py); the stock "
                "attention body never materializes them"
            )
        self.model = model
        # compute_dtype (e.g. jnp.bfloat16, or the policy strings
        # "bf16"/"fp8"): cast float params/stats ONCE so every path — the
        # perturbation-fan model_fn AND the CAM/LRP routes that re-apply
        # self.variables — runs at the same precision; inputs are cast at
        # the model boundary, logits come back float32 (the bind_inference
        # convention, models/resnet.py). ``precision`` (a
        # `config.PrecisionPolicy` or fan_dtype string) is the policy form
        # of the same knob: it supplies compute_dtype when none is given
        # and tags the fan plans so runner/AOT keys separate dtypes.
        from wam_tpu.config import PrecisionPolicy

        if isinstance(precision, str):
            precision = PrecisionPolicy(fan_dtype=precision)
        if isinstance(compute_dtype, str):
            compute_dtype = PrecisionPolicy(
                fan_dtype=compute_dtype).compute_dtype()
        if compute_dtype is None and precision is not None:
            compute_dtype = precision.compute_dtype()
        if precision is not None:
            self._fan_dtype = precision.fan_dtype
        elif compute_dtype is not None:
            self._fan_dtype = {"bfloat16": "bf16", "float8_e4m3fn": "fp8",
                               "float8_e5m2": "fp8"}.get(
                                   jnp.dtype(compute_dtype).name)
        else:
            self._fan_dtype = None
        self.compute_dtype = compute_dtype
        if compute_dtype is not None:
            variables = jax.tree_util.tree_map(
                lambda a: a.astype(compute_dtype)
                if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                variables,
            )
        self.variables = variables
        self.method = method
        self.batch_size = batch_size
        self.random_seed = random_seed
        self.n_samples = n_samples
        self.stdev_spread = stdev_spread
        self.cam_layer = cam_layer
        self.nchw = nchw
        self.mesh = mesh
        self.data_axis = data_axis
        self.donate_inputs = donate_inputs
        self.aot_key = aot_key
        self.explanations = None
        self._expl_key = None
        self.insertion_curves = []
        self.deletion_curves = []

        base = {k: v for k, v in self.variables.items() if k != "perturbations"}

        def model_fn(x):
            inp = jnp.transpose(x, (0, 2, 3, 1)) if nchw else x
            if self.compute_dtype is not None:
                inp = inp.astype(self.compute_dtype)
            out = self.model.apply(base, inp)
            out = out[0] if isinstance(out, tuple) else out
            return out.astype(jnp.float32) if self.compute_dtype is not None else out

        self.model_fn = model_fn
        self._auc_runners: dict = {}
        self._mu_runners: dict = {}
        self._mu_draw_cache: dict = {}
        # one jit around the whole explanation: the method bodies
        # (baselines.py) are plain traced JAX, and dispatching them eagerly
        # costs the tunneled TPU's ~100 ms host RTT PER OP — the round-3
        # methods_tpu.jsonl rows measured 6-23 s "explain" times that were
        # almost entirely dispatch (see the LRP 216 s → 0.1 s diagnosis,
        # BASELINE.md round-4)
        self._explain_jit = jax.jit(self._explain_impl)

    def compute_explanations(self, x, y) -> jax.Array:
        """(B, H, W) maps in the perturbation domain
        (`src/evaluators.py:904-959`)."""
        return self._explain_jit(jnp.asarray(x), jnp.asarray(y))

    def _explain_impl(self, x, y) -> jax.Array:
        m = self.method
        if m == "saliency":
            return B.saliency(self.model_fn, x, y)
        if m == "integratedgrad":
            return B.integrated_gradients(self.model_fn, x, y, n_steps=self.n_samples)
        if m == "smoothgrad":
            key = jax.random.PRNGKey(self.random_seed)
            return B.smoothgrad_pixel(
                self.model_fn, x, y, key, n_samples=self.n_samples, stdev_spread=self.stdev_spread
            )
        if m == "gradcam":
            return B.gradcam(self.model, self.variables, x, y, layer=self.cam_layer, nchw=self.nchw)
        if m == "gradcampp":
            return B.gradcam_pp(self.model, self.variables, x, y, layer=self.cam_layer, nchw=self.nchw)
        if m == "layercam":
            return B.layercam(self.model, self.variables, x, y, layer=self.cam_layer, nchw=self.nchw)
        if m == "guided_backprop":
            return B.guided_backprop(self.model, self.variables, x, y, nchw=self.nchw)
        if m == "gradxinput":
            return B.gradient_x_input(self.model_fn, x, y)
        if m == "lrp":
            return B.lrp(self.model, self.variables, x, y, nchw=self.nchw)
        if m == "rollout":
            return B.attention_rollout(self.model, self.variables, x, y, nchw=self.nchw)
        if m == "attngrad":
            return B.attention_gradient(self.model, self.variables, x, y, nchw=self.nchw)
        raise AssertionError(m)

    def precompute(self, x, y):
        """Compute (or reuse) the cached explanations, fingerprinted on
        ``(shape, dtype, y)`` — a different batch recomputes instead of
        silently reusing stale explanations; directly-assigned
        ``explanations`` adopt the first fingerprint they are used with
        (see `Eval2DWAM.precompute`)."""
        key = _batch_fingerprint(x, y)
        if self.explanations is not None:
            if self._expl_key is None or self._expl_key == key:
                self._expl_key = key
                return self.explanations
        self.explanations = self.compute_explanations(x, y)
        self._expl_key = key
        return self.explanations

    def reset(self):
        self.explanations = None
        self._expl_key = None

    def _perturb(self, x_s: jax.Array, masks: jax.Array) -> jax.Array:
        raise NotImplementedError

    def _fan_plan(self, fan: int) -> FanPlan:
        """Perturbation-fan geometry: ``batch_size="auto"`` consults the
        tuned ``fan_cap`` + ``fan_chunk`` schedule (wam_tpu.tune), explicit
        int caps derive chunks by the cap//fan law. The plan's fan_dtype
        (compute_dtype / precision, already baked into model_fn) rides
        along so every runner/AOT key derived from a plan separates
        precisions."""
        return plan_fan(self.batch_size, fan, fan_dtype=self._fan_dtype)

    def _fan_cap(self, fan: int) -> int:
        return self._fan_plan(fan).cap

    def evaluate_auc(self, x, y, mode: str, n_iter: int = 128):
        x = jnp.asarray(x)
        y = np.asarray(y)
        expl = self.precompute(x, y)

        def inputs_fn(x_s, expl_s):
            ins, dele = generate_masks(n_iter, expl_s)
            masks = ins if mode == "insertion" else dele
            return self._perturb(x_s, masks)

        # one jit dispatch for the whole batch (VERDICT.md round-1 #6);
        # with a mesh the image axis is sharded inside the SAME runner via
        # shard_map — no per-image host loop on-mesh either (r4 verdict #4)
        return run_cached_auc(
            self._auc_runners,
            (mode, tuple(expl.shape[1:])),
            inputs_fn,
            self.model_fn,
            self._fan_plan(n_iter + 1),
            n_iter,
            x,
            expl,
            y,
            mesh=self.mesh,
            data_axis=self.data_axis,
            donate=self.donate_inputs,
            aot_key=self.aot_key,
        )

    def insertion(self, x, y, n_iter: int = 128):
        scores, curves = self.evaluate_auc(x, y, "insertion", n_iter)
        self.insertion_curves = curves
        return scores

    def deletion(self, x, y, n_iter: int = 128):
        scores, curves = self.evaluate_auc(x, y, "deletion", n_iter)
        self.deletion_curves = curves
        return scores


class EvalImageBaselines(_BaseEvalBaselines):
    """Pixel-domain perturbation of images (B, 3, H, W)
    (`src/evaluators.py:805-1180`; mask-multiply reconstruction per
    `src/evaluation_helpers.py:325-357`)."""

    def __init__(
        self,
        model,
        variables,
        method: str = "saliency",
        batch_size: int | str = 128,
        random_seed: int = 42,
        n_samples: int = 25,
        stdev_spread: float = 0.25,
        cam_layer: str = "stage4",
        denormalize_fn: Callable = imagenet_denormalize,
        preprocess_fn: Callable = imagenet_preprocess,
        nchw: bool = True,
        mesh=None,
        data_axis: str = "data",
        compute_dtype=None,
        donate_inputs: bool | None = None,
        aot_key: str | None = None,
        precision=None,
    ):
        super().__init__(model, variables, method, batch_size, random_seed,
                         n_samples, stdev_spread, cam_layer, nchw=nchw,
                         methods=IMAGE_METHODS, mesh=mesh, data_axis=data_axis,
                         compute_dtype=compute_dtype,
                         donate_inputs=donate_inputs, aot_key=aot_key,
                         precision=precision)
        self.denormalize_fn = denormalize_fn
        self.preprocess_fn = preprocess_fn

    def _perturb(self, x_s, masks):
        image01 = self.denormalize_fn(x_s)  # (3, H, W)
        pert = image01[None] * masks[:, None]  # (M, 3, H, W)
        return self.preprocess_fn(_minmax01(pert))

    def _make_mu_runner(self, grid_size: int, sample_size: int, img_hw,
                        plan: FanPlan | None = None):
        """ONE-jit-dispatch pixel-domain μ-fidelity for the whole batch
        (VERDICT.md round-2 weak #3), chunked per the fan plan (tuned cap +
        fan_chunk override) — correlations accumulate device-resident
        across chunks."""
        if plan is None:
            plan = self._fan_plan(sample_size)
        images_per_chunk = plan.images_per_chunk
        forward = make_chunked_forward(self.model_fn, plan.fan_chunk)

        def forward_probs(inputs, label):
            return jnp.take(softmax_probs(forward(inputs)), label, axis=1)

        def run(xb, explb, yb, onehotb):
            base_probs = jnp.take_along_axis(
                softmax_probs(self.model_fn(xb)), yb[:, None], axis=1
            )[:, 0]

            def one(args):
                x_s, expl_s, lab, onehot, bp = args
                attr_map = gaussian_filter2d(expl_s, sigma=2.0)
                masks_grid = 1.0 - onehot.reshape(sample_size, grid_size, grid_size)
                masks = upsample_nearest(masks_grid, img_hw)
                probs = forward_probs(self._perturb(x_s, masks), lab)
                deltas = bp - probs
                # every pixel lands in the same cell the mask upsample maps
                # it to (superpixel_sum's nearest-resize partition)
                cell = superpixel_sum(attr_map, grid_size).reshape(-1)
                attrs = onehot @ cell
                return spearman(deltas, attrs)

            return jax.lax.map(
                one, (xb, explb, yb, onehotb, base_probs), batch_size=images_per_chunk
            )

        aot_key = None
        if self.aot_key is not None:
            aot_key = (f"{self.aot_key}|mu|g{grid_size}|s{sample_size}"
                       f"|c{images_per_chunk}|{plan.fan_dtype}")
        return fan_runner(run, mesh=self.mesh, data_axis=self.data_axis,
                          donate=self.donate_inputs, donate_argnums=(0,),
                          aot_key=aot_key)

    def mu_fidelity(self, x, y, grid_size: int = 28, sample_size: int = 128, subset_size: int = 157):
        """Pixel-domain μ-fidelity (`src/evaluators.py:1074-1180`).

        One jit dispatch for the whole batch in both configurations — the
        mesh variant shards the image axis inside the same runner
        (round-4 verdict #4)."""
        x = jnp.asarray(x)
        y = np.asarray(y)
        expl = self.precompute(x, y)
        from wam_tpu.evalsuite.metrics import mu_fidelity_draws

        onehot_all = mu_fidelity_draws(
            self._mu_draw_cache, self.random_seed, x.shape[0], grid_size,
            sample_size, subset_size, with_rand_masks=False,
        )

        plan = self._fan_plan(sample_size)
        key = (grid_size, sample_size, tuple(x.shape[1:]),
               tuple(expl.shape[1:]), plan.images_per_chunk, plan.fan_chunk,
               plan.fan_dtype)
        runner = self._mu_runners.get(key)
        if runner is None:
            runner = self._make_mu_runner(grid_size, sample_size,
                                          tuple(x.shape[-2:]), plan)
            self._mu_runners[key] = runner
        # the whole batch's correlations come back in ONE counted fetch
        out = run_fan(runner, (x, expl, jnp.asarray(y), onehot_all),
                      donate=self.donate_inputs, mesh=self.mesh, protect=(0,))
        return [float(v) for v in np.asarray(out)]


class EvalAudioBaselines(_BaseEvalBaselines):
    """Melspec-domain perturbation of audio inputs (B, 1, T, M)
    (`src/evaluators.py:310-548`): explanations are computed on the melspec
    input and masks multiply the melspec cells."""

    def __init__(
        self,
        model,
        variables,
        method: str = "saliency",
        batch_size: int | str = 128,
        random_seed: int = 42,
        n_samples: int = 25,
        stdev_spread: float = 0.001,
        cam_layer: str = "out3",
        mesh=None,
        data_axis: str = "data",
        compute_dtype=None,
        donate_inputs: bool | None = None,
        aot_key: str | None = None,
        precision=None,
    ):
        super().__init__(model, variables, method, batch_size, random_seed,
                         n_samples, stdev_spread, cam_layer, nchw=False,
                         methods=AUDIO_METHODS, mesh=mesh, data_axis=data_axis,
                         compute_dtype=compute_dtype,
                         donate_inputs=donate_inputs, aot_key=aot_key,
                         precision=precision)

    def _perturb(self, x_s, masks):
        # x_s: (1, T, M); masks: (n_iter+1, T, M) -> (n_iter+1, 1, T, M)
        return x_s[None] * masks[:, None]

    def insertion(self, x, y, n_iter: int = 64):
        scores, curves = self.evaluate_auc(x, y, "insertion", n_iter)
        self.insertion_curves = curves
        return scores

    def deletion(self, x, y, n_iter: int = 64):
        scores, curves = self.evaluate_auc(x, y, "deletion", n_iter)
        self.deletion_curves = curves
        return scores

    def evaluate_auc(self, x, y, mode: str, n_iter: int = 64, argmax: bool = False):
        """AUC over melspec-cell mask families; ``argmax=True`` returns raw
        logits rows instead (the input-fidelity path). Both routes are ONE
        jit dispatch via the batched runner off-mesh (VERDICT.md round-2
        weak #3 — the `return_logits` hook built for exactly this)."""
        if not argmax:
            return super().evaluate_auc(x, y, mode, n_iter)
        x = jnp.asarray(x)
        y = np.asarray(y)
        expl = self.precompute(x, y)

        def inputs_fn(x_s, expl_s):
            ins, dele = generate_masks(n_iter, expl_s)
            masks = ins if mode == "insertion" else dele
            return self._perturb(x_s, masks)

        return run_cached_auc(
            self._auc_runners,
            (mode, tuple(expl.shape[1:])),
            inputs_fn,
            self.model_fn,
            self._fan_plan(n_iter + 1),
            n_iter,
            x,
            expl,
            y,
            return_logits=True,
            mesh=self.mesh,
            data_axis=self.data_axis,
            donate=self.donate_inputs,
            aot_key=self.aot_key,
        )

    def faithfulness_of_spectra(self, x, y):
        _, curves = self.evaluate_auc(x, y, "deletion", n_iter=2)
        arr = np.asarray(curves)
        return (arr[:, 0] - arr[:, 1]).tolist()

    def input_fidelity(self, x, y):
        raw = self.evaluate_auc(x, y, "insertion", n_iter=2, argmax=True)
        preds = np.asarray(raw)[:, 1:, :]
        return np.argmax(preds, axis=2).tolist()
