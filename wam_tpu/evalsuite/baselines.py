"""Baseline attribution methods, native JAX.

Replaces the reference's captum / pytorch_grad_cam / custom-torch baselines
(`src/evaluators.py:339-351,851-902`; self-contained torch specs at
`src/evaluation_helpers.py:72-320`):

- saliency — |∂ logit_y / ∂ x| (captum Saliency role)
- integrated_gradients — pixel-domain IG from a zero baseline
- smoothgrad — pixel-domain twin of the WAM smoothing
  (`src/evaluation_helpers.py:234-320`)
- gradcam / gradcam_pp / layercam — activation-tap methods using the
  `nn.Module.perturb` gradient taps wired into the model zoo (the JAX
  analogue of the reference's forward/backward hooks,
  `src/evaluation_helpers.py:52-70`)

Every method maps (x, y) → a (B, H, W) pixel-domain map.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from wam_tpu.core.engine import target_loss
from wam_tpu.core.estimators import noise_sigma

__all__ = [
    "saliency",
    "integrated_gradients",
    "smoothgrad_pixel",
    "gradcam",
    "gradcam_pp",
    "layercam",
    "guided_relu",
    "guided_backprop",
    "gradient_x_input",
    "make_eps_tap",
    "lrp_eps",
    "lrp",
    "attention_rollout",
    "attention_gradient",
]


def _input_grads(model_fn: Callable, x: jax.Array, y) -> jax.Array:
    return jax.grad(lambda v: target_loss(model_fn(v), y))(x)


def saliency(model_fn: Callable, x: jax.Array, y) -> jax.Array:
    """|grad| averaged over channels → (B, H, W)."""
    return jnp.abs(_input_grads(model_fn, x, y)).mean(axis=1)


def integrated_gradients(model_fn: Callable, x: jax.Array, y, n_steps: int = 25) -> jax.Array:
    """x ⊙ mean of grads along the zero→x path (Riemann), channel-averaged."""
    alphas = jnp.linspace(0.0, 1.0, n_steps, dtype=x.dtype)
    grads = jax.lax.map(lambda a: _input_grads(model_fn, x * a, y), alphas)
    return (x * grads.mean(axis=0)).mean(axis=1)


def smoothgrad_pixel(
    model_fn: Callable,
    x: jax.Array,
    y,
    key: jax.Array,
    n_samples: int = 25,
    stdev_spread: float = 0.25,
) -> jax.Array:
    """Mean |grad| over noisy copies with per-image σ
    (`src/evaluation_helpers.py:234-320`)."""
    sigma = noise_sigma(x, stdev_spread)
    sigma = sigma.reshape(sigma.shape + (1,) * (x.ndim - 1))
    noise = jax.random.normal(key, (n_samples,) + x.shape, dtype=x.dtype) * sigma
    grads = jax.lax.map(lambda n: _input_grads(model_fn, x + n, y), noise)
    return jnp.abs(grads.mean(axis=0)).mean(axis=1)


# -- GradCAM family ---------------------------------------------------------


def _acts_and_grads(model, variables, x, y, layer: str, nchw: bool):
    """Forward with sow'd intermediates + gradient at the layer via the
    zero perturbation tap."""
    if layer not in (variables.get("perturbations") or {}):
        raise ValueError(
            f"Model has no perturbation tap {layer!r}; init the model and pass "
            "its full variables (including 'perturbations')"
        )
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    # The stored perturbation variables carry the INIT batch size; gradients
    # against them would be summed over any larger apply batch. Materialize
    # zero taps with this batch's activation shapes instead (shape-only
    # trace, no compute).
    inp0 = jnp.transpose(x, (0, 2, 3, 1)) if nchw else x
    pert_shapes = jax.eval_shape(
        lambda v: model.apply(v, inp0, mutable=["perturbations", "intermediates"])[1][
            "perturbations"
        ],
        base,
    )
    perturbs = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), pert_shapes
    )

    def loss_fn(pert):
        inp = jnp.transpose(x, (0, 2, 3, 1)) if nchw else x
        out, state = model.apply(
            {**base, "perturbations": pert}, inp, mutable=["intermediates"]
        )
        out = out[0] if isinstance(out, tuple) else out
        # Sum (not batch-mean) of the picked logits: per-sample gradients are
        # then independent of the batch size, so CAM weights for one image
        # don't change when it is evaluated alongside others.
        if y is None:
            return out.sum(), state["intermediates"]
        picked = jnp.take_along_axis(out, jnp.asarray(y)[:, None], axis=1)
        return picked.sum(), state["intermediates"]

    (_, inter), grads = jax.value_and_grad(loss_fn, has_aux=True)(perturbs)
    acts = inter[layer][0]  # (B, h, w, c) NHWC — or (B, 1+N, D) tokens
    g = grads[layer]
    if acts.ndim == 3:
        # Transformer token tap (e.g. ViT 'tokens'): drop the class token
        # and fold the N patch tokens back onto their √N × √N grid so the
        # CAM weighting sees a spatial activation map (VERDICT.md round-1
        # #10 — the reference's CAM registry was CNN-only).
        n = acts.shape[1] - 1
        side = int(n**0.5)
        if side * side != n:
            raise ValueError(
                f"token tap {layer!r} has {n} patch tokens, not a square grid"
            )
        acts = acts[:, 1:].reshape(acts.shape[0], side, side, acts.shape[-1])
        g = g[:, 1:].reshape(g.shape[0], side, side, g.shape[-1])
    return acts, g


def _resize_to(cam: jax.Array, hw: tuple[int, int]) -> jax.Array:
    return jax.image.resize(cam, cam.shape[:-2] + hw, method="bilinear")


def gradcam(model, variables, x, y, layer: str = "stage4", nchw: bool = True) -> jax.Array:
    """ReLU(Σ_c w_c A_c), w = spatial mean of gradients
    (`src/evaluation_helpers.py:157-230`)."""
    acts, grads = _acts_and_grads(model, variables, x, y, layer, nchw)
    w = grads.mean(axis=(1, 2), keepdims=True)
    cam = jax.nn.relu((w * acts).sum(axis=-1))
    return _resize_to(cam, x.shape[-2:])


def gradcam_pp(model, variables, x, y, layer: str = "stage4", nchw: bool = True) -> jax.Array:
    """GradCAM++ α-weights (`src/evaluation_helpers.py:72-152`):
    α = g² / (2g² + Σ A g³), w = Σ α·relu(g)."""
    acts, grads = _acts_and_grads(model, variables, x, y, layer, nchw)
    g2, g3 = grads**2, grads**3
    denom = 2.0 * g2 + (acts * g3).sum(axis=(1, 2), keepdims=True)
    alpha = g2 / jnp.where(denom == 0, 1.0, denom)
    w = (alpha * jax.nn.relu(grads)).sum(axis=(1, 2), keepdims=True)
    cam = jax.nn.relu((w * acts).sum(axis=-1))
    return _resize_to(cam, x.shape[-2:])


def layercam(model, variables, x, y, layer: str = "stage3", nchw: bool = True) -> jax.Array:
    """LayerCAM: ReLU(Σ_c relu(g)⊙A) — positional weighting."""
    acts, grads = _acts_and_grads(model, variables, x, y, layer, nchw)
    cam = jax.nn.relu((jax.nn.relu(grads) * acts).sum(axis=-1))
    return _resize_to(cam, x.shape[-2:])


@jax.custom_vjp
def guided_relu(x: jax.Array) -> jax.Array:
    """ReLU whose backward passes only positive gradients at positive inputs
    (Springenberg et al. 2014) — the modified-backward primitive behind
    guided backprop (reference registry entry 'guided_backprop',
    `src/evaluators.py:851-902`)."""
    return jnp.maximum(x, 0.0)


def _guided_relu_fwd(x):
    return jnp.maximum(x, 0.0), x


def _guided_relu_bwd(x, g):
    return (jnp.where((x > 0) & (g > 0), g, 0.0),)


guided_relu.defvjp(_guided_relu_fwd, _guided_relu_bwd)


def guided_backprop(model, variables, x: jax.Array, y, nchw: bool = True) -> jax.Array:
    """Guided backprop: input gradients through a clone of the model whose
    activations are `guided_relu` (same params — the activation carries no
    state). Requires a ReLU model exposing an `act` attribute (the ResNet
    and voxel zoos do; GELU models like ConvNeXt/ViT are out of scope for
    the guided rule); channel-averaged |grad| → (B, H, W)."""
    if not hasattr(model, "act"):
        raise ValueError(
            f"guided_backprop needs a model with a swappable `act` attribute; "
            f"{type(model).__name__} has none (use a ReLU model such as the "
            "ResNet or voxel zoo, or add an `act` field to the module)"
        )
    guided = model.clone(act=guided_relu)

    def model_fn(v):
        inp = jnp.transpose(v, (0, 2, 3, 1)) if nchw else v
        out = guided.apply(variables, inp)
        return out[0] if isinstance(out, tuple) else out

    return jnp.abs(_input_grads(model_fn, x, y)).mean(axis=1)


def gradient_x_input(model_fn: Callable, x: jax.Array, y) -> jax.Array:
    """x ⊙ ∂logit_y/∂x, channel-averaged → (B, H, W)."""
    return (x * _input_grads(model_fn, x, y)).mean(axis=1)


def make_eps_tap(eps: float) -> Callable:
    """Identity-forward op whose backward applies the LRP ε-rule cotangent
    rescale: g → g · z / (z + ε·sign z).

    Inserted after every linear(+bias/BatchNorm) output (the models'
    ``post_linear`` hook), this turns the standard VJP into exact ε-LRP for
    ReLU networks: the invariant "cotangent = relevance / activation" is
    preserved by ReLU (mask), additions (copy — residual relevance splits
    proportionally when the branch activation multiplies downstream),
    average pooling (linear spread), and maxpool (winner-take-all routing,
    the LRP convention). Input relevance is then x ⊙ ∂/∂x."""

    @jax.custom_vjp
    def tap(z):
        return z

    def fwd(z):
        return z, z

    def bwd(z, g):
        denom = z + eps * jnp.sign(z)
        safe = jnp.where(denom == 0, 1.0, denom)
        return (g * z / safe,)

    tap.defvjp(fwd, bwd)
    return tap


def lrp_eps(model, variables, x: jax.Array, y, eps: float = 1e-6,
            nchw: bool = True) -> jax.Array:
    """Pure ε-rule LRP via the ``post_linear`` cotangent tap (`make_eps_tap`).

    Per-layer ε-rule through conv/dense with BatchNorm treated jointly with
    its conv as one linear-plus-bias layer (tap after the BN output), seeded
    with a plain one-hot at the picked class (the zennit convention — see
    `picked_logit_sum`), harvested as x ⊙ grad summed over channels.

    Note the known identity (Ancona et al. 2018): for ReLU networks the
    ε→0 limit of this rule IS gradient x input — with or without biases —
    so use a finite ε (or `lrp`'s EpsilonPlusFlat composite, the
    reference's actual configuration) when a distinct method is wanted.
    """
    if not hasattr(model, "post_linear"):
        raise ValueError(
            f"lrp_eps needs a model with a `post_linear` hook; "
            f"{type(model).__name__} has none (the ResNet zoo provides it)"
        )
    tapped = model.clone(post_linear=make_eps_tap(eps))
    base = {k: v for k, v in variables.items() if k != "perturbations"}

    def picked_logit_sum(v):
        inp = jnp.transpose(v, (0, 2, 3, 1)) if nchw else v
        out = tapped.apply(base, inp)
        out = out[0] if isinstance(out, tuple) else out
        yy = jnp.asarray(y)
        picked = jnp.take_along_axis(out, yy[:, None], axis=1)[:, 0]
        # Normalize per sample by the (stop-grad, stabilized) picked logit:
        # this seeds the OUTPUT RELEVANCE with a plain one-hot (R_y = 1),
        # the reference's zennit convention (`src/evaluators.py:950-952`),
        # rather than with the logit value — see lrp.py's seed note.
        denom = jax.lax.stop_gradient(picked + eps * jnp.sign(picked))
        denom = jnp.where(denom == 0, 1.0, denom)
        return (picked / denom).sum()

    grads = jax.grad(picked_logit_sum)(x)
    return (x * grads).sum(axis=1 if nchw else -1)


def lrp(model, variables, x: jax.Array, y, eps: float = 1e-6,
        nchw: bool = True) -> jax.Array:
    """Layer-wise relevance propagation, matching the reference registry.

    For the ResNet zoo this is the zennit-`EpsilonPlusFlat`-with-canonizer
    counterpart (`src/evaluators.py:885-899`): BN folded into convs, ZPlus
    rule on convs, ε on dense, Flat on the stem — see
    `wam_tpu.evalsuite.lrp.lrp_resnet`. Other models with a ``post_linear``
    hook fall back to the pure ε-rule (`lrp_eps`)."""
    from wam_tpu.evalsuite.lrp import lrp_resnet
    from wam_tpu.models.resnet import ResNet

    if isinstance(model, ResNet):
        return lrp_resnet(model, variables, x, y, eps=eps, nchw=nchw)
    return lrp_eps(model, variables, x, y, eps=eps, nchw=nchw)


def attention_rollout(model, variables, x: jax.Array, y=None,
                      nchw: bool = True) -> jax.Array:
    """Attention rollout (Abnar & Zuidema 2020) for capture_attn ViTs —
    registry delegation to `wam_tpu.xattr.attention` (the transformer
    pillar lives there; this keeps one import site per method family)."""
    from wam_tpu.xattr.attention import attention_rollout as impl

    return impl(model, variables, x, y, nchw=nchw)


def attention_gradient(model, variables, x: jax.Array, y,
                       nchw: bool = True) -> jax.Array:
    """grad⊙attn relevance (Chefer et al. 2021) for capture_attn ViTs —
    registry delegation to `wam_tpu.xattr.attention`."""
    from wam_tpu.xattr.attention import attention_gradient as impl

    return impl(model, variables, x, y, nchw=nchw)
