"""Device-mesh helpers: the distributed backbone of the framework.

The reference has no distributed execution at all (SURVEY.md §2.10); the
TPU-native counterpart scales WAM's two embarrassingly-parallel axes — the
image batch and the estimator's noise/path samples — over a
`jax.sharding.Mesh`, with XLA inserting the ICI collectives (psum for the
sample mean, all_gather for mosaic assembly) per `BASELINE.json`'s
north-star design.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "data_sample_mesh", "replica_mesh", "P", "NamedSharding", "Mesh"]

P = PartitionSpec


def make_mesh(axis_sizes: dict[str, int], devices=None) -> Mesh:
    """Build a Mesh with named axes from an {axis: size} mapping.

    The product of sizes must equal the device count (use -1 for one axis to
    infer it)."""
    devices = jax.devices() if devices is None else devices
    sizes = dict(axis_sizes)
    unknown = [k for k, v in sizes.items() if v == -1]
    known = math.prod(v for v in sizes.values() if v != -1)
    if len(unknown) > 1:
        raise ValueError("At most one axis size may be -1")
    if unknown:
        if len(devices) % known:
            raise ValueError(f"{len(devices)} devices not divisible by {known}")
        sizes[unknown[0]] = len(devices) // known
    if math.prod(sizes.values()) != len(devices):
        raise ValueError(f"Mesh {sizes} does not match {len(devices)} devices")
    arr = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes))


def replica_mesh(n_replicas: int, devices=None) -> Mesh:
    """1D ``('data',)`` mesh over the first ``n_replicas`` chips — the serve
    fleet's oversize-dispatch mesh (`wam_tpu.serve.fleet`): batch rows shard
    across replicas while model/coefficient axes stay whole per chip, so a
    pjit'd ``serve_entry`` over this mesh is plain data parallelism with no
    intra-op collectives."""
    devices = jax.devices() if devices is None else list(devices)
    n = int(n_replicas)
    if not 1 <= n <= len(devices):
        raise ValueError(f"replica_mesh({n}) with {len(devices)} devices")
    return make_mesh({"data": n}, devices[:n])


def data_sample_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """Default 2D mesh for attribution workloads: ('data', 'sample').

    Splits the device count into the most square data×sample factorization,
    favoring the data axis.
    """
    devices = jax.devices() if devices is None else devices
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    best_sample = 1
    for s in range(1, int(math.isqrt(n)) + 1):
        if n % s == 0:
            best_sample = s
    return make_mesh({"data": n // best_sample, "sample": best_sample}, devices)
