"""Sequence-sharded SmoothGrad / Integrated-Gradients estimators.

Round-4 verdict gap being closed: the long-context machinery
(`halo.sharded_coeff_grads_per`, `halo_modes.sharded_coeff_grads_mode`)
ended at a raw gradient function — no estimator composed with it and the
`WaveletAttribution{1,2,3}D` classes exposed no sequence entry point. This
module is that composition: the SmoothGrad sample loop (reference:
`lib/wam_1D.py:311-326`) and the IG α-path (`lib/wam_1D.py:384-409`) run
over the sequence-sharded decompose → reconstruct → model → grads core, so
no device ever holds the whole signal.

Design:
- Noise is drawn SHARD-LOCAL over the sequence axis: the per-sample draw is
  `normal(fold_in(key, i), x.shape)` with its output constrained to the
  input's sequence sharding — JAX's partitionable threefry generates each
  shard's slice locally (no replicated noise buffer, no gather), and the
  values are sharding-invariant, so every per-sample draw and gradient is
  BIT-IDENTICAL to the single-device estimator's ``materialize_noise=False``
  stream (`core.estimators.smoothgrad`, same fold_in keys); the sample
  mean differs only by float summation order.
- Each sample / α-step / chunk is ONE fused dispatch by default: noise
  draw → decompose → reconstruct → front → model → VJP → accumulate trace
  as a single jit (`fused=True`), with the engines' mean-of-picked-logits
  loss (`core.engine.target_loss`), so class-level parity with the
  single-device estimators is exact. The historical XLA SPMD-partitioner
  failure on zero-size tail buffers that forced a decompose→grads split no
  longer arises — statically-empty tails are OMITTED from the coefficient
  pytree rather than carried as (B, 0) arrays (see `halo_modes` and
  tests/test_partitioner_repro.py) — but the split loop is kept behind
  ``fused=False`` for A/B timing and bit-exactness pinning. Dispatches
  launched by the estimator loops are counted in ``dispatch_count`` so the
  one-dispatch contract is testable without profiles.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wam_tpu.core.engine import target_loss
from wam_tpu.core.estimators import noise_sigma
from wam_tpu.obs import sentinel as obs_sentinel
from wam_tpu.parallel import halo
from wam_tpu.parallel import halo_modes
from wam_tpu.parallel.halo_modes import gather_coeffs, gather_leaf

__all__ = ["seq_sharded_wam", "SeqShardedWam"]


def _sentinel_jit(fn, *, detail: str | None = None, **jit_kwargs):
    """`jax.jit` with a trace-time report to the compile sentinel
    (`wam_tpu.obs.sentinel`, entry_kind ``"seq"``). ``dispatch_count``
    counts launches; the sentinel counts COMPILES — the serve fleet's
    sequence-sharded oversize route warm-verifies through
    ``assert_no_retrace``, which only sees jits that self-report. The
    report is a python side effect of tracing, so cached executions cost
    nothing. Split-path dec/rec builder jits (`halo`, `halo_modes`) stay
    silent; the fused path's outer jit inlines them at trace time, so one
    event per fused graph is the complete compile story there."""
    name = detail or getattr(fn, "__name__", "seq")

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        obs_sentinel.record_trace("seq", detail=name)
        return fn(*args, **kwargs)

    return jax.jit(traced, **jit_kwargs)

_DEC_PER = {1: halo.sharded_wavedec_per, 2: halo.sharded_wavedec2_per,
            3: halo.sharded_wavedec3_per}
_REC_PER = {1: halo.sharded_waverec_per, 2: halo.sharded_waverec2_per,
            3: halo.sharded_waverec3_per}
_DEC_MODE = {1: halo_modes.sharded_wavedec_mode, 2: halo_modes.sharded_wavedec2_mode,
             3: halo_modes.sharded_wavedec3_mode}
_REC_MODE = {1: halo_modes.sharded_waverec_mode, 2: halo_modes.sharded_waverec2_mode,
             3: halo_modes.sharded_waverec3_mode}


class SeqShardedWam:
    """Sequence-sharded WAM gradient core + estimators for one modality.

    Parameters mirror `core.engine.WamEngine` plus the mesh geometry:
    ``seq_axis`` names the mesh axis the signal's sequence dimension (last
    for ndim=1, rows for ndim=2, depth for ndim=3) is sharded over.
    ``front_fn`` is the optional differentiable front-end between the
    reconstruction and the model (the 1D melspec); its output tap gradient
    is returned alongside the coefficient gradients when ``front_grads``.
    ``post_fn`` maps the GATHERED per-sample coefficient-gradient pytree to
    the per-sample output (e.g. the 2D mosaic packer); identity when None.

    ``model_fn`` must be XLA-partitionable over the sequence axis for the
    sharding to survive into the model (convs and reductions are; GSPMD
    inserts the model-side halos). The DWT/IDWT stages are gather-free by
    construction — audited in tests/test_seq_estimators.py the same way as
    tests/test_halo_modes.py.

    Inputs are BATCHED: `attribute` / `smoothgrad` / `integrated` take x of
    rank ``ndim + leading batch dims`` (at least one — (B, L) for ndim=1,
    (B, H, W) or (B, C, H, W) for ndim=2, (B, D, H, W) for ndim=3). An
    unbatched signal slips past the sharding constraints (its leading axis
    is read as batch) and mis-shards silently, so the entry points reject
    ``x.ndim <= ndim`` loudly instead.

    ``fused`` (default True) traces each sample / chunk / α-step as ONE jit
    — draw, decompose, grads and accumulation in a single dispatch.
    ``fused=False`` keeps the historical split loop (separate noisy / dec /
    grads / accum dispatches) for A/B timing; ``fused="auto"`` consults the
    schedule cache (key ``seq_fused``, swept by `wam_tpu.tune`). Both paths
    produce BIT-IDENTICAL results (same primitives, same summation order —
    pinned in tests/test_seq_estimators.py). ``dispatch_count`` advances
    once per jitted computation the entry points launch.

    ``dwt_bf16`` casts the signal to bfloat16 at the decompose boundary
    (the sharded analysis kernels accumulate in float32 — same convention
    as the single-device engines' ``dwt_bf16``); everything downstream of
    the coefficients stays float32.
    """

    def __init__(
        self,
        mesh: Mesh,
        model_fn: Callable[[jax.Array], jax.Array],
        *,
        ndim: int,
        wavelet: str = "haar",
        level: int = 3,
        mode: str = "symmetric",
        seq_axis: str = "data",
        front_fn: Callable[[jax.Array], jax.Array] | None = None,
        front_grads: bool = False,
        post_fn: Callable[[Any], Any] | None = None,
        batch_axis: str | None = None,
        fused: bool | str = True,
        dwt_bf16: bool = False,
    ):
        if ndim not in (1, 2, 3):
            raise ValueError(f"ndim must be 1, 2 or 3, got {ndim}")
        if front_grads and front_fn is None:
            raise ValueError("front_grads=True requires front_fn")
        if front_grads and post_fn is not None:
            raise ValueError("front_grads and post_fn are mutually exclusive")
        if fused not in (True, False, "auto"):
            raise ValueError(f"fused must be True, False or 'auto'; "
                             f"got {fused!r}")
        if batch_axis is not None:
            if batch_axis not in mesh.axis_names:
                raise ValueError(
                    f"batch_axis {batch_axis!r} is not a mesh axis "
                    f"{tuple(mesh.axis_names)}"
                )
            if batch_axis == seq_axis:
                raise ValueError("batch_axis must differ from seq_axis")
        self.mesh = mesh
        self.ndim = ndim
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis
        self.front_fn = front_fn
        self.front_grads = front_grads
        self.post_fn = post_fn
        self.model_fn = model_fn
        self.fused = fused
        self.dwt_bf16 = dwt_bf16
        self.dispatch_count = 0  # jitted dispatches launched by entry points
        self.periodized = mode == "periodization"
        if self.periodized:
            # batch_axis shards the LEADING axis over the remaining mesh —
            # without it, devices off the seq axis replicate all compute
            self.dec = _DEC_PER[ndim](mesh, wavelet, level, seq_axis,
                                      batch_axis)
            rec = _REC_PER[ndim](mesh, wavelet, seq_axis, batch_axis)
            self._rec_signal = rec
            self._gather = lambda tree: tree  # leaves already plain arrays
        else:
            # batch_axis note: the expansive paths shard only the CORES over
            # it — the O(L) tails stay fully replicated (see halo_modes /
            # DESIGN.md "Sequence-sharded fusion" on the legacy-shard_map
            # batch-sharded-tail miscompile)
            self.dec = _DEC_MODE[ndim](mesh, wavelet, level, mode, seq_axis,
                                       batch_axis)
            rec = _REC_MODE[ndim](mesh, wavelet, seq_axis, batch_axis)
            self._rec_signal = lambda cs: gather_leaf(rec(cs), axis=-ndim)
            self._gather = lambda tree: gather_coeffs(tree, ndim=ndim)
        # one jitted gradient step per (labelled?, spatial shape); spatial is
        # static so the crop after reconstruction has a fixed slice size
        self._grads = _sentinel_jit(self._grads_impl,
                                    static_argnames=("spatial",))
        self._grads_ig = _sentinel_jit(
            lambda cs, alpha, y, spatial: self._grads_impl(
                jax.tree_util.tree_map(lambda c: c * alpha, cs), y, spatial
            ),
            detail="_grads_ig",
            static_argnames=("spatial",),
        )
        self._noisy = _sentinel_jit(self._noisy_impl)
        self._noisy_chunk = _sentinel_jit(self._noisy_chunk_impl,
                                          static_argnames=("g",))
        self._grads_chunk = _sentinel_jit(self._grads_chunk_impl,
                                          static_argnames=("spatial", "g"))
        self._grads_ig_chunk = _sentinel_jit(self._grads_ig_chunk_impl,
                                             static_argnames=("spatial", "g"))
        # smooth accumulates plain sums (like `estimators.smoothgrad`); the
        # IG accumulator applies the per-element nan_to_num of
        # `estimators.trapezoid`
        self._accum = _sentinel_jit(
            lambda acc, g, w: jax.tree_util.tree_map(lambda a, b: a + w * b, acc, g),
            detail="_accum",
        )
        self._accum_nan = _sentinel_jit(
            lambda acc, g, w: jax.tree_util.tree_map(
                lambda a, b: a + w * jnp.nan_to_num(b), acc, g
            ),
            detail="_accum_nan",
        )
        self._first_nan = _sentinel_jit(
            lambda g, w: jax.tree_util.tree_map(lambda b: w * jnp.nan_to_num(b), g),
            detail="_first_nan",
        )
        self._scale = _sentinel_jit(
            lambda tree, s: jax.tree_util.tree_map(lambda a: s * a, tree),
            detail="_scale",
        )
        # fused one-dispatch steps: draw → decompose → grads (→ accumulate)
        # in a single jit; the *_acc variants take the running accumulator so
        # steps after the first stay one dispatch (plain a + b — bit-equal to
        # the split loop's `a + 1.0 * b` accumulator)
        self._fused_attr = _sentinel_jit(self._fused_attr_impl,
                                         static_argnames=("spatial",))
        self._fused_step = _sentinel_jit(self._fused_step_impl,
                                         static_argnames=("spatial",))
        self._fused_step_acc = _sentinel_jit(self._fused_step_acc_impl,
                                             static_argnames=("spatial",))
        self._fused_chunk = _sentinel_jit(self._fused_chunk_impl,
                                          static_argnames=("spatial", "g"))
        self._fused_chunk_acc = _sentinel_jit(self._fused_chunk_acc_impl,
                                              static_argnames=("spatial", "g"))
        self._fused_ig_first = _sentinel_jit(self._fused_ig_first_impl,
                                             static_argnames=("spatial",))
        self._fused_ig_step = _sentinel_jit(self._fused_ig_step_impl,
                                            static_argnames=("spatial",))
        self._fused_ig_chunk_acc = _sentinel_jit(self._fused_ig_chunk_acc_impl,
                                                 static_argnames=("spatial", "g"))
        # anytime checkpointing (wam_tpu.anytime): Welford M2 from
        # consecutive SUM accumulators + the per-row confidence vector.
        # Both are SIDE computations — they read the accumulator, never
        # feed back into it, so the accumulator chain of the checkpointed
        # loops stays the exact same jitted dispatches as the plain loops
        # (the bit-equal-checkpoint invariant, pinned in tests).
        from wam_tpu.anytime.state import conf_stats, m2_update

        self._anytime_m2 = _sentinel_jit(m2_update, detail="_anytime_m2")
        self._anytime_conf = _sentinel_jit(conf_stats, detail="_anytime_conf")

    # -- pieces ------------------------------------------------------------

    def _resolve_seq_chunk(self, sample_chunk, x, n_samples: int):
        """``sample_chunk="auto"``: consult the round-6 schedule cache under
        workload ``"wamseq{ndim}d"`` (tuned via `wam_tpu.tune`); with no
        matching entry, fall back to this module's sequential default (1) —
        NOT the single-device 128-row law, whose full-vmap non-TPU branch
        would materialize every sequence-sized sample graph at once."""
        if sample_chunk != "auto":
            return sample_chunk
        from wam_tpu.tune import lookup_schedule

        ent = lookup_schedule(f"wamseq{self.ndim}d", tuple(x.shape[1:]),
                              x.shape[0])
        if ent is not None and "sample_chunk" in ent:
            chunk = ent["sample_chunk"]
            return None if chunk is None else max(1, int(chunk))
        return 1

    def _resolve_fused(self, x) -> bool:
        """``fused="auto"``: consult the same schedule cache as
        `_resolve_seq_chunk` (key ``seq_fused``, swept by `wam_tpu.tune`);
        no entry → True, the one-jit step."""
        if self.fused != "auto":
            return bool(self.fused)
        from wam_tpu.tune import lookup_schedule

        ent = lookup_schedule(f"wamseq{self.ndim}d", tuple(x.shape[1:]),
                              x.shape[0])
        if ent is not None and "seq_fused" in ent:
            return bool(ent["seq_fused"])
        return True

    def _call(self, fn, *args, **kwargs):
        """Launch one jitted computation, counting it — ``dispatch_count``
        lets tests and benches assert the fused path's one-dispatch-per-
        sample contract without parsing profiles."""
        self.dispatch_count += 1
        return fn(*args, **kwargs)

    def _dec_input(self, sig):
        """Decompose-boundary cast (trace-level): ``dwt_bf16`` rounds the
        signal to bfloat16 before analysis; the sharded kernels upcast to
        float32 internally, so only the input quantization changes."""
        return sig.astype(jnp.bfloat16) if self.dwt_bf16 else sig

    def _reconstruct(self, cs, spatial):
        sig = self._rec_signal(cs)
        idx = (Ellipsis,) + tuple(slice(0, s) for s in spatial)
        return sig[idx]

    def _loss(self, cs, tap, y, spatial):
        sig = self._reconstruct(cs, spatial)
        h = self.front_fn(sig) if self.front_fn is not None else sig
        if tap is not None:
            h = h + tap
        return target_loss(self.model_fn(h), y)

    def _tap_grads(self, cs, y, spatial):
        """Two-tap gradient (coefficients + front output) via the zero-tap
        trick — the one definition both the sequential and chunked steps
        wrap."""
        tap_shape = jax.eval_shape(
            lambda c: self.front_fn(self._reconstruct(c, spatial)), cs
        )
        tap0 = jnp.zeros(tap_shape.shape, tap_shape.dtype)
        return jax.grad(
            lambda c, t: self._loss(c, t, y, spatial), argnums=(0, 1)
        )(cs, tap0)

    def _grads_impl(self, cs, y, spatial):
        """Per-sample gradient step. Without ``post_fn`` the output is the
        RAW coefficient-gradient tree (TailedLeaf for the expansive modes) —
        gathering to plain arrays happens once, eagerly, after accumulation
        (`_finalize`): the core↔tail concat along the sharded axis would
        otherwise force per-sample all-gathers inside this graph (audited in
        tests/test_seq_estimators.py). With ``post_fn`` (the 2D mosaic / 3D
        cube packers, which need plain arrays and per-sample normalization)
        the gather+pack runs in-graph; the packed canvas is output-sized and
        its assembly sharding is left to propagation."""
        if self.front_grads:
            return self._tap_grads(cs, y, spatial)
        g_cs = jax.grad(lambda c: self._loss(c, None, y, spatial))(cs)
        return self.post_fn(self._gather(g_cs)) if self.post_fn is not None else g_cs

    def _finalize(self, tree):
        """Gather an accumulated raw gradient tree to the single-device
        pytree structure (plain arrays, still sequence-sharded) — a single
        eager concat per leaf, outside the per-sample graphs. Identity when
        ``post_fn`` already packed the samples."""
        if self.post_fn is not None:
            return tree
        if self.front_grads:
            return (self._gather(tree[0]), tree[1])
        return self._gather(tree)

    def _noisy_impl(self, x, key, i, stdev_spread):
        """One SmoothGrad draw, generated SHARD-LOCAL: same keys and values
        as `core.estimators.smoothgrad(materialize_noise=False)` (fold_in
        stream; partitionable threefry is sharding-invariant)."""
        sigma = noise_sigma(x, stdev_spread)
        sigma = sigma.reshape(sigma.shape + (1,) * (x.ndim - 1))
        k = jax.random.fold_in(key, i)
        n = jax.random.normal(k, x.shape, x.dtype) * sigma
        spec = [None] * x.ndim
        spec[0] = self.batch_axis
        spec[x.ndim - self.ndim] = self.seq_axis
        n = lax.with_sharding_constraint(n, NamedSharding(self.mesh, P(*spec)))
        return x + n

    def _noisy_chunk_impl(self, x, key, i0, stdev_spread, g):
        """``g`` consecutive draws of the SAME fold_in stream as
        `_noisy_impl`, flattened into the batch axis: (g·B, ...). The
        sample axis rides the conv batch, so one dispatch carries g·B
        model rows (the 128-row schedule law) instead of B."""
        sigma = noise_sigma(x, stdev_spread)
        sigma = sigma.reshape(sigma.shape + (1,) * (x.ndim - 1))

        def draw(i):
            k = jax.random.fold_in(key, i)
            return jax.random.normal(k, x.shape, x.dtype) * sigma

        noise = jax.vmap(draw)(i0 + jnp.arange(g, dtype=jnp.int32))
        # seq-only constraint pre-flatten (g alone may not divide the batch
        # axis); the flattened g·B form below carries the batch sharding
        spec = [None] * (x.ndim + 1)
        spec[1 + x.ndim - self.ndim] = self.seq_axis
        noise = lax.with_sharding_constraint(
            noise, NamedSharding(self.mesh, P(*spec))
        )
        noisy = x[None] + noise
        flat_spec = [None] * x.ndim
        flat_spec[0] = self.batch_axis
        flat_spec[x.ndim - self.ndim] = self.seq_axis
        return lax.with_sharding_constraint(
            noisy.reshape((-1,) + x.shape[1:]),
            NamedSharding(self.mesh, P(*flat_spec)),
        )

    def _chunk_grads_core(self, cs_flat, y_flat, w, spatial, g, nan: bool):
        """Shared chunked gradient core: grads over a (g·B)-row flattened
        coefficient tree, returned as the ``w``-WEIGHTED SUM of the g
        per-sample gradient trees (leading axis back to B). ``w`` (g,) is
        the per-sample weight (0 for the pad samples of a remainder chunk —
        padding keeps every chunk the same static shape, so a non-dividing
        chunk never re-compiles; pad rows are batch-diagonal and masked
        here). ``nan`` applies `trapezoid`'s per-element nan_to_num (the IG
        path).

        The loss means over g·B rows, so gradients come back 1/g of the
        per-sample mean-over-B convention — rescaled by g here. ``post_fn``
        is vmapped over the g groups so its per-sample-call semantics
        (e.g. the mosaic's normalize-over-the-batch) are preserved
        exactly."""
        by_sample = lambda a: a.reshape((g, a.shape[0] // g) + a.shape[1:])

        def wsum(a):
            if nan:
                a = jnp.nan_to_num(a)
            return (a * w.reshape((g,) + (1,) * (a.ndim - 1))).sum(axis=0)

        wsum_g = lambda tree: jax.tree_util.tree_map(
            lambda a: wsum(by_sample(a)), tree
        )
        scale = lambda tree: jax.tree_util.tree_map(lambda a: g * a, tree)
        if self.front_grads:
            return wsum_g(scale(self._tap_grads(cs_flat, y_flat, spatial)))
        g_cs = scale(jax.grad(
            lambda c: self._loss(c, None, y_flat, spatial))(cs_flat))
        if self.post_fn is not None:
            gathered = self._gather(g_cs)
            per = jax.vmap(self.post_fn)(
                jax.tree_util.tree_map(by_sample, gathered)
            )
            return jax.tree_util.tree_map(wsum, per)
        return wsum_g(g_cs)

    def _grads_chunk_impl(self, cs, y_flat, w, spatial, g):
        """SmoothGrad chunk step (see `_chunk_grads_core`); ``cs`` is the
        decomposition of the (g·B)-row noisy chunk."""
        return self._chunk_grads_core(cs, y_flat, w, spatial, g, nan=False)

    def _grads_ig_chunk_impl(self, cs, alphas, y_flat, w, spatial, g):
        """IG chunk step: coefficients broadcast g× along the batch axis,
        each group scaled by its α, then the shared core with trapezoid
        weights (× dx, 0 for pad slots) and nan_to_num (see
        `_chunk_grads_core`)."""

        def scaled(c):
            rep = jnp.broadcast_to(c[None], (g,) + c.shape)
            a = alphas.reshape((g,) + (1,) * c.ndim).astype(c.dtype)
            return (rep * a).reshape((g * c.shape[0],) + c.shape[1:])

        cs_flat = jax.tree_util.tree_map(scaled, cs)
        return self._chunk_grads_core(cs_flat, y_flat, w, spatial, g, nan=True)

    # -- fused one-dispatch steps ------------------------------------------
    # Each wraps the SAME impl pieces the split loop dispatches separately,
    # so the two paths share every primitive and stay bit-identical; only
    # the jit boundary moves. `self.dec._apply` is the decomposition's
    # jitted body (nested jit — inlined into this trace); its eager shape
    # checks run once per entry point via `self.dec._check`.

    def _fused_attr_impl(self, x, y, spatial):
        cs = self.dec._apply(self._dec_input(x))
        return cs, self._grads_impl(cs, y, spatial)

    def _fused_step_impl(self, x, key, i, stdev_spread, y, spatial):
        noisy = self._noisy_impl(x, key, i, stdev_spread)
        cs = self.dec._apply(self._dec_input(noisy))
        return self._grads_impl(cs, y, spatial)

    def _fused_step_acc_impl(self, acc, x, key, i, stdev_spread, y, spatial):
        g = self._fused_step_impl(x, key, i, stdev_spread, y, spatial)
        return jax.tree_util.tree_map(lambda a, b: a + b, acc, g)

    def _fused_chunk_impl(self, x, key, i0, stdev_spread, y_flat, w, spatial,
                          g):
        noisy = self._noisy_chunk_impl(x, key, i0, stdev_spread, g)
        cs = self.dec._apply(self._dec_input(noisy))
        return self._chunk_grads_core(cs, y_flat, w, spatial, g, nan=False)

    def _fused_chunk_acc_impl(self, acc, x, key, i0, stdev_spread, y_flat, w,
                              spatial, g):
        part = self._fused_chunk_impl(x, key, i0, stdev_spread, y_flat, w,
                                      spatial, g)
        return jax.tree_util.tree_map(lambda a, b: a + b, acc, part)

    def _fused_ig_first_impl(self, cs, alpha, w, y, spatial):
        g = self._grads_impl(
            jax.tree_util.tree_map(lambda c: c * alpha, cs), y, spatial
        )
        return jax.tree_util.tree_map(lambda b: w * jnp.nan_to_num(b), g)

    def _fused_ig_step_impl(self, acc, cs, alpha, w, y, spatial):
        g = self._grads_impl(
            jax.tree_util.tree_map(lambda c: c * alpha, cs), y, spatial
        )
        return jax.tree_util.tree_map(
            lambda a, b: a + w * jnp.nan_to_num(b), acc, g
        )

    def _fused_ig_chunk_acc_impl(self, acc, cs, alphas, y_flat, w, spatial,
                                 g):
        part = self._grads_ig_chunk_impl(cs, alphas, y_flat, w, spatial, g)
        return jax.tree_util.tree_map(lambda a, b: a + b, acc, part)

    # -- gradient core (single pass) ---------------------------------------

    def _check_batched(self, x):
        """Entry-point guard for the batched-input contract (class
        docstring): rank ndim inputs would alias the batch slot."""
        if x.ndim <= self.ndim:
            raise ValueError(
                f"SeqShardedWam(ndim={self.ndim}) takes BATCHED inputs "
                f"(rank > {self.ndim}); got rank {x.ndim} {x.shape} — add a "
                f"leading batch axis (x[None]) for a single signal")

    def attribute(self, x, y=None):
        """One un-noised pass: (coeffs, grads) like `WamEngine.attribute`,
        coefficient leaves gathered to plain (sequence-sharded) arrays.
        Fused: decompose AND grads in one dispatch."""
        self._check_batched(x)
        spatial = tuple(x.shape[-self.ndim:])
        if self._resolve_fused(x):
            self.dec._check(x)
            coeffs, grads = self._call(self._fused_attr, x, y,
                                       spatial=spatial)
        else:
            coeffs = self._call(self.dec, self._dec_input(x))
            grads = self._call(self._grads, coeffs, y, spatial=spatial)
        return self._gather(coeffs), self._finalize(grads)

    # -- estimators --------------------------------------------------------

    def smoothgrad(self, x, y, key, *, n_samples: int, stdev_spread: float,
                   sample_chunk: int | None | str = 1):
        """Mean over ``n_samples`` shard-local noisy passes. Same draws and
        per-sample gradients as `core.estimators.smoothgrad(step, x, key,
        .., materialize_noise=False)` wrapping the same single-device step
        (fold_in key stream; partitionable threefry is sharding-invariant);
        the sample mean differs only by float summation order.

        ``sample_chunk`` > 1 processes that many samples PER DISPATCH by
        flattening them into the batch axis (g·B model rows — the v5e
        128-row schedule law; memory grows by the same factor). ``None``
        means ALL samples in one dispatch (the resolvers' full-vmap
        convention). Identical draws and per-sample gradients; only the
        summation order differs. ``"auto"`` consults the round-6 schedule
        cache (`_resolve_seq_chunk`).

        Fused (default): ONE dispatch per sample (or per chunk) — draw,
        decompose, grads and accumulation in a single jit."""
        self._check_batched(x)
        fused = self._resolve_fused(x)
        sample_chunk = self._resolve_seq_chunk(sample_chunk, x, n_samples)
        if sample_chunk is None:
            sample_chunk = n_samples
        spatial = tuple(x.shape[-self.ndim:])
        spread = jnp.asarray(stdev_spread, x.dtype)
        if fused:
            self.dec._check(x)  # eager guards once; the loop skips run()
        acc = None
        if sample_chunk <= 1:
            for i in range(n_samples):
                ii = jnp.asarray(i, jnp.int32)
                if fused:
                    acc = (self._call(self._fused_step, x, key, ii, spread,
                                      y, spatial=spatial)
                           if acc is None else
                           self._call(self._fused_step_acc, acc, x, key, ii,
                                      spread, y, spatial=spatial))
                else:
                    noisy = self._call(self._noisy, x, key, ii, spread)
                    coeffs = self._call(self.dec, self._dec_input(noisy))
                    g = self._call(self._grads, coeffs, y, spatial=spatial)
                    acc = (g if acc is None
                           else self._call(self._accum, acc, g, 1.0))
        else:
            # every chunk runs at the SAME static size g (a remainder chunk
            # is padded with weight-0 samples), so one compiled shape covers
            # the whole loop even when sample_chunk doesn't divide
            # n_samples; g is BALANCED across the chunk count so padding is
            # minimal (n=25 chunk=16 → two chunks of 13, one pad slot —
            # not 16+16 with seven)
            n_chunks = -(-n_samples // min(sample_chunk, n_samples))
            g = -(-n_samples // n_chunks)
            y_flat = None if y is None else jnp.tile(jnp.asarray(y), g)
            i = 0
            while i < n_samples:
                n_real = min(g, n_samples - i)
                w = jnp.asarray([1.0] * n_real + [0.0] * (g - n_real),
                                x.dtype)
                ii = jnp.asarray(i, jnp.int32)
                if fused:
                    acc = (self._call(self._fused_chunk, x, key, ii, spread,
                                      y_flat, w, spatial=spatial, g=g)
                           if acc is None else
                           self._call(self._fused_chunk_acc, acc, x, key, ii,
                                      spread, y_flat, w, spatial=spatial,
                                      g=g))
                else:
                    noisy = self._call(self._noisy_chunk, x, key, ii, spread,
                                       g=g)
                    coeffs = self._call(self.dec, self._dec_input(noisy))
                    part = self._call(self._grads_chunk, coeffs, y_flat, w,
                                      spatial=spatial, g=g)
                    acc = (part if acc is None
                           else self._call(self._accum, acc, part, 1.0))
                i += n_real
        return self._finalize(self._call(self._scale, acc, 1.0 / n_samples))

    # -- anytime checkpointed estimators -----------------------------------
    # Per-sample loops (the fused path's sample_chunk=1 cadence) with a
    # confidence checkpoint every `stride` samples. The accumulator chain
    # is the SAME jitted calls in the same order as the plain estimators,
    # so the checkpoint at stride=n is bit-identical to the
    # non-checkpointed result; the M2/conf side dispatches never touch it.

    def smoothgrad_checkpointed(self, x, y, key, *, n_samples: int,
                                stdev_spread: float,
                                stride: int | str = "auto",
                                min_confidence: float = 0.0,
                                plateau_tol: float = 0.0,
                                on_checkpoint=None):
        """`smoothgrad` with progressive-refinement checkpoints: every
        ``stride`` samples (and at the end) the running mean's confidence
        vector (`wam_tpu.anytime.state`) is read back — a tiny
        control-plane sync, the map itself never crosses early — and
        ``on_checkpoint(count, conf)`` fires. With ``plateau_tol > 0`` the
        loop EXITS EARLY once every row's checkpoint delta is under the
        tolerance and every row's confidence clears ``min_confidence``;
        the returned map is then the mean over the samples actually used.

        ``stride="auto"`` consults the tuned ``anytime_stride`` schedule
        axis (`core.estimators.resolve_checkpoint_stride`). Returns
        ``(map, info)`` — info carries ``n_used / n_total / complete /
        converged / conf`` (the last host conf vector, (B, 4))."""
        from wam_tpu.core.estimators import resolve_checkpoint_stride

        self._check_batched(x)
        fused = self._resolve_fused(x)
        stride = resolve_checkpoint_stride(
            stride, n_samples, workload=f"wamseq{self.ndim}d",
            shape=tuple(x.shape[1:]), batch=x.shape[0])
        spatial = tuple(x.shape[-self.ndim:])
        spread = jnp.asarray(stdev_spread, x.dtype)
        if fused:
            self.dec._check(x)
        m2 = jnp.zeros((x.shape[0],), jnp.float32)
        acc = None
        prev_acc, prev_count = None, 0
        conf_host = None
        converged = False
        count = 0
        for i in range(n_samples):
            ii = jnp.asarray(i, jnp.int32)
            if fused:
                if acc is None:
                    acc_new = self._call(self._fused_step, x, key, ii,
                                         spread, y, spatial=spatial)
                else:
                    acc_new = self._call(self._fused_step_acc, acc, x, key,
                                         ii, spread, y, spatial=spatial)
            else:
                noisy = self._call(self._noisy, x, key, ii, spread)
                coeffs = self._call(self.dec, self._dec_input(noisy))
                g = self._call(self._grads, coeffs, y, spatial=spatial)
                acc_new = (g if acc is None
                           else self._call(self._accum, acc, g, 1.0))
            if acc is not None:
                m2 = self._call(self._anytime_m2, m2, acc, acc_new,
                                jnp.asarray(i, jnp.float32))
            acc = acc_new
            count = i + 1
            acc, m2, prev_acc, prev_count, conf_host, converged = (
                self._checkpoint(acc, m2, count, n_samples, stride,
                                 prev_acc, prev_count, conf_host,
                                 min_confidence, plateau_tol,
                                 on_checkpoint))
            if converged:
                break
        attr = self._finalize(self._call(self._scale, acc, 1.0 / count))
        info = {"n_used": count, "n_total": n_samples,
                "complete": count >= n_samples, "converged": converged,
                "conf": conf_host}
        return attr, info

    def integrated_checkpointed(self, x, y, *, n_steps: int,
                                dx: float = 1.0,
                                stride: int | str = "auto",
                                min_confidence: float = 0.0,
                                plateau_tol: float = 0.0,
                                on_checkpoint=None):
        """`integrated` with checkpoints every ``stride`` α-steps (see
        `smoothgrad_checkpointed` — same policy, same conf vector; the
        plateau signal is the running trapezoid integral's motion). An
        early exit truncates the α-path: the best-so-far integral over
        [0, α_k]. Returns ``(coeffs, integral, info)``."""
        from wam_tpu.core.estimators import resolve_checkpoint_stride

        self._check_batched(x)
        fused = self._resolve_fused(x)
        stride = resolve_checkpoint_stride(
            stride, n_steps, workload=f"wamseq{self.ndim}d",
            shape=tuple(x.shape[1:]), batch=x.shape[0])
        spatial = tuple(x.shape[-self.ndim:])
        coeffs = self._call(self.dec, self._dec_input(x))
        alphas = jnp.linspace(0.0, 1.0, n_steps, dtype=jnp.float32)

        def trap_w(i):
            if n_steps == 1:
                return 1.0
            return 0.5 if i in (0, n_steps - 1) else 1.0

        m2 = jnp.zeros((x.shape[0],), jnp.float32)
        acc = None
        prev_acc, prev_count = None, 0
        conf_host = None
        converged = False
        count = 0
        for i in range(n_steps):
            w = trap_w(i) * dx
            if fused:
                if acc is None:
                    acc_new = self._call(self._fused_ig_first, coeffs,
                                         alphas[i], w, y, spatial=spatial)
                else:
                    acc_new = self._call(self._fused_ig_step, acc, coeffs,
                                         alphas[i], w, y, spatial=spatial)
            else:
                g = self._call(self._grads_ig, coeffs, alphas[i], y,
                               spatial=spatial)
                acc_new = (self._call(self._first_nan, g, w)
                           if acc is None
                           else self._call(self._accum_nan, acc, g, w))
            if acc is not None:
                m2 = self._call(self._anytime_m2, m2, acc, acc_new,
                                jnp.asarray(i, jnp.float32))
            acc = acc_new
            count = i + 1
            acc, m2, prev_acc, prev_count, conf_host, converged = (
                self._checkpoint(acc, m2, count, n_steps, stride,
                                 prev_acc, prev_count, conf_host,
                                 min_confidence, plateau_tol,
                                 on_checkpoint))
            if converged:
                break
        info = {"n_used": count, "n_total": n_steps,
                "complete": count >= n_steps, "converged": converged,
                "conf": conf_host}
        return self._gather(coeffs), self._finalize(acc), info

    def _checkpoint(self, acc, m2, count, n_total, stride, prev_acc,
                    prev_count, conf_host, min_confidence, plateau_tol,
                    on_checkpoint):
        """Shared checkpoint read + early-exit policy for the checkpointed
        loops: at each stride boundary (and at n_total) compute the conf
        vector on device, sync it back, snapshot the accumulator for the
        next delta, and decide convergence."""
        from wam_tpu.anytime.state import SLOT_CONFIDENCE, SLOT_DELTA

        converged = False
        if count % stride == 0 or count >= n_total:
            ref = prev_acc if prev_acc is not None else acc
            conf_dev = self._call(
                self._anytime_conf, acc, m2,
                jnp.asarray(count, jnp.float32), ref,
                jnp.asarray(prev_count, jnp.float32))
            conf_host = jax.device_get(conf_dev)
            prev_acc, prev_count = acc, count
            if on_checkpoint is not None:
                on_checkpoint(count, conf_host)
            if (count < n_total and plateau_tol > 0.0
                    and float(conf_host[:, SLOT_DELTA].max()) <= plateau_tol
                    and float(conf_host[:, SLOT_CONFIDENCE].min())
                    >= min_confidence):
                converged = True
        return acc, m2, prev_acc, prev_count, conf_host, converged

    def integrated(self, x, y, *, n_steps: int, dx: float = 1.0,
                   sample_chunk: int | None | str = 1):
        """Trapezoidal path integral of the gradient over α·coeffs — the
        per-element `nan_to_num` and endpoint halving reproduce
        `core.estimators.trapezoid` up to float summation order. Returns
        (gathered coeffs, integral pytree); the caller multiplies by its
        baseline. ``sample_chunk`` batches that many α-steps per dispatch
        (None = all, "auto" = schedule cache), same mechanics as
        `smoothgrad`'s.

        Fused (default): decompose once, then ONE dispatch per α-step (or
        per chunk) — grads and trapezoid accumulation in a single jit."""
        self._check_batched(x)
        fused = self._resolve_fused(x)
        sample_chunk = self._resolve_seq_chunk(sample_chunk, x, n_steps)
        spatial = tuple(x.shape[-self.ndim:])
        coeffs = self._call(self.dec, self._dec_input(x))
        alphas = jnp.linspace(0.0, 1.0, n_steps, dtype=jnp.float32)

        def trap_w(i):
            # a length-1 path is its own both endpoints → weight 1.0
            if n_steps == 1:
                return 1.0
            return 0.5 if i in (0, n_steps - 1) else 1.0

        if sample_chunk is None:
            sample_chunk = n_steps
        acc = None
        if sample_chunk <= 1:
            for i in range(n_steps):
                if fused:
                    acc = (self._call(self._fused_ig_first, coeffs,
                                      alphas[i], trap_w(i) * dx, y,
                                      spatial=spatial)
                           if acc is None else
                           self._call(self._fused_ig_step, acc, coeffs,
                                      alphas[i], trap_w(i) * dx, y,
                                      spatial=spatial))
                else:
                    g = self._call(self._grads_ig, coeffs, alphas[i], y,
                                   spatial=spatial)
                    acc = (self._call(self._first_nan, g, trap_w(i) * dx)
                           if acc is None else
                           self._call(self._accum_nan, acc, g,
                                      trap_w(i) * dx))
        else:
            n_chunks = -(-n_steps // min(sample_chunk, n_steps))
            g_sz = -(-n_steps // n_chunks)
            y_flat = None if y is None else jnp.tile(jnp.asarray(y), g_sz)
            alphas_np = alphas.tolist()  # one transfer, not n_steps
            i = 0
            while i < n_steps:
                n_real = min(g_sz, n_steps - i)
                a_chunk = jnp.asarray(
                    alphas_np[i:i + n_real] + [0.0] * (g_sz - n_real),
                    jnp.float32,
                )
                w = jnp.asarray(
                    [trap_w(i + k) * dx for k in range(n_real)]
                    + [0.0] * (g_sz - n_real),
                    jnp.float32,
                )
                if fused and acc is not None:
                    # chunk step is already one dispatch; fusing folds the
                    # accumulator add in too
                    acc = self._call(self._fused_ig_chunk_acc, acc, coeffs,
                                     a_chunk, y_flat, w, spatial=spatial,
                                     g=g_sz)
                else:
                    part = self._call(self._grads_ig_chunk, coeffs, a_chunk,
                                      y_flat, w, spatial=spatial, g=g_sz)
                    acc = (part if acc is None
                           else self._call(self._accum, acc, part, 1.0))
                i += n_real
        return self._gather(coeffs), self._finalize(acc)


def seq_sharded_wam(mesh: Mesh, model_fn, **kwargs) -> SeqShardedWam:
    """Convenience constructor (see `SeqShardedWam`)."""
    return SeqShardedWam(mesh, model_fn, **kwargs)
