from wam_tpu.parallel.halo import (
    sharded_coeff_grads_per,
    sharded_dwt_per,
    sharded_wavedec2_per,
    sharded_wavedec3_per,
    sharded_wavedec_per,
    sharded_waverec2_per,
    sharded_waverec3_per,
    sharded_waverec_per,
)
from wam_tpu.parallel.halo_modes import (
    TailedLeaf,
    gather_coeffs,
    gather_leaf,
    sharded_coeff_grads_mode,
    sharded_wavedec2_mode,
    sharded_wavedec3_mode,
    sharded_wavedec_mode,
    sharded_waverec2_mode,
    sharded_waverec3_mode,
    sharded_waverec_mode,
)
from wam_tpu.parallel.mesh import P, data_sample_mesh, make_mesh, replica_mesh
from wam_tpu.parallel.seq_estimators import SeqShardedWam, seq_sharded_wam
from wam_tpu.parallel.multihost import hybrid_mesh, init_distributed, process_local_batch
from wam_tpu.parallel.sharded import sharded_integrated_path, sharded_smoothgrad, sharded_smoothgrad_spmd

__all__ = [
    "make_mesh",
    "data_sample_mesh",
    "replica_mesh",
    "P",
    "sharded_smoothgrad",
    "sharded_smoothgrad_spmd",
    "sharded_integrated_path",
    "init_distributed",
    "hybrid_mesh",
    "process_local_batch",
    "sharded_dwt_per",
    "sharded_wavedec_per",
    "sharded_wavedec2_per",
    "sharded_wavedec3_per",
    "sharded_waverec_per",
    "sharded_waverec2_per",
    "sharded_waverec3_per",
    "sharded_coeff_grads_per",
    "TailedLeaf",
    "gather_leaf",
    "gather_coeffs",
    "sharded_wavedec_mode",
    "sharded_wavedec2_mode",
    "sharded_wavedec3_mode",
    "sharded_waverec_mode",
    "sharded_waverec2_mode",
    "sharded_waverec3_mode",
    "sharded_coeff_grads_mode",
    "SeqShardedWam",
    "seq_sharded_wam",
]
