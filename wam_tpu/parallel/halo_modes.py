"""Sequence-sharded DWT for the engines' default boundary modes.

`halo.py` ships the periodized-mode ring-halo decomposition, where the ring
wrap IS the boundary condition and every coefficient array tiles evenly
across shards. The engines, however, default to pywt's expansive modes
(reflect for 2D, symmetric for 1D/3D — reference `lib/wam_2D.py:96`,
`lib/wam_1D.py:109`, `lib/wam_3D.py:194` via ptwt defaults), whose
per-level output length (n + L - 1)//2 exceeds n/2: the extra boundary
coefficients make the leaves indivisible across shards, which is why the
ring-halo path could not cover them (`shard_map` requires identical static
shapes per shard).

This module closes that gap with a **core + tail** decomposition of every
coefficient array. For one analysis level over a length-N signal
(N = C + T, C evenly sharded "core", T replicated "tail"), output j's
correlation window covers signal samples [2j-L+2, 2j+1], so:

- outputs j < C/2 ("core outputs") touch only the signal interior plus the
  LEFT boundary extension. Shard 0 builds that extension locally from its
  own head samples; every other shard needs only the usual (L-2)-sample
  ring halo from its predecessor. The core outputs therefore stay evenly
  sharded and cost one `lax.ppermute` per level — identical ICI traffic to
  the periodized path.
- outputs j >= C/2 ("tail outputs", (T + L - 1)//2 of them) have windows
  crossing the signal's right edge. They depend only on the last ~2L
  signal samples, are computed replicated at the jit level, and stay O(L)
  for any signal length: T_next = (T + L - 1)//2 converges to <= L - 2.

Every leaf is a `TailedLeaf(core, tail)` pair — core sharded over the
sequence axis, tail replicated; `gather_leaf`/`gather_coeffs` concatenate
them into the exact `wam_tpu.wavelets.transform.wavedec*` arrays (parity
pinned by tests/test_halo_modes.py). The `periodic`/`periodization` modes
are excluded: their boundary is the ring wrap itself, which is what
`halo.sharded_wavedec*_per` already implements non-expansively.

Constraints (all checked eagerly with precise messages): the sharded axis
length must be divisible by 2·shards at every level, and the per-shard
block must be at least the filter length L at every level so the halo is a
single hop and shard 0's local extension only consults its own samples.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wam_tpu.wavelets.filters import Wavelet
from wam_tpu.wavelets.transform import (
    _PAD_MODE,
    _analysis,
    _pad_axes,
    _resolve,
    _subband_kernel,
    DETAIL3D_KEYS,
    Detail2D,
)

__all__ = [
    "TailedLeaf",
    "gather_leaf",
    "gather_coeffs",
    "sharded_wavedec_mode",
    "sharded_wavedec2_mode",
    "sharded_wavedec3_mode",
]


class TailedLeaf(NamedTuple):
    """One coefficient array split as (evenly sharded core, replicated tail)."""

    core: jax.Array
    tail: jax.Array


def gather_leaf(leaf: TailedLeaf, axis: int = -1) -> jax.Array:
    """Concatenate core and tail into the full coefficient array."""
    return jnp.concatenate([leaf.core, leaf.tail], axis=axis)


def gather_coeffs(coeffs, ndim: int = 1):
    """Materialize a full `transform.wavedec{,2,3}`-shaped coefficient list
    from the TailedLeaf structure (concat along the sharded axis)."""
    axis = -ndim
    out = []
    for c in coeffs:
        if isinstance(c, TailedLeaf):
            out.append(gather_leaf(c, axis))
        elif isinstance(c, Detail2D):
            out.append(Detail2D(*(gather_leaf(f, axis) for f in c)))
        elif isinstance(c, dict):
            out.append({k: gather_leaf(v, axis) for k, v in c.items()})
        else:  # pragma: no cover - defensive
            raise TypeError(f"unexpected leaf type {type(c)!r}")
    return out


def _check_mode(mode: str):
    if mode in ("periodic", "periodization"):
        raise ValueError(
            f"mode {mode!r}: the wrap boundary IS the ring — use "
            "wam_tpu.parallel.sharded_wavedec{,2,3}_per, which is non-"
            "expansive and fully sharded"
        )
    if mode not in _PAD_MODE:
        raise ValueError(f"Unsupported mode {mode!r}; one of "
                         f"{sorted(set(_PAD_MODE) - {'periodic'})}")


def _check_divisibility(n: int, k: int, L: int, level: int, what: str):
    c = n
    for lev in range(1, level + 1):
        if c % (2 * k):
            raise ValueError(
                f"{what} length {n}: level-{lev} core length {c} is not "
                f"divisible by 2*shards={2 * k}"
            )
        m = c // k
        if m < L:
            raise ValueError(
                f"{what} length {n}: level-{lev} per-shard block {m} is "
                f"shorter than the filter (L={L}); use fewer shards or "
                f"levels"
            )
        c //= 2


def _corr2(x2: jax.Array, wav: Wavelet) -> jax.Array:
    """Valid strided correlation with the fused (lo, hi) analysis bank:
    (B, N) -> (B, 2, (N - L)//2 + 1). Same kernel/precision as
    `transform._analysis` so sharded and single-device numerics agree."""
    kernel = _subband_kernel(wav, 1, x2.dtype)
    out = lax.conv_general_dilated(
        x2[:, None, :],
        kernel,
        window_strides=(2,),
        padding=[(0, 0)],
        dimension_numbers=lax.conv_dimension_numbers(
            (1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")
        ),
        precision=lax.Precision.HIGHEST,
    )
    return out


def _core_local(x_local: jax.Array, wav: Wavelet, mode: str, seq_axis: str) -> jax.Array:
    """Per-shard core-output kernel: (B, m) -> (B, 2, m//2).

    Interior shards prepend the (L-2)-sample ring halo from their
    predecessor; shard 0 instead prepends the mode's left boundary
    extension, built from its own head via the same `_pad_axes` helper the
    single-device transform uses (global padded signal = pad L-1 then drop
    the first sample, so the live left extension is entries [1, L-1))."""
    L = wav.filt_len
    if L > 2:
        need = L - 2
        k = lax.axis_size(seq_axis)
        perm = [(i, (i + 1) % k) for i in range(k)]
        halo = lax.ppermute(x_local[:, -need:], seq_axis, perm=perm)
        head = x_local[:, : min(x_local.shape[-1], 2 * L)]
        lext = _pad_axes(head, L - 1, (-1,), mode)[:, 1 : L - 1]
        first = lax.axis_index(seq_axis) == 0
        ext = jnp.concatenate([jnp.where(first, lext, halo), x_local], axis=-1)
    else:
        ext = x_local
    return _corr2(ext, wav)


def _tail_coeffs(core: jax.Array, tail: jax.Array, wav: Wavelet, mode: str) -> jax.Array:
    """Replicated tail outputs for one level: windows j >= C/2 cover the
    last <= 2L-3 signal samples plus the right boundary extension, all
    derivable from a ~2L-sample end segment. (B, C) x (B, T) ->
    (B, 2, (T + L - 1)//2)."""
    L = wav.filt_len
    C = core.shape[-1]
    T = tail.shape[-1]
    t_out = (T + L - 1) // 2
    if t_out == 0:
        return jnp.zeros((core.shape[0], 2, 0), core.dtype)
    take = min(C, 2 * L)
    seg = jnp.concatenate([lax.slice_in_dim(core, C - take, C, axis=-1), tail], axis=-1)
    segp = jnp.pad(seg, [(0, 0), (0, L - 1)], mode=_PAD_MODE[mode])
    # first tail window (j = C/2) starts at signal coordinate C - L + 2,
    # i.e. offset take - L + 2 into the segment
    return _corr2(segp[:, take - L + 2 :], wav)


def _build_core_run(mesh: Mesh, wav: Wavelet, mode: str, seq_axis: str):
    return shard_map(
        partial(_core_local, wav=wav, mode=mode, seq_axis=seq_axis),
        mesh=mesh,
        in_specs=P(None, seq_axis),
        out_specs=P(None, None, seq_axis),
    )


def _build_local_analysis(mesh: Mesh, wav: Wavelet, mode: str, seq_axis: str, ndim: int):
    """Unsharded-axes analysis of the core, run INSIDE shard_map so the
    sharded axis never enters a jit-level reshape. `_analysis` flattens all
    leading dims into the conv batch; done at the jit level on a
    (B, sharded, ...) array that merges the sharded axis as a minor batch
    factor — unrepresentable for GSPMD, which would silently replicate the
    whole signal. Inside shard_map the op is local, so the sharded axis
    stays sharded by construction and no collective is emitted."""
    spec_in = P(*((None, seq_axis) + (None,) * ndim))
    spec_out = P(*((None, seq_axis) + (None,) * (ndim + 1)))
    return shard_map(
        lambda c: _analysis(c, wav, mode, ndim),
        mesh=mesh,
        in_specs=spec_in,
        out_specs=spec_out,
    )


def _level_1d(core, tail, core_run, wav, mode):
    """One analysis level along the LAST axis of flattened (B, C)/(B, T)
    arrays. Returns ((cA_core, cA_tail), (cD_core, cD_tail))."""
    out2 = core_run(core)
    t2 = _tail_coeffs(core, tail, wav, mode)
    return (out2[:, 0], t2[:, 0]), (out2[:, 1], t2[:, 1])


def sharded_wavedec_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "symmetric", seq_axis: str = "data"
):
    """Multi-level 1D decomposition with pywt boundary modes, sequence-
    sharded over ``seq_axis`` on the LAST axis. Returns a function
    `x -> [cA_J, cD_J, ..., cD_1]` of `TailedLeaf` pairs; `gather_coeffs`
    reproduces `transform.wavedec(x, wavelet, level, mode)` exactly."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis)
    sh = NamedSharding(mesh, P(None, seq_axis))

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead, n = x.shape[:-1], x.shape[-1]
        core = lax.with_sharding_constraint(x.reshape((-1, n)), sh)
        tail = jnp.zeros((core.shape[0], 0), core.dtype)
        leaves = []
        for _ in range(level):
            (core, tail_a), (d_core, d_tail) = _level_1d(core, tail, core_run, wav, mode)
            leaves.append(TailedLeaf(d_core, d_tail))
            tail = tail_a
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return [
            TailedLeaf(c.reshape(lead + c.shape[1:]), t.reshape(lead + t.shape[1:]))
            for c, t in coeffs
        ]

    def run(x):
        _check_divisibility(x.shape[-1], k, wav.filt_len, level, "sequence axis")
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def _flatten2(x):
    """(..., A, B) -> (prod, B) with the static leading shape returned."""
    lead = x.shape[:-1]
    return x.reshape((int(np.prod(lead)) if lead else 1, x.shape[-1])), lead


def _axis_level(core, tail, axis, core_run, wav, mode):
    """One analysis level along ``axis`` (negative index) of core/tail,
    threading the sharded-axis machinery. Returns pairs of
    ((a_core, a_tail), (d_core, d_tail)) with ``axis`` halved."""
    cm = jnp.moveaxis(core, axis, -1)
    tm = jnp.moveaxis(tail, axis, -1)
    cf, lead = _flatten2(cm)
    tf, _ = _flatten2(tm)
    (a_c, a_t), (d_c, d_t) = _level_1d(cf, tf, core_run, wav, mode)

    def unpack(o):
        return jnp.moveaxis(o.reshape(lead + (o.shape[-1],)), -1, axis)

    return (unpack(a_c), unpack(a_t)), (unpack(d_c), unpack(d_t))


def sharded_wavedec2_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "reflect", seq_axis: str = "data"
):
    """Multi-level 2D decomposition with pywt boundary modes for images
    whose ROW axis exceeds one core's memory: x (..., H, W) with H sharded
    over ``seq_axis``. Returns `x -> [cA_J, Detail2D_J, ..., Detail2D_1]`
    where every field is a `TailedLeaf` split along H; `gather_coeffs(out,
    ndim=2)` reproduces `transform.wavedec2` (the W axis is transformed
    locally — boundary extension along H commutes exactly with the per-row
    W transform, so separable == fused)."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis)
    w_run = _build_local_analysis(mesh, wav, mode, seq_axis, 1)
    sh = NamedSharding(mesh, P(None, seq_axis, None))

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-2]
        core = lax.with_sharding_constraint(x.reshape((-1,) + x.shape[-2:]), sh)
        tail = jnp.zeros((core.shape[0], 0, core.shape[-1]), core.dtype)
        leaves = []
        for _ in range(level):
            # W axis first, locally (elementwise over the sharded H axis)
            cw = w_run(core)                    # (B, Hc, 2, W')
            tw = _analysis(tail, wav, mode, 1)  # (B, Ht, 2, W')
            # H axis second, via the sharded core+tail machinery
            (a_c, a_t), (d_c, d_t) = _axis_level(cw, tw, -3, core_run, wav, mode)
            det = Detail2D(
                horizontal=TailedLeaf(d_c[..., 0, :], d_t[..., 0, :]),  # da
                vertical=TailedLeaf(a_c[..., 1, :], a_t[..., 1, :]),    # ad
                diagonal=TailedLeaf(d_c[..., 1, :], d_t[..., 1, :]),    # dd
            )
            leaves.append(det)
            core, tail = a_c[..., 0, :], a_t[..., 0, :]
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return jax.tree_util.tree_map(
            lambda a: a.reshape(lead + a.shape[1:]), coeffs
        )

    def run(x):
        _check_divisibility(x.shape[-2], k, wav.filt_len, level, "row axis")
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def sharded_wavedec3_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "symmetric", seq_axis: str = "data"
):
    """Multi-level 3D decomposition with pywt boundary modes for volumes
    whose DEPTH axis exceeds one core's memory: x (..., D, H, W) with D
    sharded over ``seq_axis``. Returns `x -> [cA_J, {aad..ddd}_J, ...]`
    with `TailedLeaf` values split along D; `gather_coeffs(out, ndim=3)`
    reproduces `transform.wavedec3`."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis)
    hw_run = _build_local_analysis(mesh, wav, mode, seq_axis, 2)
    sh = NamedSharding(mesh, P(None, seq_axis, None, None))
    keys = ("aaa",) + DETAIL3D_KEYS

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-3]
        core = lax.with_sharding_constraint(x.reshape((-1,) + x.shape[-3:]), sh)
        tail = jnp.zeros((core.shape[0], 0) + core.shape[-2:], core.dtype)
        leaves = []
        for _ in range(level):
            # H and W axes first, locally (fused 4-channel conv per slab)
            chw = hw_run(core)                   # (B, Dc, 4, H', W')
            thw = _analysis(tail, wav, mode, 2)  # (B, Dt, 4, H', W')
            # D axis second, via the sharded core+tail machinery
            (a_c, a_t), (d_c, d_t) = _axis_level(chw, thw, -4, core_run, wav, mode)
            det = {}
            for code in range(1, 8):
                d_bit, ch2d = code >> 2, code & 3
                src_c, src_t = (d_c, d_t) if d_bit else (a_c, a_t)
                det[keys[code]] = TailedLeaf(
                    src_c[..., ch2d, :, :], src_t[..., ch2d, :, :]
                )
            leaves.append(det)
            core, tail = a_c[..., 0, :, :], a_t[..., 0, :, :]
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return jax.tree_util.tree_map(
            lambda a: a.reshape(lead + a.shape[1:]), coeffs
        )

    def run(x):
        _check_divisibility(x.shape[-3], k, wav.filt_len, level, "depth axis")
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run
