"""Sequence-sharded DWT for the engines' default boundary modes.

`halo.py` ships the periodized-mode ring-halo decomposition, where the ring
wrap IS the boundary condition and every coefficient array tiles evenly
across shards. The engines, however, default to pywt's expansive modes
(reflect for 2D, symmetric for 1D/3D — reference `lib/wam_2D.py:96`,
`lib/wam_1D.py:109`, `lib/wam_3D.py:194` via ptwt defaults), whose
per-level output length (n + L - 1)//2 exceeds n/2: the extra boundary
coefficients make the leaves indivisible across shards, which is why the
ring-halo path could not cover them (`shard_map` requires identical static
shapes per shard).

This module closes that gap with a **core + tail** decomposition of every
coefficient array. For one analysis level over a length-N signal
(N = C + T, C evenly sharded "core", T replicated "tail"), output j's
correlation window covers signal samples [2j-L+2, 2j+1], so:

- outputs j < C/2 ("core outputs") touch only the signal interior plus the
  LEFT boundary extension. Shard 0 builds that extension locally from its
  own head samples; every other shard needs only the usual (L-2)-sample
  ring halo from its predecessor. The core outputs therefore stay evenly
  sharded and cost one `lax.ppermute` per level — identical ICI traffic to
  the periodized path.
- outputs j >= C/2 ("tail outputs", (T + L - 1)//2 of them) have windows
  crossing the signal's right edge. They depend only on the last ~2L
  signal samples, are computed replicated at the jit level, and stay O(L)
  for any signal length: T_next = (T + L - 1)//2 converges to <= L - 2.

Every leaf is a `TailedLeaf(core, tail)` pair — core sharded over the
sequence axis, tail replicated; `gather_leaf`/`gather_coeffs` concatenate
them into the exact `wam_tpu.wavelets.transform.wavedec*` arrays (parity
pinned by tests/test_halo_modes.py). The `periodic`/`periodization` modes
are excluded: their boundary is the ring wrap itself, which is what
`halo.sharded_wavedec*_per` already implements non-expansively.

**Statically-empty tails are omitted, not carried.** When a tail is
provably empty at trace time (haar chains, where T_next = (T + 1)//2
never leaves 0; the top-level reconstruction tail, 2h - L + 2 == 0 for
every even-length filter), the leaf stores ``tail=None`` instead of a
``(B, 0)`` array. A zero-size buffer is dead weight the SPMD partitioner
still has to assign a sharding to — and on some XLA versions a sharded
zero-size operand feeding a concat/reshape chain trips the partitioner's
reshape verifier ("reshape element count mismatch, failed after
spmd-partitioning"). Slicing the empty tail out of the pytree BEFORE the
jit boundary turns that from a runtime sharding question into static
structure: the partitioner never sees the buffer at all, which is what
lets `sharded_coeff_grads_mode` trace decompose → reconstruct → model →
VJP as ONE jit (see tests/test_partitioner_repro.py for the pinned
trigger pattern). `None` is an empty pytree node, so `jax.grad` and
`tree_map` handle the omission for free. Hand-built leaves with zero-size
tail arrays are normalized to the None form at the eager entry points.

Constraints (all checked eagerly with precise messages): the sharded axis
length must be divisible by 2·shards at every level, and the per-shard
block must be at least the filter length L at every level so the halo is a
single hop and shard 0's local extension only consults its own samples.
``batch_axis=`` additionally shards the flattened leading axis over a
second mesh axis on every entry point (1D/2D/3D, both directions): cores
carry P(batch, seq, ...). The O(L) tails are P(batch, None) in 1D but
FULLY replicated in 2D/3D — constraining them batch-sharded miscompiles
the downstream synthesis under the legacy shard_map lowering (DESIGN.md
"Sequence-sharded fusion" documents the failure).
"""

from __future__ import annotations

import functools
import math
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wam_tpu.compat import axis_size, shard_map

from wam_tpu.wavelets.filters import Wavelet
from wam_tpu.wavelets.transform import (
    _PAD_MODE,
    _analysis,
    _pad_axes,
    _resolve,
    _subband_kernel,
    _synthesis,
    DETAIL3D_KEYS,
    Detail2D,
)

__all__ = [
    "TailedLeaf",
    "gather_leaf",
    "gather_coeffs",
    "sharded_wavedec_mode",
    "sharded_wavedec2_mode",
    "sharded_wavedec3_mode",
    "sharded_waverec_mode",
    "sharded_waverec2_mode",
    "sharded_waverec3_mode",
    "sharded_coeff_grads_mode",
]


class TailedLeaf(NamedTuple):
    """One coefficient array split as (evenly sharded core, replicated tail).

    ``tail`` is ``None`` when the tail is statically empty (haar chains,
    top-level reconstructions): the empty buffer is omitted from the pytree
    instead of carried as a ``(B, 0)`` array the partitioner would have to
    shard. ``None`` is an empty pytree node, so gradients and tree_maps
    flow through the omission unchanged."""

    core: jax.Array
    tail: Optional[jax.Array]


def _tail_len(tail, axis: int = -1) -> int:
    return 0 if tail is None else tail.shape[axis]


def gather_leaf(leaf: TailedLeaf, axis: int = -1) -> jax.Array:
    """Concatenate core and tail into the full coefficient array.

    A ``None`` (or hand-built zero-size) tail returns the core directly:
    besides being a no-op, a concat with a zero-size operand is exactly the
    pattern that trips the XLA SPMD-partitioner reshape verifier on
    affected versions when the core is sharded (see module docstring)."""
    if _tail_len(leaf.tail, axis) == 0:
        return leaf.core
    return jnp.concatenate([leaf.core, leaf.tail], axis=axis)


def gather_coeffs(coeffs, ndim: int = 1):
    """Materialize a full `transform.wavedec{,2,3}`-shaped coefficient list
    from the TailedLeaf structure (concat along the sharded axis)."""
    axis = -ndim
    out = []
    for c in coeffs:
        if isinstance(c, TailedLeaf):
            out.append(gather_leaf(c, axis))
        elif isinstance(c, Detail2D):
            out.append(Detail2D(*(gather_leaf(f, axis) for f in c)))
        elif isinstance(c, dict):
            out.append({k: gather_leaf(v, axis) for k, v in c.items()})
        else:  # pragma: no cover - defensive
            raise TypeError(f"unexpected leaf type {type(c)!r}")
    return out


def _normalize_tails(coeffs, axis: int):
    """Map hand-built zero-size tail arrays onto the ``tail=None`` static
    structure so every downstream trace sees one canonical pytree."""

    def norm(leaf: TailedLeaf) -> TailedLeaf:
        if leaf.tail is not None and leaf.tail.shape[axis] == 0:
            return TailedLeaf(leaf.core, None)
        return leaf

    out = []
    for c in coeffs:
        if isinstance(c, TailedLeaf):
            out.append(norm(c))
        elif isinstance(c, dict):
            out.append({k: norm(v) for k, v in c.items()})
        else:
            out.append(type(c)(*(norm(f) for f in c)))
    return out


def _check_mode(mode: str):
    if mode in ("periodic", "periodization"):
        raise ValueError(
            f"mode {mode!r}: the wrap boundary IS the ring — use "
            "wam_tpu.parallel.sharded_wavedec{,2,3}_per, which is non-"
            "expansive and fully sharded"
        )
    if mode not in _PAD_MODE:
        raise ValueError(f"Unsupported mode {mode!r}; one of "
                         f"{sorted(set(_PAD_MODE) - {'periodic'})}")


def _check_divisibility(n: int, k: int, L: int, level: int, what: str):
    c = n
    for lev in range(1, level + 1):
        if c % (2 * k):
            raise ValueError(
                f"{what} length {n}: level-{lev} core length {c} is not "
                f"divisible by 2*shards={2 * k}"
            )
        m = c // k
        if m < L:
            raise ValueError(
                f"{what} length {n}: level-{lev} per-shard block {m} is "
                f"shorter than the filter (L={L}); use fewer shards or "
                f"levels"
            )
        c //= 2


def _corr2(x2: jax.Array, wav: Wavelet) -> jax.Array:
    """Valid strided correlation with the fused (lo, hi) analysis bank:
    (B, N) -> (B, 2, (N - L)//2 + 1). Same kernel/precision as
    `transform._analysis` so sharded and single-device numerics agree."""
    kernel = _subband_kernel(wav, 1, x2.dtype)
    out = lax.conv_general_dilated(
        x2[:, None, :],
        kernel,
        window_strides=(2,),
        padding=[(0, 0)],
        dimension_numbers=lax.conv_dimension_numbers(
            (1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")
        ),
        precision=lax.Precision.HIGHEST,
    )
    return out


def _core_local(x_local: jax.Array, wav: Wavelet, mode: str, seq_axis: str) -> jax.Array:
    """Per-shard core-output kernel: (B, m) -> (B, 2, m//2).

    Interior shards prepend the (L-2)-sample ring halo from their
    predecessor; shard 0 instead prepends the mode's left boundary
    extension, built from its own head via the same `_pad_axes` helper the
    single-device transform uses (global padded signal = pad L-1 then drop
    the first sample, so the live left extension is entries [1, L-1))."""
    L = wav.filt_len
    if L > 2:
        need = L - 2
        k = axis_size(seq_axis)
        perm = [(i, (i + 1) % k) for i in range(k)]
        halo = lax.ppermute(x_local[:, -need:], seq_axis, perm=perm)
        head = x_local[:, : min(x_local.shape[-1], 2 * L)]
        lext = _pad_axes(head, L - 1, (-1,), mode)[:, 1 : L - 1]
        first = lax.axis_index(seq_axis) == 0
        ext = jnp.concatenate([jnp.where(first, lext, halo), x_local], axis=-1)
    else:
        ext = x_local
    return _corr2(ext, wav)


def _tail_coeffs(core: jax.Array, tail, wav: Wavelet, mode: str, repl_sh=None):
    """Replicated tail outputs for one level: windows j >= C/2 cover the
    last <= 2L-3 signal samples plus the right boundary extension, all
    derivable from a ~2L-sample end segment. (B, C) x (B, T) ->
    (B, 2, (T + L - 1)//2), or ``None`` when that length is statically 0
    (haar: the tail never leaves 0, so the leaf omits it entirely)."""
    L = wav.filt_len
    C = core.shape[-1]
    T = _tail_len(tail)
    t_out = (T + L - 1) // 2
    if t_out == 0:
        return None
    take = min(C, 2 * L)
    end = lax.slice_in_dim(core, C - take, C, axis=-1)
    seg = end if T == 0 else jnp.concatenate([end, tail], axis=-1)
    if repl_sh is not None:
        seg = lax.with_sharding_constraint(seg, repl_sh)
    segp = jnp.pad(seg, [(0, 0), (0, L - 1)], mode=_PAD_MODE[mode])
    # first tail window (j = C/2) starts at signal coordinate C - L + 2,
    # i.e. offset take - L + 2 into the segment
    out = _corr2(segp[:, take - L + 2 :], wav)
    # anchor the tiny conv replicated AT THE OP: propagation left alone may
    # shard its ~L-long output over the mesh into zero-size partitions and
    # die after spmd-partitioning (db6-J>=3 and 3D-db2-J=3 regressions)
    if repl_sh is not None:
        out = lax.with_sharding_constraint(out, repl_sh)
    return out


def _pin(tail, sh):
    """Tail sharding constraint that tolerates the omitted-tail form."""
    return None if tail is None else lax.with_sharding_constraint(tail, sh)


def _build_core_run(mesh: Mesh, wav: Wavelet, mode: str, seq_axis: str,
                    batch_axis: str | None = None):
    return shard_map(
        partial(_core_local, wav=wav, mode=mode, seq_axis=seq_axis),
        mesh=mesh,
        in_specs=P(batch_axis, seq_axis),
        out_specs=P(batch_axis, None, seq_axis),
    )


def _build_local_analysis(mesh: Mesh, wav: Wavelet, mode: str, seq_axis: str,
                          ndim: int, batch_axis: str | None = None):
    """Unsharded-axes analysis of the core, run INSIDE shard_map so the
    sharded axis never enters a jit-level reshape. `_analysis` flattens all
    leading dims into the conv batch; done at the jit level on a
    (B, sharded, ...) array that merges the sharded axis as a minor batch
    factor — unrepresentable for GSPMD, which would silently replicate the
    whole signal. Inside shard_map the op is local, so the sharded axis
    stays sharded by construction and no collective is emitted."""
    spec_in = P(*((batch_axis, seq_axis) + (None,) * ndim))
    spec_out = P(*((batch_axis, seq_axis) + (None,) * (ndim + 1)))
    return shard_map(
        lambda c: _analysis(c, wav, mode, ndim),
        mesh=mesh,
        in_specs=spec_in,
        out_specs=spec_out,
    )


def _level_1d(core, tail, core_run, wav, mode, repl_sh=None):
    """One analysis level along the LAST axis of flattened (B, C)/(B, T)
    arrays. Returns ((cA_core, cA_tail), (cD_core, cD_tail)); the tails are
    ``None`` when statically empty (haar)."""
    out2 = core_run(core)
    t2 = _tail_coeffs(core, tail, wav, mode, repl_sh)
    if t2 is None:
        return (out2[:, 0], None), (out2[:, 1], None)
    return (out2[:, 0], t2[:, 0]), (out2[:, 1], t2[:, 1])


def sharded_wavedec_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "symmetric", seq_axis: str = "data",
    batch_axis: str | None = None
):
    """Multi-level 1D decomposition with pywt boundary modes, sequence-
    sharded over ``seq_axis`` on the LAST axis. Returns a function
    `x -> [cA_J, cD_J, ..., cD_1]` of `TailedLeaf` pairs; `gather_coeffs`
    reproduces `transform.wavedec(x, wavelet, level, mode)` exactly.
    ``batch_axis`` additionally shards the flattened LEADING axis over that
    mesh axis (cores AND the O(L) tails — the tails stay replicated along
    the sequence axis only); the flattened leading dims must divide it."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis, batch_axis)
    sh = NamedSharding(mesh, P(batch_axis, seq_axis))
    repl = NamedSharding(mesh, P(batch_axis, None))

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead, n = x.shape[:-1], x.shape[-1]
        core = lax.with_sharding_constraint(x.reshape((-1, n)), sh)
        tail = None  # statically empty at the input — omitted, not (B, 0)
        leaves = []
        for _ in range(level):
            (core, tail_a), (d_core, d_tail) = _level_1d(core, tail, core_run, wav, mode, repl)
            # keep the O(L) tails replicated — see sharded_waverec_mode
            leaves.append(TailedLeaf(d_core, _pin(d_tail, repl)))
            tail = _pin(tail_a, repl)
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return [
            TailedLeaf(
                c.reshape(lead + c.shape[1:]),
                None if t is None else t.reshape(lead + t.shape[1:]),
            )
            for c, t in coeffs
        ]

    def check(x):
        from wam_tpu.parallel.halo import _check_batch_divisible

        _check_divisibility(x.shape[-1], k, wav.filt_len, level, "sequence axis")
        _check_batch_divisible(math.prod(x.shape[:-1]), mesh, batch_axis)

    def run(x):
        check(x)
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    run._check = check  # eager shape checks, reused by the fused grads path
    return run


def _flatten2(x):
    """(..., A, B) -> (prod, B) with the static leading shape returned."""
    lead = x.shape[:-1]
    return x.reshape((math.prod(lead), x.shape[-1])), lead


def _axis_level(core, tail, axis, core_run, wav, mode, repl_sh=None):
    """One analysis level along ``axis`` (negative index) of core/tail,
    threading the sharded-axis machinery. Returns pairs of
    ((a_core, a_tail), (d_core, d_tail)) with ``axis`` halved; tails may be
    ``None`` (statically empty)."""
    cm = jnp.moveaxis(core, axis, -1)
    cf, lead = _flatten2(cm)
    tf = None if tail is None else _flatten2(jnp.moveaxis(tail, axis, -1))[0]
    (a_c, a_t), (d_c, d_t) = _level_1d(cf, tf, core_run, wav, mode, repl_sh)

    def unpack(o):
        if o is None:
            return None
        return jnp.moveaxis(o.reshape(lead + (o.shape[-1],)), -1, axis)

    return (unpack(a_c), unpack(a_t)), (unpack(d_c), unpack(d_t))


def sharded_wavedec2_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "reflect", seq_axis: str = "data",
    batch_axis: str | None = None
):
    """Multi-level 2D decomposition with pywt boundary modes for images
    whose ROW axis exceeds one core's memory: x (..., H, W) with H sharded
    over ``seq_axis``. Returns `x -> [cA_J, Detail2D_J, ..., Detail2D_1]`
    where every field is a `TailedLeaf` split along H; `gather_coeffs(out,
    ndim=2)` reproduces `transform.wavedec2` (the W axis is transformed
    locally — boundary extension along H commutes exactly with the per-row
    W transform, so separable == fused). ``batch_axis``: see
    `sharded_wavedec_mode`."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis, batch_axis)
    w_run = _build_local_analysis(mesh, wav, mode, seq_axis, 1, batch_axis)
    sh = NamedSharding(mesh, P(batch_axis, seq_axis, None))
    # tails stay FULLY replicated even under batch_axis: constraining the
    # O(L) tails batch-sharded miscompiles the downstream synthesis under
    # legacy shard_map (wrong values in the tail-influenced rows, jax
    # 0.4.37 CPU — see DESIGN.md "Sequence-sharded fusion"); replicating a
    # few KB across the batch axis costs nothing
    repl2 = NamedSharding(mesh, P(None, None))

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-2]
        core = lax.with_sharding_constraint(x.reshape((-1,) + x.shape[-2:]), sh)
        tail = None
        leaves = []
        for _ in range(level):
            # W axis first, locally (elementwise over the sharded H axis)
            cw = w_run(core)                    # (B, Hc, 2, W')
            tw = None if tail is None else _analysis(tail, wav, mode, 1)
            # H axis second, via the sharded core+tail machinery
            (a_c, a_t), (d_c, d_t) = _axis_level(cw, tw, -3, core_run, wav, mode, repl2)
            tsel = lambda t, ch: None if t is None else t[..., ch, :]
            det = Detail2D(
                horizontal=TailedLeaf(d_c[..., 0, :], tsel(d_t, 0)),  # da
                vertical=TailedLeaf(a_c[..., 1, :], tsel(a_t, 1)),    # ad
                diagonal=TailedLeaf(d_c[..., 1, :], tsel(d_t, 1)),    # dd
            )
            leaves.append(det)
            core, tail = a_c[..., 0, :], tsel(a_t, 0)
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return jax.tree_util.tree_map(
            lambda a: a.reshape(lead + a.shape[1:]), coeffs
        )

    def check(x):
        from wam_tpu.parallel.halo import _check_batch_divisible

        _check_divisibility(x.shape[-2], k, wav.filt_len, level, "row axis")
        _check_batch_divisible(math.prod(x.shape[:-2]), mesh, batch_axis)

    def run(x):
        check(x)
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    run._check = check
    return run


def sharded_wavedec3_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "symmetric", seq_axis: str = "data",
    batch_axis: str | None = None
):
    """Multi-level 3D decomposition with pywt boundary modes for volumes
    whose DEPTH axis exceeds one core's memory: x (..., D, H, W) with D
    sharded over ``seq_axis``. Returns `x -> [cA_J, {aad..ddd}_J, ...]`
    with `TailedLeaf` values split along D; `gather_coeffs(out, ndim=3)`
    reproduces `transform.wavedec3`. ``batch_axis``: see
    `sharded_wavedec_mode`."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis, batch_axis)
    hw_run = _build_local_analysis(mesh, wav, mode, seq_axis, 2, batch_axis)
    sh = NamedSharding(mesh, P(batch_axis, seq_axis, None, None))
    # tails fully replicated under batch_axis — see sharded_wavedec2_mode
    repl2 = NamedSharding(mesh, P(None, None))
    keys = ("aaa",) + DETAIL3D_KEYS

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-3]
        core = lax.with_sharding_constraint(x.reshape((-1,) + x.shape[-3:]), sh)
        tail = None
        leaves = []
        for _ in range(level):
            # H and W axes first, locally (fused 4-channel conv per slab)
            chw = hw_run(core)                   # (B, Dc, 4, H', W')
            thw = None if tail is None else _analysis(tail, wav, mode, 2)
            # D axis second, via the sharded core+tail machinery
            (a_c, a_t), (d_c, d_t) = _axis_level(chw, thw, -4, core_run, wav, mode, repl2)
            tsel = lambda t, ch: None if t is None else t[..., ch, :, :]
            det = {}
            for code in range(1, 8):
                d_bit, ch2d = code >> 2, code & 3
                src_c, src_t = (d_c, d_t) if d_bit else (a_c, a_t)
                det[keys[code]] = TailedLeaf(
                    src_c[..., ch2d, :, :], tsel(src_t, ch2d)
                )
            leaves.append(det)
            core, tail = a_c[..., 0, :, :], tsel(a_t, 0)
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return jax.tree_util.tree_map(
            lambda a: a.reshape(lead + a.shape[1:]), coeffs
        )

    def check(x):
        from wam_tpu.parallel.halo import _check_batch_divisible

        _check_divisibility(x.shape[-3], k, wav.filt_len, level, "depth axis")
        _check_batch_divisible(math.prod(x.shape[:-3]), mesh, batch_axis)

    def run(x):
        check(x)
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    run._check = check
    return run


# ---------------------------------------------------------------------------
# Inverse (synthesis) direction for the expansive modes — completes the
# DEFAULT-mode long-context loop: decompose → perturb → reconstruct → model.
# ---------------------------------------------------------------------------


def _synth_core_local(subs_local: jax.Array, halo_src: jax.Array, wav: Wavelet, seq_axis: str) -> jax.Array:
    """Per-shard synthesis kernel: (B, 2, m) local subbands -> (B, 2m) local
    reconstruction. Output sample t depends on coefficients
    j ∈ [⌈(t-1)/2⌉, ⌊(t+L-2)/2⌋], i.e. the halo travels from the SUCCESSOR
    (the reversed ring of the analysis direction); the last shard's
    successor-halo is the replicated tail's head, passed in as ``halo_src``."""
    L = wav.filt_len
    m = subs_local.shape[-1]
    h = (L - 1) // 2
    if h > 0:
        k = axis_size(seq_axis)
        perm = [(i, (i - 1) % k) for i in range(k)]
        ring = lax.ppermute(subs_local[..., :h], seq_axis, perm=perm)
        last = lax.axis_index(seq_axis) == k - 1
        ext = jnp.concatenate([subs_local, jnp.where(last, halo_src, ring)], axis=-1)
    else:
        ext = subs_local
    # trimming to 2m keeps exactly this shard's outputs (the [0, 2m) window
    # of the block reconstruction equals the global samples [2sm, 2(s+1)m))
    flat = ext.reshape((-1,) + ext.shape[-2:])
    out = _synthesis(flat, wav, 1, (2 * m,))
    return out.reshape(ext.shape[:-2] + (2 * m,))


def _level_inv_1d(coreA, tailA, coreD, tailD, synth_run, wav, repl_sh=None):
    """One synthesis level on TailedLeaf pieces (flattened (B, ·) arrays):
    returns (core_out (B, 2C) sharded, tail_out (B, 2T-L+2) replicated, or
    ``None`` when that length is statically 0). Tail outputs t >= 2C depend
    ONLY on tail coefficients (jmin(2C) = C), so they synthesize replicated
    from the tails alone."""
    L = wav.filt_len
    T = _tail_len(tailA)
    h = (L - 1) // 2
    if T < h:
        raise ValueError(
            f"tail length {T} < {h} coefficients: the last shard's synthesis "
            "halo must come from the tail; feed leaves produced by "
            "sharded_wavedec_mode (its tails always satisfy this)"
        )
    subs = jnp.stack([coreA, coreD], axis=-2)          # (B, 2, C)
    if tailA is None:
        # statically-empty tails (haar chains): h == 0, so the successor
        # halo is never consulted and there are no tail outputs — pass a
        # zero-size slice of the subbands purely to satisfy the signature
        return synth_run(subs, subs[..., :0]), None
    tail_subs = jnp.stack([tailA, tailD], axis=-2)     # (B, 2, T)
    if repl_sh is not None:
        # bracket the tiny synthesis conv replicated on BOTH sides: the
        # partitioner derives a conv's sharding from its operands, so an
        # output-side constraint alone lands after the internal squeeze and
        # the conv still gets spatially partitioned into zero-size pieces
        # (the batch entry of repl_sh rides along — batch_axis support)
        tail_subs = lax.with_sharding_constraint(
            tail_subs, NamedSharding(repl_sh.mesh, P(repl_sh.spec[0], None, None))
        )
    core_out = synth_run(subs, tail_subs[..., :h])
    t_len = max(2 * T - L + 2, 0)
    if t_len == 0:  # exact-h tails: the top level of every even-L chain
        return core_out, None
    tail_out = _synthesis(tail_subs, wav, 1, (t_len,))
    if repl_sh is not None:
        tail_out = lax.with_sharding_constraint(tail_out, repl_sh)
    return core_out, tail_out


def _check_coeff_leaves(coeffs, wav: Wavelet, axis: int, k: int,
                        producer: str, what: str):
    """Shared eager validation for the waverec run() wrappers — ONE
    container flattening (TailedLeaf | Detail2D | 3D dict) for both checks:

    - core divisibility by the shard count along ``axis``;
    - the `_level_inv_1d` trace-time invariant (round-4 advisor): the last
      shard's synthesis halo comes from the tail, so every leaf's tail must
      hold at least (L-1)//2 coefficients along ``axis`` (``producer``'s
      tails always do; ``None`` counts as length 0 and only passes for
      haar, whose halo is empty)."""
    h_min = (wav.filt_len - 1) // 2
    for c in coeffs:
        if isinstance(c, TailedLeaf):
            pieces = [c]
        elif isinstance(c, dict):
            pieces = list(c.values())
        else:
            pieces = list(c)
        for piece in pieces:
            n = piece.core.shape[axis]
            if n % k:
                raise ValueError(
                    f"coefficient core {what} {n} is not divisible by "
                    f"shards={k}: these leaves were not produced by "
                    f"{producer} on this mesh"
                )
            if _tail_len(piece.tail, axis) < h_min:
                raise ValueError(
                    f"coefficient tail length {_tail_len(piece.tail, axis)} < "
                    f"{h_min}: the last shard's synthesis halo must come "
                    f"from the tail; feed leaves produced by {producer}"
                )


def _build_synth_run(mesh: Mesh, wav: Wavelet, seq_axis: str,
                     batch_axis: str | None = None):
    return shard_map(
        partial(_synth_core_local, wav=wav, seq_axis=seq_axis),
        mesh=mesh,
        in_specs=(P(batch_axis, None, seq_axis), P(batch_axis, None, None)),
        out_specs=P(batch_axis, seq_axis),
    )


def sharded_waverec_mode(mesh: Mesh, wavelet, seq_axis: str = "data",
                         batch_axis: str | None = None):
    """Inverse of `sharded_wavedec_mode`: the TailedLeaf coefficient list
    back to the (..., N) signal as a `TailedLeaf` (core (..., 2C_top)
    sharded, tail ``None`` — statically empty for every even-length filter,
    so `gather_leaf` returns the core as the full signal directly).
    Matches `transform.waverec` exactly — including its trim-to-detail
    convention, which in core+tail form touches only the replicated tail.
    ``batch_axis``: see `sharded_wavedec_mode`."""
    wav = _resolve(wavelet)
    synth_run = _build_synth_run(mesh, wav, seq_axis, batch_axis)
    # pin every tail op replicated ALONG THE SEQ AXIS (batch may shard):
    # left to propagation, the partitioner may try to shard a length-~L
    # tail conv over the mesh, producing zero-size partitions and an
    # invalid reshape ("failed after spmd-partitioning")
    repl = NamedSharding(mesh, P(batch_axis, None))

    @jax.jit
    def apply(coeffs):
        lead = coeffs[0].core.shape[:-1]
        b = math.prod(lead)
        flat = [
            TailedLeaf(
                c.core.reshape((b, c.core.shape[-1])),
                None if c.tail is None
                else c.tail.reshape((b, c.tail.shape[-1])),
            )
            for c in coeffs
        ]
        a = flat[0]
        for d in flat[1:]:
            td = _tail_len(d.tail)
            if _tail_len(a.tail) > td:
                a = TailedLeaf(a.core, a.tail[..., :td] if td else None)
            core, tail = _level_inv_1d(a.core, a.tail, d.core, d.tail, synth_run, wav, repl)
            a = TailedLeaf(core, _pin(tail, repl))
        return TailedLeaf(
            a.core.reshape(lead + a.core.shape[1:]),
            None if a.tail is None
            else a.tail.reshape(lead + a.tail.shape[1:]),
        )

    k = mesh.shape[seq_axis]

    def run(coeffs):
        from wam_tpu.parallel.halo import _check_batch_divisible

        coeffs = _normalize_tails(coeffs, -1)
        _check_coeff_leaves(coeffs, wav, -1, k, "sharded_wavedec_mode",
                            "length")
        _check_batch_divisible(math.prod(coeffs[0].core.shape[:-1]),
                               mesh, batch_axis)
        return apply(coeffs)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def sharded_coeff_grads_mode(
    mesh: Mesh, wavelet, level: int, model_fn, mode: str = "symmetric",
    seq_axis: str = "data", ndim: int = 1, fused: bool = True
):
    """End-to-end long-context WAM gradient core in the engines' DEFAULT
    boundary modes (the periodized variant is
    `halo.sharded_coeff_grads_per`): sequence-sharded decompose →
    reconstruct → model → per-coefficient gradients. ``ndim`` selects the
    modality (1 = waveform, 2 = image rows, 3 = volume depth). `model_fn`
    maps the reconstructed signal to (B, classes) logits (sequence-
    partitionable); gradients come back in the TailedLeaf structure of the
    coefficients. The reconstruction handed to the model is evenly sharded:
    the top-level tail is empty by construction.

    ``fused=True`` (default) traces the whole chain as ONE jit — one
    dispatch per call. Historically this was impossible: the zero-size tail
    buffers the chain carried tripped an XLA SPMD-partitioner verifier bug
    ("reshape element count mismatch, failed after spmd-partitioning") on
    the one-jit graph. With statically-empty tails now omitted from the
    pytree (``tail=None`` — see module docstring) the partitioner never
    sees a zero-size operand and the fusion partitions cleanly;
    tests/test_partitioner_repro.py pins the historical trigger pattern and
    xfails only where the bug still fires. ``fused=False`` keeps the old
    two-dispatch split (decompose, then grads) for A/B timing and
    bit-exactness checks; the halves stay exposed as ``step._dec`` /
    ``step._grads`` either way for HLO audits."""
    wav = _resolve(wavelet)
    if ndim not in (1, 2, 3):
        raise ValueError(f"ndim must be 1, 2, or 3; got {ndim!r}")
    dec = {
        1: sharded_wavedec_mode,
        2: sharded_wavedec2_mode,
        3: sharded_wavedec3_mode,
    }[ndim](mesh, wav, level, mode, seq_axis)
    rec = {
        1: sharded_waverec_mode,
        2: sharded_waverec2_mode,
        3: sharded_waverec3_mode,
    }[ndim](mesh, wav, seq_axis)

    def _objective(cs, y):
        out = model_fn(gather_leaf(rec(cs), axis=-ndim))
        if y is None:
            return out.mean()
        return jnp.take_along_axis(out, y[:, None], axis=1).sum()

    grads_labeled = jax.jit(lambda cs, y: jax.grad(_objective)(cs, y))
    grads_rep = jax.jit(lambda cs: jax.grad(_objective)(cs, None))

    if fused:
        fused_labeled = jax.jit(
            lambda x, y: jax.grad(_objective)(dec._apply(x), y))
        fused_rep = jax.jit(
            lambda x: jax.grad(_objective)(dec._apply(x), None))

        def step(x, y=None):
            dec._check(x)  # eager shape errors, then exactly one dispatch
            return fused_labeled(x, y) if y is not None else fused_rep(x)

        step._fused = fused_labeled  # the one-jit graph, for HLO audits
    else:
        def step(x, y=None):
            coeffs = dec(x)
            return grads_labeled(coeffs, y) if y is not None else grads_rep(coeffs)

        step._fused = None

    step._dec = dec  # jitted halves, exposed for HLO audits (tests)
    step._grads = grads_labeled
    return step


def _build_local_synthesis(mesh: Mesh, wav: Wavelet, seq_axis: str, ndim: int,
                           out_shape, batch_axis: str | None = None):
    """Unsharded-axes synthesis of the core, run INSIDE shard_map for the
    same reason as `_build_local_analysis`: `_synthesis` flattens leading
    dims (including the sharded axis) into the conv batch, which at the jit
    level merges the sharded axis as a minor factor — unrepresentable for
    GSPMD, which would replicate. ``out_shape`` is the trimmed per-axis
    target (static per level)."""
    spec_in = P(*((batch_axis, seq_axis) + (None,) * (ndim + 1)))
    spec_out = P(*((batch_axis, seq_axis) + (None,) * ndim))
    return shard_map(
        lambda s: _synthesis(s, wav, ndim, out_shape),
        mesh=mesh,
        in_specs=spec_in,
        out_specs=spec_out,
    )


def _axis_level_inv(a_pair, d_pair, axis, synth_run, wav, repl_sh=None):
    """One synthesis level along ``axis`` (negative index): the inverse of
    `_axis_level`. ``a_pair``/``d_pair`` are (core, tail) along that axis
    (tails possibly ``None``); returns (core 2C, tail 2T-L+2 or ``None``)
    with ``axis`` doubled."""
    (a_c, a_t), (d_c, d_t) = a_pair, d_pair
    cf_a, lead = _flatten2(jnp.moveaxis(a_c, axis, -1))
    cf_d, _ = _flatten2(jnp.moveaxis(d_c, axis, -1))
    tf_a = None if a_t is None else _flatten2(jnp.moveaxis(a_t, axis, -1))[0]
    tf_d = None if d_t is None else _flatten2(jnp.moveaxis(d_t, axis, -1))[0]
    core, tail = _level_inv_1d(cf_a, tf_a, cf_d, tf_d, synth_run, wav, repl_sh)

    def unpack(o):
        if o is None:
            return None
        return jnp.moveaxis(o.reshape(lead + (o.shape[-1],)), -1, axis)

    return unpack(core), unpack(tail)


def sharded_waverec2_mode(mesh: Mesh, wavelet, seq_axis: str = "data",
                          batch_axis: str | None = None):
    """Inverse of `sharded_wavedec2_mode` (row axis sharded): TailedLeaf
    coefficient structure back to the (..., H, W) image as a `TailedLeaf`
    split along H (top-level tail ``None`` — see `sharded_waverec_mode`).
    Matches `transform.waverec2` exactly, including its trim-to-detail
    convention on both axes. ``batch_axis``: see `sharded_wavedec_mode`."""
    wav = _resolve(wavelet)
    L = wav.filt_len
    synth_run = _build_synth_run(mesh, wav, seq_axis, batch_axis)
    # tail constraints carry NO batch entry — see sharded_wavedec2_mode
    repl = NamedSharding(mesh, P(None, None, None))
    repl2 = NamedSharding(mesh, P(None, None))
    k = mesh.shape[seq_axis]
    # local-synthesis wrappers memoized by their static per-level target
    # shape — built once per (shape) instead of on every trace of every
    # level (round-4 advisor), mirroring how synth_run is built once
    get_w_run = functools.lru_cache(maxsize=None)(
        lambda target: _build_local_synthesis(mesh, wav, seq_axis, 1, target,
                                              batch_axis)
    )

    @jax.jit
    def apply(coeffs):
        lead = coeffs[0].core.shape[:-2]
        b = math.prod(lead)
        flat3 = lambda t: None if t is None else t.reshape((b,) + t.shape[-2:])
        tcat = lambda ts: None if ts[0] is None else jnp.concatenate(ts, axis=0)
        a = TailedLeaf(flat3(coeffs[0].core), flat3(coeffs[0].tail))
        for det in coeffs[1:]:
            hor = TailedLeaf(flat3(det.horizontal.core), flat3(det.horizontal.tail))
            ver = TailedLeaf(flat3(det.vertical.core), flat3(det.vertical.tail))
            dia = TailedLeaf(flat3(det.diagonal.core), flat3(det.diagonal.tail))
            # trim a to the detail's (H-tail, W) footprint before inverting
            ht, wt = _tail_len(hor.tail, -2), hor.core.shape[-1]
            a = TailedLeaf(
                a.core[..., :wt],
                None if a.tail is None else a.tail[..., :ht, :wt],
            )
            # H axis first (sharded): both W-subband letters ride ONE
            # shard_map call (stacked along the batch axis), so each level
            # pays a single ring exchange — same batching trick as the
            # analysis direction
            ac = jnp.concatenate([a.core, ver.core], axis=0)   # w=a | w=d rows: a-part
            at = tcat([a.tail, ver.tail])
            dc = jnp.concatenate([hor.core, dia.core], axis=0)  # d-part
            dt = tcat([hor.tail, dia.tail])
            cc, tt = _axis_level_inv((ac, at), (dc, dt), -2, synth_run, wav, repl2)
            aa_c, ad_c = cc[:b], cc[b:]
            # W axis second (local): stack the two W-subbands and synthesize
            w_target = 2 * wt - L + 2
            core = get_w_run((w_target,))(jnp.stack([aa_c, ad_c], axis=-2))
            if tt is None:
                tail = None
            else:
                t_in = lax.with_sharding_constraint(
                    jnp.stack([tt[:b], tt[b:]], axis=-2),
                    NamedSharding(mesh, P(None, None, None, None)),
                )
                tail = lax.with_sharding_constraint(
                    _synthesis(t_in, wav, 1, (w_target,)), repl
                )
            a = TailedLeaf(core, tail)
        return TailedLeaf(
            a.core.reshape(lead + a.core.shape[1:]),
            None if a.tail is None
            else a.tail.reshape(lead + a.tail.shape[1:]),
        )

    def run(coeffs):
        from wam_tpu.parallel.halo import _check_batch_divisible

        coeffs = _normalize_tails(coeffs, -2)
        _check_coeff_leaves(coeffs, wav, -2, k, "sharded_wavedec2_mode",
                            "row count")
        _check_batch_divisible(math.prod(coeffs[0].core.shape[:-2]),
                               mesh, batch_axis)
        return apply(coeffs)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def sharded_waverec3_mode(mesh: Mesh, wavelet, seq_axis: str = "data",
                          batch_axis: str | None = None):
    """Inverse of `sharded_wavedec3_mode` (depth axis sharded); matches
    `transform.waverec3` exactly. ``batch_axis``: see
    `sharded_wavedec_mode`."""
    wav = _resolve(wavelet)
    L = wav.filt_len
    synth_run = _build_synth_run(mesh, wav, seq_axis, batch_axis)
    # tail constraints carry NO batch entry — see sharded_wavedec2_mode
    repl = NamedSharding(mesh, P(None, None, None, None))
    repl2 = NamedSharding(mesh, P(None, None))
    k = mesh.shape[seq_axis]
    # memoized like sharded_waverec2_mode's get_w_run (round-4 advisor)
    get_hw_run = functools.lru_cache(maxsize=None)(
        lambda target: _build_local_synthesis(mesh, wav, seq_axis, 2, target,
                                              batch_axis)
    )

    @jax.jit
    def apply(coeffs):
        lead = coeffs[0].core.shape[:-3]
        b = math.prod(lead)
        flat4 = lambda t: None if t is None else t.reshape((b,) + t.shape[-3:])
        tcat = lambda ts: None if ts[0] is None else jnp.concatenate(ts, axis=0)
        a = TailedLeaf(flat4(coeffs[0].core), flat4(coeffs[0].tail))
        for det in coeffs[1:]:
            det_f = {kk: TailedLeaf(flat4(v.core), flat4(v.tail)) for kk, v in det.items()}
            ref = det_f["ddd"]
            dt_, ht, wt = _tail_len(ref.tail, -3), ref.core.shape[-2], ref.core.shape[-1]
            a = TailedLeaf(
                a.core[..., :ht, :wt],
                None if a.tail is None else a.tail[..., :dt_, :ht, :wt],
            )
            # D axis first (sharded): all four (H, W)-subband letter pairs
            # ride ONE shard_map call (stacked along the batch axis) — a
            # single ring exchange per level instead of four
            order = ("aa", "ad", "da", "dd")
            a_pieces = [a if kk == "aa" else det_f["a" + kk] for kk in order]
            d_pieces = [det_f["d" + kk] for kk in order]
            ac = jnp.concatenate([pp.core for pp in a_pieces], axis=0)
            at = tcat([pp.tail for pp in a_pieces])
            dc = jnp.concatenate([pp.core for pp in d_pieces], axis=0)
            dtl = tcat([pp.tail for pp in d_pieces])
            cc, tt = _axis_level_inv((ac, at), (dc, dtl), -3, synth_run, wav, repl2)
            # H and W axes second (local): fused 4-channel 2D synthesis
            target = (2 * ht - L + 2, 2 * wt - L + 2)
            core = get_hw_run(target)(jnp.stack(
                [cc[i * b : (i + 1) * b] for i in range(4)], axis=-3))
            if tt is None:
                tail = None
            else:
                t_in = lax.with_sharding_constraint(
                    jnp.stack([tt[i * b : (i + 1) * b] for i in range(4)],
                              axis=-3),
                    NamedSharding(mesh, P(None, None, None, None, None)),
                )
                tail = lax.with_sharding_constraint(
                    _synthesis(t_in, wav, 2, target), repl
                )
            a = TailedLeaf(core, tail)

        return TailedLeaf(
            a.core.reshape(lead + a.core.shape[1:]),
            None if a.tail is None
            else a.tail.reshape(lead + a.tail.shape[1:]),
        )

    def run(coeffs):
        from wam_tpu.parallel.halo import _check_batch_divisible

        coeffs = _normalize_tails(coeffs, -3)
        _check_coeff_leaves(coeffs, wav, -3, k, "sharded_wavedec3_mode",
                            "depth")
        _check_batch_divisible(math.prod(coeffs[0].core.shape[:-3]),
                               mesh, batch_axis)
        return apply(coeffs)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run
