"""Sequence-sharded DWT for the engines' default boundary modes.

`halo.py` ships the periodized-mode ring-halo decomposition, where the ring
wrap IS the boundary condition and every coefficient array tiles evenly
across shards. The engines, however, default to pywt's expansive modes
(reflect for 2D, symmetric for 1D/3D — reference `lib/wam_2D.py:96`,
`lib/wam_1D.py:109`, `lib/wam_3D.py:194` via ptwt defaults), whose
per-level output length (n + L - 1)//2 exceeds n/2: the extra boundary
coefficients make the leaves indivisible across shards, which is why the
ring-halo path could not cover them (`shard_map` requires identical static
shapes per shard).

This module closes that gap with a **core + tail** decomposition of every
coefficient array. For one analysis level over a length-N signal
(N = C + T, C evenly sharded "core", T replicated "tail"), output j's
correlation window covers signal samples [2j-L+2, 2j+1], so:

- outputs j < C/2 ("core outputs") touch only the signal interior plus the
  LEFT boundary extension. Shard 0 builds that extension locally from its
  own head samples; every other shard needs only the usual (L-2)-sample
  ring halo from its predecessor. The core outputs therefore stay evenly
  sharded and cost one `lax.ppermute` per level — identical ICI traffic to
  the periodized path.
- outputs j >= C/2 ("tail outputs", (T + L - 1)//2 of them) have windows
  crossing the signal's right edge. They depend only on the last ~2L
  signal samples, are computed replicated at the jit level, and stay O(L)
  for any signal length: T_next = (T + L - 1)//2 converges to <= L - 2.

Every leaf is a `TailedLeaf(core, tail)` pair — core sharded over the
sequence axis, tail replicated; `gather_leaf`/`gather_coeffs` concatenate
them into the exact `wam_tpu.wavelets.transform.wavedec*` arrays (parity
pinned by tests/test_halo_modes.py). The `periodic`/`periodization` modes
are excluded: their boundary is the ring wrap itself, which is what
`halo.sharded_wavedec*_per` already implements non-expansively.

Constraints (all checked eagerly with precise messages): the sharded axis
length must be divisible by 2·shards at every level, and the per-shard
block must be at least the filter length L at every level so the halo is a
single hop and shard 0's local extension only consults its own samples.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wam_tpu.compat import axis_size, shard_map

from wam_tpu.wavelets.filters import Wavelet
from wam_tpu.wavelets.transform import (
    _PAD_MODE,
    _analysis,
    _pad_axes,
    _resolve,
    _subband_kernel,
    _synthesis,
    DETAIL3D_KEYS,
    Detail2D,
)

__all__ = [
    "TailedLeaf",
    "gather_leaf",
    "gather_coeffs",
    "sharded_wavedec_mode",
    "sharded_wavedec2_mode",
    "sharded_wavedec3_mode",
    "sharded_waverec_mode",
    "sharded_waverec2_mode",
    "sharded_waverec3_mode",
    "sharded_coeff_grads_mode",
]


class TailedLeaf(NamedTuple):
    """One coefficient array split as (evenly sharded core, replicated tail)."""

    core: jax.Array
    tail: jax.Array


def gather_leaf(leaf: TailedLeaf, axis: int = -1) -> jax.Array:
    """Concatenate core and tail into the full coefficient array.

    The empty-tail case returns the core directly: besides being a no-op,
    a concat with a zero-size operand trips an XLA SPMD-partitioner reshape
    verifier bug when the core is sharded (observed on the one-jit
    decompose→reconstruct→model gradient graph)."""
    if leaf.tail.shape[axis] == 0:
        return leaf.core
    return jnp.concatenate([leaf.core, leaf.tail], axis=axis)


def gather_coeffs(coeffs, ndim: int = 1):
    """Materialize a full `transform.wavedec{,2,3}`-shaped coefficient list
    from the TailedLeaf structure (concat along the sharded axis)."""
    axis = -ndim
    out = []
    for c in coeffs:
        if isinstance(c, TailedLeaf):
            out.append(gather_leaf(c, axis))
        elif isinstance(c, Detail2D):
            out.append(Detail2D(*(gather_leaf(f, axis) for f in c)))
        elif isinstance(c, dict):
            out.append({k: gather_leaf(v, axis) for k, v in c.items()})
        else:  # pragma: no cover - defensive
            raise TypeError(f"unexpected leaf type {type(c)!r}")
    return out


def _check_mode(mode: str):
    if mode in ("periodic", "periodization"):
        raise ValueError(
            f"mode {mode!r}: the wrap boundary IS the ring — use "
            "wam_tpu.parallel.sharded_wavedec{,2,3}_per, which is non-"
            "expansive and fully sharded"
        )
    if mode not in _PAD_MODE:
        raise ValueError(f"Unsupported mode {mode!r}; one of "
                         f"{sorted(set(_PAD_MODE) - {'periodic'})}")


def _check_divisibility(n: int, k: int, L: int, level: int, what: str):
    c = n
    for lev in range(1, level + 1):
        if c % (2 * k):
            raise ValueError(
                f"{what} length {n}: level-{lev} core length {c} is not "
                f"divisible by 2*shards={2 * k}"
            )
        m = c // k
        if m < L:
            raise ValueError(
                f"{what} length {n}: level-{lev} per-shard block {m} is "
                f"shorter than the filter (L={L}); use fewer shards or "
                f"levels"
            )
        c //= 2


def _corr2(x2: jax.Array, wav: Wavelet) -> jax.Array:
    """Valid strided correlation with the fused (lo, hi) analysis bank:
    (B, N) -> (B, 2, (N - L)//2 + 1). Same kernel/precision as
    `transform._analysis` so sharded and single-device numerics agree."""
    kernel = _subband_kernel(wav, 1, x2.dtype)
    out = lax.conv_general_dilated(
        x2[:, None, :],
        kernel,
        window_strides=(2,),
        padding=[(0, 0)],
        dimension_numbers=lax.conv_dimension_numbers(
            (1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")
        ),
        precision=lax.Precision.HIGHEST,
    )
    return out


def _core_local(x_local: jax.Array, wav: Wavelet, mode: str, seq_axis: str) -> jax.Array:
    """Per-shard core-output kernel: (B, m) -> (B, 2, m//2).

    Interior shards prepend the (L-2)-sample ring halo from their
    predecessor; shard 0 instead prepends the mode's left boundary
    extension, built from its own head via the same `_pad_axes` helper the
    single-device transform uses (global padded signal = pad L-1 then drop
    the first sample, so the live left extension is entries [1, L-1))."""
    L = wav.filt_len
    if L > 2:
        need = L - 2
        k = axis_size(seq_axis)
        perm = [(i, (i + 1) % k) for i in range(k)]
        halo = lax.ppermute(x_local[:, -need:], seq_axis, perm=perm)
        head = x_local[:, : min(x_local.shape[-1], 2 * L)]
        lext = _pad_axes(head, L - 1, (-1,), mode)[:, 1 : L - 1]
        first = lax.axis_index(seq_axis) == 0
        ext = jnp.concatenate([jnp.where(first, lext, halo), x_local], axis=-1)
    else:
        ext = x_local
    return _corr2(ext, wav)


def _tail_coeffs(core: jax.Array, tail: jax.Array, wav: Wavelet, mode: str, repl_sh=None) -> jax.Array:
    """Replicated tail outputs for one level: windows j >= C/2 cover the
    last <= 2L-3 signal samples plus the right boundary extension, all
    derivable from a ~2L-sample end segment. (B, C) x (B, T) ->
    (B, 2, (T + L - 1)//2)."""
    L = wav.filt_len
    C = core.shape[-1]
    T = tail.shape[-1]
    t_out = (T + L - 1) // 2
    if t_out == 0:
        return jnp.zeros((core.shape[0], 2, 0), core.dtype)
    take = min(C, 2 * L)
    seg = jnp.concatenate([lax.slice_in_dim(core, C - take, C, axis=-1), tail], axis=-1)
    if repl_sh is not None:
        seg = lax.with_sharding_constraint(seg, repl_sh)
    segp = jnp.pad(seg, [(0, 0), (0, L - 1)], mode=_PAD_MODE[mode])
    # first tail window (j = C/2) starts at signal coordinate C - L + 2,
    # i.e. offset take - L + 2 into the segment
    out = _corr2(segp[:, take - L + 2 :], wav)
    # anchor the tiny conv replicated AT THE OP: propagation left alone may
    # shard its ~L-long output over the mesh into zero-size partitions and
    # die after spmd-partitioning (db6-J>=3 and 3D-db2-J=3 regressions)
    if repl_sh is not None:
        out = lax.with_sharding_constraint(out, repl_sh)
    return out


def _build_core_run(mesh: Mesh, wav: Wavelet, mode: str, seq_axis: str,
                    batch_axis: str | None = None):
    return shard_map(
        partial(_core_local, wav=wav, mode=mode, seq_axis=seq_axis),
        mesh=mesh,
        in_specs=P(batch_axis, seq_axis),
        out_specs=P(batch_axis, None, seq_axis),
    )


def _build_local_analysis(mesh: Mesh, wav: Wavelet, mode: str, seq_axis: str, ndim: int):
    """Unsharded-axes analysis of the core, run INSIDE shard_map so the
    sharded axis never enters a jit-level reshape. `_analysis` flattens all
    leading dims into the conv batch; done at the jit level on a
    (B, sharded, ...) array that merges the sharded axis as a minor batch
    factor — unrepresentable for GSPMD, which would silently replicate the
    whole signal. Inside shard_map the op is local, so the sharded axis
    stays sharded by construction and no collective is emitted."""
    spec_in = P(*((None, seq_axis) + (None,) * ndim))
    spec_out = P(*((None, seq_axis) + (None,) * (ndim + 1)))
    return shard_map(
        lambda c: _analysis(c, wav, mode, ndim),
        mesh=mesh,
        in_specs=spec_in,
        out_specs=spec_out,
    )


def _level_1d(core, tail, core_run, wav, mode, repl_sh=None):
    """One analysis level along the LAST axis of flattened (B, C)/(B, T)
    arrays. Returns ((cA_core, cA_tail), (cD_core, cD_tail))."""
    out2 = core_run(core)
    t2 = _tail_coeffs(core, tail, wav, mode, repl_sh)
    return (out2[:, 0], t2[:, 0]), (out2[:, 1], t2[:, 1])


def sharded_wavedec_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "symmetric", seq_axis: str = "data",
    batch_axis: str | None = None
):
    """Multi-level 1D decomposition with pywt boundary modes, sequence-
    sharded over ``seq_axis`` on the LAST axis. Returns a function
    `x -> [cA_J, cD_J, ..., cD_1]` of `TailedLeaf` pairs; `gather_coeffs`
    reproduces `transform.wavedec(x, wavelet, level, mode)` exactly.
    ``batch_axis`` additionally shards the flattened LEADING axis over that
    mesh axis (cores AND the O(L) tails — the tails stay replicated along
    the sequence axis only); the flattened leading dims must divide it."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis, batch_axis)
    sh = NamedSharding(mesh, P(batch_axis, seq_axis))
    repl = NamedSharding(mesh, P(batch_axis, None))

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead, n = x.shape[:-1], x.shape[-1]
        core = lax.with_sharding_constraint(x.reshape((-1, n)), sh)
        tail = jnp.zeros((core.shape[0], 0), core.dtype)
        leaves = []
        for _ in range(level):
            (core, tail_a), (d_core, d_tail) = _level_1d(core, tail, core_run, wav, mode, repl)
            # keep the O(L) tails replicated — see sharded_waverec_mode
            leaves.append(TailedLeaf(d_core, lax.with_sharding_constraint(d_tail, repl)))
            tail = lax.with_sharding_constraint(tail_a, repl)
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return [
            TailedLeaf(c.reshape(lead + c.shape[1:]), t.reshape(lead + t.shape[1:]))
            for c, t in coeffs
        ]

    def run(x):
        from wam_tpu.parallel.halo import _check_batch_divisible

        _check_divisibility(x.shape[-1], k, wav.filt_len, level, "sequence axis")
        _check_batch_divisible(int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1,
                               mesh, batch_axis)
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def _flatten2(x):
    """(..., A, B) -> (prod, B) with the static leading shape returned."""
    lead = x.shape[:-1]
    return x.reshape((int(np.prod(lead)) if lead else 1, x.shape[-1])), lead


def _axis_level(core, tail, axis, core_run, wav, mode, repl_sh=None):
    """One analysis level along ``axis`` (negative index) of core/tail,
    threading the sharded-axis machinery. Returns pairs of
    ((a_core, a_tail), (d_core, d_tail)) with ``axis`` halved."""
    cm = jnp.moveaxis(core, axis, -1)
    tm = jnp.moveaxis(tail, axis, -1)
    cf, lead = _flatten2(cm)
    tf, _ = _flatten2(tm)
    (a_c, a_t), (d_c, d_t) = _level_1d(cf, tf, core_run, wav, mode, repl_sh)

    def unpack(o):
        return jnp.moveaxis(o.reshape(lead + (o.shape[-1],)), -1, axis)

    return (unpack(a_c), unpack(a_t)), (unpack(d_c), unpack(d_t))


def sharded_wavedec2_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "reflect", seq_axis: str = "data"
):
    """Multi-level 2D decomposition with pywt boundary modes for images
    whose ROW axis exceeds one core's memory: x (..., H, W) with H sharded
    over ``seq_axis``. Returns `x -> [cA_J, Detail2D_J, ..., Detail2D_1]`
    where every field is a `TailedLeaf` split along H; `gather_coeffs(out,
    ndim=2)` reproduces `transform.wavedec2` (the W axis is transformed
    locally — boundary extension along H commutes exactly with the per-row
    W transform, so separable == fused)."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis)
    w_run = _build_local_analysis(mesh, wav, mode, seq_axis, 1)
    sh = NamedSharding(mesh, P(None, seq_axis, None))
    repl2 = NamedSharding(mesh, P(None, None))

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-2]
        core = lax.with_sharding_constraint(x.reshape((-1,) + x.shape[-2:]), sh)
        tail = jnp.zeros((core.shape[0], 0, core.shape[-1]), core.dtype)
        leaves = []
        for _ in range(level):
            # W axis first, locally (elementwise over the sharded H axis)
            cw = w_run(core)                    # (B, Hc, 2, W')
            tw = _analysis(tail, wav, mode, 1)  # (B, Ht, 2, W')
            # H axis second, via the sharded core+tail machinery
            (a_c, a_t), (d_c, d_t) = _axis_level(cw, tw, -3, core_run, wav, mode, repl2)
            det = Detail2D(
                horizontal=TailedLeaf(d_c[..., 0, :], d_t[..., 0, :]),  # da
                vertical=TailedLeaf(a_c[..., 1, :], a_t[..., 1, :]),    # ad
                diagonal=TailedLeaf(d_c[..., 1, :], d_t[..., 1, :]),    # dd
            )
            leaves.append(det)
            core, tail = a_c[..., 0, :], a_t[..., 0, :]
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return jax.tree_util.tree_map(
            lambda a: a.reshape(lead + a.shape[1:]), coeffs
        )

    def run(x):
        _check_divisibility(x.shape[-2], k, wav.filt_len, level, "row axis")
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def sharded_wavedec3_mode(
    mesh: Mesh, wavelet, level: int, mode: str = "symmetric", seq_axis: str = "data"
):
    """Multi-level 3D decomposition with pywt boundary modes for volumes
    whose DEPTH axis exceeds one core's memory: x (..., D, H, W) with D
    sharded over ``seq_axis``. Returns `x -> [cA_J, {aad..ddd}_J, ...]`
    with `TailedLeaf` values split along D; `gather_coeffs(out, ndim=3)`
    reproduces `transform.wavedec3`."""
    wav = _resolve(wavelet)
    _check_mode(mode)
    k = mesh.shape[seq_axis]
    core_run = _build_core_run(mesh, wav, mode, seq_axis)
    hw_run = _build_local_analysis(mesh, wav, mode, seq_axis, 2)
    sh = NamedSharding(mesh, P(None, seq_axis, None, None))
    repl2 = NamedSharding(mesh, P(None, None))
    keys = ("aaa",) + DETAIL3D_KEYS

    @jax.jit
    def apply(x):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-3]
        core = lax.with_sharding_constraint(x.reshape((-1,) + x.shape[-3:]), sh)
        tail = jnp.zeros((core.shape[0], 0) + core.shape[-2:], core.dtype)
        leaves = []
        for _ in range(level):
            # H and W axes first, locally (fused 4-channel conv per slab)
            chw = hw_run(core)                   # (B, Dc, 4, H', W')
            thw = _analysis(tail, wav, mode, 2)  # (B, Dt, 4, H', W')
            # D axis second, via the sharded core+tail machinery
            (a_c, a_t), (d_c, d_t) = _axis_level(chw, thw, -4, core_run, wav, mode, repl2)
            det = {}
            for code in range(1, 8):
                d_bit, ch2d = code >> 2, code & 3
                src_c, src_t = (d_c, d_t) if d_bit else (a_c, a_t)
                det[keys[code]] = TailedLeaf(
                    src_c[..., ch2d, :, :], src_t[..., ch2d, :, :]
                )
            leaves.append(det)
            core, tail = a_c[..., 0, :, :], a_t[..., 0, :, :]
        leaves.append(TailedLeaf(core, tail))
        coeffs = leaves[::-1]
        return jax.tree_util.tree_map(
            lambda a: a.reshape(lead + a.shape[1:]), coeffs
        )

    def run(x):
        _check_divisibility(x.shape[-3], k, wav.filt_len, level, "depth axis")
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


# ---------------------------------------------------------------------------
# Inverse (synthesis) direction for the expansive modes — completes the
# DEFAULT-mode long-context loop: decompose → perturb → reconstruct → model.
# ---------------------------------------------------------------------------


def _synth_core_local(subs_local: jax.Array, halo_src: jax.Array, wav: Wavelet, seq_axis: str) -> jax.Array:
    """Per-shard synthesis kernel: (B, 2, m) local subbands -> (B, 2m) local
    reconstruction. Output sample t depends on coefficients
    j ∈ [⌈(t-1)/2⌉, ⌊(t+L-2)/2⌋], i.e. the halo travels from the SUCCESSOR
    (the reversed ring of the analysis direction); the last shard's
    successor-halo is the replicated tail's head, passed in as ``halo_src``."""
    L = wav.filt_len
    m = subs_local.shape[-1]
    h = (L - 1) // 2
    if h > 0:
        k = axis_size(seq_axis)
        perm = [(i, (i - 1) % k) for i in range(k)]
        ring = lax.ppermute(subs_local[..., :h], seq_axis, perm=perm)
        last = lax.axis_index(seq_axis) == k - 1
        ext = jnp.concatenate([subs_local, jnp.where(last, halo_src, ring)], axis=-1)
    else:
        ext = subs_local
    # trimming to 2m keeps exactly this shard's outputs (the [0, 2m) window
    # of the block reconstruction equals the global samples [2sm, 2(s+1)m))
    flat = ext.reshape((-1,) + ext.shape[-2:])
    out = _synthesis(flat, wav, 1, (2 * m,))
    return out.reshape(ext.shape[:-2] + (2 * m,))


def _level_inv_1d(coreA, tailA, coreD, tailD, synth_run, wav, repl_sh=None):
    """One synthesis level on TailedLeaf pieces (flattened (B, ·) arrays):
    returns (core_out (B, 2C) sharded, tail_out (B, 2T-L+2) replicated).
    Tail outputs t >= 2C depend ONLY on tail coefficients (jmin(2C) = C), so
    they synthesize replicated from the tails alone."""
    L = wav.filt_len
    T = tailA.shape[-1]
    h = (L - 1) // 2
    if T < h:
        raise ValueError(
            f"tail length {T} < {h} coefficients: the last shard's synthesis "
            "halo must come from the tail; feed leaves produced by "
            "sharded_wavedec_mode (its tails always satisfy this)"
        )
    subs = jnp.stack([coreA, coreD], axis=-2)          # (B, 2, C)
    tail_subs = jnp.stack([tailA, tailD], axis=-2)     # (B, 2, T)
    if repl_sh is not None:
        # bracket the tiny synthesis conv replicated on BOTH sides: the
        # partitioner derives a conv's sharding from its operands, so an
        # output-side constraint alone lands after the internal squeeze and
        # the conv still gets spatially partitioned into zero-size pieces
        # (the batch entry of repl_sh rides along — batch_axis support)
        tail_subs = lax.with_sharding_constraint(
            tail_subs, NamedSharding(repl_sh.mesh, P(repl_sh.spec[0], None, None))
        )
    core_out = synth_run(subs, tail_subs[..., :h])
    t_len = max(2 * T - L + 2, 0)
    if t_len == 0:  # haar chains (T=0) and the exact-h tails of deep chains
        return core_out, tailA[..., :0]
    tail_out = _synthesis(tail_subs, wav, 1, (t_len,))
    if repl_sh is not None:
        tail_out = lax.with_sharding_constraint(tail_out, repl_sh)
    return core_out, tail_out


def _check_coeff_leaves(coeffs, wav: Wavelet, axis: int, k: int,
                        producer: str, what: str):
    """Shared eager validation for the waverec run() wrappers — ONE
    container flattening (TailedLeaf | Detail2D | 3D dict) for both checks:

    - core divisibility by the shard count along ``axis``;
    - the `_level_inv_1d` trace-time invariant (round-4 advisor): the last
      shard's synthesis halo comes from the tail, so every leaf's tail must
      hold at least (L-1)//2 coefficients along ``axis`` (``producer``'s
      tails always do)."""
    h_min = (wav.filt_len - 1) // 2
    for c in coeffs:
        if isinstance(c, TailedLeaf):
            pieces = [c]
        elif isinstance(c, dict):
            pieces = list(c.values())
        else:
            pieces = list(c)
        for piece in pieces:
            n = piece.core.shape[axis]
            if n % k:
                raise ValueError(
                    f"coefficient core {what} {n} is not divisible by "
                    f"shards={k}: these leaves were not produced by "
                    f"{producer} on this mesh"
                )
            if piece.tail.shape[axis] < h_min:
                raise ValueError(
                    f"coefficient tail length {piece.tail.shape[axis]} < "
                    f"{h_min}: the last shard's synthesis halo must come "
                    f"from the tail; feed leaves produced by {producer}"
                )


def _build_synth_run(mesh: Mesh, wav: Wavelet, seq_axis: str,
                     batch_axis: str | None = None):
    return shard_map(
        partial(_synth_core_local, wav=wav, seq_axis=seq_axis),
        mesh=mesh,
        in_specs=(P(batch_axis, None, seq_axis), P(batch_axis, None, None)),
        out_specs=P(batch_axis, seq_axis),
    )


def sharded_waverec_mode(mesh: Mesh, wavelet, seq_axis: str = "data",
                         batch_axis: str | None = None):
    """Inverse of `sharded_wavedec_mode`: the TailedLeaf coefficient list
    back to the (..., N) signal as a `TailedLeaf` (core (..., 2C_top)
    sharded, tail replicated; `gather_leaf` yields the full signal).
    Matches `transform.waverec` exactly — including its trim-to-detail
    convention, which in core+tail form touches only the replicated tail.
    ``batch_axis``: see `sharded_wavedec_mode`."""
    wav = _resolve(wavelet)
    synth_run = _build_synth_run(mesh, wav, seq_axis, batch_axis)
    # pin every tail op replicated ALONG THE SEQ AXIS (batch may shard):
    # left to propagation, the partitioner may try to shard a length-~L
    # tail conv over the mesh, producing zero-size partitions and an
    # invalid reshape ("failed after spmd-partitioning")
    repl = NamedSharding(mesh, P(batch_axis, None))

    @jax.jit
    def apply(coeffs):
        lead = coeffs[0].core.shape[:-1]
        b = int(np.prod(lead)) if lead else 1
        flat = [
            TailedLeaf(
                c.core.reshape((b, c.core.shape[-1])),
                c.tail.reshape((b, c.tail.shape[-1])),
            )
            for c in coeffs
        ]
        a = flat[0]
        for d in flat[1:]:
            if a.tail.shape[-1] > d.tail.shape[-1]:
                a = TailedLeaf(a.core, a.tail[..., : d.tail.shape[-1]])
            core, tail = _level_inv_1d(a.core, a.tail, d.core, d.tail, synth_run, wav, repl)
            a = TailedLeaf(core, lax.with_sharding_constraint(tail, repl))
        return TailedLeaf(
            a.core.reshape(lead + a.core.shape[1:]),
            a.tail.reshape(lead + a.tail.shape[1:]),
        )

    k = mesh.shape[seq_axis]

    def run(coeffs):
        from wam_tpu.parallel.halo import _check_batch_divisible

        _check_coeff_leaves(coeffs, wav, -1, k, "sharded_wavedec_mode",
                            "length")
        lead = coeffs[0].core.shape[:-1]
        _check_batch_divisible(int(np.prod(lead)) if lead else 1,
                               mesh, batch_axis)
        return apply(coeffs)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def sharded_coeff_grads_mode(
    mesh: Mesh, wavelet, level: int, model_fn, mode: str = "symmetric",
    seq_axis: str = "data", ndim: int = 1
):
    """End-to-end long-context WAM gradient core in the engines' DEFAULT
    boundary modes (the periodized variant is
    `halo.sharded_coeff_grads_per`): sequence-sharded decompose →
    reconstruct → model → per-coefficient gradients, one jit over the mesh.
    ``ndim`` selects the modality (1 = waveform, 2 = image rows, 3 = volume
    depth). `model_fn` maps the reconstructed signal to (B, classes) logits
    (sequence-partitionable); gradients come back in the TailedLeaf
    structure of the coefficients. The reconstruction handed to the model
    is evenly sharded: the top-level tail is empty by construction."""
    wav = _resolve(wavelet)
    if ndim not in (1, 2, 3):
        raise ValueError(f"ndim must be 1, 2, or 3; got {ndim!r}")
    dec = {
        1: sharded_wavedec_mode,
        2: sharded_wavedec2_mode,
        3: sharded_wavedec3_mode,
    }[ndim](mesh, wav, level, mode, seq_axis)
    rec = {
        1: sharded_waverec_mode,
        2: sharded_waverec2_mode,
        3: sharded_waverec3_mode,
    }[ndim](mesh, wav, seq_axis)

    def _objective(cs, y):
        out = model_fn(gather_leaf(rec(cs), axis=-ndim))
        if y is None:
            return out.mean()
        return jnp.take_along_axis(out, y[:, None], axis=1).sum()

    # Two dispatches (decompose, then grads), not one: fusing them into a
    # single jit trips an XLA SPMD-partitioner verifier bug ("reshape
    # element count mismatch, failed after spmd-partitioning") on the
    # zero-size tail buffers the chain carries; each half compiles and
    # partitions cleanly on its own, and the split costs one extra host
    # round trip per step on workloads dominated by device compute.
    grads_labeled = jax.jit(lambda cs, y: jax.grad(_objective)(cs, y))
    grads_rep = jax.jit(lambda cs: jax.grad(_objective)(cs, None))

    def step(x, y=None):
        coeffs = dec(x)
        return grads_labeled(coeffs, y) if y is not None else grads_rep(coeffs)

    step._dec = dec  # jitted halves, exposed for HLO audits (tests)
    step._grads = grads_labeled
    return step


def _build_local_synthesis(mesh: Mesh, wav: Wavelet, seq_axis: str, ndim: int, out_shape):
    """Unsharded-axes synthesis of the core, run INSIDE shard_map for the
    same reason as `_build_local_analysis`: `_synthesis` flattens leading
    dims (including the sharded axis) into the conv batch, which at the jit
    level merges the sharded axis as a minor factor — unrepresentable for
    GSPMD, which would replicate. ``out_shape`` is the trimmed per-axis
    target (static per level)."""
    spec_in = P(*((None, seq_axis) + (None,) * (ndim + 1)))
    spec_out = P(*((None, seq_axis) + (None,) * ndim))
    return shard_map(
        lambda s: _synthesis(s, wav, ndim, out_shape),
        mesh=mesh,
        in_specs=spec_in,
        out_specs=spec_out,
    )


def _axis_level_inv(a_pair, d_pair, axis, synth_run, wav, repl_sh=None):
    """One synthesis level along ``axis`` (negative index): the inverse of
    `_axis_level`. ``a_pair``/``d_pair`` are (core, tail) along that axis;
    returns (core 2C, tail 2T-L+2) with ``axis`` doubled."""
    (a_c, a_t), (d_c, d_t) = a_pair, d_pair
    cm_a, tm_a = jnp.moveaxis(a_c, axis, -1), jnp.moveaxis(a_t, axis, -1)
    cm_d, tm_d = jnp.moveaxis(d_c, axis, -1), jnp.moveaxis(d_t, axis, -1)
    cf_a, lead = _flatten2(cm_a)
    tf_a, _ = _flatten2(tm_a)
    cf_d, _ = _flatten2(cm_d)
    tf_d, _ = _flatten2(tm_d)
    core, tail = _level_inv_1d(cf_a, tf_a, cf_d, tf_d, synth_run, wav, repl_sh)

    def unpack(o):
        return jnp.moveaxis(o.reshape(lead + (o.shape[-1],)), -1, axis)

    return unpack(core), unpack(tail)


def sharded_waverec2_mode(mesh: Mesh, wavelet, seq_axis: str = "data"):
    """Inverse of `sharded_wavedec2_mode` (row axis sharded): TailedLeaf
    coefficient structure back to the (..., H, W) image as a `TailedLeaf`
    split along H (top-level tail empty — see `sharded_waverec_mode`).
    Matches `transform.waverec2` exactly, including its trim-to-detail
    convention on both axes."""
    wav = _resolve(wavelet)
    L = wav.filt_len
    synth_run = _build_synth_run(mesh, wav, seq_axis)
    repl = NamedSharding(mesh, P(None, None, None))
    repl2 = NamedSharding(mesh, P(None, None))
    k = mesh.shape[seq_axis]
    # local-synthesis wrappers memoized by their static per-level target
    # shape — built once per (shape) instead of on every trace of every
    # level (round-4 advisor), mirroring how synth_run is built once
    get_w_run = functools.lru_cache(maxsize=None)(
        lambda target: _build_local_synthesis(mesh, wav, seq_axis, 1, target)
    )

    @jax.jit
    def apply(coeffs):
        lead = coeffs[0].core.shape[:-2]
        b = int(np.prod(lead)) if lead else 1
        flat3 = lambda t: t.reshape((b,) + t.shape[-2:])
        a = TailedLeaf(flat3(coeffs[0].core), flat3(coeffs[0].tail))
        for det in coeffs[1:]:
            hor = TailedLeaf(flat3(det.horizontal.core), flat3(det.horizontal.tail))
            ver = TailedLeaf(flat3(det.vertical.core), flat3(det.vertical.tail))
            dia = TailedLeaf(flat3(det.diagonal.core), flat3(det.diagonal.tail))
            # trim a to the detail's (H-tail, W) footprint before inverting
            ht, wt = hor.tail.shape[-2], hor.core.shape[-1]
            a = TailedLeaf(a.core[..., :wt], a.tail[..., :ht, :wt])
            # H axis first (sharded): both W-subband letters ride ONE
            # shard_map call (stacked along the batch axis), so each level
            # pays a single ring exchange — same batching trick as the
            # analysis direction
            ac = jnp.concatenate([a.core, ver.core], axis=0)   # w=a | w=d rows: a-part
            at = jnp.concatenate([a.tail, ver.tail], axis=0)
            dc = jnp.concatenate([hor.core, dia.core], axis=0)  # d-part
            dt = jnp.concatenate([hor.tail, dia.tail], axis=0)
            cc, tt = _axis_level_inv((ac, at), (dc, dt), -2, synth_run, wav, repl2)
            aa_c, ad_c = cc[:b], cc[b:]
            aa_t, ad_t = tt[:b], tt[b:]
            # W axis second (local): stack the two W-subbands and synthesize
            w_target = 2 * wt - L + 2
            core = get_w_run((w_target,))(jnp.stack([aa_c, ad_c], axis=-2))
            t_in = lax.with_sharding_constraint(
                jnp.stack([aa_t, ad_t], axis=-2),
                NamedSharding(mesh, P(None, None, None, None)),
            )
            tail = lax.with_sharding_constraint(
                _synthesis(t_in, wav, 1, (w_target,)), repl
            )
            a = TailedLeaf(core, tail)
        return TailedLeaf(
            a.core.reshape(lead + a.core.shape[1:]),
            a.tail.reshape(lead + a.tail.shape[1:]),
        )

    def run(coeffs):
        _check_coeff_leaves(coeffs, wav, -2, k, "sharded_wavedec2_mode",
                            "row count")
        return apply(coeffs)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run


def sharded_waverec3_mode(mesh: Mesh, wavelet, seq_axis: str = "data"):
    """Inverse of `sharded_wavedec3_mode` (depth axis sharded); matches
    `transform.waverec3` exactly."""
    wav = _resolve(wavelet)
    L = wav.filt_len
    synth_run = _build_synth_run(mesh, wav, seq_axis)
    repl = NamedSharding(mesh, P(None, None, None, None))
    repl2 = NamedSharding(mesh, P(None, None))
    k = mesh.shape[seq_axis]
    # memoized like sharded_waverec2_mode's get_w_run (round-4 advisor)
    get_hw_run = functools.lru_cache(maxsize=None)(
        lambda target: _build_local_synthesis(mesh, wav, seq_axis, 2, target)
    )

    @jax.jit
    def apply(coeffs):
        lead = coeffs[0].core.shape[:-3]
        b = int(np.prod(lead)) if lead else 1
        flat4 = lambda t: t.reshape((b,) + t.shape[-3:])
        a = TailedLeaf(flat4(coeffs[0].core), flat4(coeffs[0].tail))
        for det in coeffs[1:]:
            det_f = {kk: TailedLeaf(flat4(v.core), flat4(v.tail)) for kk, v in det.items()}
            ref = det_f["ddd"]
            dt, ht, wt = ref.tail.shape[-3], ref.core.shape[-2], ref.core.shape[-1]
            a = TailedLeaf(a.core[..., :ht, :wt], a.tail[..., :dt, :ht, :wt])
            # D axis first (sharded): all four (H, W)-subband letter pairs
            # ride ONE shard_map call (stacked along the batch axis) — a
            # single ring exchange per level instead of four
            order = ("aa", "ad", "da", "dd")
            a_pieces = [a if kk == "aa" else det_f["a" + kk] for kk in order]
            d_pieces = [det_f["d" + kk] for kk in order]
            ac = jnp.concatenate([pp.core for pp in a_pieces], axis=0)
            at = jnp.concatenate([pp.tail for pp in a_pieces], axis=0)
            dc = jnp.concatenate([pp.core for pp in d_pieces], axis=0)
            dtl = jnp.concatenate([pp.tail for pp in d_pieces], axis=0)
            cc, tt = _axis_level_inv((ac, at), (dc, dtl), -3, synth_run, wav, repl2)
            hw = {kk: (cc[i * b : (i + 1) * b], tt[i * b : (i + 1) * b])
                  for i, kk in enumerate(order)}
            # H and W axes second (local): fused 4-channel 2D synthesis
            target = (2 * ht - L + 2, 2 * wt - L + 2)
            core = get_hw_run(target)(jnp.stack([hw[kk][0] for kk in order], axis=-3))
            t_in = lax.with_sharding_constraint(
                jnp.stack([hw[kk][1] for kk in order], axis=-3),
                NamedSharding(mesh, P(None, None, None, None, None)),
            )
            tail = lax.with_sharding_constraint(
                _synthesis(t_in, wav, 2, target), repl
            )
            a = TailedLeaf(core, tail)

        return TailedLeaf(
            a.core.reshape(lead + a.core.shape[1:]),
            a.tail.reshape(lead + a.tail.shape[1:]),
        )

    def run(coeffs):
        _check_coeff_leaves(coeffs, wav, -3, k, "sharded_wavedec3_mode",
                            "depth")
        return apply(coeffs)

    run._apply = apply  # jitted body, exposed for HLO audits (tests)
    return run
