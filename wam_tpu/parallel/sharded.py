"""Sharded attribution pipelines: SmoothGrad / IG over a (data, sample) mesh.

The reference's SmoothGrad is a sequential 25-iteration host loop
(`lib/wam_2D.py:390-406`); here the noise-sample axis and the batch axis are
both first-class mesh axes. The full estimator is ONE jit graph: noise
generation, 2^d-subband DWT, model fwd+bwd, mosaic packing, and the sample
mean (an ICI psum inserted by XLA from the sharding constraints).

Layout: noisy inputs (n_samples, B, C, H, W) sharded P('sample', 'data');
outputs (B, S, S) sharded P('data'). The mean over the sample axis is the
only cross-device reduction — it rides ICI, never the host.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wam_tpu.compat import shard_map

from wam_tpu.core.estimators import noise_sigma, trapezoid

__all__ = ["sharded_smoothgrad", "sharded_smoothgrad_spmd", "sharded_integrated_path"]


def _constraint(mesh: Mesh, *axes):
    return NamedSharding(mesh, P(*axes))


def sharded_smoothgrad(
    step_fn: Callable[[jax.Array], Any],
    mesh: Mesh,
    *,
    n_samples: int,
    stdev_spread: float,
    data_axis: str = "data",
    sample_axis: str = "sample",
) -> Callable[[jax.Array, jax.Array], Any]:
    """Build a jitted `(x, key) -> mean pytree` SmoothGrad runner.

    ``step_fn`` maps one perturbed batch (B, ...) to an output pytree whose
    leaves have a leading batch axis (e.g. a partially-applied WAM step with
    the labels closed over). Requires n_samples % sample_axis_size == 0 and
    B % data_axis_size == 0.
    """
    n_sample_shards = mesh.shape[sample_axis]
    if n_samples % n_sample_shards:
        raise ValueError(f"n_samples={n_samples} not divisible by {sample_axis}={n_sample_shards}")

    def run(x, key):
        sigma = noise_sigma(x, stdev_spread)
        sigma = sigma.reshape(sigma.shape + (1,) * (x.ndim - 1))
        noise = jax.random.normal(key, (n_samples,) + x.shape, dtype=x.dtype) * sigma
        noisy = x[None] + noise
        noisy = jax.lax.with_sharding_constraint(
            noisy, _constraint(mesh, sample_axis, data_axis)
        )
        outs = jax.vmap(step_fn)(noisy)
        # anchor the per-sample outputs too (input + output + post-mean all
        # constrained). KNOWN LIMIT (round-4 HLO audit,
        # tests/test_parallel.py::test_sharded_smoothgrad_hlo_audit): the
        # noise buffer and outputs stay fully sharded and the sample mean is
        # a psum, but vmap's conv batching rule merges the (sample, data)
        # axes into one model-batch dim, whose product sharding XLA cannot
        # represent — it all-gathers the DATA axis at the model input, so
        # model compute is replicated across data shards. Exact
        # reference semantics (batch-global mosaic normalization) are
        # preserved; a shard_map redesign with an explicit-label step
        # contract is the planned fix.
        outs = jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(
                a, _constraint(mesh, sample_axis, data_axis)
            ),
            outs,
        )
        mean = jax.tree_util.tree_map(lambda a: a.mean(axis=0), outs)
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, _constraint(mesh, data_axis)), mean
        )

    return jax.jit(run)


def sharded_smoothgrad_spmd(
    step_fn: Callable[[jax.Array, jax.Array, float], Any],
    mesh: Mesh,
    *,
    n_samples: int,
    stdev_spread: float,
    data_axis: str = "data",
    sample_axis: str = "sample",
) -> Callable[[jax.Array, jax.Array, jax.Array], Any]:
    """`sharded_smoothgrad` with a GUARANTEED data-parallel graph.

    The propagation-based `sharded_smoothgrad` preserves exact
    single-device semantics but lets vmap's conv batching rule merge the
    (sample, data) axes, which XLA resolves by ALL-GATHERING the data axis
    at the model input — model compute replicated across data shards
    (round-4 HLO audit). This variant runs the step under `shard_map`, so
    each device computes ONLY its (n_samples/sample_shards, B/data_shards)
    block and the sole collective is the sample-mean `psum` over ICI — the
    scaling-correct multi-chip estimator (SURVEY.md §2.10 / scaling-book
    recipe: pick the mesh, keep compute local, reduce once).

    Contract changes vs `sharded_smoothgrad`:
    - ``step_fn(noisy_local, y_local, grad_scale)`` receives the LOCAL
      batch rows, their labels (passed to the runner, not closed over),
      and the loss-mean rescale factor described below;
    - the runner signature is ``run(x, y, key)``;
    - any batch-global reduction inside ``step_fn`` (e.g. the mosaic's
      normalize-by-max) is computed PER DATA SHARD. With
      ``mosaic2d(..., normalize=False)`` (or any shard-local step) results
      are bit-identical to the single-device materialized `smoothgrad` —
      asserted by tests/test_parallel.py; with normalization the maps
      differ by the per-shard normalizer exactly as documented.

    Loss-mean rescale: the engine's diag-logit loss takes the MEAN over the
    batch it sees, so a shard computing local_b rows produces gradients
    B/local_b× larger than the full-batch run. The runner passes
    ``grad_scale = local_b/B`` (= 1/data_shards for a divisible batch) as
    the step's third argument; the step must multiply its COEFFICIENT
    GRADIENTS by it before any (scale-invariant) normalization:

        def step(noisy_local, y_local, grad_scale):
            _, grads = engine.attribute(noisy_local, y_local)
            grads = jax.tree_util.tree_map(lambda g: g * grad_scale, grads)
            return mosaic2d(grads, normalize, channel_axis)

    With that, normalize=False is bit-identical to the single-device
    materialized `smoothgrad` (asserted in tests/test_parallel.py) and
    normalize=True differs only by the documented per-shard normalizer.

    Batch divisibility: B need NOT divide the data axis. A non-divisible
    batch is padded up to the next multiple by cyclically repeating the
    already-noised real rows, run sharded, and the pad rows sliced off the
    result — the model is batch-diagonal (inference-mode BN), so the real
    rows' gradients are untouched and normalize=False stays bit-identical.
    With normalize=True the per-shard normalizer of a padding shard sees
    the duplicated rows (same documented per-shard semantics).

    Requires n_samples % sample_shards == 0.
    """
    n_sample_shards = mesh.shape[sample_axis]
    if n_samples % n_sample_shards:
        raise ValueError(
            f"n_samples={n_samples} not divisible by {sample_axis}={n_sample_shards}"
        )

    def run(x, y, key):
        n_data_shards = mesh.shape[data_axis]
        batch = x.shape[0]
        sigma = noise_sigma(x, stdev_spread)
        sigma = sigma.reshape(sigma.shape + (1,) * (x.ndim - 1))
        # same draws as the materialized single-device path (same key →
        # same (n_samples, B, ...) normal tensor), then sharded as input
        noise = jax.random.normal(key, (n_samples,) + x.shape, dtype=x.dtype) * sigma
        noisy = x[None] + noise
        y = jnp.asarray(y)

        pad = (-batch) % n_data_shards
        if pad:
            # cyclic repetition of the NOISED real rows: every shard sees
            # genuine inputs (finite normalizers), duplicates are discarded
            # below, and real rows are untouched (batch-diagonal model)
            idx = jnp.arange(batch + pad) % batch
            noisy = noisy[:, idx]
            y = y[idx]

        local_b = (batch + pad) // n_data_shards
        grad_scale = local_b / batch

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(sample_axis, data_axis), P(data_axis)),
            out_specs=P(data_axis),
        )
        def local(noisy_l, y_l):
            outs = jax.vmap(lambda nb: step_fn(nb, y_l, grad_scale))(noisy_l)
            sums = jax.tree_util.tree_map(lambda a: a.sum(axis=0), outs)
            return jax.tree_util.tree_map(
                lambda a: lax.psum(a, sample_axis) / n_samples, sums
            )

        out = local(noisy, y)
        if pad:
            out = jax.tree_util.tree_map(lambda a: a[:batch], out)
        return out

    return jax.jit(run)


def sharded_integrated_path(
    grad_fn: Callable[[Any], Any],
    decompose_fn: Callable[[jax.Array], Any],
    mesh: Mesh,
    *,
    n_steps: int,
    data_axis: str = "data",
    sample_axis: str = "sample",
    dx: float = 1.0,
) -> Callable[[jax.Array], Any]:
    """Build a jitted `(x,) -> integral pytree` IG runner with the α-path
    vmapped and sharded over the sample axis."""

    def run(x):
        x = jax.lax.with_sharding_constraint(x, _constraint(mesh, data_axis))
        coeffs = decompose_fn(x)
        alphas = jnp.linspace(0.0, 1.0, n_steps, dtype=x.dtype)

        def one(alpha):
            scaled = jax.tree_util.tree_map(lambda c: c * alpha, coeffs)
            return grad_fn(scaled)

        path = jax.vmap(one)(alphas)
        path = jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(sample_axis, data_axis))
            ),
            path,
        )
        return jax.tree_util.tree_map(lambda a: trapezoid(a, dx=dx), path)

    return jax.jit(run)
