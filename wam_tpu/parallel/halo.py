"""Sequence-sharded DWT with ring halo exchange (long-context support).

The 1D DWT is a filter-width-local stencil, so a sequence sharded across
devices only needs L−2 boundary samples from its ring neighbour per level —
exchanged with `lax.ppermute` over ICI inside `shard_map` (SURVEY.md §5.7:
"the ring-attention-shaped pattern, but for convolution"). With the
periodized transform the ring wrap IS the correct boundary condition, so the
sharded result is bit-compatible with the single-device `dwt_per`.

This is the scaling story for sequences far beyond one core's memory
(the reference processes its longest input, a 220k-sample waveform, whole —
`src/dataloader.py:83-97`; this path removes that ceiling).

This module is periodized-only by design: with the `*_per` transforms the
ring wrap IS the boundary condition and every leaf tiles evenly. The
engines' default expansive modes (reflect 2D, symmetric 1D/3D) produce
(n+L−1)//2 coefficients per level, which does not tile — those are covered
by `halo_modes.sharded_wavedec{,2,3}_mode`, which keeps the evenly-sharded
core on this same one-ppermute-per-level schedule and carries the O(L)
boundary coefficients in a small replicated tail.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wam_tpu.compat import axis_size, shard_map
from wam_tpu.wavelets.filters import build_wavelet
from wam_tpu.wavelets.periodized import dwt_per, separable_dwt2, separable_dwt3

__all__ = [
    "sharded_dwt_per",
    "sharded_wavedec_per",
    "sharded_wavedec2_per",
    "sharded_wavedec3_per",
    "sharded_waverec_per",
    "sharded_waverec2_per",
    "sharded_waverec3_per",
    "sharded_coeff_grads_per",
]


def _local_dwt_with_halo(x_local: jax.Array, wavelet: str, axis_name: str):
    """Per-shard kernel: fetch L−2 left-halo samples from the ring
    predecessor chain, then run the strided correlation locally. When the
    halo exceeds one shard's length (long filters at deep levels), blocks
    from further predecessors are pulled with additional ppermute hops —
    hop count is static, derived from shapes."""
    wav = build_wavelet(wavelet)
    L = wav.filt_len
    n_shards = axis_size(axis_name)
    if L > 2:
        need = L - 2
        local_len = x_local.shape[-1]
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        if need <= local_len:
            # common case: one hop carrying only the L−2-sample tail
            halo = lax.ppermute(x_local[..., -need:], axis_name, perm=perm)
        else:
            # halo spans several shards (long filter, deep level): pull full
            # predecessor blocks hop by hop — every block but the farthest is
            # fully consumed, so full-block traffic is necessary here
            hops = -(-need // local_len)  # ceil
            blocks = []
            prev = x_local
            for _ in range(hops):
                # after k hops `prev` holds shard i-k's block
                prev = lax.ppermute(prev, axis_name, perm=perm)
                blocks.insert(0, prev)
            halo = jnp.concatenate(blocks, axis=-1)[..., -need:]
        ext = jnp.concatenate([halo, x_local], axis=-1)
    else:
        ext = x_local
    import numpy as np

    kernel = jnp.asarray(
        np.stack([np.asarray(wav.dec_lo[::-1]), np.asarray(wav.dec_hi[::-1])])[:, None],
        dtype=x_local.dtype,
    )
    batch_shape = ext.shape[:-1]
    xb = ext.reshape(-1, 1, ext.shape[-1])
    out = lax.conv_general_dilated(
        xb, kernel, window_strides=(2,), padding=[(0, 0)],
        dimension_numbers=lax.conv_dimension_numbers((1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")),
    )
    out = out.reshape(batch_shape + (2, x_local.shape[-1] // 2))
    return out[..., 0, :], out[..., 1, :]


def sharded_dwt_per(mesh: Mesh, wavelet: str, seq_axis: str = "data"):
    """Build a jitted `(x,) -> (cA, cD)` single-level sharded DWT: x (..., N)
    sharded over ``seq_axis`` on its last dimension; outputs keep the same
    sharding. Matches `dwt_per` exactly."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(None, seq_axis),
        out_specs=(P(None, seq_axis), P(None, seq_axis)),
    )
    def run(x_local):
        return _local_dwt_with_halo(x_local, wavelet, seq_axis)

    return run


def sharded_wavedec_per(mesh: Mesh, wavelet: str, level: int, seq_axis: str = "data",
                        batch_axis: str | None = None):
    """Multi-level sharded decomposition: [cA_J, cD_J, ..., cD_1], each leaf
    sharded over ``seq_axis``. Requires the local shard length to stay even
    at every level (N divisible by shards·2^level).

    ``batch_axis`` additionally shards the LEADING (batch) axis over that
    mesh axis — without it, devices on non-``seq_axis`` mesh axes replicate
    the whole computation (round-5: the sample/batch-parallel seq
    estimator). With ``batch_axis`` the leading axis must divide that mesh
    axis (checked eagerly)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(batch_axis, seq_axis),
        out_specs=P(batch_axis, seq_axis),
    )
    def run_levels(x_local):
        coeffs = []
        a = x_local
        for _ in range(level):
            a, d = _local_dwt_with_halo(a, wavelet, seq_axis)
            coeffs.append(d)
        coeffs.append(a)
        return coeffs[::-1]

    @jax.jit
    def apply(x):
        # framework-wide bf16-in / f32-accumulate (`wavelets.transform`)
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        return run_levels(x)

    def check(x):
        _check_batch_divisible(x.shape[0], mesh, batch_axis)

    def run(x):
        check(x)
        return apply(x)

    run._apply = apply  # jitted body, exposed for HLO/sharding audits
    run._check = check  # eager guards, callable separately by fused callers
    return run


def _check_batch_divisible(n: int, mesh: Mesh, batch_axis: str | None):
    """Eager guard for the batch_axis contract: the (flattened) leading
    axis must divide the batch mesh axis — otherwise shard_map fails at
    trace time with an opaque divisibility error (round-5 review)."""
    if batch_axis is not None and n % mesh.shape[batch_axis]:
        raise ValueError(
            f"flattened leading axis {n} is not divisible by "
            f"{batch_axis}={mesh.shape[batch_axis]}: batch_axis sharding "
            "needs the (product of) leading dims divisible by that mesh "
            "axis; reshape, pad, or drop batch_axis"
        )


def _sharded_wavedec_nd(mesh: Mesh, level: int, seq_axis: str, ndim: int, level_fn,
                        batch_axis: str | None = None):
    """Shared multi-level builder for the 2D/3D sharded decompositions:
    shard_map over the sharded spatial axis (first of the trailing ``ndim``),
    loop ``level_fn`` per level, flatten/restore arbitrary leading dims
    (``batch_axis`` shards the flattened leading axis — see
    `sharded_wavedec_per`)."""
    spec = P(*((batch_axis, seq_axis) + (None,) * (ndim - 1)))

    @partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
    def run(x_local):
        coeffs = []
        a = x_local
        for _ in range(level):
            a, det = level_fn(a)
            coeffs.append(det)
        coeffs.append(a)
        return coeffs[::-1]

    @jax.jit
    def apply(x):
        # framework-wide bf16-in / f32-accumulate (`wavelets.transform`)
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        lead = x.shape[:-ndim]
        out = run(x.reshape((-1,) + x.shape[-ndim:]))
        return jax.tree_util.tree_map(lambda a: a.reshape(lead + a.shape[1:]), out)

    def check(x):
        import math as _math

        _check_batch_divisible(_math.prod(x.shape[:-ndim]) if x.ndim > ndim
                               else 1, mesh, batch_axis)

    def checked(x):
        check(x)
        return apply(x)

    checked._apply = apply  # jitted body, exposed for HLO/sharding audits
    checked._check = check  # eager guards, callable separately by fused callers
    return checked


def _level_fn_2d(wavelet: str, seq_axis: str):
    """One 2D analysis level with the row axis halo-sharded. Shared by the
    forward (`sharded_wavedec2_per`) and the inverse (`sharded_waverec2_per`
    transposes exactly this function) so the two cannot drift."""

    def level_fn(x_local):
        return separable_dwt2(
            x_local,
            dwt1_w=lambda t: dwt_per(t, wavelet),
            dwt1_h=lambda t: _local_dwt_with_halo(t, wavelet, seq_axis),
        )

    return level_fn


def _level_fn_3d(wavelet: str, seq_axis: str):
    """One 3D analysis level with the depth axis halo-sharded (see
    `_level_fn_2d` for the forward/inverse sharing contract)."""

    def level_fn(x_local):
        one = lambda t: dwt_per(t, wavelet)
        return separable_dwt3(
            x_local, one, one, lambda t: _local_dwt_with_halo(t, wavelet, seq_axis)
        )

    return level_fn


def sharded_wavedec2_per(mesh: Mesh, wavelet: str, level: int, seq_axis: str = "data",
                         batch_axis: str | None = None):
    """Multi-level 2D sharded decomposition for images/feature maps whose
    row axis exceeds one core's memory: x (..., H, W) — any leading dims —
    with H sharded over ``seq_axis``; every output leaf keeps that sharding.
    Bit-compatible with `wam_tpu.wavelets.periodized.wavedec2_per`. Requires
    H divisible by shards·2^level and W divisible by 2^level."""
    return _sharded_wavedec_nd(mesh, level, seq_axis, 2,
                               _level_fn_2d(wavelet, seq_axis), batch_axis)


def sharded_wavedec3_per(mesh: Mesh, wavelet: str, level: int, seq_axis: str = "data",
                         batch_axis: str | None = None):
    """Multi-level 3D sharded decomposition for volumes whose depth axis
    exceeds one core's memory: x (..., D, H, W) — any leading dims — with D
    sharded over ``seq_axis``. Bit-compatible with
    `wam_tpu.wavelets.periodized.wavedec3_per`. Requires D divisible by
    shards·2^level and H, W divisible by 2^level."""
    return _sharded_wavedec_nd(mesh, level, seq_axis, 3,
                               _level_fn_3d(wavelet, seq_axis), batch_axis)


# ---------------------------------------------------------------------------
# Inverse (synthesis) direction — completes the long-context engine loop:
# decompose → perturb coefficients → reconstruct → model, all sharded.
# ---------------------------------------------------------------------------


def _sharded_waverec_nd(mesh: Mesh, seq_axis: str, ndim: int, level_fn,
                        batch_axis: str | None = None):
    """Shared multi-level builder for the sharded reconstructions.

    The single-device `idwt*_per` invert via `jax.linear_transpose` of the
    forward (the transform is orthogonal, so adjoint = inverse). The same
    identity holds per shard: transposing the forward level kernel INSIDE
    `shard_map` flips its `lax.ppermute` (the transpose of a permutation is
    the inverse permutation), so the synthesis halo travels the opposite
    ring direction automatically and the result is the exact inverse of the
    sharded decomposition — one collective per level, no gathers.

    `check_vma=False`: the transposed kernel's cotangents are device-varying
    (they carry the mesh-axis variance annotation), which the
    `linear_transpose` expectation — traced from a plain ShapeDtypeStruct —
    cannot express; the variance check is disabled and correctness is
    pinned by the round-trip/parity tests instead."""
    spec = P(*((batch_axis, seq_axis) + (None,) * (ndim - 1)))

    @partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False)
    def run(coeffs):
        a = coeffs[0]
        for det in coeffs[1:]:
            spatial = tuple(2 * s for s in a.shape[-ndim:])
            x_spec = jax.ShapeDtypeStruct(a.shape[:-ndim] + spatial, a.dtype)
            transpose = jax.linear_transpose(level_fn, x_spec)
            (a,) = transpose((a, det))
        return a

    @jax.jit
    def apply(coeffs):
        leaves = jax.tree_util.tree_leaves(coeffs)
        lead = leaves[0].shape[: leaves[0].ndim - ndim]
        flat = jax.tree_util.tree_map(
            lambda t: t.reshape((-1,) + t.shape[t.ndim - ndim :]), coeffs
        )
        out = run(flat)
        return out.reshape(lead + out.shape[1:])

    def check(coeffs):
        import math as _math

        lead = jax.tree_util.tree_leaves(coeffs)[0].shape[:-ndim]
        _check_batch_divisible(_math.prod(lead) if lead else 1,
                               mesh, batch_axis)

    def checked(coeffs):
        check(coeffs)
        return apply(coeffs)

    checked._apply = apply  # jitted body, exposed for HLO/sharding audits
    checked._check = check  # eager guards, callable separately by fused callers
    return checked


def sharded_waverec_per(mesh: Mesh, wavelet: str, seq_axis: str = "data",
                        batch_axis: str | None = None):
    """Inverse of `sharded_wavedec_per`: [cA_J, cD_J, ..., cD_1] — every
    leaf (..., n) sharded over ``seq_axis`` on its last axis — back to the
    (..., N) signal with the same sharding. Exact adjoint inverse,
    bit-compatible with `wam_tpu.wavelets.periodized.waverec_per`."""
    return _sharded_waverec_nd(
        mesh, seq_axis, 1, lambda t: _local_dwt_with_halo(t, wavelet, seq_axis),
        batch_axis,
    )


def sharded_waverec2_per(mesh: Mesh, wavelet: str, seq_axis: str = "data",
                         batch_axis: str | None = None):
    """Inverse of `sharded_wavedec2_per` (rows sharded). Bit-compatible
    with `waverec2_per`."""
    return _sharded_waverec_nd(mesh, seq_axis, 2, _level_fn_2d(wavelet, seq_axis),
                               batch_axis)


def sharded_waverec3_per(mesh: Mesh, wavelet: str, seq_axis: str = "data",
                         batch_axis: str | None = None):
    """Inverse of `sharded_wavedec3_per` (depth sharded). Bit-compatible
    with `waverec3_per`."""
    return _sharded_waverec_nd(mesh, seq_axis, 3, _level_fn_3d(wavelet, seq_axis),
                               batch_axis)


def sharded_coeff_grads_per(
    mesh: Mesh, wavelet: str, level: int, model_fn, seq_axis: str = "data", ndim: int = 1
):
    """End-to-end long-context WAM gradient core over a sequence-sharded
    input: decompose -> reconstruct -> model -> per-coefficient gradients,
    every stage sharded over ``seq_axis`` (reference gradient loop being
    replaced: `lib/wam_1D.py:88-150`, which back-props through
    waverec on a whole in-memory waveform).

    ``ndim`` selects the modality: 1 = waveform last axis, 2 = image ROW
    axis (x (..., H, W)), 3 = volume DEPTH axis (x (..., D, H, W)).
    `model_fn` maps the reconstructed signal to (B, classes) logits and
    must itself be XLA-partitionable over the sequence axis (convs and
    reductions are; GSPMD inserts the model-side halos/all-reduces). The
    returned step computes `grad over coeffs of sum(logits[b, y[b]])` — or
    of `mean(logits)` when ``y is None``, the engines' representation mode —
    and every gradient leaf keeps the coefficient sharding, so the WAM
    packing/analysis stages downstream can stay sharded too."""
    if ndim not in (1, 2, 3):
        raise ValueError(f"ndim must be 1, 2, or 3; got {ndim!r}")
    dec = {
        1: sharded_wavedec_per,
        2: sharded_wavedec2_per,
        3: sharded_wavedec3_per,
    }[ndim](mesh, wavelet, level, seq_axis)
    rec = {
        1: sharded_waverec_per,
        2: sharded_waverec2_per,
        3: sharded_waverec3_per,
    }[ndim](mesh, wavelet, seq_axis)

    @jax.jit
    def step(x, y=None):
        coeffs = dec(x)

        def objective(cs):
            out = model_fn(rec(cs))
            if y is None:
                return out.mean()
            return jnp.take_along_axis(out, y[:, None], axis=1).sum()

        return jax.grad(objective)(coeffs)

    return step
