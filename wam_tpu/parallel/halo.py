"""Sequence-sharded DWT with ring halo exchange (long-context support).

The 1D DWT is a filter-width-local stencil, so a sequence sharded across
devices only needs L−2 boundary samples from its ring neighbour per level —
exchanged with `lax.ppermute` over ICI inside `shard_map` (SURVEY.md §5.7:
"the ring-attention-shaped pattern, but for convolution"). With the
periodized transform the ring wrap IS the correct boundary condition, so the
sharded result is bit-compatible with the single-device `dwt_per`.

This is the scaling story for sequences far beyond one core's memory
(the reference processes its longest input, a 220k-sample waveform, whole —
`src/dataloader.py:83-97`; this path removes that ceiling).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from wam_tpu.wavelets.filters import build_wavelet
from wam_tpu.wavelets.periodized import dwt_per

__all__ = ["sharded_dwt_per", "sharded_wavedec_per"]


def _local_dwt_with_halo(x_local: jax.Array, wavelet: str, axis_name: str):
    """Per-shard kernel: fetch L−2 left-halo samples from the ring
    predecessor, then run the strided correlation locally."""
    wav = build_wavelet(wavelet)
    L = wav.filt_len
    n_shards = lax.axis_size(axis_name)
    if L > 2:
        tail = x_local[..., -(L - 2):]
        # ring shift: shard i receives the tail of shard i-1 (circular)
        halo = lax.ppermute(
            tail, axis_name, perm=[(i, (i + 1) % n_shards) for i in range(n_shards)]
        )
        ext = jnp.concatenate([halo, x_local], axis=-1)
    else:
        ext = x_local
    import numpy as np

    kernel = jnp.asarray(
        np.stack([np.asarray(wav.dec_lo[::-1]), np.asarray(wav.dec_hi[::-1])])[:, None],
        dtype=x_local.dtype,
    )
    batch_shape = ext.shape[:-1]
    xb = ext.reshape(-1, 1, ext.shape[-1])
    out = lax.conv_general_dilated(
        xb, kernel, window_strides=(2,), padding=[(0, 0)],
        dimension_numbers=lax.conv_dimension_numbers((1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")),
    )
    out = out.reshape(batch_shape + (2, x_local.shape[-1] // 2))
    return out[..., 0, :], out[..., 1, :]


def sharded_dwt_per(mesh: Mesh, wavelet: str, seq_axis: str = "data"):
    """Build a jitted `(x,) -> (cA, cD)` single-level sharded DWT: x (..., N)
    sharded over ``seq_axis`` on its last dimension; outputs keep the same
    sharding. Matches `dwt_per` exactly."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(None, seq_axis),
        out_specs=(P(None, seq_axis), P(None, seq_axis)),
    )
    def run(x_local):
        return _local_dwt_with_halo(x_local, wavelet, seq_axis)

    return run


def sharded_wavedec_per(mesh: Mesh, wavelet: str, level: int, seq_axis: str = "data"):
    """Multi-level sharded decomposition: [cA_J, cD_J, ..., cD_1], each leaf
    sharded over ``seq_axis``. Requires the local shard length to stay even
    at every level (N divisible by shards·2^level)."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(None, seq_axis),
        out_specs=P(None, seq_axis),
    )
    def run(x_local):
        coeffs = []
        a = x_local
        for _ in range(level):
            a, d = _local_dwt_with_halo(a, wavelet, seq_axis)
            coeffs.append(d)
        coeffs.append(a)
        return coeffs[::-1]

    return run
