"""Multi-host execution: jax.distributed bootstrap + DCN-aware hybrid meshes.

The reference has no distributed backend at all (SURVEY.md §2.10/§5.8); the
TPU-native counterpart runs one Python process per host, connects them with
`jax.distributed.initialize`, and lays out a hybrid mesh whose outer axis
maps to DCN (slice-to-slice network) and inner axes to ICI — so the
bandwidth-hungry collectives (the SmoothGrad sample psum, mosaic all_gather)
stay on ICI within each slice, and only the small data-parallel reductions
cross DCN.

Single-process usage is unchanged: every helper degrades to the local
device mesh when there is one process.
"""

from __future__ import annotations

import math
import time

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["CoordinatorConnectError", "init_distributed", "hybrid_mesh",
           "process_local_batch"]


class CoordinatorConnectError(RuntimeError):
    """Could not reach the jax.distributed coordinator within the retry
    budget. The message names the coordinator address and the attempts
    made — a pod bring-up that fails here fails diagnosable, not as a raw
    hang or a bare RuntimeError from deep inside the runtime."""


def _distributed_client_exists() -> bool:
    """Whether jax's distributed client is already up (private API probe,
    guarded against jax-version drift)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def _initialize_with_retries(
    coordinator_address,
    connect_attempts: int,
    connect_backoff_s: float,
    **kwargs,
) -> None:
    """Bounded-retry wrapper around `jax.distributed.initialize`: slow pod
    bring-up (coordinator container still scheduling, DNS not yet
    propagated) retries with linear backoff; exhaustion raises
    `CoordinatorConnectError` naming the address. Already-initialized
    runtimes pass through as success on any attempt."""
    last: Exception | None = None
    for attempt in range(1, max(1, connect_attempts) + 1):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address, **kwargs)
            return
        except RuntimeError as exc:
            if _distributed_client_exists() or "already" in str(exc).lower():
                return
            last = exc
            if attempt < connect_attempts:
                time.sleep(connect_backoff_s * attempt)
    raise CoordinatorConnectError(
        f"could not connect to jax.distributed coordinator at "
        f"{coordinator_address or '<env-discovered>'} after "
        f"{connect_attempts} attempt(s) "
        f"(backoff {connect_backoff_s:g}s/attempt): {last!r}"
    ) from last


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    initialization_timeout: float | None = None,
    connect_attempts: int = 3,
    connect_backoff_s: float = 2.0,
) -> dict:
    """Connect this process to the multi-host runtime.

    On TPU pods the arguments are discovered from the environment, so a bare
    ``init_distributed()`` works under standard launchers; explicit arguments
    support manual bring-up. Safe to call in a single process with no
    cluster environment (no-op). Coordinator connect is bounded:
    ``connect_attempts`` tries with ``connect_backoff_s``-linear backoff,
    then `CoordinatorConnectError` naming the coordinator address (pod
    workers surface it verbatim instead of hanging bring-up). Returns
    {"process_index", "process_count", "local_devices", "global_devices"}.
    """
    import os

    if coordinator_address is not None or num_processes not in (None, 1):
        kwargs = {}
        if initialization_timeout is not None:
            kwargs["initialization_timeout"] = initialization_timeout
        _initialize_with_retries(
            coordinator_address,
            connect_attempts,
            connect_backoff_s,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    else:
        # Auto-init only when a launcher really indicates multiple hosts: a
        # coordinator address, or a multi-entry worker list. (A bare
        # initialize() in a genuinely single-process run would hang waiting
        # for peers; single-host TPU VMs also set TPU_WORKER_HOSTNAMES.)
        multi_host = any(
            os.environ.get(k)
            for k in (
                "JAX_COORDINATOR_ADDRESS",
                "COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS",
            )
        ) or ("," in os.environ.get("TPU_WORKER_HOSTNAMES", ""))
        if multi_host:
            # Already-initialized/backend-already-up still passes through as
            # success inside the retry wrapper; a genuine bring-up failure
            # (unreachable coordinator, bad env) must not silently degrade
            # to single-process (round-1 ADVICE.md item 3) — it exhausts the
            # retries and raises CoordinatorConnectError.
            _initialize_with_retries(None, connect_attempts, connect_backoff_s)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def hybrid_mesh(
    axis_sizes: dict[str, int],
    dcn_axis: str | None = None,
    devices=None,
) -> Mesh:
    """Mesh over ALL processes' devices with one axis mapped to DCN.

    ``dcn_axis`` (default: the first axis) is laid out across process
    granules so that every other axis stays within a slice (ICI). With one
    process this is exactly ``make_mesh``. Use -1 for one axis size to infer
    it from the global device count.
    """
    from wam_tpu.parallel.mesh import make_mesh

    devices = jax.devices() if devices is None else list(devices)
    n_proc = jax.process_count()
    sizes = dict(axis_sizes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if unknown:
        known = math.prod(v for v in sizes.values() if v != -1)
        if len(unknown) > 1 or len(devices) % known:
            raise ValueError(f"cannot infer {unknown} from {len(devices)} devices")
        sizes[unknown[0]] = len(devices) // known
    if n_proc == 1:
        return make_mesh(sizes, devices)

    dcn_axis = dcn_axis or next(iter(sizes))
    if sizes[dcn_axis] % n_proc:
        raise ValueError(
            f"DCN axis {dcn_axis!r}={sizes[dcn_axis]} not divisible by "
            f"{n_proc} processes"
        )
    # Topology-aware assignment: per-slice (ICI) shape × per-axis DCN
    # multiplier. Only dcn_axis spans slice boundaries.
    from jax.experimental import mesh_utils

    axis_names = tuple(sizes)
    ici_shape = [sizes[a] // n_proc if a == dcn_axis else sizes[a] for a in axis_names]
    dcn_shape = [n_proc if a == dcn_axis else 1 for a in axis_names]
    # process_is_granule matches the n_proc-based shapes above on topologies
    # where one slice hosts several processes (the default slice granule
    # would require slices == product(dcn_shape)).
    arr = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=np.asarray(devices), process_is_granule=True
    )
    return Mesh(arr, axis_names)


def process_local_batch(global_batch: int) -> int:
    """Per-process batch size for a data-parallel input pipeline: each host
    feeds only its shard (jax.make_array_from_process_local_data assembles
    the global array)."""
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by {n} processes")
    return global_batch // n
