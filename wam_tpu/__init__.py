"""wam_tpu — TPU-native Wavelet Attribution Method framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
`michalpiasecki0/wam` repository (Wavelet Attribution Method, ICML 2025):
differentiable multi-level wavelet transforms (1D/2D/3D), gradient-based
attribution in the wavelet domain, SmoothGrad / Integrated-Gradients
estimators, a faithfulness-evaluation suite, scale analyzers, baselines,
model zoo, data loaders, and visualization for audio / image / volume
modalities.

Everything in the compute path is pure-functional JAX: transforms are
jit-able, vmap-able, and shardable over a `jax.sharding.Mesh`
(wam_tpu.parallel). Host-side IO has a native C++ fast path
(wam_tpu.native).
"""

from wam_tpu.wavelets import (
    Detail2D,
    Wavelet,
    build_wavelet,
    dwt,
    dwt2,
    dwt3,
    idwt,
    idwt2,
    idwt3,
    wavedec,
    wavedec2,
    wavedec3,
    waverec,
    waverec2,
    waverec3,
)
from wam_tpu.core import WamEngine, integrated_path, smoothgrad, target_loss

# Modality front-ends (the reference's lib/wam_{1,2,3}D.py surface)
from wam_tpu.wam1d import BaseWAM1D, VisualizerWAM1D, WaveletAttribution1D
from wam_tpu.wam2d import BaseWAM2D, WaveletAttribution2D
from wam_tpu.wam3d import BaseWAM3D, WaveletAttribution3D
from wam_tpu.analyzers import WAMAnalyzer2D, WAMAnalyzerViT

# Transformer-native & temporal attribution (wam_tpu.xattr)
from wam_tpu.xattr import (
    EvalVideoWAM,
    VideoLevels,
    WaveletAttributionVideo,
    attention_gradient,
    attention_rollout,
    plan_patch_levels,
    token_grid_map,
)

__version__ = "0.1.0"

__all__ = [
    "Wavelet",
    "build_wavelet",
    "Detail2D",
    "dwt",
    "idwt",
    "dwt2",
    "idwt2",
    "dwt3",
    "idwt3",
    "wavedec",
    "waverec",
    "wavedec2",
    "waverec2",
    "wavedec3",
    "waverec3",
    "WamEngine",
    "target_loss",
    "smoothgrad",
    "integrated_path",
    "BaseWAM1D",
    "WaveletAttribution1D",
    "VisualizerWAM1D",
    "BaseWAM2D",
    "WaveletAttribution2D",
    "BaseWAM3D",
    "WaveletAttribution3D",
    "WAMAnalyzer2D",
    "WAMAnalyzerViT",
    "attention_rollout",
    "attention_gradient",
    "plan_patch_levels",
    "token_grid_map",
    "VideoLevels",
    "WaveletAttributionVideo",
    "EvalVideoWAM",
]
