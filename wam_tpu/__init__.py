"""wam_tpu — TPU-native Wavelet Attribution Method framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
`michalpiasecki0/wam` repository (Wavelet Attribution Method, ICML 2025):
differentiable multi-level wavelet transforms (1D/2D/3D), gradient-based
attribution in the wavelet domain, SmoothGrad / Integrated-Gradients
estimators, a faithfulness-evaluation suite, scale analyzers, and
visualization for audio / image / volume modalities.

Everything in the compute path is pure-functional JAX: transforms are
jit-able, vmap-able, and shardable over a `jax.sharding.Mesh`.
"""

from wam_tpu.wavelets import (
    Wavelet,
    build_wavelet,
    dwt,
    idwt,
    wavedec,
    waverec,
    wavedec2,
    waverec2,
    wavedec3,
    waverec3,
)

__version__ = "0.1.0"
