"""Environment verification — the `test_environment.py` role (C15): import
smoke-test of the dependency stack, accelerator probe, and a tiny end-to-end
attribution. Run as `python -m wam_tpu.env_check`."""

from __future__ import annotations

import sys

CORE_DEPS = ["jax", "flax", "numpy", "scipy", "matplotlib", "PIL", "einops", "h5py", "pandas"]


def check_imports() -> list[str]:
    failed = []
    for mod in CORE_DEPS:
        try:
            __import__(mod)
        except Exception:
            failed.append(mod)
    return failed


def check_devices() -> str:
    import jax

    from wam_tpu.config import ensure_usable_backend

    platform = ensure_usable_backend(timeout_s=60.0)
    devs = jax.devices()
    note = " (accelerator unavailable; CPU fallback)" if platform == "cpu" else ""
    return f"{len(devs)} × {devs[0].platform}{note}"


def check_wam() -> None:
    import jax
    import jax.numpy as jnp

    from wam_tpu import WaveletAttribution2D, wavedec2, waverec2

    x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 32, 32))
    rec = waverec2(wavedec2(x, "db2", 2), "db2")[..., :32, :32]
    assert float(jnp.abs(rec - x).max()) < 1e-3, "DWT round-trip failed"

    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, v):
            v = jnp.transpose(v, (0, 2, 3, 1))
            return nn.Dense(4)(nn.relu(nn.Conv(4, (3, 3))(v)).mean(axis=(1, 2)))

    m = M()
    p = m.init(jax.random.PRNGKey(0), x)
    expl = WaveletAttribution2D(lambda v: m.apply(p, v), J=2, n_samples=2)
    out = expl(x, jnp.array([1]))
    assert out.shape[0] == 1


def main() -> int:
    failed = check_imports()
    if failed:
        print(f"FAIL: missing imports: {failed}")
        return 1
    print(f"devices: {check_devices()}")
    try:
        check_wam()
    except Exception as e:
        print(f"FAIL: end-to-end attribution: {e}")
        return 1
    print("OK: imports, devices, DWT round-trip, end-to-end attribution")
    return 0


if __name__ == "__main__":
    sys.exit(main())
