"""Core gradient engine: ∂ logit_y / ∂ wavelet-coefficients as a pure VJP.

Replaces the reference's requires_grad/backward dance
(`lib/wam_2D.py:102-116`, `lib/wam_1D.py:112-126`, `lib/wam_3D.py:197-238`)
with `jax.grad` of the function coeffs ↦ model(idwt(coeffs)) — differentiable
by construction, jit-able, vmap-able (SURVEY.md §7.1 step 2).

Supports the `y=None` representation mode of the 3D engine
(`lib/wam_3D.py:226-232`): differentiate the mean of the model output instead
of a class logit.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from wam_tpu.wavelets import transform as wt

__all__ = ["WamEngine", "target_loss"]

_DEC = {1: wt.wavedec, 2: wt.wavedec2, 3: wt.wavedec3}
_REC = {1: wt.waverec, 2: wt.waverec2, 3: wt.waverec3}


def target_loss(output: jax.Array, y: jax.Array | None) -> jax.Array:
    """Scalar objective: mean over the batch of logit[i, y[i]]
    (the reference's `torch.diag(output[:, y]).mean()`, lib/wam_2D.py:115),
    or mean of the whole output when y is None (representation mode)."""
    if y is None:
        return output.mean()
    y = jnp.asarray(y)
    picked = jnp.take_along_axis(output, y[:, None], axis=1)[:, 0]
    return picked.mean()


class WamEngine:
    """Single-pass wavelet attribution for one modality.

    Parameters
    ----------
    model_fn : callable mapping the reconstructed input batch to logits
        (params already bound; compose with a front-end like a mel
        spectrogram by passing ``front_fn``).
    ndim : spatial rank (1 audio, 2 image, 3 volume).
    front_fn : optional differentiable transform between reconstruction and
        the model (the 1D melspec front-end, `lib/wam_1D.py:117-126`). Its
        gradients can be harvested via ``attribute_with_front_grads``.
    channel_last : 2D only — inputs/reconstructions are NHWC (B, H, W, C)
        and ``model_fn`` consumes NHWC directly
        (``bind_inference(nchw=False)``), so no layout copy sits between
        the IDWT and the model inside the per-sample step
        (`wam_tpu.wavelets.nhwc`; round-3 layout-copy audit, BASELINE.md).
        This path has exactly ONE implementation (axis-aware banded-matrix
        contractions) — `wavelets.set_dwt2_impl` selects among the
        last-two-axes impls and does NOT apply here.
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        *,
        ndim: int,
        wavelet: str = "haar",
        level: int = 3,
        mode: str = "reflect",
        front_fn: Callable[[jax.Array], jax.Array] | None = None,
        channel_last: bool = False,
    ):
        if ndim not in (1, 2, 3):
            raise ValueError(f"ndim must be 1, 2 or 3, got {ndim}")
        if channel_last and ndim != 2:
            raise ValueError("channel_last is only supported for ndim=2")
        self.model_fn = model_fn
        self.ndim = ndim
        self.wavelet = wavelet
        self.level = level
        self.mode = mode
        self.front_fn = front_fn
        self.channel_last = channel_last

    # -- decomposition / reconstruction ------------------------------------

    def decompose(self, x: jax.Array):
        if self.channel_last:
            from wam_tpu.wavelets.nhwc import wavedec2_nhwc

            return wavedec2_nhwc(x, self.wavelet, self.level, self.mode)
        return _DEC[self.ndim](x, self.wavelet, self.level, self.mode)

    def reconstruct(self, coeffs, spatial_shape: Sequence[int]):
        if self.channel_last:
            from wam_tpu.wavelets.nhwc import waverec2_nhwc

            rec = waverec2_nhwc(coeffs, self.wavelet)
            h, w = spatial_shape
            return rec[..., :h, :w, :]
        rec = _REC[self.ndim](coeffs, self.wavelet)
        # Reconstruction length is >= the original for non-haar filters /
        # odd sizes; crop to the model's expected spatial shape.
        idx = (Ellipsis,) + tuple(slice(0, s) for s in spatial_shape)
        return rec[idx]

    # -- attribution -------------------------------------------------------

    def _loss_from_coeffs(self, coeffs, y, spatial_shape):
        x = self.reconstruct(coeffs, spatial_shape)
        if self.front_fn is not None:
            x = self.front_fn(x)
        return target_loss(self.model_fn(x), y)

    def grads_from_coeffs(self, coeffs, y, spatial_shape) -> Any:
        """Gradient pytree with the same structure as the coefficients —
        the per-coefficient attribution."""
        return jax.grad(lambda cs: self._loss_from_coeffs(cs, y, spatial_shape))(coeffs)

    def spatial_shape(self, x_shape) -> tuple:
        """The transform's spatial dims of an input shape (layout-aware)."""
        if self.channel_last:
            return tuple(x_shape[-3:-1])
        return tuple(x_shape[-self.ndim :])

    def attribute(self, x: jax.Array, y: jax.Array | None):
        """Full single pass: decompose → grads. Returns (coeffs, grads)."""
        coeffs = self.decompose(x)
        grads = self.grads_from_coeffs(coeffs, y, self.spatial_shape(x.shape))
        return coeffs, grads

    def attribute_with_health(self, x: jax.Array, y: jax.Array | None):
        """`attribute` plus the gradient tree's numeric-health vector
        (`wam_tpu.obs.health.health_stats` over the coefficient gradients
        — the per-call grad-norm / NaN-Inf summary). Pure jax: health-fused
        serving entries fold the vector into the same compiled graph, so
        the stats ride the result fetch already happening. Returns
        ``(coeffs, grads, health_vec)``."""
        from wam_tpu.obs.health import health_stats

        coeffs, grads = self.attribute(x, y)
        return coeffs, grads, health_stats(grads)

    def attribute_with_front_grads(self, x: jax.Array, y: jax.Array | None):
        """Like `attribute`, additionally returning the gradient at the
        front-end output (the reference's `melspecs.retain_grad()` tap,
        `lib/wam_1D.py:121`). Implemented with a zero additive tap so a
        single backward pass yields both gradients."""
        if self.front_fn is None:
            raise ValueError("attribute_with_front_grads requires front_fn")
        coeffs = self.decompose(x)
        spatial = x.shape[-self.ndim :]

        front_shape = jax.eval_shape(
            lambda cs: self.front_fn(self.reconstruct(cs, spatial)), coeffs
        )

        def loss(cs, tap):
            rec = self.reconstruct(cs, spatial)
            front = self.front_fn(rec) + tap
            return target_loss(self.model_fn(front), y)

        zeros_tap = jnp.zeros(front_shape.shape, front_shape.dtype)
        g_coeffs, g_front = jax.grad(loss, argnums=(0, 1))(coeffs, zeros_tap)
        return coeffs, g_coeffs, g_front
