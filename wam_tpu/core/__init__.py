from wam_tpu.core.engine import WamEngine, target_loss
from wam_tpu.core.estimators import integrated_path, noise_sigma, smoothgrad, trapezoid

__all__ = [
    "WamEngine",
    "target_loss",
    "smoothgrad",
    "integrated_path",
    "noise_sigma",
    "trapezoid",
]
