"""Attribution smoothing estimators: SmoothGrad and Integrated Gradients.

TPU-native redesign of the reference's sequential Python loops
(`lib/wam_2D.py:379-459`, `lib/wam_1D.py:294-421`, `lib/wam_3D.py:550-643`):
the n_samples / α-path loops become a `lax.map` (optionally chunk-vmapped via
``batch_size``) inside one jit graph, so the whole estimator is a single XLA
program — no host round-trips per sample (the reference does 25 CPU↔GPU
transfers per batch, SURVEY.md §3.1).

Fixes by construction:
- reference 3D SmoothGrad divides by n_samples inside the loop
  (`lib/wam_3D.py:585-587`, SURVEY.md §2.11.4) — here the mean is taken once;
- per-image noise σ (`lib/wam_2D.py:394-403`) is computed with a vectorized
  reduce, and RNG is a splittable `jax.random` key.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["noise_sigma", "smoothgrad", "integrated_path", "trapezoid",
           "resolve_sample_chunk", "resolve_checkpoint_stride",
           "validate_sample_batch_size"]


def validate_sample_batch_size(value) -> None:
    """Reject any string other than exactly "auto" (bool("false") is True —
    an unvalidated config string would silently change the schedule)."""
    if isinstance(value, str) and value != "auto":
        raise ValueError(
            f"sample_batch_size must be an int, None or 'auto', got {value!r}"
        )


# The v5e scheduling law all three modalities obey (BASELINE.md round-3
# scaling study + the round-4 median-of-k re-sweeps that overturned the
# "audio/3D prefer full vmap" single-min artifact): ~128 model rows per
# mapped sample step. Since round 6 this is the FALLBACK: a tuned schedule
# in the `wam_tpu.tune` cache (keyed by workload/shape/batch/dtype/impl/
# backend) wins over the law when the caller identifies its workload.
_AUTO_TARGET_ROWS = 128


def _clamp_chunk(chunk, n_samples: int):
    if chunk is None or int(chunk) >= n_samples:
        return None
    return max(1, int(chunk))


def resolve_sample_chunk(sample_batch_size, batch: int, n_samples: int,
                         *, workload: str | None = None, shape=None,
                         dtype: str = "f32", dwt_impl: str | None = None):
    """Trace-time resolution of sample_batch_size="auto".

    Explicit ints/None pass through. For "auto", a tuned entry from the
    schedule cache (`wam_tpu.tune.lookup_schedule`, keyed by
    ``workload``/``shape``/``batch``/``dtype``/dwt impl/backend) is
    consulted first — on ANY backend, so a CPU- or future-backend tune is
    honored too; its chunk is clamped to ``n_samples`` (chunk ≥ n → full
    vmap, same convention as the law). Without a matching entry (or with
    ``workload=None``, the legacy call shape): chunk·batch ≈ 128 model rows
    on TPU, full vmap elsewhere — exactly the round-5 behavior.
    """
    if sample_batch_size != "auto":
        return sample_batch_size
    if workload is not None:
        from wam_tpu.tune import lookup_schedule

        ent = lookup_schedule(workload, shape, batch, dtype, dwt_impl)
        if ent is not None and "sample_chunk" in ent:
            return _clamp_chunk(ent["sample_chunk"], n_samples)
    if jax.default_backend() != "tpu":
        return None
    chunk = max(1, _AUTO_TARGET_ROWS // max(1, int(batch)))
    return _clamp_chunk(chunk, n_samples)


def resolve_checkpoint_stride(stride, n_samples: int, *,
                              workload: str | None = None, shape=None,
                              batch: int | None = None,
                              dtype: str = "f32",
                              default: int = 5) -> int:
    """Trace-time resolution of the anytime checkpoint stride k
    (``stride="auto"``, `wam_tpu.anytime`).

    Explicit ints pass through (clamped to [1, n_samples]). For "auto", a
    tuned ``anytime_stride`` from the schedule cache wins when the caller
    identifies its workload (the `tune` sweep axis added with the anytime
    round); otherwise ``default`` — small enough that a deadline-pressed
    request still lands several checkpoints inside a typical window,
    large enough that the conf-vector control sync stays a rounding error
    next to the sample dispatches."""
    if stride != "auto":
        stride = int(stride)
        if stride < 1:
            raise ValueError(f"checkpoint stride must be >= 1, got {stride}")
        return min(stride, max(1, int(n_samples)))
    if workload is not None:
        from wam_tpu.tune import lookup_schedule

        ent = lookup_schedule(workload, shape, batch, dtype)
        if ent is not None and ent.get("anytime_stride"):
            return min(int(ent["anytime_stride"]), max(1, int(n_samples)))
    return min(int(default), max(1, int(n_samples)))


def noise_sigma(x: jax.Array, stdev_spread: float) -> jax.Array:
    """Per-sample noise scale σ_i = spread · (max(x_i) − min(x_i)), reduced
    over all non-batch axes (reference: `lib/wam_2D.py:396-399`)."""
    axes = tuple(range(1, x.ndim))
    return stdev_spread * (jnp.max(x, axis=axes) - jnp.min(x, axis=axes))


def smoothgrad(
    step_fn: Callable[[jax.Array], Any],
    x: jax.Array,
    key: jax.Array,
    *,
    n_samples: int,
    stdev_spread: float,
    batch_size: int | None = None,
    materialize_noise: bool = True,
) -> Any:
    """Mean of `step_fn` over ``n_samples`` noisy copies of ``x``.

    ``step_fn`` maps a perturbed input batch to any pytree (coefficient
    grads, a packed mosaic, ...). Samples are evaluated by `lax.map`
    (chunked by ``batch_size``) so memory is bounded; the sample axis can
    also be sharded across devices by wrapping the caller in shard_map
    (wam_tpu.parallel).

    ``materialize_noise=False`` draws each sample's noise INSIDE the map
    body (keys via `fold_in`) instead of materializing the full
    (n_samples, *x.shape) buffer up front — at the flagship's b128 that
    buffer is 1.9 GB of HBM traffic. Different (equally valid) draws than
    the materialized path: same σ, different stream.
    """
    sigma = noise_sigma(x, stdev_spread)
    sigma = sigma.reshape(sigma.shape + (1,) * (x.ndim - 1))
    if materialize_noise:
        noise = jax.random.normal(key, (n_samples,) + x.shape, dtype=x.dtype) * sigma
        outs = lax.map(lambda n: step_fn(x + n), noise, batch_size=batch_size)
    else:
        def body(i):
            k = jax.random.fold_in(key, i)
            n = jax.random.normal(k, x.shape, x.dtype) * sigma
            return step_fn(x + n)

        idx = jnp.arange(n_samples)
        outs = lax.map(body, idx, batch_size=batch_size)
    return jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0), outs)


def trapezoid(path: jax.Array, dx: float = 1.0) -> jax.Array:
    """Trapezoidal rule along axis 0, NaN-safe (the reference applies
    `np.trapz(np.nan_to_num(...), axis=1)` with default dx=1,
    `lib/wam_2D.py:452`)."""
    path = jnp.nan_to_num(path)
    return (path[0] / 2 + path[1:-1].sum(axis=0) + path[-1] / 2) * dx


def integrated_path(
    grad_fn: Callable[[Any], Any],
    coeffs: Any,
    *,
    n_steps: int,
    batch_size: int | None = None,
    dx: float = 1.0,
) -> Any:
    """Integrated gradients along the straight path α·coeffs, α ∈ [0, 1].

    ``grad_fn`` maps a coefficient pytree to any pytree (e.g. grad mosaics);
    the result is the trapezoidal integral of that pytree over the path
    (reference: `lib/wam_2D.py:417-459` with the arXiv:1908.06214 trapezoid
    refinement).
    """
    alphas = jnp.linspace(0.0, 1.0, n_steps, dtype=jnp.float32)

    def one(alpha):
        scaled = jax.tree_util.tree_map(lambda c: c * alpha.astype(c.dtype), coeffs)
        return grad_fn(scaled)

    path = lax.map(one, alphas, batch_size=batch_size)
    return jax.tree_util.tree_map(lambda a: trapezoid(a, dx=dx), path)
