"""Anytime attribution: progressive refinement with confidence-gated
deadline serving (DESIGN.md "Anytime attribution").

WAM's smoothing estimators are running means, which makes them anytime
algorithms by construction: the fused accumulator loops (round 9,
`parallel.seq_estimators`) already carry a running sum that is a
bit-equal checkpoint of the final map at every sample count. This package
surfaces, scores, and serves those partial results:

- `anytime.state` — the checkpoint math: Welford-style M2 reconstructed
  from consecutive sum accumulators (never touching the accumulator
  chain) and the fixed-size per-row confidence vector.
- `anytime.entry.make_anytime_entry` — checkpointed serving entries:
  begin/step/finalize jits with the conf vector fused into the stride
  graph (one health-vector-style extra output leaf, zero extra fetches).
- `anytime.driver` — the shared stride-loop policy (complete / converged
  / deadline) driving an entry; `run_anytime` for direct callers, the
  serve worker embeds `drive_anytime`.
- `anytime.result.AnytimeResult` — what anytime-server futures resolve
  to: best-so-far map + confidence instead of `DeadlineExceededError`.

`SeqShardedWam.smoothgrad_checkpointed` / `integrated_checkpointed` are
the sequence-sharded checkpointed estimators (same module as the fused
loops they wrap); `WaveletAttribution2D.anytime_serve_entry` builds the
single-device serving entry. ``WAM_TPU_NO_ANYTIME=1`` makes anytime
servers treat their entry as a plain full-n one (kill switch).
"""

from wam_tpu.anytime.driver import AnytimeOutcome, drive_anytime, run_anytime
from wam_tpu.anytime.entry import (
    DEFAULT_PLATEAU_TOL,
    AnytimeEntry,
    make_anytime_entry,
)
from wam_tpu.anytime.result import AnytimeResult
from wam_tpu.anytime.state import (
    ANYTIME_VEC_SIZE,
    SLOT_CONFIDENCE,
    SLOT_COUNT,
    SLOT_DELTA,
    SLOT_REL_SEM,
    conf_stats,
    m2_update,
)

__all__ = [
    "ANYTIME_VEC_SIZE",
    "SLOT_COUNT",
    "SLOT_REL_SEM",
    "SLOT_DELTA",
    "SLOT_CONFIDENCE",
    "DEFAULT_PLATEAU_TOL",
    "AnytimeEntry",
    "AnytimeOutcome",
    "AnytimeResult",
    "conf_stats",
    "drive_anytime",
    "m2_update",
    "make_anytime_entry",
    "run_anytime",
]
