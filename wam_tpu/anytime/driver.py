"""The stride-loop policy: drive an `AnytimeEntry` until complete,
converged, or out of deadline.

This is the one place the serving semantics live — the serve worker
(`serve.runtime`) and direct callers (tests, benches) share it, so the
policy cannot drift between them:

- always run at least one stride (a deadline-pressed request gets a real
  best-so-far map, never nothing);
- stop when every sample is in (``complete``);
- stop early when the batch has CONVERGED — every row's checkpoint delta
  under the entry's ``plateau_tol`` — and every row clears the requested
  confidence floor (the early exit that frees the batch slot);
- stop when the next stride cannot land before the deadline (projected
  from an EMA of observed stride seconds), delivering the running mean.

Per-stride progress reads the tiny conf vector with a raw
``jax.device_get`` — a control-plane sync that also serves as the
stride's completion barrier. The RESULT crosses host-ward exactly once,
through `evalsuite.fan.device_fetch` (`run_anytime`; the serve worker
fetches at its existing single-harvest point instead), so `fetch_scope`
probes count one fetch per request with checkpointing on — the same
zero-extra-fetch contract the health plane rides.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax

from wam_tpu.anytime.state import SLOT_CONFIDENCE, SLOT_COUNT, SLOT_DELTA

__all__ = ["drive_anytime", "run_anytime", "AnytimeOutcome"]


@dataclass
class AnytimeOutcome:
    """`run_anytime`'s host-side result (one batch)."""

    out: Any  # finalized attribution tree (host)
    conf: Any  # (B, ANYTIME_VEC_SIZE) confidence vector (host)
    n_used: int
    n_total: int
    complete: bool
    converged: bool
    strides: int
    deadline_hit: bool


def drive_anytime(entry, xs, ys, *, deadline: float | None = None,
                  min_confidence: float = 0.0, n_rows: int | None = None):
    """Run the stride loop (policy above); returns ``(out_dev, conf_dev,
    info)`` with the finalized attribution and conf vector still ON DEVICE
    (the caller owns the single result fetch) and ``info`` a dict of
    ``n_used/n_total/complete/converged/strides/deadline_hit``.

    ``deadline`` is an absolute `time.perf_counter` timestamp (None = run
    to convergence or completion); ``min_confidence`` the floor every row
    must clear for the convergence early exit; ``n_rows`` limits the
    policy to the first rows of the batch (the serve worker's real rows —
    pad rows replicate row 0 and must not hold the batch open)."""
    state = entry.begin(xs, ys)
    n_total = entry.n_total
    tol = entry.plateau_tol
    strides = 0
    ema_stride_s: float | None = None
    converged = False
    deadline_hit = False
    count = 0
    while True:
        t0 = time.perf_counter()
        state = entry.step(state, xs, ys)
        # control-plane sync: blocks until the stride lands, so the wall
        # delta is an honest per-stride service time for the projection
        cv = jax.device_get(entry.confidence(state))
        dt = time.perf_counter() - t0
        ema_stride_s = dt if ema_stride_s is None else 0.5 * (ema_stride_s + dt)
        strides += 1
        rows = cv[:n_rows] if n_rows else cv
        count = int(rows[0, SLOT_COUNT])
        if count >= n_total:
            break
        converged = (tol > 0.0
                     and float(rows[:, SLOT_DELTA].max()) <= tol
                     and float(rows[:, SLOT_CONFIDENCE].min())
                     >= min_confidence)
        if converged:
            break
        now = time.perf_counter()
        if deadline is not None and now + ema_stride_s > deadline:
            deadline_hit = True
            break
    out_dev, conf_dev = entry.finalize(state)
    info = {
        "n_used": count,
        "n_total": n_total,
        "complete": count >= n_total,
        "converged": converged,
        "strides": strides,
        "deadline_hit": deadline_hit,
    }
    return out_dev, conf_dev, info


def run_anytime(entry, xs, ys, *, deadline_ms: float | None = None,
                min_confidence: float = 0.0,
                n_rows: int | None = None) -> AnytimeOutcome:
    """`drive_anytime` plus THE one result fetch
    (`evalsuite.fan.device_fetch` — the counted, scoped fetch). Direct
    drive for tests and benches; ``deadline_ms`` is relative to now."""
    from wam_tpu.evalsuite.fan import device_fetch

    deadline = (time.perf_counter() + deadline_ms / 1e3
                if deadline_ms is not None else None)
    out_dev, conf_dev, info = drive_anytime(
        entry, xs, ys, deadline=deadline,
        min_confidence=min_confidence, n_rows=n_rows)
    out, conf = device_fetch((out_dev, conf_dev))
    return AnytimeOutcome(out=out, conf=conf, **info)
