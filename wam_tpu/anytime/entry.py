"""Checkpointed serving entries: the progressive-refinement counterpart of
`serve.entry.jit_entry`.

A plain serving entry is one jitted ``entry(x, y) -> attribution``; an
*anytime* entry splits the same estimator into three jitted pieces the
serve worker drives stride-by-stride:

- ``begin(x, y) -> state``         zero state (sum accumulator, Welford
                                   M2, checkpoint snapshot, conf vector)
- ``step(state, x, y) -> state``   ONE dispatch accumulating ``stride``
                                   samples (a masked `lax.fori_loop`, so a
                                   non-dividing n_total never re-compiles)
- ``finalize(state) -> (attr, conf)``  the running mean through the
                                   caller's finalize plus the
                                   (B, ANYTIME_VEC_SIZE) confidence vector

``confidence(state)`` is a zero-dispatch field read: the worker's
per-stride progress check `jax.device_get`s that tiny array — a
control-plane sync, NOT a result fetch; the attribution itself crosses
once, in the worker's single existing harvest (the zero-extra-fetch
contract, `evalsuite.fan.device_fetch`).

The entry object also answers ``entry(x, y)``: the full-n synchronous
path (drive every stride, return the finalized attribution alone), which
is what a server with ``WAM_TPU_NO_ANYTIME=1`` — or a plain warmup —
sees. Like `jit_entry(with_health=...)`, the marker attribute
(``wam_anytime``) rides a plain-object shell because jit callables reject
attribute assignment.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from wam_tpu.anytime.state import conf_stats, m2_update
from wam_tpu.obs import sentinel as obs_sentinel

__all__ = ["AnytimeEntry", "make_anytime_entry", "DEFAULT_PLATEAU_TOL"]

# relative per-checkpoint motion below which an input counts as converged
# (the early-exit trigger); ~half a percent of the map's RMS per stride
DEFAULT_PLATEAU_TOL = 5e-3


class AnytimeEntry:
    """The begin/step/confidence/finalize bundle (module docstring). Built
    by `make_anytime_entry`; consumed by the serve worker via
    `anytime.driver.drive_anytime` or called directly as ``entry(x, y)``
    for the non-anytime full-n path."""

    wam_anytime = True

    def __init__(self, begin, step, finalize, *, n_total: int, stride: int,
                 plateau_tol: float, name: str):
        self.begin = begin
        self.step = step
        self.finalize = finalize
        self.n_total = int(n_total)
        self.stride = int(stride)
        self.plateau_tol = float(plateau_tol)
        self.__name__ = name

    def confidence(self, state):
        """The state's live conf vector — a device-array field read, no
        dispatch; the worker's per-stride control sync reads this."""
        return state[-1]

    def n_strides(self) -> int:
        return -(-self.n_total // self.stride)

    def __call__(self, x, y):
        """Full-n synchronous entry: every stride, finalized attribution
        only — the `WAM_TPU_NO_ANYTIME` / plain-server compatibility
        surface (confidence is computed and dropped)."""
        state = self.begin(x, y)
        for _ in range(self.n_strides()):
            state = self.step(state, x, y)
        out, _conf = self.finalize(state)
        return out


def make_anytime_entry(
    sample_fn: Callable,
    finalize_fn: Callable | None = None,
    *,
    n_total: int,
    stride: int = 5,
    plateau_tol: float = DEFAULT_PLATEAU_TOL,
    on_trace: Callable[[], None] | None = None,
    obs_kind: str = "serve",
    name: str = "anytime_entry",
) -> AnytimeEntry:
    """Build an `AnytimeEntry` from a per-sample estimator step.

    ``sample_fn(x, y, i) -> g`` is sample ``i``'s contribution (leading
    batch axis on every leaf; e.g. a SmoothGrad draw's mosaic) whose mean
    over ``n_total`` samples is the attribution; ``finalize_fn(mean) ->
    attr`` post-processes the mean (identity when None). ``stride`` is the
    checkpoint cadence k — samples per `step` dispatch; the remainder of a
    non-dividing ``n_total`` is weight-masked inside the same compiled
    graph, so every stride shares one executable. Each of the three jits
    reports its trace to the compile sentinel under ``obs_kind`` and fires
    ``on_trace`` (the serve ledger's compile counter), exactly like
    `serve.entry.jit_entry` — an anytime bucket warms at 3 compiles
    (begin/step/finalize), not 1."""
    if n_total < 1:
        raise ValueError(f"n_total must be >= 1, got {n_total}")
    if not 1 <= stride <= n_total:
        raise ValueError(
            f"stride must be in [1, n_total={n_total}], got {stride}")
    if finalize_fn is None:
        finalize_fn = lambda mean: mean  # noqa: E731

    def _traced(fn, detail):
        def wrapped(*args):
            obs_sentinel.record_trace(obs_kind, detail=f"{name}:{detail}")
            if on_trace is not None:
                on_trace()
            return fn(*args)

        return jax.jit(wrapped)

    def begin_impl(x, y):
        g_shape = jax.eval_shape(sample_fn, x, y, jnp.asarray(0, jnp.int32))
        zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), g_shape)
        b = x.shape[0]
        state = (zeros, jnp.zeros((b,), jnp.float32),
                 jnp.asarray(0, jnp.int32),
                 zeros, jnp.asarray(0, jnp.int32),
                 jnp.zeros((b, 4), jnp.float32))
        return state

    def step_impl(state, x, y):
        acc, m2, count, prev_acc, prev_count, _ = state

        def body(_, carry):
            acc, m2, count = carry
            g = sample_fn(x, y, count)
            # weight-mask past n_total: the tail stride of a non-dividing
            # n keeps the same compiled shape, extra samples are inert
            w = jnp.where(count < n_total, 1.0, 0.0).astype(jnp.float32)
            acc_new = jax.tree_util.tree_map(
                lambda a, b: a + (w * b).astype(a.dtype), acc, g)
            m2 = jnp.where(w > 0.0, m2_update(m2, acc, acc_new, count), m2)
            return acc_new, m2, count + jnp.asarray(w, jnp.int32)

        acc, m2, count = jax.lax.fori_loop(
            0, stride, body, (acc, m2, count))
        conf = conf_stats(acc, m2, count, prev_acc, prev_count)
        # the checkpoint snapshot the NEXT stride's delta measures against
        return (acc, m2, count, acc, count, conf)

    def finalize_impl(state):
        acc, _m2, count, _pa, _pc, conf = state
        scale = 1.0 / jnp.maximum(count.astype(jnp.float32), 1.0)
        mean = jax.tree_util.tree_map(
            lambda a: (a.astype(jnp.float32) * scale).astype(a.dtype), acc)
        return finalize_fn(mean), conf

    return AnytimeEntry(
        _traced(begin_impl, "begin"),
        _traced(step_impl, "step"),
        _traced(finalize_impl, "finalize"),
        n_total=n_total, stride=stride, plateau_tol=plateau_tol, name=name)
