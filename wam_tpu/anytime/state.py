"""Anytime-attribution checkpoint math: variance-derived confidence from
the fused estimator loops' running SUM accumulators.

The round-9 fused loops (`parallel.seq_estimators`) carry a plain sum
accumulator per sample — `acc + g`, scaled once by 1/n at the end — and
that sum IS a bit-equal checkpoint of the final map at any count (the
bit-equal-checkpoint invariant this subsystem is built on). Everything
here is derived WITHOUT touching that accumulator chain:

- **M2 from consecutive sums** (`m2_update`): a Welford-style second
  moment reconstructed from ``(acc_prev, acc_new)`` — the per-sample
  gradient is recovered as ``g ≈ acc_new - acc_prev`` (exact up to one
  float rounding, irrelevant to a variance *estimate*), so the update
  never re-enters the gradient graph and the sum chain stays literally
  the same jitted dispatches as the non-checkpointed path.
- **Confidence vector** (`conf_stats`): per batch-row, one fixed-size
  f32 ``(B, ANYTIME_VEC_SIZE)`` array — the health-vector convention
  (`obs.health`): one more output leaf of a program already being
  fetched, never a second result fetch. Slots:

  ===== ================ ====================================================
  slot  name             meaning
  ===== ================ ====================================================
  0     count            samples accumulated so far
  1     rel_sem          RMS standard error of the mean / RMS of the mean
  2     delta            relative L2 change since the previous checkpoint
                         (1.0 before a previous checkpoint exists)
  3     confidence       1 / (1 + rel_sem + delta), in (0, 1]
  ===== ================ ====================================================

  ``confidence`` folds both signals so a single scalar drives serving
  policy: sampling noise still high (rel_sem) OR the estimate still
  moving between checkpoints (delta) both hold it down; most inputs
  plateau well before n=25 and ride to ~1.

All functions are pure jax and shape-polymorphic over arbitrary gradient
pytrees with a leading batch axis on every leaf (TailedLeaf nodes of the
expansive sharded modes flatten to plain leaves) — callers jit them alone
(`SeqShardedWam`) or inline them into a fused stride graph
(`anytime.entry`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "ANYTIME_VEC_SIZE",
    "SLOT_COUNT",
    "SLOT_REL_SEM",
    "SLOT_DELTA",
    "SLOT_CONFIDENCE",
    "m2_update",
    "conf_stats",
]

ANYTIME_VEC_SIZE = 4
SLOT_COUNT, SLOT_REL_SEM, SLOT_DELTA, SLOT_CONFIDENCE = range(ANYTIME_VEC_SIZE)

_EPS = 1e-12


def _row_sum(a: jax.Array) -> jax.Array:
    """Sum over every non-leading axis -> (B,) float32."""
    return a.astype(jnp.float32).reshape(a.shape[0], -1).sum(axis=1)


def _tree_row_sum(fn, *trees) -> jax.Array:
    """Σ over leaves of per-row reductions: ``fn(*leaves) -> (B,)``."""
    leaves = [jax.tree_util.tree_leaves(t) for t in trees]
    total = None
    for group in zip(*leaves):
        part = fn(*group)
        total = part if total is None else total + part
    return total


def tree_row_elems(tree) -> int:
    """Elements per batch row across the whole tree (static)."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = 1
        for d in leaf.shape[1:]:
            size *= int(d)
        n += size
    return n


def m2_update(m2: jax.Array, acc_prev, acc_new, count_prev) -> jax.Array:
    """One Welford M2 step per batch row, reconstructed from consecutive
    sum accumulators: with ``g = acc_new - acc_prev``, ``mean_prev =
    acc_prev / count_prev`` and ``mean_new = acc_new / (count_prev + 1)``,
    the increment is ``Σ_elems (g - mean_prev)·(g - mean_new)``. The first
    sample (``count_prev == 0``) contributes exactly 0, matching textbook
    Welford; ``m2`` is (B,) float32 and never feeds back into ``acc``."""
    count_prev = jnp.asarray(count_prev, jnp.float32)
    safe_prev = jnp.maximum(count_prev, 1.0)

    def inc(p, n):
        p32 = p.astype(jnp.float32)
        n32 = n.astype(jnp.float32)
        g = n32 - p32
        mean_prev = p32 / safe_prev
        mean_new = n32 / (count_prev + 1.0)
        return _row_sum((g - mean_prev) * (g - mean_new))

    delta = _tree_row_sum(inc, acc_prev, acc_new)
    return m2 + jnp.where(count_prev >= 1.0, delta, 0.0)


def conf_stats(acc, m2: jax.Array, count, prev_acc, prev_count) -> jax.Array:
    """The (B, ANYTIME_VEC_SIZE) confidence vector for the running state
    (module docstring slot table). ``acc``/``prev_acc`` are the current /
    previous-checkpoint SUM accumulator trees (``prev_count == 0`` means
    no previous checkpoint yet -> delta pinned at 1.0, never converged)."""
    count = jnp.asarray(count, jnp.float32)
    prev_count = jnp.asarray(prev_count, jnp.float32)
    n_elems = float(max(tree_row_elems(acc), 1))
    safe_n = jnp.maximum(count, 1.0)
    safe_pn = jnp.maximum(prev_count, 1.0)

    # RMS of the running mean, per row (the normalizer for both signals)
    sq_mean = _tree_row_sum(
        lambda a: _row_sum((a.astype(jnp.float32) / safe_n) ** 2), acc)
    rms = jnp.sqrt(sq_mean / n_elems)

    # RMS standard error of the mean: sqrt(mean elementwise variance / n)
    var = m2 / jnp.maximum(count - 1.0, 1.0) / n_elems
    sem = jnp.sqrt(jnp.maximum(var, 0.0) / safe_n)
    rel_sem = jnp.where(count >= 2.0, sem / (rms + _EPS), 1.0)

    # relative L2 motion since the previous checkpoint
    sq_move = _tree_row_sum(
        lambda a, p: _row_sum(
            (a.astype(jnp.float32) / safe_n
             - p.astype(jnp.float32) / safe_pn) ** 2),
        acc, prev_acc)
    move = jnp.sqrt(sq_move / n_elems)
    delta = jnp.where(prev_count >= 1.0, move / (rms + _EPS), 1.0)

    confidence = 1.0 / (1.0 + rel_sem + delta)
    b = count.shape[0] if count.ndim else m2.shape[0]
    return jnp.stack([
        jnp.broadcast_to(count, (b,)) if count.ndim == 0 else count,
        jnp.broadcast_to(rel_sem, (b,)),
        jnp.broadcast_to(delta, (b,)),
        jnp.broadcast_to(confidence, (b,)),
    ], axis=1)
