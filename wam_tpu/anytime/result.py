"""Result types for anytime attribution serving."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

__all__ = ["AnytimeResult"]


@dataclass(frozen=True)
class AnytimeResult:
    """One served request's best-so-far attribution plus its certainty.

    Futures of an anytime server (`serve.AttributionServer` over an entry
    built by `anytime.entry.make_anytime_entry`) resolve to this instead
    of a bare attribution row: a deadline-closed window delivers the
    running mean at whatever sample count it reached (``complete=False``)
    rather than raising `DeadlineExceededError`, and a converged input
    exits early (``converged=True``) with fewer samples than ``n_total``.

    ``confidence`` is the `anytime.state` scalar in (0, 1]; ``rel_sem``
    and ``delta`` are the two raw signals it folds (relative standard
    error of the mean; relative motion since the previous checkpoint)."""

    attribution: Any
    confidence: float
    n_used: int
    n_total: int
    complete: bool
    converged: bool
    rel_sem: float = 0.0
    delta: float = 0.0

    def meets(self, min_confidence: float) -> bool:
        """Did this result clear a confidence floor (goodput predicate)?"""
        return self.confidence >= float(min_confidence)
