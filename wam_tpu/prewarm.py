"""Cache prewarm CLI — one documented command to pay every cold cost offline.

    python -m wam_tpu.prewarm --config flagship
    python -m wam_tpu.prewarm --config toy --device cpu   # CI smoke

First TPU compiles of the full estimator graph run 20-40 s; a serving
process that pays them on the hot path blows its first requests' deadlines
(VERDICT.md round-5 directive 6). This CLI populates BOTH persistent layers
in one run:

- the **XLA compilation cache** (`config.enable_compilation_cache`,
  ``$WAM_TPU_CACHE_DIR`` or ``~/.cache/wam_tpu/xla``) by compiling and
  executing the config's estimator graph once, at the SAME schedule
  production resolves — the tuned schedule-cache entry when one exists, the
  128-row law otherwise;
- the **schedule cache** (`wam_tpu.tune`, ``~/.cache/wam_tpu/schedules.json``
  + repo-pinned defaults) by loading it before the trace, exactly as
  `AttributionServer.start()` warmup does;
- the **AOT executable cache** (`wam_tpu.pipeline.aot`,
  ``~/.cache/wam_tpu/aot``) by exporting the traced runner under a key
  derived from the schedule-cache key plus the resolved schedule — a later
  process with the same config skips the Python trace entirely
  (``--no-aot`` opts out; the JSON line reports hit/exported/fallback).

A server started afterwards (same config, same caches) deserializes its
bucket compiles in well under a second instead of compiling. Run
``python -m wam_tpu.tune`` first if you want a freshly tuned schedule
rather than the pinned defaults. Prints ONE JSON summary line.

The zero-compile contract this prewarm buys is only as good as the code
it warms: a jit wrapper rebuilt per call or an array-valued default
invalidates the cache key no matter how warm the caches are. The
``retrace-risk`` rule of ``python -m wam_tpu.lint --all`` gates exactly
those patterns statically — keep it green before chasing cold-start
regressions here.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m wam_tpu.prewarm",
        description="Populate the XLA compilation cache and schedule cache.",
    )
    p.add_argument("--config", default="flagship",
                   help="workload preset: flagship | toy | mu2d "
                        "(wam_tpu.tune.workloads)")
    p.add_argument("--device", default="auto", help="backend: auto | tpu | cpu")
    p.add_argument("--batch", type=int, default=None,
                   help="override the preset's batch size")
    p.add_argument("--no-aot", action="store_true",
                   help="skip the AOT executable cache (XLA + schedule "
                        "caches are still warmed)")
    p.add_argument("--manifest", default=None, metavar="PATH",
                   help="also write the JSON summary (including the "
                        "machine-readable 'warmed' block) to this file — "
                        "the handoff `python -m wam_tpu.registry publish "
                        "--from-prewarm` consumes")
    args = p.parse_args(argv)

    from wam_tpu.config import (
        enable_compilation_cache,
        ensure_usable_backend,
        select_backend,
    )

    # Pin the backend BEFORE first jax use (the axon plugin force-selects
    # itself and can hang when its pool is unreachable — verify-skill gotcha)
    select_backend(args.device)
    if args.device in ("auto", "tpu"):
        ensure_usable_backend(timeout_s=180.0)
    xla_dir = enable_compilation_cache()

    import jax

    from wam_tpu.core.estimators import resolve_sample_chunk
    from wam_tpu.profiling import device_sync
    from wam_tpu.tune import load_schedule_cache, lookup_schedule, schedule_key
    from wam_tpu.tune.autotuner import Candidate
    from wam_tpu.tune.workloads import get_workload

    # the same pre-trace load serve warmup performs
    cache = load_schedule_cache()

    overrides = {} if args.batch is None else {"batch": args.batch}
    wl = get_workload(args.config, **overrides)

    # Resolve the schedule PRODUCTION will run (tuned entry > law) and bake
    # it into one runner — its trace is byte-identical to what serve warmup
    # / bench.py will request, so the XLA cache hit is guaranteed.
    ent = lookup_schedule(wl.workload, wl.shape, wl.batch, wl.dtype) or {}
    chunk = resolve_sample_chunk("auto", wl.batch, 25, workload=wl.workload,
                                 shape=wl.shape, dtype=wl.dtype)
    cand = Candidate(sample_chunk=chunk,
                     stream_noise=ent.get("stream_noise"),
                     synth_impl=ent.get("synth_impl"),
                     fan_cap=ent.get("fan_cap", 128))
    fn, wargs = wl.build(cand)
    # wl.build applied the candidate's synth knob; record what it RESOLVES
    # to on this backend — the AOT key must pin the baked synthesis path
    from wam_tpu.wavelets.transform import resolved_synth2_impl

    synth = resolved_synth2_impl()

    # Third persistent layer: export the runner's executable so the NEXT
    # process skips the Python trace too. The key extends the schedule-cache
    # key with the resolved schedule — a retune that changes the chunk or
    # stream mode changes the key and re-exports. Safe to key on the preset
    # alone because workload presets init their models from fixed seeds
    # (process-stable closed-over params — the aot.py keying contract).
    from wam_tpu.pipeline import aot as aot_cache

    runner, aot_status, aot_key = fn, "disabled", None
    if not args.no_aot and not aot_cache._disabled():
        aot_key = "|".join((
            "prewarm",
            schedule_key(wl.workload, wl.shape, wl.batch, wl.dtype),
            f"chunk{chunk}",
            f"stream{ent.get('stream_noise')}",
            f"synth{synth}",
            aot_cache.aval_signature(wargs),
        ))
        hit = aot_cache.load_aot(aot_key) is not None
        runner = aot_cache.cached_jit(fn, wargs, aot_key)
        if hit:
            aot_status = "hit"
        else:
            aot_status = ("exported"
                          if aot_cache.load_aot(aot_key) is not None
                          else "fallback")

    t0 = time.perf_counter()
    device_sync(runner(*wargs))  # compile (or cache-deserialize) + one run
    warm_s = time.perf_counter() - t0

    # machine-readable manifest of exactly what this run warmed — the
    # `registry publish --from-prewarm` handoff, so publish snapshots the
    # keys this run touched instead of re-walking the cache blind
    from wam_tpu.registry.bundle import platform_fingerprint
    from wam_tpu.tune.cache import SCHEDULE_CACHE_VERSION

    summary = {
        "config": wl.name,
        "backend": jax.default_backend(),
        "batch": wl.batch,
        "sample_chunk": chunk,
        "stream_noise": ent.get("stream_noise"),
        "synth_impl": synth,
        "schedule_entries": len(cache.entries),
        "schedule_stale_files": cache.stale_files,
        "xla_cache_dir": xla_dir,
        "aot": aot_status,
        "aot_cache_dir": aot_cache.default_aot_dir(),
        "warm_s": round(warm_s, 3),
        "warmed": {
            "bucket_keys": [
                schedule_key(wl.workload, wl.shape, wl.batch, wl.dtype)],
            "aot_keys": [aot_key] if aot_key is not None else [],
            "schedule_version": SCHEDULE_CACHE_VERSION,
            "platform": platform_fingerprint(),
        },
    }
    line = json.dumps(summary)
    print(line)
    if args.manifest:
        with open(args.manifest, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
