"""Fork analytics layer — parity with the fork's root `utils.py` and the
`compare_iou_models.ipynb` experiment helpers: diagonal-block extraction,
cross-level pixel-wise variance ranking, per-level attribution shares, and
cross-wavelet IoU of top-p% attribution masks (the metrics behind
`results/iou.csv` and `results/results_variance.csv`).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = [
    "get_explanation_for_image",
    "get_diagonal",
    "get_mean_pixelwise_variance",
    "rank_images",
    "get_gradients_attribution_on_levels",
    "get_multiple_grad_attr",
    "get_mean_across_images",
    "top_percentage_mask",
    "iou",
    "mean_pairwise_iou",
    "cross_wavelet_iou",
    "cross_wavelet_reprojection_maps",
    "iou_from_reprojection_maps",
    "reprojection_map",
]


def get_explanation_for_image(image, model_fn, explainer, preprocess) -> np.ndarray:
    """Single-image explanation at the model's argmax class
    (`utils.py:8-19`). ``preprocess`` maps the raw image to a (1, C, H, W)
    tensor."""
    x = preprocess(image)
    y = int(np.asarray(model_fn(x)).argmax())
    return np.asarray(explainer(x, [y])).squeeze()


def get_diagonal(grad_wam: np.ndarray, J: int) -> dict:
    """Diagonal blocks level_0 (finest) .. level_{J-1} + approx
    (`utils.py:23-42`)."""
    grad_wam = np.asarray(grad_wam)
    H, W = grad_wam.shape
    assert H == W, "grad_wam must be square"
    out = {}
    for j in range(J):
        s, e = H // (2 ** (j + 1)), H // (2**j)
        out[f"level_{j}"] = grad_wam[s:e, s:e]
    a = H // (2**J)
    out["approx"] = grad_wam[:a, :a]
    return out


def _zoom_linear_np(a: np.ndarray, target: int) -> np.ndarray:
    """The reference's exact resize primitive for the variance experiment:
    `scipy.ndimage.zoom(lvl, target/n, order=1)` then crop (`utils.py:74-78`).
    zoom's origin-aligned sampling differs from half-pixel bilinear
    (cv2/jax.image) at the edges, so matching the published
    `results_variance.csv` numbers requires zoom itself."""
    from scipy.ndimage import zoom

    scale = target / a.shape[0]
    return zoom(np.asarray(a, dtype=np.float64), scale, order=1)[:target, :target]


def get_mean_pixelwise_variance(grad_wam: np.ndarray, J: int, size: str = "maximal"):
    """Pixel-wise variance across detail levels, resized to the largest or
    smallest level (`utils.py:45-85`). Returns (mean, variance_map)."""
    diags = get_diagonal(grad_wam, J)
    details = [diags[f"level_{j}"] for j in range(J)]
    sizes = [d.shape[0] for d in details]
    if size == "maximal":
        target = max(sizes)
    elif size == "minimal":
        target = min(sizes)
    else:
        raise ValueError("size must be 'maximal' or 'minimal'")
    stack = np.stack([_zoom_linear_np(d, target) for d in details])
    var_map = stack.var(axis=0)
    return float(var_map.mean()), var_map


def rank_images(explanations: Sequence[np.ndarray], J: int, size: str = "maximal"):
    """Sort images by cross-level variance, descending (`utils.py:88-110`)."""
    ranking = [
        {"image_index": i, "mean_pixelwise_variance": get_mean_pixelwise_variance(e, J, size)[0]}
        for i, e in enumerate(explanations)
    ]
    ranking.sort(key=lambda r: r["mean_pixelwise_variance"], reverse=True)
    return ranking


def get_gradients_attribution_on_levels(explanations: Sequence[np.ndarray], J: int):
    """Normalized per-level attribution mass Σ|grad| per diagonal block
    (`utils.py:112-134`; method note `results/README.md:1-4`)."""
    out = []
    for expl in explanations:
        sums = np.array([np.abs(v).sum() for v in get_diagonal(expl, J).values()])
        out.append(sums / sums.sum())
    return out


def get_multiple_grad_attr(explanations_per_model: Sequence[Sequence[np.ndarray]], J: int):
    """Per-(model, image) level shares (`utils.py:136-141`)."""
    return [get_gradients_attribution_on_levels(expls, J) for expls in explanations_per_model]


def get_mean_across_images(all_grads):
    """Mean level share per model (`utils.py:143-151`)."""
    return [np.asarray(g).mean(axis=0) for g in all_grads]


# -- cross-wavelet IoU (compare_iou_models.ipynb cells 2, 5-6) --------------


def top_percentage_mask(a: np.ndarray, p: float) -> np.ndarray:
    """Boolean mask of the top-p fraction of values."""
    flat = np.asarray(a).ravel()
    k = max(1, int(len(flat) * p))
    thr = np.sort(flat)[::-1][k - 1]
    return np.asarray(a) >= thr


def iou(m1: np.ndarray, m2: np.ndarray) -> float:
    union = np.logical_or(m1, m2).sum()
    if union == 0:
        return 0.0
    return float(np.logical_and(m1, m2).sum() / union)


def mean_pairwise_iou(masks: Sequence[np.ndarray]) -> float:
    vals = [iou(masks[i], masks[j]) for i in range(len(masks)) for j in range(i + 1, len(masks))]
    return float(np.mean(vals)) if vals else 0.0


def reprojection_map(explanation: np.ndarray, J: int) -> np.ndarray:
    """Mosaic → mean over per-level reprojections → single pixel map
    (`get_grad_reprojection`, notebook cell 2)."""
    from wam_tpu.ops.packing2d import reproject_mosaic

    expl = jnp.asarray(explanation)[None]
    maps = reproject_mosaic(expl, J)
    return np.asarray(maps.mean(axis=1)[0])


def cross_wavelet_reprojection_maps(
    image,
    make_explainer: Callable[[str], Callable],
    wavelets: Sequence[str],
    model_fn,
    preprocess,
    J: int,
) -> list[np.ndarray]:
    """One reprojection pixel map per wavelet for `image` — the expensive,
    p-independent half of the cross-wavelet IoU experiment. Following the
    reference exactly, the mosaic is HARD-CROPPED to the input resolution
    BEFORE reprojection (`lib/wam_2D.py:448` crops the gradient path to 224
    and reprojects at 224) — longer filters grow the mosaic past the image
    size by boundary extension, and crop-first vs crop-last changes every
    block boundary, so matching `results/iou.csv` requires this order
    (pinned cross-framework by
    `tests/test_oracle_torch.py::test_iou_experiment_pipeline_matches_torch`)."""
    x = preprocess(image)  # (1, C, H, W) contract
    hw = np.asarray(x).shape[-2:]
    y = int(np.asarray(model_fn(x)).argmax())  # class is wavelet-independent
    maps = []
    for wave in wavelets:
        expl = np.asarray(make_explainer(wave)(x, [y])).squeeze()
        maps.append(reprojection_map(expl[: hw[0], : hw[1]], J))
    return maps


def iou_from_reprojection_maps(maps: Sequence[np.ndarray], p: float) -> float:
    """Mean pairwise IoU of top-p% masks of precomputed reprojection maps —
    the cheap half; sweep `p` over the same maps without re-explaining."""
    return mean_pairwise_iou([top_percentage_mask(m, p) for m in maps])


def cross_wavelet_iou(
    image,
    make_explainer: Callable[[str], Callable],
    wavelets: Sequence[str],
    p: float,
    model_fn,
    preprocess,
    J: int,
) -> float:
    """Mean pairwise IoU of top-p% reprojection masks across wavelets
    (`get_iou_between_wavelets`, notebook cell 5)."""
    maps = cross_wavelet_reprojection_maps(
        image, make_explainer, wavelets, model_fn, preprocess, J
    )
    return iou_from_reprojection_maps(maps, p)
