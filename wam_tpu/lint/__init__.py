"""`wam_tpu.lint` — rule-based static analysis for TPU hot-path
invariants.

The invariants this repo's performance and correctness rest on are
mostly *invisible to the type system*: no host syncs inside traced
bodies, jit wrappers constructed once, donated buffers never re-read,
`_GUARDED_BY` attributes mutated under their lock, bf16 contractions
accumulating in f32, metric/ledger names matching the declared schema.
Each is cheap to state as an AST rule and expensive to discover as a
production incident — so they live here, as a pure-stdlib AST scan:
the scanned code is never imported or executed, so the lint runs on
broken trees and needs no device.

Layout:
  core.py       loader, traced-fn detection, findings, pragmas, baseline
  registry.py   Rule base class + @register
  rules/        one module per rule (host_sync, retrace, donation,
                locks, precision[+schema-drift])
  emitters.py   text / json / sarif
  knobs.py      WAM_TPU_* env-knob audit (--knobs)
  compat.py     byte-identical legacy check_host_syncs output
  baseline.json ratcheted pre-existing findings (counts only decrease)

CLI: ``python -m wam_tpu.lint --all`` (see __main__.py). Suppress a
deliberate finding inline with ``# wamlint: disable=<rule-id>`` on (or
one line above) the flagged line, with a justification comment.
"""

from wam_tpu.lint.core import (DEFAULT_BASELINE, Finding, LintContext,
                               LintResult, SourceFile, apply_baseline,
                               load_baseline, load_files, repo_root,
                               run_rules, write_baseline)
from wam_tpu.lint.registry import Rule, all_rules, get_rule, rule_ids

__all__ = [
    "Finding", "SourceFile", "LintContext", "LintResult",
    "Rule", "all_rules", "get_rule", "rule_ids",
    "load_files", "repo_root", "run_rules",
    "load_baseline", "apply_baseline", "write_baseline",
    "DEFAULT_BASELINE",
]
