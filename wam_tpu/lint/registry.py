"""Rule registry: rules self-register at import time; the CLI and tests
resolve them by id. Keeping registration declarative (a decorator on the
class) means adding a rule is: write the class, import its module from
`wam_tpu.lint.rules`, done — the CLI, `--list-rules`, scope union, and
the SARIF rule catalog all pick it up from here."""

from __future__ import annotations

from wam_tpu.lint.core import Finding, LintContext, SourceFile  # noqa: F401

__all__ = ["Rule", "register", "all_rules", "get_rule", "rule_ids"]

_REGISTRY: dict[str, type] = {}


class Rule:
    """Base class for one static-analysis rule.

    Class attributes:
      id          stable kebab-case identifier (pragmas/baseline key on it)
      severity    "error" | "warning"
      scope       repo-relative path prefixes this rule runs on by default
                  (None = every file the driver was pointed at)
      description one-liner for --list-rules and the SARIF rule catalog
    """

    id: str = ""
    severity: str = "error"
    scope: tuple[str, ...] | None = None
    description: str = ""

    def __init__(self, config: dict | None = None):
        self.config = dict(config or {})

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        raise NotImplementedError

    def finding(self, line: int, message: str) -> Finding:
        # path/abspath are filled in by the driver (core.run_rules)
        return Finding(rule=self.id, severity=self.severity, path="",
                       line=line, message=message)


def register(cls: type) -> type:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> list[type]:
    import wam_tpu.lint.rules  # noqa: F401 - triggers registration

    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def rule_ids() -> list[str]:
    return [c.id for c in all_rules()]


def get_rule(rule_id: str) -> type:
    import wam_tpu.lint.rules  # noqa: F401 - triggers registration

    try:
        return _REGISTRY[rule_id]
    except KeyError:
        raise KeyError(
            f"unknown rule {rule_id!r}; known: {', '.join(sorted(_REGISTRY))}"
        ) from None
