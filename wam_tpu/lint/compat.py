"""Legacy-CLI compatibility: the `scripts/check_host_syncs.py` contract.

The original script printed absolute-path findings in sorted-file order
with syntax errors interleaved at the file's position, a
``check_host_syncs: N files, M findings`` summary, and exited 1 on any
finding. CI jobs and the verify skill grep that output, so the shim must
be byte-identical — which is why this module drives the `host-sync` rule
directly (in the legacy order, with NO pragma or baseline filtering)
instead of going through the normal `run_rules` driver: parity beats
features for a deprecated entry point.

tests/test_lint.py pins this by diffing the shim's output against the
modern ``python -m wam_tpu.lint --rules host-sync`` findings on the
live tree.
"""

from __future__ import annotations

import sys

from wam_tpu.lint.core import iter_traced_functions, load_files, repo_root
from wam_tpu.lint.rules.host_sync import LEGACY_SCOPE, sync_messages


def legacy_host_sync_lines(argv=None) -> tuple[list[str], int]:
    """(output lines sans summary, file count) in the legacy script's
    exact format and order."""
    args = list(argv) if argv else list(LEGACY_SCOPE)
    files = load_files(args, root=repo_root())
    findings: list[str] = []
    for src in files:
        if src.error is not None:
            findings.append(f"{src.path}: syntax error: {src.error}")
            continue
        for fn in iter_traced_functions(src.tree):
            for line, msg in sync_messages(fn):
                findings.append(f"{src.path}:{line}: {msg}")
    return findings, len(files)


def legacy_host_sync_main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    findings, nfiles = legacy_host_sync_lines(argv)
    for line in findings:
        print(line)
    print(f"check_host_syncs: {nfiles} files, {len(findings)} findings")
    return 1 if findings else 0
