"""Rule modules. Importing this package registers every rule with
`wam_tpu.lint.registry` (each module's classes carry ``@register``)."""

from wam_tpu.lint.rules import donation as _donation  # noqa: F401
from wam_tpu.lint.rules import host_sync as _host_sync  # noqa: F401
from wam_tpu.lint.rules import locks as _locks  # noqa: F401
from wam_tpu.lint.rules import precision as _precision  # noqa: F401
from wam_tpu.lint.rules import retrace as _retrace  # noqa: F401
