"""retrace-risk: jit wrappers whose construction pattern defeats the
compile-artifact registry's zero-compile contract.

Three shapes:

1. A jit-family wrapper (`jax.jit` / `cached_jit` / `jit_entry` /
   `donating_jit` / `cached_entry` / `pjit`) constructed inside a loop —
   every iteration builds a fresh wrapper with an empty jit cache, so
   every iteration retraces and the AOT/registry hydration can never hit.
2. The same wrapper constructed AND invoked in one expression inside a
   function body (``jax.jit(f)(x)``): the wrapper is garbage after the
   call, so each call of the enclosing function retraces.
3. An array-valued default argument (`jnp.zeros(...)`, `np.array(...)`,
   ...) on a function that jax traces: the default is captured into the
   jitted closure; arrays are unhashable / compared by id, so the jit
   cache misses per construction and the "same" entry silently recompiles.

Module-level one-shot constructions are fine (they run once per process)
and are not flagged.
"""

from __future__ import annotations

import ast

from wam_tpu.lint.core import (Finding, LintContext, SourceFile,
                               collect_traced_names, tail_name)
from wam_tpu.lint.registry import Rule, register

# wrapper constructors: a call to one of these BUILDS a compiled-callable
# wrapper (vs. invoking one)
JIT_WRAPPERS = {"jit", "pjit", "cached_jit", "cached_entry", "jit_entry",
                "donating_jit"}

ARRAY_CTORS = {"array", "asarray", "zeros", "ones", "full", "arange",
               "linspace", "eye"}
ARRAY_MODULES = {"np", "numpy", "onp", "jnp"}

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_wrapper_construction(node: ast.Call) -> bool:
    return tail_name(node.func) in JIT_WRAPPERS


def _is_array_default(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and tail_name(node.func) in ARRAY_CTORS
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ARRAY_MODULES)


@register
class RetraceRiskRule(Rule):
    id = "retrace-risk"
    severity = "error"
    scope = ("wam_tpu",)
    description = ("jit wrappers constructed per loop iteration / per call, "
                   "or array-valued defaults captured into jitted closures")

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        self._visit(src.tree, in_loop=False, in_func=False, out=out)
        traced = collect_traced_names(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            decorated = any(
                tail_name(d.func if isinstance(d, ast.Call) else d)
                in JIT_WRAPPERS for d in node.decorator_list)
            if node.name not in traced and not decorated:
                continue
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if _is_array_default(d):
                    out.append(self.finding(
                        d.lineno,
                        f"array-valued default argument on traced function "
                        f"'{node.name}' is captured into the jitted closure "
                        "(unhashable default -> jit cache miss per "
                        "construction)"))
        return out

    def _visit(self, node: ast.AST, in_loop: bool, in_func: bool, out) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(child, _LOOPS)
            child_in_func = in_func or isinstance(child, _FUNCS)
            if isinstance(child, ast.Call):
                if _is_wrapper_construction(child) and in_loop:
                    out.append(self.finding(
                        child.lineno,
                        f"{tail_name(child.func)}(...) constructed inside a "
                        "loop: every iteration rebuilds the wrapper and "
                        "retraces (hoist it, or cache by shape)"))
                elif (isinstance(child.func, ast.Call)
                      and _is_wrapper_construction(child.func) and in_func
                      and not in_loop):  # in-loop: the inner call reports
                    out.append(self.finding(
                        child.lineno,
                        f"{tail_name(child.func.func)}(f)(...) constructed "
                        "and invoked in one expression inside a function "
                        "body: the wrapper (and its jit cache) is discarded "
                        "after the call -> retrace per call"))
            self._visit(child, child_in_loop, child_in_func, out)
