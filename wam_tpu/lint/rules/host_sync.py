"""host-sync: device→host transfers inside traced (jitted/vmapped) code.

The port of ``scripts/check_host_syncs.py`` (which is now a shim over
this rule). `np.asarray(...)`, `.item()`, `float(...)`/`int(...)` on a
traced value force a device→host transfer; inside a function jax traces
they either fail at trace time or — in shapes that happen to be
concrete — silently sync the device per call. `jax.device_get` /
`device_fetch` inside a fan step would break the fan engine's
one-fetch-per-metric contract, and wall-clock reads freeze into
trace-time constants.

Finding messages are byte-identical to the legacy script's so the
`scripts/check_host_syncs.py` shim keeps its output contract
(tests/test_lint.py pins the parity on the live tree).
"""

from __future__ import annotations

import ast

from wam_tpu.lint.core import (Finding, LintContext, SourceFile,
                               iter_traced_functions, tail_name)
from wam_tpu.lint.registry import Rule, register

# the curated hot-path scope inherited from the legacy script: every
# directory whose traced bodies sit on a serving/eval/bench hot path
LEGACY_SCOPE = (
    "wam_tpu/core", "wam_tpu/evalsuite", "wam_tpu/serve",
    "wam_tpu/pipeline", "wam_tpu/wavelets", "wam_tpu/obs",
    "wam_tpu/testing", "wam_tpu/registry", "wam_tpu/pod",
    "wam_tpu/xattr",
    "wam_tpu/parallel/mesh.py", "wam_tpu/parallel/multihost.py",
    "wam_tpu/parallel/halo.py", "wam_tpu/parallel/halo_modes.py",
    "wam_tpu/parallel/seq_estimators.py",
)

# wall-clock reads that become trace-time constants inside a jitted body
CLOCK_CALLS = {"time", "perf_counter", "monotonic", "monotonic_ns",
               "perf_counter_ns", "time_ns"}

NP_MODULES = {"np", "numpy", "onp"}


def sync_messages(fn: ast.AST) -> list[tuple[int, str]]:
    """(line, legacy message) pairs for host-sync calls inside ``fn`` —
    kept message-for-message identical to check_host_syncs.py."""
    found: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr == "asarray"
                and isinstance(f.value, ast.Name) and f.value.id in NP_MODULES):
            found.append((node.lineno, "np.asarray() in traced function"))
        elif isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
            found.append((node.lineno, ".item() in traced function"))
        elif (isinstance(f, ast.Name) and f.id in ("float", "int")
              and len(node.args) == 1
              and isinstance(node.args[0], (ast.Name, ast.Attribute, ast.Call))):
            found.append((node.lineno,
                          f"{f.id}() on a value in traced function"))
        elif tail_name(f) in ("device_get", "device_fetch"):
            found.append((node.lineno,
                          f"{tail_name(f)}() in traced function "
                          "(fetches belong in run_fan, after the fan step)"))
        elif (isinstance(f, ast.Attribute) and f.attr in CLOCK_CALLS
              and isinstance(f.value, ast.Name) and f.value.id == "time"):
            found.append((node.lineno,
                          f"time.{f.attr}() in traced function "
                          "(freezes to a trace-time constant; time spans "
                          "outside the jitted body)"))
    return found


@register
class HostSyncRule(Rule):
    id = "host-sync"
    severity = "error"
    scope = LEGACY_SCOPE
    description = ("host-sync calls (np.asarray/.item()/float()/device_get/"
                   "wall-clock reads) inside traced functions")

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for fn in iter_traced_functions(src.tree):
            for line, msg in sync_messages(fn):
                out.append(self.finding(line, msg))
        return out
