"""lock-discipline: `_GUARDED_BY`-declared attributes mutated outside
their lock.

Classes opt in by declaring a class-level map from attribute name to the
lock attribute that guards it::

    class AttributionServer:
        _GUARDED_BY = {"_queues": "_cond", "_started": "_cond"}

The rule then checks every method of the class: a mutation of
``self._queues`` (assignment, augmented assignment, subscript store,
or a mutating method call like ``.append(...)``) must be lexically
enclosed in ``with self._cond:`` (or ``with self._cond: ...`` via an
alias bound from ``self._cond`` is NOT recognized — the convention is
the direct form, which is what the serve/pod code uses).

Deliberately lexical, not flow-sensitive: it catches the real bug class
we have hit (a `_started = True` slipped outside the lock during a
refactor) without needing alias analysis. ``__init__`` is exempt —
construction happens-before any concurrent access. Nested functions
reset the held-lock set: a closure may run on another thread after the
``with`` block exits.
"""

from __future__ import annotations

import ast

from wam_tpu.lint.core import Finding, LintContext, SourceFile
from wam_tpu.lint.registry import Rule, register

# method names that mutate their receiver in place
MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
            "clear", "update", "add", "discard", "setdefault", "appendleft"}

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _guarded_by_map(cls: ast.ClassDef) -> dict[str, str] | None:
    """The literal `_GUARDED_BY` dict of a class body, or None."""
    for stmt in cls.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
        if not any(isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                   for t in targets):
            continue
        value = stmt.value
        if not isinstance(value, ast.Dict):
            return None
        out: dict[str, str] = {}
        for k, v in zip(value.keys, value.values):
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                out[k.value] = v.value
        return out
    return None


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a `self.x` expression, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _MethodScan:
    """Walk one method body tracking which `self.<lock>` locks are
    lexically held; report guarded-attr mutations made without them."""

    def __init__(self, rule: Rule, guarded: dict[str, str], method: str):
        self.rule = rule
        self.guarded = guarded
        self.method = method
        self.findings: list[Finding] = []

    def scan(self, body: list[ast.stmt]) -> list[Finding]:
        for stmt in body:
            self._visit(stmt, held=frozenset())
        return self.findings

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            for item in node.items:
                lock = _self_attr(item.context_expr)
                if lock is not None:
                    held = held | {lock}
            for stmt in node.body:
                self._visit(stmt, held)
            return
        if isinstance(node, _FUNCS):
            # closures may run on another thread, after the with-block
            body = node.body if not isinstance(node, ast.Lambda) else [node.body]
            for stmt in body:
                self._visit(stmt, frozenset())
            return
        self._check(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _check(self, node: ast.AST, held: frozenset) -> None:
        attr = None
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                a = _self_attr(t)
                if a is None and isinstance(t, ast.Subscript):
                    a = _self_attr(t.value)  # self._queues[k] = v
                if a is not None and a in self.guarded:
                    attr = a
                    break
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATORS:
                a = _self_attr(node.func.value)
                if a is not None and a in self.guarded:
                    attr = a
        elif isinstance(node, (ast.Delete,)):
            for t in node.targets:
                a = _self_attr(t)
                if a is None and isinstance(t, ast.Subscript):
                    a = _self_attr(t.value)
                if a is not None and a in self.guarded:
                    attr = a
                    break
        if attr is None:
            return
        lock = self.guarded[attr]
        if lock not in held:
            self.findings.append(self.rule.finding(
                node.lineno,
                f"self.{attr} mutated in {self.method}() without holding "
                f"self.{lock} (declared in _GUARDED_BY)"))


@register
class LockDisciplineRule(Rule):
    id = "lock-discipline"
    severity = "error"
    scope = ("wam_tpu",)
    description = ("_GUARDED_BY-declared attributes mutated outside "
                   "`with self.<lock>:` blocks")

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guarded = _guarded_by_map(node)
            if not guarded:
                continue
            for stmt in node.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if stmt.name == "__init__":
                    continue  # construction happens-before concurrency
                scan = _MethodScan(self, guarded, stmt.name)
                out.extend(scan.scan(stmt.body))
        return out
