"""donation-safety: use-after-donate on buffers handed to donating calls.

Donation consumes the caller's buffer (`pipeline.donation`): after a
donating call the donated `jax.Array` is deleted and any later read
raises (TPU) or silently aliases (backends that ignore donation). The
rule tracks, per function scope:

1. names bound to donating wrappers — ``w = donating_jit(f)``,
   ``w = jax.jit(f, donate_argnums=(0,))``, ``w = jit_entry(impl, ...)``
   (the serving entry donates argument 0 on TPU by policy);
2. calls through those names (or a construct-and-call in one
   expression): the plain-Name arguments at the donated positions are
   marked *donated* at that source position;
3. any later read of a donated name in the same scope -> finding.
   Re-assigning the name clears the mark (a fresh buffer is fine), and
   arguments wrapped in `donation_safe(...)` are never marked (that IS
   the sanctioned way to keep a handle alive across a donating call).

Scope-local and position-based by design: cross-function flows and
loop-carried reads need runtime information a static pass does not have
— those stay the job of the donation tests.
"""

from __future__ import annotations

import ast

from wam_tpu.lint.core import Finding, LintContext, SourceFile, tail_name
from wam_tpu.lint.registry import Rule, register

# constructors that ALWAYS donate (by repo policy) -> donated positions
ALWAYS_DONATING = {"donating_jit": (0,), "jit_entry": (0,)}


def _donate_positions(call: ast.Call):
    """Donated arg positions for a wrapper construction, or None when the
    construction does not donate. `jax.jit` donates only with a non-empty
    ``donate_argnums``; literal positions are honored, non-literal ones
    conservatively mean "position 0"."""
    name = tail_name(call.func)
    if name in ALWAYS_DONATING:
        return ALWAYS_DONATING[name]
    if name in ("jit", "pjit"):
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    elts = [e.value for e in v.elts
                            if isinstance(e, ast.Constant)]
                    return tuple(elts) if elts else None  # () donates nothing
                return (0,)  # dynamic donate_argnums: assume arg 0
    return None


class _ScopeScan(ast.NodeVisitor):
    """Collect, in (line, col) order: wrapper bindings, donation events,
    name stores, and name loads for one function scope (nested defs are
    separate scopes and skipped here)."""

    def __init__(self):
        self.wrappers: dict[str, tuple] = {}  # name -> donated positions
        self.events: list[tuple] = []  # (pos, kind, payload)
        self._donated_arg_ids: set[int] = set()
        self._moved_store_ids: set[int] = set()
        self._depth = 0

    def visit_FunctionDef(self, node):  # nested scope: not ours
        if self._depth == 0:
            self._depth += 1
            self.generic_visit(node)
            self._depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call):
            pos = _donate_positions(node.value)
            if pos is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.wrappers[t.id] = pos
        # the store takes effect AFTER the RHS evaluates: position target
        # stores at the end of the statement so `x = g(x)` (donate + rebind
        # in one statement) is donate-then-clear, not clear-then-donate
        end = (node.end_lineno or node.lineno, 1 << 30)
        for t in node.targets:
            if isinstance(t, ast.Name):
                self._moved_store_ids.add(id(t))
                self.events.append((end, "store", t.id))
        self.generic_visit(node)

    def visit_Call(self, node):
        donated_pos = None
        callee = None
        if isinstance(node.func, ast.Name) and node.func.id in self.wrappers:
            donated_pos = self.wrappers[node.func.id]
            callee = node.func.id
        elif isinstance(node.func, ast.Call):
            donated_pos = _donate_positions(node.func)
            callee = tail_name(node.func.func)
        if donated_pos is not None:
            for i in donated_pos:
                if i < len(node.args) and isinstance(node.args[i], ast.Name):
                    arg = node.args[i]
                    self._donated_arg_ids.add(id(arg))
                    self.events.append(((node.lineno, node.col_offset),
                                        "donate", (arg.id, callee)))
        self.generic_visit(node)

    def visit_Name(self, node):
        pos = (node.lineno, node.col_offset)
        if isinstance(node.ctx, ast.Store):
            if id(node) not in self._moved_store_ids:
                self.events.append((pos, "store", node.id))
        elif isinstance(node.ctx, ast.Load) and id(node) not in self._donated_arg_ids:
            self.events.append((pos, "load", node.id))
        self.generic_visit(node)


@register
class DonationSafetyRule(Rule):
    id = "donation-safety"
    severity = "error"
    scope = ("wam_tpu",)
    description = ("variables read after being passed to a donating call "
                   "(donating_jit / donate_argnums / jit_entry)")

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan = _ScopeScan()
            scan._depth = 1  # we're already inside `node`
            for stmt in node.body:
                scan.visit(stmt)
            donated: dict[str, str] = {}  # name -> callee it was donated to
            for _pos, kind, payload in sorted(scan.events,
                                              key=lambda e: e[0]):
                if kind == "donate":
                    name, callee = payload
                    donated[name] = callee or "a donating call"
                elif kind == "store":
                    donated.pop(payload, None)
                elif kind == "load" and payload in donated:
                    out.append(self.finding(
                        _pos[0],
                        f"'{payload}' read after being donated to "
                        f"{donated[payload]}() — the buffer is deleted on "
                        "TPU; device-copy it first (pipeline.donation"
                        ".donation_safe) or rebind the name"))
                    donated.pop(payload)  # one report per donation
        return out
