"""precision-flow + schema-drift: numeric and observability contracts.

**precision-flow** — a bfloat16 cast that reaches a contraction
(`dot` / `matmul` / `einsum` / `tensordot` / `dot_general` /
`conv_general_dilated`) without ``preferred_element_type=jnp.float32``
accumulates in bf16 on the MXU: ~8 bits of mantissa across a K-deep
reduction, which is exactly the silent-quality-cliff the wavelet
kernels guard against (see wavelets/nhwc.py). The rule taints names
assigned from a bf16 cast (``x = x.astype(jnp.bfloat16)``,
``dtype=jnp.bfloat16``) or from the policy casting shim
(``x = compute_cast(x, dtype)`` with a non-f32 dtype — round 17's
boundary casts), clears the taint on any other rebind, and
flags contraction calls fed a tainted name — or an inline bf16 cast —
when the call has no ``preferred_element_type`` keyword. ``a @ b`` on
a tainted name is flagged too (operator form can't request f32
accumulation at all).

**schema-drift** — metric instruments and ledger row types are an
external contract (dashboards, ledger readers). Every
``registry.counter/gauge/histogram("wam_tpu_...")`` name and every
``{"metric": "<row_type>", ...}`` ledger row literal must appear in
the declared registry `wam_tpu/obs/schema.py`; a literal that isn't
declared is drift — either a typo or a schema change that skipped the
registry (and therefore the dashboards).
"""

from __future__ import annotations

import ast
import os

from wam_tpu.lint.core import Finding, LintContext, SourceFile, tail_name
from wam_tpu.lint.registry import Rule, register

CONTRACTIONS = {"dot", "matmul", "einsum", "tensordot", "dot_general",
                "conv_general_dilated"}

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_bf16_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "bfloat16":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "bfloat16":
        return True
    return False


def _is_f32_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value in ("float32", "f32"):
        return True
    if isinstance(node, ast.Attribute) and node.attr == "float32":
        return True
    return False


def _cast_dtype(expr: ast.AST) -> str | None:
    """'bf16' / 'f32' / None for the *outermost* cast in an expression:
    ``<x>.astype(<dtype>)``, a call carrying ``dtype=<dtype>``, or the
    policy casting shim ``compute_cast(x, <policy dtype>)``
    (`wam_tpu.config.compute_cast` — its dtype is usually a runtime
    policy value that may resolve to bf16/fp8, so the shim is treated as
    a low-precision taint source unless its dtype argument is statically
    f32/None)."""
    if not isinstance(expr, ast.Call):
        return None
    dtype_nodes = []
    if (isinstance(expr.func, ast.Attribute) and expr.func.attr == "astype"
            and expr.args):
        dtype_nodes.append(expr.args[0])
    dtype_nodes.extend(kw.value for kw in expr.keywords if kw.arg == "dtype")
    if tail_name(expr.func) == "compute_cast":
        d = expr.args[1] if len(expr.args) > 1 else None
        d = next((kw.value for kw in expr.keywords if kw.arg == "dtype"), d)
        if d is None or _is_f32_dtype(d) or (
                isinstance(d, ast.Constant) and d.value is None):
            return "f32"
        return "bf16"
    for d in dtype_nodes:
        if _is_bf16_dtype(d):
            return "bf16"
        if _is_f32_dtype(d):
            return "f32"
    return None


def _has_preferred(call: ast.Call) -> bool:
    return any(kw.arg == "preferred_element_type" for kw in call.keywords)


def _walk_no_defs(node: ast.AST):
    """ast.walk that does not descend into nested function definitions."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if not isinstance(child, _FUNCS):
                stack.append(child)


class _PrecisionScan:
    """Source-order bf16-taint pass over one scope. Nested defs are their
    own scope (fresh taint set — closures see outer arrays, but flow
    through a closure boundary is beyond a lexical pass)."""

    def __init__(self, rule: Rule):
        self.rule = rule
        self.findings: list[Finding] = []

    def scan(self, body: list[ast.stmt], tainted: set | None = None) -> list:
        tainted = set() if tainted is None else tainted
        for stmt in body:
            self._stmt(stmt, tainted)
        return self.findings

    def _stmt(self, node: ast.stmt, tainted: set) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.scan(node.body, set())
            return
        # check sinks in this statement's own expressions (bodies of
        # compound statements are recursed into below, statement by
        # statement, so taint updates inside them are seen in order)
        bodies: list[list[ast.stmt]] = []
        exprs: list[ast.AST] = []
        if isinstance(node, (ast.If, ast.While)):
            exprs.append(node.test)
            bodies = [node.body, node.orelse]
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            exprs.append(node.iter)
            bodies = [node.body, node.orelse]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            exprs.extend(i.context_expr for i in node.items)
            bodies = [node.body]
        elif isinstance(node, ast.Try):
            bodies = [node.body, node.orelse, node.finalbody]
            bodies.extend(h.body for h in node.handlers)
        elif isinstance(node, ast.ClassDef):
            bodies = [node.body]
        else:
            exprs.append(node)  # simple statement: scan it whole
        for e in exprs:
            self._check_exprs(e, tainted)
        # taint update AFTER the RHS sinks were checked
        if isinstance(node, ast.Assign):
            kind = _cast_dtype(node.value)
            src = node.value.id if isinstance(node.value, ast.Name) else None
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if kind == "bf16" or (kind is None and src in tainted):
                        tainted.add(t.id)
                    else:
                        tainted.discard(t.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name):
                tainted.discard(node.target.id)
        for body in bodies:
            for stmt in body:
                self._stmt(stmt, tainted)

    def _check_exprs(self, root: ast.AST, tainted: set) -> None:
        for sub in _walk_no_defs(root):
            if isinstance(sub, ast.Call):
                self._check_sink(sub, tainted)
            elif (isinstance(sub, ast.BinOp)
                  and isinstance(sub.op, ast.MatMult)):
                for side in (sub.left, sub.right):
                    name = side.id if isinstance(side, ast.Name) else None
                    if name in tainted or _cast_dtype(side) == "bf16":
                        self.findings.append(self.rule.finding(
                            sub.lineno,
                            "bf16 operand in `@` matmul: operator form "
                            "cannot request f32 accumulation; use "
                            "jnp.matmul(..., preferred_element_type="
                            "jnp.float32)"))
                        break

    def _check_sink(self, call: ast.Call, tainted: set) -> None:
        if tail_name(call.func) not in CONTRACTIONS or _has_preferred(call):
            return
        for arg in call.args:
            bf16 = (isinstance(arg, ast.Name) and arg.id in tainted) \
                or _cast_dtype(arg) == "bf16"
            if bf16:
                what = (f"'{arg.id}'" if isinstance(arg, ast.Name)
                        else "a bf16-cast value")
                self.findings.append(self.rule.finding(
                    call.lineno,
                    f"{tail_name(call.func)}() consumes {what} (bfloat16) "
                    "without preferred_element_type=jnp.float32: the MXU "
                    "accumulates in bf16 (~8 mantissa bits over the "
                    "contraction)"))
                return


@register
class PrecisionFlowRule(Rule):
    id = "precision-flow"
    severity = "error"
    scope = ("wam_tpu",)
    description = ("bf16 values reaching dot/matmul/einsum without "
                   "preferred_element_type=jnp.float32")

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        return _PrecisionScan(self).scan(src.tree.body)


# ---------------------------------------------------------------------------
# schema-drift


def _load_declared(ctx: LintContext):
    """(metric_names, row_types) from rule config or the declared registry
    wam_tpu/obs/schema.py, AST-parsed (never imported)."""
    cfg = ctx.rule_config("schema-drift")
    if "metric_names" in cfg or "row_types" in cfg:
        return (set(cfg.get("metric_names", ())),
                set(cfg.get("row_types", ())))
    cached = getattr(ctx, "_schema_cache", None)
    if cached is not None:
        return cached
    path = os.path.join(ctx.root, "wam_tpu", "obs", "schema.py")
    metric_names: set[str] = set()
    row_types: set[str] = set()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            target = None
            if "METRIC_NAMES" in names:
                target = metric_names
            elif "LEDGER_ROW_TYPES" in names:
                target = row_types
            if target is None:
                continue
            for sub in ast.walk(node.value):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)):
                    target.add(sub.value)
    ctx._schema_cache = (metric_names, row_types)
    return ctx._schema_cache


@register
class SchemaDriftRule(Rule):
    id = "schema-drift"
    severity = "error"
    scope = ("wam_tpu",)
    description = ("wam_tpu_* metric names / ledger row types not declared "
                   "in wam_tpu/obs/schema.py")

    INSTRUMENTS = {"counter", "gauge", "histogram"}

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        if src.rel.replace(os.sep, "/") == "wam_tpu/obs/schema.py":
            return []  # the registry itself
        metric_names, row_types = _load_declared(ctx)
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.INSTRUMENTS and node.args):
                first = node.args[0]
                if (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)
                        and first.value.startswith("wam_tpu_")
                        and first.value not in metric_names):
                    out.append(self.finding(
                        node.lineno,
                        f"metric '{first.value}' is not declared in "
                        "wam_tpu/obs/schema.py METRIC_NAMES (dashboards "
                        "key on declared names)"))
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant) and k.value == "metric"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                            and v.value not in row_types):
                        out.append(self.finding(
                            node.lineno,
                            f"ledger row type '{v.value}' is not declared "
                            "in wam_tpu/obs/schema.py LEDGER_ROW_TYPES"))
        return out
