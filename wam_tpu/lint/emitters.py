"""Finding emitters: text (default), JSON (tooling), SARIF 2.1.0 (code
hosts / CI annotation UIs). All three consume the same `LintResult`; the
exit-code decision stays in `__main__` so emitters are pure."""

from __future__ import annotations

import json

from wam_tpu.lint.core import LintResult
from wam_tpu.lint.registry import all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def emit_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    lines.append(
        f"wam_tpu.lint: {len(result.files)} files, {len(result.findings)} "
        f"findings ({result.suppressed} pragma-suppressed, "
        f"{result.baselined} baselined)")
    return "\n".join(lines)


def emit_json(result: LintResult) -> str:
    return json.dumps(
        {
            "version": 1,
            "files": len(result.files),
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "findings": [
                {
                    "rule": f.rule,
                    "severity": f.severity,
                    "path": f.path,
                    "line": f.line,
                    "message": f.message,
                }
                for f in result.findings
            ],
        },
        indent=2, sort_keys=True) + "\n"


def emit_sarif(result: LintResult) -> str:
    sev_map = {"error": "error", "warning": "warning"}
    rules_meta = [
        {
            "id": cls.id,
            "shortDescription": {"text": cls.description},
            "defaultConfiguration": {
                "level": sev_map.get(cls.severity, "warning")},
        }
        for cls in all_rules()
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": sev_map.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace("\\", "/"),
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": max(1, f.line)},
                    }
                }
            ],
        }
        for f in result.findings
    ]
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "wam_tpu.lint",
                        "informationUri":
                            "https://github.com/wam-tpu/wam_tpu",
                        "rules": rules_meta,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


EMITTERS = {"text": emit_text, "json": emit_json, "sarif": emit_sarif}
