"""CLI for the static-analysis subsystem.

    python -m wam_tpu.lint --all                  # every rule, own scopes
    python -m wam_tpu.lint wam_tpu/serve          # explicit paths, all rules
    python -m wam_tpu.lint --rules host-sync      # subset of rules
    python -m wam_tpu.lint --format sarif         # text | json | sarif
    python -m wam_tpu.lint --write-baseline       # ratchet current findings
    python -m wam_tpu.lint --knobs                # env-knob audit
    python -m wam_tpu.lint --knobs --write-docs   # + regenerate README table
    python -m wam_tpu.lint --list-rules

Exit 1 on any non-baselined, non-pragma'd finding (or knob-audit
problem); 0 otherwise. Explicit paths disable per-rule scope filtering —
you asked for this file, every rule scans it (the legacy
check_host_syncs contract).
"""

from __future__ import annotations

import argparse
import os
import sys

from wam_tpu.lint import core
from wam_tpu.lint.emitters import EMITTERS
from wam_tpu.lint.registry import all_rules, get_rule


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m wam_tpu.lint",
        description="TPU hot-path static analysis (AST scan, no imports "
                    "of the scanned code)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: each rule's scope)")
    p.add_argument("--all", action="store_true",
                   help="scan every rule over its default scope "
                        "(the default when no paths are given; the flag "
                        "exists so CI lines read explicitly)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids (default: all)")
    p.add_argument("--format", default="text", choices=sorted(EMITTERS),
                   dest="fmt")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {core.DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="ratchet: write current findings to the baseline")
    p.add_argument("--knobs", action="store_true",
                   help="audit WAM_TPU_* env knobs against README/DESIGN")
    p.add_argument("--write-docs", action="store_true",
                   help="with --knobs: regenerate the README knob table")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    root = core.repo_root()

    if args.list_rules:
        for cls in all_rules():
            scope = ", ".join(cls.scope) if cls.scope else "(everything)"
            print(f"{cls.id:<16} {cls.severity:<8} {scope}")
            print(f"{'':<16} {cls.description}")
        return 0

    if args.knobs:
        from wam_tpu.lint import knobs
        problems, report = knobs.audit(root, write_docs=args.write_docs)
        for line in report:
            print(line)
        for line in problems:
            print(f"PROBLEM: {line}", file=sys.stderr)
        print(f"wam_tpu.lint --knobs: {len(report)} knobs, "
              f"{len(problems)} problems")
        return 1 if problems else 0

    if args.rules:
        rule_classes = [get_rule(r.strip())
                        for r in args.rules.split(",") if r.strip()]
    else:
        rule_classes = all_rules()
    rules = [cls() for cls in rule_classes]

    explicit = bool(args.paths)
    if explicit:
        files = core.load_files(args.paths, root=root)
    else:
        scopes = set()
        for cls in rule_classes:
            scopes.update(cls.scope or ("wam_tpu",))
        files = core.load_files(sorted(scopes), root=root)
        # de-dup: nested scopes (wam_tpu + wam_tpu/serve) load twice
        seen: set[str] = set()
        files = [f for f in files
                 if not (f.rel in seen or seen.add(f.rel))]

    ctx = core.LintContext(root=root)
    result = core.run_rules(rules, files, ctx,
                            respect_scope=not explicit,
                            apply_pragmas=True)

    if args.write_baseline:
        path = args.baseline or os.path.join(root, core.DEFAULT_BASELINE)
        data = core.write_baseline(path, result.findings)
        print(f"wrote {path}: {len(data['findings'])} keys, "
              f"{sum(data['findings'].values())} findings")
        return 0

    if not args.no_baseline:
        path = args.baseline or os.path.join(root, core.DEFAULT_BASELINE)
        baseline = core.load_baseline(path)
        result.findings, result.baselined = core.apply_baseline(
            result.findings, baseline)

    out = EMITTERS[args.fmt](result)
    print(out, end="" if out.endswith("\n") else "\n")
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
