"""Env-knob audit (`python -m wam_tpu.lint --knobs`).

Every ``WAM_TPU_*`` environment variable read in ``wam_tpu/`` or
``scripts/`` is an operational surface: kill switches, cache locations,
kernel-impl overrides. This mode AST-scans for the reads
(``os.environ[...]`` / ``.get`` / ``.setdefault`` / ``.pop`` /
``os.getenv``, including reads through a module-level ``FOO_ENV =
"WAM_TPU_..."`` constant), cross-references them against README.md /
DESIGN.md, and regenerates the knob table README carries between the
``<!-- wamlint-knobs:begin/end -->`` markers.

Exit-1 conditions: a knob read in code but undocumented (no README/DESIGN
mention AND no curated description here), a doc-mentioned knob that no
code reads (dead — stale docs), or a stale generated table.
``--knobs --write-docs`` rewrites the table in place.
"""

from __future__ import annotations

import ast
import os
import re

from wam_tpu.lint.core import load_files, repo_root, tail_name

KNOB_RE = re.compile(r"\bWAM_TPU_[A-Z0-9_]+\b")

BEGIN_MARK = "<!-- wamlint-knobs:begin -->"
END_MARK = "<!-- wamlint-knobs:end -->"

SCAN_DIRS = ("wam_tpu", "scripts")
DOC_FILES = ("README.md", "DESIGN.md")

# curated one-liners for the generated README table; the audit fails on a
# knob read in code that has no entry here (add one when adding a knob)
KNOB_DOCS = {
    "WAM_TPU_AOT_CACHE":
        "AOT executable cache directory (default `~/.cache/wam_tpu/aot`)",
    "WAM_TPU_NO_AOT_CACHE":
        "`1` disables AOT export/import entirely (kill switch)",
    "WAM_TPU_SCHEDULE_CACHE":
        "tuner schedule-cache path (default "
        "`~/.cache/wam_tpu/schedules.json`)",
    "WAM_TPU_NO_SCHEDULE_CACHE":
        "`1` disables schedule-cache lookups (law-only tuning)",
    "WAM_TPU_CACHE_DIR":
        "XLA persistent compilation-cache directory (default "
        "`~/.cache/wam_tpu/xla`)",
    "WAM_TPU_NO_REGISTRY":
        "`1` skips compile-artifact registry hydration (kill switch)",
    "WAM_TPU_NO_RESULT_CACHE":
        "`1` bypasses the serve result cache; read per call, so it can "
        "be flipped live",
    "WAM_TPU_NO_ONLINE_TUNE":
        "`1` disables the online schedule tuner: no drift rows, no shadow "
        "sweeps, no canary promotion (kill switch; gauges still update)",
    "WAM_TPU_NO_ANYTIME":
        "`1` disables anytime serving: servers over anytime entries fall "
        "back to full-n synchronous attribution (kill switch)",
    "WAM_TPU_NO_MODEL_PAGING":
        "`1` freezes multi-model residency: no eviction, page-in degrades "
        "to grow-only (kill switch; read per call, so it can be flipped "
        "live)",
    "WAM_TPU_DWT2_IMPL":
        "2-D DWT backend override (`auto`/`conv`/`matmul`/`pallas`)",
    "WAM_TPU_DWT1_IMPL":
        "1-D DWT backend override (`auto`/`conv`/`folded`/`folded_nhc`)",
    "WAM_TPU_SYNTH2_IMPL":
        "2-D synthesis backend override (`auto`/`conv`/`matmul`/`pallas`)",
    "WAM_TPU_SYNTH_COLLAPSE":
        "level-collapse tile crossover for fused synthesis (default 128 "
        "= one lane width)",
    "WAM_TPU_STFT_IMPL":
        "STFT backend override for the audio path "
        "(`auto`/`fft`/`matmul`)",
    "WAM_TPU_FAN_DTYPE":
        "eval-fan compute dtype override (`f32`/`bf16`/`fp8`): fan inputs "
        "cast at the jit boundary, reductions stay f32; fp8 degrades to "
        "bf16 off-backend",
    "WAM_TPU_MEL_BF16":
        "`1` runs the mel front-end's DFT/filterbank matmuls with bf16 "
        "inputs and f32 accumulation (fidelity-gated; "
        "`0`/`false`/`no` = f32)",
    "WAM_TPU_FUSED_RELU_IMPL":
        "fused-ReLU backend override (`auto`/`xla`/`pallas`)",
    "WAM_TPU_POD_AUTHKEY":
        "hex connection auth key the pod router hands to worker "
        "processes (set by the router; workers refuse to start without "
        "it)",
    "WAM_TPU_POD_TRANSPORT":
        "pod control-plane transport (`tcp` = framed zero-copy sockets, "
        "the default; `pipe` = legacy multiprocessing pipes, loopback "
        "only)",
    "WAM_TPU_POD_HEARTBEAT_S":
        "pod router heartbeat interval in seconds (default 0.25); also "
        "the staleness bound on routing's drain estimates",
}

_ENV_METHODS = {"get", "setdefault", "pop"}


def _is_environ(node: ast.AST) -> bool:
    return ((isinstance(node, ast.Attribute) and node.attr == "environ")
            or (isinstance(node, ast.Name) and node.id == "environ"))


def _module_env_consts(tree: ast.AST) -> dict[str, str]:
    """Module-level ``NAME = "WAM_TPU_..."`` constants (e.g. the pod's
    AUTHKEY_ENV) so reads through the name still count."""
    out: dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and KNOB_RE.fullmatch(node.value.value)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _key_name(node: ast.AST, consts: dict[str, str]) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if KNOB_RE.fullmatch(node.value) else None
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def scan_knob_reads(root: str | None = None) -> dict[str, list[str]]:
    """knob name -> sorted read sites ("path:line") across SCAN_DIRS.
    Reads through imported constants count at the import-site module too
    when the key is re-exported by name (the pod router's AUTHKEY_ENV
    write is a set, not a read, and is ignored)."""
    root = root if root is not None else repo_root()
    reads: dict[str, set[str]] = {}
    for src in load_files(SCAN_DIRS, root=root):
        if src.tree is None:
            continue
        consts = _module_env_consts(src.tree)
        for node in ast.walk(src.tree):
            key = None
            if isinstance(node, ast.Call):
                f = node.func
                if tail_name(f) == "getenv" and node.args:
                    key = _key_name(node.args[0], consts)
                elif (isinstance(f, ast.Attribute)
                        and f.attr in _ENV_METHODS
                        and _is_environ(f.value) and node.args):
                    key = _key_name(node.args[0], consts)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _is_environ(node.value)):
                key = _key_name(node.slice, consts)
            if key is not None:
                reads.setdefault(key, set()).add(
                    f"{src.rel}:{node.lineno}")
    return {k: sorted(v) for k, v in sorted(reads.items())}


def doc_mentions(root: str | None = None) -> dict[str, set[str]]:
    """knob name -> doc files mentioning it."""
    root = root if root is not None else repo_root()
    out: dict[str, set[str]] = {}
    for doc in DOC_FILES:
        p = os.path.join(root, doc)
        if not os.path.isfile(p):
            continue
        with open(p, "r", encoding="utf-8") as f:
            for m in KNOB_RE.finditer(f.read()):
                out.setdefault(m.group(0), set()).add(doc)
    return out


def render_table(reads: dict[str, list[str]]) -> str:
    lines = [
        BEGIN_MARK,
        "<!-- generated by `python -m wam_tpu.lint --knobs --write-docs`"
        " — do not edit by hand -->",
        "| Knob | Read in | Meaning |",
        "| --- | --- | --- |",
    ]
    for knob, sites in reads.items():
        mods = sorted({s.rsplit(":", 1)[0] for s in sites})
        shown = ", ".join(f"`{m}`" for m in mods[:2])
        if len(mods) > 2:
            shown += f" (+{len(mods) - 2} more)"
        desc = KNOB_DOCS.get(knob, "*(undocumented)*")
        lines.append(f"| `{knob}` | {shown} | {desc} |")
    lines.append(END_MARK)
    return "\n".join(lines)


def current_table(root: str) -> str | None:
    p = os.path.join(root, "README.md")
    if not os.path.isfile(p):
        return None
    with open(p, "r", encoding="utf-8") as f:
        text = f.read()
    b, e = text.find(BEGIN_MARK), text.find(END_MARK)
    if b < 0 or e < 0:
        return None
    return text[b:e + len(END_MARK)]


def write_table(root: str, table: str) -> bool:
    p = os.path.join(root, "README.md")
    with open(p, "r", encoding="utf-8") as f:
        text = f.read()
    b, e = text.find(BEGIN_MARK), text.find(END_MARK)
    if b < 0 or e < 0:
        return False
    new = text[:b] + table + text[e + len(END_MARK):]
    with open(p, "w", encoding="utf-8") as f:
        f.write(new)
    return True


def audit(root: str | None = None, write_docs: bool = False):
    """Returns (problem lines, report lines). Non-empty problems => exit 1."""
    root = root if root is not None else repo_root()
    reads = scan_knob_reads(root)
    docs = doc_mentions(root)
    problems: list[str] = []
    report: list[str] = []
    for knob, sites in reads.items():
        where = sites[0] + (f" (+{len(sites) - 1} more)"
                            if len(sites) > 1 else "")
        report.append(f"{knob}: read at {where}; documented in "
                      f"{sorted(docs.get(knob, set())) or 'nowhere'}")
        if knob not in KNOB_DOCS:
            problems.append(
                f"undocumented knob {knob} (read at {where}): add a "
                "KNOB_DOCS entry in wam_tpu/lint/knobs.py and regenerate "
                "the README table")
    for knob, places in sorted(docs.items()):
        if knob not in reads:
            problems.append(
                f"dead knob {knob}: mentioned in {sorted(places)} but no "
                "code under wam_tpu/ or scripts/ reads it")
    table = render_table(reads)
    if write_docs:
        if not write_table(root, table):
            problems.append(
                "README.md has no wamlint-knobs markers to write the "
                "table between")
    elif current_table(root) != table:
        problems.append(
            "README knob table is stale (or missing): run "
            "`python -m wam_tpu.lint --knobs --write-docs`")
    return problems, report
