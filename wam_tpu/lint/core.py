"""Shared analysis core for `wam_tpu.lint`.

Everything the rules have in common lives here so a new rule is ~one
class: the module loader (parse once, share the AST), the traced-function
detection generalized out of the original ``scripts/check_host_syncs.py``
(jit-family decorator or referenced-by-name in a jit-family call, nested
defs inherit), the finding model (rule id + severity + file:line),
inline ``# wamlint: disable=<rule>`` pragma resolution, and the baseline
ratchet (pre-existing findings are *capped*, never bulk-suppressed: the
count per (path, rule, message) key may only go down).

No module under analysis is ever imported — the whole subsystem is a
static AST scan, so it is safe to run on broken trees and in CI without
a device or a jax install... almost: `wam_tpu.lint` itself only needs
the stdlib.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field, replace

__all__ = [
    "Finding", "SourceFile", "LintContext", "LintResult",
    "repo_root", "load_files", "tail_name", "ref_names",
    "collect_traced_names", "iter_traced_functions", "TRACING_CALLS",
    "suppressed_by_pragma", "load_baseline", "apply_baseline",
    "baseline_key", "write_baseline", "DEFAULT_BASELINE",
]

SEVERITIES = ("error", "warning")

# call targets whose function-valued arguments get traced (the repo's
# jit-family surface; kept in one place so host-sync, retrace-risk and
# donation-safety agree on what "traced" means)
TRACING_CALLS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "map", "scan", "shard_map", "make_sharded_runner", "jit_entry",
    "cached_jit", "cached_entry", "donating_jit", "smoothgrad",
    "fan_runner",
}

DEFAULT_BASELINE = os.path.join("wam_tpu", "lint", "baseline.json")

_PRAGMA_RE = re.compile(r"#\s*wamlint:\s*disable=([A-Za-z0-9_,\-]+)")
_PRAGMA_FILE_RE = re.compile(r"#\s*wamlint:\s*disable-file=([A-Za-z0-9_,\-]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a file:line."""

    rule: str
    severity: str
    path: str       # repo-relative, "/" separators (stable across hosts)
    line: int
    message: str
    abspath: str = ""  # as-loaded path (legacy-parity emitters want it)

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    """One parsed module. ``tree`` is None when the file failed to parse
    (``error`` carries the SyntaxError) — rules skip those; the driver
    reports a ``parse-error`` finding so broken files fail the gate."""

    path: str               # absolute
    rel: str                # repo-relative, "/" separators
    text: str = ""
    tree: ast.AST | None = None
    error: SyntaxError | None = None
    _pragma_cache: dict | None = None

    @property
    def lines(self) -> list[str]:
        return self.text.splitlines()

    def pragmas(self) -> tuple[dict[int, set[str]], set[str]]:
        """(line -> disabled rule ids, file-wide disabled rule ids)."""
        if self._pragma_cache is None:
            per_line: dict[int, set[str]] = {}
            whole: set[str] = set()
            for i, line in enumerate(self.lines, start=1):
                m = _PRAGMA_RE.search(line)
                if m:
                    per_line.setdefault(i, set()).update(
                        r.strip() for r in m.group(1).split(",") if r.strip())
                m = _PRAGMA_FILE_RE.search(line)
                if m:
                    whole.update(
                        r.strip() for r in m.group(1).split(",") if r.strip())
            self._pragma_cache = (per_line, whole)
        return self._pragma_cache


@dataclass
class LintContext:
    """Run-wide state shared by every rule: the repo root (README/DESIGN
    and the schema registry are resolved against it) and per-rule config
    overrides keyed by rule id (tests inject fixture schemas here)."""

    root: str
    config: dict = field(default_factory=dict)

    def rule_config(self, rule_id: str) -> dict:
        return self.config.get(rule_id, {})


@dataclass
class LintResult:
    findings: list[Finding]
    files: list[SourceFile]
    suppressed: int = 0      # dropped by inline pragmas
    baselined: int = 0       # absorbed by the baseline ratchet


def repo_root() -> str:
    """The checkout root: two levels above this file (wam_tpu/lint/)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_files(paths, root: str | None = None) -> list[SourceFile]:
    """Resolve files/dirs (relative paths against ``root``) into parsed
    `SourceFile`s, sorted by path — the same walk order as the legacy
    host-sync script so finding order is reproducible."""
    root = root if root is not None else repo_root()
    out: list[str] = []
    for a in paths:
        p = a if os.path.isabs(a) else os.path.join(root, a)
        if os.path.isfile(p):
            out.append(p)
        else:
            for dirpath, _, names in os.walk(p):
                out.extend(os.path.join(dirpath, n)
                           for n in sorted(names) if n.endswith(".py"))
    files: list[SourceFile] = []
    for p in sorted(out):
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        try:
            with open(p, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            files.append(SourceFile(p, rel, "", None,
                                    SyntaxError(str(e))))
            continue
        try:
            tree = ast.parse(text, filename=p)
            files.append(SourceFile(p, rel, text, tree))
        except SyntaxError as e:
            files.append(SourceFile(p, rel, text, None, e))
    return files


# -- traced-function detection (generalized from check_host_syncs.py) -------

def tail_name(node: ast.AST) -> str | None:
    """`jax.jit` -> "jit", `lax.map` -> "map", `jit` -> "jit"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def ref_names(node: ast.AST) -> set[str]:
    """Function names referenced by an argument expression: bare names,
    `self._method` / `obj.method` attributes, and the same inside a
    `functools.partial(...)` first argument."""
    out: set[str] = set()
    if isinstance(node, ast.Name):
        out.add(node.id)
    elif isinstance(node, ast.Attribute):
        out.add(node.attr)
    elif isinstance(node, ast.Call) and tail_name(node.func) == "partial":
        if node.args:
            out |= ref_names(node.args[0])
    return out


def is_tracing_call(node: ast.Call) -> bool:
    """Whether this call traces its function-valued arguments. "map" /
    "scan" count only off `lax` — otherwise ThreadPoolExecutor.map and
    plain builtins collide."""
    name = tail_name(node.func)
    if name in ("map", "scan"):
        return (isinstance(node.func, ast.Attribute)
                and tail_name(node.func.value) == "lax")
    return name in TRACING_CALLS


def collect_traced_names(tree: ast.AST) -> set[str]:
    """Names of functions that jax traces in this module: defs decorated
    with a jit-family decorator, or referenced (incl. `self.<name>` /
    `partial(<name>, ...)`) as an argument to a jit-family call."""
    traced: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if tail_name(target) in TRACING_CALLS:
                    traced.add(node.name)
        elif isinstance(node, ast.Call) and is_tracing_call(node):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                traced |= ref_names(arg)
    return traced


def iter_traced_functions(tree: ast.AST):
    """Yield each outermost traced function def exactly once (nested defs
    share the traced body and are not yielded separately) — the shared
    traversal under host-sync and friends."""
    traced = collect_traced_names(tree)
    seen: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        name = getattr(node, "name", None)
        if name not in traced or id(node) in seen:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                seen.add(id(sub))
        yield node


# -- pragma + baseline plumbing ---------------------------------------------

def suppressed_by_pragma(finding: Finding, src: SourceFile) -> bool:
    """True when an inline pragma disables this finding: file-wide
    ``# wamlint: disable-file=<rule>``, or ``# wamlint: disable=<rule>``
    on the finding's line or the line directly above it."""
    per_line, whole = src.pragmas()
    if finding.rule in whole:
        return True
    for ln in (finding.line, finding.line - 1):
        if finding.rule in per_line.get(ln, set()):
            return True
    return False


def baseline_key(f: Finding) -> str:
    """Line-number-free identity so unrelated edits above a baselined
    finding do not churn the file."""
    return f"{f.path}::{f.rule}::{f.message}"


def load_baseline(path: str) -> dict[str, int]:
    if not os.path.isfile(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, int]) -> tuple[list[Finding], int]:
    """Ratchet semantics: each baseline key absorbs up to its recorded
    count of matching findings; everything beyond that (new findings, or
    a file getting WORSE than its baseline) is reported. Returns
    (non-baselined findings, absorbed count)."""
    budget = dict(baseline)
    kept: list[Finding] = []
    absorbed = 0
    for f in findings:
        k = baseline_key(f)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            absorbed += 1
        else:
            kept.append(f)
    return kept, absorbed


def write_baseline(path: str, findings: list[Finding]) -> dict:
    counts: dict[str, int] = {}
    for f in findings:
        counts[baseline_key(f)] = counts.get(baseline_key(f), 0) + 1
    data = {
        "version": 1,
        "comment": ("wam_tpu.lint baseline — pre-existing findings ratcheted "
                    "here; counts may only decrease. Regenerate with "
                    "`python -m wam_tpu.lint --all --write-baseline`."),
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return data


def parse_error_findings(files: list[SourceFile]) -> list[Finding]:
    out = []
    for src in files:
        if src.error is not None:
            out.append(Finding(
                rule="parse-error", severity="error", path=src.rel,
                line=getattr(src.error, "lineno", 1) or 1,
                message=f"syntax error: {src.error}", abspath=src.path))
    return out


def _rel_in_scope(rel: str, scope) -> bool:
    if scope is None:
        return True
    for s in scope:
        s = s.rstrip("/")
        if rel == s or rel.startswith(s + "/"):
            return True
    return False


def run_rules(rules, files: list[SourceFile], ctx: LintContext,
              respect_scope: bool = True,
              apply_pragmas: bool = True) -> LintResult:
    """Drive ``rules`` over ``files``. Scope filtering keeps each rule on
    its curated directory set when the caller ran with the default scope;
    explicit path runs pass ``respect_scope=False`` (the legacy
    check_host_syncs contract: you asked for this file, you get scanned)."""
    findings: list[Finding] = list(parse_error_findings(files))
    for rule in rules:
        scope = rule.scope if respect_scope else None
        for src in files:
            if src.tree is None or not _rel_in_scope(src.rel, scope):
                continue
            for f in rule.check_file(src, ctx):
                findings.append(replace(
                    f, rule=rule.id, severity=rule.severity,
                    path=src.rel, abspath=src.path))
    suppressed = 0
    if apply_pragmas:
        by_rel = {src.rel: src for src in files}
        kept = []
        for f in findings:
            src = by_rel.get(f.path)
            if src is not None and suppressed_by_pragma(f, src):
                suppressed += 1
            else:
                kept.append(f)
        findings = kept
    return LintResult(findings=findings, files=files, suppressed=suppressed)
