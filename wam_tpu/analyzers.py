"""Scale analyzers — parity with `src/analyzers.py` (WAMAnalyzer2D) and
`src/analyzers_helpers.py`: decompose an image into per-scale partial images
and search for the minimal set of wavelet components that preserves the
prediction.

The reference's per-channel pywt coeffs_to_array round trips
(`src/analyzers_helpers.py:35-81`) are the batched masked-IDWT used across
the evaluation suite; the quantile sweep (`src/analyzers.py:94-203`)
evaluates every quantile's reconstruction in ONE model call per image.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from wam_tpu.evalsuite.eval2d import imagenet_denormalize, imagenet_preprocess, _minmax01
from wam_tpu.evalsuite.metrics import softmax_probs
from wam_tpu.evalsuite.packing import array_to_coeffs2d, coeffs_to_array2d
from wam_tpu.wavelets import wavedec2, waverec2

__all__ = [
    "compute_levelized_masks",
    "generate_partial_image",
    "generate_disentangled_images",
    "WAMAnalyzer2D",
    "WAMAnalyzerViT",
]


def compute_levelized_masks(grad_wam: jax.Array, J: int) -> jax.Array:
    """(S, S) mosaic → (J+1, S, S): per-level masks carrying that level's
    H/V/D blocks (finest first), last = approximation corner
    (`src/analyzers_helpers.py:6-33`)."""
    size = grad_wam.shape[-1]
    out = jnp.zeros((J + 1, size, size), dtype=grad_wam.dtype)
    for j in range(J):
        s = size // (2 ** (j + 1))
        e = size // (2**j)
        out = out.at[j, s:e, s:e].set(grad_wam[s:e, s:e])
        out = out.at[j, s:e, :s].set(grad_wam[s:e, :s])
        out = out.at[j, :s, s:e].set(grad_wam[:s, s:e])
    sa = size // (2**J)
    out = out.at[J, :sa, :sa].set(grad_wam[:sa, :sa])
    return out


def _masked_rec(image: jax.Array, masks: jax.Array, J: int, wavelet: str, mode: str = "reflect"):
    """image (3, H, W) × packed-domain masks (M, Ph, Pw) → (M, 3, H, W)."""
    H, W = image.shape[-2:]
    coeffs = wavedec2(image, wavelet, J, mode)
    shapes = [tuple(coeffs[0].shape[-2:])] + [tuple(d.diagonal.shape[-2:]) for d in coeffs[1:]]
    packed = coeffs_to_array2d(coeffs)
    if masks.shape[-2:] != packed.shape[-2:]:
        masks = jax.image.resize(masks, masks.shape[:-2] + packed.shape[-2:], method="nearest")
    rec = waverec2(array_to_coeffs2d(packed[None] * masks[:, None], shapes), wavelet)
    return rec[..., :H, :W]


def generate_partial_image(image: jax.Array, grad_wam: jax.Array, q: float, J: int, wavelet: str = "haar"):
    """Reconstruction keeping coefficients above the q-th quantile of the
    mosaic (`src/analyzers_helpers.py:35-81`). Returns (image (3,H,W),
    filtered wam)."""
    thr = jnp.quantile(grad_wam, q)
    mask = (grad_wam >= thr).astype(image.dtype)
    rec = _masked_rec(image, mask[None], J, wavelet)[0]
    return rec, mask * grad_wam


def generate_disentangled_images(
    grad_wam: jax.Array, image: jax.Array, J: int, EPS: float = 0.1, wavelet: str = "haar"
):
    """Per-level partial images (J+1, 3, H, W) + levelized masks
    (`src/analyzers_helpers.py:83-134`): level mask cells must exceed
    min + EPS."""
    masks = compute_levelized_masks(grad_wam, J)
    binary = (masks > (masks.min() + EPS)).astype(image.dtype)
    partial = _masked_rec(image, binary, J, wavelet)
    return partial, masks


class WAMAnalyzerViT:
    """Token-grid aggregation of patch-aligned WAM mosaics — the
    transformer sibling of the CAM path's token-tap fold
    (`evalsuite.baselines._acts_and_grads`).

    ``explainer`` is a `WaveletAttribution2D` built with
    ``level_plan="patch"`` (wam_tpu.xattr.planner plans the depth); its
    plan fixes the token grid, and every per-level pixel map average-pools
    EXACTLY onto it, so scale disentanglement reads off per token: which
    tokens matter, and at which dyadic scale."""

    def __init__(self, explainer):
        plan = getattr(explainer, "patch_plan", None)
        if plan is None:
            raise ValueError(
                "WAMAnalyzerViT needs an explainer constructed with "
                "level_plan='patch' (WaveletAttribution2D) — an explicit-J "
                "explainer carries no token grid to aggregate onto"
            )
        self.explainer = explainer
        self.plan = plan

    def token_maps(self, x, y=None) -> jax.Array:
        """(B, J(+1), t, t): per-level token-grid importance — |mosaic|
        reprojected to per-level pixel maps, pooled onto the plan's
        token grid (the approximation band joins per the explainer's
        ``approx_coeffs``)."""
        from wam_tpu.ops.packing2d import reproject_mosaic
        from wam_tpu.xattr.planner import token_grid_map

        mosaic = self.explainer(x, y)
        scales = reproject_mosaic(
            jnp.abs(mosaic), self.plan.J, self.explainer.approx_coeffs
        )
        return token_grid_map(scales, self.plan.tokens)

    def token_importance(self, x, y=None) -> jax.Array:
        """(B, t, t): level-summed token importance."""
        return self.token_maps(x, y).sum(axis=1)


class WAMAnalyzer2D:
    """`src/analyzers.py:16-203`. ``explainer``: (x, y) → (B, S, S) mosaics;
    ``model_fn``: (B, 3, H, W) → logits."""

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        explainer: Callable,
        wavelet: str = "haar",
        J: int = 3,
        mode: str = "reflect",
        denormalize_fn: Callable = imagenet_denormalize,
        preprocess_fn: Callable = imagenet_preprocess,
    ):
        self.model_fn = model_fn
        self.explainer = explainer
        self.wavelet = wavelet
        self.J = J
        self.mode = mode
        self.denormalize_fn = denormalize_fn
        self.preprocess_fn = preprocess_fn
        self.grad_wams = None
        self.insertion_quantile: list = []
        self.deletion_quantile: list = []

    def precompute(self, x, y):
        if self.grad_wams is None:
            self.grad_wams = jnp.asarray(self.explainer(x, y))
        return self.grad_wams

    def isolate_scales(self, x, y, EPS: float = 0.1):
        """Per-image (partial_images (J+1, 3, H, W), masks (J+1, S, S))
        (`src/analyzers.py:73-92`)."""
        x = jnp.asarray(x)
        wams = self.precompute(x, y)
        outs = []
        for i in range(x.shape[0]):
            image01 = self.denormalize_fn(x[i])
            outs.append(
                generate_disentangled_images(wams[i], image01, self.J, EPS=EPS, wavelet=self.wavelet)
            )
        return outs

    def isolate_necessary_components(self, x, y, qs: Sequence[float], mode: str):
        """Quantile sweep (`src/analyzers.py:94-203`): reconstructions at
        every q evaluated in one batch; insertion keeps the first
        correctly-predicted one, deletion the last. Records the quantile in
        insertion_quantile/deletion_quantile; yields (None, ...) entries
        when no reconstruction predicts the true class."""
        if mode not in ("insertion", "deletion"):
            raise ValueError("mode must be 'insertion' or 'deletion'")
        qs = list(qs)
        if mode == "deletion" and len(qs) > 1:
            assert qs[0] <= qs[1]
        if mode == "insertion" and len(qs) > 1:
            assert qs[0] >= qs[1]

        x = jnp.asarray(x)
        y = np.asarray(y)
        wams = self.precompute(x, y)

        outs = []
        for i in range(x.shape[0]):
            image01 = self.denormalize_fn(x[i])
            wam = wams[i]
            thr = jnp.quantile(wam, jnp.asarray(qs))
            masks = (wam[None] >= thr[:, None, None]).astype(x.dtype)
            recs = _masked_rec(image01, masks, self.J, self.wavelet, self.mode)
            inputs = self.preprocess_fn(_minmax01(recs))
            probs = np.asarray(softmax_probs(self.model_fn(inputs)))
            predicted = probs.argmax(axis=1)
            correct = np.where(predicted == y[i])[0]
            if len(correct):
                idx = int(correct[-1] if mode == "deletion" else correct[0])
                (self.deletion_quantile if mode == "deletion" else self.insertion_quantile).append(
                    qs[idx]
                )
                outs.append(
                    (
                        (np.asarray(recs[0]), np.asarray(recs[idx]), np.asarray(recs[-1])),
                        np.asarray(masks[idx] * wam),
                        np.asarray(wam),
                        (probs, idx),
                    )
                )
            else:
                outs.append(((None, None, None), None, np.asarray(wam), (None, np.nan)))
        return outs
