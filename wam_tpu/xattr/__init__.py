"""Transformer-native & temporal attribution (`wam_tpu/xattr/`).

Three pillars on top of the conv-shaped core:

- `xattr.attention` — attention rollout and grad⊙attn relevance from the
  ViT's captured softmax weights (``capture_attn=True``), the standard
  transformer baselines, under the evalsuite's (x, y) → (B, H, W)
  contract;
- `xattr.planner` — patch-aligned wavelet level planning
  (``level_plan="patch"`` in `WaveletAttribution2D`) + token-grid
  aggregation, so WAM's scale disentanglement maps onto ViT tokens;
- `xattr.video` / `xattr.video_eval` — video WAM (2D space + time with
  an anisotropic level spec) and temporal insertion/deletion through the
  fan engine's one-fetch contract.
"""

from wam_tpu.xattr.attention import (
    attention_gradient,
    attention_rollout,
    attention_weight_grads,
    capture_attention_weights,
    relevance_from_grads,
    rollout_from_weights,
)
from wam_tpu.xattr.planner import PatchLevelPlan, plan_patch_levels, token_grid_map
from wam_tpu.xattr.video import (
    VideoLevels,
    WaveletAttributionVideo,
    frame_importance,
    spacetime_map,
    wavedec_video,
    waverec_video,
)
from wam_tpu.xattr.video_eval import EvalVideoWAM

__all__ = [
    "attention_rollout",
    "attention_gradient",
    "attention_weight_grads",
    "capture_attention_weights",
    "rollout_from_weights",
    "relevance_from_grads",
    "PatchLevelPlan",
    "plan_patch_levels",
    "token_grid_map",
    "VideoLevels",
    "WaveletAttributionVideo",
    "wavedec_video",
    "waverec_video",
    "spacetime_map",
    "frame_importance",
    "EvalVideoWAM",
]
