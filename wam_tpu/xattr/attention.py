"""Transformer-native attribution baselines: attention rollout and
grad⊙attn relevance propagation.

Both read the per-block softmax weights that `models/vit.py` exposes when
built with ``capture_attn=True``:

- forward weights are **sown** into
  ``intermediates/block{i}/attn/attention_weights`` — read with
  ``mutable=["intermediates"]`` (`capture_attention_weights`);
- the same tensors are routed through a zero **perturb tap** of the same
  name, so ∂logit/∂A materializes exactly like the CAM taps do
  (`wam_tpu.evalsuite.baselines._acts_and_grads`) — the JAX analogue of
  Chefer et al.'s backward hooks.

Methods (both map (x, y) → a (B, H, W) pixel-domain map, the
`evalsuite/baselines.py` contract, and both are plain traced JAX — the
evaluators jit ONE dispatch around them):

- `attention_rollout` — Abnar & Zuidema (2020): per block, head-averaged
  weights mixed with the residual identity (``0.5·A + 0.5·I``),
  row-normalized, then matmul-composed input→output; the class-token row
  of the composite is the per-patch relevance.
- `attention_gradient` — the gradient-weighted variant of Chefer et al.
  (2021, "generic attention explainability"): per block
  ``Ā = ReLU(E_h[∂logit/∂A ⊙ A])``, propagated through the residual
  stream as ``R ← R + Ā @ R`` from the first block up; class-token row
  again.

Token-grid maps are bilinearly resized to the input (H, W) so the fan
evaluators perturb pixels exactly as they do for the CNN baselines.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "capture_attention_weights",
    "attention_weight_grads",
    "rollout_from_weights",
    "relevance_from_grads",
    "attention_rollout",
    "attention_gradient",
]


def _require_capture(model) -> None:
    if not getattr(model, "capture_attn", False):
        raise ValueError(
            "attention baselines need the ViT built with capture_attn=True "
            "(models/vit.py) — the stock attention body never materializes "
            "its softmax weights"
        )


def _block_stack(tree: dict, leaf: str) -> jax.Array:
    """Stack ``block{i}/attn/{leaf}`` entries into (L, B, heads, N, N),
    ordered by block index (dict order is insertion order = depth order,
    but sort defensively)."""
    names = sorted(
        (k for k in tree if k.startswith("block")), key=lambda k: int(k[5:])
    )
    if not names:
        raise ValueError(
            "no block*/attn attention weights captured — was the model built "
            "with capture_attn=True?"
        )
    leaves = []
    for name in names:
        v = tree[name]["attn"][leaf]
        # sown values arrive as a 1-tuple (flax sow default reduce_fn)
        leaves.append(v[0] if isinstance(v, tuple) else v)
    return jnp.stack(leaves)


def capture_attention_weights(model, variables, x, nchw: bool = True) -> jax.Array:
    """One forward pass; returns the softmax stacks (L, B, heads, N, N)
    including the class token (N = 1 + tokens)."""
    _require_capture(model)
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    inp = jnp.transpose(x, (0, 2, 3, 1)) if nchw else x
    _, state = model.apply(base, inp, mutable=["intermediates"])
    return _block_stack(state["intermediates"], "attention_weights")


def attention_weight_grads(model, variables, x, y, nchw: bool = True):
    """(weights, grads), each (L, B, heads, N, N): ∂(picked-logit sum)/∂A
    through the zero perturb taps. Sum (not mean) of picked logits keeps
    per-sample gradients batch-size independent, matching the CAM
    convention (`evalsuite.baselines._acts_and_grads`)."""
    _require_capture(model)
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    inp = jnp.transpose(x, (0, 2, 3, 1)) if nchw else x
    # Materialize zero taps at THIS batch's shapes (shape-only trace): the
    # stored perturbation variables carry the init batch size.
    pert_shapes = jax.eval_shape(
        lambda v: model.apply(v, inp, mutable=["perturbations", "intermediates"])[1][
            "perturbations"
        ],
        base,
    )
    perturbs = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), pert_shapes
    )

    def loss_fn(pert):
        out, state = model.apply(
            {**base, "perturbations": pert}, inp, mutable=["intermediates"]
        )
        out = out[0] if isinstance(out, tuple) else out
        if y is None:
            return out.sum(), state["intermediates"]
        picked = jnp.take_along_axis(out, jnp.asarray(y)[:, None], axis=1)
        return picked.sum(), state["intermediates"]

    (_, inter), grads = jax.value_and_grad(loss_fn, has_aux=True)(perturbs)
    weights = _block_stack(inter, "attention_weights")
    gstack = _block_stack(grads, "attention_weights")
    return weights, gstack


def _cls_row_to_grid(rel_row: jax.Array) -> jax.Array:
    """(B, N) class-token relevance row → (B, side, side) patch grid."""
    n = rel_row.shape[-1] - 1
    side = int(n**0.5)
    if side * side != n:
        raise ValueError(f"{n} patch tokens is not a square grid")
    return rel_row[:, 1:].reshape(rel_row.shape[0], side, side)


def rollout_from_weights(weights: jax.Array, residual: float = 0.5) -> jax.Array:
    """Attention rollout over a (L, B, heads, N, N) stack → (B, s, s).

    Head-average each block, mix in the residual identity, row-normalize,
    then compose input→output; the class-token row of the composite is the
    relevance of each patch token for the classification read-out."""
    a = weights.mean(axis=2)  # (L, B, N, N)
    eye = jnp.eye(a.shape[-1], dtype=a.dtype)
    a = (1.0 - residual) * a + residual * eye
    a = a / a.sum(axis=-1, keepdims=True)

    def compose(carry, layer):
        return layer @ carry, None

    rollout, _ = jax.lax.scan(compose, jnp.broadcast_to(eye, a.shape[1:]), a)
    return _cls_row_to_grid(rollout[:, 0, :])


def relevance_from_grads(weights: jax.Array, grads: jax.Array) -> jax.Array:
    """Chefer-style grad⊙attn relevance over (L, B, heads, N, N) stacks
    → (B, s, s): per block ``Ā = ReLU(E_h[grad ⊙ A])``, accumulated
    through the residual stream as ``R ← R + Ā @ R`` from block 0 up."""
    abar = jax.nn.relu((grads * weights).mean(axis=2))  # (L, B, N, N)
    eye = jnp.eye(abar.shape[-1], dtype=abar.dtype)

    def accumulate(carry, layer):
        return carry + layer @ carry, None

    rel, _ = jax.lax.scan(accumulate, jnp.broadcast_to(eye, abar.shape[1:]), abar)
    return _cls_row_to_grid(rel[:, 0, :])


def _resize_to(grid: jax.Array, hw) -> jax.Array:
    return jax.image.resize(grid, grid.shape[:-2] + tuple(hw), method="bilinear")


def attention_rollout(model, variables, x, y=None, nchw: bool = True) -> jax.Array:
    """Abnar & Zuidema rollout → (B, H, W). ``y`` is accepted (and
    ignored) so the evaluator registry can call every method uniformly —
    rollout is class-agnostic by construction."""
    del y
    weights = capture_attention_weights(model, variables, x, nchw=nchw)
    return _resize_to(rollout_from_weights(weights), x.shape[-2:] if nchw else x.shape[1:3])


def attention_gradient(model, variables, x, y, nchw: bool = True) -> jax.Array:
    """Gradient-weighted attention relevance (grad⊙attn) → (B, H, W)."""
    weights, grads = attention_weight_grads(model, variables, x, y, nchw=nchw)
    return _resize_to(
        relevance_from_grads(weights, grads), x.shape[-2:] if nchw else x.shape[1:3]
    )
