"""Video WAM: wavelet attribution over 2D space + time.

Extends the volumetric `wam_tpu.wam3d` machinery to clips (B, C, T, H, W)
with an **anisotropic level spec** — video statistics are anisotropic
(spatial structure is far richer than frame-to-frame change), so
`VideoLevels(spatial=J_s, temporal=J_t)` decomposes the finest ``J_t``
levels with the separable 3D DWT (space AND time) and the remaining
``J_s − J_t`` levels with the 2D DWT only (time rides as a batch axis at
the decimated frame rate). ``VideoLevels(J, J)`` degenerates to the
uniform `wavedec3` cube; ``VideoLevels(J, 0)`` is per-frame 2D WAM.

Attribution mirrors `WaveletAttribution3D`: decompose → gradient of the
target logit w.r.t. every coefficient through the reconstruction →
aggregate. The aggregate here is `spacetime_map`: per-level |gradient|
energy nearest-upsampled to the clip's (T, H, W) box and summed — the
video analogue of `visualize_cube`'s per-level maps, collapsed. From it,
`frame_importance` reduces to a (B, T) per-frame score that the temporal
insertion/deletion fan perturbs (`wam_tpu.xattr.video_eval`).

Long clips: ``mesh=`` composes with PR 9's `SeqShardedWam` — the TIME
axis is halo-sharded across ``seq_axis`` exactly like volume depth
(uniform levels + single-channel clips only; the anisotropic 2D tail
would need a time-gather the halo layer doesn't provide).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from wam_tpu.core.engine import target_loss
from wam_tpu.core.estimators import (
    resolve_sample_chunk,
    smoothgrad,
    trapezoid,
    validate_sample_batch_size,
)
from wam_tpu.wavelets import Detail2D, dwt2, dwt3, idwt2, idwt3
from wam_tpu.wavelets.filters import build_wavelet

__all__ = [
    "VideoLevels",
    "wavedec_video",
    "waverec_video",
    "spacetime_map",
    "frame_importance",
    "WaveletAttributionVideo",
]


@dataclasses.dataclass(frozen=True)
class VideoLevels:
    """Anisotropic decomposition depth: ``spatial`` total levels, of which
    the finest ``temporal`` also decimate time."""

    spatial: int
    temporal: int

    def __post_init__(self):
        if self.spatial < 1:
            raise ValueError(f"spatial={self.spatial} must be >= 1")
        if not 0 <= self.temporal <= self.spatial:
            raise ValueError(
                f"temporal={self.temporal} must satisfy "
                f"0 <= temporal <= spatial (={self.spatial})"
            )

    @property
    def uniform(self) -> bool:
        return self.temporal == self.spatial


def _as_levels(levels) -> VideoLevels:
    if isinstance(levels, VideoLevels):
        return levels
    s, t = levels
    return VideoLevels(spatial=int(s), temporal=int(t))


def wavedec_video(x: jax.Array, wavelet, levels, mode: str = "symmetric"):
    """Anisotropic multi-level DWT over the last three axes (T, H, W).

    Returns ``[cA, det_J, ..., det_1]`` coarsest-first like `wavedec3`;
    a level's detail entry is a 7-key dict (3D level, finest ``temporal``
    of them) or a `Detail2D` (spatial-only level — the decimated time axis
    rides as a batch dim)."""
    lv = _as_levels(levels)
    coeffs = []
    a = x
    for j in range(lv.spatial):
        if j < lv.temporal:
            a, det = dwt3(a, wavelet, mode)
        else:
            # (..., T', H', W') → fold T' into the batch for the 2D kernel
            a, det = dwt2(a, wavelet, mode)
        coeffs.append(det)
    coeffs.append(a)
    return coeffs[::-1]


def waverec_video(coeffs, wavelet):
    """Inverse of `wavedec_video` (coarsest-first walk, trimming pads per
    level exactly like `waverec3`/`waverec2`). The result may overshoot
    the original (T, H, W) by boundary pads — callers trim."""
    L = wavelet.filt_len if hasattr(wavelet, "filt_len") else build_wavelet(wavelet).filt_len
    a = coeffs[0]
    for det in coeffs[1:]:
        if isinstance(det, dict):
            tgt = det["ddd"].shape[-3:]
            a = a[..., : tgt[0], : tgt[1], : tgt[2]]
            a = idwt3(a, det, wavelet, out_shape=tuple(2 * s - L + 2 for s in tgt))
        else:
            tgt = det.horizontal.shape[-2:]
            a = a[..., : tgt[0], : tgt[1]]
            a = idwt2(a, det, wavelet, out_shape=(2 * tgt[0] - L + 2, 2 * tgt[1] - L + 2))
    return a


def spacetime_map(grads, shape, approx_coeffs: bool = False) -> jax.Array:
    """Collapse a `wavedec_video` gradient pytree to one (..., T, H, W)
    saliency box: per level, |gradient| energy of every orientation,
    nearest-upsampled to ``shape`` and summed (the approximation band
    joins only with ``approx_coeffs=True``, matching the 2D/3D engines'
    convention)."""
    shape = tuple(shape)

    def up(g):
        return jax.image.resize(
            jnp.abs(g), g.shape[:-3] + shape, method="nearest"
        )

    total = None
    entries = list(coeff_leaves(grads, approx_coeffs))
    for g in entries:
        total = up(g) if total is None else total + up(g)
    return total


def coeff_leaves(coeffs, include_approx: bool = True):
    """Yield every (..., t, h, w) leaf of a video coefficient list —
    Detail2D fields, 3D dict values, and (optionally) the approximation."""
    if include_approx:
        yield coeffs[0]
    for det in coeffs[1:]:
        if isinstance(det, dict):
            yield from det.values()
        else:
            yield det.horizontal
            yield det.vertical
            yield det.diagonal


def frame_importance(box: jax.Array) -> jax.Array:
    """(..., T, H, W) saliency box → (..., T) per-frame scores (spatial
    mean) — what the temporal insertion/deletion fan ranks."""
    return box.mean(axis=(-2, -1))


class WaveletAttributionVideo:
    """SmoothGrad / IG WAM over clips (B, C, T, H, W).

    The estimator bodies mirror `WaveletAttribution3D`: one jit per
    (method, has_label), sample chunking through
    `resolve_sample_chunk(workload="wamvid3d")`, tuned synthesis impl
    applied at trace time. ``__call__`` returns the (B, T, H, W)
    spacetime saliency box (channel-averaged); `frame_importance` of it
    feeds the temporal eval fan.

    IG is coefficient-domain like the 3D engine: attribution =
    coeff ⊙ trapezoid(path of coefficient gradients), then aggregated —
    not a path integral of the (lossy) aggregated maps.
    """

    def __init__(
        self,
        model_fn,
        wavelet: str = "haar",
        levels=(3, 1),
        method: str = "smooth",
        mode: str = "symmetric",
        approx_coeffs: bool = False,
        n_samples: int = 25,
        stdev_spread: float = 1e-4,
        random_seed: int = 42,
        sample_batch_size: int | None | str = "auto",
        stream_noise: bool = False,
        mesh=None,
        seq_axis: str = "data",
        batch_axis: str | None = None,
        seq_fused: bool | str = "auto",
    ):
        if method not in ("smooth", "integratedgrad"):
            raise ValueError(f"Unknown method {method!r}")
        validate_sample_batch_size(sample_batch_size)
        self.model_fn = model_fn
        self.wavelet = wavelet
        self.levels = _as_levels(levels)
        self.method = method
        self.mode = mode
        self.approx_coeffs = approx_coeffs
        self.n_samples = n_samples
        self.stdev_spread = stdev_spread
        self.random_seed = random_seed
        self.sample_batch_size = sample_batch_size
        self.stream_noise = stream_noise
        if mesh is not None and not self.levels.uniform:
            raise ValueError(
                "mesh= (long-clip time sharding) requires uniform levels "
                f"(spatial == temporal); got {self.levels} — the halo layer "
                "shards the axis every level decimates"
            )
        if mesh is None and batch_axis is not None:
            raise ValueError("batch_axis= requires mesh=")
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis
        self.seq_fused = seq_fused
        self.grads = None
        self._jit_smooth = functools.cache(self._build_smooth)
        self._jit_ig = functools.cache(self._build_ig)
        self._seq_cache: dict = {}

    # -- shared plumbing ---------------------------------------------------

    def _resolve_chunk(self, clip_shape) -> int | None:
        return resolve_sample_chunk(
            self.sample_batch_size, clip_shape[0], self.n_samples,
            workload="wamvid3d", shape=tuple(clip_shape[1:]),
        )

    def _apply_tuned_synth(self, clip_shape) -> None:
        from wam_tpu.tune import apply_tuned_synth_impl

        apply_tuned_synth_impl("wamvid3d", tuple(clip_shape[1:]), clip_shape[0])

    def _decompose(self, clip):
        return wavedec_video(clip, self.wavelet, self.levels, self.mode)

    def _grad_step(self, clip, y):
        """clip (B, C, T, H, W) → coefficient-gradient pytree."""
        coeffs = self._decompose(clip)

        def loss(cs):
            rec = waverec_video(cs, self.wavelet)
            t, h, w = clip.shape[-3:]
            out = self.model_fn(rec[..., :t, :h, :w])
            return target_loss(out, y)

        return jax.grad(loss)(coeffs)

    def _box_step(self, clip, y):
        """clip → (B, T, H, W) channel-averaged spacetime saliency."""
        grads = self._grad_step(clip, y)
        box = spacetime_map(grads, clip.shape[-3:], self.approx_coeffs)
        return box.mean(axis=1)

    # -- SmoothGrad --------------------------------------------------------

    def _smooth_impl(self, clip, y, key):
        self._apply_tuned_synth(clip.shape)
        return smoothgrad(
            lambda noisy: self._box_step(noisy, y),
            clip,
            key,
            n_samples=self.n_samples,
            stdev_spread=self.stdev_spread,
            batch_size=self._resolve_chunk(clip.shape),
            materialize_noise=not self.stream_noise,
        )

    def _build_smooth(self, has_label: bool):
        if has_label:
            return jax.jit(self._smooth_impl)
        return jax.jit(lambda clip, key: self._smooth_impl(clip, None, key))

    def _get_seq(self, clip_shape):
        """Lazy per-(T,H,W) SeqShardedWam: the aggregation post_fn bakes in
        the clip geometry, which `__init__` doesn't know yet."""
        key = tuple(clip_shape[-3:])
        if key not in self._seq_cache:
            from wam_tpu.parallel.seq_estimators import SeqShardedWam

            def post_fn(grads):
                return spacetime_map(grads, key, self.approx_coeffs)

            self._seq_cache[key] = SeqShardedWam(
                self.mesh,
                lambda rec: self.model_fn(rec[:, None]),
                ndim=3,
                wavelet=self.wavelet,
                level=self.levels.spatial,
                mode=self.mode,
                seq_axis=self.seq_axis,
                post_fn=post_fn,
                batch_axis=self.batch_axis,
                fused=self.seq_fused,
            )
        return self._seq_cache[key]

    def smooth(self, x, y=None):
        clip = jnp.asarray(x)
        key = jax.random.PRNGKey(self.random_seed)
        if self.mesh is not None:
            if clip.shape[1] != 1:
                raise ValueError(
                    "mesh= long-clip dispatch supports single-channel clips "
                    f"(C=1); got C={clip.shape[1]}"
                )
            y_arr = None if y is None else jnp.asarray(y)
            self.grads = self._get_seq(clip.shape).smoothgrad(
                clip[:, 0], y_arr, key, n_samples=self.n_samples,
                stdev_spread=self.stdev_spread,
                sample_chunk=self._resolve_chunk(clip.shape),
            )
        elif y is None:
            self.grads = self._jit_smooth(False)(clip, key)
        else:
            self.grads = self._jit_smooth(True)(clip, jnp.asarray(y), key)
        return self.grads

    # -- Integrated Gradients ----------------------------------------------

    def _ig_impl(self, clip, y):
        self._apply_tuned_synth(clip.shape)
        coeffs = self._decompose(clip)
        alphas = jnp.linspace(0.0, 1.0, self.n_samples, dtype=clip.dtype)

        def one(alpha):
            scaled = jax.tree_util.tree_map(lambda c: c * alpha, coeffs)

            def loss(cs):
                rec = waverec_video(cs, self.wavelet)
                t, h, w = clip.shape[-3:]
                return target_loss(self.model_fn(rec[..., :t, :h, :w]), y)

            return jax.grad(loss)(scaled)

        path = jax.lax.map(one, alphas, batch_size=self._resolve_chunk(clip.shape))
        integral = jax.tree_util.tree_map(trapezoid, path)
        attr = jax.tree_util.tree_map(jnp.multiply, coeffs, integral)
        box = spacetime_map(attr, clip.shape[-3:], self.approx_coeffs)
        return box.mean(axis=1)

    def _build_ig(self, has_label: bool):
        if has_label:
            return jax.jit(self._ig_impl)
        return jax.jit(lambda clip: self._ig_impl(clip, None))

    def integrated_wam(self, x, y=None):
        clip = jnp.asarray(x)
        if self.mesh is not None:
            raise ValueError(
                "mesh= supports method='smooth' only for video — the IG "
                "path's coefficient-domain multiply needs the gathered "
                "pytree; run IG unsharded or via chunked batches"
            )
        if y is None:
            self.grads = self._jit_ig(False)(clip)
        else:
            self.grads = self._jit_ig(True)(clip, jnp.asarray(y))
        return self.grads

    def __call__(self, x, y=None):
        if self.method == "smooth":
            return self.smooth(x, y)
        return self.integrated_wam(x, y)

    def frame_scores(self, x, y=None) -> jax.Array:
        """(B, T) per-frame importance — `frame_importance(self(x, y))`."""
        return frame_importance(self(x, y))

    def serve_entry(self, donate: bool | None = None, on_trace=None,
                    aot_key: str | None = None, with_health: bool = False):
        """Batched serving entry ``(x, y) → (B, T, H, W)`` for the serve
        worker (labeled-only, single device — same contract as
        `WaveletAttribution3D.serve_entry`)."""
        if self.mesh is not None:
            raise ValueError(
                "serve_entry() does not support mesh=; the serve worker owns "
                "a single device — drive the sharded estimator directly")
        from wam_tpu.serve.entry import jit_entry
        from wam_tpu.wam2d import _synth_tagged

        if self.method == "smooth":
            key = jax.random.PRNGKey(self.random_seed)
            impl = lambda x, y: self._smooth_impl(x, y, key)  # noqa: E731
        else:
            impl = self._ig_impl
        return jit_entry(impl, donate=donate, on_trace=on_trace,
                         aot_key=_synth_tagged(aot_key),
                         with_health=with_health)
