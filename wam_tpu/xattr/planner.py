"""Patch-aligned wavelet level planning for ViT attribution.

A ViT tokenizes an (S, S) image into an (S/p, S/p) grid of p×p patches.
Dyadic wavelet level j has coefficient cells of side ``2**j`` pixels, so
levels ``j ≥ log2(p)`` are **token-granular**: every coefficient cell
covers a whole number of tokens and WAM's scale disentanglement maps
cleanly onto the token grid (224/patch-16 → 14×14 tokens ⇒ level 4 cells
= 1 token, level 5 = 2×2 tokens, …).

`plan_patch_levels` picks ``J = log2(patch)`` — the deepest decomposition
whose FINEST level is still sub-token (levels 1..J-1 localize within a
patch, level J lands exactly on the token grid) — and validates the
geometry: power-of-two patch, image divisible by the patch, and J within
`dwt_max_level` for the wavelet. `WaveletAttribution2D` consumes this as
``level_plan="patch"`` (wam_tpu/wam2d.py).

`token_grid_map` is the aggregation half: average-pool any (…, S, S)
pixel-domain map onto the (…, t, t) token grid, the bridge between WAM
mosaics / rollout maps and per-token scores.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from wam_tpu.wavelets import build_wavelet, dwt_max_level

__all__ = ["PatchLevelPlan", "plan_patch_levels", "token_grid_map"]


@dataclasses.dataclass(frozen=True)
class PatchLevelPlan:
    """Planned decomposition: ``J`` dyadic levels for ``image_size`` px
    inputs on a ``patch`` px grid of ``tokens``×``tokens`` tokens."""

    J: int
    patch: int
    image_size: int
    tokens: int
    wavelet: str = "haar"

    def level_cell_px(self, j: int) -> int:
        """Pixel side of one level-j coefficient cell (1 ≤ j ≤ J)."""
        return 2**j

    def token_granular_levels(self) -> tuple[int, ...]:
        """Levels whose cells tile whole tokens — with J = log2(patch)
        that is exactly (J,); kept as a tuple for forward-compat with
        deeper plans."""
        return tuple(j for j in range(1, self.J + 1) if 2**j >= self.patch)


def plan_patch_levels(
    image_size: int, patch: int = 16, wavelet: str = "haar"
) -> PatchLevelPlan:
    """Plan dyadic levels that respect the patch grid; raises ValueError
    on any geometry the token mapping cannot honor."""
    if patch < 2 or (patch & (patch - 1)) != 0:
        raise ValueError(
            f"patch={patch} is not a power of two ≥ 2 — dyadic wavelet "
            "levels cannot align to it"
        )
    if image_size <= 0 or image_size % patch != 0:
        raise ValueError(
            f"image_size={image_size} is not divisible by patch={patch} — "
            "no token grid exists (ViT would reject this input too)"
        )
    J = patch.bit_length() - 1  # log2(patch)
    filt_len = len(build_wavelet(wavelet).dec_lo)
    max_j = dwt_max_level(image_size, filt_len)
    if J > max_j:
        raise ValueError(
            f"patch={patch} needs J={J} levels but wavelet {wavelet!r} "
            f"supports at most {max_j} on {image_size}px inputs"
        )
    return PatchLevelPlan(
        J=J, patch=patch, image_size=image_size,
        tokens=image_size // patch, wavelet=wavelet,
    )


def token_grid_map(maps: jnp.ndarray, tokens: int) -> jnp.ndarray:
    """Average-pool (…, S, S) pixel maps onto the (…, tokens, tokens)
    token grid. Pure reshape-mean — exact when S % tokens == 0, which the
    planner guarantees."""
    *lead, h, w = maps.shape
    if h % tokens or w % tokens:
        raise ValueError(
            f"map of {(h, w)} px does not tile a {tokens}×{tokens} token grid"
        )
    ph, pw = h // tokens, w // tokens
    pooled = maps.reshape(*lead, tokens, ph, tokens, pw)
    return pooled.mean(axis=(-3, -1))
